"""Spec layer of the :class:`~chainermn_tpu.parallel.plan.ParallelPlan`.

The reference expressed every parallel form as a *call-site wrapper* around
a per-process communicator (``communicators/`` (dagger), SURVEY.md
section 2.1); here the per-axis modules are **spec providers** instead:
each publishes a small descriptor — how its parameter/opt-state leaves lay
out over its mesh axis, and which HLO collectives it owes the compiled
step — and this module turns those descriptors plus the user's per-leaf
``PartitionSpec`` tree into the concrete shard_map specs and update groups
one compiled train step composes.

Provider contract (``{tensor,zero,pipeline}.{tp,zero,pipe}_plan_axis``):

- ``name``: the mesh axis name;
- ``stacked``: parameter leaves sharded by this axis stack a leading
  ``[n, ...]`` shard dim (``stack_tp_params`` / ``stack_stage_params``
  layout) carried with ``P(axis)`` and collapsed inside the program;
- ``state_stacked``: the axis shards the *optimizer state* (ZeRO): state
  leaves stack ``[n, ...]`` chunks over the axis, params stay replicated;
- ``collectives``: the HLO collective ops the axis owes the step — the
  vocabulary of the structural count tests (``all-reduce``,
  ``reduce-scatter``, ``all-gather``, ``collective-permute``).

The ``data`` axis is the plain data-parallel provider and lives here (it
has no module of its own: its only artifact is the gradient ``pmean``).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Mapping, Sequence

import jax
from jax.sharding import PartitionSpec as P

PyTree = Any

#: Canonical mesh-axis order: DCN-tolerant axes first, ICI-hungry last
#: (the repo's mesh convention — the fast/intra axis sits last). ``data``
#: tolerates DCN (one allreduce/step), ``model`` wants ICI (one psum per
#: layer pair), ``zero``/``pipe`` sit between; ``seq`` (ring-attention
#: neighbour exchange per layer, ISSUE 13) sits just before ``model`` —
#: its ppermutes want ICI, but only to a neighbour, so ``model``'s
#: all-reduces keep the fastest slot. ``expert`` (MoE all_to_all token
#: dispatch, ISSUE 20) sits between ``seq`` and ``model``: its two
#: per-layer all_to_alls move full token payloads and want ICI, but
#: ``model``'s per-layer-pair all-reduces still claim the fastest slot
#: (an a2a moves 1/n of the payload per link the allreduce moves twice).
CANONICAL_AXES = ("data", "zero", "pipe", "seq", "expert", "model")

#: the ``seq_attn_impl`` tuning decision's candidates and the HLO
#: collectives each routes the compiled step through (what
#: :meth:`~chainermn_tpu.parallel.plan.ParallelPlan.seq_attention`
#: substitutes into the axis descriptor once the impl is resolved).
SEQ_ATTN_IMPLS = ("ring", "ulysses")
SEQ_IMPL_COLLECTIVES = {
    # n-1 kv hops/layer/pass (the unrolled plan ring) + the one grad mean
    "ring": ("collective-permute", "all-reduce"),
    # two reshards in, one out, per layer + the one grad mean
    "ulysses": ("all-to-all", "all-reduce"),
}


def seq_plan_axis(impl: str = "ring", axis_name: str = "seq") -> dict:
    """Spec-provider descriptor for the ``seq`` axis (ISSUE 13): the
    batch's SEQUENCE dim shards over it (``ParallelPlan.batch_spec``
    appends it after the dp axes), params and optimizer state stay
    replicated (it is token parallelism, not weight parallelism), and it
    owes the compiled step one gradient all-reduce plus the per-layer
    attention collectives of the routed impl —
    :func:`~chainermn_tpu.parallel.ring_attention.
    seq_ring_attention_local` (``collective-permute``, the default) or
    :func:`~chainermn_tpu.parallel.ulysses.ulysses_attention_local`
    (``all-to-all``)."""
    if impl not in SEQ_ATTN_IMPLS:
        raise ValueError(
            f"seq_plan_axis impl must be one of {SEQ_ATTN_IMPLS}, got "
            f"{impl!r}"
        )
    return {
        "name": axis_name,
        "stacked": False,
        "state_stacked": False,
        "collectives": SEQ_IMPL_COLLECTIVES[impl],
    }


def moe_plan_axis(axis_name: str = "expert") -> dict:
    """Spec-provider descriptor for the ``expert`` axis (ISSUE 20 — MoE
    expert parallelism over :func:`~chainermn_tpu.parallel.moe.
    moe_layer_local`): expert parameter leaves STACK a leading
    ``[n, ...]`` shard dim (``P('expert')`` — each shard hosts its slice
    of the expert set, :func:`~chainermn_tpu.parallel.moe.
    make_expert_params` layout), the batch's token dim shards over the
    axis too (``ParallelPlan.batch_spec`` folds it into the dp tuple —
    the axis is extra data parallelism for every NON-expert leaf), and
    it owes the compiled step exactly two ``all-to-all``s per MoE layer
    per pass (dispatch + combine; their backward transposes are again
    all_to_alls) plus the one fused gradient all-reduce that makes
    replicated leaves' grads the global token mean. Expert-stacked
    leaves take NO collective over the axis: the all_to_all's exact
    transpose already accumulates every shard's cotangents onto the
    owning shard (the plan rescales them to the mean)."""
    return {
        "name": axis_name,
        "stacked": True,
        "state_stacked": False,
        "collectives": ("all-to-all", "all-reduce"),
    }


@dataclasses.dataclass(frozen=True)
class AxisSpec:
    """One resolved plan axis: the provider descriptor plus its size."""

    name: str
    size: int
    stacked: bool
    state_stacked: bool
    collectives: tuple[str, ...]


def _provider(role: str) -> dict:
    if role == "data":
        return {
            "name": "data",
            "stacked": False,
            "state_stacked": False,
            "collectives": ("all-reduce",),
        }
    if role == "zero":
        from chainermn_tpu.parallel.zero import zero_plan_axis

        return zero_plan_axis()
    if role == "model":
        from chainermn_tpu.parallel.tensor import tp_plan_axis

        return tp_plan_axis()
    if role == "pipe":
        from chainermn_tpu.parallel.pipeline import pipe_plan_axis

        return pipe_plan_axis()
    if role == "seq":
        return seq_plan_axis()
    if role == "expert":
        return moe_plan_axis()
    raise ValueError(
        f"unknown plan axis {role!r}: a ParallelPlan composes "
        f"{CANONICAL_AXES} (any subset)"
    )


def resolve_axes(sizes: Mapping[str, int]) -> dict[str, AxisSpec]:
    """Resolve provider descriptors for ``sizes`` (name -> size), in
    canonical mesh order."""
    for name in sizes:
        if name not in CANONICAL_AXES:
            _provider(name)  # raises with the canonical list
    out: dict[str, AxisSpec] = {}
    for name in CANONICAL_AXES:
        if name not in sizes:
            continue
        d = _provider(name)
        out[name] = AxisSpec(
            name=d["name"],
            size=int(sizes[name]),
            stacked=bool(d["stacked"]),
            state_stacked=bool(d["state_stacked"]),
            collectives=tuple(d["collectives"]),
        )
    return out


def normalize_param_specs(
    params: PyTree,
    specs: PyTree | None,
    axes: Mapping[str, AxisSpec],
) -> PyTree:
    """Expand the user's spec tree to a FULL per-leaf ``PartitionSpec``
    tree over ``params`` and validate it against the plan's axes.

    ``specs`` may be ``None`` (everything replicated), a single ``P``
    (broadcast), or a prefix pytree of ``P`` leaves (each broadcast over
    its params subtree). Each leaf spec must be ``P()``, ``P(axis)``,
    or a canonical-order run of *stacked* plan axes
    (``P('pipe', 'model')`` — the composed pipe x model plan, ISSUE 13)
    — the leading-stack convention of
    :func:`~chainermn_tpu.parallel.tensor.stack_tp_params` /
    :func:`~chainermn_tpu.parallel.pipeline.stack_stage_params`,
    one leading dim per named axis — and each leading dim must equal
    its axis's size.
    """
    if specs is None:
        specs = P()
    is_spec = lambda x: isinstance(x, P)  # noqa: E731
    if is_spec(specs):
        full = jax.tree.map(lambda _: specs, params)
    else:
        full = jax.tree.map(
            lambda s, sub: jax.tree.map(lambda _: s, sub),
            specs,
            params,
            is_leaf=is_spec,
        )

    def check(spec, leaf):
        if not isinstance(spec, P):
            raise TypeError(
                f"param specs must be jax.sharding.PartitionSpec leaves, "
                f"got {type(spec).__name__}"
            )
        entries = tuple(spec)
        if not entries:
            return spec
        if any(e is None for e in entries):
            raise ValueError(
                f"plan param specs use the leading-stack convention: "
                f"P() or P(<stacked axes...>), got {spec}"
            )
        for ax in entries:
            if ax not in axes or not axes[ax].stacked:
                stacked = [a for a, s in axes.items() if s.stacked]
                raise ValueError(
                    f"param spec {spec} names {ax!r}, but this plan's "
                    f"stacked axes are {stacked} (zero/data/seq shard "
                    f"state, batch and activations, never parameter "
                    f"leaves)"
                )
        order = [CANONICAL_AXES.index(a) for a in entries]
        if len(set(entries)) != len(entries) or order != sorted(order):
            raise ValueError(
                f"multi-axis param spec {spec} must name distinct "
                f"stacked axes in canonical order {CANONICAL_AXES}"
            )
        shape = jax.numpy.shape(leaf)
        for d, ax in enumerate(entries):
            lead = shape[d] if len(shape) > d else None
            if lead != axes[ax].size:
                raise ValueError(
                    f"leaf sharded {spec} must stack "
                    f"[{axes[ax].size}, ...] over {ax!r} at dim {d}; "
                    f"got leading dim {lead} "
                    f"(use stack_tp_params / stack_stage_params)"
                )
        return spec

    return jax.tree.map(check, full, params)


def partition_groups(
    flat_specs: Sequence[P],
    axes: Mapping[str, AxisSpec],
) -> dict[str, list[int]]:
    """Split flattened param leaves into update groups by their spec.

    - each stacked spec (``model``, ``pipe``, or the composed
      ``pipe+model`` — keyed by ``'+'.join(axes)``) gets its own group:
      state mirrors the stacked params (already factored ``1/n`` over
      those axes), updated per shard;
    - replicated leaves form the ``'zero'`` group when a
      ``state_stacked`` axis is present (their state chunks over it), or
      the plain ``'rep'`` group otherwise.

    A leaf cannot belong to both a stacked axis AND the zero group by
    default: a TP/pipe-sharded parameter's optimizer state is already
    sharded ``n``-ways by construction, so ZeRO applies to the
    replicated leaves — the spec-provider contract (docs/parallelism.md).
    ``ParallelPlan(zero_stacked_groups=True)`` additionally chunks the
    STACKED groups' state over the zero axis (the cross-replica
    weight-update sharding of arXiv:2004.13336 applied per TP/pipe
    shard, ISSUE 13) — that changes the state layout and update wiring,
    not the grouping here.
    """
    has_zero = any(s.state_stacked for s in axes.values())
    groups: dict[str, list[int]] = {}
    for i, spec in enumerate(flat_specs):
        entries = tuple(spec)
        if entries:
            key = "+".join(entries)
        else:
            key = "zero" if has_zero else "rep"
        groups.setdefault(key, []).append(i)
    return groups


def group_stack_axes(group: str) -> tuple[str, ...]:
    """The stacked mesh axes a :func:`partition_groups` key names (empty
    for the ``zero``/``rep`` groups)."""
    if group in ("zero", "rep"):
        return ()
    return tuple(group.split("+"))


def owed_collectives(axes: Mapping[str, AxisSpec]) -> dict[str, tuple]:
    """Per-axis collective vocabulary — what the structural tests count."""
    return {name: spec.collectives for name, spec in axes.items()}


def composition_collectives(comp) -> dict[str, tuple]:
    """A :class:`~chainermn_tpu.parallel.composition.Composition` as a
    SPEC PROVIDER: per mesh axis, the HLO collectives its stages owe
    the compiled step (stage order preserved) — what
    :class:`~chainermn_tpu.parallel.plan.ParallelPlan` substitutes for
    the ``data`` provider's fixed ``('all-reduce',)`` when a derived
    schedule drives the gradient reduction (ISSUE 12). The structural
    tests count against this, same as every other provider."""
    from chainermn_tpu.parallel.composition import STAGE_HLO

    out: dict[str, list] = {}
    for st in comp.stages:
        hlo = STAGE_HLO.get(st.primitive)
        if hlo is None:
            continue
        for a in st.axes:
            out.setdefault(a, []).append(hlo)
    return {a: tuple(v) for a, v in out.items()}
