"""Device-mesh construction and pod-slice topology discovery.

TPU-native replacement for the reference's rank-topology bootstrap
(``chainermn/communicators/_communication_utility.py`` (dagger):
``init_ranks`` / ``init_intra_mpi_comm`` / ``init_inter_mpi_comm`` /
``init_nccl_comm``, SURVEY.md section 2.1). There, intra/inter-node rank
discovery ran ``MPI_Comm_split_type(SHARED)`` and NCCL rings were initialised
by broadcasting a unique id over MPI. Here the JAX runtime already knows the
slice topology: ``jax.devices()`` carries coords, ``jax.process_index()``
plays the role of the MPI rank, and collective routing over ICI vs DCN is
decided by XLA from the mesh axes. ``intra``/``inter`` axes of the reference's
hierarchical communicators map onto a factorised ``(dcn, ici)`` mesh
(SURVEY.md section 5, "Distributed communication backend").
"""

from __future__ import annotations

import dataclasses
import math
from typing import Sequence

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh


def best_mesh_shape(n: int, ndims: int = 2) -> tuple[int, ...]:
    """Factor ``n`` devices into an ``ndims``-dim balanced mesh shape.

    Most balanced factorisation, larger factors first: minimises the
    largest factor, then the next-largest, and so on (lexicographic on the
    descending-sorted tuple). E.g. 8 -> (4, 2), 16 -> (4, 4), 6 -> (3, 2),
    primes -> (n, 1); 8 over 3 dims -> (2, 2, 2), 16 over 3 -> (4, 2, 2),
    24 over 4 -> (3, 2, 2, 2). A 3-axis ``data x model x zero``
    :class:`~chainermn_tpu.parallel.plan.ParallelPlan` relies on this for
    its auto-factorised mesh (the largest factor lands on the first —
    DCN-most — axis).
    """
    if ndims < 1:
        raise ValueError(f"ndims must be >= 1, got {ndims}")
    if n < 1:
        raise ValueError(f"need a positive device count, got {n}")
    if ndims == 1:
        return (n,)

    def factorisations(m: int, k: int):
        if k == 1:
            yield (m,)
            return
        for d in range(1, m + 1):
            if m % d == 0:
                for rest in factorisations(m // d, k - 1):
                    yield tuple(sorted((d,) + rest, reverse=True))

    # min() over descending-sorted tuples = smallest largest factor,
    # ties broken by the next factor — the balanced choice.
    return min(set(factorisations(n, ndims)))


def _device_array(devices: Sequence[jax.Device], shape: tuple[int, ...]) -> np.ndarray:
    """Arrange devices into ``shape``, ICI-topology-aware when possible.

    ``mesh_utils.create_device_mesh`` understands TPU coords and lays the mesh
    out so that neighbouring mesh indices are ICI neighbours; it refuses
    non-TPU platforms' odd shapes sometimes, so fall back to a plain reshape
    (fine for CPU test meshes — there is no topology to exploit).
    """
    devices = list(devices)
    try:
        return mesh_utils.create_device_mesh(shape, devices=devices)
    except (ValueError, AssertionError, NotImplementedError):
        return np.array(devices).reshape(shape)


def make_mesh(
    axis_names: Sequence[str] = ("data",),
    shape: Sequence[int] | None = None,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Create a :class:`jax.sharding.Mesh` over ``devices``.

    Args:
      axis_names: mesh axis names, e.g. ``('data',)`` or ``('data', 'model')``.
      shape: per-axis sizes; if ``None``, all devices go on the first axis and
        remaining axes get size 1 (or a balanced 2-d factorisation if exactly
        two axes are requested with no shape).
      devices: device list; defaults to ``jax.devices()``.
    """
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    n = len(devices)
    axis_names = tuple(axis_names)
    if shape is None:
        if len(axis_names) == 1:
            shape = (n,)
        else:
            shape = best_mesh_shape(n, 2) + (1,) * (len(axis_names) - 2)
    shape = tuple(shape)
    if math.prod(shape) != n:
        raise ValueError(
            f"mesh shape {shape} does not cover {n} devices; "
            f"pass an explicit `devices` list or fix `shape`"
        )
    return Mesh(_device_array(devices, shape), axis_names)


@dataclasses.dataclass(frozen=True)
class MeshTopology:
    """Rank-topology view of a mesh, mirroring the reference communicator's
    ``rank/size/intra_rank/inter_rank/inter_size`` surface
    (``communicator_base.py`` (dagger) properties, SURVEY.md section 2.1).

    On TPU the "node" boundary of the reference (NVLink island / MPI host)
    maps to the *process* boundary: devices local to this process are the
    intra group (ICI-attached, addressable without DCN), processes are the
    inter group. For a single-process CPU/test mesh every device is intra.
    """

    mesh: Mesh
    #: Optional provider of the hostname-discovered ``(intra_rank,
    #: processes_on_this_host)`` pair, or ``None`` from the provider when
    #: the runtime is single-process (then the device-count semantics
    #: below apply). Communicators install their lazy host-plane
    #: discovery here so the intra pair is truthful AND internally
    #: consistent (``0 <= intra_rank < intra_size``) on
    #: multi-process-per-host runtimes. CAUTION: with a provider
    #: installed, the FIRST ``intra_rank``/``intra_size`` access on a
    #: multi-process runtime is a blocking host-plane collective — read
    #: it on every process or not at all (same discipline as
    #: ``CommunicatorBase.intra_rank``, where this is documented).
    host_intra_provider: "object" = dataclasses.field(
        default=None, compare=False
    )

    def _host_intra(self):
        if self.host_intra_provider is None:
            return None
        return self.host_intra_provider()

    @property
    def size(self) -> int:
        """Total number of devices in the mesh (the reference's world size —
        one process per GPU there, one mesh slot per chip here)."""
        return self.mesh.devices.size

    @property
    def rank(self) -> int:
        """Host-plane rank: ``jax.process_index()``."""
        return jax.process_index()

    @property
    def inter_size(self) -> int:
        """Number of processes (the reference's number of nodes)."""
        return jax.process_count()

    @property
    def inter_rank(self) -> int:
        return jax.process_index()

    @property
    def intra_size(self) -> int:
        """Multi-process (provider present and reporting): processes
        sharing this host — keeps ``0 <= intra_rank < intra_size``
        coherent. Otherwise: devices managed by this process (the
        reference's GPUs per node, single-controller reading)."""
        pair = self._host_intra()
        if pair is not None:
            return pair[1]
        return jax.local_device_count()

    @property
    def intra_rank(self) -> int:
        """Index of this process among the processes sharing its host.

        When a communicator owns this topology, the value comes from its
        hostname-discovery collective (``host_intra_provider`` — the
        reference's ``init_ranks`` hostname exchange; see the provider
        field's collective-access caveat). Standalone (no provider): 0,
        the one-process-per-host JAX norm — JAX itself exposes no
        host-local process index.
        """
        pair = self._host_intra()
        if pair is not None:
            return pair[0]
        return 0

    @property
    def axis_names(self) -> tuple[str, ...]:
        return tuple(self.mesh.axis_names)

    def axis_size(self, axis_name: str) -> int:
        return self.mesh.shape[axis_name]
