"""Tensor (intra-layer model) parallelism over a ``'model'`` mesh axis.

The reference's tensor-parallel story was a single channel-split
convolution example (``examples/parallel_convolution`` (dagger), SURVEY.md
section 2.2 "Tensor/channel parallel — narrow"); splitting a *layer* across
ranks otherwise required hand-wiring send/recv functions. This module is
the general library form, built the TPU way: Megatron-style column/row
parallel layers as pure functions inside ``shard_map``, with exactly one
``psum`` per column→row pair and the activation between them never
materialised unsharded. On TPU the collective rides ICI, which is what
makes intra-layer sharding practical at all.

Two identity/collective adjoint pairs do all the gradient bookkeeping
(Megatron's ``f``/``g`` operators):

- :func:`copy_to_tp` — forward identity, backward ``psum``. Placed where a
  replicated activation fans out to per-shard weight columns, so the
  replicated input's gradient sums every shard's contribution.
- :func:`reduce_from_tp` — forward ``psum``, backward identity. Placed
  where per-shard partial products recombine, so the gradient broadcast is
  free.

Everything composes with the data-parallel optimizer wrapper unchanged:
column/row shard weights get per-shard gradients (no reduction over the
model axis), replicated weights (biases after the reduce, layer norms)
receive bitwise-identical gradients on every model shard, so
``comm.grad_axes`` (data axes only) stays the correct reduction set.

Usage contract: differentiate INSIDE ``shard_map`` (``jax.value_and_grad``
of the shard-local loss — the pattern every train step in this framework
uses). The adjoint pairs make shard-local autodiff globally exact; taking
gradients *through* the shard_map boundary with ``check_vma=False`` is not
supported (the boundary transpose rescales cotangents of replicated
arguments).
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax


# ---------------------------------------------------------------------------
# Megatron f/g adjoint pairs
# ---------------------------------------------------------------------------


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def copy_to_tp(x: jax.Array, axis_name) -> jax.Array:
    """Identity forward; ``psum`` over ``axis_name`` backward.

    Wrap a replicated activation before it meets column-sharded weights:
    each shard then computes an independent cotangent slice and the true
    input gradient is their sum.
    """
    return x


def _copy_fwd(x, axis_name):
    return x, None


def _copy_bwd(axis_name, _, g):
    return (lax.psum(g, axis_name),)


copy_to_tp.defvjp(_copy_fwd, _copy_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def reduce_from_tp(x: jax.Array, axis_name) -> jax.Array:
    """``psum`` over ``axis_name`` forward; identity backward.

    Recombines per-shard partial products (row-parallel matmul outputs);
    the reduced value is replicated, so its gradient needs no collective.
    """
    return lax.psum(x, axis_name)


def _reduce_fwd(x, axis_name):
    return lax.psum(x, axis_name), None


def _reduce_bwd(axis_name, _, g):
    return (g,)


reduce_from_tp.defvjp(_reduce_fwd, _reduce_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def gather_from_tp(x: jax.Array, axis_name, dim: int) -> jax.Array:
    """All-gather shard blocks along ``dim`` forward; slice this shard's
    block out of the cotangent backward (Megatron's gather adjoint).

    ``lax.all_gather``'s default transpose is a reduce-scatter, which SUMS
    the replicated cotangents across shards — correct only when each
    shard's cotangent is its own independent contribution. After a gather
    the cotangent is replicated, so the sum overcounts by the axis size;
    slicing is the true adjoint.
    """
    return lax.all_gather(x, axis_name, axis=dim, tiled=True)


def _gather_fwd(x, axis_name, dim):
    return lax.all_gather(x, axis_name, axis=dim, tiled=True), x.shape[dim]


def _gather_bwd(axis_name, dim, local_size, g):
    start = lax.axis_index(axis_name) * local_size
    return (lax.dynamic_slice_in_dim(g, start, local_size, dim),)


gather_from_tp.defvjp(_gather_fwd, _gather_bwd)


def tp_plan_axis(axis_name: str = "model") -> dict:
    """Spec-provider descriptor for :class:`~chainermn_tpu.parallel.plan.
    ParallelPlan` (ISSUE 10): tensor-parallel parameter leaves stack a
    leading ``[n, ...]`` shard dim over ``axis_name`` (the
    :func:`stack_tp_params` layout, ``P(axis_name)`` on the stack dim),
    and the axis owes the compiled step one ``psum`` per column→row pair
    — an all-reduce forward and its mirror backward, nothing else."""
    return {
        "name": axis_name,
        "stacked": True,  # params stack [n, ...] over this axis
        "state_stacked": False,
        "collectives": ("all-reduce",),
    }


# ---------------------------------------------------------------------------
# Parameter sharding helpers
# ---------------------------------------------------------------------------


def tp_slice(w: jax.Array, axis_name, dim: int) -> jax.Array:
    """This shard's slice of a full weight along ``dim`` (inside
    ``shard_map``). ``dim`` must divide evenly by the axis size — TPU
    tiling wants equal static shards; pad upstream if it doesn't."""
    n = lax.axis_size(axis_name)
    size = w.shape[dim]
    if size % n != 0:
        raise ValueError(
            f"dim {dim} of shape {w.shape} not divisible by mesh axis "
            f"size {n}; pad the layer width"
        )
    local = size // n
    return lax.dynamic_slice_in_dim(w, lax.axis_index(axis_name) * local, local, dim)


def stack_tp_params(full: jax.Array, n: int, dim: int) -> jax.Array:
    """Pre-split a full weight into ``[n, ...]`` stacked shards along
    ``dim`` (host-side; feed through ``shard_map`` with ``P('model')`` on
    the leading axis)."""
    parts = jnp.split(full, n, axis=dim)
    return jnp.stack(parts, axis=0)


def shard_qkv_columns(w: jax.Array, n_q_heads: int, n_kv_heads: int,
                      head_dim: int, n: int) -> jax.Array:
    """Head-shard a FUSED QKV kernel ``[d_in, (Hq + 2*Hkv) * dh]``.

    The fused layout concatenates ``[q | k | v]`` column groups, so a
    plain ``stack_tp_params`` column split would hand shard 0 all of q
    and shard 1 the k/v tail. This splits each group by heads and
    re-concatenates per shard: shard ``i`` gets its ``Hq/n`` query heads
    plus its ``Hkv/n`` key and value heads, matching a block built with
    LOCAL head counts (``TransformerBlock(tp_axis=...)``). Returns
    ``[n, d_in, (Hq + 2*Hkv)//n * dh]``.
    """
    if n_q_heads % n or n_kv_heads % n:
        raise ValueError(
            f"heads ({n_q_heads} q, {n_kv_heads} kv) not divisible by "
            f"axis size {n}"
        )
    q, k, v = jnp.split(
        w, [n_q_heads * head_dim, (n_q_heads + n_kv_heads) * head_dim],
        axis=-1,
    )
    shards = []
    for i in range(n):
        ql = n_q_heads // n * head_dim
        kl = n_kv_heads // n * head_dim
        shards.append(jnp.concatenate(
            [q[:, i * ql:(i + 1) * ql],
             k[:, i * kl:(i + 1) * kl],
             v[:, i * kl:(i + 1) * kl]], axis=-1,
        ))
    return jnp.stack(shards, axis=0)


# ---------------------------------------------------------------------------
# Parallel layers (pure functions, shard_map-local)
# ---------------------------------------------------------------------------


def column_parallel_dense(
    x: jax.Array,
    w_local: jax.Array,  # [d_in, d_out // n]
    b_local: Optional[jax.Array] = None,  # [d_out // n]
    *,
    axis_name,
    gather_output: bool = False,
) -> jax.Array:
    """Output-dimension-sharded dense layer. Input replicated; output is
    this shard's column block (or gathered when ``gather_output``)."""
    x = copy_to_tp(x, axis_name)
    y = x @ w_local
    if b_local is not None:
        y = y + b_local
    if gather_output:
        y = gather_from_tp(y, axis_name, y.ndim - 1)
    return y


def row_parallel_dense(
    x_local: jax.Array,  # [..., d_in // n] — typically a column layer's output
    w_local: jax.Array,  # [d_in // n, d_out]
    b: Optional[jax.Array] = None,  # [d_out], replicated; added AFTER the reduce
    *,
    axis_name,
) -> jax.Array:
    """Input-dimension-sharded dense layer; the single ``psum`` of the
    column→row pair lives here."""
    y = reduce_from_tp(x_local @ w_local, axis_name)
    if b is not None:
        y = y + b
    return y


def tp_mlp(
    x: jax.Array,
    w1_local: jax.Array,  # [d, d_ff // n]
    b1_local: Optional[jax.Array],
    w2_local: jax.Array,  # [d_ff // n, d]
    b2: Optional[jax.Array],
    *,
    axis_name,
    activation: Callable[[jax.Array], jax.Array] = jax.nn.gelu,
) -> jax.Array:
    """The transformer MLP block, hidden dimension sharded: column dense →
    activation (on the shard-local hidden slice) → row dense. One forward
    ``psum``, one backward ``psum`` total."""
    h = column_parallel_dense(x, w1_local, b1_local, axis_name=axis_name)
    return row_parallel_dense(activation(h), w2_local, b2, axis_name=axis_name)


def tp_attention(
    x: jax.Array,  # [batch, seq, d_model], replicated over the model axis
    wq_local: jax.Array,  # [d_model, d_model // n] — heads sharded
    wk_local: jax.Array,
    wv_local: jax.Array,
    wo_local: jax.Array,  # [d_model // n, d_model]
    *,
    axis_name,
    n_heads: int,
    causal: bool = False,
) -> jax.Array:
    """Multi-head attention with heads sharded over the model axis (each
    shard owns ``n_heads / n`` complete heads — head count must divide).
    QKV projections are column-parallel, the attention itself is purely
    local to the shard's heads (delegated to
    :func:`chainermn_tpu.ops.attention.dot_product_attention` — one
    implementation to maintain, f32 accumulation included), and the output
    projection is row-parallel: one ``psum`` for the whole block."""
    from chainermn_tpu.ops.attention import dot_product_attention

    n = lax.axis_size(axis_name)
    if n_heads % n != 0:
        raise ValueError(f"n_heads={n_heads} not divisible by axis size {n}")
    heads_local = n_heads // n
    b, t, d_model = x.shape
    if d_model % n_heads != 0:
        raise ValueError(
            f"d_model={d_model} not divisible by n_heads={n_heads}"
        )
    head_dim = d_model // n_heads

    xc = copy_to_tp(x, axis_name)
    q = (xc @ wq_local).reshape(b, t, heads_local, head_dim)
    k = (xc @ wk_local).reshape(b, t, heads_local, head_dim)
    v = (xc @ wv_local).reshape(b, t, heads_local, head_dim)

    ctx = dot_product_attention(q, k, v, causal=causal)
    ctx = ctx.reshape(b, t, heads_local * head_dim)
    return row_parallel_dense(ctx, wo_local, axis_name=axis_name)


__all__ = [
    "copy_to_tp",
    "reduce_from_tp",
    "gather_from_tp",
    "tp_slice",
    "stack_tp_params",
    "shard_qkv_columns",
    "column_parallel_dense",
    "row_parallel_dense",
    "tp_mlp",
    "tp_attention",
]
