"""Topology-composed collective schedules — the composition DSL.

The reference hand-wrote ONE reduction pipeline per topology class
(``two_dimensional_communicator.py`` (dagger): intra reduce-scatter ->
inter allreduce -> intra all-gather, fixed) and our schedule layer
started the same way: a three-entry menu (``flat`` / ``two_level`` /
``zero``). HiCCL (arXiv:2408.05962) and The Big Send-off
(arXiv:2504.18658) make the case that the winning schedule should be
COMPOSED from primitives per topology level — on a 3-level
``(dcn, ici_y, ici_x)`` mesh the menu cannot even express the best
pipeline (e.g. the per-level ladder ``rs(ici_x) > rs(ici_y) > ar(dcn) >
ag(ici_y) > ag(ici_x)``), and an autotuner can only search what its
candidate set contains.

This module is that generalisation, in three pieces:

- a tiny DSL: a :class:`Composition` is an ordered tuple of
  :class:`Stage` s, each ``(primitive x axis-subset)`` with primitives
  ``reduce_scatter`` / ``allreduce`` / ``allgather`` /
  ``sharded_update`` (the ZeRO fuse point, arXiv:2004.13336). Each
  composition prints as a stable signature string
  (``"rs(a2)>ar(a0+a1)>ag(a2)"``) — the spelling the autotune registry,
  trace ``wire`` events and bench rows all key on;
- a VALIDATOR (:func:`validate_composition`) that proves a composition
  is a correct mean-allreduce *before* anything runs: every element
  reduced over every mesh axis exactly once, every scatter conjugated
  by a gather (LIFO, same axis group), the sharded-update placed at the
  fully-reduced shard. Violations raise :class:`CompositionError`
  naming the broken invariant;
- a DERIVER (:func:`derive_compositions`) that enumerates the legal
  reduction compositions for an arbitrary n-level mesh (per-level
  rs->ar->ag ladders, axis-merged variants, slow-axis-innermost
  orderings — ``2^k`` compositions for ``k`` axes), so schedules for
  new topologies are generated, not hand-written. The old menu entries
  are DERIVED INSTANCES: ``flat`` is ``ar(all)``, ``two_level`` is
  ``rs(fast) > ar(rest) > ag(fast)``, and ``zero`` is
  ``rs(fast) > ar(rest) > su > ag(fast)`` (``rs(all) > su > ag(all)``
  on a flat mesh).

Execution is :func:`reduce_composed` — the ONE executor every schedule
(menu name or derived signature) compiles down to, inside the named-
axis context. Its per-stage primitives are exactly the collectives the
signature predicts (:func:`predicted_collectives`), which is what the
structural HLO-count tests pin (``tests/test_composition.py``).

BUCKET SLICING (ISSUE 15): every stage is additionally addressable on a
SLICE of the bucket. A composition with ``slices=S`` cuts the bucket
into S equal contiguous slices (:func:`slice_bounds`; a bucket smaller
than S degrades to ``min(S, elements)`` slices — the
``bucket_partition`` zero-leaf contract, never an empty stage) and
software-pipelines the stages across them in skewed order
(:func:`expand_slices`): slice i's slow inter-level stage (e.g.
``ar(a0+a1)``) is issued concurrently with slice i+1's fast-axis
``rs``/``ag`` — the classic hierarchical-allreduce interleave, so the
slow axis hides behind the fast one. Spelled ``rs(a2)[s0..3]>
ar(a0+a1)>ag(a2)`` (the slice range rides the first stage); an
individual expanded stage prints as ``rs(a2)[s1:4]`` (slice 1 of 4).
The compiled HLO carries exactly S× the per-stage collective count at
1/S payload each — total wire bytes unchanged — and every sliced
composition is bitwise == its flat rendering on exact-dyadic inputs
(slices partition the bucket disjointly; each element is still reduced
over every mesh axis exactly once). The ``sharded_update`` fuse point
is unsliceable (the inner optimizer runs ONCE on the whole chunk
tree), refused loudly by the validator.

Mesh-axis convention: the tuple is in MESH ORDER, slow/DCN-most first,
fast/ICI-most last (the repo's convention) — so "scatter the fast axes
first, reduce the slow axis innermost" is "partition the reversed axis
tuple".
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Callable, Mapping, Optional, Sequence

PyTree = Any

#: Stage primitives. ``sharded_update`` is the ZeRO fuse point: the
#: caller's update function runs on the fully-reduced 1/n shard.
#: ``broadcast`` (ISSUE 16) is the one-to-many multicast-tree stage:
#: the merged group's root fans its buffer out over a radix-r tree of
#: ``ppermute`` rounds — the device-mesh rendering of the serving
#: plane's tree push (multicast-tree collectives, arXiv:2605.22428).
PRIMITIVES = ("reduce_scatter", "allreduce", "allgather", "sharded_update",
              "broadcast")

_SHORT = {"reduce_scatter": "rs", "allreduce": "ar", "allgather": "ag",
          "sharded_update": "su", "broadcast": "bc"}
_LONG = {v: k for k, v in _SHORT.items()}

#: HLO op a stage lowers to (the vocabulary of the structural tests;
#: ``sharded_update`` owes the wire nothing). A ``broadcast`` stage
#: lowers to ``tree_sends(n, radix)`` collective-permutes, not one op —
#: :func:`predicted_collectives` multiplies the sub-sends in.
STAGE_HLO = {"reduce_scatter": "reduce-scatter", "allreduce": "all-reduce",
             "allgather": "all-gather", "broadcast": "collective-permute"}

#: Default multicast-tree radix (binary tree: doubling rounds).
DEFAULT_RADIX = 2


def tree_depth(n: int, radix: int = DEFAULT_RADIX) -> int:
    """Rounds a radix-``radix`` multicast tree needs to cover ``n``
    members from one root: ``ceil(log_radix(n))``, computed by the same
    holder-doubling walk the executor runs so the two can never
    disagree. The HLO collective-permute count of a ``bc`` stage, the
    donor-send depth of the serving tree push."""
    n, r = int(n), int(radix)
    if r < 2:
        raise CompositionError(f"multicast radix must be >= 2, got {radix}")
    d, holders = 0, 1
    while holders < n:
        holders *= r
        d += 1
    return d


def tree_sends(n: int, radix: int = DEFAULT_RADIX) -> int:
    """``ppermute`` ops a radix-``radix`` multicast over ``n`` members
    lowers to. A ppermute's sources must be unique, so each holder-
    doubling round decomposes into up to ``radix - 1`` sub-sends
    (holder ``s`` -> ``s + j*holders``, one ppermute per ``j``) — at
    radix 2 this equals :func:`tree_depth`; a larger radix trades
    rounds for per-round sends (``(r-1)*ceil(log_r(n))`` at full
    occupancy). The per-stage HLO collective-permute count
    :func:`predicted_collectives` pins."""
    n, r = int(n), int(radix)
    if r < 2:
        raise CompositionError(f"multicast radix must be >= 2, got {radix}")
    sends, holders = 0, 1
    while holders < n:
        for j in range(1, r):
            if j * holders < n:  # sub-send j has at least sender s=0
                sends += 1
        holders *= r
    return sends


class CompositionError(ValueError):
    """A composition failed validation; the message names the broken
    invariant."""


@dataclasses.dataclass(frozen=True)
class Stage:
    """One pipeline stage: ``primitive`` over the merged axis group
    ``axes`` (mesh-order tuple; empty only for ``sharded_update``).

    ``slice`` (ISSUE 15) addresses the stage at ONE slice of the
    bucket: ``(index, n_slices)``, printed ``rs(a2)[s1:4]``. ``None``
    = the whole bucket (the pre-slicing spelling, unchanged). Slice-
    annotated stages appear in the EXPANDED rendering of a sliced
    composition (:func:`expand_slices`); the compact spelling keeps the
    slice count on the :class:`Composition` instead.

    ``radix`` (ISSUE 16) is the multicast-tree fan-out of a
    ``broadcast`` stage (``None`` = :data:`DEFAULT_RADIX`); printed
    only when non-default (``bc(a0+a1)@4``). Reduction stages carry no
    radix — the validator refuses one."""

    primitive: str
    axes: tuple[str, ...] = ()
    slice: Optional[tuple[int, int]] = None
    radix: Optional[int] = None

    def signature(self) -> str:
        tag = f"[s{self.slice[0]}:{self.slice[1]}]" if self.slice else ""
        if self.primitive == "sharded_update":
            return f"su{tag}"
        rad = (f"@{self.radix}"
               if self.radix is not None and self.radix != DEFAULT_RADIX
               else "")
        return f"{_SHORT[self.primitive]}({'+'.join(self.axes)}){rad}{tag}"


@dataclasses.dataclass(frozen=True)
class Composition:
    """An ordered stage list; build via :func:`parse_signature`,
    :func:`compile_schedule` or :func:`derive_compositions`, then prove
    it with :func:`validate_composition` before running it.

    ``slices`` (ISSUE 15): the bucket-slice count the executor cuts
    each bucket into (1 = the whole-bucket rendering, unchanged).
    Spelled by annotating the FIRST stage with the slice range:
    ``rs(a2)[s0..3]>ar(a0+a1)>ag(a2)`` is the two_level pipeline over
    four bucket slices.

    ``slice_layout`` (ISSUE 16 satellite): how the bucket is cut —
    ``'contiguous'`` (ISSUE 15's balanced runs) or ``'zigzag'``
    (strided: slice i takes elements ``i, i+S, i+2S, ...``, so every
    slice samples the whole bucket uniformly and the gather tails stay
    interleave-balanced at extreme S). Spelled with a ``z`` range tag:
    ``rs(a2)[z0..3]>ar(a0+a1)>ag(a2)``. Per-slice element counts are
    identical to contiguous (first ``n % S`` slices one longer), so
    wire layout and HLO counts do not move — only the cut/reassembly
    indexing does, and both layouts are bitwise-equal reductions."""

    stages: tuple[Stage, ...]
    slices: int = 1
    slice_layout: str = "contiguous"

    def signature(self) -> str:
        sigs = [s.signature() for s in self.stages]
        if self.slices > 1 and sigs:
            letter = "z" if self.slice_layout == "zigzag" else "s"
            sigs[0] = f"{sigs[0]}[{letter}0..{self.slices - 1}]"
        return ">".join(sigs)

    @property
    def has_update(self) -> bool:
        return any(s.primitive == "sharded_update" for s in self.stages)

    def split_update(self) -> tuple[tuple[Stage, ...], tuple[Stage, ...]]:
        """``(reduce_prefix, gather_suffix)`` around the
        ``sharded_update`` stage — the seam the ZeRO executors use (the
        inner optimizer runs BETWEEN them, once, on the whole chunk
        tree)."""
        for i, s in enumerate(self.stages):
            if s.primitive == "sharded_update":
                return self.stages[:i], self.stages[i + 1:]
        raise CompositionError(
            f"composition {self.signature()!r} has no sharded_update "
            "stage to split at"
        )

    def __str__(self) -> str:  # pragma: no cover - convenience
        return self.signature()


_STAGE_RE = re.compile(
    r"^(rs|ar|ag|su|bc)(?:\(([^()]*)\))?(?:@(\d+))?"
    r"(?:\[([sz])(\d+)(?:\.\.(\d+)|:(\d+))?\])?$"
)


def parse_signature(sig: str) -> Composition:
    """Parse ``"rs(a2)>ar(a0+a1)>ag(a2)"`` back into a
    :class:`Composition` (the registry stores winners as signature
    strings; this is the way back). Two slice spellings (ISSUE 15):
    a range ``rs(a2)[s0..3]>...`` marks the whole COMPOSITION sliced
    (S = range length, must start at s0; annotations on several stages
    must agree), and ``rs(a2)[s1:4]`` addresses one expanded stage at
    slice 1 of 4. A ``z`` range (``rs(a2)[z0..3]``, ISSUE 16) selects
    the zigzag slice layout — composition-level only, expanded stages
    always address contiguous slices. ``bc(a0+a1)@4`` (ISSUE 16) is a
    radix-4 multicast-tree broadcast stage (``@2`` is the default and
    never printed)."""
    stages = []
    slices: Optional[int] = None
    layout: Optional[str] = None
    for part in str(sig).split(">"):
        m = _STAGE_RE.match(part.strip())
        if not m:
            raise CompositionError(
                f"unparseable composition stage {part!r} in {sig!r} "
                "(expected e.g. 'rs(intra)', 'ar(a0+a1)', 'su', "
                "'bc(a0)@4', 'rs(a2)[s0..3]', 'rs(a2)[z0..3]', "
                "'rs(a2)[s1:4]')"
            )
        short, axes, radix, letter, s_lo, s_hi, s_tot = m.groups()
        if radix is not None and short != "bc":
            raise CompositionError(
                f"stage {part!r}: only broadcast (bc) stages carry a "
                "multicast radix"
            )
        stage_slice: Optional[tuple[int, int]] = None
        if s_lo is not None:
            if s_tot is not None:  # [sI:S] — one expanded stage
                if letter == "z":
                    raise CompositionError(
                        f"stage {part!r}: zigzag is a composition-level "
                        "slice layout — expanded stages address slices "
                        "with [sI:S]"
                    )
                idx, tot = int(s_lo), int(s_tot)
                if not 0 <= idx < tot:
                    raise CompositionError(
                        f"stage slice [s{idx}:{tot}] in {part!r} is out "
                        "of range"
                    )
                stage_slice = (idx, tot)
            else:  # [s0..N] / [z0..N] (or degenerate) — the composition
                lo = int(s_lo)
                hi = int(s_hi) if s_hi is not None else lo
                if lo != 0 or hi < lo:
                    raise CompositionError(
                        f"composition slice range [{letter}{lo}..{hi}] in "
                        f"{part!r} must start at {letter}0"
                    )
                n = hi + 1
                if slices is not None and slices != n:
                    raise CompositionError(
                        f"conflicting slice counts in {sig!r}: "
                        f"{slices} vs {n}"
                    )
                this_layout = "zigzag" if letter == "z" else "contiguous"
                if layout is not None and layout != this_layout:
                    raise CompositionError(
                        f"conflicting slice layouts in {sig!r}: "
                        f"{layout} vs {this_layout}"
                    )
                slices = n
                layout = this_layout
        if short == "su":
            if axes:
                raise CompositionError(
                    f"sharded_update stage carries no axes, got {part!r}"
                )
            stages.append(Stage("sharded_update", slice=stage_slice))
        else:
            names = tuple(a for a in (axes or "").split("+") if a)
            # an explicit @2 normalizes to the default-radix spelling
            # (signatures stay canonical: parse(sig).signature() == sig)
            r = int(radix) if radix is not None else None
            stages.append(Stage(
                _LONG[short], names, slice=stage_slice,
                radix=(r if r != DEFAULT_RADIX else None),
            ))
    return Composition(tuple(stages), slices=slices or 1,
                       slice_layout=layout or "contiguous")


def canonical_axis_names(k: int) -> tuple[str, ...]:
    """Positional axis tokens ``('a0', ..., 'a<k-1>')`` — the spelling
    the WORLD-SHAPE-keyed tuning decision uses, so a cached winner is
    portable across communicators whose meshes name their axes
    differently (``bind_composition`` maps tokens back by position)."""
    return tuple(f"a{i}" for i in range(k))


def bind_composition(comp: Composition, axes: Sequence[str]) -> Composition:
    """Rebind a composition written over :func:`canonical_axis_names`
    onto the actual mesh ``axes`` by position. A composition already
    spelled in ``axes``'s names passes through unchanged."""
    names = tuple(axes)
    used = {a for s in comp.stages for a in s.axes}
    if used <= set(names):
        return comp
    canon = canonical_axis_names(len(names))
    if not used <= set(canon):
        raise CompositionError(
            f"composition {comp.signature()!r} names axes "
            f"{sorted(used - set(names))} that are neither on the mesh "
            f"{names} nor canonical positional tokens {canon}"
        )
    table = dict(zip(canon, names))
    return dataclasses.replace(comp, stages=tuple(
        dataclasses.replace(s, axes=tuple(table[a] for a in s.axes))
        for s in comp.stages
    ))


# ---------------------------------------------------------------------------
# Bucket slicing (ISSUE 15)
# ---------------------------------------------------------------------------


def effective_slices(slices: int, n_elems: int) -> int:
    """The slice count a bucket of ``n_elems`` elements actually cuts
    into: ``min(slices, n_elems)``, floored at 1 — a bucket smaller
    than the requested slice count DEGRADES instead of emitting an
    empty stage or a zero-size collective (the ``bucket_partition``
    zero-leaf contract, ISSUE 15 satellite; callers that degrade
    record the requested vs effective counts as provenance)."""
    s = int(slices)
    if s < 1:
        raise CompositionError(f"slices must be >= 1, got {slices}")
    return max(1, min(s, int(n_elems)))


def slice_bounds(n_elems: int, n_slices: int) -> list[tuple[int, int]]:
    """Balanced contiguous ``[start, end)`` bounds cutting ``n_elems``
    into ``n_slices`` slices (first ``n % S`` slices one element
    longer). The bounds are disjoint, cover the bucket exactly, and —
    given ``n_slices <= n_elems``, which :func:`effective_slices`
    guarantees — never empty: the structural half of the "every
    element reduced exactly once across slices" invariant."""
    n, s = int(n_elems), int(n_slices)
    if s < 1:
        raise CompositionError(f"slice count must be >= 1, got {n_slices}")
    base, rem = divmod(n, s)
    out = []
    lo = 0
    for i in range(s):
        hi = lo + base + (1 if i < rem else 0)
        out.append((lo, hi))
        lo = hi
    return out


def sliced_composition(comp: Composition, slices: int,
                       layout: str = "contiguous") -> Composition:
    """``comp`` re-rendered over ``slices`` bucket slices (the compact
    form — :func:`expand_slices` produces the per-slice stage list).
    Refuses a ``sharded_update`` pipeline: the ZeRO fuse point runs the
    inner optimizer ONCE on the whole chunk tree and cannot slice.
    ``layout`` (ISSUE 16 satellite) picks the cut: ``'contiguous'``
    runs or the ``'zigzag'`` stride (see :class:`Composition`)."""
    s = int(slices)
    if s < 1:
        raise CompositionError(f"slices must be >= 1, got {slices}")
    if layout not in ("contiguous", "zigzag"):
        raise CompositionError(
            f"slice layout must be 'contiguous' or 'zigzag', got "
            f"{layout!r}"
        )
    if s > 1 and comp.has_update:
        raise CompositionError(
            f"{comp.signature()!r}: a sharded_update pipeline cannot be "
            "sliced — the fuse point runs the inner optimizer once on "
            "the whole chunk tree"
        )
    return dataclasses.replace(comp, slices=s, slice_layout=layout)


def compact_slices(comp: Composition) -> Composition:
    """Reconstitute an EXPANDED composition (per-stage ``[sI:S]``
    addresses) back into the compact ``slices=S`` form the executors
    run — the inverse of :func:`expand_slices`. Unannotated
    compositions pass through unchanged. Every slice must run the SAME
    base pipeline (a heterogeneous expansion validates mathematically
    but has no compact rendering to execute) and the composition must
    have passed :func:`validate_composition` first — this only
    re-groups, it does not re-prove."""
    if not any(s.slice is not None for s in comp.stages):
        return comp
    per_slice: dict[int, list[Stage]] = {}
    total = 0
    for s in comp.stages:
        if s.slice is None:
            raise CompositionError(
                f"{comp.signature()!r}: stage {s.signature()!r} has no "
                "slice address while others do"
            )
        per_slice.setdefault(s.slice[0], []).append(
            dataclasses.replace(s, slice=None))
        total = max(total, s.slice[1])
    base = per_slice.get(0)
    if base is None or sorted(per_slice) != list(range(total)):
        raise CompositionError(
            f"{comp.signature()!r}: slice indices do not cover "
            f"0..{total - 1}"
        )
    for i, stages in per_slice.items():
        if stages != base:
            raise CompositionError(
                f"{comp.signature()!r}: slice s{i} runs a different "
                f"pipeline than slice s0 "
                f"({'>'.join(s.signature() for s in stages)} vs "
                f"{'>'.join(s.signature() for s in base)}) — only a "
                "uniform expansion has a compact executable rendering"
            )
    return Composition(tuple(base), slices=total)


def expand_slices(
    comp: Composition, size: Optional[int] = None
) -> tuple[Stage, ...]:
    """The sliced composition's per-slice stage list in SOFTWARE-
    PIPELINED (skewed) issue order: tick t issues stage j of slice i
    for every ``i + j == t`` (later slices first within a tick), so
    slice i's slow inter-level stage is in flight while slice i+1 runs
    its fast-axis stage — the interleave that lets the slow axis hide
    behind the fast one. Each emitted :class:`Stage` carries its
    ``slice=(i, S)`` address. ``size`` (bucket element count) applies
    the :func:`effective_slices` degrade; an unsliced composition
    expands to its own stages unchanged."""
    s_eff = (effective_slices(comp.slices, size) if size is not None
             else comp.slices)
    if s_eff <= 1:
        return comp.stages
    k = len(comp.stages)
    out: list[Stage] = []
    for t in range(s_eff + k - 1):
        for j in range(k):
            i = t - j
            if 0 <= i < s_eff:
                out.append(dataclasses.replace(
                    comp.stages[j], slice=(i, s_eff)))
    return tuple(out)


# ---------------------------------------------------------------------------
# Validator: prove the composition is a correct mean-allreduce
# ---------------------------------------------------------------------------


def validate_composition(
    comp: Composition, mesh_axes: Sequence[str]
) -> Composition:
    """Prove ``comp`` is a correct mean-allreduce over ``mesh_axes``
    BEFORE anything runs. Invariants (each violation raises
    :class:`CompositionError` naming it):

    - the stage list is non-empty and every primitive is known;
    - every reduce/scatter/gather stage names >= 1 mesh axis, no axis
      twice within a stage;
    - every mesh axis is REDUCED EXACTLY ONCE (by a ``reduce_scatter``
      or ``allreduce`` stage) — a missed axis leaves a partial sum, a
      doubled axis over-reduces;
    - scatters and gathers are CONJUGATE: each ``allgather`` closes the
      most recent open ``reduce_scatter`` with the SAME axis group
      (LIFO), and no scatter is left open at the end — otherwise the
      output shards don't reassemble to the input layout;
    - at most one ``sharded_update``, placed at the fully-reduced shard:
      after every reduction, before every gather, with at least one
      scatter open (otherwise the update is not sharded — that is the
      plain post-reduction update, not a composition stage).

    Sliced compositions (ISSUE 15) add:

    - ``slices`` is an integer >= 1; a sliced composition must not
      carry a ``sharded_update`` (the fuse point is unsliceable);
    - an EXPANDED composition (stages carrying ``slice`` addresses):
      every stage is addressed or none, all totals agree, every slice
      index 0..S-1 appears, and each slice's stage subsequence is
      independently a complete, conjugate mean-allreduce — PER-SLICE
      CONJUGACY. Together with :func:`slice_bounds`' disjoint cover,
      that is "every element reduced exactly once across slices".
    """
    mesh = tuple(mesh_axes)
    if not isinstance(comp, Composition):
        raise CompositionError(
            f"expected a Composition, got {type(comp).__name__}"
        )
    if not comp.stages:
        raise CompositionError(
            "empty stage list: a composition must reduce over "
            f"{mesh} and an empty pipeline reduces nothing"
        )
    if not isinstance(comp.slices, int) or comp.slices < 1:
        raise CompositionError(
            f"{comp.signature()!r}: slices must be an integer >= 1, "
            f"got {comp.slices!r}"
        )
    if comp.slice_layout not in ("contiguous", "zigzag"):
        raise CompositionError(
            f"{comp.signature()!r}: slice layout must be 'contiguous' "
            f"or 'zigzag', got {comp.slice_layout!r}"
        )
    sliced = [s for s in comp.stages if s.slice is not None]
    if comp.has_update and (comp.slices > 1 or sliced):
        raise CompositionError(
            f"{comp.signature()!r}: a sliced composition cannot carry a "
            "sharded_update stage — the ZeRO fuse point runs the inner "
            "optimizer once on the whole chunk tree and is unsliceable"
        )
    if sliced:
        if comp.slices > 1:
            raise CompositionError(
                f"{comp.signature()!r}: both a composition-level slice "
                f"count ({comp.slices}) and per-stage slice addresses — "
                "spell one form (compact slices= OR the expanded "
                "per-stage [sI:S] addressing), not both"
            )
        if len(sliced) != len(comp.stages):
            bare = next(s for s in comp.stages if s.slice is None)
            raise CompositionError(
                f"{comp.signature()!r}: stage {bare.signature()!r} has "
                "no slice address while others do — an expanded "
                "composition addresses every stage"
            )
        totals = {s.slice[1] for s in comp.stages}
        if len(totals) != 1:
            raise CompositionError(
                f"{comp.signature()!r}: conflicting slice totals "
                f"{sorted(totals)} — every stage of one expansion "
                "shares one slice count"
            )
        total = totals.pop()
        per_slice: dict[int, list[Stage]] = {}
        for s in comp.stages:
            per_slice.setdefault(s.slice[0], []).append(
                dataclasses.replace(s, slice=None))
        missing = [i for i in range(total) if i not in per_slice]
        if missing:
            raise CompositionError(
                f"{comp.signature()!r}: slice(s) {missing} have no "
                f"stages — {total} slices were addressed and each "
                "must run the full pipeline (its elements would "
                "otherwise never be reduced)"
            )
        for i in range(total):
            try:
                _validate_walk(
                    Composition(tuple(per_slice[i])), mesh
                )
            except CompositionError as e:
                raise CompositionError(
                    f"slice s{i}:{total}: {e}"
                ) from None
        return comp
    _validate_walk(comp, mesh)
    return comp


def _validate_walk(comp: Composition, mesh: tuple) -> Composition:
    """Route one pipeline's stage list to its family walk: a pipeline
    with any ``broadcast`` stage is the BROADCAST FAMILY (all stages
    bc — :func:`_validate_broadcast_walk`), everything else is the
    reduction family (:func:`_validate_stage_walk`). The two families
    never mix in one pipeline: a broadcast inside a reduction would
    overwrite partially-reduced shards with the root's, and a
    reduction inside a broadcast has nothing summed to reduce."""
    if any(s.primitive == "broadcast" for s in comp.stages):
        return _validate_broadcast_walk(comp, mesh)
    return _validate_stage_walk(comp, mesh)


def _validate_broadcast_walk(comp: Composition, mesh: tuple) -> Composition:
    """The broadcast-family walk (ISSUE 16): every stage is ``bc``,
    every mesh axis is broadcast EXACTLY ONCE (a missed axis leaves
    stale replicas, a doubled axis re-sends bytes the first tree
    already delivered), radix >= 2, no ``sharded_update`` (nothing is
    reduced, so there is no fully-reduced shard to fuse at)."""
    covered: list[str] = []
    for st in comp.stages:
        if st.primitive != "broadcast":
            raise CompositionError(
                f"{comp.signature()!r}: {st.signature()} mixed into a "
                "broadcast pipeline — bc stages never compose with "
                "reduction stages (the tree would overwrite partial "
                "sums with the root's buffer)"
            )
        if not st.axes:
            raise CompositionError(
                f"{comp.signature()!r}: broadcast stage with an empty "
                "axis group — every tree names the axes it fans over"
            )
        if len(set(st.axes)) != len(st.axes):
            raise CompositionError(
                f"{comp.signature()!r}: duplicate axis within stage "
                f"{st.signature()!r}"
            )
        for a in st.axes:
            if a not in mesh:
                raise CompositionError(
                    f"{comp.signature()!r}: axis {a!r} is not on the "
                    f"mesh {mesh}"
                )
            if a in covered:
                raise CompositionError(
                    f"{comp.signature()!r}: axis {a!r} broadcast more "
                    "than once — the second tree re-sends bytes the "
                    "first already delivered"
                )
        if st.radix is not None and st.radix < 2:
            raise CompositionError(
                f"{comp.signature()!r}: multicast radix must be >= 2, "
                f"got {st.radix}"
            )
        covered.extend(st.axes)
    missing = [a for a in mesh if a not in covered]
    if missing:
        raise CompositionError(
            f"{comp.signature()!r}: axes {tuple(missing)} never "
            "broadcast — those mesh levels would keep stale replicas"
        )
    return comp


def _validate_stage_walk(comp: Composition, mesh: tuple) -> Composition:
    """The per-stage invariant walk over ONE pipeline's stage list
    (:func:`validate_composition` runs it once for an unsliced/compact
    composition and once PER SLICE for an expanded one — per-slice
    conjugacy is literally the same walk)."""
    reduced: list[str] = []
    open_scatters: list[tuple[str, ...]] = []
    update_seen = False
    for st in comp.stages:
        if st.primitive not in PRIMITIVES:
            raise CompositionError(
                f"unknown primitive {st.primitive!r} (stages compose "
                f"{PRIMITIVES})"
            )
        if st.radix is not None:
            raise CompositionError(
                f"{comp.signature()!r}: stage {st.signature()!r} carries "
                "a multicast radix — only broadcast (bc) stages fan "
                "over a tree"
            )
        if st.primitive == "sharded_update":
            if update_seen:
                raise CompositionError(
                    f"{comp.signature()!r}: more than one sharded_update "
                    "stage — the ZeRO fuse point is single"
                )
            if set(reduced) != set(mesh):
                raise CompositionError(
                    f"{comp.signature()!r}: sharded_update before every "
                    f"axis is reduced (reduced {tuple(reduced)}, mesh "
                    f"{mesh}) — the update must see the fully-reduced "
                    "mean chunk"
                )
            if not open_scatters:
                raise CompositionError(
                    f"{comp.signature()!r}: sharded_update with no open "
                    "reduce_scatter — the update would not be sharded "
                    "(that is a plain post-reduction update, not a "
                    "composition stage)"
                )
            update_seen = True
            continue
        if not st.axes:
            raise CompositionError(
                f"{comp.signature()!r}: {st.primitive} stage with an "
                "empty axis group — every collective stage names the "
                "axes it rides"
            )
        if len(set(st.axes)) != len(st.axes):
            raise CompositionError(
                f"{comp.signature()!r}: duplicate axis within stage "
                f"{st.signature()!r}"
            )
        for a in st.axes:
            if a not in mesh:
                raise CompositionError(
                    f"{comp.signature()!r}: axis {a!r} is not on the "
                    f"mesh {mesh}"
                )
        if st.primitive in ("reduce_scatter", "allreduce"):
            if update_seen:
                raise CompositionError(
                    f"{comp.signature()!r}: {st.signature()} after the "
                    "sharded_update — every reduction precedes the fuse "
                    "point"
                )
            dup = [a for a in st.axes if a in reduced]
            if dup:
                raise CompositionError(
                    f"{comp.signature()!r}: axis {dup[0]!r} reduced more "
                    "than once — the mean would be over-divided"
                )
            reduced.extend(st.axes)
            if st.primitive == "reduce_scatter":
                open_scatters.append(st.axes)
        else:  # allgather
            if not open_scatters:
                raise CompositionError(
                    f"{comp.signature()!r}: {st.signature()} with no open "
                    "reduce_scatter to conjugate"
                )
            top = open_scatters.pop()
            if top != st.axes:
                raise CompositionError(
                    f"{comp.signature()!r}: {st.signature()} does not "
                    f"conjugate the open reduce_scatter over {top} — "
                    "scatter/gather pairs close LIFO with the same axis "
                    "group"
                )
    missing = [a for a in mesh if a not in reduced]
    if missing:
        raise CompositionError(
            f"{comp.signature()!r}: axes {tuple(missing)} never reduced "
            "— the result would not be the mean over the mesh"
        )
    if open_scatters:
        raise CompositionError(
            f"{comp.signature()!r}: reduce_scatter over "
            f"{open_scatters[-1]} never gathered back — the output "
            "would stay sharded"
        )
    return comp


def predicted_collectives(
    comp: Composition, size: Optional[int] = None,
    axis_sizes: Optional[Mapping[str, int]] = None,
) -> dict[str, int]:
    """HLO collective counts the compiled program must carry — one op
    per stage PER SLICE (``tests/test_composition.py`` compiles and
    compares): a sliced composition carries exactly S× the per-stage
    count at 1/S payload each. ``size`` (bucket element count) applies
    the :func:`effective_slices` degrade; without it the requested
    slice count is assumed achievable.

    A ``broadcast`` stage (ISSUE 16) lowers to ``tree_sends(n, radix)``
    collective-permutes, not one op, so its count needs the merged
    group size — pass ``axis_sizes`` (axis name -> size) for any
    composition carrying a bc stage; the ``"collective-permute"`` key
    appears ONLY then (reduction-only counts keep the exact three-key
    dict the structural tests compare against)."""
    s_eff = (effective_slices(comp.slices, size) if size is not None
             else comp.slices)
    out = {"reduce-scatter": 0, "all-reduce": 0, "all-gather": 0}
    if any(st.primitive == "broadcast" for st in comp.stages):
        out["collective-permute"] = 0
    for st in comp.stages:
        hlo = STAGE_HLO.get(st.primitive)
        if hlo is None:
            continue
        if st.primitive == "broadcast":
            if axis_sizes is None:
                raise CompositionError(
                    f"predicted_collectives: broadcast stage "
                    f"{st.signature()!r} lowers to tree_sends(n, radix) "
                    "collective-permutes — pass axis_sizes to size the "
                    "merged group"
                )
            n = 1
            for a in st.axes:
                n *= int(axis_sizes[a])
            out[hlo] += tree_sends(n, st.radix or DEFAULT_RADIX) * s_eff
        else:
            out[hlo] += s_eff
    return out


# ---------------------------------------------------------------------------
# Deriver: enumerate the legal compositions for an n-level mesh
# ---------------------------------------------------------------------------


def _contiguous_partitions(items: tuple) -> list[list[tuple]]:
    """All ordered partitions of ``items`` into contiguous groups."""
    if not items:
        return [[]]
    out = []
    for i in range(1, len(items) + 1):
        head = items[:i]
        for rest in _contiguous_partitions(items[i:]):
            out.append([head] + rest)
    return out


def derive_compositions(mesh_axes: Sequence[str]) -> tuple[Composition, ...]:
    """Enumerate the legal mean-allreduce compositions for a mesh.

    Recipe: reverse the axis tuple (fast level scatters first, slow
    level reduces innermost — the dcn-last ordering), partition it into
    contiguous LEVEL GROUPS (axis-merged variants: one collective per
    group over the merged axes), scatter every outer group, reduce the
    innermost group by either an ``allreduce`` or its own
    ``reduce_scatter``/``allgather`` pair, and conjugate-gather back
    out. ``2^k`` compositions for ``k`` axes — the menu's entries fall
    out as instances (``flat`` = the one-group allreduce,
    ``two_level`` = the ((fast), (rest)) split), and the rest are the
    pipelines the menu could not express (per-level ladders, merged
    scatters, scattered-slow-level variants). Every derived composition
    passes :func:`validate_composition` by construction (property-swept
    in the tests anyway).
    """
    names = tuple(mesh_axes)
    if not names:
        raise CompositionError("derive_compositions: empty mesh axis tuple")
    seen = set()
    out: list[Composition] = []
    for parts in _contiguous_partitions(names[::-1]):
        # each group back in mesh order for readable signatures
        groups = [tuple(sorted(g, key=names.index)) for g in parts]
        outer, inner = groups[:-1], groups[-1]
        for innermost in ("allreduce", "reduce_scatter"):
            stages = [Stage("reduce_scatter", g) for g in outer]
            stages.append(Stage(innermost, inner))
            if innermost == "reduce_scatter":
                stages.append(Stage("allgather", inner))
            stages.extend(Stage("allgather", g) for g in reversed(outer))
            comp = Composition(tuple(stages))
            sig = comp.signature()
            if sig not in seen:
                seen.add(sig)
                out.append(validate_composition(comp, names))
    return tuple(out)


def flat_composition(mesh_axes: Sequence[str]) -> Composition:
    """``flat`` as a derived instance: one fused allreduce over the
    merged axes."""
    return Composition((Stage("allreduce", tuple(mesh_axes)),))


def two_level_composition(mesh_axes: Sequence[str]) -> Composition:
    """``two_level`` as a derived instance: scatter the last (fast)
    axis, allreduce the shard over the rest, gather back — the
    reference's ``TwoDimensionalCommunicator`` pipeline
    (``two_dimensional_communicator.py`` (dagger)). On a flat mesh the
    rest is empty and this is the pinned rs->ag decomposition."""
    names = tuple(mesh_axes)
    fast, rest = (names[-1],), names[:-1]
    stages = [Stage("reduce_scatter", fast)]
    if rest:
        stages.append(Stage("allreduce", rest))
    stages.append(Stage("allgather", fast))
    return Composition(tuple(stages))


def zero_composition(mesh_axes: Sequence[str]) -> Composition:
    """``zero`` as a derived instance: the two_level reduction with the
    sharded update fused at the fully-reduced chunk —
    ``rs(all) > su > ag(all)`` on a flat mesh (arXiv:2004.13336),
    ``rs(fast) > ar(rest) > su > ag(fast)`` on a hierarchical one (the
    exact pipeline ``MultiNodeOptimizer._zero_update`` and the
    ParallelPlan zero group hand-wired before this layer existed)."""
    names = tuple(mesh_axes)
    fast, rest = (names[-1],), names[:-1]
    stages = [Stage("reduce_scatter", fast)]
    if rest:
        stages.append(Stage("allreduce", rest))
    stages.append(Stage("sharded_update"))
    stages.append(Stage("allgather", fast))
    return Composition(tuple(stages))


def broadcast_composition(
    mesh_axes: Sequence[str], radix: int = DEFAULT_RADIX
) -> Composition:
    """One multicast tree over the merged mesh axes (ISSUE 16): the
    root of the flattened group fans its buffer out in
    ``tree_depth(n, radix)`` ppermute rounds — the device-mesh
    rendering of the serving plane's one-to-many tree push. Spelled
    ``bc(a0+a1+a2)`` (``@r`` when the radix is non-default)."""
    r = int(radix)
    if r < 2:
        raise CompositionError(f"multicast radix must be >= 2, got {radix}")
    return Composition((Stage(
        "broadcast", tuple(mesh_axes),
        radix=(r if r != DEFAULT_RADIX else None),
    ),))


def compile_schedule(schedule, mesh_axes: Sequence[str]) -> Composition:
    """Lower a schedule spelling to a validated :class:`Composition`:
    a menu name (``'flat'``/``'two_level'``/``'zero'``), a signature
    string (actual axis names or canonical positional tokens), or a
    ``Composition`` instance. This is the ONE front door every executor
    call site uses — the menu entries are compiled, not special-cased.
    """
    names = tuple(mesh_axes)
    if isinstance(schedule, Composition):
        # compact_slices: an EXPANDED spelling (per-stage [sI:S]
        # addresses) validates but only the compact slices=S form is
        # executable — reconstitute it here, the one front door, so no
        # executor ever sees stage-addressed pipelines (review finding).
        return compact_slices(validate_composition(
            bind_composition(schedule, names), names))
    if schedule == "flat":
        return flat_composition(names)
    if schedule == "two_level":
        return two_level_composition(names)
    if schedule == "zero":
        return zero_composition(names)
    if isinstance(schedule, str) and (">" in schedule or "(" in schedule):
        comp = parse_signature(schedule)
        return compact_slices(validate_composition(
            bind_composition(comp, names), names))
    from chainermn_tpu.parallel.reduction_schedule import SCHEDULES

    raise CompositionError(
        f"unknown schedule {schedule!r}: expected one of {SCHEDULES}, a "
        "composition signature (e.g. 'rs(a1)>ar(a0)>ag(a1)'), or a "
        "Composition"
    )


def schedule_candidates(n_axes: int) -> tuple[str, ...]:
    """The ``reduction_schedule`` decision's candidate set for a
    ``n_axes``-level world shape: the legacy menu names first (cache
    back-compat — existing entries keep resolving, and the table default
    ``'flat'`` stays a member), then the DERIVED compositions the menu
    cannot express, keyed by canonical-token signature string. This is
    what makes the autotuner search generated schedules instead of a
    fixed menu."""
    from chainermn_tpu.parallel.reduction_schedule import SCHEDULES

    names = canonical_axis_names(max(1, int(n_axes)))
    menu_sigs = {flat_composition(names).signature(),
                 two_level_composition(names).signature()}
    derived = tuple(
        c.signature() for c in derive_compositions(names)
        if c.signature() not in menu_sigs
    )
    return tuple(SCHEDULES) + derived


def normalize_schedule_name(schedule: str, n_axes: int) -> str:
    """Map a menu-instance SIGNATURE back to its menu name — the
    spelling :func:`schedule_candidates` (and therefore the registry's
    candidate matching) uses. A composed sweep times every derived
    pipeline by signature, and ``flat``/``two_level`` are among them as
    ``ar(all)`` / ``rs(fast)>ar(rest)>ag(fast)``: adopting such a
    winner under its signature would store a cache entry the candidate
    list never matches (silently discarded, table default wins).
    Non-menu signatures and menu names pass through unchanged."""
    names = canonical_axis_names(max(1, int(n_axes)))
    table = {
        flat_composition(names).signature(): "flat",
        two_level_composition(names).signature(): "two_level",
        zero_composition(names).signature(): "zero",
    }
    return table.get(schedule, schedule)


def signature_for(schedule, n_axes: int) -> str:
    """Canonical-token signature for a winner string (menu name or
    signature) — the provenance spelling ``resolve_schedule`` reports,
    so a decision record names the actual pipeline, not just the menu
    label."""
    names = canonical_axis_names(max(1, int(n_axes)))
    return compile_schedule(schedule, names).signature()


# ---------------------------------------------------------------------------
# Executor: one staged interpreter for every composition
# ---------------------------------------------------------------------------


def _axes_arg(axes: tuple[str, ...]):
    return axes if len(axes) > 1 else axes[0]


def _replay_sizes(stages: Sequence[Stage], size: int, axis_sizes):
    """Static walk of the scatter frame: per-stage (size_in, size_out)
    element counts and the LIFO scatter stack — shared by the executor,
    the split ZeRO runners and the trace-time wire layout, so no two
    consumers can disagree about padding."""
    cur = int(size)
    stack: list[tuple[tuple[str, ...], int]] = []
    rows: list[tuple[Stage, int, int]] = []
    for st in stages:
        if st.primitive == "reduce_scatter":
            n = 1
            for a in st.axes:
                n *= int(axis_sizes[a])
            out = -(-cur // n)  # ceil: the padded shard length
            stack.append((st.axes, cur))
            rows.append((st, cur, out))
            cur = out
        elif st.primitive == "allgather":
            axes, orig = stack.pop()
            rows.append((st, cur, orig))
            cur = orig
        else:  # allreduce / sharded_update / broadcast: size unchanged
            rows.append((st, cur, cur))
    return rows, cur, stack


def stage_wire_layout(
    comp: Composition, axis_sizes: Mapping[str, int], itemsize: int,
    size: int,
) -> list[dict]:
    """Host-side per-stage wire table for one bucket of ``size``
    elements at ``itemsize`` wire bytes each: the payload bytes each
    collective stage carries (full buffer into a scatter / out of a
    gather, the reduced shard through an allreduce). This is what the
    trace ``wire`` events record per stage and what
    ``tools/trace_report.py``'s overlap section tabulates per
    composition signature.

    A SLICED composition (ISSUE 15) emits one row per stage PER SLICE,
    in the executor's skewed interleave order; each row additionally
    carries ``slice`` / ``n_slices`` (the effective, possibly degraded
    count) and that slice's own payload bytes — summed over slices the
    per-stage wire bytes equal the unsliced rendering's."""
    comp = compact_slices(comp)  # expanded spellings lay out compacted
    s_eff = effective_slices(comp.slices, size)
    if s_eff <= 1:
        rows, _, _ = _replay_sizes(comp.stages, size, axis_sizes)
        out = []
        for st, size_in, size_out in rows:
            hlo = STAGE_HLO.get(st.primitive)
            if hlo is None:
                continue
            nbytes = max(size_in, size_out) * itemsize
            row = {"stage": st.signature(), "op": hlo, "nbytes": nbytes}
            if st.primitive == "broadcast":
                n = 1
                for a in st.axes:
                    n *= int(axis_sizes[a])
                row["rounds"] = tree_depth(n, st.radix or DEFAULT_RADIX)
            out.append(row)
        return out
    bounds = slice_bounds(size, s_eff)
    # per-slice stage rows, keyed back to the BASE stage signature (the
    # spelling trace_report groups on); order = the skewed interleave.
    per_slice_rows = [
        {(st.signature(), j): (st, size_in, size_out)
         for j, (st, size_in, size_out) in enumerate(
             _replay_sizes(comp.stages, hi - lo, axis_sizes)[0])}
        for lo, hi in bounds
    ]
    out = []
    for st in expand_slices(comp, size):
        i, _ = st.slice
        base = dataclasses.replace(st, slice=None)
        j = comp.stages.index(base)
        hlo = STAGE_HLO.get(st.primitive)
        if hlo is None:
            continue
        _, size_in, size_out = per_slice_rows[i][(base.signature(), j)]
        row = {
            "stage": base.signature(), "op": hlo,
            "nbytes": max(size_in, size_out) * itemsize,
            "slice": i, "n_slices": s_eff,
        }
        if st.primitive == "broadcast":
            n = 1
            for a in st.axes:
                n *= int(axis_sizes[a])
            row["rounds"] = tree_depth(n, st.radix or DEFAULT_RADIX)
        out.append(row)
    return out


def reduce_composed(
    x,
    comp: Composition,
    *,
    op: str = "mean",
    update_fn: Optional[Callable] = None,
) -> Any:
    """Run ``comp`` on one buffer inside its named-axis context — THE
    executor every schedule lowers to. Stage semantics:

    - ``reduce_scatter``: ceil-pad the flat buffer into ``[n, c]`` rows
      over the stage's merged axis group and ``psum_scatter`` it (the
      shard is this member's exactly-summed 1/n slice);
    - ``allreduce``: ``psum`` over the group;
    - ``allgather``: conjugate gather of the matching scatter, un-pad;
    - ``sharded_update``: call ``update_fn`` on the fully-reduced
      shard (the ZeRO fuse point).

    The mean division lands immediately after the stage that completes
    the reduction over every mesh axis — exactly where
    ``decomposed_allreduce`` divides, so the menu schedules compile to
    byte-identical programs through this path. The single-stage
    ``ar(all)`` composition short-circuits to ``lax.pmean`` (the
    legacy ``flat`` program, literally).

    A SLICED composition (``comp.slices > 1``, ISSUE 15) cuts the flat
    buffer into ``effective_slices`` contiguous slices and issues the
    stages in the skewed interleave order (:func:`expand_slices`):
    the slices are data-independent, so slice i's slow stage and slice
    i+1's fast stage are concurrently schedulable — S× the per-stage
    collectives at 1/S payload, total wire bytes unchanged, and the
    concatenated result bitwise == the unsliced rendering on exact-
    dyadic inputs (each element still reduced over every axis exactly
    once).
    """
    from jax import lax

    from chainermn_tpu.parallel.collectives import (
        staged_allgather,
        staged_allreduce,
        staged_broadcast,
        staged_reduce_scatter,
    )

    if op not in ("sum", "mean"):
        raise ValueError(f"op must be 'sum' or 'mean', got {op!r}")
    comp = compact_slices(comp)  # expanded spellings run compacted
    stages = comp.stages
    if comp.has_update and update_fn is None:
        raise ValueError(
            f"composition {comp.signature()!r} has a sharded_update "
            "stage but no update_fn was given"
        )
    reduce_axes = tuple(
        a for s in stages
        if s.primitive in ("reduce_scatter", "allreduce") for a in s.axes
    )
    n_tot = 1
    for a in reduce_axes:
        n_tot *= lax.axis_size(a)
    # A broadcast-family pipeline reduces nothing: start the mean guard
    # already tripped so it never divides (n_tot is 1 anyway, but the
    # guard documents the invariant instead of relying on /1).
    rem_init = len(reduce_axes) if reduce_axes else -1

    s_eff = effective_slices(comp.slices, x.size)
    if s_eff > 1:
        if comp.has_update:
            raise CompositionError(
                f"{comp.signature()!r}: sliced execution with a "
                "sharded_update stage — the fuse point is unsliceable"
            )
        zigzag = comp.slice_layout == "zigzag"
        flat = x.reshape(-1)
        bounds = slice_bounds(flat.size, s_eff)
        # Per-slice pipeline state, stepped in the skewed interleave
        # order — each slice owns its scatter frame and divides once
        # when ITS reduction completes. The zigzag layout (ISSUE 16)
        # strides the cut — slice i = elements i, i+S, i+2S, ... — with
        # per-slice element counts identical to the contiguous bounds,
        # so only the indexing differs, never the wire.
        if zigzag:
            cur_s = [flat[i::s_eff] for i in range(s_eff)]
        else:
            cur_s = [flat[lo:hi] for lo, hi in bounds]
        stack_s: list[list[int]] = [[] for _ in range(s_eff)]
        rem_s = [rem_init] * s_eff
        for st in expand_slices(comp, flat.size):
            i, _ = st.slice
            if st.primitive == "reduce_scatter":
                stack_s[i].append(cur_s[i].size)
                cur_s[i] = staged_reduce_scatter(cur_s[i], st.axes)
                rem_s[i] -= len(st.axes)
            elif st.primitive == "allreduce":
                cur_s[i] = staged_allreduce(cur_s[i], st.axes)
                rem_s[i] -= len(st.axes)
            elif st.primitive == "broadcast":
                cur_s[i] = staged_broadcast(
                    cur_s[i], st.axes, radix=st.radix or DEFAULT_RADIX)
            else:  # allgather
                cur_s[i] = staged_allgather(
                    cur_s[i], st.axes, stack_s[i].pop())
            if rem_s[i] == 0 and op == "mean":
                cur_s[i] = cur_s[i] / n_tot
                rem_s[i] = -1  # divide exactly once per slice
        import jax.numpy as jnp

        if zigzag:
            out = jnp.zeros(flat.shape, cur_s[0].dtype)
            for i in range(s_eff):
                out = out.at[i::s_eff].set(cur_s[i])
            return out.reshape(x.shape)
        return jnp.concatenate(cur_s).reshape(x.shape)

    # flat short-circuit: one fused pmean, the pre-composition program.
    if (len(stages) == 1 and stages[0].primitive == "allreduce"
            and op == "mean"):
        return lax.pmean(x, _axes_arg(stages[0].axes))
    shape = x.shape
    cur = x.reshape(-1)
    stack: list[int] = []  # original sizes, LIFO with the scatters
    remaining = rem_init
    for st in stages:
        if st.primitive == "reduce_scatter":
            stack.append(cur.size)
            cur = staged_reduce_scatter(cur, st.axes)
            remaining -= len(st.axes)
        elif st.primitive == "allreduce":
            cur = staged_allreduce(cur, st.axes)
            remaining -= len(st.axes)
        elif st.primitive == "allgather":
            cur = staged_allgather(cur, st.axes, stack.pop())
        elif st.primitive == "broadcast":
            cur = staged_broadcast(
                cur, st.axes, radix=st.radix or DEFAULT_RADIX)
        else:  # sharded_update
            cur = update_fn(cur)
        if remaining == 0 and op == "mean":
            cur = cur / n_tot
            remaining = -1  # divide exactly once
    return cur.reshape(shape)


# -- split execution around the ZeRO fuse point -----------------------------


def run_reduce_prefix(
    g,
    stages: Sequence[Stage],
    *,
    total: int,
    wire_dtype=None,
):
    """Run a composition's reduce prefix (the stages before
    ``sharded_update``) on one leaf: flatten, optionally cast to the
    compressed wire dtype, scatter/reduce per stage, divide by
    ``total`` (the full data-parallel degree) and return the mean chunk
    in the leaf's dtype — exactly the hand-wired
    ``zero_grad_scatter``/``MultiNodeOptimizer._zero_update`` scatter
    arithmetic, now derived from the composition."""
    import jax.numpy as jnp

    from chainermn_tpu.parallel.collectives import (
        staged_allreduce,
        staged_reduce_scatter,
    )

    cur = g.reshape(-1)
    if wire_dtype is not None and jnp.issubdtype(g.dtype, jnp.floating):
        cur = cur.astype(wire_dtype)
    for st in stages:
        if st.primitive == "reduce_scatter":
            cur = staged_reduce_scatter(cur, st.axes)
        elif st.primitive == "allreduce":
            cur = staged_allreduce(cur, st.axes)
        else:
            raise CompositionError(
                f"{st.signature()}: only reduce stages run before the "
                "sharded_update"
            )
    return (cur / total).astype(g.dtype)


def run_gather_suffix(
    u_chunk,
    like,
    stages: Sequence[Stage],
    prefix: Sequence[Stage],
):
    """Run a composition's gather suffix (the stages after
    ``sharded_update``) on one updated chunk, reassembling ``like``'s
    shape/dtype. The un-pad sizes replay the prefix's static scatter
    frame (:func:`_replay_sizes`), so prefix and suffix can never
    disagree about the padding."""
    from jax import lax

    from chainermn_tpu.parallel.collectives import staged_allgather

    axis_sizes = {}
    for st in tuple(prefix) + tuple(stages):
        for a in st.axes:
            if a not in axis_sizes:
                axis_sizes[a] = lax.axis_size(a)
    _, _, stack = _replay_sizes(prefix, like.size, axis_sizes)
    cur = u_chunk
    for st in stages:
        if st.primitive != "allgather":
            raise CompositionError(
                f"{st.signature()}: only allgather stages run after the "
                "sharded_update"
            )
        _, orig = stack.pop()
        cur = staged_allgather(cur, st.axes, orig)
    return cur.reshape(like.shape).astype(like.dtype)


def reduce_composed_tree(leaves: list, comp: Composition, *, op="mean"):
    """Reduce a LIST of leaves under ``comp``. The single-stage
    ``ar(all)`` composition keeps the hand-wired list form (one fused
    ``pmean`` over all leaves — ONE HLO all-reduce, the ParallelPlan's
    pre-composition program, byte-identical); every other composition
    pipelines each leaf's flat buffer through the executor (per-leaf
    stage collectives — the documented cost of a scattered pipeline
    without a packing layer, pinned in tests/test_composition.py)."""
    from jax import lax

    comp = compact_slices(comp)  # expanded spellings run compacted
    stages = comp.stages
    if (len(stages) == 1 and stages[0].primitive == "allreduce"
            and op == "mean" and comp.slices == 1):
        return lax.pmean(leaves, _axes_arg(stages[0].axes))
    return [reduce_composed(g, comp, op=op) for g in leaves]


__all__ = [
    "Composition",
    "CompositionError",
    "DEFAULT_RADIX",
    "PRIMITIVES",
    "STAGE_HLO",
    "Stage",
    "bind_composition",
    "broadcast_composition",
    "canonical_axis_names",
    "compact_slices",
    "compile_schedule",
    "derive_compositions",
    "effective_slices",
    "expand_slices",
    "flat_composition",
    "normalize_schedule_name",
    "parse_signature",
    "predicted_collectives",
    "reduce_composed",
    "reduce_composed_tree",
    "run_gather_suffix",
    "run_reduce_prefix",
    "schedule_candidates",
    "signature_for",
    "slice_bounds",
    "sliced_composition",
    "stage_wire_layout",
    "tree_depth",
    "tree_sends",
    "two_level_composition",
    "validate_composition",
    "zero_composition",
]
