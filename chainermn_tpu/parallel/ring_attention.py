"""Ring attention — sequence/context parallelism over a mesh axis.

NEW capability relative to the reference (SURVEY.md section 5: ChainerMN is
2017-era and has no sequence parallelism; its seq2seq example bucketed long
sequences on one device). Designed as another communicator-consuming layer,
sitting where the model-parallel functions sit in the reference's stack
(``chainermn/functions/`` (dagger), SURVEY.md section 2.4).

Mechanism: the sequence is sharded over a ``'seq'`` mesh axis. Each shard
keeps its Q block resident and the K/V blocks *rotate around the ring* via
``lax.ppermute`` (ICI neighbour exchange — bandwidth-optimal, no all-gather
of the full sequence). Each arriving block is processed by the Pallas flash
kernel (:mod:`chainermn_tpu.ops.flash_attention`), which returns the block's
attention output plus its logsumexp row; successive blocks merge in log
space, so per-shard memory stays ``O(T_local * D)`` and the full ``[T, T]``
score matrix never exists anywhere — the SURVEY §5/§7 "ring attention as a
Pallas kernel" requirement.

Differentiability: a hand-written ``custom_vjp``. The backward pass is a
second ring pass — K/V blocks rotate again, now accompanied by their
gradient accumulators, and each stop adds that shard's (dq, dk, dv)
contribution via the Pallas backward kernels. This is the same send/recv
duality the reference hand-built in ``Send.backward``/``Recv.backward``
(``functions/point_to_point_communication.py`` (dagger)), lifted to whole
ring rotations. ``impl='einsum'`` keeps the lax/einsum path (differentiated
automatically through ``scan``+``ppermute``) as the correctness reference.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from chainermn_tpu.ops.attention import (
    NEG_INF,
    finalize_online_softmax,
    online_softmax_block,
)
from chainermn_tpu.ops.flash_attention import (
    _use_interpret,
    flash_block_bwd,
    flash_block_fwd,
)


def merge_partials(o, lse, o_blk, lse_blk):
    """Merge two normalised attention partials in log space.

    ``o``/``o_blk``: [B, T, H, D] f32 outputs, each normalised within its own
    key set; ``lse``/``lse_blk``: [B, H, T] logsumexps of those key sets. The
    merged pair is the attention over the union of the key sets.
    """
    lse_new = jnp.logaddexp(lse, lse_blk)
    # Both -inf (no keys seen yet, e.g. fully-masked rows): keep output 0.
    safe = lse_new > NEG_INF / 2
    a = jnp.where(safe, jnp.exp(lse - lse_new), 0.0)
    b = jnp.where(safe, jnp.exp(lse_blk - lse_new), 0.0)
    o_new = (
        o * a.transpose(0, 2, 1)[..., None]
        + o_blk.astype(jnp.float32) * b.transpose(0, 2, 1)[..., None]
    )
    return o_new, lse_new


def _ring_perm(n):
    return [(i, (i + 1) % n) for i in range(n)]


# ---------------------------------------------------------------------------
# Zigzag layout helpers (host/jit-level, run once per batch outside the ring)
# ---------------------------------------------------------------------------

def zigzag_indices(n: int, total: int):
    """Global→zigzag gather indices: the global sequence is split into ``2n``
    chunks and shard ``s`` holds the pair ``(s, 2n-1-s)``, so under a causal
    mask every shard owns exactly half a "past-heavy" and half a
    "future-heavy" chunk — per-(shard, ring-step) work becomes a constant 2
    chunk² instead of growing with the shard index (the load imbalance
    VERDICT r2 item 4 called out: contiguous shard ``s`` computes ``s+1`` of
    ``n`` blocks, so the ring's wall clock was the LAST shard's full-n work).
    """
    import numpy as np

    if total % (2 * n):
        raise ValueError(f"sequence length {total} not divisible by 2n={2*n}")
    c = total // (2 * n)
    idx = []
    for s in range(n):
        idx.extend(range(s * c, (s + 1) * c))
        idx.extend(range((2 * n - 1 - s) * c, (2 * n - s) * c))
    return np.asarray(idx, dtype=np.int32)


def to_zigzag(x, n: int, axis: int = 1):
    """Reorder a GLOBAL array's sequence axis so that contiguous equal
    slices correspond to zigzag shards (apply before sharding over the ring
    axis; one gather, done once per batch)."""
    return jnp.take(x, jnp.asarray(zigzag_indices(n, x.shape[axis])), axis=axis)


def from_zigzag(x, n: int, axis: int = 1):
    """Inverse of :func:`to_zigzag`."""
    import numpy as np

    idx = zigzag_indices(n, x.shape[axis])
    inv = np.empty_like(idx)
    inv[idx] = np.arange(idx.size, dtype=np.int32)
    return jnp.take(x, jnp.asarray(inv), axis=axis)


def _ring_flash_fwd_impl(q, k, v, seg_q, seg_kv, axis_name, causal, scale,
                         block_q, block_k, interpret):
    """Shared forward ring. ``seg_q``/``seg_kv`` are either both None or the
    local ``[B, T_local]`` packed-segment id slices; the kv ids travel with
    their K/V block around the ring."""
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    B, Tq, H, D = q.shape
    kw = dict(scale=scale, block_q=block_q, block_k=block_k,
              interpret=interpret)
    has_seg = seg_q is not None

    o = jnp.zeros((B, Tq, H, D), jnp.float32)
    lse = jnp.full((B, H, Tq), NEG_INF, jnp.float32)
    perm = _ring_perm(n)

    def _full(o, lse, k_blk, v_blk, sk):
        o_b, lse_b = flash_block_fwd(
            q, k_blk, v_blk, causal=False,
            seg_q=seg_q, seg_kv=sk, **kw,
        )
        return merge_partials(o, lse, o_b, lse_b)

    def _diag(o, lse, k_blk, v_blk, sk):
        # src == my: equal global offsets, so the causal mask is the static
        # relative mask — no dynamic offsets reach the kernel.
        o_b, lse_b = flash_block_fwd(
            q, k_blk, v_blk, causal=True,
            seg_q=seg_q, seg_kv=sk, **kw,
        )
        return merge_partials(o, lse, o_b, lse_b)

    def _skip(o, lse, k_blk, v_blk, sk):
        return o, lse

    def step(carry, s):
        k_blk, v_blk, sk, o, lse = carry
        # Rotate FIRST (depends only on the carry): the async
        # collective-permute overlaps this step's kernels.
        k_nxt, v_nxt, sk_nxt = lax.ppermute(
            (k_blk, v_blk, sk), axis_name, perm
        )
        sk_cur = sk if has_seg else None
        if causal:
            src = (my - s) % n
            # src < my: block is entirely in the past — full attention.
            # src == my: the diagonal block. src > my: entirely future — skip
            # (no matmul at all; the causal ring does ~half the FLOPs).
            branch = jnp.where(src < my, 0, jnp.where(src == my, 1, 2))
            o, lse = lax.switch(
                branch, (_full, _diag, _skip), o, lse, k_blk, v_blk, sk_cur
            )
        else:
            o, lse = _full(o, lse, k_blk, v_blk, sk_cur)
        return (k_nxt, v_nxt, sk_nxt, o, lse), None

    # A tiny dummy travels in place of kv segment ids when unused, keeping
    # one scan structure for both cases.
    sk0 = seg_kv if has_seg else jnp.zeros((1, 1), jnp.int32)
    (k, v, seg_kv, o, lse), _ = lax.scan(
        step, (k, v, sk0, o, lse), jnp.arange(n)
    )
    # After n rotations K/V are home again — return them as residuals so the
    # backward ring starts from the same layout without re-gathering.
    return o.astype(q.dtype), lse, k, v


def _ring_flash_bwd_impl(q, k, v, seg_q, seg_kv, out, lse, g, axis_name,
                         causal, scale, block_q, block_k, interpret):
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    kw = dict(scale=scale, block_q=block_q, block_k=block_k,
              interpret=interpret)
    has_seg = seg_q is not None
    do = g
    delta = jnp.sum(
        do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1
    ).transpose(0, 2, 1)  # [B, H, Tq]

    dq0 = jnp.zeros(q.shape, jnp.float32)
    dk0 = jnp.zeros(k.shape, jnp.float32)
    dv0 = jnp.zeros(v.shape, jnp.float32)
    perm = _ring_perm(n)

    def _full(k_blk, v_blk, sk):
        return flash_block_bwd(q, k_blk, v_blk, do, lse, delta,
                               causal=False, seg_q=seg_q, seg_kv=sk, **kw)

    def _diag(k_blk, v_blk, sk):
        return flash_block_bwd(q, k_blk, v_blk, do, lse, delta,
                               causal=True, seg_q=seg_q, seg_kv=sk, **kw)

    def _skip(k_blk, v_blk, sk):
        return dq0, jnp.zeros(k_blk.shape, jnp.float32), \
            jnp.zeros(v_blk.shape, jnp.float32)

    def step(carry, s):
        k_blk, v_blk, sk, dk_t, dv_t, dq = carry
        # KV rotates eagerly (overlaps this step's kernels).
        k_nxt, v_nxt, sk_nxt = lax.ppermute(
            (k_blk, v_blk, sk), axis_name, perm
        )
        sk_cur = sk if has_seg else None
        if causal:
            src = (my - s) % n
            branch = jnp.where(src < my, 0, jnp.where(src == my, 1, 2))
            dq_c, dk_c, dv_c = lax.switch(
                branch, (_full, _diag, _skip), k_blk, v_blk, sk_cur
            )
        else:
            dq_c, dk_c, dv_c = _full(k_blk, v_blk, sk_cur)
        dq = dq + dq_c
        # The gradient accumulators travel WITH their K/V block: after the
        # full ring each block's dk/dv has collected every shard's
        # contribution and arrived back at the block's home shard. Rotating
        # them in their own ppermute (after accumulation) lets the transfer
        # overlap the NEXT step's kernels.
        dk_t, dv_t = lax.ppermute(
            (dk_t + dk_c, dv_t + dv_c), axis_name, perm
        )
        return (k_nxt, v_nxt, sk_nxt, dk_t, dv_t, dq), None

    sk0 = seg_kv if has_seg else jnp.zeros((1, 1), jnp.int32)
    (k, v, _sk, dk, dv, dq), _ = lax.scan(
        step, (k, v, sk0, dk0, dv0, dq0), jnp.arange(n)
    )
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _ring_flash(q, k, v, axis_name, causal, scale, block_q, block_k,
                interpret):
    out, _lse, _k, _v = _ring_flash_fwd_impl(
        q, k, v, None, None, axis_name, causal, scale, block_q, block_k,
        interpret
    )
    return out


def _ring_flash_fwd(q, k, v, axis_name, causal, scale, block_q, block_k,
                    interpret):
    out, lse, k, v = _ring_flash_fwd_impl(
        q, k, v, None, None, axis_name, causal, scale, block_q, block_k,
        interpret
    )
    return out, (q, k, v, out, lse)


def _ring_flash_bwd(axis_name, causal, scale, block_q, block_k, interpret,
                    res, g):
    q, k, v, out, lse = res
    return _ring_flash_bwd_impl(
        q, k, v, None, None, out, lse, g, axis_name, causal, scale,
        block_q, block_k, interpret
    )


_ring_flash.defvjp(_ring_flash_fwd, _ring_flash_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9))
def _ring_flash_seg(q, k, v, seg, axis_name, causal, scale, block_q,
                    block_k, interpret):
    out, _lse, _k, _v = _ring_flash_fwd_impl(
        q, k, v, seg, seg, axis_name, causal, scale, block_q, block_k,
        interpret
    )
    return out


def _ring_flash_seg_fwd(q, k, v, seg, axis_name, causal, scale, block_q,
                        block_k, interpret):
    out, lse, k, v = _ring_flash_fwd_impl(
        q, k, v, seg, seg, axis_name, causal, scale, block_q, block_k,
        interpret
    )
    return out, (q, k, v, seg, out, lse)


def _ring_flash_seg_bwd(axis_name, causal, scale, block_q, block_k,
                        interpret, res, g):
    q, k, v, seg, out, lse = res
    dq, dk, dv = _ring_flash_bwd_impl(
        q, k, v, seg, seg, out, lse, g, axis_name, causal, scale,
        block_q, block_k, interpret
    )
    return dq, dk, dv, None


_ring_flash_seg.defvjp(_ring_flash_seg_fwd, _ring_flash_seg_bwd)


# ---------------------------------------------------------------------------
# Zigzag causal ring (balanced): shard s holds chunks (s, 2n-1-s) of 2n.
#
# Work per (q-shard i, kv-block j), in chunk² units (chunk = T_local/2):
#   j < i ("past"):   [front_i + back_i] × front_j  = 2
#   j == i ("diag"):  ½ front-diag + back×front + ½ back-diag = 2
#   j > i ("future"): back_i × [front_j + back_j]  = 2
# — constant for every pair, so the causal ring's wall clock is ~half the
# non-causal ring's instead of equal to it. The KV ppermute for step s+1 is
# issued BEFORE step s's kernels (it depends only on the carried block), so
# XLA's async collective-permute overlaps the transfer with the compute; in
# the backward the travelling dk/dv accumulators rotate after accumulation
# and overlap the NEXT step's kernels.
# ---------------------------------------------------------------------------


def _zz_branch(my, s, n):
    src = (my - s) % n
    return jnp.where(src < my, 0, jnp.where(src == my, 1, 2))


def _zigzag_ring_flash_fwd_impl(q, k, v, seg, axis_name, scale, block_q,
                                block_k, interpret):
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    B, Tq, H, D = q.shape
    C = Tq // 2
    kw = dict(scale=scale, block_q=block_q, block_k=block_k,
              interpret=interpret)
    has_seg = seg is not None
    qf, qb = q[:, :C], q[:, C:]
    sq_f = seg[:, :C] if has_seg else None
    sq_b = seg[:, C:] if has_seg else None
    of = jnp.zeros((B, C, H, D), jnp.float32)
    ob = jnp.zeros((B, C, H, D), jnp.float32)
    lf = jnp.full((B, H, C), NEG_INF, jnp.float32)
    lb = jnp.full((B, H, C), NEG_INF, jnp.float32)
    perm = _ring_perm(n)

    def _halves(sk):
        if not has_seg:
            return None, None
        return sk[:, :C], sk[:, C:]

    def _past(of, lf, ob, lb, k_blk, v_blk, sk):
        # Whole local q attends the block's FRONT chunk (fully past); the
        # block's back chunk is entirely in this shard's future.
        sk_f, _ = _halves(sk)
        o_n, l_n = flash_block_fwd(q, k_blk[:, :C], v_blk[:, :C],
                                   causal=False, seg_q=seg, seg_kv=sk_f,
                                   **kw)
        of, lf = merge_partials(of, lf, o_n[:, :C], l_n[..., :C])
        ob, lb = merge_partials(ob, lb, o_n[:, C:], l_n[..., C:])
        return of, lf, ob, lb

    def _diag(of, lf, ob, lb, k_blk, v_blk, sk):
        # Equal global offsets chunk-by-chunk: both diagonals are static
        # relative causal masks; back×front is fully past.
        sk_f, sk_b = _halves(sk)
        o_fd, l_fd = flash_block_fwd(qf, k_blk[:, :C], v_blk[:, :C],
                                     causal=True, seg_q=sq_f, seg_kv=sk_f,
                                     **kw)
        o_bf, l_bf = flash_block_fwd(qb, k_blk[:, :C], v_blk[:, :C],
                                     causal=False, seg_q=sq_b, seg_kv=sk_f,
                                     **kw)
        o_bd, l_bd = flash_block_fwd(qb, k_blk[:, C:], v_blk[:, C:],
                                     causal=True, seg_q=sq_b, seg_kv=sk_b,
                                     **kw)
        of, lf = merge_partials(of, lf, o_fd, l_fd)
        ob, lb = merge_partials(ob, lb, o_bf, l_bf)
        ob, lb = merge_partials(ob, lb, o_bd, l_bd)
        return of, lf, ob, lb

    def _future(of, lf, ob, lb, k_blk, v_blk, sk):
        # Only the local BACK chunk is after both of the block's chunks.
        o_n, l_n = flash_block_fwd(qb, k_blk, v_blk, causal=False,
                                   seg_q=sq_b, seg_kv=sk, **kw)
        ob, lb = merge_partials(ob, lb, o_n, l_n)
        return of, lf, ob, lb

    def step(carry, s):
        k_blk, v_blk, sk, of, lf, ob, lb = carry
        # Rotate FIRST: the permute depends only on the carried block, so it
        # runs concurrently with this step's kernels (double-buffered KV).
        k_nxt, v_nxt, sk_nxt = lax.ppermute(
            (k_blk, v_blk, sk), axis_name, perm
        )
        sk_cur = sk if has_seg else None
        of, lf, ob, lb = lax.switch(
            _zz_branch(my, s, n), (_past, _diag, _future),
            of, lf, ob, lb, k_blk, v_blk, sk_cur,
        )
        return (k_nxt, v_nxt, sk_nxt, of, lf, ob, lb), None

    sk0 = seg if has_seg else jnp.zeros((1, 1), jnp.int32)
    (k, v, _sk, of, lf, ob, lb), _ = lax.scan(
        step, (k, v, sk0, of, lf, ob, lb), jnp.arange(n)
    )
    o = jnp.concatenate([of, ob], axis=1).astype(q.dtype)
    lse = jnp.concatenate([lf, lb], axis=2)
    return o, lse, k, v


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _zigzag_ring_flash(q, k, v, axis_name, scale, block_q, block_k,
                       interpret):
    out, _lse, _k, _v = _zigzag_ring_flash_fwd_impl(
        q, k, v, None, axis_name, scale, block_q, block_k, interpret
    )
    return out


def _zigzag_ring_flash_fwd(q, k, v, axis_name, scale, block_q, block_k,
                           interpret):
    out, lse, k, v = _zigzag_ring_flash_fwd_impl(
        q, k, v, None, axis_name, scale, block_q, block_k, interpret
    )
    return out, (q, k, v, out, lse)


def _zigzag_ring_flash_bwd(axis_name, scale, block_q, block_k, interpret,
                           res, g):
    q, k, v, out, lse = res
    return _zigzag_ring_flash_bwd_impl(
        q, k, v, None, out, lse, g, axis_name, scale, block_q, block_k,
        interpret
    )


def _zigzag_ring_flash_bwd_impl(q, k, v, seg, out, lse, g, axis_name, scale,
                                block_q, block_k, interpret):
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    B, Tq, H, D = q.shape
    C = Tq // 2
    kw = dict(scale=scale, block_q=block_q, block_k=block_k,
              interpret=interpret)
    has_seg = seg is not None
    qf, qb = q[:, :C], q[:, C:]
    sq_f = seg[:, :C] if has_seg else None
    sq_b = seg[:, C:] if has_seg else None
    do = g
    delta = jnp.sum(
        do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1
    ).transpose(0, 2, 1)  # [B, H, Tq]
    do_f, do_b = do[:, :C], do[:, C:]
    lse_f, lse_b = lse[..., :C], lse[..., C:]
    dlt_f, dlt_b = delta[..., :C], delta[..., C:]

    # dq pads at the Q head count; dk/dv pads at the KV head count (GQA:
    # flash_block_bwd group-sums dk/dv down to the kv heads).
    zQ = jnp.zeros((B, C, H, D), jnp.float32)
    zKV = jnp.zeros((B, C, k.shape[2], D), jnp.float32)
    perm = _ring_perm(n)

    def _halves(sk):
        if not has_seg:
            return None, None
        return sk[:, :C], sk[:, C:]

    def _past(k_blk, v_blk, sk):
        sk_f, _ = _halves(sk)
        dq_c, dkf, dvf = flash_block_bwd(
            q, k_blk[:, :C], v_blk[:, :C], do, lse, delta,
            causal=False, seg_q=seg, seg_kv=sk_f, **kw,
        )
        return (dq_c,
                jnp.concatenate([dkf, zKV], axis=1),
                jnp.concatenate([dvf, zKV], axis=1))

    def _diag(k_blk, v_blk, sk):
        sk_f, sk_b = _halves(sk)
        dqf, dkf1, dvf1 = flash_block_bwd(
            qf, k_blk[:, :C], v_blk[:, :C], do_f, lse_f, dlt_f,
            causal=True, seg_q=sq_f, seg_kv=sk_f, **kw,
        )
        dqb1, dkf2, dvf2 = flash_block_bwd(
            qb, k_blk[:, :C], v_blk[:, :C], do_b, lse_b, dlt_b,
            causal=False, seg_q=sq_b, seg_kv=sk_f, **kw,
        )
        dqb2, dkb, dvb = flash_block_bwd(
            qb, k_blk[:, C:], v_blk[:, C:], do_b, lse_b, dlt_b,
            causal=True, seg_q=sq_b, seg_kv=sk_b, **kw,
        )
        dq_c = jnp.concatenate([dqf, dqb1 + dqb2], axis=1)
        return (dq_c,
                jnp.concatenate([dkf1 + dkf2, dkb], axis=1),
                jnp.concatenate([dvf1 + dvf2, dvb], axis=1))

    def _future(k_blk, v_blk, sk):
        dqb, dk_c, dv_c = flash_block_bwd(
            qb, k_blk, v_blk, do_b, lse_b, dlt_b, causal=False,
            seg_q=sq_b, seg_kv=sk, **kw,
        )
        return jnp.concatenate([zQ, dqb], axis=1), dk_c, dv_c

    def step(carry, s):
        k_blk, v_blk, sk, dk_t, dv_t, dq = carry
        # KV rotates eagerly (overlaps this step's kernels); the gradient
        # accumulators rotate after accumulation and overlap the next
        # step's kernels (they're consumed late in the next body).
        k_nxt, v_nxt, sk_nxt = lax.ppermute(
            (k_blk, v_blk, sk), axis_name, perm
        )
        sk_cur = sk if has_seg else None
        dq_c, dk_c, dv_c = lax.switch(
            _zz_branch(my, s, n), (_past, _diag, _future), k_blk, v_blk,
            sk_cur,
        )
        dk_t, dv_t = lax.ppermute(
            (dk_t + dk_c, dv_t + dv_c), axis_name, perm
        )
        return (k_nxt, v_nxt, sk_nxt, dk_t, dv_t, dq + dq_c), None

    dk0 = jnp.zeros(k.shape, jnp.float32)
    dv0 = jnp.zeros(v.shape, jnp.float32)
    dq0 = jnp.zeros(q.shape, jnp.float32)
    sk0 = seg if has_seg else jnp.zeros((1, 1), jnp.int32)
    (k, v, _sk, dk, dv, dq), _ = lax.scan(
        step, (k, v, sk0, dk0, dv0, dq0), jnp.arange(n)
    )
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_zigzag_ring_flash.defvjp(_zigzag_ring_flash_fwd, _zigzag_ring_flash_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _zigzag_ring_flash_seg(q, k, v, seg, axis_name, scale, block_q, block_k,
                           interpret):
    out, _lse, _k, _v = _zigzag_ring_flash_fwd_impl(
        q, k, v, seg, axis_name, scale, block_q, block_k, interpret
    )
    return out


def _zigzag_ring_flash_seg_fwd(q, k, v, seg, axis_name, scale, block_q,
                               block_k, interpret):
    out, lse, k, v = _zigzag_ring_flash_fwd_impl(
        q, k, v, seg, axis_name, scale, block_q, block_k, interpret
    )
    return out, (q, k, v, seg, out, lse)


def _zigzag_ring_flash_seg_bwd(axis_name, scale, block_q, block_k,
                               interpret, res, g):
    q, k, v, seg, out, lse = res
    dq, dk, dv = _zigzag_ring_flash_bwd_impl(
        q, k, v, seg, out, lse, g, axis_name, scale, block_q, block_k,
        interpret
    )
    return dq, dk, dv, None


_zigzag_ring_flash_seg.defvjp(_zigzag_ring_flash_seg_fwd,
                              _zigzag_ring_flash_seg_bwd)


def ring_attention_local(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    impl: str = "flash",
    layout: str = "contiguous",
    segment_ids: Optional[jax.Array] = None,
    block_q: int = 512,
    block_k: int = 1024,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Ring attention over local shards — call INSIDE ``shard_map``.

    Args:
      q/k/v: local sequence shards ``[B, T_local, H, D]``; the global
        sequence is the concatenation over ``axis_name`` in ring order
        (``layout='contiguous'``) or the zigzag chunk-pair order
        (``layout='zigzag'`` — shard ``s`` holds global chunks
        ``(s, 2n-1-s)`` of ``2n``; see :func:`to_zigzag`).
      causal: apply a causal mask over *global* positions.
      impl: ``'flash'`` (Pallas block kernels, hand-written ring backward;
        the production path) or ``'einsum'`` (lax online-softmax blocks,
        autodiff through scan+ppermute; the correctness reference).
      layout: ``'zigzag'`` balances causal work across shards (constant 2
        chunk²/step everywhere vs the contiguous ring's last-shard
        bottleneck); requires ``causal=True`` and ``impl='flash'``.
      segment_ids: optional local ``[B, T_local]`` packed-segment id slice
        (flash impl only); kv ids travel with their block around the ring,
        so attention is confined to equal ids across the whole global
        sequence. K/V may also carry fewer heads than q (GQA/MQA) — kv
        blocks rotate at their own (smaller) size.
      interpret: run the Pallas kernels in interpreter mode. Inside
        ``shard_map`` the mesh platform is invisible, so the default guesses
        from the default backend/device — pass it explicitly when the
        enclosing mesh's platform differs (``make_ring_attention`` derives
        it from its mesh automatically).

    Returns:
      Local output shard ``[B, T_local, H, D]`` (dtype of ``q``).
    """
    if layout not in ("contiguous", "zigzag"):
        raise ValueError(
            f"layout must be 'contiguous' or 'zigzag', got {layout!r}"
        )
    if layout == "zigzag":
        if not causal or impl != "flash":
            raise ValueError(
                "layout='zigzag' exists to balance CAUSAL work and is "
                "implemented for impl='flash' (non-causal rings are already "
                "balanced — use layout='contiguous')"
            )
        if scale is None:
            scale = q.shape[-1] ** -0.5
        if interpret is None:
            interpret = _use_interpret()
        if segment_ids is not None:
            return _zigzag_ring_flash_seg(
                q, k, v, segment_ids.astype(jnp.int32), axis_name,
                float(scale), block_q, block_k, interpret
            )
        return _zigzag_ring_flash(
            q, k, v, axis_name, float(scale), block_q, block_k, interpret
        )
    if impl == "flash":
        if scale is None:
            scale = q.shape[-1] ** -0.5
        if interpret is None:
            interpret = _use_interpret()
        if segment_ids is not None:
            return _ring_flash_seg(
                q, k, v, segment_ids.astype(jnp.int32), axis_name, causal,
                float(scale), block_q, block_k, interpret
            )
        return _ring_flash(
            q, k, v, axis_name, causal, float(scale), block_q, block_k,
            interpret,
        )
    if impl != "einsum":
        raise ValueError(f"impl must be 'flash' or 'einsum', got {impl!r}")
    if segment_ids is not None:
        raise NotImplementedError(
            "segment_ids requires impl='flash' (the production path)"
        )

    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    if k.shape[2] != H:
        # GQA in the reference path: materialize the head repeat (autodiff's
        # transpose sums the group — matching the kernel path's group-sum).
        rep = H // k.shape[2]
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)

    o = jnp.zeros((B, Tq, H, D), jnp.float32)
    m = jnp.full((B, H, Tq), NEG_INF, jnp.float32)
    l = jnp.zeros((B, H, Tq), jnp.float32)

    # Rotate kv by +1 each step: after step s this shard holds the block that
    # started on shard (my - s) % n.
    perm = _ring_perm(n)

    def body(carry, s):
        k_blk, v_blk, o, m, l = carry
        src = (my - s) % n
        o, m, l = online_softmax_block(
            q, k_blk, v_blk, o, m, l,
            causal=causal,
            q_offset=my * Tq,
            kv_offset=src * Tk,
            scale=scale,
        )
        k_blk, v_blk = lax.ppermute((k_blk, v_blk), axis_name, perm)
        return (k_blk, v_blk, o, m, l), None

    (k, v, o, m, l), _ = lax.scan(body, (k, v, o, m, l), jnp.arange(n))
    return finalize_online_softmax(o, l, q.dtype)


# ---------------------------------------------------------------------------
# Plan-provider ring (ISSUE 13): statically UNROLLED, n-1 forward hops.
#
# The scan-based rings above rotate n times (the last rotation brings K/V
# home for the backward's residuals); fine for a loop the HLO shows once,
# but the ParallelPlan's structural acceptance pins the compiled program's
# collective-permute COUNT at ``n_seq_shards - 1`` per layer per forward
# ring pass — the minimal neighbour exchange (block s needs n-1 hops to
# visit every other shard). So the plan's provider unrolls the ring over
# the static mesh size, rotates K and V as ONE stacked array (one
# collective-permute per hop), and skips the useless homing hop; the
# custom-vjp backward restarts from the saved home K/V (they are the
# function's own inputs — nothing to re-gather). Backward counts, also
# pinned: n-1 kv hops (same argument) plus n hops for the travelling
# dk/dv accumulator — it starts at home, must visit all n shards, and
# needs one extra hop to come home after the last accumulation.
# ---------------------------------------------------------------------------


def _seq_ring_fwd_impl(q, k, v, axis_name, causal, scale, block_q, block_k,
                       interpret):
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    B, Tq, H, D = q.shape
    kw = dict(scale=scale, block_q=block_q, block_k=block_k,
              interpret=interpret)
    o = jnp.zeros((B, Tq, H, D), jnp.float32)
    lse = jnp.full((B, H, Tq), NEG_INF, jnp.float32)
    perm = _ring_perm(n)

    def _full(o, lse, k_blk, v_blk):
        o_b, lse_b = flash_block_fwd(q, k_blk, v_blk, causal=False, **kw)
        return merge_partials(o, lse, o_b, lse_b)

    def _diag(o, lse, k_blk, v_blk):
        o_b, lse_b = flash_block_fwd(q, k_blk, v_blk, causal=True, **kw)
        return merge_partials(o, lse, o_b, lse_b)

    def _skip(o, lse, k_blk, v_blk):
        return o, lse

    kv = jnp.stack([k, v])
    for s in range(n):
        # Rotate FIRST (depends only on the carried pair) so the async
        # collective-permute overlaps this step's kernels — but never
        # after the LAST step: the homing hop is pure waste and the
        # ppermute-count pin forbids it.
        kv_next = lax.ppermute(kv, axis_name, perm) if s + 1 < n else None
        k_blk, v_blk = kv[0], kv[1]
        if causal:
            src = (my - s) % n
            branch = jnp.where(src < my, 0, jnp.where(src == my, 1, 2))
            o, lse = lax.switch(
                branch, (_full, _diag, _skip), o, lse, k_blk, v_blk
            )
        else:
            o, lse = _full(o, lse, k_blk, v_blk)
        if kv_next is not None:
            kv = kv_next
    return o.astype(q.dtype), lse


def _seq_ring_bwd_impl(q, k, v, out, lse, g, axis_name, causal, scale,
                       block_q, block_k, interpret):
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    kw = dict(scale=scale, block_q=block_q, block_k=block_k,
              interpret=interpret)
    do = g
    delta = jnp.sum(
        do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1
    ).transpose(0, 2, 1)  # [B, H, Tq]
    perm = _ring_perm(n)

    def _full(k_blk, v_blk):
        return flash_block_bwd(q, k_blk, v_blk, do, lse, delta,
                               causal=False, **kw)

    def _diag(k_blk, v_blk):
        return flash_block_bwd(q, k_blk, v_blk, do, lse, delta,
                               causal=True, **kw)

    def _skip(k_blk, v_blk):
        return (jnp.zeros(q.shape, jnp.float32),
                jnp.zeros(k_blk.shape, jnp.float32),
                jnp.zeros(v_blk.shape, jnp.float32))

    kv = jnp.stack([k, v])
    dkv = jnp.zeros((2,) + k.shape, jnp.float32)
    dq = jnp.zeros(q.shape, jnp.float32)
    for s in range(n):
        kv_next = lax.ppermute(kv, axis_name, perm) if s + 1 < n else None
        k_blk, v_blk = kv[0], kv[1]
        if causal:
            src = (my - s) % n
            branch = jnp.where(src < my, 0, jnp.where(src == my, 1, 2))
            dq_c, dk_c, dv_c = lax.switch(
                branch, (_full, _diag, _skip), k_blk, v_blk
            )
        else:
            dq_c, dk_c, dv_c = _full(k_blk, v_blk)
        dq = dq + dq_c
        # The accumulator travels WITH its block and rotates after EVERY
        # accumulation (n hops total): after the last one the block's
        # dk/dv sits one shard past its last visit — exactly home.
        dkv = lax.ppermute(dkv + jnp.stack([dk_c, dv_c]), axis_name, perm)
        if kv_next is not None:
            kv = kv_next
    return (dq.astype(q.dtype), dkv[0].astype(k.dtype),
            dkv[1].astype(v.dtype))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _seq_ring(q, k, v, axis_name, causal, scale, block_q, block_k,
              interpret):
    out, _lse = _seq_ring_fwd_impl(
        q, k, v, axis_name, causal, scale, block_q, block_k, interpret
    )
    return out


def _seq_ring_fwd(q, k, v, axis_name, causal, scale, block_q, block_k,
                  interpret):
    out, lse = _seq_ring_fwd_impl(
        q, k, v, axis_name, causal, scale, block_q, block_k, interpret
    )
    # Home k/v are the function's own inputs — saving them costs nothing
    # and lets the backward ring start without the scan rings' homing
    # rotation.
    return out, (q, k, v, out, lse)


def _seq_ring_bwd(axis_name, causal, scale, block_q, block_k, interpret,
                  res, g):
    q, k, v, out, lse = res
    return _seq_ring_bwd_impl(
        q, k, v, out, lse, g, axis_name, causal, scale, block_q, block_k,
        interpret
    )


_seq_ring.defvjp(_seq_ring_fwd, _seq_ring_bwd)


def seq_ring_attention_local(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str = "seq",
    *,
    causal: bool = True,
    scale: Optional[float] = None,
    block_q: int = 512,
    block_k: int = 1024,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """The ParallelPlan ``seq``-axis ring — call INSIDE ``shard_map``.

    Same contract as :func:`ring_attention_local` (contiguous layout,
    flash kernels, GQA via smaller K/V head counts), but the ring is
    statically unrolled with exactly ``n - 1`` K/V hops per forward pass
    and ``(n - 1) + n`` per backward (kv + travelling dk/dv accumulator)
    — each hop ONE ``collective-permute`` of the stacked (K, V) pair, so
    the plan's structural HLO-count acceptance can pin the program
    (tests/test_sequence_parallel.py). Signature matches the
    ``attention_fn`` contract of
    :class:`~chainermn_tpu.models.transformer.TransformerBlock`.
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if interpret is None:
        interpret = _use_interpret()
    try:
        from chainermn_tpu.observability import trace as _trace

        rec = _trace.active()
    except Exception:
        rec = None
    if rec is not None:
        # Trace-time layout event (the in-jit bucketed schedules'
        # convention — what the compiled program COMMITTED to, once per
        # compile, no duration): one forward ring pass moves the
        # stacked (K, V) pair n-1 hops; overlapped=True because the
        # hop is issued before the step's kernels (async
        # collective-permute rides behind compute by construction).
        n = lax.axis_size(axis_name)
        per_hop = 2 * k.size * jnp.dtype(k.dtype).itemsize
        rec.event(
            "wire", schedule="seq_ring", axis=str(axis_name),
            hops=n - 1, bucket=0, n_buckets=1,
            nbytes=per_hop * (n - 1),
            wire_dtype=str(k.dtype), overlapped=True,
        )
    return _seq_ring(q, k, v, axis_name, bool(causal), float(scale),
                     int(block_q), int(block_k), bool(interpret))


def make_ring_attention(
    mesh: Mesh,
    axis_name: str = "seq",
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    batch_axis: Optional[str] = None,
    impl: str = "flash",
    layout: str = "contiguous",
    with_segments: bool = False,
):
    """Jitted ring attention over globally (sequence-)sharded BTHD arrays.

    Returns ``fn(q, k, v) -> out`` (or ``fn(q, k, v, segment_ids)`` when
    ``with_segments``) where inputs/outputs are global arrays whose sequence
    dim is sharded over ``axis_name`` (and batch over ``batch_axis`` when
    given). With ``layout='zigzag'`` the fn reorders the global sequence
    into zigzag chunk-pair order at entry and back at exit (two gathers;
    amortise them by keeping the whole model in zigzag layout and calling
    :func:`ring_attention_local` inside your own ``shard_map`` instead).
    The returned fn composes under a larger jitted program.
    """
    from jax import shard_map

    spec = P(batch_axis, axis_name, None, None)
    seg_spec = P(batch_axis, axis_name)
    # The mesh knows where this will execute; don't guess from the default
    # backend (a TPU plugin may be loaded while this mesh is CPU).
    interpret = mesh.devices.flat[0].platform != "tpu"
    n = mesh.shape[axis_name]

    def local(q, k, v, seg=None):
        return ring_attention_local(
            q, k, v, axis_name, causal=causal, scale=scale, impl=impl,
            layout=layout, segment_ids=seg, interpret=interpret,
        )

    if with_segments:
        fn = shard_map(
            local, mesh=mesh, in_specs=(spec, spec, spec, seg_spec),
            out_specs=spec, check_vma=False,
        )
    else:
        fn = shard_map(
            local, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False,
        )

    if layout == "zigzag":
        def zz(q, k, v, seg=None):
            q, k, v = (to_zigzag(t, n, axis=1) for t in (q, k, v))
            if with_segments:
                out = fn(q, k, v, to_zigzag(seg, n, axis=1))
            else:
                out = fn(q, k, v)
            return from_zigzag(out, n, axis=1)

        return jax.jit(zz)
    return jax.jit(fn)
