"""Ring attention — sequence/context parallelism over a mesh axis.

NEW capability relative to the reference (SURVEY.md section 5: ChainerMN is
2017-era and has no sequence parallelism; its seq2seq example bucketed long
sequences on one device). Designed as another communicator-consuming layer,
sitting where the model-parallel functions sit in the reference's stack
(``chainermn/functions/`` (dagger), SURVEY.md section 2.4).

Mechanism: the sequence is sharded over a ``'seq'`` mesh axis. Each shard
keeps its Q block resident and the K/V blocks *rotate around the ring* via
``lax.ppermute`` (ICI neighbour exchange — bandwidth-optimal, no all-gather
of the full sequence). Each arriving block is processed by the Pallas flash
kernel (:mod:`chainermn_tpu.ops.flash_attention`), which returns the block's
attention output plus its logsumexp row; successive blocks merge in log
space, so per-shard memory stays ``O(T_local * D)`` and the full ``[T, T]``
score matrix never exists anywhere — the SURVEY §5/§7 "ring attention as a
Pallas kernel" requirement.

Differentiability: a hand-written ``custom_vjp``. The backward pass is a
second ring pass — K/V blocks rotate again, now accompanied by their
gradient accumulators, and each stop adds that shard's (dq, dk, dv)
contribution via the Pallas backward kernels. This is the same send/recv
duality the reference hand-built in ``Send.backward``/``Recv.backward``
(``functions/point_to_point_communication.py`` (dagger)), lifted to whole
ring rotations. ``impl='einsum'`` keeps the lax/einsum path (differentiated
automatically through ``scan``+``ppermute``) as the correctness reference.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from chainermn_tpu.ops.attention import (
    NEG_INF,
    finalize_online_softmax,
    online_softmax_block,
)
from chainermn_tpu.ops.flash_attention import (
    _use_interpret,
    flash_block_bwd,
    flash_block_fwd,
)


def merge_partials(o, lse, o_blk, lse_blk):
    """Merge two normalised attention partials in log space.

    ``o``/``o_blk``: [B, T, H, D] f32 outputs, each normalised within its own
    key set; ``lse``/``lse_blk``: [B, H, T] logsumexps of those key sets. The
    merged pair is the attention over the union of the key sets.
    """
    lse_new = jnp.logaddexp(lse, lse_blk)
    # Both -inf (no keys seen yet, e.g. fully-masked rows): keep output 0.
    safe = lse_new > NEG_INF / 2
    a = jnp.where(safe, jnp.exp(lse - lse_new), 0.0)
    b = jnp.where(safe, jnp.exp(lse_blk - lse_new), 0.0)
    o_new = (
        o * a.transpose(0, 2, 1)[..., None]
        + o_blk.astype(jnp.float32) * b.transpose(0, 2, 1)[..., None]
    )
    return o_new, lse_new


def _ring_perm(n):
    return [(i, (i + 1) % n) for i in range(n)]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _ring_flash(q, k, v, axis_name, causal, scale, block_q, block_k,
                interpret):
    out, _lse, _k, _v = _ring_flash_fwd_impl(
        q, k, v, axis_name, causal, scale, block_q, block_k, interpret
    )
    return out


def _ring_flash_fwd_impl(q, k, v, axis_name, causal, scale, block_q, block_k,
                         interpret):
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    B, Tq, H, D = q.shape
    kw = dict(scale=scale, block_q=block_q, block_k=block_k,
              interpret=interpret)

    o = jnp.zeros((B, Tq, H, D), jnp.float32)
    lse = jnp.full((B, H, Tq), NEG_INF, jnp.float32)
    perm = _ring_perm(n)

    def _full(o, lse, k_blk, v_blk):
        o_b, lse_b = flash_block_fwd(q, k_blk, v_blk, causal=False, **kw)
        return merge_partials(o, lse, o_b, lse_b)

    def _diag(o, lse, k_blk, v_blk):
        # src == my: equal global offsets, so the causal mask is the static
        # relative mask — no dynamic offsets reach the kernel.
        o_b, lse_b = flash_block_fwd(q, k_blk, v_blk, causal=True, **kw)
        return merge_partials(o, lse, o_b, lse_b)

    def _skip(o, lse, k_blk, v_blk):
        return o, lse

    def step(carry, s):
        k_blk, v_blk, o, lse = carry
        if causal:
            src = (my - s) % n
            # src < my: block is entirely in the past — full attention.
            # src == my: the diagonal block. src > my: entirely future — skip
            # (no matmul at all; the causal ring does ~half the FLOPs).
            branch = jnp.where(src < my, 0, jnp.where(src == my, 1, 2))
            o, lse = lax.switch(
                branch, (_full, _diag, _skip), o, lse, k_blk, v_blk
            )
        else:
            o, lse = _full(o, lse, k_blk, v_blk)
        k_blk, v_blk = lax.ppermute((k_blk, v_blk), axis_name, perm)
        return (k_blk, v_blk, o, lse), None

    (k, v, o, lse), _ = lax.scan(step, (k, v, o, lse), jnp.arange(n))
    # After n rotations K/V are home again — return them as residuals so the
    # backward ring starts from the same layout without re-gathering.
    return o.astype(q.dtype), lse, k, v


def _ring_flash_fwd(q, k, v, axis_name, causal, scale, block_q, block_k,
                    interpret):
    out, lse, k, v = _ring_flash_fwd_impl(
        q, k, v, axis_name, causal, scale, block_q, block_k, interpret
    )
    return out, (q, k, v, out, lse)


def _ring_flash_bwd(axis_name, causal, scale, block_q, block_k, interpret,
                    res, g):
    q, k, v, out, lse = res
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    kw = dict(scale=scale, block_q=block_q, block_k=block_k,
              interpret=interpret)
    do = g
    delta = jnp.sum(
        do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1
    ).transpose(0, 2, 1)  # [B, H, Tq]

    dq0 = jnp.zeros(q.shape, jnp.float32)
    dk0 = jnp.zeros(k.shape, jnp.float32)
    dv0 = jnp.zeros(v.shape, jnp.float32)
    perm = _ring_perm(n)

    def _full(k_blk, v_blk):
        return flash_block_bwd(q, k_blk, v_blk, do, lse, delta,
                               causal=False, **kw)

    def _diag(k_blk, v_blk):
        return flash_block_bwd(q, k_blk, v_blk, do, lse, delta,
                               causal=True, **kw)

    def _skip(k_blk, v_blk):
        return dq0, jnp.zeros(k_blk.shape, jnp.float32), \
            jnp.zeros(v_blk.shape, jnp.float32)

    def step(carry, s):
        k_blk, v_blk, dk_t, dv_t, dq = carry
        if causal:
            src = (my - s) % n
            branch = jnp.where(src < my, 0, jnp.where(src == my, 1, 2))
            dq_c, dk_c, dv_c = lax.switch(
                branch, (_full, _diag, _skip), k_blk, v_blk
            )
        else:
            dq_c, dk_c, dv_c = _full(k_blk, v_blk)
        dq = dq + dq_c
        dk_t = dk_t + dk_c
        dv_t = dv_t + dv_c
        # The gradient accumulators travel WITH their K/V block: after the
        # full ring each block's dk/dv has collected every shard's
        # contribution and arrived back at the block's home shard.
        k_blk, v_blk, dk_t, dv_t = lax.ppermute(
            (k_blk, v_blk, dk_t, dv_t), axis_name, perm
        )
        return (k_blk, v_blk, dk_t, dv_t, dq), None

    (k, v, dk, dv, dq), _ = lax.scan(
        step, (k, v, dk0, dv0, dq0), jnp.arange(n)
    )
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_ring_flash.defvjp(_ring_flash_fwd, _ring_flash_bwd)


def ring_attention_local(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    impl: str = "flash",
    block_q: int = 512,
    block_k: int = 1024,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Ring attention over local shards — call INSIDE ``shard_map``.

    Args:
      q/k/v: local sequence shards ``[B, T_local, H, D]``; the global
        sequence is the concatenation over ``axis_name`` in ring order.
      causal: apply a causal mask over *global* positions.
      impl: ``'flash'`` (Pallas block kernels, hand-written ring backward;
        the production path) or ``'einsum'`` (lax online-softmax blocks,
        autodiff through scan+ppermute; the correctness reference).
      interpret: run the Pallas kernels in interpreter mode. Inside
        ``shard_map`` the mesh platform is invisible, so the default guesses
        from the default backend/device — pass it explicitly when the
        enclosing mesh's platform differs (``make_ring_attention`` derives
        it from its mesh automatically).

    Returns:
      Local output shard ``[B, T_local, H, D]`` (dtype of ``q``).
    """
    if impl == "flash":
        if scale is None:
            scale = q.shape[-1] ** -0.5
        if interpret is None:
            interpret = _use_interpret()
        return _ring_flash(
            q, k, v, axis_name, causal, float(scale), block_q, block_k,
            interpret,
        )
    if impl != "einsum":
        raise ValueError(f"impl must be 'flash' or 'einsum', got {impl!r}")

    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    B, Tq, H, D = q.shape
    Tk = k.shape[1]

    o = jnp.zeros((B, Tq, H, D), jnp.float32)
    m = jnp.full((B, H, Tq), NEG_INF, jnp.float32)
    l = jnp.zeros((B, H, Tq), jnp.float32)

    # Rotate kv by +1 each step: after step s this shard holds the block that
    # started on shard (my - s) % n.
    perm = _ring_perm(n)

    def body(carry, s):
        k_blk, v_blk, o, m, l = carry
        src = (my - s) % n
        o, m, l = online_softmax_block(
            q, k_blk, v_blk, o, m, l,
            causal=causal,
            q_offset=my * Tq,
            kv_offset=src * Tk,
            scale=scale,
        )
        k_blk, v_blk = lax.ppermute((k_blk, v_blk), axis_name, perm)
        return (k_blk, v_blk, o, m, l), None

    (k, v, o, m, l), _ = lax.scan(body, (k, v, o, m, l), jnp.arange(n))
    return finalize_online_softmax(o, l, q.dtype)


def make_ring_attention(
    mesh: Mesh,
    axis_name: str = "seq",
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    batch_axis: Optional[str] = None,
    impl: str = "flash",
):
    """Jitted ring attention over globally (sequence-)sharded BTHD arrays.

    Returns ``fn(q, k, v) -> out`` where inputs/outputs are global arrays
    whose sequence dim is sharded over ``axis_name`` (and batch over
    ``batch_axis`` when given). The returned fn composes under a larger
    jitted program; use :func:`ring_attention_local` directly when already
    inside a ``shard_map``.
    """
    from jax import shard_map

    spec = P(batch_axis, axis_name, None, None)
    # The mesh knows where this will execute; don't guess from the default
    # backend (a TPU plugin may be loaded while this mesh is CPU).
    interpret = mesh.devices.flat[0].platform != "tpu"

    def local(q, k, v):
        return ring_attention_local(
            q, k, v, axis_name, causal=causal, scale=scale, impl=impl,
            interpret=interpret,
        )

    fn = shard_map(
        local, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )
    return jax.jit(fn)
