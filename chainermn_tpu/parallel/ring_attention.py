"""Ring attention — sequence/context parallelism over a mesh axis.

NEW capability relative to the reference (SURVEY.md section 5: ChainerMN is
2017-era and has no sequence parallelism; its seq2seq example bucketed long
sequences on one device). Designed as another communicator-consuming layer,
sitting where the model-parallel functions sit in the reference's stack
(``chainermn/functions/`` (dagger), SURVEY.md section 2.4).

Mechanism: the sequence is sharded over a ``'seq'`` mesh axis. Each shard
keeps its Q block resident and the K/V blocks *rotate around the ring* via
``lax.ppermute`` (ICI neighbour exchange — bandwidth-optimal, no all-gather
of the full sequence). Attention is accumulated blockwise with the online
(flash) softmax, so per-shard memory stays ``O(T_local^2 / n)`` and the full
``[T, T]`` score matrix never exists anywhere.

Differentiability: the whole loop is ``lax.scan`` + ``ppermute``, both of
which JAX knows how to transpose — the backward pass is automatically the
reverse ring rotation, the same send/recv duality the reference hand-built
in ``Send.backward``/``Recv.backward``
(``functions/point_to_point_communication.py`` (dagger)).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from chainermn_tpu.ops.attention import (
    NEG_INF,
    finalize_online_softmax,
    online_softmax_block,
)


def ring_attention_local(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
) -> jax.Array:
    """Ring attention over local shards — call INSIDE ``shard_map``.

    Args:
      q/k/v: local sequence shards ``[B, T_local, H, D]``; the global
        sequence is the concatenation over ``axis_name`` in ring order.
      causal: apply a causal mask over *global* positions.

    Returns:
      Local output shard ``[B, T_local, H, D]`` (dtype of ``q``).
    """
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    B, Tq, H, D = q.shape
    Tk = k.shape[1]

    o = jnp.zeros((B, Tq, H, D), jnp.float32)
    m = jnp.full((B, H, Tq), NEG_INF, jnp.float32)
    l = jnp.zeros((B, H, Tq), jnp.float32)

    # Rotate kv by +1 each step: after step s this shard holds the block that
    # started on shard (my - s) % n.
    perm = [(i, (i + 1) % n) for i in range(n)]

    def body(carry, s):
        k_blk, v_blk, o, m, l = carry
        src = (my - s) % n
        o, m, l = online_softmax_block(
            q, k_blk, v_blk, o, m, l,
            causal=causal,
            q_offset=my * Tq,
            kv_offset=src * Tk,
            scale=scale,
        )
        k_blk, v_blk = lax.ppermute((k_blk, v_blk), axis_name, perm)
        return (k_blk, v_blk, o, m, l), None

    (k, v, o, m, l), _ = lax.scan(body, (k, v, o, m, l), jnp.arange(n))
    return finalize_online_softmax(o, l, q.dtype)


def make_ring_attention(
    mesh: Mesh,
    axis_name: str = "seq",
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    batch_axis: Optional[str] = None,
):
    """Jitted ring attention over globally (sequence-)sharded BTHD arrays.

    Returns ``fn(q, k, v) -> out`` where inputs/outputs are global arrays
    whose sequence dim is sharded over ``axis_name`` (and batch over
    ``batch_axis`` when given). The returned fn composes under a larger
    jitted program; use :func:`ring_attention_local` directly when already
    inside a ``shard_map``.
    """
    from jax import shard_map

    spec = P(batch_axis, axis_name, None, None)

    def local(q, k, v):
        return ring_attention_local(
            q, k, v, axis_name, causal=causal, scale=scale
        )

    fn = shard_map(
        local, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )
    return jax.jit(fn)
