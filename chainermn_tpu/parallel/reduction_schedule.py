"""Interchangeable gradient-reduction schedules — the hot-path abstraction.

The one collective every data-parallel workload shares is the gradient
reduction, and the right ALGORITHM for it depends on the topology:
HiCCL (arXiv:2408.05962) shows hierarchy-aware collective composition
(intra reduce-scatter -> inter allreduce -> allgather) beating a flat
allreduce on multi-chip meshes, and Xu et al. (arXiv:2004.13336) show a
reduce-scatter + sharded weight update strictly dominating replicated
allreduce+update at data-parallel scale. This module gives the
framework ONE schedule abstraction whose entries are DERIVED INSTANCES
of the composition DSL (:mod:`chainermn_tpu.parallel.composition`,
ISSUE 12): every spelling — a menu name below, a composition signature
string, or a ``Composition`` — compiles through ``compile_schedule``
and runs through the one staged executor ``reduce_composed``, and the
autotuner's candidate set is the deriver's output for the world shape,
not a fixed menu. The three named, equivalence-tested strategies
(``tests/test_reduction_schedule.py``; derived sweep in
``tests/test_composition.py``):

- ``'flat'`` — the existing packed allreduce: float leaves ride ~64 MB
  flat buckets (the reference's ``_memory_utility.pack_params`` (dagger)
  flat-buffer discipline, in-jit so XLA owns the copies), one fused
  ``pmean`` per bucket.
- ``'two_level'`` — the pinned hierarchical pipeline per bucket:
  ``psum_scatter`` over the last (fast/intra) mesh axis, allreduce of
  the 1/n shard over the remaining axes, ``all_gather`` back — the
  reference's ``TwoDimensionalCommunicator`` algorithm
  (``two_dimensional_communicator.py`` (dagger)) generalised to any
  mesh (on a flat mesh it pins the reduce-scatter/all-gather
  decomposition).
- ``'zero'`` — reduce-scatter + SHARDED update + allgather, fusing with
  :mod:`chainermn_tpu.parallel.zero`: the optimizer update itself runs
  on 1/n of the parameters (1/n optimizer state, 1/n update FLOPs,
  same wire bytes as the allreduce it replaces). Structural — lives in
  :class:`chainermn_tpu.optimizers.MultiNodeOptimizer`, which calls the
  chunk/scatter/gather building blocks here.

Schedule choice is a first-class decision in the autotune registry
(:mod:`chainermn_tpu.tuning`, decision ``'reduction_schedule'``), keyed
(device_kind x world-shape x payload-MB bucket) and seedable offline
from ``bench.py``'s ``overlap`` phase rows — :func:`resolve_schedule`.

Double buffering (the reference's ``double_buffering_optimizer.py``
(dagger) staleness-1 semantics) composes with the bucketed schedules:
an overlapped reduction tags its per-bucket ``wire`` trace events with
``overlapped=True`` so ``tools/trace_report.py`` can report the
comm-hidden fraction; :class:`OverlappedBucketReducer` is the eager
per-bucket driver that MEASURES the overlap (dispatch step N's bucket
collectives without blocking, collect them after step N+1's compute).
"""

from __future__ import annotations

import time
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from chainermn_tpu.observability import trace as _trace

PyTree = Any

#: The NAMED strategies (the head of the registry's candidate list —
#: the full choice set for a world shape is
#: :func:`chainermn_tpu.parallel.composition.schedule_candidates`,
#: which appends the derived beyond-menu composition signatures).
SCHEDULES = ("flat", "two_level", "zero")

#: Registry decision name for the ``'auto'`` schedule resolution.
DECISION = "reduction_schedule"

#: Registry decision name for the bucket-slice count a composed
#: schedule interleaves over (ISSUE 15): ∈ {1, 2, 4, 8}, table default
#: 1 — slicing multiplies per-stage collective dispatches S× (at 1/S
#: payload each), so the interleave must EARN adoption through the
#: bench ``composed`` phase's sliced arms (spread-gated, the
#: spec_tokens/prefill_chunk precedent). Keyed beside ``DECISION`` on
#: world-shape x payload-MB so one capture adjudicates both.
SLICES_DECISION = "comp_slices"

#: The ``comp_slices`` candidate set (registry spellings are strings).
SLICE_CANDIDATES = ("1", "2", "4", "8")

#: ~64 MB (the tuned table default of ``allreduce_bucket_mb``) — the
#: single fallback the bucket partition uses when no tuned size is
#: pinned; large enough to keep the slow level bandwidth-bound, small
#: enough to bound the transient flat copy in HBM.
DEFAULT_BUCKET_BYTES = 64 << 20


def bucket_partition(
    idxs: Sequence[int],
    sizes: Sequence[int],
    itemsize: int = 4,
    bucket_bytes: Optional[int] = None,
) -> list[list[int]]:
    """Deterministic greedy ~``bucket_bytes`` partition of the entries
    ``idxs`` (element counts in ``sizes``) — the ONE bucket layout
    shared by every schedule, the EF residual allocation, and the
    overlapped reducer, so no two consumers can disagree.

    Edge contract (ISSUE 3 satellite, unit-tested):

    - zero-size entries are SKIPPED — they would otherwise produce
      empty buckets whose concatenated payload has no max-abs for the
      int8 wire's scale (callers reduce them on the exact per-leaf
      path, where an empty array is trivially its own mean);
    - a payload smaller than one bucket yields EXACTLY one bucket (no
      degenerate empty tail);
    - a single entry larger than the bucket gets its own bucket,
      unsplit;
    - no emitted bucket is ever empty.
    """
    if bucket_bytes is None:
        bucket_bytes = DEFAULT_BUCKET_BYTES
    buckets: list[list[int]] = []
    cur: list[int] = []
    cur_bytes = 0
    for i in idxs:
        nbytes = sizes[i] * itemsize
        if nbytes == 0:
            continue
        if cur and cur_bytes + nbytes > bucket_bytes:
            buckets.append(cur)
            cur, cur_bytes = [], 0
        cur.append(i)
        cur_bytes += nbytes
    if cur:
        buckets.append(cur)
    return buckets


def resolve_comp_slices(
    device_kind: Optional[str],
    payload_bytes: int,
    world_shape: Sequence[int],
) -> int:
    """The ``comp_slices`` resolution (ISSUE 15): how many bucket
    slices a composed reduction interleaves over, through the autotune
    registry — keyed exactly like :func:`resolve_schedule` (world-shape
    x payload-MB, dtype tag ``'slices'``), table default 1 (slicing
    must earn adoption; a cache entry seeded from bench's
    ``composed_sliced_ms`` rows moves it)."""
    from chainermn_tpu import tuning

    mb = max(1, int(payload_bytes) >> 20)
    key = tuning.decision_key(
        device_kind, shape=tuple(int(d) for d in world_shape) + (mb,),
        dtype="slices",
    )
    return int(tuning.choice(SLICES_DECISION, SLICE_CANDIDATES, key))


def resolve_schedule(
    device_kind: Optional[str],
    payload_bytes: int,
    world_shape: Sequence[int],
    *,
    candidates: Optional[Sequence[str]] = None,
    slices=None,
):
    """The ``reduction_schedule='auto'`` resolution: winner through the
    autotune registry, keyed ``device_kind x (world-shape, payload-MB)
    x 'sched'`` (each dim power-of-two bucketed by ``decision_key``, so
    nearby payloads share one decision). Returns ``(winner, record)``
    with ``record`` the registry's decision provenance (name / winner /
    source / key, plus ``composition`` — the canonical-token signature
    the winner compiles to, so provenance names the actual pipeline and
    not just a menu label) for the observability layer.

    ``candidates`` defaults to the DERIVED choice set for this world
    shape (:func:`~chainermn_tpu.parallel.composition.
    schedule_candidates`): the menu names plus every composition the
    deriver generates for a ``len(world_shape)``-level mesh, keyed by
    signature string — the autotuner searches generated schedules, not
    a fixed menu. Table default is ``'flat'``; a cache entry seeded
    from bench's ``overlap``/``composed`` phase rows
    (``python -m chainermn_tpu.tuning seed``) moves it where a measured
    comparison shows another pipeline paying (spread-gated, as always).

    ``slices='auto'`` (ISSUE 15) additionally consults the
    ``comp_slices`` decision (:func:`resolve_comp_slices`) and, when it
    resolves > 1 and the winner is sliceable (not the structural
    ``'zero'``), returns the winner's SLICED signature — the record
    then carries ``comp_slices`` and the sliced ``composition``
    spelling. An explicit integer pins the count; ``None`` (default)
    leaves the winner unsliced, the pre-ISSUE-15 behaviour."""
    from chainermn_tpu import tuning
    from chainermn_tpu.parallel.composition import (
        schedule_candidates,
        signature_for,
    )

    n_axes = max(1, len(tuple(world_shape)))
    if candidates is None:
        candidates = schedule_candidates(n_axes)
    mb = max(1, int(payload_bytes) >> 20)
    key = tuning.decision_key(
        device_kind, shape=tuple(int(d) for d in world_shape) + (mb,),
        dtype="sched",
    )
    winner = tuning.choice(DECISION, tuple(candidates), key)
    rec = next(
        (d for d in reversed(tuning.decisions_taken())
         if d.get("name") == DECISION and d.get("key") == key),
        None,
    )
    if rec is not None:
        rec = dict(rec)
        try:
            rec["composition"] = signature_for(winner, n_axes)
        except Exception:
            pass
    if slices is not None and winner != "zero":
        from chainermn_tpu.parallel.composition import (
            canonical_axis_names,
            compile_schedule,
            sliced_composition,
        )

        n_slices = (resolve_comp_slices(device_kind, payload_bytes,
                                        world_shape)
                    if slices == "auto" else int(slices))
        if n_slices > 1:
            comp = sliced_composition(
                compile_schedule(winner, canonical_axis_names(n_axes)),
                n_slices,
            )
            winner = comp.signature()
            if rec is not None:
                rec["comp_slices"] = n_slices
                rec["composition"] = winner
    return winner, rec


def reduce_tree(
    grads: PyTree,
    *,
    schedule,
    axes,
    compress_dtype=None,
    bucket_bytes: Optional[int] = None,
    overlapped: bool = False,
    provenance: Optional[dict] = None,
    op: Optional[str] = None,
    size: Optional[int] = None,
) -> PyTree:
    """Bucketed, schedule-pinned in-jit MEAN reduction of a gradient
    pytree. Must run inside the named-axis context of ``axes`` (callers
    probe ``collectives.axes_bound`` and fall back to their legacy
    identity/pmean path outside it — this function does not degrade).

    ``schedule`` is a menu name (``'flat'`` / ``'two_level'``), a
    composition signature string, or a
    :class:`~chainermn_tpu.parallel.composition.Composition` — every
    spelling is COMPILED to a validated composition
    (:func:`~chainermn_tpu.parallel.composition.compile_schedule`) and
    run through the one staged executor
    (:func:`~chainermn_tpu.parallel.composition.reduce_composed`), so
    the menu entries are derived instances, not separate code paths
    (``'flat'`` = ``ar(all)``, one fused pmean per bucket;
    ``'two_level'`` = ``rs(fast) > ar(rest) > ag(fast)``, the pinned
    hierarchical pipeline). Leaves are grouped by wire dtype and packed
    into ~``bucket_bytes`` flat buffers (:func:`bucket_partition`);
    each bucket crosses the wire as that composition's stage pipeline.
    The int8 wire is a WIRE variant, not a schedule: it has a flat and
    a two-level rendering only (the two-phase quantized scheme has no
    generic staged form), and any other composition on an int8 wire is
    refused loudly. SLICED spellings of those two renderings (ISSUE 16
    satellite, e.g. ``rs(data)[s0..3]>ag(data)``) ARE accepted: each
    bucket slice rides its own two-phase wire — same grammar, per-slice
    quantization scales (so the result matches the unsliced int8 wire
    to quantization tolerance, not bitwise; both stay within the wire's
    stated ~1/127-per-stage error of the exact mean), zigzag ``[z...]``
    cut/reassembly honored.

    Zero-size leaves take the exact per-leaf path (see
    :func:`bucket_partition`'s edge contract). At TRACE time (host-side
    Python, once per compilation — the lowered HLO is untouched) one
    ``pack`` event plus one ``wire`` event PER BUCKET PER STAGE are
    recorded: each wire event carries the bucket's ``composition``
    signature, its ``stage`` (e.g. ``rs(intra)``) and that stage's
    payload bytes, plus ``overlapped`` (true under the double-buffered
    mode, whose update consumes the PREVIOUS step's buckets — the
    dependency break that lets the runtime run these collectives
    concurrently with compute) so ``tools/trace_report.py`` can
    attribute comm time per composition stage.
    """
    from chainermn_tpu.parallel.collectives import (
        int8_allreduce_mean,
        int8_decomposed_allreduce_mean,
        _names_tuple,
    )
    from chainermn_tpu.parallel.composition import (
        CompositionError,
        compact_slices,
        compile_schedule,
        effective_slices,
        reduce_composed,
        slice_bounds,
        stage_wire_layout,
        two_level_composition,
    )

    names = _names_tuple(axes)
    try:
        comp = compile_schedule(schedule, names)
    except CompositionError as e:
        raise ValueError(str(e)) from None
    if comp.has_update:
        valid = tuple(s for s in SCHEDULES if s != "zero")
        raise ValueError(
            f"reduce_tree runs the pure reduction schedules {valid} (or "
            f"any validated composition without a sharded_update stage), "
            f"got {schedule!r} — the sharded update is structural, see "
            "MultiNodeOptimizer's 'zero' schedule"
        )
    label = (schedule if isinstance(schedule, str) and "(" not in schedule
             else comp.signature())
    sig = comp.signature()
    int8_wire = (compress_dtype is not None
                 and jnp.dtype(compress_dtype) == jnp.dtype(jnp.int8))
    flat_sig = compile_schedule("flat", names).signature()
    two_level_sig = two_level_composition(names).signature()
    # The int8 gate compares the UNSLICED base pipeline: sliced
    # spellings of the two renderings ride per-slice two-phase wires
    # (ISSUE 16 satellite), anything else is refused.
    import dataclasses as _dc

    base_sig = _dc.replace(
        compact_slices(comp), slices=1, slice_layout="contiguous"
    ).signature()
    if int8_wire and base_sig not in (flat_sig, two_level_sig):
        raise ValueError(
            f"the int8 two-phase wire has flat and two-level renderings "
            f"only (sliced spellings of those included) — composition "
            f"{sig!r} cannot ride it; use the bf16/f32 wire for composed "
            "schedules"
        )
    leaves, treedef = jax.tree.flatten(grads)
    if not leaves:
        return grads

    def cast_dtype(g):
        if compress_dtype is not None and jnp.issubdtype(
            g.dtype, jnp.floating
        ):
            # int8 wire: buckets pack in f32; quantization happens
            # inside the wire per bucket.
            return (jnp.dtype(jnp.float32) if int8_wire
                    else jnp.dtype(compress_dtype))
        return jnp.dtype(g.dtype)

    out: list = [None] * len(leaves)
    sizes = [g.size for g in leaves]
    groups: dict = {}
    for i, g in enumerate(leaves):
        groups.setdefault(cast_dtype(g), []).append(i)

    def exact_mean(g):
        # Per-leaf exact path (zero-size leaves): pmean keeps the
        # reference-parity dtype contract.
        return lax.pmean(g, names).astype(g.dtype)

    def reduce_bucket(flat, dt):
        if int8_wire and jnp.issubdtype(dt, jnp.floating):
            # The quantized wire's rendering is chosen by the
            # composition's SHAPE: a scatter stage means the int8
            # phases ride only the non-scatter axes. Sliced spellings
            # run the two-phase wire per bucket slice (each slice
            # quantizes against its own max-abs), same cut/reassembly
            # indexing as reduce_composed's sliced path.
            fn = (int8_decomposed_allreduce_mean
                  if base_sig == two_level_sig else int8_allreduce_mean)
            s_eff = effective_slices(comp.slices, flat.size)
            if s_eff <= 1:
                return fn(flat, names)
            if comp.slice_layout == "zigzag":
                red = jnp.zeros_like(flat)
                for i in range(s_eff):
                    red = red.at[i::s_eff].set(fn(flat[i::s_eff], names))
                return red
            return jnp.concatenate([
                fn(flat[lo:hi], names)
                for lo, hi in slice_bounds(flat.size, s_eff)
            ])
        return reduce_composed(flat, comp, op="mean")

    rec = _trace.active()
    n_buckets_total = 0
    # (bucket wire bytes, dtype name, element count) per bucket
    bucket_meta: list[tuple[int, str, int]] = []
    for dt, idxs in groups.items():
        itemsize = jnp.dtype(dt).itemsize
        wire_item = (1 if int8_wire and jnp.issubdtype(dt, jnp.floating)
                     else itemsize)
        buckets = bucket_partition(idxs, sizes, itemsize, bucket_bytes)
        bucketed = {i for b in buckets for i in b}
        for i in idxs:
            if i not in bucketed:  # zero-size leaf: exact per-leaf path
                out[i] = exact_mean(leaves[i])
        n_buckets_total += len(buckets)
        for bidx in buckets:
            flat = jnp.concatenate(
                [leaves[i].astype(dt).ravel() for i in bidx]
            )
            red = reduce_bucket(flat, dt)
            off = 0
            for i in bidx:
                n = leaves[i].size
                out[i] = (
                    red[off: off + n]
                    .reshape(leaves[i].shape)
                    .astype(leaves[i].dtype)
                )
                off += n
            bucket_meta.append(
                (flat.size * wire_item, jnp.dtype(dt).name, flat.size)
            )

    if rec is not None:
        def wire_itemsize(g):
            if int8_wire and jnp.issubdtype(g.dtype, jnp.floating):
                return 1
            return jnp.dtype(cast_dtype(g)).itemsize

        wire_name = ("int8" if int8_wire else
                     (jnp.dtype(compress_dtype).name
                      if compress_dtype is not None else "none"))
        # Slice-degrade provenance (ISSUE 15 satellite, LOUD): a bucket
        # smaller than the requested slice count runs min(S, elements)
        # slices — the pack event names every degraded bucket so the
        # adopted comp_slices can be audited against what actually ran.
        slice_note = {}
        if comp.slices > 1:
            from chainermn_tpu.parallel.composition import (
                effective_slices,
            )

            degraded = {
                b_i: effective_slices(comp.slices, n_elems)
                for b_i, (_, _, n_elems) in enumerate(bucket_meta)
                if effective_slices(comp.slices, n_elems) < comp.slices
            }
            slice_note["comp_slices"] = comp.slices
            if degraded:
                slice_note["comp_slices_degraded"] = degraded
                slice_note["comp_slices_note"] = (
                    f"requested {comp.slices} slices; bucket(s) "
                    f"{sorted(degraded)} smaller than S degraded to "
                    f"min(S, elements) (zero-leaf contract)"
                )
        rec.event(
            "pack", op=(op or f"scheduled_reduce[{label}]"),
            nbytes=sum(g.size * wire_itemsize(g) for g in leaves),
            bucket_bytes=(bucket_bytes if bucket_bytes is not None
                          else DEFAULT_BUCKET_BYTES),
            n_buckets=n_buckets_total,
            wire_dtype=wire_name,
            provenance=provenance,
            **slice_note,
            **({"size": size} if size is not None else {}),
        )
        axis_sizes = {a: lax.axis_size(a) for a in names}
        for b_i, (nbytes, dt_name, n_elems) in enumerate(bucket_meta):
            wire_item = max(1, nbytes // max(1, n_elems))
            for s_i, row in enumerate(
                stage_wire_layout(comp, axis_sizes, wire_item, n_elems)
            ):
                rec.event(
                    "wire", schedule=label, composition=sig,
                    stage=row["stage"], stage_index=s_i,
                    stage_op=row["op"], bucket=b_i,
                    n_buckets=n_buckets_total, nbytes=row["nbytes"],
                    wire_dtype=("int8" if int8_wire and "float" in dt_name
                                else dt_name),
                    overlapped=bool(overlapped),
                    **({"slice": row["slice"],
                        "n_slices": row["n_slices"]}
                       if "slice" in row else {}),
                )
    return jax.tree.unflatten(treedef, out)


class OverlappedBucketReducer:
    """Eager double-buffered per-bucket gradient reduction — the
    MEASURED side of the overlap story (the in-jit double-buffered mode
    relies on XLA's async scheduler; this driver makes the overlap an
    explicit host-side pipeline, and its wire events carry true
    durations).

    Usage (the staleness-1 loop, reference
    ``double_buffering_optimizer.py`` (dagger) semantics)::

        red = OverlappedBucketReducer(comm)
        red.dispatch(stacked_grads_t)       # per-bucket collectives fly
        ...compute step t+1's backward...   # overlaps the wire
        mean_t = red.collect()              # blocks only on what's left

    ``dispatch`` partitions the stacked gradient tree (leaves
    ``[size, ...]``, the eager-communicator convention) into the tuned
    ~64 MB buckets and launches one jitted mean-allreduce per bucket
    WITHOUT blocking — JAX's async dispatch keeps them in flight while
    the caller computes. ``collect`` blocks on each bucket and records
    one ``wire`` trace event per bucket with ``dur_s`` (dispatch ->
    ready) and ``blocked_s`` (time actually spent waiting inside
    collect): the difference is the comm time HIDDEN behind compute,
    which ``tools/trace_report.py``'s overlap section aggregates into
    the comm-hidden fraction.

    ``slices`` (ISSUE 15): each bucket is additionally cut into
    ``min(slices, elements)`` contiguous column slices
    (:func:`~chainermn_tpu.parallel.composition.slice_bounds` — the
    zero-leaf degrade contract) and ONE collective flies per slice —
    the REAL async interleave: slice i can retire while slice i+1 is
    still on the wire, and each slice's ``wire`` event carries its
    ``slice``/``n_slices`` address beside ``dur_s``/``blocked_s``, so
    the overlap table shows per-slice hiding, not just per-bucket.
    """

    def __init__(self, comm, *, bucket_bytes: Optional[int] = None,
                 slices: int = 1) -> None:
        self.comm = comm
        if bucket_bytes is None:
            from chainermn_tpu.parallel.collectives import tuned_bucket_bytes

            bucket_bytes = tuned_bucket_bytes(comm.device_kind, comm.size)
        self.bucket_bytes = bucket_bytes
        if int(slices) < 1:
            raise ValueError(f"slices must be >= 1, got {slices}")
        self.slices = int(slices)
        self._inflight: list = []
        self._layout = None

    @property
    def in_flight(self) -> bool:
        return bool(self._inflight)

    def dispatch(self, grads_stacked: PyTree) -> int:
        """Launch this step's per-bucket mean-allreduces (leaves are
        stacked ``[size, ...]`` per-rank contributions); returns the
        bucket count. A previous step's reduction must have been
        collected first."""
        if self._inflight:
            raise RuntimeError(
                "a bucketed reduction is already in flight — collect() "
                "the previous step before dispatching the next"
            )
        n = self.comm.size
        leaves, treedef = jax.tree.flatten(grads_stacked)
        for leaf in leaves:
            if leaf.shape[0] != n:
                raise ValueError(
                    f"stacked leaves must have leading dim == size ({n}), "
                    f"got {leaf.shape}"
                )
        sizes = [leaf[0].size for leaf in leaves]
        # itemsize 4: every bucket packs (and crosses the wire) in f32.
        buckets = bucket_partition(
            list(range(len(leaves))), sizes, 4, self.bucket_bytes,
        )
        self._layout = (treedef, leaves, buckets)
        mean = self.comm._jitted["mean"]
        from chainermn_tpu.parallel.composition import (
            effective_slices,
            slice_bounds,
        )

        for b_i, bidx in enumerate(buckets):
            flat = jnp.concatenate(
                [jnp.asarray(leaves[i]).astype(jnp.float32).reshape(n, -1)
                 for i in bidx],
                axis=1,
            )
            s_eff = effective_slices(self.slices, flat.shape[1])
            for s_i, (lo, hi) in enumerate(slice_bounds(flat.shape[1],
                                                        s_eff)):
                part = flat[:, lo:hi] if s_eff > 1 else flat
                t0 = time.perf_counter()
                out = mean(part)  # async dispatch: returns pre-wire
                self._inflight.append(
                    (b_i, s_i, s_eff, bidx, out, t0, int(part.nbytes)))
        return len(buckets)

    def collect(self) -> PyTree:
        """Block on the in-flight buckets and return the reduced mean
        tree (leaves ``[...]``, un-stacked). Records one ``wire`` event
        per bucket: ``dur_s`` is dispatch->ready, ``blocked_s`` the
        wait actually paid here — ``dur_s - blocked_s`` is comm hidden
        behind whatever the caller computed in between."""
        if not self._inflight:
            raise RuntimeError("collect() with no dispatched reduction")
        treedef, leaves, buckets = self._layout
        rec = _trace.active()
        out: list = [None] * len(leaves)
        bucketed = {i for b in buckets for i in b}
        for i, leaf in enumerate(leaves):
            if i not in bucketed:  # zero-size leaves: mean is identity
                out[i] = jnp.asarray(leaf)[0]
        rows: dict[int, list] = {}
        for b_i, s_i, s_eff, bidx, red, t0, nbytes in self._inflight:
            t_c = time.perf_counter()
            red = jax.block_until_ready(red)
            t_r = time.perf_counter()
            if rec is not None:
                dur = t_r - t0
                blocked = t_r - t_c
                rec.event(
                    "wire", schedule="overlap_eager", bucket=b_i,
                    n_buckets=len(buckets), nbytes=nbytes,
                    dur_s=round(dur, 9), blocked_s=round(blocked, 9),
                    overlapped=bool(dur - blocked > 0),
                    **({"slice": s_i, "n_slices": s_eff}
                       if s_eff > 1 else {}),
                )
            rows.setdefault(b_i, []).append((s_i, bidx, red[0]))
        for b_i, parts in rows.items():
            parts.sort()
            bidx = parts[0][1]
            row = (jnp.concatenate([p[2] for p in parts])
                   if len(parts) > 1 else parts[0][2])  # [k]: the mean
            off = 0
            for i in bidx:
                k = leaves[i][0].size
                out[i] = (row[off: off + k]
                          .reshape(leaves[i].shape[1:])
                          .astype(leaves[i].dtype))
                off += k
        self._inflight = []
        self._layout = None
        return jax.tree.unflatten(treedef, out)


class MeasuredComposedReducer:
    """Eager per-STAGE composed reduction — the measured side of the
    composed-schedule story (ISSUE 13 satellite, the PR 11 follow-up).

    The in-jit composed executor (:func:`~chainermn_tpu.parallel.
    composition.reduce_composed`) emits trace-time ``wire`` layout
    events per stage — bytes the program COMMITTED to, no durations.
    This driver runs the SAME stage list eagerly (one jitted shard_map
    program per stage over the communicator's mesh, the stacked
    ``[size, ...]`` eager-communicator convention), blocks between
    stages, and records one ``wire`` event per stage carrying
    ``dur_s`` — so ``tools/trace_report.py``'s overlap section gains a
    MEASURED per-stage duration column in the per-signature stage table
    (``summarize_overlap`` folds ``dur_s`` into ``stages[..].dur_ms``).
    The blocking is the point: a per-stage wall clock is only honest
    when the previous stage's collective has retired
    (the :class:`OverlappedBucketReducer` dur_s/blocked_s pattern,
    applied per stage instead of per bucket).

    Pure reductions only — a ``sharded_update`` stage belongs to the
    optimizer fuse point, not an eager wire driver (refused loudly).

    ``slices`` (ISSUE 15): the composition is run SLICED — the flat
    buffer cut into ``min(slices, elements)`` contiguous slices, the
    per-slice stages DISPATCHED in the skewed interleave order without
    blocking (slice i's slow stage in flight while slice i+1's fast
    stage dispatches — JAX's async dispatch realises the overlap the
    in-jit rendering only commits to), then collected in the same
    order: each per-slice stage ``wire`` event carries ``slice``/
    ``n_slices`` beside ``dur_s`` (dispatch -> ready) and ``blocked_s``
    (wait paid at collection) — the per-slice ``dur_ms``/``blocked_ms``
    columns of the overlap table. Unsliced (default) keeps the
    block-per-stage honest wall clock unchanged.

    Usage::

        red = MeasuredComposedReducer(comm, schedule="two_level")
        mean = red.reduce(stacked_grads)   # [size, ...] leaves -> mean
    """

    def __init__(self, comm, schedule="two_level", *,
                 slices: int = 1) -> None:
        from chainermn_tpu.parallel.composition import (
            CompositionError,
            compile_schedule,
            sliced_composition,
        )

        self.comm = comm
        axes = comm.grad_axes
        axes = axes if isinstance(axes, tuple) else (axes,)
        self.comp = compile_schedule(schedule, axes)
        if self.comp.has_update:
            raise CompositionError(
                f"{self.comp.signature()!r} carries a sharded_update "
                "stage — the eager measured reducer runs pure "
                "reductions (the update fuse point is "
                "MultiNodeOptimizer's 'zero' schedule)"
            )
        if int(slices) > 1:
            self.comp = sliced_composition(self.comp, int(slices))
        self._axes = axes
        self._stage_jits: dict = {}

    def _stage_fn(self, i: int, primitive, stage_axes, orig_size,
                  cur_size):
        # orig_size is in the key too: two slices can share a padded
        # shard width while un-padding to different lengths (ISSUE 15),
        # and equal-width slices share one compiled program.
        key = (i, cur_size, orig_size)
        if key in self._stage_jits:
            return self._stage_jits[key]
        from jax import shard_map
        from jax.sharding import PartitionSpec as P

        from chainermn_tpu.parallel.collectives import (
            staged_allgather,
            staged_allreduce,
            staged_reduce_scatter,
        )

        def local(x):
            b = x[0]
            if primitive == "reduce_scatter":
                out = staged_reduce_scatter(b, stage_axes)
            elif primitive == "allreduce":
                out = staged_allreduce(b, stage_axes)
            else:
                out = staged_allgather(b, stage_axes, orig_size)
            return out[None]

        fn = jax.jit(shard_map(
            local, mesh=self.comm.mesh,
            in_specs=P(self._axes), out_specs=P(self._axes),
            check_vma=False,
        ))
        self._stage_jits[key] = fn
        return fn

    def reduce(self, grads_stacked: PyTree) -> PyTree:
        """Run the composition stage by stage on ONE flat f32 buffer
        (leaves ``[size, ...]`` stacked per-rank contributions,
        concatenated), blocking per stage, and return the un-stacked
        mean tree. Records one measured ``wire`` event per stage."""
        from chainermn_tpu.parallel.composition import (
            _replay_sizes,
            stage_wire_layout,
        )

        n = self.comm.size
        leaves, treedef = jax.tree.flatten(grads_stacked)
        for leaf in leaves:
            if leaf.shape[0] != n:
                raise ValueError(
                    f"stacked leaves must have leading dim == size "
                    f"({n}), got {leaf.shape}"
                )
        sizes = [leaf[0].size for leaf in leaves]
        flat = jnp.concatenate(
            [jnp.asarray(leaf).astype(jnp.float32).reshape(n, -1)
             for leaf in leaves], axis=1,
        ) if leaves else jnp.zeros((n, 0), jnp.float32)
        n_elems = flat.shape[1]
        axis_sizes = {a: int(self.comm.mesh.shape[a])
                      for a in self._axes}
        layout = stage_wire_layout(self.comp, axis_sizes, 4, n_elems)
        sig = self.comp.signature()
        rec = _trace.active()

        from chainermn_tpu.parallel.composition import effective_slices

        s_eff = effective_slices(self.comp.slices, n_elems)
        if s_eff > 1:
            mean = self._reduce_sliced(flat, s_eff, axis_sizes, layout,
                                       sig, rec) / n
        else:
            rows, _, _ = _replay_sizes(self.comp.stages, n_elems,
                                       axis_sizes)
            cur = flat
            li = 0
            for i, (st, size_in, size_out) in enumerate(rows):
                fn = self._stage_fn(i, st.primitive, st.axes, size_out,
                                    size_in)
                t0 = time.perf_counter()
                cur = jax.block_until_ready(fn(cur))
                dur = time.perf_counter() - t0
                if rec is not None and li < len(layout):
                    rec.event(
                        "wire", schedule="composed_eager",
                        composition=sig,
                        stage=st.signature(), stage_index=li,
                        stage_op=layout[li]["op"], bucket=0, n_buckets=1,
                        nbytes=layout[li]["nbytes"],
                        dur_s=round(dur, 9), overlapped=False,
                    )
                li += 1
            mean = cur[0] / n  # replicated sum row -> mean
        out = []
        off = 0
        for leaf, k in zip(leaves, sizes):
            out.append(mean[off:off + k].reshape(leaf.shape[1:])
                       .astype(leaf.dtype))
            off += k
        return jax.tree.unflatten(treedef, out)

    def _reduce_sliced(self, flat, s_eff, axis_sizes, layout, sig, rec):
        """The sliced eager run (ISSUE 15): dispatch every per-slice
        stage in the skewed interleave order WITHOUT blocking, then
        collect in the same order — ``dur_s`` is dispatch->ready,
        ``blocked_s`` the wait paid here, their gap the comm hidden
        behind the other slices' stages. Returns the replicated sum
        row (caller divides by the world size)."""
        import dataclasses as _dc

        from chainermn_tpu.parallel.composition import (
            _replay_sizes as _replay,
            expand_slices,
            slice_bounds,
        )

        bounds = slice_bounds(flat.shape[1], s_eff)
        # Honor the composition's cut: zigzag slice i is the strided
        # comb i, i+S, ... (same per-slice sizes as the contiguous
        # bounds, so the replayed stage rows are shared).
        zigzag = self.comp.slice_layout == "zigzag"
        if zigzag:
            cur_s = [flat[:, i::s_eff] for i in range(s_eff)]
        else:
            cur_s = [flat[:, lo:hi] for lo, hi in bounds]
        per_rows = [
            _replay(self.comp.stages, hi - lo, axis_sizes)[0]
            for lo, hi in bounds
        ]
        nodes = []  # (layout_index, slice, out_array, t0)
        li = 0
        for st in expand_slices(self.comp, flat.shape[1]):
            i, _ = st.slice
            base = _dc.replace(st, slice=None)
            j = self.comp.stages.index(base)
            _, size_in, size_out = per_rows[i][j]
            fn = self._stage_fn(j, st.primitive, st.axes,
                                size_out, size_in)
            t0 = time.perf_counter()
            cur_s[i] = fn(cur_s[i])  # async dispatch: no block here
            nodes.append((li, i, cur_s[i], t0))
            li += 1
        for li, i, arr, t0 in nodes:
            t_c = time.perf_counter()
            jax.block_until_ready(arr)
            t_r = time.perf_counter()
            if rec is not None and li < len(layout):
                rec.event(
                    "wire", schedule="composed_eager", composition=sig,
                    stage=layout[li]["stage"], stage_index=li,
                    stage_op=layout[li]["op"], bucket=0, n_buckets=1,
                    nbytes=layout[li]["nbytes"],
                    slice=layout[li]["slice"],
                    n_slices=layout[li]["n_slices"],
                    dur_s=round(t_r - t0, 9),
                    blocked_s=round(t_r - t_c, 9),
                    overlapped=bool((t_r - t0) - (t_r - t_c) > 0),
                )
        import jax.numpy as _jnp

        if zigzag:
            out = _jnp.zeros((flat.shape[1],), cur_s[0].dtype)
            for i, c in enumerate(cur_s):
                out = out.at[i::s_eff].set(c[0])
            return out
        return _jnp.concatenate([c[0] for c in cur_s])


__all__ = [
    "DECISION",
    "DEFAULT_BUCKET_BYTES",
    "MeasuredComposedReducer",
    "OverlappedBucketReducer",
    "SCHEDULES",
    "SLICES_DECISION",
    "SLICE_CANDIDATES",
    "bucket_partition",
    "reduce_tree",
    "resolve_comp_slices",
    "resolve_schedule",
]
