"""Mesh topology and in-program collective primitives.

This package is the TPU-native replacement for the reference's L0-L2 layers
(native NCCL binding + ``_communication_utility.py`` (dagger) +
``_memory_utility.py`` (dagger), see SURVEY.md section 1): instead of
bootstrapping NCCL rings over MPI and packing gradients into flat device
buffers by hand, we build a ``jax.sharding.Mesh`` over the pod slice and let
XLA lower named-axis collectives onto ICI/DCN. Flat-buffer packing is
deliberately absent — XLA fuses the pack/cast/scale/unpack pipeline that the
reference implemented manually (SURVEY.md section 3.2 TPU mapping).
"""

from chainermn_tpu.parallel.mesh import (
    MeshTopology,
    make_mesh,
    best_mesh_shape,
)
from chainermn_tpu.parallel import collectives

__all__ = ["MeshTopology", "make_mesh", "best_mesh_shape", "collectives"]
