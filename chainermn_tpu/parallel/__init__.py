"""Mesh topology and in-program collective primitives.

This package is the TPU-native replacement for the reference's L0-L2 layers
(native NCCL binding + ``_communication_utility.py`` (dagger) +
``_memory_utility.py`` (dagger), see SURVEY.md section 1): instead of
bootstrapping NCCL rings over MPI and packing gradients into flat device
buffers by hand, we build a ``jax.sharding.Mesh`` over the pod slice and let
XLA lower named-axis collectives onto ICI/DCN. Flat-buffer packing is
deliberately absent — XLA fuses the pack/cast/scale/unpack pipeline that the
reference implemented manually (SURVEY.md section 3.2 TPU mapping).
"""

from chainermn_tpu.parallel.mesh import (
    MeshTopology,
    make_mesh,
    best_mesh_shape,
)
from chainermn_tpu.parallel import collectives


def __getattr__(name):
    # Lazy: ring_attention/ulysses import ops (attention locals), which must
    # not load during communicator bootstrap.
    if name in ("ring_attention_local", "make_ring_attention"):
        from chainermn_tpu.parallel import ring_attention as _ra

        return getattr(_ra, name)
    if name == "sliding_window_attention_local":
        from chainermn_tpu.parallel import local_attention as _la

        return getattr(_la, name)
    if name in ("ulysses_attention_local", "make_ulysses_attention"):
        from chainermn_tpu.parallel import ulysses as _ul

        return getattr(_ul, name)
    if name in (
        "pipeline_local", "make_pipeline", "stack_stage_params",
        "stack_interleaved_stage_params", "pipeline_total_ticks",
        "pipeline_1f1b_local", "make_pipeline_1f1b",
        "pipeline_hetero_local", "make_pipeline_hetero", "pipe_plan_axis",
        "unscale_replicated_grads",
    ):
        from chainermn_tpu.parallel import pipeline as _pp

        return getattr(_pp, name)
    if name in ("zero_shard_optimizer", "zero_state_specs",
                "zero_plan_axis", "zero_stacked_init", "zero_grad_scatter",
                "zero_param_chunk", "zero_gather_updates"):
        from chainermn_tpu.parallel import zero as _z

        return getattr(_z, name)
    if name in ("ParallelPlan", "PipelinePlanSpec"):
        from chainermn_tpu.parallel import plan as _plan

        return getattr(_plan, name)
    if name in ("AxisSpec", "CANONICAL_AXES"):
        from chainermn_tpu.parallel import plan_specs as _pspec

        return getattr(_pspec, name)
    if name in ("reduce_tree", "resolve_schedule", "bucket_partition",
                "OverlappedBucketReducer", "SCHEDULES"):
        from chainermn_tpu.parallel import reduction_schedule as _rs

        return getattr(_rs, name)
    if name in ("Composition", "CompositionError", "Stage",
                "compile_schedule", "derive_compositions",
                "parse_signature", "predicted_collectives",
                "reduce_composed", "schedule_candidates",
                "validate_composition", "zero_composition"):
        from chainermn_tpu.parallel import composition as _comp

        return getattr(_comp, name)
    if name in ("moe_layer_local", "top1_route", "topk_route",
                "load_balancing_loss", "make_expert_params",
                "moe_capacity", "routing_stats",
                "record_moe_dispatch", "resolve_expert_parallel"):
        from chainermn_tpu.parallel import moe as _m

        return getattr(_m, name)
    if name == "moe_plan_axis":
        from chainermn_tpu.parallel import plan_specs as _pspec

        return getattr(_pspec, name)
    if name in (
        "fsdp_shardings", "create_fsdp_train_state", "make_fsdp_train_step"
    ):
        from chainermn_tpu.parallel import fsdp as _f

        return getattr(_f, name)
    if name in (
        "copy_to_tp", "reduce_from_tp", "gather_from_tp", "tp_slice", "stack_tp_params",
        "column_parallel_dense", "row_parallel_dense", "tp_mlp",
        "tp_attention", "shard_qkv_columns", "tp_plan_axis",
    ):
        from chainermn_tpu.parallel import tensor as _t

        return getattr(_t, name)
    raise AttributeError(name)


__all__ = [
    "MeshTopology",
    "make_mesh",
    "best_mesh_shape",
    "collectives",
    "ring_attention_local",
    "make_ring_attention",
    "sliding_window_attention_local",
    "ulysses_attention_local",
    "make_ulysses_attention",
    "pipeline_local",
    "make_pipeline",
    "stack_interleaved_stage_params",
    "pipeline_total_ticks",
    "stack_stage_params",
    "pipeline_1f1b_local",
    "make_pipeline_1f1b",
    "pipeline_hetero_local",
    "make_pipeline_hetero",
    "zero_shard_optimizer",
    "zero_state_specs",
    "zero_plan_axis",
    "zero_stacked_init",
    "zero_grad_scatter",
    "zero_param_chunk",
    "zero_gather_updates",
    "ParallelPlan",
    "PipelinePlanSpec",
    "AxisSpec",
    "CANONICAL_AXES",
    "reduce_tree",
    "resolve_schedule",
    "bucket_partition",
    "OverlappedBucketReducer",
    "SCHEDULES",
    "Composition",
    "CompositionError",
    "Stage",
    "compile_schedule",
    "derive_compositions",
    "parse_signature",
    "predicted_collectives",
    "reduce_composed",
    "schedule_candidates",
    "validate_composition",
    "zero_composition",
    "moe_layer_local",
    "top1_route",
    "topk_route",
    "load_balancing_loss",
    "make_expert_params",
    "moe_capacity",
    "routing_stats",
    "record_moe_dispatch",
    "resolve_expert_parallel",
    "moe_plan_axis",
    "fsdp_shardings",
    "create_fsdp_train_state",
    "make_fsdp_train_step",
    "copy_to_tp",
    "reduce_from_tp",
    "gather_from_tp",
    "tp_slice",
    "stack_tp_params",
    "column_parallel_dense",
    "row_parallel_dense",
    "tp_mlp",
    "tp_attention",
    "tp_plan_axis",
    "pipe_plan_axis",
]
