"""α–β cost model for composed reduction schedules (ISSUE 16).

PR 11's deriver enumerates ``2^k`` legal pipelines per mesh and PR 15's
slicing multiplied that by slice-count arms; brute-force measurement of
the grid stops scaling past ~3 mesh levels. This module prices every
derived pipeline (sliced variants included) with a per-LEVEL α–β model
— the HiCCL-style decomposition (arXiv:2408.05962): each mesh level ℓ
has a latency coefficient ``α_ℓ`` (ms per ring step — the per-hop
fixed cost) and a bandwidth coefficient ``β_ℓ`` (ms per wire byte),
and a stage over a merged axis group costs ``steps·α_ℓ + wire·β_ℓ``
where ℓ is the SLOWEST member level of the group (axis 0 is the
slow/DCN-most level, the repo's mesh convention — merging a fast axis
into a slow group rides the slow wire).

Stage terms (``n`` = merged group size, ``b`` = payload bytes through
the stage — the ring-algorithm arithmetic):

- ``rs`` / ``ag``: ``n-1`` steps, ``((n-1)/n)·b`` wire bytes;
- ``ar``: ``2(n-1)`` steps, ``2((n-1)/n)·b`` (reduce-scatter +
  all-gather fused);
- ``bc``: ``tree_sends(n, radix)`` steps, ``tree_sends·b`` wire (every
  sub-send moves the full buffer along the donor path);
- ``su``: free (owes the wire nothing).

A SLICED composition is priced as its software pipeline's critical
path: the skewed issue order puts stage j of slice i at tick ``i+j``,
concurrent stages within a tick overlap, so the tick costs the MAX of
its members and the pipeline costs the sum over ticks — which is
exactly why slicing can win (the slow inter-level stage hides behind
the fast one) and why the model can rank sliced arms without measuring
them.

FIT SOURCES, in trust order:

- :func:`fit_pipeline_rows` — least squares over the whole-pipeline
  medians the bench already measured (``composed_schedule_ms`` rows in
  BENCH_DETAILS.json): k levels give 2k unknowns, the 8-arm grid gives
  8 equations, overdetermined from 3 levels down. This is the offline
  path :func:`load_from_bench_details` rides.
- :func:`calibrate` — a short live probe (whole-pipeline wall clocks
  through :class:`~chainermn_tpu.parallel.reduction_schedule.
  MeasuredComposedReducer`, median of n repeats) fitted the same way,
  for a box with no bench rows yet.

NEVER TRUSTED BLIND: :func:`rank_compositions` with ``model=None``
(no rows for this mesh shape) returns mode ``exhaustive`` with
provenance ``forced:uncalibrated`` — rank on a default-initialized
model is the failure mode this module refuses by construction — and
every top-k adoption records its predicted-vs-measured error as cache
evidence (``tuning.record_measurement(extra_evidence=...)``), so a
model that drifts past the measurement spread is audited in the cache
and the bench falls back to exhaustive coverage.
"""

from __future__ import annotations

import dataclasses
import json
import time
from typing import Mapping, Optional, Sequence

from chainermn_tpu.parallel.composition import (
    Composition,
    CompositionError,
    DEFAULT_RADIX,
    _replay_sizes,
    canonical_axis_names,
    compact_slices,
    compile_schedule,
    effective_slices,
    slice_bounds,
    tree_sends,
)

#: The composed wire is f32 (the executor reduces f32 buffers).
WIRE_ITEMSIZE = 4

#: Provenance string for the forced-exhaustive degrade — the loud
#: spelling ISSUE 16 pins (never rank on a default-initialized model).
UNCALIBRATED = "forced:uncalibrated"


def stage_terms(
    comp: Composition,
    n_elems: int,
    world_shape: Sequence[int],
    mesh_axes: Optional[Sequence[str]] = None,
) -> list[tuple[int, int, float, float]]:
    """Per-stage model terms for ONE pipeline (unsliced rendering) of
    ``n_elems`` f32 elements: ``(tick, level, steps, wire_bytes)``
    rows, one per collective stage per slice. ``tick`` is the software-
    pipeline issue tick (``slice + stage_index``; 0.. for the unsliced
    rendering) — :func:`predict` maxes within a tick and sums across.

    ``mesh_axes`` defaults to the canonical positional tokens; pass the
    actual mesh names when pricing a bound composition."""
    shape = tuple(int(d) for d in world_shape)
    names = (tuple(mesh_axes) if mesh_axes is not None
             else canonical_axis_names(len(shape)))
    if len(names) != len(shape):
        raise CompositionError(
            f"world shape {shape} and mesh axes {names} disagree"
        )
    axis_sizes = {a: shape[i] for i, a in enumerate(names)}
    level_of = {a: i for i, a in enumerate(names)}
    comp = compact_slices(comp)
    s_eff = effective_slices(comp.slices, int(n_elems))

    def rows_for(elems: int, slice_i: int) -> list:
        out = []
        replayed, _, _ = _replay_sizes(comp.stages, elems, axis_sizes)
        for j, (st, size_in, size_out) in enumerate(replayed):
            if st.primitive == "sharded_update":
                continue
            n = 1
            for a in st.axes:
                n *= axis_sizes[a]
            level = min(level_of[a] for a in st.axes)
            if st.primitive == "broadcast":
                sends = tree_sends(n, st.radix or DEFAULT_RADIX)
                steps = sends
                wire = float(sends * size_in * WIRE_ITEMSIZE)
            elif st.primitive == "allreduce":
                steps = 2 * (n - 1)
                wire = 2.0 * (n - 1) / n * size_in * WIRE_ITEMSIZE
            elif st.primitive == "reduce_scatter":
                steps = n - 1
                wire = float(n - 1) / n * size_in * WIRE_ITEMSIZE
            else:  # allgather: the gathered (output) size rides the wire
                steps = n - 1
                wire = float(n - 1) / n * size_out * WIRE_ITEMSIZE
            out.append((slice_i + j, level, steps, wire))
        return out

    if s_eff <= 1:
        return rows_for(int(n_elems), 0)
    rows = []
    for i, (lo, hi) in enumerate(slice_bounds(int(n_elems), s_eff)):
        rows.extend(rows_for(hi - lo, i))
    return rows


@dataclasses.dataclass(frozen=True)
class CostModel:
    """Fitted per-level α–β coefficients for one world shape.

    ``alphas[ℓ]`` is ms per ring step at level ℓ, ``betas[ℓ]`` ms per
    wire byte; ``source`` is the fit provenance
    (``"fit:bench_details"`` / ``"fit:calibration"``); ``fit_err_pct``
    the max relative error of the model on the rows it was fitted from
    (the round-trip bound the tests pin); ``fit_rows`` those rows'
    signatures."""

    world_shape: tuple[int, ...]
    alphas: tuple[float, ...]
    betas: tuple[float, ...]
    source: str
    fit_err_pct: float
    fit_rows: tuple[str, ...] = ()

    def predict(
        self,
        comp,
        payload_bytes: int,
        mesh_axes: Optional[Sequence[str]] = None,
    ) -> float:
        """Predicted ms for ``comp`` (signature string or
        :class:`Composition`) moving ``payload_bytes`` through the
        wire. Sliced compositions are priced as their software
        pipeline's critical path: concurrent stages within an issue
        tick overlap (the tick costs their max), ticks serialize."""
        names = (tuple(mesh_axes) if mesh_axes is not None
                 else canonical_axis_names(len(self.world_shape)))
        if not isinstance(comp, Composition):
            comp = compile_schedule(comp, names)
        n_elems = max(1, int(payload_bytes) // WIRE_ITEMSIZE)
        ticks: dict[int, float] = {}
        for tick, level, steps, wire in stage_terms(
                comp, n_elems, self.world_shape, names):
            cost = steps * self.alphas[level] + wire * self.betas[level]
            ticks[tick] = max(ticks.get(tick, 0.0), cost)
        return float(sum(ticks.values()))


def fit_pipeline_rows(
    rows_ms: Mapping[str, float],
    world_shape: Sequence[int],
    payload_bytes: int,
    *,
    source: str = "fit:pipeline_rows",
) -> CostModel:
    """Fit the per-level α–β coefficients from whole-pipeline medians
    (``{signature: ms}`` at one world shape and payload) by
    non-negative least squares: ``k`` levels give ``2k`` unknowns and
    the composed sweep's ``2^k`` arms give the equations —
    overdetermined from 3 levels down. Coefficients are physical
    (non-negative: a step or a byte never pays back time), enforced by
    projected re-solves on the active set, and the residual of the fit
    on its own rows is stored as ``fit_err_pct`` — the model's stated
    round-trip tolerance, which callers gate adoptions against."""
    import numpy as np

    shape = tuple(int(d) for d in world_shape)
    k = len(shape)
    sigs = sorted(rows_ms)
    if len(sigs) < 2:
        raise CompositionError(
            f"fit needs >= 2 pipeline rows, got {len(sigs)}"
        )
    names = canonical_axis_names(k)
    n_elems = max(1, int(payload_bytes) // WIRE_ITEMSIZE)
    A = np.zeros((len(sigs), 2 * k))
    b = np.array([float(rows_ms[s]) for s in sigs])
    for i, sig in enumerate(sigs):
        comp = compile_schedule(sig, names)
        for _, level, steps, wire in stage_terms(
                comp, n_elems, shape, names):
            A[i, 2 * level] += steps
            A[i, 2 * level + 1] += wire
    # Column scaling (steps are O(1), bytes O(1e6)) + a tiny ridge for
    # rank-deficient grids, then clip-and-refit on the active set so
    # the returned coefficients are non-negative without distorting
    # the free ones.
    col = np.maximum(np.abs(A).max(axis=0), 1e-12)
    As = A / col
    free = np.ones(2 * k, dtype=bool)
    x = np.zeros(2 * k)
    for _ in range(2 * k + 1):
        idx = np.where(free)[0]
        if idx.size == 0:
            break
        Af = As[:, idx]
        ridge = 1e-8 * np.eye(idx.size)
        xf = np.linalg.solve(Af.T @ Af + ridge, Af.T @ b)
        neg = xf < 0
        if not neg.any():
            x = np.zeros(2 * k)
            x[idx] = xf
            break
        free[idx[neg]] = False
    coeffs = x / col
    pred = A @ coeffs
    err = float(np.max(np.abs(pred - b) / np.maximum(np.abs(b), 1e-12)))
    return CostModel(
        world_shape=shape,
        alphas=tuple(float(coeffs[2 * i]) for i in range(k)),
        betas=tuple(float(coeffs[2 * i + 1]) for i in range(k)),
        source=source,
        fit_err_pct=round(err * 100.0, 3),
        fit_rows=tuple(sigs),
    )


def load_from_bench_details(
    path: str = "BENCH_DETAILS.json",
    *,
    world_shape: Optional[Sequence[int]] = None,
) -> Optional[CostModel]:
    """Fit from the composed-sweep rows a prior bench left on disk
    (``composed_schedule_ms`` + ``composed_world_shape`` +
    ``composed_payload_mb``). Returns ``None`` — the UNCALIBRATED
    degrade, never a default model — when the file, the rows, or the
    requested mesh shape are missing/mismatched, and ALSO when the
    rows cannot overdetermine the ``2k`` coefficients (< ``2k+1``
    rows): a prior TOP-K capture leaves only the arms it measured,
    and an interpolating fit over them would round-trip perfectly
    while extrapolating garbage to the skipped arms — the one failure
    mode the predicted-vs-measured audit cannot see (the audited arms
    ARE the fit rows). Refusing keeps the cadence honest: a top-k
    capture is followed by one exhaustive sweep that restores full
    coverage, then top-k resumes."""
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError):
        return None
    rows = data.get("composed_schedule_ms")
    shape = data.get("composed_world_shape")
    payload_mb = data.get("composed_payload_mb")
    if not isinstance(rows, dict) or not shape:
        return None
    if len(rows) < 2 * len(shape) + 1:
        return None
    if world_shape is not None and tuple(int(d) for d in shape) != tuple(
            int(d) for d in world_shape):
        return None
    try:
        return fit_pipeline_rows(
            {str(k): float(v) for k, v in rows.items()},
            tuple(int(d) for d in shape),
            int(float(payload_mb or 1.0) * (1 << 20)),
            source="fit:bench_details",
        )
    except Exception:
        return None


def calibrate(
    comm,
    *,
    payload_mb: float = 1.0,
    candidates: Optional[Sequence[str]] = None,
    repeats: int = 3,
) -> CostModel:
    """Short LIVE probe: run a calibration subset of the derived
    pipelines eagerly (whole-pipeline wall clocks through
    :class:`~chainermn_tpu.parallel.reduction_schedule.
    MeasuredComposedReducer`, median of ``repeats``) and fit the same
    per-level least squares. The default subset is every derived
    composition for the communicator's mesh — at 3 levels that is the
    8-arm grid the bench measures, so calibration and bench rows are
    directly comparable."""
    import numpy as np

    from chainermn_tpu.parallel.composition import derive_compositions
    from chainermn_tpu.parallel.reduction_schedule import (
        MeasuredComposedReducer,
    )
    from chainermn_tpu.tuning.measure import repeat_median

    axes = comm.grad_axes
    axes = axes if isinstance(axes, tuple) else (axes,)
    shape = tuple(int(comm.mesh.shape[a]) for a in axes)
    if candidates is None:
        candidates = [c.signature() for c in derive_compositions(axes)]
    n_elems = max(1, int(float(payload_mb) * (1 << 20)) // WIRE_ITEMSIZE)
    rng = np.random.RandomState(0)
    stacked = {"g": np.asarray(
        rng.randn(comm.size, n_elems), np.float32)}
    rows: dict[str, float] = {}
    for sig in candidates:
        red = MeasuredComposedReducer(comm, schedule=sig)
        red.reduce(stacked)  # warm the per-stage jit caches

        def sample(red=red):
            t0 = time.perf_counter()
            red.reduce(stacked)
            return (time.perf_counter() - t0) * 1000.0

        med, _ = repeat_median(sample, repeats=repeats)
        rows[canonical_signature(sig, len(shape))] = med
    model = fit_pipeline_rows(
        rows, shape, n_elems * WIRE_ITEMSIZE, source="fit:calibration")
    return model


def canonical_signature(sig: str, n_axes: int) -> str:
    """A signature re-spelled over the canonical positional tokens —
    the spelling fit rows and rank orders key on."""
    from chainermn_tpu.parallel.composition import signature_for

    return signature_for(sig, n_axes)


@dataclasses.dataclass(frozen=True)
class RankResult:
    """One schedule-search ranking: ``order`` is every candidate
    best-predicted-first (deterministic: ties break on the signature
    string), ``measured`` the prefix the caller should actually time,
    ``skipped`` the rest WITH their predicted costs still in
    ``predicted_ms`` (no silent coverage loss — the bench logs them).
    ``mode`` is ``"topk"`` or ``"exhaustive"``; ``provenance`` names
    why (``cost_model:<fit source>`` or ``forced:uncalibrated``)."""

    mode: str
    provenance: str
    order: tuple[str, ...]
    predicted_ms: dict[str, float]
    measured: tuple[str, ...]
    skipped: tuple[str, ...]


def rank_compositions(
    model: Optional[CostModel],
    candidates: Sequence[str],
    payload_bytes: int,
    *,
    k: int = 3,
    mesh_axes: Optional[Sequence[str]] = None,
    mode: str = "topk",
) -> RankResult:
    """Rank ``candidates`` (signature strings) by predicted cost and
    pick the top-``k`` to measure. DEGRADES LOUDLY: ``model=None``
    (no wire rows for this mesh shape) or ``mode="exhaustive"`` marks
    every candidate measured — ``forced:uncalibrated`` provenance in
    the None case, so a ranking is never silently built on a
    default-initialized model."""
    cands = tuple(dict.fromkeys(candidates))  # stable de-dup
    if model is None or mode == "exhaustive":
        return RankResult(
            mode="exhaustive",
            provenance=(UNCALIBRATED if model is None
                        else "exhaustive:requested"),
            order=cands,
            predicted_ms={},
            measured=cands,
            skipped=(),
        )
    preds = {
        sig: model.predict(sig, payload_bytes, mesh_axes)
        for sig in cands
    }
    order = tuple(sorted(cands, key=lambda s: (preds[s], s)))
    k = max(1, int(k))
    return RankResult(
        mode="topk",
        provenance=f"cost_model:{model.source}",
        order=order,
        predicted_ms={s: round(preds[s], 4) for s in order},
        measured=order[:k],
        skipped=order[k:],
    )


def emit_sched_search_event(
    rank: RankResult,
    measured_ms: Optional[Mapping[str, float]] = None,
    *,
    spread_pct: Optional[float] = None,
) -> Optional[float]:
    """One ``sched_search`` trace event — the search's audit record
    (``docs/observability.md``): every ranked arm's predicted price,
    the measured ms for the arms actually timed, and the resulting
    :func:`model_error_pct` beside the measurement spread so
    ``tools/trace_report.py`` can print predicted-vs-measured and flag
    a model past the gate LOUDLY. No-op without an active recorder;
    returns the error either way so callers gate on it."""
    from chainermn_tpu.observability import trace as _trace

    err = model_error_pct(rank.predicted_ms, measured_ms or {})
    rec = _trace.active()
    if rec is not None:
        fields: dict = {
            "mode": rank.mode,
            "provenance": rank.provenance,
            "predicted_ms": dict(rank.predicted_ms),
            "measured": list(rank.measured),
            "skipped": list(rank.skipped),
        }
        if measured_ms:
            fields["measured_ms"] = {
                k: round(float(v), 4) for k, v in measured_ms.items()
            }
        if spread_pct is not None:
            fields["spread_pct"] = round(float(spread_pct), 3)
        if err is not None:
            fields["err_pct"] = err
        rec.event("sched_search", **fields)
    return err


def model_error_pct(
    predicted_ms: Mapping[str, float],
    measured_ms: Mapping[str, float],
) -> Optional[float]:
    """Max relative predicted-vs-measured error (percent) over the
    signatures present in BOTH maps — the audit number every top-k
    adoption records as cache evidence and the bench publishes as
    ``cost_model_err_pct``. None when the maps share nothing."""
    errs = [
        abs(predicted_ms[s] - measured_ms[s]) / max(abs(measured_ms[s]),
                                                    1e-12)
        for s in predicted_ms if s in measured_ms
    ]
    if not errs:
        return None
    return round(max(errs) * 100.0, 3)


__all__ = [
    "CostModel",
    "RankResult",
    "UNCALIBRATED",
    "WIRE_ITEMSIZE",
    "calibrate",
    "canonical_signature",
    "emit_sched_search_event",
    "fit_pipeline_rows",
    "load_from_bench_details",
    "model_error_pct",
    "rank_compositions",
    "stage_terms",
]
