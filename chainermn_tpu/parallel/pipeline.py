"""Pipeline parallelism — GPipe-style micro-batching over a ``'stage'`` axis.

The reference had the *pattern* but not the *engine* (SURVEY.md section 2.2):
``MultiNodeChainList`` chained differentiable send/recv across ranks
(``links/multi_node_chain_list.py`` (dagger)) with no micro-batching, so one
rank computed while the others idled. This module supplies the real engine
the TPU way: all stages live in ONE jitted SPMD program, the schedule is a
``lax.scan`` over ``n_micro + n_stages - 1`` ticks (fill + steady state +
drain), and stage-to-stage activation transfer is a ``ppermute`` shift that
XLA lowers to neighbour ICI DMA.

Differentiability is free: ``scan`` + ``ppermute`` both have transposes, so
``jax.grad`` through the pipeline yields exactly the reversed-schedule
backward pass the reference hand-encoded via ``Send.backward = recv``
(``functions/point_to_point_communication.py`` (dagger)).

Design constraints (idiomatic-TPU, deliberate):
  - Homogeneous stages: every stage runs the same ``stage_fn`` with its own
    slice of the stacked parameters (leading axis = stage). Embed/head
    layers run *outside* the pipelined region — on TPU they are usually
    data/tensor-sharded, not pipelined.
  - During fill/drain, idle stages compute on zeros; their outputs are
    masked out of the result. This wastes the classic GPipe bubble
    (``(n_stages - 1) / (n_micro + n_stages - 1)``) — increase
    ``n_micro`` to amortise, as with any GPipe schedule.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

PyTree = Any


def pipeline_local(
    stage_fn: Callable,
    stage_params: PyTree,
    x: jax.Array,
    axis_name: str = "stage",
) -> jax.Array:
    """Run the GPipe schedule over local shards — call INSIDE ``shard_map``.

    Args:
      stage_fn: ``stage_fn(params, x_microbatch) -> y_microbatch`` — one
        pipeline stage; output shape/dtype must equal input shape/dtype
        (stage-to-stage activations travel a homogeneous ring buffer).
      stage_params: this stage's parameter pytree (the caller's in_spec
        sharded the stacked params over ``axis_name`` and collapsed the
        leading axis).
      x: ``[n_micro, mb, ...]`` microbatched input (replicated across
        stages; only stage 0 consumes it).

    Returns:
      ``[n_micro, mb, ...]`` — the final stage's outputs, valid on the last
      stage and replicated to all stages for convenience (psum-broadcast).
    """
    n = lax.axis_size(axis_name)
    s = lax.axis_index(axis_name)
    n_micro = x.shape[0]
    mb_shape = x.shape[1:]
    total = n_micro + n - 1

    # send stage i -> i+1 (last stage's output falls off the conveyor)
    perm = [(i, i + 1) for i in range(n - 1)]

    def tick(carry, t):
        buf, outputs = carry
        # Stage 0 eats microbatch t (clamped; masked when t >= n_micro),
        # other stages eat what arrived from the left neighbour.
        mb_idx = jnp.clip(t, 0, n_micro - 1)
        feed = lax.dynamic_index_in_dim(x, mb_idx, keepdims=False)
        inp = jnp.where(s == 0, feed, buf)
        out = stage_fn(stage_params, inp)
        # Valid iff this stage is currently working on a real microbatch:
        # stage s works on microbatch t - s.
        valid = jnp.logical_and(t - s >= 0, t - s < n_micro)
        out = jnp.where(valid, out, jnp.zeros_like(out))
        # Last stage banks its finished microbatch.
        out_idx = jnp.clip(t - (n - 1), 0, n_micro - 1)
        is_last = s == n - 1
        bank = jnp.logical_and(is_last, t - (n - 1) >= 0)
        outputs = lax.dynamic_update_index_in_dim(
            outputs,
            jnp.where(
                bank,
                out,
                lax.dynamic_index_in_dim(outputs, out_idx, keepdims=False),
            ),
            out_idx,
            0,
        )
        buf = lax.ppermute(out, axis_name, perm)
        return (buf, outputs), None

    buf0 = jnp.zeros(mb_shape, x.dtype)
    outputs0 = jnp.zeros((n_micro,) + mb_shape, x.dtype)
    (_, outputs), _ = lax.scan(tick, (buf0, outputs0), jnp.arange(total))

    # Replicate the last stage's result to every stage (mask + psum): the
    # caller sees one coherent output regardless of stage placement.
    outputs = jnp.where(s == n - 1, outputs, jnp.zeros_like(outputs))
    return lax.psum(outputs, axis_name)


def make_pipeline(
    stage_fn: Callable,
    mesh: Mesh,
    *,
    axis_name: str = "stage",
    n_microbatches: Optional[int] = None,
    remat_stages: bool = False,
):
    """Build a jitted pipelined apply over stacked stage parameters.

    Returns ``fn(stacked_params, x) -> y`` where ``stacked_params`` leaves
    have leading dim ``n_stages`` (sharded over ``axis_name``) and ``x`` is
    the full batch ``[batch, ...]``; the batch is split into
    ``n_microbatches`` equal microbatches (default: the stage count, the
    classic GPipe minimum for full utilisation... of the steady state).

    ``remat_stages=True`` wraps each stage in ``jax.checkpoint``: the
    backward recomputes each stage's INTERNAL activations instead of
    storing them per schedule tick. The per-tick stage *inputs* are still
    saved by the scan (``O(n_micro + n_stages)`` boundary tensors — that
    part is inherent to replaying the schedule), so the saving scales with
    stage depth: deep stages drop from "every intermediate per tick" to
    "one boundary tensor per tick" — activation checkpointing per
    microbatch, not a full 1F1B scheduler.
    """
    from jax import shard_map

    n_stages = mesh.shape[axis_name]
    n_micro = n_microbatches or n_stages
    if remat_stages:
        stage_fn = jax.checkpoint(stage_fn)

    param_spec = P(axis_name)
    x_spec = P()  # replicated; stage 0 reads it

    def local(stacked_params, x):
        # shard_map gave us a [1, ...] slice of each stacked leaf: collapse.
        params = jax.tree.map(lambda p: p[0], stacked_params)
        batch = x.shape[0]
        if batch % n_micro:
            raise ValueError(
                f"batch {batch} not divisible by n_microbatches {n_micro}"
            )
        mb = batch // n_micro
        xm = x.reshape((n_micro, mb) + x.shape[1:])
        ym = pipeline_local(stage_fn, params, xm, axis_name)
        return ym.reshape((batch,) + ym.shape[2:])

    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(param_spec, x_spec),
        out_specs=P(),
        check_vma=False,
    )
    return jax.jit(fn)


def stack_stage_params(params_list) -> PyTree:
    """Stack per-stage parameter pytrees (identical structure) along a new
    leading axis — the layout ``make_pipeline`` expects, shardable over the
    ``'stage'`` mesh axis."""
    return jax.tree.map(lambda *ls: jnp.stack(ls), *params_list)
