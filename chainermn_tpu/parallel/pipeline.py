"""Pipeline parallelism — GPipe-style micro-batching over a ``'stage'`` axis.

The reference had the *pattern* but not the *engine* (SURVEY.md section 2.2):
``MultiNodeChainList`` chained differentiable send/recv across ranks
(``links/multi_node_chain_list.py`` (dagger)) with no micro-batching, so one
rank computed while the others idled. This module supplies the real engine
the TPU way: all stages live in ONE jitted SPMD program, the schedule is a
``lax.scan`` over ``n_micro + n_stages - 1`` ticks (fill + steady state +
drain), and stage-to-stage activation transfer is a ``ppermute`` shift that
XLA lowers to neighbour ICI DMA.

Differentiability is free: ``scan`` + ``ppermute`` both have transposes, so
``jax.grad`` through the pipeline yields exactly the reversed-schedule
backward pass the reference hand-encoded via ``Send.backward = recv``
(``functions/point_to_point_communication.py`` (dagger)).

Design constraints (idiomatic-TPU, deliberate):
  - Homogeneous stages: every stage runs the same ``stage_fn`` with its own
    slice of the stacked parameters (leading axis = stage). Embed/head
    layers run *outside* the pipelined region — on TPU they are usually
    data/tensor-sharded, not pipelined.
  - During fill/drain, idle stages compute on zeros; their outputs are
    masked out of the result. This wastes the classic GPipe bubble
    (``(n_stages - 1) / (n_micro + n_stages - 1)``) — increase
    ``n_micro`` to amortise, as with any GPipe schedule.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

PyTree = Any


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def unscale_replicated_grads(x: jax.Array, axis_name) -> jax.Array:
    """Identity forward; cotangent divided by the axis size backward.

    :func:`pipeline_local` replicates its outputs with a raw ``psum``
    whose shard-local transpose is itself a psum — so when every stage
    redundantly computes the same loss from the replicated outputs
    INSIDE ``shard_map`` (the plan's pipe path), the cotangent arrives
    scaled by ``n_stages``. Wrapping the pipeline output in this adjoint
    restores exactness (measured: a 4-stage conveyor returns 4x grads
    unwrapped). Differentiating from OUTSIDE the shard_map needs no
    correction — the boundary transpose already accounts for the
    replication.
    """
    return x


def _unscale_fwd(x, axis_name):
    return x, None


def _unscale_bwd(axis_name, _, g):
    return (g / lax.axis_size(axis_name),)


unscale_replicated_grads.defvjp(_unscale_fwd, _unscale_bwd)


def pipe_plan_axis(axis_name: str = "pipe") -> dict:
    """Spec-provider descriptor for :class:`~chainermn_tpu.parallel.plan.
    ParallelPlan` (ISSUE 10): stage parameters stack a leading
    ``[n_stages, ...]`` dim over ``axis_name`` (the
    :func:`stack_stage_params` layout, ``P(axis_name)`` on the stack
    dim), and the axis owes the compiled step the conveyor's
    ``ppermute`` (one collective-permute per schedule tick, forward and
    transposed backward). Contract inherited from :func:`pipeline_local`:
    leaves consumed INSIDE ``stage_fn`` must be pipe-stacked; replicated
    leaves (embed/head) belong outside the pipelined region."""
    return {
        "name": axis_name,
        "stacked": True,
        "state_stacked": False,
        "collectives": ("collective-permute",),
    }


def pipeline_total_ticks(n_stages: int, n_micro: int,
                         virtual_stages: int = 1) -> int:
    """Schedule length of :func:`pipeline_local` in conveyor ticks (one
    chunk execution per stage per tick).

    ``virtual_stages == 1``: the classic GPipe ``n_micro + n - 1``, bubble
    fraction ``(n-1)/(n_micro + n - 1)``.

    ``virtual_stages == v > 1``: microbatches stream in waves of ``n``
    through the looped conveyor; each wave occupies ``v*n`` ticks per
    stage back-to-back, so for ``n | n_micro`` the total is
    ``v*n_micro + n - 1`` and the bubble fraction shrinks to
    ``(n - 1) / (v*n_micro + n - 1)`` — each tick is 1/v of a full-stage
    forward, so the fill/drain cost is amortised over v× more (smaller)
    ticks. Partial waves still occupy a full ``v*n``-tick wave slot
    (choose ``n_micro`` a multiple of ``n_stages``)."""
    if virtual_stages == 1:
        return n_micro + n_stages - 1
    waves = -(-n_micro // n_stages)
    return virtual_stages * n_stages * waves + n_stages - 1


def pipeline_local(
    stage_fn: Callable,
    stage_params: PyTree,
    x: jax.Array,
    axis_name: str = "stage",
    virtual_stages: int = 1,
) -> jax.Array:
    """Run the (interleaved) GPipe schedule over local shards — call INSIDE
    ``shard_map``.

    Args:
      stage_fn: ``stage_fn(params, x_microbatch) -> y_microbatch`` — one
        pipeline stage; output shape/dtype must equal input shape/dtype
        (stage-to-stage activations travel a homogeneous ring buffer).
      stage_params: this stage's parameter pytree. With
        ``virtual_stages == 1`` the caller's in_spec sharded the stacked
        params over ``axis_name`` and collapsed the leading axis; with
        ``v > 1`` the leaves keep a leading ``[v, ...]`` axis — this
        stage's model chunks (global stage ``j*n + s`` is chunk ``j``
        here; see :func:`stack_interleaved_stage_params`).
      x: ``[n_micro, mb, ...]`` microbatched input (replicated across
        stages; only stage 0 consumes it).
      virtual_stages: interleave ``v`` model chunks per physical stage —
        the looped conveyor: microbatch ``i`` (wave ``w = i // n``, slot
        ``r = i % n``) runs chunk ``j`` on stage ``s`` at tick
        ``t = w*v*n + j*n + r + s``. Activations hop ``s → s+1`` every
        tick, and the last stage's chunk-``j`` output loops back to stage
        0 as chunk ``j+1``'s input — which the formula shows arrives
        exactly one tick later. Each stage is busy ``v*n`` CONSECUTIVE
        ticks per wave (fill is still only ``n-1`` ticks), so the bubble
        shrinks to ``(n-1)/(v*n_micro + n - 1)``
        (:func:`pipeline_total_ticks`). The transposed backward replays
        the mirrored conveyor with the same fill — interleaving composes
        with autodiff at full efficiency.

        Why the GPipe engine and not 1F1B: an interleaved 1F1B built on
        this conveyor (forwards on even ticks, mirrored backward conveyor
        on odd ticks) idles ``(2v+2)n - 4`` chunk-ticks per stage — MORE
        than plain 1F1B's ``2vn - 2v`` at equal microbatch count, because
        the parity split wastes the warmup's odd slots and the drain's
        even slots. Closing that gap needs Megatron's warmup/steady/drain
        op reordering with per-chunk arrival buffers, which buys nothing
        over this schedule in bubble terms (both reach ``(n-1)`` fill) —
        its advantage is bounded activation memory, which
        :func:`pipeline_1f1b_local` already provides at ``v == 1``. So:
        interleave for bubble (here, GPipe memory profile, pair with
        ``remat_stages``), 1F1B for memory.

    Returns:
      ``[n_micro, mb, ...]`` — the final chunk's outputs, valid on the last
      stage and replicated to all stages for convenience (psum-broadcast).
    """
    n = lax.axis_size(axis_name)
    s = lax.axis_index(axis_name)
    v = virtual_stages
    n_micro = x.shape[0]
    mb_shape = x.shape[1:]
    total = pipeline_total_ticks(n, n_micro, v)

    if v == 1:
        # send stage i -> i+1 (last stage's output falls off the conveyor)
        perm = [(i, i + 1) for i in range(n - 1)]
    else:
        # full rotation: the last stage's output loops back as the next
        # chunk's input on stage 0
        perm = [(i, (i + 1) % n) for i in range(n)]

    def tick(carry, t):
        buf, outputs = carry
        d = t - s
        if v == 1:
            j = jnp.int32(0)
            i_raw = d
            chunk_params = stage_params
        else:
            dm = d % (v * n)
            j = dm // n  # this tick's model chunk
            i_raw = (d // (v * n)) * n + d % n
            chunk_params = jax.tree.map(
                lambda p: lax.dynamic_index_in_dim(
                    p, jnp.clip(j, 0, v - 1), keepdims=False
                ),
                stage_params,
            )
        valid = jnp.logical_and(d >= 0, i_raw < n_micro)
        mb_idx = jnp.clip(i_raw, 0, n_micro - 1)
        feed = lax.dynamic_index_in_dim(x, mb_idx, keepdims=False)
        # Stage 0 chunk 0 eats microbatch i; everything else eats the
        # conveyor: stage s>0 gets (s-1, same chunk), stage 0 gets the
        # loop-back (n-1, previous chunk).
        inp = jnp.where(jnp.logical_and(s == 0, j == 0), feed, buf)
        out = stage_fn(chunk_params, inp)
        out = jnp.where(valid, out, jnp.zeros_like(out))
        # Last stage banks its finished microbatch (final chunk only).
        bank = jnp.logical_and(
            valid, jnp.logical_and(s == n - 1, j == v - 1)
        )
        outputs = lax.dynamic_update_index_in_dim(
            outputs,
            jnp.where(
                bank,
                out,
                lax.dynamic_index_in_dim(outputs, mb_idx, keepdims=False),
            ),
            mb_idx,
            0,
        )
        buf = lax.ppermute(out, axis_name, perm)
        return (buf, outputs), None

    buf0 = jnp.zeros(mb_shape, x.dtype)
    outputs0 = jnp.zeros((n_micro,) + mb_shape, x.dtype)
    (_, outputs), _ = lax.scan(tick, (buf0, outputs0), jnp.arange(total))

    # Replicate the last stage's result to every stage (mask + psum): the
    # caller sees one coherent output regardless of stage placement.
    outputs = jnp.where(s == n - 1, outputs, jnp.zeros_like(outputs))
    return lax.psum(outputs, axis_name)


def make_pipeline(
    stage_fn: Callable,
    mesh: Mesh,
    *,
    axis_name: str = "stage",
    n_microbatches: Optional[int] = None,
    remat_stages: bool = False,
    batch_axis: Optional[str] = None,
    virtual_stages: int = 1,
):
    """Build a jitted pipelined apply over stacked stage parameters.

    Returns ``fn(stacked_params, x) -> y`` where ``stacked_params`` leaves
    have leading dim ``n_stages`` (sharded over ``axis_name``) and ``x`` is
    the full batch ``[batch, ...]``; the batch is split into
    ``n_microbatches`` equal microbatches (default: the stage count, the
    classic GPipe minimum for full utilisation... of the steady state).

    ``virtual_stages=v`` interleaves ``v`` model chunks per physical stage
    (``stacked_params`` leading dim becomes ``n_stages * v``, in the
    layout of :func:`stack_interleaved_stage_params`), shrinking the
    bubble to ``(n-1)/(v*n_micro + n - 1)`` — see :func:`pipeline_local`.

    ``remat_stages=True`` wraps each stage in ``jax.checkpoint``: the
    backward recomputes each stage's INTERNAL activations instead of
    storing them per schedule tick. The per-tick stage *inputs* are still
    saved by the scan (``O(n_micro + n_stages)`` boundary tensors — that
    part is inherent to replaying the schedule), so the saving scales with
    stage depth: deep stages drop from "every intermediate per tick" to
    "one boundary tensor per tick" — activation checkpointing per
    microbatch, not a full 1F1B scheduler.

    ``batch_axis`` composes data parallelism with the pipeline (a 2-D
    ``(batch_axis, axis_name)`` mesh): the global batch is sharded over
    ``batch_axis``, each data-slice runs its own pipeline schedule over
    the stage axis, and ``n_microbatches`` splits each shard's LOCAL
    batch. Gradient reduction over ``batch_axis`` is the caller's (e.g.
    the multi-node optimizer's) job, as with any data-parallel step.
    """
    from jax import shard_map

    n_stages = mesh.shape[axis_name]
    n_micro = n_microbatches or n_stages
    if remat_stages:
        stage_fn = jax.checkpoint(stage_fn)

    param_spec = P(axis_name)
    x_spec = P(batch_axis)  # replicated over stages; dp-sharded if asked

    def local(stacked_params, x):
        if virtual_stages == 1:
            # shard_map gave a [1, ...] slice of each stacked leaf: collapse.
            params = jax.tree.map(lambda p: p[0], stacked_params)
        else:
            # [v, ...] slice — this stage's model chunks, kept stacked.
            leaves = jax.tree.leaves(stacked_params)
            if leaves and leaves[0].shape[0] != virtual_stages:
                raise ValueError(
                    f"virtual_stages={virtual_stages} needs params stacked "
                    f"to leading dim n_stages*virtual_stages="
                    f"{n_stages * virtual_stages} (per-stage slice "
                    f"{virtual_stages}); got per-stage slice "
                    f"{leaves[0].shape[0]} — use "
                    f"stack_interleaved_stage_params"
                )
            params = stacked_params
        batch = x.shape[0]
        if batch % n_micro:
            raise ValueError(
                f"batch {batch} not divisible by n_microbatches {n_micro}"
            )
        mb = batch // n_micro
        xm = x.reshape((n_micro, mb) + x.shape[1:])
        ym = pipeline_local(stage_fn, params, xm, axis_name,
                            virtual_stages=virtual_stages)
        return ym.reshape((batch,) + ym.shape[2:])

    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(param_spec, x_spec),
        out_specs=P(batch_axis),
        check_vma=False,
    )
    return jax.jit(fn)


def stack_stage_params(params_list) -> PyTree:
    """Stack per-stage parameter pytrees (identical structure) along a new
    leading axis — the layout ``make_pipeline`` expects, shardable over the
    ``'stage'`` mesh axis."""
    return jax.tree.map(lambda *ls: jnp.stack(ls), *params_list)


def stack_interleaved_stage_params(params_list, n_stages: int,
                                   virtual_stages: int) -> PyTree:
    """Stack ``n_stages * virtual_stages`` per-global-stage pytrees (in
    execution order) into the interleaved layout ``make_pipeline(...,
    virtual_stages=v)`` expects: position ``s*v + j`` holds global stage
    ``j*n + s``, so the ``axis_name`` sharding hands physical stage ``s``
    a contiguous ``[v, ...]`` slice containing exactly its chunks."""
    n, v = n_stages, virtual_stages
    if len(params_list) != n * v:
        raise ValueError(
            f"need n_stages*virtual_stages={n * v} stage params, "
            f"got {len(params_list)}"
        )
    order = [j * n + s for s in range(n) for j in range(v)]
    return stack_stage_params([params_list[g] for g in order])


# ---------------------------------------------------------------------------
# Heterogeneous stages
# ---------------------------------------------------------------------------


def pipeline_hetero_local(
    stage_fns,
    stage_params,
    x: jax.Array,
    axis_name: str = "stage",
):
    """GPipe schedule with a DIFFERENT function per stage — call INSIDE
    ``shard_map``.

    Lifts the homogeneous engine's two contract restrictions (VERDICT r2
    weak #5: "embed/head forced outside"):

      - ``stage_fns[s]`` is stage ``s``'s own callable, dispatched with
        ``lax.switch`` on the stage index (one TPU conditional per tick —
        only the resident stage's branch executes).
      - The CONVEYOR dtype/shape (stage-to-stage activations) is decoupled
        from both the FEED (stage 0's input — e.g. int32 token ids) and
        the BANK (last stage's output — e.g. ``[mb, T, vocab]`` logits or
        a scalar loss): an embedding stage consumes the raw microbatch and
        an LM-head stage banks logits, so the WHOLE model pipelines.

    Remaining contract: middle stages must map the activation shape to
    itself (one homogeneous ring buffer — checked eagerly via
    ``eval_shape``), and each stage's params live in ``stage_params[s]``,
    a tuple of per-stage pytrees REPLICATED to every device (heterogeneous
    trees cannot stack; for big homogeneous trunks prefer
    :func:`pipeline_local`, which shards params over the stage axis).

    Args:
      stage_fns: ``n_stages`` callables, ``fns[s](params[s], a) -> b``.
        ``fns[0]`` eats a feed microbatch and emits an activation; middle
        fns map activation -> activation; ``fns[-1]`` emits the banked
        output.
      stage_params: tuple/list of ``n_stages`` parameter pytrees.
      x: ``[n_micro, mb, ...]`` microbatched feed.

    Returns:
      ``[n_micro, ...bank_shape]`` outputs (psum-replicated to all stages).
    """
    n = lax.axis_size(axis_name)
    s = lax.axis_index(axis_name)
    if len(stage_fns) != n:
        raise ValueError(f"need {n} stage_fns, got {len(stage_fns)}")
    if len(stage_params) != n:
        raise ValueError(f"need {n} stage params, got {len(stage_params)}")
    if n < 2:
        raise ValueError("hetero pipeline needs >= 2 stages")
    n_micro = x.shape[0]

    feed_struct = jax.eval_shape(lambda v: v[0], x)
    act_struct = jax.eval_shape(stage_fns[0], stage_params[0], feed_struct)
    h = act_struct
    for i in range(1, n - 1):
        h = jax.eval_shape(stage_fns[i], stage_params[i], h)
        if (h.shape, h.dtype) != (act_struct.shape, act_struct.dtype):
            raise ValueError(
                f"stage {i} breaks the conveyor: emits {h.dtype}{h.shape}, "
                f"ring carries {act_struct.dtype}{act_struct.shape} — "
                "middle stages must preserve the activation shape"
            )
    out_struct = jax.eval_shape(stage_fns[n - 1], stage_params[n - 1], h)

    def _branch(i):
        if i == 0:
            def b(feed, buf):
                act = stage_fns[0](stage_params[0], feed)
                return act, jnp.zeros(out_struct.shape, out_struct.dtype)
        elif i == n - 1:
            def b(feed, buf):
                out = stage_fns[i](stage_params[i], buf)
                return jnp.zeros(act_struct.shape, act_struct.dtype), out
        else:
            def b(feed, buf):
                act = stage_fns[i](stage_params[i], buf)
                return act, jnp.zeros(out_struct.shape, out_struct.dtype)
        return b

    branches = [_branch(i) for i in range(n)]
    perm = [(i, i + 1) for i in range(n - 1)]

    def tick(carry, t):
        buf, outputs = carry
        d = t - s
        valid = jnp.logical_and(d >= 0, d < n_micro)
        mb_idx = jnp.clip(d, 0, n_micro - 1)
        feed = lax.dynamic_index_in_dim(x, mb_idx, keepdims=False)
        act, out = lax.switch(s, branches, feed, buf)
        act = jnp.where(valid, act, jnp.zeros_like(act))
        bank = jnp.logical_and(valid, s == n - 1)
        outputs = lax.dynamic_update_index_in_dim(
            outputs,
            jnp.where(
                bank,
                out,
                lax.dynamic_index_in_dim(outputs, mb_idx, keepdims=False),
            ),
            mb_idx,
            0,
        )
        buf = lax.ppermute(act, axis_name, perm)
        return (buf, outputs), None

    buf0 = jnp.zeros(act_struct.shape, act_struct.dtype)
    outputs0 = jnp.zeros((n_micro,) + out_struct.shape, out_struct.dtype)
    (_, outputs), _ = lax.scan(
        tick, (buf0, outputs0), jnp.arange(n_micro + n - 1)
    )
    outputs = jnp.where(s == n - 1, outputs, jnp.zeros_like(outputs))
    return lax.psum(outputs, axis_name)


def make_pipeline_hetero(
    stage_fns,
    mesh: Mesh,
    *,
    axis_name: str = "stage",
    n_microbatches: Optional[int] = None,
    remat_stages: bool = False,
    batch_axis: Optional[str] = None,
):
    """Build a jitted pipelined apply over PER-STAGE functions and params.

    Returns ``fn(stage_params, x) -> y`` where ``stage_params`` is a
    tuple of ``n_stages`` pytrees (one per stage, any structures) and
    ``x`` is the full batch. Unlike :func:`make_pipeline`, stage 0 may
    change the activation shape/dtype (embedding) and the last stage may
    emit a different shape (head/logits) — the whole model pipelines.

    Params are replicated (not stage-sharded): the price of heterogeneous
    trees. ``remat_stages`` checkpoints each stage fn. ``batch_axis``
    composes data parallelism exactly as in :func:`make_pipeline`.
    """
    from jax import shard_map

    n_stages = mesh.shape[axis_name]
    n_micro = n_microbatches or n_stages
    fns = [jax.checkpoint(f) if remat_stages else f for f in stage_fns]

    def local(stage_params, x):
        batch = x.shape[0]
        if batch % n_micro:
            raise ValueError(
                f"batch {batch} not divisible by n_microbatches {n_micro}"
            )
        mb = batch // n_micro
        xm = x.reshape((n_micro, mb) + x.shape[1:])
        ym = pipeline_hetero_local(fns, stage_params, xm, axis_name)
        if ym.ndim < 2 or ym.shape[1] != mb:
            raise ValueError(
                f"last stage must emit [microbatch={mb}, ...] outputs for "
                f"batch reassembly; got {ym.shape[1:]} — reduce losses "
                "per-example ([mb]), not to a scalar"
            )
        return ym.reshape((batch,) + ym.shape[2:])

    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(P(), P(batch_axis)),
        out_specs=P(batch_axis),
        check_vma=False,
    )
    return jax.jit(fn)


# ---------------------------------------------------------------------------
# 1F1B schedule
# ---------------------------------------------------------------------------


def pipeline_1f1b_local(
    stage_fn: Callable,
    loss_grad_fn: Callable,
    stage_params: PyTree,
    x: jax.Array,
    targets: jax.Array,
    axis_name: str = "stage",
    *,
    head_params: PyTree = None,
    collect_input_grads: bool = False,
):
    """One-forward-one-backward pipeline schedule — call INSIDE ``shard_map``.

    Where :func:`pipeline_local` + ``jax.grad`` replays the whole forward
    schedule before the transposed backward (so every microbatch's boundary
    activation is live at once — GPipe's memory profile), 1F1B interleaves:
    after warmup each stage alternates one microbatch's forward with an
    earlier microbatch's backward, so at most ``n_stages`` microbatch
    inputs are ever saved per stage (a static ring buffer here), for any
    number of microbatches. The backward recomputes the stage forward from
    the saved INPUT (per-microbatch rematerialisation — the standard
    trade in every 1F1B implementation).

    Schedule (stage ``s`` of ``n``, microbatch ``i``): forward at tick
    ``s + 2i``, backward at tick ``2(n-1) - s + 2i + 1`` — disjoint
    parities, so each tick a stage executes exactly ONE op — forward,
    backward, or (during fill/drain) nothing — selected by a true
    per-stage ``lax.switch`` (not a masked all-branches select).
    Forward activations hop stage ``s → s+1`` and backward cotangents hop
    ``s → s-1``, each arriving exactly at its consumption tick.

    Args:
      stage_fn: ``stage_fn(params, x_mb) -> y_mb``, output shape == input
        shape (homogeneous stages, as in :func:`pipeline_local`).
      loss_grad_fn: without ``head_params``:
        ``loss_grad_fn(y_mb, target_mb) -> (loss, dy_mb)`` — per-microbatch
        loss and its gradient wrt the final stage output (typically
        ``jax.value_and_grad`` of the caller's loss). With ``head_params``
        (a trainable loss head living after the pipelined region):
        ``loss_grad_fn(head_params, y_mb, target_mb) -> (loss, (dhead,
        dy_mb))``. Runs ONLY on the LAST stage, where 1F1B starts each
        microbatch's backward.
      stage_params: this stage's parameter pytree.
      x: ``[n_micro, mb, ...]`` microbatched input (stage 0 consumes it).
      targets: ``[n_micro, ...]`` per-microbatch loss targets (last stage
        consumes them).
      head_params: optional trainable parameters of the loss head; their
        gradients are accumulated alongside the stage gradients.
      collect_input_grads: also return the loss gradient wrt ``x``
        (``[n_micro, mb, ...]``, replicated) — backprop it into an
        embed/encoder living before the pipelined region. Costs one
        ``O(n_micro)`` buffer, the same order as ``x`` itself.

    Returns:
      ``(loss, grads[, head_grads][, x_grads])``: mean per-microbatch loss
      (replicated), this stage's parameter gradients (mean over
      microbatches), and — when requested — the head-parameter and input
      gradients.
    """
    n = lax.axis_size(axis_name)
    s = lax.axis_index(axis_name)
    n_micro = x.shape[0]
    mb_shape = x.shape[1:]
    total = 2 * (n + n_micro - 1)

    fwd_perm = [(i, i + 1) for i in range(n - 1)]
    bwd_perm = [(i + 1, i) for i in range(n - 1)]
    zeros_mb = jnp.zeros(mb_shape, x.dtype)
    zeros_grads = jax.tree.map(jnp.zeros_like, stage_params)
    zeros_head = jax.tree.map(jnp.zeros_like, head_params)

    def tick(carry, t):
        (fwd_msg, cot_msg, saved, y_last, grads, hgrads, dx_buf,
         loss_sum) = carry

        tf = t - s
        parity_f = (tf % 2) == 0  # F ticks for this stage; B on the other
        i_f_raw = tf // 2
        f_valid = jnp.logical_and(
            parity_f, jnp.logical_and(i_f_raw >= 0, i_f_raw < n_micro)
        )
        i_f = jnp.clip(i_f_raw, 0, n_micro - 1)
        tb = t - (2 * (n - 1) - s + 1)
        i_b_raw = tb // 2
        b_valid = jnp.logical_and(
            jnp.logical_not(parity_f),
            jnp.logical_and(i_b_raw >= 0, i_b_raw < n_micro),
        )
        i_b = jnp.clip(i_b_raw, 0, n_micro - 1)

        feed = lax.dynamic_index_in_dim(x, i_f, keepdims=False)
        inp = jnp.where(s == 0, feed, fwd_msg)

        zero_scalar = jnp.zeros((), jnp.float32)

        def idle_branch(_):
            return zeros_mb, zeros_mb, zeros_grads, zeros_head, zero_scalar

        def f_branch(_):
            out = stage_fn(stage_params, inp)
            return out, zeros_mb, zeros_grads, zeros_head, zero_scalar

        def b_branch(_):
            x_saved = lax.dynamic_index_in_dim(saved, i_b % n, keepdims=False)

            # The loss head runs ONLY on the last stage (nested true
            # conditional): other stages take the arriving cotangent. This
            # also keeps loss_grad_fn away from the zero-initialised
            # y_last — a loss with a pole at 0 (e.g. log-likelihood) would
            # otherwise produce NaNs that survive masked accumulation
            # (NaN * 0 == NaN).
            def last_stage(_):
                tgt = lax.dynamic_index_in_dim(targets, i_b, keepdims=False)
                if head_params is None:
                    loss, dy = loss_grad_fn(y_last, tgt)
                    dhead = zeros_head
                else:
                    loss, (dhead, dy) = loss_grad_fn(head_params, y_last, tgt)
                return loss.astype(jnp.float32), dhead, dy

            def mid_stage(_):
                return zero_scalar, zeros_head, cot_msg

            loss, dhead, dy = lax.cond(s == n - 1, last_stage, mid_stage, None)
            _, vjp_fn = jax.vjp(stage_fn, stage_params, x_saved)
            dparams, dx = vjp_fn(dy)
            return zeros_mb, dx, dparams, dhead, loss

        # Exactly one op per stage per tick; idle stages (fill/drain, and
        # invalid parities) do NOTHING — no garbage evaluation to mask.
        branch = jnp.where(f_valid, 1, jnp.where(b_valid, 2, 0))
        out, dx, dparams, dhead, loss_d = lax.switch(
            branch, (idle_branch, f_branch, b_branch), None
        )

        # Bank state touched only by valid ops.
        saved = lax.dynamic_update_index_in_dim(
            saved,
            jnp.where(
                f_valid,
                inp,
                lax.dynamic_index_in_dim(saved, i_f % n, keepdims=False),
            ),
            i_f % n,
            0,
        )
        y_last = jnp.where(jnp.logical_and(f_valid, s == n - 1), out, y_last)
        # Branch outputs are zeros except for the op that actually ran.
        grads = jax.tree.map(jnp.add, grads, dparams)
        hgrads = jax.tree.map(jnp.add, hgrads, dhead)
        if dx_buf is not None:
            write = jnp.logical_and(b_valid, s == 0)
            dx_buf = lax.dynamic_update_index_in_dim(
                dx_buf,
                jnp.where(
                    write,
                    dx,
                    lax.dynamic_index_in_dim(dx_buf, i_b, keepdims=False),
                ),
                i_b,
                0,
            )
        loss_sum = loss_sum + loss_d

        fwd_msg = lax.ppermute(
            jnp.where(f_valid, out, zeros_mb), axis_name, fwd_perm
        )
        cot_msg = lax.ppermute(
            jnp.where(b_valid, dx, zeros_mb), axis_name, bwd_perm
        )
        return (fwd_msg, cot_msg, saved, y_last, grads, hgrads, dx_buf,
                loss_sum), None

    carry0 = (
        zeros_mb,  # fwd_msg
        zeros_mb,  # cot_msg
        jnp.zeros((n,) + mb_shape, x.dtype),  # saved input ring
        zeros_mb,  # y_last
        zeros_grads,
        zeros_head,
        jnp.zeros((n_micro,) + mb_shape, x.dtype)
        if collect_input_grads
        else None,
        jnp.zeros((), jnp.float32),
    )
    (_, _, _, _, grads, hgrads, dx_buf, loss_sum), _ = lax.scan(
        tick, carry0, jnp.arange(total)
    )

    grads = jax.tree.map(lambda g: g / n_micro, grads)
    loss = lax.psum(jnp.where(s == n - 1, loss_sum, 0.0), axis_name) / n_micro
    out = (loss, grads)
    if head_params is not None:
        # Only the last stage accumulated head grads; broadcast via psum.
        out += (
            jax.tree.map(
                lambda g: lax.psum(g, axis_name) / n_micro, hgrads
            ),
        )
    if collect_input_grads:
        # Only stage 0 wrote its slots; psum broadcasts to every stage.
        # Same mean-over-microbatches normalisation as the param grads:
        # x_grads is d(returned loss)/dx.
        out += (lax.psum(dx_buf, axis_name) / n_micro,)
    return out


def make_pipeline_1f1b(
    stage_fn: Callable,
    loss_grad_fn: Callable,
    mesh: Mesh,
    *,
    axis_name: str = "stage",
    n_microbatches: Optional[int] = None,
    batch_axis: Optional[str] = None,
):
    """Build the jitted 1F1B train-step core:
    ``fn(stacked_params, x, targets[, head_params]) ->
    (loss, stacked_grads[, head_grads][, x_grads])``.

    ``stacked_params`` leaves have leading dim ``n_stages`` (sharded over
    ``axis_name``); ``x`` is the full batch ``[batch, ...]`` and
    ``targets`` the per-example targets ``[batch, ...]``, both split into
    ``n_microbatches``. Unlike :func:`make_pipeline` (a differentiable
    *apply*), this IS the fwd+bwd engine — feed the returned grads to any
    optimizer; raise ``n_microbatches`` freely, saved activations stay
    ``O(n_stages)``. Passing ``head_params`` to the returned ``fn``
    switches ``loss_grad_fn`` to the trainable-head contract (see
    :func:`pipeline_1f1b_local`) and appends the head gradients to the
    result; ``collect_input_grads=True`` additionally appends the
    gradient wrt ``x`` (shape ``[batch, ...]``) for an embed before the
    pipeline.

    ``batch_axis`` composes data parallelism (2-D ``(batch_axis,
    axis_name)`` mesh): the global batch/targets shard over
    ``batch_axis``, each data-slice runs its own 1F1B schedule, and the
    returned loss / stage grads / head grads are ALREADY averaged over
    ``batch_axis`` (x_grads stay per-shard, matching the sharded x).
    """
    from jax import shard_map

    n_stages = mesh.shape[axis_name]
    n_micro = n_microbatches or n_stages

    def build(with_head: bool, collect_input_grads: bool):
        def local(stacked_params, x, targets, head_params):
            params = jax.tree.map(lambda p: p[0], stacked_params)
            batch = x.shape[0]
            if batch % n_micro:
                raise ValueError(
                    f"batch {batch} not divisible by n_microbatches {n_micro}"
                )
            mb = batch // n_micro
            xm = x.reshape((n_micro, mb) + x.shape[1:])
            tm = targets.reshape((n_micro, mb) + targets.shape[1:])
            res = pipeline_1f1b_local(
                stage_fn, loss_grad_fn, params, xm, tm, axis_name,
                head_params=head_params if with_head else None,
                collect_input_grads=collect_input_grads,
            )
            loss, grads = res[0], res[1]
            rest = list(res[2:])
            if batch_axis is not None:
                # Data-parallel reduction INSIDE the program — the same
                # place the train step pmeans its grads.
                loss = lax.pmean(loss, batch_axis)
                grads = lax.pmean(grads, batch_axis)
                if with_head:
                    rest[0] = lax.pmean(rest[0], batch_axis)
            grads = jax.tree.map(lambda g: g[None], grads)
            if collect_input_grads:
                xg = rest.pop()
                if batch_axis is not None:
                    # x is sharded over batch_axis and each element lives
                    # in exactly one shard, so d(pmean-ed loss)/dx is the
                    # per-shard gradient scaled by 1/n_data — keeping the
                    # 'gradient of the RETURNED loss' contract exact.
                    xg = xg / lax.axis_size(batch_axis)
                rest.append(xg.reshape((batch,) + xg.shape[2:]))
            return (loss, grads) + tuple(rest)

        extra_specs = ()
        if with_head:
            extra_specs += (P(),)
        if collect_input_grads:
            extra_specs += (P(batch_axis),)
        return shard_map(
            local,
            mesh=mesh,
            in_specs=(P(axis_name), P(batch_axis), P(batch_axis), P()),
            out_specs=(P(), P(axis_name)) + extra_specs,
            check_vma=False,
        )

    import functools

    @functools.lru_cache(maxsize=4)
    def _jitted(with_head: bool, collect_input_grads: bool):
        return jax.jit(build(with_head, collect_input_grads))

    def fn(stacked_params, x, targets, head_params=None, *,
           collect_input_grads=False):
        return _jitted(head_params is not None, collect_input_grads)(
            stacked_params, x, targets, head_params
        )

    return fn
