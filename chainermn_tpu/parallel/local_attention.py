"""Sequence-parallel sliding-window (local) attention — O(1) communication.

NEW capability relative to the reference (SURVEY.md section 5: no sequence
parallelism existed in the 2017-era codebase). The distributed complement
of ``flash_attention(window=W)``: when the attention window fits within
one sequence shard (``W - 1 <= T_local``), a query can only reach keys in
its OWN shard and the TAIL of the PREVIOUS shard. So instead of rotating
K/V around the full ring (n - 1 ``ppermute`` hops, O(n) traffic —
:mod:`chainermn_tpu.parallel.ring_attention`), each shard exchanges ONE
neighbour tail of ``W - 1`` positions: communication is O(window), an
n-fold saving that grows with the mesh.

Mechanism (inside ``shard_map`` over the sequence axis):

1. every shard sends the last ``W - 1`` K/V positions to its successor
   (single ``ppermute`` shift);
2. the receiver prepends them and runs the banded flash kernel with
   ``q_offset = W - 1`` — local query row ``i`` sits at extended-key
   position ``i + W - 1``, so the standard causal-window band lands
   exactly on the right keys;
3. shard 0's received tail is the wrap-around from the LAST shard and
   must see nothing: a segment-id sentinel masks it (the kernel's packed
   -segment mask, reused);
4. backward: the flash backward yields gradients for the extended K/V;
   the tail slice ``ppermute``s BACK to its owner (the transpose of the
   forward shift — the same Send/Recv duality the reference hand-built in
   ``functions/point_to_point_communication.py`` (dagger)) and adds into
   the owner's last ``W - 1`` positions. The wrap-around edge carries
   exact zeros (masked in forward ⇒ zero gradient), so no special case.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from chainermn_tpu.ops.flash_attention import (
    _use_interpret,
    flash_block_bwd,
    flash_block_fwd,
)
from chainermn_tpu.parallel.collectives import shift

# Wrap-around mask sentinel: INT32_MIN cannot legitimately appear as a
# user segment id (ids are labels, and -1-style padding conventions stay
# far from the extreme), so shard 0's received tail can never match a
# query id.
_WRAP_SENTINEL = jnp.iinfo(jnp.int32).min


def _ext_and_segs(k, v, seg_q_ids, axis_name, tail):
    """Build the extended K/V (previous shard's tail prepended) and the
    segment ids that (a) mask shard 0's wrap-around tail and (b) carry
    any user packed-segment ids across the boundary (all-zero ids when
    the caller has no packed segments). ONE bundled ``ppermute`` moves
    k/v/ids together (a single ICI exchange)."""
    L = k.shape[1]
    k_tail, v_tail, tail_ids = shift(
        (k[:, L - tail:], v[:, L - tail:], seg_q_ids[:, L - tail:]),
        axis_name, 1,
    )
    k_ext = jnp.concatenate([k_tail, k], axis=1)
    v_ext = jnp.concatenate([v_tail, v], axis=1)
    first = lax.axis_index(axis_name) == 0
    tail_ids = jnp.where(
        first, jnp.full_like(tail_ids, _WRAP_SENTINEL), tail_ids
    )
    seg_k_ids = jnp.concatenate([tail_ids, seg_q_ids], axis=1)
    return k_ext, v_ext, seg_q_ids, seg_k_ids


def _local_fwd_impl(q, k, v, seg, axis_name, window, scale, block_q,
                    block_k, interpret):
    tail = window - 1
    k_ext, v_ext, seg_q_ids, seg_k_ids = _ext_and_segs(
        k, v, seg, axis_name, tail
    )
    out, lse = flash_block_fwd(
        q, k_ext, v_ext, causal=True, scale=scale, window=window,
        q_offset=tail, seg_q=seg_q_ids, seg_kv=seg_k_ids,
        block_q=block_q, block_k=block_k, interpret=interpret,
    )
    return out.astype(q.dtype), lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9))
def _local_window(q, k, v, seg, axis_name, window, scale, block_q, block_k,
                  interpret):
    out, _ = _local_fwd_impl(q, k, v, seg, axis_name, window, scale,
                             block_q, block_k, interpret)
    return out


def _local_window_fwd(q, k, v, seg, axis_name, window, scale, block_q,
                      block_k, interpret):
    out, lse = _local_fwd_impl(q, k, v, seg, axis_name, window, scale,
                               block_q, block_k, interpret)
    return out, (q, k, v, seg, out, lse)


def _local_window_bwd(axis_name, window, scale, block_q, block_k, interpret,
                      res, g):
    q, k, v, seg, out, lse = res
    tail = window - 1
    L = q.shape[1]
    # Rebuild the extended K/V (recompute beats storing an overlapping
    # copy — same remat philosophy as the flash backward itself).
    k_ext, v_ext, seg_q_ids, seg_k_ids = _ext_and_segs(
        k, v, seg, axis_name, tail
    )
    do = g.astype(jnp.float32)
    delta = jnp.sum(
        do * out.astype(jnp.float32), axis=-1
    ).transpose(0, 2, 1)  # [B, H, L]
    dq, dk_ext, dv_ext = flash_block_bwd(
        q, k_ext, v_ext, g, lse, delta, causal=True, scale=scale,
        window=window, q_offset=tail, seg_q=seg_q_ids, seg_kv=seg_k_ids,
        block_q=block_q, block_k=block_k, interpret=interpret,
    )
    # Own-shard part + the tail gradient returned to its owner (adds into
    # the owner's LAST `tail` positions). Shard 0's tail grads are exact
    # zeros (its tail was segment-masked), so the wrap-around is inert.
    dk = dk_ext[:, tail:]
    dv = dv_ext[:, tail:]
    dk_back, dv_back = shift(
        (dk_ext[:, :tail], dv_ext[:, :tail]), axis_name, -1
    )
    dk = dk.at[:, L - tail:].add(dk_back)
    dv = dv.at[:, L - tail:].add(dv_back)
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype),
            None)


_local_window.defvjp(_local_window_fwd, _local_window_bwd)


def sliding_window_attention_local(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    *,
    window: int,
    scale: Optional[float] = None,
    segment_ids: Optional[jax.Array] = None,
    block_q: int = 512,
    block_k: int = 1024,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Causal sliding-window attention over sequence shards — call INSIDE
    ``shard_map``. See the module docstring for the design.

    Args:
      q/k/v: local shards ``[B, T_local, H|Hkv, D]`` of a sequence
        sharded CONTIGUOUSLY over ``axis_name`` (GQA/MQA supported —
        fewer kv heads than q heads).
      window: band width ``W``; global query ``i`` sees keys
        ``(i - W, i]``. Requires ``W - 1 <= T_local`` (the band spans at
        most one shard boundary; for wider windows use
        :func:`~chainermn_tpu.parallel.ring_attention.ring_attention_local`,
        which covers any reach).
      segment_ids: optional local ``[B, T_local]`` packed-segment slice;
        ids travel with the tail so cross-boundary masking stays exact.
        Any int32 value except ``INT32_MIN`` is a valid id (that value is
        the internal wrap-around mask sentinel).

    Returns:
      Local output shard ``[B, T_local, H, D]`` (dtype of ``q``).
    """
    if window < 1:
        raise ValueError(f"window must be >= 1, got {window}")
    L = q.shape[1]
    if window - 1 > L:
        raise ValueError(
            f"window {window} reaches {window - 1} positions back but the "
            f"local shard holds only {L}; use ring attention for windows "
            "wider than a shard"
        )
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if interpret is None:
        interpret = _use_interpret()
    if window == 1:
        # Degenerate: each query sees only itself — no communication.
        from chainermn_tpu.ops.flash_attention import flash_attention

        return flash_attention(
            q, k, v, causal=True, window=1, scale=scale,
            segment_ids=segment_ids, block_q=block_q, block_k=block_k,
            interpret=interpret,
        )
    seg = (segment_ids.astype(jnp.int32) if segment_ids is not None
           else jnp.zeros((q.shape[0], L), jnp.int32))
    return _local_window(q, k, v, seg, axis_name, window, float(scale),
                         block_q, block_k, interpret)


__all__ = ["sliding_window_attention_local"]
