"""Sequence-parallel sliding-window (local) attention — O(window) comm.

NEW capability relative to the reference (SURVEY.md section 5: no sequence
parallelism existed in the 2017-era codebase). The distributed complement
of ``flash_attention(window=W)``: a query can only reach keys within the
last ``W`` positions, which live on its OWN shard plus the TAILS of its
``m = ceil((W-1)/T_local)`` nearest predecessors. So instead of rotating
K/V around the full ring (n - 1 ``ppermute`` hops, O(T) traffic —
:mod:`chainermn_tpu.parallel.ring_attention`), each shard exchanges
exactly the ``W - 1`` needed positions (one bundled ``ppermute`` per
neighbour distance): communication is O(window) regardless of sequence
length or mesh size — a T/W-fold saving.

Mechanism (inside ``shard_map`` over the sequence axis):

1. predecessor ``s-d`` (``d = 1..m``) sends its last
   ``c_d = min(T_local, W-1-(d-1)·T_local)`` K/V positions ``d`` steps
   forward; the receiver prepends them furthest-first;
2. the banded flash kernel runs with ``q_offset = prefix_len`` — local
   query row ``i`` sits at extended-key position ``i + prefix_len``, so
   the standard causal-window band lands exactly on the right keys;
3. wrap-around slices (shard ``s`` receiving from ``s - d < 0``) must
   see nothing: a segment-id sentinel masks them (the kernel's packed
   -segment mask, reused);
4. backward: the flash backward yields gradients for the extended K/V;
   each prefix slice ``ppermute``s BACK to its owner (the transpose of
   the forward shift — the same Send/Recv duality the reference
   hand-built in ``functions/point_to_point_communication.py`` (dagger))
   and adds into the owner's last ``c_d`` positions. Wrap-around edges
   carry exact zeros (masked in forward ⇒ zero gradient), no special
   case.

Known cost accepted (round-4 ADVICE, low): the wrap sentinel rides the
segment-id path even when the caller has no packed segments, so every
block pays a small ([1, block] int32) segment DMA + compare. The
sentinel-free alternative — masking wrapped positions by GLOBAL
position — needs a traced per-shard scalar (``axis_index``-derived)
threaded into all three flash kernels via SMEM; measured against the
K/V block DMAs (hundreds of KB vs ~4 KB) the saving is marginal, and
kernel-signature changes are not made without same-session Mosaic
compile-checks on a real chip (CLAUDE.md kernel convention).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from chainermn_tpu.ops.flash_attention import (
    _use_interpret,
    flash_block_bwd,
    flash_block_fwd,
)
from chainermn_tpu.parallel.collectives import shift

# Wrap-around mask sentinel: INT32_MIN cannot legitimately appear as a
# user segment id (ids are labels, and -1-style padding conventions stay
# far from the extreme), so shard 0's received tail can never match a
# query id.
_WRAP_SENTINEL = jnp.iinfo(jnp.int32).min


def _tail_slices(tail: int, L: int, n: int):
    """Static geometry of the multi-neighbour prefix: predecessor ``s-d``
    (``d = 1..m``) contributes its LAST ``c_d = min(L, tail - (d-1)L)``
    positions. ``m`` is capped at ``n - 1`` — further reach is before the
    sequence start (or a full wrap) and simply doesn't exist. Returns
    ``[(d, c_d), ...]`` ordered FURTHEST-first (prefix concat order)."""
    m = min(-(-tail // L), n - 1)
    # Every c_d >= 1 by construction: d <= ceil(tail/L) ⇒ tail-(d-1)L >= 1.
    return [(d, min(L, tail - (d - 1) * L)) for d in range(m, 0, -1)]


def _ext_and_segs(k, v, seg_q_ids, axis_name, tail):
    """Build the extended K/V (predecessors' tails prepended, furthest
    first) and the segment ids that (a) mask wrap-around slices — shard
    ``s`` receives garbage from ``s - d`` whenever ``s < d`` — and (b)
    carry any user packed-segment ids across the boundaries (all-zero
    ids when the caller has no packed segments). One bundled ``ppermute``
    per neighbour distance moves k/v/ids together."""
    L = k.shape[1]
    n = lax.axis_size(axis_name)
    me = lax.axis_index(axis_name)
    k_parts, v_parts, id_parts = [], [], []
    for d, c in _tail_slices(tail, L, n):
        k_t, v_t, ids_t = shift(
            (k[:, L - c:], v[:, L - c:], seg_q_ids[:, L - c:]),
            axis_name, d,
        )
        ids_t = jnp.where(
            me >= d, ids_t, jnp.full_like(ids_t, _WRAP_SENTINEL)
        )
        k_parts.append(k_t)
        v_parts.append(v_t)
        id_parts.append(ids_t)
    k_ext = jnp.concatenate(k_parts + [k], axis=1)
    v_ext = jnp.concatenate(v_parts + [v], axis=1)
    seg_k_ids = jnp.concatenate(id_parts + [seg_q_ids], axis=1)
    return k_ext, v_ext, seg_q_ids, seg_k_ids


def _pad_ext_to_block(k_ext, v_ext, seg_k_ids, block_k):
    """Round the extended K axis up to a multiple of the effective K
    block. The extended length ``T_local + prefix`` is odd whenever the
    window is even (the common case) — without padding no power-of-two
    block divides it, ``_pick_block`` collapses to one whole-T block and
    the banded grid degenerates to O(T + W) DMA per query block (and a
    potentially VMEM-busting single K/V block). Back-padding is inert:
    pad positions exceed every query's extended position, so the causal
    mask kills them; the wrap sentinel in the segment ids is
    belt-and-braces."""
    T = k_ext.shape[1]
    b = min(block_k, T)
    pad = -T % b
    if pad:
        widths = [(0, 0)] * k_ext.ndim
        widths[1] = (0, pad)
        k_ext = jnp.pad(k_ext, widths)
        v_ext = jnp.pad(v_ext, widths)
        seg_k_ids = jnp.pad(seg_k_ids, ((0, 0), (0, pad)),
                            constant_values=_WRAP_SENTINEL)
    return k_ext, v_ext, seg_k_ids


def _local_fwd_impl(q, k, v, seg, axis_name, window, scale, block_q,
                    block_k, interpret):
    tail = window - 1
    k_ext, v_ext, seg_q_ids, seg_k_ids = _ext_and_segs(
        k, v, seg, axis_name, tail
    )
    # The realized prefix may be SHORTER than tail when the window
    # reaches past the sequence start (slices are capped at n-1
    # predecessors): q_offset is the true prefix length. Computed BEFORE
    # tile padding (the pad goes on the back; the prefix is the front).
    prefix = k_ext.shape[1] - k.shape[1]
    k_ext, v_ext, seg_k_ids = _pad_ext_to_block(
        k_ext, v_ext, seg_k_ids, block_k
    )
    out, lse = flash_block_fwd(
        q, k_ext, v_ext, causal=True, scale=scale, window=window,
        q_offset=prefix, seg_q=seg_q_ids, seg_kv=seg_k_ids,
        block_q=block_q, block_k=block_k, interpret=interpret,
    )
    return out.astype(q.dtype), lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9))
def _local_window(q, k, v, seg, axis_name, window, scale, block_q, block_k,
                  interpret):
    out, _ = _local_fwd_impl(q, k, v, seg, axis_name, window, scale,
                             block_q, block_k, interpret)
    return out


def _local_window_fwd(q, k, v, seg, axis_name, window, scale, block_q,
                      block_k, interpret):
    out, lse = _local_fwd_impl(q, k, v, seg, axis_name, window, scale,
                               block_q, block_k, interpret)
    return out, (q, k, v, seg, out, lse)


def _local_window_bwd(axis_name, window, scale, block_q, block_k, interpret,
                      res, g):
    q, k, v, seg, out, lse = res
    tail = window - 1
    L = q.shape[1]
    n = lax.axis_size(axis_name)
    # Rebuild the extended K/V (recompute beats storing an overlapping
    # copy — same remat philosophy as the flash backward itself).
    k_ext, v_ext, seg_q_ids, seg_k_ids = _ext_and_segs(
        k, v, seg, axis_name, tail
    )
    prefix = k_ext.shape[1] - L
    k_ext, v_ext, seg_k_ids = _pad_ext_to_block(
        k_ext, v_ext, seg_k_ids, block_k
    )
    do = g.astype(jnp.float32)
    delta = jnp.sum(
        do * out.astype(jnp.float32), axis=-1
    ).transpose(0, 2, 1)  # [B, H, L]
    dq, dk_ext, dv_ext = flash_block_bwd(
        q, k_ext, v_ext, g, lse, delta, causal=True, scale=scale,
        window=window, q_offset=prefix, seg_q=seg_q_ids, seg_kv=seg_k_ids,
        block_q=block_q, block_k=block_k, interpret=interpret,
    )
    # Own-shard part + each prefix slice's gradient returned to its owner
    # (the transpose of the forward shift-by-d), added into the owner's
    # last c_d positions. Wrapped slices carry exact zeros (they were
    # segment-masked in the forward), so no special case. Tile padding
    # (fully masked, zero grad) is simply dropped.
    dk = dk_ext[:, prefix:prefix + L]
    dv = dv_ext[:, prefix:prefix + L]
    off = 0
    for d, c in _tail_slices(tail, L, n):
        dk_b, dv_b = shift(
            (dk_ext[:, off:off + c], dv_ext[:, off:off + c]),
            axis_name, -d,
        )
        dk = dk.at[:, L - c:].add(dk_b)
        dv = dv.at[:, L - c:].add(dv_b)
        off += c
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype),
            None)


_local_window.defvjp(_local_window_fwd, _local_window_bwd)


def sliding_window_attention_local(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    *,
    window: int,
    scale: Optional[float] = None,
    segment_ids: Optional[jax.Array] = None,
    block_q: int = 512,
    block_k: int = 1024,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Causal sliding-window attention over sequence shards — call INSIDE
    ``shard_map``. See the module docstring for the design.

    Args:
      q/k/v: local shards ``[B, T_local, H|Hkv, D]`` of a sequence
        sharded CONTIGUOUSLY over ``axis_name`` (GQA/MQA supported —
        fewer kv heads than q heads).
      window: band width ``W``; global query ``i`` sees keys
        ``(i - W, i]``. Any width: the prefix gathers from
        ``ceil((W-1)/T_local)`` predecessors (capped at the mesh — a
        window covering the whole sequence degenerates to full causal
        attention, where the plain ring is the better choice).
      segment_ids: optional local ``[B, T_local]`` packed-segment slice;
        ids travel with the tail so cross-boundary masking stays exact.
        Any int32 value except ``INT32_MIN`` is a valid id (that value is
        the internal wrap-around mask sentinel).

    Returns:
      Local output shard ``[B, T_local, H, D]`` (dtype of ``q``).
    """
    if window < 1:
        raise ValueError(f"window must be >= 1, got {window}")
    L = q.shape[1]
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if interpret is None:
        interpret = _use_interpret()
    if window == 1:
        # Degenerate: each query sees only itself — no communication.
        from chainermn_tpu.ops.flash_attention import flash_attention

        return flash_attention(
            q, k, v, causal=True, window=1, scale=scale,
            segment_ids=segment_ids, block_q=block_q, block_k=block_k,
            interpret=interpret,
        )
    seg = (segment_ids.astype(jnp.int32) if segment_ids is not None
           else jnp.zeros((q.shape[0], L), jnp.int32))
    return _local_window(q, k, v, seg, axis_name, window, float(scale),
                         block_q, block_k, interpret)


__all__ = ["sliding_window_attention_local"]
