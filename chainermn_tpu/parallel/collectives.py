"""In-program (jit-traceable) named-axis collectives.

TPU-native replacement for the hot paths of the reference's communicator
implementations (``pure_nccl_communicator.py`` (dagger),
``mpi_communicator_base.py`` (dagger) — SURVEY.md section 2.1): every function
here is meant to be called *inside* ``jax.jit`` within a ``shard_map`` (or
``pmap``-style) named-axis context, and lowers to a single XLA collective that
rides ICI/DCN. Sum/mean/max reductions map to what ``ncclAllReduce`` did;
``bcast``/``gather``/``scatter`` are built from ``psum``/``all_gather``/
``axis_index`` with the same root semantics the MPI versions had.

All of these are differentiable: JAX already knows the transposes of
``psum``/``all_gather``/``ppermute``/``all_to_all``, which is exactly the
collective/transpose pairing the reference hand-implemented as Chainer
Functions (``functions/collective_communication.py`` (dagger), SURVEY.md
section 2.4). The user-facing differentiable wrappers live in
:mod:`chainermn_tpu.functions`; this module is the primitive layer.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

PyTree = Any


def axis_index(axis_name: str):
    """This shard's index along ``axis_name`` (the in-program rank)."""
    return lax.axis_index(axis_name)


def axis_size_of(axis_name: str) -> int:
    """Static size of ``axis_name`` (the in-program world size)."""
    return lax.axis_size(axis_name)


# ---------------------------------------------------------------------------
# Reductions (the reference's allreduce family)
# ---------------------------------------------------------------------------

def allreduce(x: PyTree, axis_name: str, op: str = "sum") -> PyTree:
    """Allreduce over a mesh axis. ``op`` in {'sum', 'mean', 'max', 'min'}.

    Replaces ``MpiCommunicatorBase.allreduce`` / ``ncclAllReduce``
    (``pure_nccl_communicator.py`` (dagger)).
    """
    if op == "sum":
        return lax.psum(x, axis_name)
    if op == "mean":
        return lax.pmean(x, axis_name)
    if op == "max":
        return lax.pmax(x, axis_name)
    if op == "min":
        return lax.pmin(x, axis_name)
    raise ValueError(f"unknown reduction op: {op!r}")


def reduce_scatter(x: jax.Array, axis_name: str, *, scatter_dimension: int = 0,
                   tiled: bool = True) -> jax.Array:
    """psum_scatter: the building block of the reference's two-dimensional
    communicator (intra ``ncclReduceScatter``, ``two_dimensional_communicator.py``
    (dagger))."""
    return lax.psum_scatter(
        x, axis_name, scatter_dimension=scatter_dimension, tiled=tiled
    )


# ---------------------------------------------------------------------------
# Rooted collectives
# ---------------------------------------------------------------------------

def bcast(x: PyTree, axis_name: str, root: int = 0) -> PyTree:
    """Broadcast ``root``'s value of ``x`` to every shard along ``axis_name``.

    Implemented as mask-then-psum — one XLA collective, no host round-trip
    (vs the reference's ``MPI_Bcast`` / ``ncclBcast``).
    """
    idx = lax.axis_index(axis_name)
    take = (idx == root)

    def _mask(leaf):
        return jnp.where(take, leaf, jnp.zeros_like(leaf))

    return lax.psum(jax.tree.map(_mask, x), axis_name)


def gather(x: jax.Array, axis_name: str, root: int = 0,
           *, axis: int = 0, tiled: bool = False) -> jax.Array:
    """Gather shards to ``root``. SPMD has no true single-rank ownership, so
    every shard materialises the gathered value but only ``root``'s copy is
    meaningful (others receive zeros, keeping the transpose well-defined).

    Mirrors ``MpiCommunicatorBase.gather`` semantics at the program level.
    """
    full = lax.all_gather(x, axis_name, axis=axis, tiled=tiled)
    idx = lax.axis_index(axis_name)
    return jnp.where(idx == root, full, jnp.zeros_like(full))


def allgather(x: jax.Array, axis_name: str, *, axis: int = 0,
              tiled: bool = False) -> jax.Array:
    """``ncclAllGather`` equivalent (``mpi_communicator_base.py`` (dagger))."""
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def scatter(x: jax.Array, axis_name: str, root: int = 0,
            *, axis: int = 0) -> jax.Array:
    """Scatter ``root``'s leading-``axis`` slices across the axis group.

    Every shard holds the full input (SPMD); shard ``i`` keeps slice ``i`` of
    *root's* copy. Broadcast-from-root first so non-root inputs are ignored,
    matching MPI_Scatter semantics.
    """
    x = bcast(x, axis_name, root)
    idx = lax.axis_index(axis_name)
    n = lax.axis_size(axis_name)
    if x.shape[axis] % n != 0:
        raise ValueError(
            f"scatter: dimension {axis} of size {x.shape[axis]} not divisible "
            f"by axis {axis_name!r} size {n}"
        )
    chunk = x.shape[axis] // n
    return lax.dynamic_slice_in_dim(x, idx * chunk, chunk, axis=axis)


# ---------------------------------------------------------------------------
# Permutation / all-to-all (model- and sequence-parallel plumbing)
# ---------------------------------------------------------------------------

def ppermute(x: PyTree, axis_name: str, perm) -> PyTree:
    """Point-to-point pairwise sends: the substrate for differentiable
    send/recv (``functions/point_to_point_communication.py`` (dagger) maps
    here, see chainermn_tpu.functions.point_to_point)."""
    return lax.ppermute(x, axis_name, perm)


def alltoall(x: jax.Array, axis_name: str, *, split_axis: int = 0,
             concat_axis: int = 0, tiled: bool = True) -> jax.Array:
    """``MPI_Alltoall`` equivalent; also the Ulysses sequence-parallel
    head<->sequence reshard primitive (SURVEY.md section 5)."""
    return lax.all_to_all(
        x, axis_name, split_axis=split_axis, concat_axis=concat_axis,
        tiled=tiled,
    )


def axes_bound(axis_names) -> bool:
    """Whether every named mesh axis in ``axis_names`` (a name or a
    name-sequence) is bound in the current trace. The degrade-gracefully
    probe shared by the optimizer's pmean, the two-dimensional
    communicator's packed reduction, and ``create_mnbn_model``'s BN axis
    injection: outside ``shard_map``/``pmap`` these fall back to local
    semantics instead of raising the unbound-axis NameError."""
    names = (
        axis_names
        if isinstance(axis_names, (tuple, list))
        else (axis_names,)
    )
    try:
        for name in names:
            lax.axis_size(name)
    except NameError:
        return False
    return True


#: wire-name -> compress dtype for the gradient allreduce ("auto"
#: resolution target; None = uncompressed f32 master wire).
WIRE_DTYPES = {"f32": None, "bf16": jnp.bfloat16, "int8": jnp.int8}


def tuned_bucket_bytes(device_kind: str | None = None,
                       n_devices: int = 1) -> int:
    """Gradient-pack bucket size for the two-level allreduce pipeline,
    through the autotune registry (decision ``allreduce_bucket_mb``,
    candidates 16/64/256 MB or ``none`` = one fused buffer). The ~64 MB
    table default keeps the inter (DCN) level bandwidth-bound while
    bounding the transient flat-copy in HBM; a cache entry seeded from
    an on-chip busbw curve can move it. Deterministic per
    (device_kind, n_devices) within a process — the EF residual
    allocation and the reduction path both call this and must agree."""
    from chainermn_tpu import tuning

    key = tuning.decision_key(device_kind, shape=(max(1, n_devices),),
                              dtype="grad")
    mb = tuning.choice(
        "allreduce_bucket_mb", ("16", "64", "256", "none"), key
    )
    return (1 << 62) if mb == "none" else int(mb) << 20


def resolve_allreduce_wire(device_kind: str | None = None,
                           n_devices: int = 1):
    """The ``allreduce_grad_dtype="auto"`` resolution: wire variant
    (f32 / bf16 / the int8 two-phase wire) through the autotune registry
    (decision ``allreduce_wire``), returning the compress dtype the
    communicator stores. Table default is bf16 — the measured default
    (halved bytes, zero rounding risk); int8 is adopted only when a
    cache entry (live-measured or seeded from a busbw curve) shows its
    two rounding stages paying on this topology."""
    from chainermn_tpu import tuning

    key = tuning.decision_key(device_kind, shape=(max(1, n_devices),),
                              dtype="grad")
    wire = tuning.choice("allreduce_wire", ("f32", "bf16", "int8"), key)
    return WIRE_DTYPES[wire]


def _two_level_frame(x, intra_axis, inter_reduce):
    """The shared scatter/gather frame of BOTH two-level reductions:
    ceil-pad, intra ``psum_scatter`` (exact sum of this member's 1/n
    slice), ``inter_reduce(shard)`` at the inter level, intra
    ``all_gather``, un-pad."""
    n_intra = lax.axis_size(intra_axis)
    flat = x.reshape(-1)
    # two_level_shard_len IS this padding rule (the EF residual is
    # allocated from it at init time) — one definition, two users.
    c = two_level_shard_len(flat.size, n_intra)
    rows = jnp.pad(flat, (0, n_intra * c - flat.size)).reshape(n_intra, c)
    shard = lax.psum_scatter(
        rows, intra_axis, scatter_dimension=0, tiled=False
    )  # [c] — the intra-sum of this member's 1/n slice
    shard = inter_reduce(shard)
    rows = lax.all_gather(shard, intra_axis, axis=0, tiled=False)
    return rows.reshape(-1)[: flat.size].reshape(x.shape)


def two_level_allreduce(
    x: jax.Array, intra_axis: str, inter_axis: str, *, op: str = "mean"
) -> jax.Array:
    """Bandwidth-optimal two-level allreduce, written out explicitly:
    intra-level ``psum_scatter`` → inter-level ``psum`` of the 1/n shard →
    intra-level ``all_gather``. Each intra member moves only its shard over
    the slow inter links — the reference's ``TwoDimensionalCommunicator``
    algorithm (intra ``ncclReduceScatter`` → inter MPI allreduce → intra
    ``ncclAllGather``, ``two_dimensional_communicator.py`` (dagger)),
    expressed in named-axis collectives. XLA usually derives an equivalent
    schedule from a plain 2-axis psum; this explicit form pins it.
    """
    if op not in ("sum", "mean"):
        raise ValueError(f"op must be 'sum' or 'mean', got {op!r}")

    def inter(shard):
        shard = lax.psum(shard, inter_axis)
        if op == "mean":
            shard = shard / (
                lax.axis_size(intra_axis) * lax.axis_size(inter_axis)
            )
        return shard

    return _two_level_frame(x, intra_axis, inter)


def int8_allreduce_mean(x: jax.Array, axis_names) -> jax.Array:
    """Quantized mean-allreduce with an INT8 WIRE — beyond the
    reference's fp16 compression (``allreduce_grad_dtype='float16'``,
    ``pure_nccl_communicator.py`` (dagger), shu65's v1.3 feature): 4x
    fewer gradient bytes than f32, 2x fewer than bf16.

    A summing allreduce cannot stay int8 (n ranks of +-127 overflow), so
    the bandwidth-honest algorithm is TWO quantized phases, mirroring
    reduce-scatter -> all-gather:

    1. each member quantizes its full buffer against its own max-abs
       scale and ``all_to_all``s int8 CHUNKS (+ an all-gather of the
       n scalar scales);
    2. each member dequantizes the n received chunks in f32, sums them
       (its exactly-reduced 1/n shard), requantizes against the shard's
       new scale, and ``all_gather``s int8 shards back.

    Wire cost per element: ~2(n-1)/n bytes (vs 4(n-1)/n for a bf16 ring
    and 8(n-1)/n for f32) — certified structurally in
    ``tests/test_optimizer.py`` (the jaxpr's all_to_all/all_gather carry
    int8). Error: two rounding stages, relative error ~1/127 of each
    stage's max-abs — gradient-sized noise well under bf16+momentum
    tolerances for SGD-scale training; see the accuracy tests.

    Must run inside the named-axis context of ``axis_names`` (a name or
    tuple of names, flattened into one logical ring).

    Differentiation: quantization (round/clip) has zero gradient almost
    everywhere, so this op carries a STRAIGHT-THROUGH custom VJP — the
    backward pass is the exact mean-allreduce's transpose (``pmean`` of
    the cotangent), i.e. gradients flow as if the wire were lossless.
    The estimator bias is the quantization noise itself (~1/127 of each
    stage's max-abs).
    """
    return _int8_allreduce_mean(x, _names_tuple(axis_names))


def _names_tuple(axis_names):
    return (tuple(axis_names) if isinstance(axis_names, (tuple, list))
            else (axis_names,))


def axes_size(axis_names) -> int:
    """Product of the sizes of ``axis_names`` (a name or name-sequence) —
    the logical world size of a reduction over the flattened axes."""
    n = 1
    for a in _names_tuple(axis_names):
        n *= lax.axis_size(a)
    return n


def axes_index(axis_names):
    """Row-major ravelled index of this shard over the flattened
    ``axis_names`` — the in-program rank of a multi-axis group (the
    single-axis :func:`axis_index`, generalised)."""
    idx = 0
    for a in _names_tuple(axis_names):
        idx = idx * lax.axis_size(a) + lax.axis_index(a)
    return idx


def decomposed_allreduce(x: jax.Array, axes, *, op: str = "mean") -> jax.Array:
    """Allreduce written out as its bandwidth-optimal decomposition:
    ``psum_scatter`` over the LAST axis of ``axes`` (the mesh convention
    puts the fast/intra axis last), allreduce of the 1/n shard over the
    remaining axes (none on a flat mesh), ``all_gather`` back. On a
    2-axis ``('inter', 'intra')`` mesh this IS the reference's
    ``TwoDimensionalCommunicator`` pipeline
    (``two_dimensional_communicator.py`` (dagger)); on a flat mesh it
    pins the reduce-scatter -> all-gather schedule XLA would otherwise
    be free to fuse back into one all-reduce — the explicit form the
    ``'two_level'`` reduction schedule
    (:mod:`chainermn_tpu.parallel.reduction_schedule`) compiles to,
    HiCCL-style hierarchy-aware composition (arXiv:2408.05962)."""
    if op not in ("sum", "mean"):
        raise ValueError(f"op must be 'sum' or 'mean', got {op!r}")
    names = _names_tuple(axes)
    scatter_ax, rest = names[-1], names[:-1]

    def inter(shard):
        if rest:
            shard = lax.psum(shard, rest)
        if op == "mean":
            shard = shard / axes_size(names)
        return shard

    return _two_level_frame(x, scatter_ax, inter)


def int8_decomposed_allreduce_mean(x: jax.Array, axes) -> jax.Array:
    """The quantized rendering of :func:`decomposed_allreduce`: exact
    ``psum_scatter`` over the last (fast) axis, the int8 two-phase wire
    only over the remaining axes, exact ``all_gather`` back. Flat mesh:
    the flat int8 wire (:func:`int8_allreduce_mean`) already IS the
    reduce-scatter -> all-gather decomposition, so it is used directly."""
    names = _names_tuple(axes)
    if len(names) == 1:
        return int8_allreduce_mean(x, names)
    return int8_two_level_allreduce_mean(x, names[-1], names[:-1])


def _int8_core(x: jax.Array, names):
    """Shared two-phase quantized reduction. Returns ``(mean,
    local_roundtrip)`` where ``local_roundtrip`` is THIS member's
    dequantized stage-1 message ``D(C(x))`` — what the peers actually
    received from us — enabling error feedback (``e = x - D(C(x))``)."""
    n = 1
    for a in names:
        n *= lax.axis_size(a)
    if n == 1:
        # Degenerate axis: the exact mean is x itself — do not pay two
        # lossy roundings for zero communication.
        return x, x
    orig_dtype = x.dtype
    flat = x.astype(jnp.float32).reshape(-1)
    c = -(-flat.size // n)
    rows = jnp.pad(flat, (0, n * c - flat.size)).reshape(n, c)

    def quantize(v):
        amax = jnp.max(jnp.abs(v))
        scale = jnp.maximum(amax, 1e-30) / 127.0
        q = jnp.clip(jnp.round(v / scale), -127, 127).astype(jnp.int8)
        return q, scale

    q, scale = quantize(rows)  # [n, c] int8, own scale
    local_rt = (
        (q.astype(jnp.float32) * scale).reshape(-1)[: flat.size]
        .reshape(x.shape).astype(orig_dtype)
    )
    # Phase 1: int8 chunks to their shard owners + the n tiny scales.
    qt = lax.all_to_all(q, names, split_axis=0, concat_axis=0,
                        tiled=True)              # [n, c] int8 (senders)
    scales = lax.all_gather(scale, names, axis=0, tiled=False)  # [n]
    shard = jnp.sum(
        qt.astype(jnp.float32) * scales[:, None], axis=0
    )  # [c] f32 — this member's exactly-summed shard
    # Phase 2: requantize the reduced shard, int8 all-gather back.
    q2, scale2 = quantize(shard)
    q2g = lax.all_gather(q2, names, axis=0, tiled=False)      # [n, c] int8
    scale2g = lax.all_gather(scale2, names, axis=0, tiled=False)  # [n]
    out = (q2g.astype(jnp.float32) * scale2g[:, None]).reshape(-1)
    mean = (out[: flat.size] / n).reshape(x.shape).astype(orig_dtype)
    return mean, local_rt


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _int8_allreduce_mean(x: jax.Array, names) -> jax.Array:
    return _int8_core(x, names)[0]


def int8_allreduce_mean_with_feedback(x: jax.Array, axis_names):
    """The error-feedback form: ``(mean, local_roundtrip)`` where
    ``local_roundtrip = D(C(x))`` is this member's own stage-1
    quantize-dequantize — the caller keeps ``e = x - local_roundtrip``
    and adds it into the NEXT step's message (EF-SGD: the compression
    error is fed back instead of lost, removing the systematic bias of
    deterministic rounding). NOT differentiable (optimizer-internal;
    use :func:`int8_allreduce_mean` for the straight-through form)."""
    return _int8_core(x, _names_tuple(axis_names))


def int8_two_level_allreduce_mean(
    x: jax.Array, intra_axis: str, inter_axis: str
) -> jax.Array:
    """TOPOLOGY-AWARE quantized allreduce: exact ``psum_scatter`` over
    the fast intra level (ICI — bandwidth is cheap there), the int8
    two-phase wire (both of its rounding stages) ONLY over the slow
    inter level (DCN — where the compression pays), exact ``all_gather``
    back over intra. Each host moves its 1/k shard int8 across DCN:
    compared to the flat :func:`int8_allreduce_mean` the quantization
    applies exactly where bandwidth is scarce and the intra reduction
    contributes NO quantization noise — the quantized rendering of the
    reference's TwoDimensionalCommunicator algorithm
    (``two_dimensional_communicator.py`` (dagger)). Mean semantics over
    the full (inter x intra) product.

    Differentiation: straight-through custom VJP (the exact mean's
    transpose over BOTH axes), same contract as
    :func:`int8_allreduce_mean`."""
    return _int8_two_level_allreduce_mean(x, intra_axis, inter_axis)


def two_level_shard_len(size: int, n_intra: int) -> int:
    """Per-member intra-shard length for a flat buffer of ``size``
    elements — the ceil-padded row length of the two-level frame, and
    therefore the shape of the shard-level EF residual."""
    return -(-size // n_intra)


# ---------------------------------------------------------------------------
# Staged primitives over MERGED axis tuples — the composition layer's
# vocabulary (chainermn_tpu.parallel.composition): each is one stage of
# a composed reduction pipeline, one XLA collective over the flattened
# product of its axis group.
# ---------------------------------------------------------------------------


def _merged_axes_arg(axes):
    names = _names_tuple(axes)
    return names if len(names) > 1 else names[0]


def staged_reduce_scatter(flat: jax.Array, axes) -> jax.Array:
    """One composition stage: ceil-pad the flat buffer into
    ``[n, c]`` rows over the MERGED axis group ``axes`` (``n`` = the
    product of their sizes, ``c`` = :func:`two_level_shard_len`) and
    ``psum_scatter`` it — this member's exactly-summed 1/n shard. The
    padding rule is the two-level frame's, so a single-axis stage is
    byte-identical to the pinned ``decomposed_allreduce`` scatter."""
    names = _names_tuple(axes)
    n = 1
    for a in names:
        n *= lax.axis_size(a)
    c = two_level_shard_len(flat.size, n)
    rows = jnp.pad(flat, (0, n * c - flat.size)).reshape(n, c)
    return lax.psum_scatter(
        rows, _merged_axes_arg(names), scatter_dimension=0, tiled=False
    )


def staged_allreduce(x: jax.Array, axes) -> jax.Array:
    """One composition stage: ``psum`` over the merged axis group."""
    return lax.psum(x, _names_tuple(axes))


def staged_allgather(shard: jax.Array, axes, orig_size: int) -> jax.Array:
    """One composition stage: the conjugate gather of
    :func:`staged_reduce_scatter` — ``all_gather`` the shard rows back
    over the merged group and un-pad to ``orig_size`` elements."""
    rows = lax.all_gather(shard, _merged_axes_arg(axes), axis=0, tiled=False)
    return rows.reshape(-1)[:orig_size]


def staged_broadcast(
    x: jax.Array, axes, *, radix: int = 2, root: int = 0
) -> jax.Array:
    """One composition stage (ISSUE 16): multicast-tree broadcast of
    the ``root`` member's buffer over the MERGED axis group — every
    member returns the root's ``x``. The tree is ``ceil(log_radix(n))``
    ``ppermute`` rounds of holder-doubling: non-holders carry zeros, so
    each round's ``cur + ppermute(cur)`` either delivers the payload or
    adds zero, and round d multiplies the holder set by ``radix``
    (holder s sends to ``s + j*holders`` for ``j in 1..radix-1``). The
    HLO carries exactly ``tree_depth(n, radix)`` collective-permutes —
    the count :func:`chainermn_tpu.parallel.composition
    .predicted_collectives` pins and the serving tree push's donor
    depth mirrors (multicast-tree collectives, arXiv:2605.22428)."""
    names = _names_tuple(axes)
    n = axes_size(names)
    r = int(radix)
    if r < 2:
        raise ValueError(f"multicast radix must be >= 2, got {radix}")
    if n == 1:
        return x
    idx = axes_index(names)
    rk = int(root) % n
    # Relabel so the root is position 0 in tree coordinates.
    pos = lambda s: (s + rk) % n  # noqa: E731 — tree coord -> rank
    cur = jnp.where(idx == rk, x, jnp.zeros_like(x))
    arg = _merged_axes_arg(names)
    holders = 1
    while holders < n:
        # ppermute sources must be unique, so a radix-r round is r-1
        # ppermutes (sub-send j: holder s -> s + j*holders); the
        # destination sets are disjoint and sources never receive, so
        # sequential accumulation within a round is exact. Op count =
        # composition.tree_sends (the structural pin).
        for j in range(1, r):
            perm = [(pos(s), pos(s + j * holders))
                    for s in range(holders) if s + j * holders < n]
            if perm:
                cur = cur + lax.ppermute(cur, arg, perm)
        holders = min(n, holders * r)
    return cur


def int8_two_level_allreduce_mean_with_feedback(
    x: jax.Array, residual: jax.Array, intra_axis: str, inter_axis: str
):
    """Shard-level error feedback for the TOPOLOGY-AWARE wire (round 5 —
    closes the 'EF forces the flat wire' trade-off the round-4 docstring
    recorded): the intra ``psum_scatter`` is exact, so the ONLY lossy
    stage is the int8 wire on the shard crossing inter/DCN — and that is
    where the feedback belongs. The inter message is
    ``intra_shard + residual``; the new residual is
    ``message - D(C(message))`` (this member's stage-1 roundtrip error),
    a per-member f32 buffer of shape
    ``[two_level_shard_len(x.size, n_intra)]`` — 1/n_intra the size of
    the flat-wire EF residual, stored exactly where the error arises.
    Returns ``(mean, new_residual)`` with ``mean`` shaped like ``x``
    (mean over the full inter x intra product, residual mass entering
    the average the standard EF-SGD way).

    NOT differentiable (optimizer-internal, same contract as
    :func:`int8_allreduce_mean_with_feedback`); degenerate inter axis
    (size 1) pays no quantization and returns a zero residual."""
    n_intra = lax.axis_size(intra_axis)
    captured = []

    def inter(shard):
        msg = shard + residual.astype(jnp.float32)
        mean_shard, local_rt = _int8_core(msg, (inter_axis,))
        captured.append(msg - local_rt)  # this member's new residual
        return mean_shard / n_intra

    mean = _two_level_frame(
        x.astype(jnp.float32), intra_axis, inter
    ).astype(x.dtype)
    return mean, captured[0]


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def _int8_two_level_allreduce_mean(x, intra_axis, inter_axis):
    # inter_axis may be a single name or a tuple of names (the
    # decomposed form over a >2-axis mesh quantizes over ALL non-scatter
    # axes as one logical inter ring).
    def inter(shard):
        # inter MEAN on the int8 wire, then /n_intra for the total mean.
        return (_int8_core(shard, _names_tuple(inter_axis))[0]
                / lax.axis_size(intra_axis))

    return _two_level_frame(x, intra_axis, inter).astype(x.dtype)


def _int8_2l_fwd(x, intra_axis, inter_axis):
    return _int8_two_level_allreduce_mean(x, intra_axis, inter_axis), None


def _int8_2l_bwd(intra_axis, inter_axis, _, ct):
    return (lax.pmean(ct, _names_tuple(inter_axis) + (intra_axis,)),)


_int8_two_level_allreduce_mean.defvjp(_int8_2l_fwd, _int8_2l_bwd)


def _int8_ar_fwd(x, names):
    return _int8_allreduce_mean(x, names), None


def _int8_ar_bwd(names, _, ct):
    # Straight-through: the transpose of the EXACT mean-allreduce.
    return (lax.pmean(ct, names),)


_int8_allreduce_mean.defvjp(_int8_ar_fwd, _int8_ar_bwd)


def shift(x: PyTree, axis_name: str, offset: int = 1) -> PyTree:
    """Rotate values around the axis ring by ``offset`` (ring-attention KV
    rotation step). Positive offset sends shard i's value to shard i+offset."""
    n = lax.axis_size(axis_name)
    perm = [(i, (i + offset) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm)
