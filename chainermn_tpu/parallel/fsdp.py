"""FSDP-style parameter + optimizer-state sharding (declarative "ZeRO-3").

Absent from the reference (2017-era; SURVEY.md section 2.2 lists ZeRO-style
sharding as the natural TPU-era extension). Where
:mod:`chainermn_tpu.parallel.zero` shards only the *optimizer state* with
explicit reduce-scatter/all-gather inside a ``shard_map``, this module is
the fully declarative form: parameters AND optimizer state live sharded
over the data axis, and XLA's SPMD partitioner inserts every collective —
all-gather of each layer's weights right before use (and re-gather in the
backward), reduce-scatter of its gradients — from sharding propagation
alone. This is the "pick a mesh, annotate shardings, let XLA insert
collectives" recipe; nothing here is a collective call.

Memory per device: ``O(params / n)`` for weights and optimizer state (vs
``O(params)`` replicated), at the cost of gathering each layer on demand.

Contract difference from :func:`chainermn_tpu.training.make_train_step`:
``loss_fn`` sees the GLOBAL batch (auto-SPMD jit, not shard_map), so its
local-batch mean IS the global mean — no pmean anywhere.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from chainermn_tpu.communicators.base import CommunicatorBase
from chainermn_tpu.training.train_step import TrainState, normalize_loss_fn

PyTree = Any


def fsdp_shardings(
    tree: PyTree,
    mesh: Mesh,
    axis_name: str = "data",
    *,
    min_size: int = 2**15,
) -> PyTree:
    """Per-leaf :class:`NamedSharding` tree: each sufficiently large leaf is
    sharded over ``axis_name`` along its LARGEST divisible dimension;
    scalars, small leaves, and leaves with no divisible dim stay replicated
    (sharding a 1000-element bias across 256 chips buys nothing and costs a
    gather).
    """
    n = mesh.shape[axis_name]

    def one(leaf):
        shape = jnp.shape(leaf)
        size = 1
        for s in shape:
            size *= s
        if size < min_size:
            return NamedSharding(mesh, P())
        best, best_dim = None, -1
        for d, s in enumerate(shape):
            if s % n == 0 and s > best_dim:
                best, best_dim = d, s
        if best is None:
            return NamedSharding(mesh, P())
        spec = [None] * len(shape)
        spec[best] = axis_name
        return NamedSharding(mesh, P(*spec))

    return jax.tree.map(one, tree)


def create_fsdp_train_state(
    params: PyTree,
    optimizer,
    comm: CommunicatorBase,
    *,
    model_state: PyTree = (),
    min_size: int = 2**15,
):
    """Place ``params`` and the freshly-initialised optimizer state with
    FSDP shardings over the communicator's primary axis. Returns
    ``(TrainState, state_shardings)`` — pass the shardings to
    :func:`make_fsdp_train_step`."""
    mesh = comm.mesh
    axis = comm.axis_name
    p_sh = fsdp_shardings(params, mesh, axis, min_size=min_size)
    params = jax.tree.map(jax.device_put, params, p_sh)
    opt_state = jax.jit(
        optimizer.init,
        out_shardings=fsdp_shardings(
            jax.eval_shape(optimizer.init, params), mesh, axis,
            min_size=min_size,
        ),
    )(params)
    o_sh = jax.tree.map(lambda x: x.sharding, opt_state)
    repl = NamedSharding(mesh, P())
    if jax.tree.leaves(model_state):
        model_state = jax.tree.map(
            lambda x: jax.device_put(jnp.asarray(x), repl), model_state
        )
    state = TrainState(
        params=params,
        opt_state=opt_state,
        step=jax.device_put(jnp.zeros((), jnp.int32), repl),
        model_state=model_state,
    )
    shardings = TrainState(
        params=p_sh,
        opt_state=o_sh,
        step=repl,
        model_state=jax.tree.map(lambda _: repl, model_state),
    )
    return state, shardings


def make_fsdp_train_step(
    loss_fn: Callable,
    optimizer,
    comm: CommunicatorBase,
    state_shardings: TrainState,
    *,
    batch_spec: Optional[P] = None,
    donate: bool = True,
):
    """Jitted FSDP train step (auto-SPMD — no shard_map, no explicit
    collectives; XLA partitions from the in/out shardings).

    ``loss_fn(params, batch[, model_state])`` sees GLOBAL arrays and must
    return the batch-mean loss (plus the usual aux forms); see module
    docstring.
    """
    mesh = comm.mesh
    if batch_spec is None:
        batch_spec = P(comm.grad_axes)
    batch_sharding = NamedSharding(mesh, batch_spec)
    repl = NamedSharding(mesh, P())
    _loss_with_aux = normalize_loss_fn(loss_fn)

    def step(state: TrainState, batch):
        grad_fn = jax.value_and_grad(_loss_with_aux, has_aux=True)
        (loss, (metrics, model_state)), grads = grad_fn(
            state.params, batch, state.model_state
        )
        updates, opt_state = optimizer.update(
            grads, state.opt_state, state.params
        )
        params = optax.apply_updates(state.params, updates)
        new_state = TrainState(
            params=params,
            opt_state=opt_state,
            step=state.step + 1,
            model_state=model_state,
        )
        return new_state, {"loss": loss, **metrics}

    return jax.jit(
        step,
        in_shardings=(state_shardings, batch_sharding),
        out_shardings=(state_shardings, repl),
        donate_argnums=(0,) if donate else (),
    )
