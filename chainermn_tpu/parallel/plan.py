"""ParallelPlan — one global-view mesh program for
DP x TP x ZeRO x pipeline x sequence.

The reference's training stack was per-process communicator-style: every
parallel form was a wrapper at the call site (``communicators/`` (dagger),
``optimizers.py`` (dagger) — SURVEY.md sections 2.1-2.3), so composing two
of them meant composing wrappers and hoping their collectives interleaved.
A :class:`ParallelPlan` inverts that: it lays out ONE named mesh
(``data x zero x pipe x model``, any subset, device layout via
:mod:`chainermn_tpu.parallel.mesh` — ICI-aware placement, balanced
auto-factorisation through :func:`~chainermn_tpu.parallel.mesh.
best_mesh_shape`) and compiles ONE ``shard_map`` train step in which the
per-axis modules participate as *spec providers*
(:mod:`chainermn_tpu.parallel.plan_specs`):

- ``data`` — plain data parallelism: batch shards over it, gradients
  ``pmean`` over it (one all-reduce);
- ``zero`` — data parallelism with a ZeRO-1 sharded update
  (:mod:`chainermn_tpu.parallel.zero`, arXiv:2004.13336): batch shards
  over it too, but the gradient mean arrives as a reduce-scatter, the
  inner optimizer updates a 1/n state chunk, and an all-gather returns
  the parameter updates — same wire bytes as the allreduce it replaces;
- ``model`` — Megatron-style tensor parallelism
  (:mod:`chainermn_tpu.parallel.tensor`): marked leaves stack
  ``[n, ...]`` shards, the loss is written with the ``copy_to_tp`` /
  ``reduce_from_tp`` adjoint pairs, one psum per column->row pair;
- ``pipe`` — GPipe micro-batch pipelining
  (:mod:`chainermn_tpu.parallel.pipeline`): stage leaves stack
  ``[n_stages, ...]``, the conveyor's ppermute rides the schedule;
- ``seq`` — sequence/context parallelism (ISSUE 13): the batch's
  sequence dim shards over it (``batch_spec`` appends it after the dp
  axes), attention routes through the ring
  (:func:`~chainermn_tpu.parallel.ring_attention.
  seq_ring_attention_local` — ``n - 1`` ppermutes per layer per forward
  pass) or Ulysses (:mod:`chainermn_tpu.parallel.ulysses` — two
  all_to_alls in, one out) via the ``seq_attn_impl`` tuning decision
  (:meth:`ParallelPlan.seq_attention`), and gradients take one extra
  all-reduce over the axis (mean over token shards) before the dp
  reduction;
- ``expert`` — MoE expert parallelism (ISSUE 20): expert parameter
  leaves stack ``[n, ...]`` shards (``P('expert')``), the batch's token
  dim shards over the axis (extra data parallelism for every non-expert
  leaf), and tokens ride exactly two ``all_to_all``s per MoE layer per
  pass (:func:`~chainermn_tpu.parallel.moe.moe_layer_local`, routed via
  :meth:`ParallelPlan.moe_layer` — the ``moe_dispatch`` tuning
  decision). Replicated leaves' gradients take one fused all-reduce
  over the axis; expert-stacked leaves take NONE — the all_to_all's
  exact transpose already lands every shard's cotangents on the owning
  shard, and the plan rescales them to the global token mean.

Two composed forms ride the same contract (ISSUE 13 sweep-ins):
``zero_stacked_groups=True`` chunks the STACKED groups' optimizer state
over the ``zero`` axis too (TP x ZeRO — the arXiv:2004.13336
cross-replica update sharding applied per TP/pipe shard: the stacked
groups' dp gradient mean becomes the same rs > ar > update > ag
pipeline the zero group runs, identical wire bytes); and a leaf spec
``P('pipe', 'model')`` stacks a leaf over BOTH axes (the pipe x model
composed plan — stage slices that are themselves tensor-parallel,
``stage_fn`` written with the :mod:`~chainermn_tpu.parallel.tensor`
helpers).

Buffer donation is threaded through the compiled step by construction
(``donate_argnums=(0,)`` on the whole :class:`TrainState`): step ``t+1``
reuses step ``t``'s buffers in place, so the H2D-after-D2H degradation the
verify skill documents (a fetched metric followed by a state re-upload)
cannot occur — there is no re-upload.

Acceptance is structural, not prose (tests/test_plan.py): the compiled
plan step carries exactly the hand-wired paths' HLO collective counts,
dist == single values AND gradients for every composed plan, and the jit
cache stays pinned at 1 across steps.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Callable, Mapping, Optional, Sequence

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from chainermn_tpu.parallel import plan_specs as _ps
from chainermn_tpu.parallel.mesh import best_mesh_shape, make_mesh

PyTree = Any


@dataclasses.dataclass(frozen=True)
class PipelinePlanSpec:
    """How a plan with a ``pipe`` axis runs the pipelined region.

    ``stage_fn(params_local, x_mb) -> y_mb`` is one homogeneous stage
    (output shape == input shape) receiving the COLLAPSED param tree —
    pipe-stacked leaves arrive as this stage's slice. Every TRAINABLE
    leaf of a pipe plan must be pipe-stacked: a replicated leaf consumed
    inside ``stage_fn`` would need a cross-stage gradient sum the
    schedule does not owe (the same embed/head-outside contract as
    :func:`~chainermn_tpu.parallel.pipeline.make_pipeline`).
    ``loss_fn(y, batch) -> loss`` (or ``(loss, metrics_dict)``) maps the
    reassembled pipeline output back to the local-batch-mean loss.
    """

    stage_fn: Callable
    loss_fn: Callable
    n_microbatches: Optional[int] = None
    #: pull the pipeline input out of the batch (default: ``batch[0]``
    #: for tuple/list batches, else the batch itself)
    input_of: Optional[Callable] = None


def _pipe_input(batch):
    if isinstance(batch, (tuple, list)):
        return batch[0]
    return batch


class ParallelPlan:
    """One named mesh + the specs to compile a composed train step.

    Args:
      axes: either a mapping ``{axis: size}`` (at most one size may be
        ``-1`` — inferred from the device count) or a sequence of axis
        names, auto-factorised balanced with larger factors first
        (:func:`~chainermn_tpu.parallel.mesh.best_mesh_shape`; the
        largest factor lands on the first — DCN-most — axis). Axis names
        come from :data:`~chainermn_tpu.parallel.plan_specs.
        CANONICAL_AXES`; mesh order is canonical regardless of input
        order (``model`` last — the ICI-fastest slot, the repo's mesh
        convention).
      devices: device list (default ``jax.devices()``). Layout is
        ICI-topology-aware via :func:`~chainermn_tpu.parallel.mesh.
        make_mesh` — on a pod slice the 2-D ``(dcn, ici)`` factorisation
        falls out of the canonical order.
      grad_reduction: optional schedule for the data-parallel gradient
        reduction of the non-ZeRO update groups — a menu name, a
        composition signature, or a
        :class:`~chainermn_tpu.parallel.composition.Composition` over
        exactly this plan's dp axes (``data`` [+ ``zero``]), validated
        at construction (ISSUE 12). Default ``None`` keeps the fused
        ``pmean`` (byte-identical to the pre-composition plan; the
        single-stage ``ar(all)`` composition compiles to the same
        program). A composition with stages acts as a SPEC PROVIDER:
        the affected axes' owed collectives in :meth:`describe` come
        from its stage list
        (:func:`~chainermn_tpu.parallel.plan_specs.
        composition_collectives`).
      zero_stacked_groups: chunk the STACKED groups' (``model``/``pipe``)
        optimizer state over the ``zero`` axis too (ISSUE 13 — TP x ZeRO
        per arXiv:2004.13336): their dp gradient mean becomes the zero
        composition's rs > ar > sharded-update > ag per leaf (same wire
        bytes), state leaves stack ``[n_stack, n_zero, ...]``. Requires
        a ``zero`` axis and at least one stacked axis; mutually
        exclusive with ``grad_reduction=``.
    """

    def __init__(
        self,
        axes: Mapping[str, int] | Sequence[str],
        *,
        devices=None,
        grad_reduction=None,
        zero_stacked_groups: bool = False,
    ) -> None:
        if devices is None:
            devices = jax.devices()
        devices = list(devices)
        n = len(devices)
        if isinstance(axes, Mapping):
            sizes = dict(axes)
            unknown = [a for a, s in sizes.items() if s == -1]
            if len(unknown) > 1:
                raise ValueError(
                    f"at most one axis size may be -1, got {unknown}"
                )
            if unknown:
                rest = math.prod(
                    s for a, s in sizes.items() if a not in unknown
                )
                if rest == 0 or n % rest:
                    raise ValueError(
                        f"cannot infer {unknown[0]!r}: {n} devices do not "
                        f"factor over the explicit sizes {sizes}"
                    )
                sizes[unknown[0]] = n // rest
        else:
            names = list(axes)
            if len(set(names)) != len(names):
                raise ValueError(f"duplicate plan axes: {names}")
            # canonical order first, THEN factorise: the largest factor
            # must land on the first (DCN-most) canonical axis, not on
            # whatever order the caller spelled the names in.
            ordered = [a for a in _ps.CANONICAL_AXES if a in names]
            _ps.resolve_axes(dict.fromkeys(names, 1))  # name validation
            shape = best_mesh_shape(n, len(ordered))
            sizes = dict(zip(ordered, shape))
        self.axes: dict[str, _ps.AxisSpec] = _ps.resolve_axes(sizes)
        shape = tuple(s.size for s in self.axes.values())
        if math.prod(shape) != n:
            raise ValueError(
                f"plan axes {dict((a, s.size) for a, s in self.axes.items())} "
                f"cover {math.prod(shape)} mesh slots but {n} devices were "
                f"given"
            )
        self.mesh = make_mesh(tuple(self.axes), shape, devices)
        #: decision records the plan resolved (``seq_attn_impl``
        #: provenance — the dryrun/bench line and tests read it; same
        #: shape as ``ServingEngine.decisions``).
        self.decisions: list[dict] = []
        self._seq_impl: Optional[str] = None
        self._moe_impl: Optional[str] = None
        self._zsg = bool(zero_stacked_groups)
        if self._zsg:
            if "zero" not in self.axes:
                raise ValueError(
                    "zero_stacked_groups=True needs a 'zero' axis to "
                    "chunk the stacked groups' state over"
                )
            if not any(s.stacked for s in self.axes.values()):
                raise ValueError(
                    "zero_stacked_groups=True needs a stacked axis "
                    "('model'/'pipe') whose state it can chunk — a plain "
                    "zero plan already chunks everything"
                )
            if grad_reduction is not None:
                raise ValueError(
                    "zero_stacked_groups and grad_reduction= are "
                    "mutually exclusive: the stacked groups' reduction "
                    "IS the zero composition (rs > ar > update > ag)"
                )
        self._grad_comp = None
        if grad_reduction is not None:
            from chainermn_tpu.parallel.composition import compile_schedule

            if not self.dp_axes:
                raise ValueError(
                    "grad_reduction= needs a data-parallel axis "
                    "('data'/'zero') to reduce over; this plan has none"
                )
            comp = compile_schedule(grad_reduction, self.dp_axes)
            if comp.has_update:
                raise ValueError(
                    f"grad_reduction composition {comp.signature()!r} "
                    "carries a sharded_update stage — the sharded update "
                    "is the 'zero' AXIS's job (add zero to the plan's "
                    "axes); grad_reduction takes pure reductions"
                )
            self._grad_comp = comp
            # The composition is the spec provider for the plain data
            # axis: its owed collectives come from the stage list. The
            # 'zero' axis keeps its own provider entry — the sharded
            # update's per-leaf rs/ag is that axis's job regardless of
            # how the replicated groups' gradients reduce.
            owed = _ps.composition_collectives(comp)
            if "data" in owed and "data" in self.axes:
                self.axes["data"] = dataclasses.replace(
                    self.axes["data"], collectives=owed["data"]
                )

    # -- topology accessors -------------------------------------------------

    def axis_size(self, name: str) -> int:
        return self.axes[name].size if name in self.axes else 1

    @property
    def dp_axes(self) -> tuple[str, ...]:
        """Axes the batch shards (and gradients reduce) over."""
        return tuple(a for a in ("data", "zero") if a in self.axes)

    @property
    def dp_size(self) -> int:
        return math.prod(self.axis_size(a) for a in self.dp_axes) or 1

    def batch_spec(self) -> P:
        """Batch sharding: dim 0 over the dp axes (plus ``expert`` when
        present — the expert axis shards tokens too, by batch row), and
        — with a ``seq`` axis — dim 1 (the sequence) over it: every
        batch leaf must then carry ``[B, T, ...]`` with ``T`` divisible
        by the seq size."""
        row_axes = self.dp_axes + (
            ("expert",) if "expert" in self.axes else ()
        )
        if "seq" in self.axes:
            return P(row_axes if row_axes else None, "seq")
        return P(row_axes) if row_axes else P()

    def describe(self) -> dict:
        """Axis sizes + the collectives each spec provider owes the step
        (the dryrun/bench provenance line). A composed gradient
        reduction reports its signature — the provenance names the
        pipeline, not a menu label."""
        out = {
            "mesh": {a: s.size for a, s in self.axes.items()},
            "collectives": _ps.owed_collectives(self.axes),
            "batch_spec": str(self.batch_spec()),
        }
        if self._grad_comp is not None:
            out["grad_reduction"] = self._grad_comp.signature()
        if self._zsg:
            out["zero_stacked_groups"] = True
        if self._seq_impl is not None:
            out["seq_attn_impl"] = self._seq_impl
        if self._moe_impl is not None:
            out["moe_dispatch_impl"] = self._moe_impl
        return out

    # -- the seq axis's attention router (ISSUE 13) -------------------------

    @staticmethod
    def seq_local_positions(t_local: int, axis_name: str = "seq"):
        """GLOBAL positions of this shard's ``t_local`` tokens — call
        INSIDE the compiled step (``axis_index * t_local + arange``);
        what sequence-parallel loss functions pass as the model's
        ``positions=`` so rope/learned tables line up across shards."""
        import jax.numpy as jnp

        return (lax.axis_index(axis_name) * t_local
                + jnp.arange(t_local, dtype=jnp.int32))

    def seq_attention(
        self,
        *,
        heads: int,
        t_local: int,
        kv_heads: Optional[int] = None,
        impl: str = "auto",
        causal: bool = True,
        block_q: int = 512,
        block_k: int = 1024,
    ):
        """Resolve the ``seq_attn_impl`` tuning decision and return
        ``(attn_fn, record)`` — ``attn_fn`` matches the ``attention_fn``
        contract of :class:`~chainermn_tpu.models.transformer.
        TransformerBlock` and runs INSIDE the compiled step's shard_map.

        ``impl='auto'`` resolves through the registry (decision
        ``seq_attn_impl``, keyed device_kind x seq-shards x heads x
        T-bucket; table default ``ring`` — no divisibility constraint,
        ``O(T_local)`` resident K/V). An 'auto' resolution to
        ``ulysses`` with ``heads % seq_size != 0`` (or kv heads — GQA)
        force-falls back to ``ring`` with ``source:
        'forced:heads-indivisible'`` recorded in ``plan.decisions``; an
        EXPLICIT ``impl='ulysses'`` with indivisible heads is rejected
        at entry with both numbers named
        (:func:`~chainermn_tpu.parallel.ulysses.
        check_ulysses_divisibility`). The resolved impl's owed HLO
        collectives replace the seq axis's descriptor entry
        (:data:`~chainermn_tpu.parallel.plan_specs.
        SEQ_IMPL_COLLECTIVES`), so :meth:`describe` names what actually
        compiles.
        """
        from chainermn_tpu import tuning
        from chainermn_tpu.parallel.ring_attention import (
            seq_ring_attention_local,
        )
        from chainermn_tpu.parallel.ulysses import (
            check_ulysses_divisibility,
            ulysses_attention_local,
        )

        if "seq" not in self.axes:
            raise ValueError("seq_attention needs a 'seq' plan axis")
        n = self.axis_size("seq")
        kvh = int(kv_heads or heads)
        key = tuning.decision_key(
            shape=(n, int(heads), max(1, int(t_local))), dtype="seqattn"
        )
        if impl == "auto":
            winner = tuning.choice(
                "seq_attn_impl", _ps.SEQ_ATTN_IMPLS, key
            )
            source = next(
                (d["source"] for d in tuning.decisions_taken()
                 if d["name"] == "seq_attn_impl" and d["key"] == key),
                "table",
            )
            if winner == "ulysses" and (heads % n or kvh % n):
                winner, source = "ring", "forced:heads-indivisible"
        elif impl in _ps.SEQ_ATTN_IMPLS:
            if impl == "ulysses":
                # explicit request: reject at entry, naming both numbers
                check_ulysses_divisibility(heads, kvh, n)
            winner, source = impl, "explicit"
        else:
            raise ValueError(
                f"seq_attn_impl must be one of "
                f"{_ps.SEQ_ATTN_IMPLS + ('auto',)}, got {impl!r}"
            )
        record = {"name": "seq_attn_impl", "key": key, "winner": winner,
                  "source": source}
        self.decisions.append(record)
        self._seq_impl = winner
        self.axes["seq"] = dataclasses.replace(
            self.axes["seq"],
            collectives=_ps.SEQ_IMPL_COLLECTIVES[winner],
        )
        interpret = self.mesh.devices.flat[0].platform != "tpu"

        if winner == "ring":
            def attn_fn(q, k, v, *, causal=causal, scale=None, **kw):
                return seq_ring_attention_local(
                    q, k, v, "seq", causal=causal, scale=scale,
                    block_q=block_q, block_k=block_k,
                    interpret=interpret, **kw,
                )
        else:
            def attn_fn(q, k, v, *, causal=causal, scale=None, **kw):
                return ulysses_attention_local(
                    q, k, v, "seq", causal=causal, scale=scale,
                    impl="flash", interpret=interpret, **kw,
                )
        return attn_fn, record

    # -- the expert axis's MoE router (ISSUE 20) ----------------------------

    def moe_layer(
        self,
        *,
        tokens_local: int,
        d_model: int,
        experts_per_shard: int = 1,
        capacity_factor: Optional[float] = 1.25,
        k: int = 1,
        impl: str = "auto",
        dtype=None,
    ):
        """Resolve the ``moe_dispatch`` tuning decision for the
        ``expert`` axis and return ``(moe_fn, record)`` — ``moe_fn(x,
        router_w, expert_fn, expert_params) -> (out, aux)`` runs INSIDE
        the compiled step's shard_map
        (:func:`~chainermn_tpu.parallel.moe.moe_layer_local` with
        ``return_stats=True``). ``aux`` carries the axis-invariant
        ``load_balance`` loss (add ``aux_weight * aux['load_balance']``
        to the task loss) plus the drop/pad accounting
        (``expert_load`` ``[E]``, ``dropped``, ``padded``, ``capacity``
        — globals over the axis, float32 so they ride the plan's metric
        pmean). The resolved impl is recorded in ``plan.decisions``
        (same provenance shape as :meth:`seq_attention`) and named by
        :meth:`describe`."""
        from chainermn_tpu import tuning
        from chainermn_tpu.parallel import moe as _moe

        if "expert" not in self.axes:
            raise ValueError("moe_layer needs an 'expert' plan axis")
        n = self.axis_size("expert")
        e_global = n * int(experts_per_shard)
        if k > e_global:
            raise ValueError(
                f"moe_layer k={k} exceeds n_experts={e_global} "
                f"({n} shards x {experts_per_shard} experts/shard)"
            )
        key = tuning.decision_key(
            shape=(max(1, int(tokens_local)), e_global, int(d_model)),
            dtype=dtype if dtype is not None else jnp.float32,
        )
        if impl == "auto":
            winner = tuning.choice("moe_dispatch", ("sort", "einsum"), key)
            source = next(
                (d["source"] for d in tuning.decisions_taken()
                 if d["name"] == "moe_dispatch" and d["key"] == key),
                "table",
            )
        elif impl in ("sort", "einsum"):
            winner, source = impl, "explicit"
        else:
            raise ValueError(
                f"moe_dispatch impl must be 'sort', 'einsum' or 'auto', "
                f"got {impl!r}"
            )
        record = {"name": "moe_dispatch", "key": key, "winner": winner,
                  "source": source}
        self.decisions.append(record)
        self._moe_impl = winner

        # the token dim shards over every row axis (batch_spec), so the
        # aux stats must reduce over ALL of them — reducing over 'expert'
        # alone would leave per-data-shard aux losses under expert x data
        stats_axes = self.dp_axes + ("expert",)

        def moe_fn(x, router_w, expert_fn, expert_params):
            return _moe.moe_layer_local(
                x, router_w, expert_fn, expert_params, "expert",
                capacity_factor=capacity_factor, k=k,
                dispatch_impl=winner,
                experts_per_shard=experts_per_shard,
                return_stats=True,
                stats_axes=stats_axes,
            )

        return moe_fn, record

    # -- specs --------------------------------------------------------------

    def param_specs(self, params: PyTree, specs: PyTree | None = None) -> PyTree:
        """Full per-leaf ``PartitionSpec`` tree for ``params`` (validated
        against this plan's axes; see :func:`~chainermn_tpu.parallel.
        plan_specs.normalize_param_specs`)."""
        return _ps.normalize_param_specs(params, specs, self.axes)

    def _groups(self, flat_specs):
        return _ps.partition_groups(flat_specs, self.axes)

    @staticmethod
    def _inner(optimizer):
        """Accept a plain optax transform OR a communicator-style
        wrapper: unwrapped through :func:`chainermn_tpu.optimizers.
        inner_transform` so create_train_state / state_specs /
        compile_train_step all agree on the state layout (a wrapper's
        own ``init`` would chunk by the communicator's size, not this
        plan's axes)."""
        from chainermn_tpu.optimizers import inner_transform

        return inner_transform(optimizer)

    def _group_state_init(self, inner, group: str, leaves):
        from chainermn_tpu.parallel.zero import zero_stacked_init

        if group == "zero":
            return zero_stacked_init(inner, leaves, self.axis_size("zero"))
        if group == "rep":
            return inner.init(leaves)
        stack_axes = _ps.group_stack_axes(group)
        if self._zsg:
            z = self.axis_size("zero")

            def fn(ls):
                return zero_stacked_init(inner, ls, z)
        else:
            fn = inner.init
        for _ in stack_axes:
            fn = jax.vmap(fn)
        return fn(leaves)

    def _group_state_spec_leaf(self, group: str) -> P:
        if group == "zero":
            return P("zero")
        if group == "rep":
            return P()
        axes = _ps.group_stack_axes(group)
        if self._zsg:
            axes = axes + ("zero",)
        return P(*axes)

    def state_specs(self, params: PyTree, inner, specs: PyTree | None = None):
        """The full :class:`TrainState` spec pytree the compiled step
        carries — params per their specs, each opt-state group stacked
        over its axis, step/model_state replicated."""
        from chainermn_tpu.training.train_step import TrainState

        inner = self._inner(inner)
        spec_tree = self.param_specs(params, specs)
        flat_p, treedef = jax.tree.flatten(params)
        flat_s = jax.tree.leaves(spec_tree)
        groups = self._groups(flat_s)
        opt_spec = {}
        for grp, idx in groups.items():
            template = jax.eval_shape(
                lambda ls, g=grp: self._group_state_init(inner, g, ls),
                [flat_p[i] for i in idx],
            )
            leaf_spec = self._group_state_spec_leaf(grp)
            opt_spec[grp] = jax.tree.map(lambda _: leaf_spec, template)
        return TrainState(
            params=spec_tree, opt_state=opt_spec, step=P(), model_state=P()
        )

    # -- state --------------------------------------------------------------

    def create_train_state(
        self,
        params: PyTree,
        inner: optax.GradientTransformation,
        *,
        param_specs: PyTree | None = None,
        model_state: PyTree = (),
    ):
        """Initialise the plan-sharded :class:`TrainState`: params placed
        per their specs, each opt-state group created directly in its
        stacked layout and placed sharded (``[n, ...]`` over its axis) —
        no full-state replica ever materialises on one device."""
        from chainermn_tpu.training.train_step import TrainState

        inner = self._inner(inner)
        spec_tree = self.param_specs(params, param_specs)
        flat_p, treedef = jax.tree.flatten(params)
        flat_s = jax.tree.leaves(spec_tree)
        groups = self._groups(flat_s)
        mesh = self.mesh

        def put(leaf, spec):
            # A COPY, not the caller's buffer: device_put aliases when the
            # sharding already matches, and the donating step would then
            # delete the user's template params out from under them (the
            # LocalSGD anchor lesson, measured here too).
            return jax.device_put(
                jnp.array(leaf, copy=True), NamedSharding(mesh, spec)
            )

        placed = jax.tree.unflatten(
            treedef, [put(l, s) for l, s in zip(flat_p, flat_s)]
        )
        opt_state = {}
        for grp, idx in groups.items():
            st = self._group_state_init(inner, grp, [flat_p[i] for i in idx])
            leaf_spec = self._group_state_spec_leaf(grp)
            opt_state[grp] = jax.tree.map(
                lambda e: put(e, leaf_spec), st
            )
        repl = NamedSharding(mesh, P())
        if jax.tree.leaves(model_state):
            model_state = jax.tree.map(
                lambda x: jax.device_put(jnp.asarray(x), repl), model_state
            )
        return TrainState(
            params=placed,
            opt_state=opt_state,
            step=jax.device_put(jnp.zeros((), jnp.int32), repl),
            model_state=model_state,
        )

    # -- the compiled step --------------------------------------------------

    def compile_train_step(
        self,
        loss_fn: Callable,
        inner: optax.GradientTransformation,
        params: PyTree | None = None,
        *,
        param_specs: PyTree | None = None,
        donate: bool = True,
        pipeline: PipelinePlanSpec | None = None,
    ):
        """Compile the ONE composed train step:
        ``step(state, batch) -> (state, metrics)``.

        ``loss_fn`` is the shard-local loss (local-batch mean) in any of
        the :func:`~chainermn_tpu.training.train_step.normalize_loss_fn`
        forms, written against the COLLAPSED param tree (stacked leaves
        arrive as this shard's slice — use the
        :mod:`~chainermn_tpu.parallel.tensor` helpers for model-axis
        leaves). With a ``pipe`` axis pass ``pipeline=`` instead of
        relying on ``loss_fn`` alone (see :class:`PipelinePlanSpec`; the
        plan then calls ``loss_fn`` only if ``pipeline`` is ``None``).

        ``inner`` is a plain optax transform (elementwise when a
        ``zero`` axis is present — the ZeRO constraint); a
        :class:`~chainermn_tpu.optimizers.MultiNodeOptimizer` is
        auto-unwrapped via :func:`~chainermn_tpu.optimizers.
        inner_transform` (wrapper-wire features refused loudly).

        ``params`` is the template the specs compile against; omitting it
        defers the build to the first call (same jit cache — still one
        compile). ``donate=True`` (default) donates the whole state:
        params and opt-state buffers are updated in place, a second step
        re-uploads nothing (pinned structurally in tests/test_plan.py).
        """
        if "pipe" in self.axes and pipeline is None:
            raise ValueError(
                "this plan has a 'pipe' axis: pass pipeline="
                "PipelinePlanSpec(stage_fn, loss_fn, ...)"
            )
        if pipeline is not None and "pipe" not in self.axes:
            raise ValueError("pipeline= given but the plan has no 'pipe' axis")
        inner = self._inner(inner)
        if params is not None:
            return self._build_step(
                loss_fn, inner, params, param_specs, donate, pipeline
            )

        built: list = []

        def step(state, batch):
            if not built:
                built.append(
                    self._build_step(
                        loss_fn, inner, state.params, param_specs, donate,
                        pipeline,
                    )
                )
            return built[0](state, batch)

        step.cache_size = lambda: (
            _jit_cache_size(built[0]) if built else 0
        )
        return step

    def _build_step(self, loss_fn, inner, params, param_specs, donate,
                    pipeline):
        from jax import shard_map

        from chainermn_tpu.parallel.composition import (
            reduce_composed_tree,
            run_gather_suffix,
            run_reduce_prefix,
            zero_composition,
        )
        from chainermn_tpu.parallel.zero import zero_param_chunk
        from chainermn_tpu.training.train_step import (
            TrainState,
            normalize_loss_fn,
        )

        mesh = self.mesh
        dp_axes = self.dp_axes
        dp_total = self.dp_size
        has_seq = "seq" in self.axes
        has_expert = "expert" in self.axes
        n_expert = self.axis_size("expert")
        red_axes = (dp_axes + (("seq",) if has_seq else ())
                    + (("expert",) if has_expert else ()))
        grad_comp = self._grad_comp
        zsg = self._zsg
        # the zero group's structural composition (scatter axis last in
        # dp order — 'zero' — the other dp axes reduce the shard)
        zero_comp = (zero_composition(dp_axes)
                     if "zero" in self.axes else None)
        spec_tree = self.param_specs(params, param_specs)
        treedef = jax.tree.structure(params)
        flat_specs = jax.tree.leaves(spec_tree)
        #: leaf indices stacked over the expert axis (their grads arrive
        #: fully accumulated via the all_to_all transpose — see below)
        expert_leaves = {
            i for i, s in enumerate(flat_specs) if "expert" in tuple(s)
        }
        if pipeline is not None:
            # Enforce the PipelinePlanSpec contract structurally, not by
            # docstring: a replicated leaf consumed inside stage_fn would
            # receive per-stage gradients with no cross-stage sum, and
            # check_vma=False would mask the divergence as silently wrong
            # params — reject anything not pipe-stacked up front. A
            # composed pipe x model leaf (P('pipe', 'model')) leads with
            # pipe and satisfies the same contract: its stage slice is
            # itself tensor-parallel.
            bad = [
                jax.tree_util.keystr(path)
                for (path, _), spec in zip(
                    jax.tree_util.tree_flatten_with_path(params)[0],
                    flat_specs,
                )
                if not (tuple(spec) and tuple(spec)[0] == "pipe")
            ]
            if bad:
                raise ValueError(
                    "every trainable leaf of a pipe plan must be "
                    f"pipe-stacked (P('pipe') or P('pipe', 'model')); "
                    f"got {bad[:8]} — stage "
                    "leaves carry their own slice per stage, and "
                    "replicated leaves have no cross-stage gradient sum "
                    "(the embed/head-outside contract of make_pipeline)"
                )
        groups = self._groups(flat_specs)
        #: leaf index -> leading stacked dims its local view collapses
        stack_depth = {
            i: len(_ps.group_stack_axes(grp))
            for grp, idx in groups.items() for i in idx
        }
        state_spec = self.state_specs(params, inner, param_specs)
        batch_spec = self.batch_spec()
        n_pipe = self.axis_size("pipe")
        lfn = None if pipeline is not None else normalize_loss_fn(loss_fn)

        def _peel(leaf, n):
            for _ in range(n):
                leaf = leaf[0]
            return leaf

        def _wrap(leaf, n):
            for _ in range(n):
                leaf = leaf[None]
            return leaf

        def collapse(tree):
            flat = treedef.flatten_up_to(tree)
            return jax.tree.unflatten(
                treedef,
                [_peel(l, stack_depth.get(i, 0))
                 for i, l in enumerate(flat)],
            )

        def expand(tree):
            flat = treedef.flatten_up_to(tree)
            return jax.tree.unflatten(
                treedef,
                [_wrap(l, stack_depth.get(i, 0))
                 for i, l in enumerate(flat)],
            )

        def pipe_loss(params_c, batch):
            from chainermn_tpu.parallel.pipeline import (
                pipeline_local,
                unscale_replicated_grads,
            )

            x = (pipeline.input_of or _pipe_input)(batch)
            n_micro = pipeline.n_microbatches or n_pipe
            b = x.shape[0]
            if b % n_micro:
                raise ValueError(
                    f"local batch {b} not divisible by n_microbatches "
                    f"{n_micro}"
                )
            xm = x.reshape((n_micro, b // n_micro) + x.shape[1:])
            ym = pipeline_local(
                lambda p, mb: pipeline.stage_fn(p, mb), params_c, xm, "pipe"
            )
            # every stage computes the same loss from the replicated
            # outputs; the psum replication's shard-local transpose
            # would scale the cotangent by n_stages — undo it exactly.
            ym = unscale_replicated_grads(ym, "pipe")
            y = ym.reshape((b,) + ym.shape[2:])
            out = pipeline.loss_fn(y, batch)
            if isinstance(out, tuple):
                loss, metrics = out
            else:
                loss, metrics = out, {}
            return loss, (metrics, ())

        def local_step(state, batch):
            params_c = collapse(state.params)
            if pipeline is None:
                grad_fn = jax.value_and_grad(lfn, has_aux=True)
                (loss, (metrics, model_state)), grads_c = grad_fn(
                    params_c, batch, state.model_state
                )
            else:
                grad_fn = jax.value_and_grad(pipe_loss, has_aux=True)
                (loss, (metrics, _)), grads_c = grad_fn(params_c, batch)
                model_state = state.model_state

            flat_p = treedef.flatten_up_to(params_c)
            flat_g = treedef.flatten_up_to(grads_c)
            if has_seq:
                # The seq shards each computed the mean loss of their
                # OWN tokens: one fused all-reduce makes every gradient
                # the global token mean before the dp reduction (mean of
                # equal-sized shard means).
                flat_g = lax.pmean(flat_g, "seq")
            if has_expert:
                # Expert shards also each computed their OWN tokens'
                # mean loss, but only the NON-expert leaves need the
                # fused all-reduce: an expert-stacked leaf's gradient
                # already accumulated every shard's cotangents through
                # the all_to_all transpose — reducing it again would mix
                # different experts' grads. Rescale it to the same
                # mean-of-shard-means the pmean gives the rest.
                rep = {i: g for i, g in enumerate(flat_g)
                       if i not in expert_leaves}
                if rep:
                    rep = lax.pmean(rep, "expert")
                flat_g = [
                    flat_g[i] / n_expert if i in expert_leaves else rep[i]
                    for i in range(len(flat_g))
                ]
            flat_u: list = [None] * len(flat_p)
            new_opt = {}

            # Stacked groups + plain replicated: the dp-axes gradient
            # reduction — the plan's grad_reduction composition when
            # one is set, else the fused pmean (TP/pipe leaves included
            # — those axes are extra data parallelism for them; the
            # model/pipe axes themselves are never reduced, the
            # tensor/pipeline composition rule). With
            # zero_stacked_groups the stacked groups run the zero
            # composition instead: rs(zero) > ar(other dp) > 1/z-chunk
            # update > ag(zero) per leaf — same wire bytes as the fused
            # pmean they replace, state 1/z per TP/pipe shard.
            for grp, idx in groups.items():
                if grp == "zero" or not idx:
                    continue
                depth = len(_ps.group_stack_axes(grp))
                g = [flat_g[i] for i in idx]
                p_sub = [flat_p[i] for i in idx]
                st = state.opt_state[grp]
                if depth and zsg:
                    zpre, zpost = zero_comp.split_update()
                    gch = [
                        run_reduce_prefix(gi, zpre, total=dp_total)
                        for gi in g
                    ]
                    pch = [zero_param_chunk(pi, "zero") for pi in p_sub]
                    stc = jax.tree.map(
                        lambda e: _peel(e, depth + 1), st
                    )
                    uch, st_out = inner.update(gch, stc, pch)
                    st_out = jax.tree.map(
                        lambda e: _wrap(e, depth + 1), st_out
                    )
                    for i, uc, pi in zip(idx, uch, p_sub):
                        flat_u[i] = run_gather_suffix(
                            uc, pi, zpost, zpre
                        )
                    new_opt[grp] = st_out
                    continue
                if dp_axes:
                    if grad_comp is not None:
                        g = reduce_composed_tree(g, grad_comp)
                    else:
                        g = lax.pmean(g, dp_axes)
                new_in = st
                if depth:
                    new_in = jax.tree.map(lambda e: _peel(e, depth), st)
                u, st_out = inner.update(g, new_in, p_sub)
                if depth:
                    st_out = jax.tree.map(
                        lambda e: _wrap(e, depth), st_out
                    )
                for i, ui in zip(idx, u):
                    flat_u[i] = ui
                new_opt[grp] = st_out

            # ZeRO group: the composition rs(zero) > ar(other dp) >
            # sharded_update > ag(zero) — the derived instance the
            # hand-wired zero_grad_scatter/zero_gather_updates pair
            # used to spell (identical primitives, identical counts),
            # with the inner optimizer fused at the split point.
            idx = groups.get("zero")
            if idx:
                zpre, zpost = zero_comp.split_update()
                gch = [
                    run_reduce_prefix(flat_g[i], zpre, total=dp_total)
                    for i in idx
                ]
                pch = [zero_param_chunk(flat_p[i], "zero") for i in idx]
                st = jax.tree.map(
                    lambda e: e[0], state.opt_state["zero"]
                )
                uch, st_out = inner.update(gch, st, pch)
                new_opt["zero"] = jax.tree.map(lambda e: e[None], st_out)
                for i, uc in zip(idx, uch):
                    flat_u[i] = run_gather_suffix(
                        uc, flat_p[i], zpost, zpre
                    )

            updates_c = jax.tree.unflatten(treedef, flat_u)
            params_c2 = optax.apply_updates(params_c, updates_c)
            metrics = {"loss": loss, **metrics}
            if red_axes:
                metrics = lax.pmean(metrics, red_axes)
                if jax.tree.leaves(model_state):
                    model_state = lax.pmean(model_state, red_axes)
            new_state = TrainState(
                params=expand(params_c2),
                opt_state=new_opt,
                step=state.step + 1,
                model_state=model_state,
            )
            return new_state, metrics

        sharded = shard_map(
            local_step,
            mesh=mesh,
            in_specs=(state_spec, batch_spec),
            out_specs=(state_spec, P()),
            check_vma=False,
        )
        jitted = jax.jit(sharded, donate_argnums=(0,) if donate else ())

        def cache_size():
            return _jit_cache_size(jitted)

        try:
            jitted.cache_size = cache_size
            jitted.plan_info = self.describe()
        except (AttributeError, TypeError):
            pass
        return jitted


def _jit_cache_size(jitted) -> Optional[int]:
    try:
        return jitted._cache_size()
    except (AttributeError, TypeError):
        return None


__all__ = ["ParallelPlan", "PipelinePlanSpec"]
