"""Expert parallelism (MoE) — all_to_all token routing over an ``'expert'``
mesh axis.

Absent from the reference (SURVEY.md section 2.2 lists EP as the optional
TPU-era extension). Mechanism: each shard hosts one (or more) experts; a
top-1 router scores tokens, tokens travel to their expert's shard via
``all_to_all``, the expert MLP runs, and a second ``all_to_all`` returns
outputs — the same two-collective shape as Ulysses sequence parallelism,
with capacity-bounded dispatch making every shape static for XLA.

Capacity discipline (the TPU answer to ragged routing): each expert
processes at most ``capacity = ceil(tokens/experts * capacity_factor)``
tokens per shard; overflow tokens are dropped (standard Switch-style
routing) and their outputs fall back to zero — callers add the residual
path so dropped tokens pass through unchanged.

Differentiable end to end: routing uses straight-through softmax gating
(gradient flows through the gate probability), and ``all_to_all`` has an
exact transpose.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

PyTree = Any


def top1_route(
    logits: jax.Array,  # [tokens, n_experts]
    capacity: int,
):
    """Switch-style top-1 routing with capacity.

    Returns:
      dispatch: ``[tokens, n_experts, capacity]`` one-hot dispatch mask.
      combine:  same shape, dispatch * gate probability (for the return
        trip, carries the gradient to the router).
    """
    n_experts = logits.shape[-1]
    probs = jax.nn.softmax(logits, axis=-1)
    expert = jnp.argmax(probs, axis=-1)  # [tokens]
    gate = jnp.take_along_axis(probs, expert[:, None], axis=-1)[:, 0]

    onehot = jax.nn.one_hot(expert, n_experts, dtype=jnp.int32)
    # position of each token within its expert's queue
    pos = jnp.cumsum(onehot, axis=0) * onehot - 1  # [tokens, experts]
    pos = pos.max(axis=-1)  # [tokens]
    keep = pos < capacity

    dispatch = (
        jax.nn.one_hot(expert, n_experts, dtype=logits.dtype)[:, :, None]
        * jax.nn.one_hot(pos, capacity, dtype=logits.dtype)[:, None, :]
    )
    dispatch = dispatch * keep[:, None, None].astype(logits.dtype)
    combine = dispatch * gate[:, None, None]
    return dispatch, combine


def topk_route(
    logits: jax.Array,  # [tokens, n_experts]
    capacity: int,
    k: int = 2,
):
    """GShard-style top-k routing with capacity (k=2 is the classic
    configuration; k=1 degenerates to :func:`top1_route` up to gate
    normalisation).

    Each token's k chosen experts receive it in slot order (slot 0 fills
    queues first); gates are the chosen experts' softmax probabilities
    normalised over the k choices. An overflowed (dropped) choice's share
    is simply lost — the kept choice keeps its normalised weight
    ``g_kept/(g1+..+gk)``, it is NOT re-scaled to 1 (GShard semantics;
    the residual path covers the dropped mass). Returns the same
    ``(dispatch, combine)`` pair as :func:`top1_route`
    (``[tokens, n_experts, capacity]``).
    """
    n_experts = logits.shape[-1]
    if k > n_experts:
        raise ValueError(f"k={k} exceeds n_experts={n_experts}")
    probs = jax.nn.softmax(logits, axis=-1)

    # Select in LOGIT space with an explicit taken-mask: prob-space
    # masking re-selects expert 0 when remaining softmax mass underflows
    # (diverged router), and -inf/finfo.min masking alone still re-picks a
    # taken expert when the CALLER pads disallowed experts with -inf. A
    # duplicate pick (only possible when every untaken expert is -inf) is
    # zeroed outright — no queue slot, no gate weight.
    taken = jnp.zeros_like(logits, dtype=jnp.int32)
    chosen = []  # (onehot_int [t,e], gate [t])
    for _ in range(k):
        avail = jnp.where(taken > 0, -jnp.inf, logits)
        expert = jnp.argmax(avail, axis=-1)
        onehot = jax.nn.one_hot(expert, n_experts, dtype=jnp.int32)
        onehot = onehot * (1 - taken)  # zero a duplicate pick entirely
        gate = (probs * onehot).sum(-1)
        chosen.append((onehot, gate))
        taken = taken + onehot

    # Queue bookkeeping in int32 (as top1_route does): a low-precision
    # logits dtype must never round slot indices — bf16 cumsum collides
    # queue slots past 256 tokens.
    denom = sum(g for _, g in chosen) + 1e-9
    counts = jnp.zeros((n_experts,), jnp.int32)  # kept tokens per queue
    dispatch = jnp.zeros((logits.shape[0], n_experts, capacity), logits.dtype)
    combine = jnp.zeros_like(dispatch)
    for onehot, gate in chosen:
        pos = (jnp.cumsum(onehot, axis=0) - 1) * onehot + counts[None, :]
        pos_tok = (pos * onehot).sum(-1)
        keep = (pos_tok < capacity) & (onehot.sum(-1) > 0)
        d = (
            onehot.astype(logits.dtype)[:, :, None]
            * jax.nn.one_hot(pos_tok, capacity, dtype=logits.dtype)[:, None, :]
        ) * keep[:, None, None].astype(logits.dtype)
        dispatch = dispatch + d
        combine = combine + d * (gate / denom)[:, None, None]
        counts = counts + (onehot * keep[:, None]).sum(0)
        counts = jnp.minimum(counts, capacity)
    return dispatch, combine


def load_balancing_loss(logits: jax.Array) -> jax.Array:
    """Switch/GShard auxiliary load-balancing loss:
    ``n_experts * mean_e(fraction_of_tokens_e * mean_router_prob_e)``
    (top-1 assignment fraction, the standard estimator for any k) —
    1.0 at perfect balance, grows as routing collapses onto few experts.
    Add ``aux_weight * load_balancing_loss(logits)`` to the task loss.
    """
    n_experts = logits.shape[-1]
    probs = jax.nn.softmax(logits, axis=-1)
    # fraction of tokens whose top-1 choice is each expert
    top1 = jax.nn.one_hot(jnp.argmax(probs, -1), n_experts, dtype=probs.dtype)
    frac = top1.mean(axis=0)
    mean_prob = probs.mean(axis=0)
    return n_experts * jnp.sum(frac * mean_prob)


def moe_layer_local(
    x: jax.Array,              # [tokens_local, d_model]
    router_w: jax.Array,       # [d_model, n_experts_global]
    expert_fn: Callable,       # expert_fn(params, x[capacity, d]) -> same
    expert_params: PyTree,     # THIS shard's expert params
    axis_name: str = "expert",
    *,
    capacity_factor: float = 1.25,
    k: int = 1,
) -> jax.Array:
    """One MoE layer inside ``shard_map``: one expert per shard along
    ``axis_name``; tokens ride two ``all_to_all``s. ``k=1`` is Switch-style
    top-1 routing, ``k=2`` GShard-style top-2 (capacity scales with k).

    Returns the combined expert outputs for the local tokens (zeros for
    dropped tokens — add the residual outside).
    """
    import math

    n = lax.axis_size(axis_name)
    tokens, d = x.shape
    capacity = max(1, math.ceil(tokens * k / n * capacity_factor))

    logits = x @ router_w  # [tokens, n]
    if k == 1:
        dispatch, combine = top1_route(logits, capacity)
    else:
        dispatch, combine = topk_route(logits, capacity, k)

    # Gather each expert's queue locally: [n, capacity, d]
    queues = jnp.einsum("td,tec->ecd", x, dispatch)
    # Exchange: shard i sends queue row e to shard e, receives its own
    # expert's queue from every shard -> [n(senders), capacity, d]
    recv = lax.all_to_all(queues, axis_name, split_axis=0, concat_axis=0,
                          tiled=True)
    # Run THIS shard's expert on all n*capacity tokens at once (MXU-batched)
    out = expert_fn(expert_params, recv.reshape(n * capacity, d))
    out = out.reshape(n, capacity, d)
    # Return trip + weighted combine back into token order
    back = lax.all_to_all(out, axis_name, split_axis=0, concat_axis=0,
                          tiled=True)
    return jnp.einsum("ecd,tec->td", back, combine)


def make_expert_params(init_fn: Callable, rng: jax.Array, n_experts: int):
    """Stack ``n_experts`` independently-initialised expert param trees
    along a leading axis (shard over the ``'expert'`` mesh axis)."""
    rngs = jax.random.split(rng, n_experts)
    trees = [init_fn(r) for r in rngs]
    return jax.tree.map(lambda *ls: jnp.stack(ls), *trees)
