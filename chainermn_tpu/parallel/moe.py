"""Expert parallelism (MoE) — all_to_all token routing over an ``'expert'``
mesh axis.

Absent from the reference (SURVEY.md section 2.2 lists EP as the optional
TPU-era extension). Mechanism: each shard hosts one (or more) experts; a
top-1 router scores tokens, tokens travel to their expert's shard via
``all_to_all``, the expert MLP runs, and a second ``all_to_all`` returns
outputs — the same two-collective shape as Ulysses sequence parallelism,
with capacity-bounded dispatch making every shape static for XLA.

Capacity discipline (the TPU answer to ragged routing): each expert
processes at most ``capacity = ceil(tokens/experts * capacity_factor)``
tokens per shard; overflow tokens are dropped (standard Switch-style
routing) and their outputs fall back to zero — callers add the residual
path so dropped tokens pass through unchanged.

Differentiable end to end: routing uses straight-through softmax gating
(gradient flows through the gate probability), and ``all_to_all`` has an
exact transpose.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

PyTree = Any


def _dense_from_slots(slots, logits, capacity):
    """Expand index-form routing into the dense ``(dispatch, combine)``
    pair (``[T, E, C]`` each, ``logits.dtype`` dispatch / f32-promoted
    gates as before)."""
    n_experts = logits.shape[-1]
    sentinel = n_experts * capacity
    tokens = logits.shape[0]
    dispatch = jnp.zeros((tokens, n_experts, capacity), logits.dtype)
    combine = None
    for slot, gate in slots:
        # one_hot over sentinel+1 classes; the sentinel (dropped) column is
        # sliced off, zeroing dropped tokens.
        oh = jax.nn.one_hot(slot, sentinel + 1, dtype=logits.dtype)
        oh = oh[:, :sentinel].reshape(tokens, n_experts, capacity)
        dispatch = dispatch + oh
        term = oh * gate[:, None, None]
        combine = term if combine is None else combine + term
    return dispatch, combine


def top1_route(
    logits: jax.Array,  # [tokens, n_experts]
    capacity: int,
):
    """Switch-style top-1 routing with capacity.

    Returns:
      dispatch: ``[tokens, n_experts, capacity]`` one-hot dispatch mask.
      combine:  same shape, dispatch * gate probability (for the return
        trip, carries the gradient to the router).
    """
    return _dense_from_slots(
        route_slots(logits, capacity, 1), logits, capacity
    )


def topk_route(
    logits: jax.Array,  # [tokens, n_experts]
    capacity: int,
    k: int = 2,
):
    """GShard-style top-k routing with capacity (k=2 is the classic
    configuration; k=1 degenerates to :func:`top1_route` up to gate
    normalisation).

    Each token's k chosen experts receive it in slot order (slot 0 fills
    queues first); gates are the chosen experts' softmax probabilities
    normalised over the k choices. An overflowed (dropped) choice's share
    is simply lost — the kept choice keeps its normalised weight
    ``g_kept/(g1+..+gk)``, it is NOT re-scaled to 1 (GShard semantics;
    the residual path covers the dropped mass). Returns the same
    ``(dispatch, combine)`` pair as :func:`top1_route`
    (``[tokens, n_experts, capacity]``).

    All routing bookkeeping lives in :func:`route_slots` (shared with the
    sort dispatch path, so the two ``dispatch_impl``s cannot drift).
    """
    return _dense_from_slots(
        route_slots(logits, capacity, k), logits, capacity
    )


def load_balancing_loss(logits: jax.Array) -> jax.Array:
    """Switch/GShard auxiliary load-balancing loss:
    ``n_experts * mean_e(fraction_of_tokens_e * mean_router_prob_e)``
    (top-1 assignment fraction, the standard estimator for any k) —
    1.0 at perfect balance, grows as routing collapses onto few experts.
    Add ``aux_weight * load_balancing_loss(logits)`` to the task loss.
    """
    n_experts = logits.shape[-1]
    probs = jax.nn.softmax(logits, axis=-1)
    # fraction of tokens whose top-1 choice is each expert
    top1 = jax.nn.one_hot(jnp.argmax(probs, -1), n_experts, dtype=probs.dtype)
    frac = top1.mean(axis=0)
    mean_prob = probs.mean(axis=0)
    return n_experts * jnp.sum(frac * mean_prob)


def route_slots(
    logits: jax.Array,  # [tokens, n_experts]
    capacity: int,
    k: int = 1,
):
    """Index-form routing: the same Switch/GShard bookkeeping as
    :func:`top1_route` / :func:`topk_route`, but returning per-choice
    ``(slot, gate)`` pairs instead of dense ``[T, E, C]`` tensors.

    ``slot[t] = expert[t]*capacity + queue_pos[t]`` for kept tokens and
    the sentinel ``n_experts*capacity`` for dropped ones; ``gate`` carries
    the (k-normalised) router weight. O(T·E) bookkeeping, nothing O(T·E·C).
    """
    n_experts = logits.shape[-1]
    if k > n_experts:
        raise ValueError(f"k={k} exceeds n_experts={n_experts}")
    probs = jax.nn.softmax(logits, axis=-1)
    sentinel = n_experts * capacity

    if k == 1:
        expert = jnp.argmax(probs, axis=-1)
        gate = jnp.take_along_axis(probs, expert[:, None], axis=-1)[:, 0]
        onehot = jax.nn.one_hot(expert, n_experts, dtype=jnp.int32)
        pos = ((jnp.cumsum(onehot, axis=0) - 1) * onehot).sum(-1)
        keep = pos < capacity
        slot = jnp.where(keep, expert * capacity + pos, sentinel)
        return [(slot, gate)]

    # Top-k selection in LOGIT space with an explicit taken-mask:
    # prob-space masking re-selects expert 0 when remaining softmax mass
    # underflows (diverged router), and -inf masking alone still re-picks
    # a taken expert when the CALLER pads disallowed experts with -inf. A
    # duplicate pick (only possible when every untaken expert is -inf) is
    # zeroed outright — no queue slot, no gate weight. Queue bookkeeping
    # stays int32: a low-precision logits dtype must never round slot
    # indices (bf16 cumsum collides queue slots past 256 tokens).
    taken = jnp.zeros_like(logits, dtype=jnp.int32)
    chosen = []
    for _ in range(k):
        avail = jnp.where(taken > 0, -jnp.inf, logits)
        expert = jnp.argmax(avail, axis=-1)
        onehot = jax.nn.one_hot(expert, n_experts, dtype=jnp.int32)
        onehot = onehot * (1 - taken)
        gate = (probs * onehot).sum(-1)
        chosen.append((expert, onehot, gate))
        taken = taken + onehot

    denom = sum(g for _, _, g in chosen) + 1e-9
    counts = jnp.zeros((n_experts,), jnp.int32)
    out = []
    for expert, onehot, gate in chosen:
        pos = (jnp.cumsum(onehot, axis=0) - 1) * onehot + counts[None, :]
        pos_tok = (pos * onehot).sum(-1)
        keep = (pos_tok < capacity) & (onehot.sum(-1) > 0)
        slot = jnp.where(keep, expert * capacity + pos_tok, sentinel)
        out.append((slot, gate / denom))
        counts = counts + (onehot * keep[:, None]).sum(0)
        counts = jnp.minimum(counts, capacity)
    return out


def dispatch_einsum(x, logits, capacity, k):
    """Dense one-hot dispatch (reference): builds ``[T, E, C]`` dispatch /
    combine tensors. Returns ``(queues [E, C, d], combine_fn)`` where
    ``combine_fn(back [E, C, d]) -> [T, d]``."""
    if k == 1:
        dispatch, combine = top1_route(logits, capacity)
    else:
        dispatch, combine = topk_route(logits, capacity, k)
    queues = jnp.einsum("td,tec->ecd", x, dispatch)

    def combine_fn(back):
        return jnp.einsum("ecd,tec->td", back, combine)

    return queues, combine_fn


def dispatch_sort(x, logits, capacity, k):
    """Index-based dispatch: queue assembly is one int scatter of slot ids
    plus one row gather — O(T·d + E·C·d) work and memory, no ``[T, E, C]``
    tensor anywhere (the scalable form at LM scale, where the dense form's
    O(T·E·C·d) dispatch einsum dominates the layer).

    Same routing bookkeeping as :func:`dispatch_einsum` (via
    :func:`route_slots`), so results are identical. Returns the same
    ``(queues, combine_fn)`` pair."""
    tokens, d = x.shape
    n_experts = logits.shape[-1]
    slots = route_slots(logits, capacity, k)
    sentinel = n_experts * capacity
    # Match the einsum path's promotion semantics exactly: its queue einsum
    # promotes (x, dispatch[logits.dtype]) and its combine einsum promotes
    # (back, combine[f32-promoted gates]) — switching dispatch_impl must
    # not change dtypes or gate precision.
    q_dtype = jnp.promote_types(x.dtype, logits.dtype)

    # token_of_slot: which token fills each queue slot (sentinel-initialised
    # so empty slots gather the zero row). Dropped tokens write the
    # sentinel slot, which is sliced off.
    token_of_slot = jnp.full((sentinel + 1,), tokens, jnp.int32)
    for slot, _ in slots:
        token_of_slot = token_of_slot.at[slot].set(
            jnp.arange(tokens, dtype=jnp.int32)
        )
    x_pad = jnp.concatenate([x, jnp.zeros((1, d), x.dtype)]).astype(q_dtype)
    queues = x_pad[token_of_slot[:sentinel]].reshape(n_experts, capacity, d)

    def combine_fn(back):
        gate_dtype = slots[0][1].dtype
        out_dtype = jnp.promote_types(back.dtype, gate_dtype)
        flat = jnp.concatenate(
            [back.reshape(sentinel, d),
             jnp.zeros((1, d), back.dtype)]
        ).astype(out_dtype)
        out = jnp.zeros((tokens, d), out_dtype)
        for slot, gate in slots:
            out = out + flat[slot] * gate[:, None].astype(out_dtype)
        return out

    return queues, combine_fn


_DISPATCH = {"einsum": dispatch_einsum, "sort": dispatch_sort}


def resolve_dispatch_impl(
    tokens: int, n_experts: int, d_model: int, dtype,
    impl: str = "auto",
) -> str:
    """Device-aware dispatch choice, through the autotune registry
    (:mod:`chainermn_tpu.tuning`), keyed on ``(device_kind,
    bucket(T, E, d), dtype)``.

    Measured crossover the default table encodes (r5 bench artifacts):
    sort is 167.8x the einsum path on the CPU proxy (T2048xE8xD64) but
    only 1.63x on TPU v5e at the production shape (T16384xE16xD512) —
    einsum-competitive there, dominant nowhere measured, so the table
    says ``sort`` for every backend and the persistent cache (seeded
    from on-chip sweeps) owns any shape bucket where the dense form
    wins. ``impl`` other than ``"auto"`` short-circuits (explicit
    caller choice is never overridden).
    """
    if impl != "auto":
        return impl
    from chainermn_tpu import tuning

    key = tuning.decision_key(shape=(tokens, n_experts, d_model),
                              dtype=dtype)
    return tuning.choice("moe_dispatch", ("sort", "einsum"), key)


def moe_layer_local(
    x: jax.Array,              # [tokens_local, d_model]
    router_w: jax.Array,       # [d_model, n_experts_global]
    expert_fn: Callable,       # expert_fn(params, x[capacity, d]) -> same
    expert_params: PyTree,     # THIS shard's expert params
    axis_name: str = "expert",
    *,
    capacity_factor: float = 1.25,
    k: int = 1,
    dispatch_impl: str = "auto",
) -> jax.Array:
    """One MoE layer inside ``shard_map``: one expert per shard along
    ``axis_name``; tokens ride two ``all_to_all``s. ``k=1`` is Switch-style
    top-1 routing, ``k=2`` GShard-style top-2 (capacity scales with k).

    ``dispatch_impl``: ``'einsum'`` (dense one-hot [T,E,C] tensors — the
    reference form, fine at test scale), ``'sort'`` (index scatter +
    gather, O(T·d) — the scalable form; same routing, same numbers), or
    ``'auto'`` (default): device-aware choice via the autotune registry
    — see :func:`resolve_dispatch_impl` for the measured crossover the
    default encodes. Either impl is numerically identical (tested), so
    the choice is pure performance.

    Returns the combined expert outputs for the local tokens (zeros for
    dropped tokens — add the residual outside).
    """
    import math

    n = lax.axis_size(axis_name)
    tokens, d = x.shape
    capacity = max(1, math.ceil(tokens * k / n * capacity_factor))

    logits = x @ router_w  # [tokens, n]
    impl = resolve_dispatch_impl(tokens, n, d, x.dtype, dispatch_impl)
    queues, combine_fn = _DISPATCH[impl](x, logits, capacity, k)

    # Exchange: shard i sends queue row e to shard e, receives its own
    # expert's queue from every shard -> [n(senders), capacity, d]
    recv = lax.all_to_all(queues, axis_name, split_axis=0, concat_axis=0,
                          tiled=True)
    # Run THIS shard's expert on all n*capacity tokens at once (MXU-batched)
    out = expert_fn(expert_params, recv.reshape(n * capacity, d))
    out = out.reshape(n, capacity, d)
    # Return trip + weighted combine back into token order
    back = lax.all_to_all(out, axis_name, split_axis=0, concat_axis=0,
                          tiled=True)
    return combine_fn(back)


def make_expert_params(init_fn: Callable, rng: jax.Array, n_experts: int):
    """Stack ``n_experts`` independently-initialised expert param trees
    along a leading axis (shard over the ``'expert'`` mesh axis)."""
    rngs = jax.random.split(rng, n_experts)
    trees = [init_fn(r) for r in rngs]
    return jax.tree.map(lambda *ls: jnp.stack(ls), *trees)
