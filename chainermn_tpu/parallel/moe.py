"""Expert parallelism (MoE) — all_to_all token routing over an ``'expert'``
mesh axis.

Absent from the reference (SURVEY.md section 2.2 lists EP as the optional
TPU-era extension). Mechanism: each shard hosts one (or more) experts; a
top-1 router scores tokens, tokens travel to their expert's shard via
``all_to_all``, the expert MLP runs, and a second ``all_to_all`` returns
outputs — the same two-collective shape as Ulysses sequence parallelism,
with capacity-bounded dispatch making every shape static for XLA.

Capacity discipline (the TPU answer to ragged routing): each expert
processes at most ``capacity = ceil(tokens/experts * capacity_factor)``
tokens per shard; overflow tokens are dropped (standard Switch-style
routing) and their outputs fall back to zero — callers add the residual
path so dropped tokens pass through unchanged.

Differentiable end to end: routing uses straight-through softmax gating
(gradient flows through the gate probability), and ``all_to_all`` has an
exact transpose.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

PyTree = Any


def _dense_from_slots(slots, logits, capacity):
    """Expand index-form routing into the dense ``(dispatch, combine)``
    pair (``[T, E, C]`` each, ``logits.dtype`` dispatch / f32-promoted
    gates as before)."""
    n_experts = logits.shape[-1]
    sentinel = n_experts * capacity
    tokens = logits.shape[0]
    dispatch = jnp.zeros((tokens, n_experts, capacity), logits.dtype)
    combine = None
    for slot, gate in slots:
        # one_hot over sentinel+1 classes; the sentinel (dropped) column is
        # sliced off, zeroing dropped tokens.
        oh = jax.nn.one_hot(slot, sentinel + 1, dtype=logits.dtype)
        oh = oh[:, :sentinel].reshape(tokens, n_experts, capacity)
        dispatch = dispatch + oh
        term = oh * gate[:, None, None]
        combine = term if combine is None else combine + term
    return dispatch, combine


def top1_route(
    logits: jax.Array,  # [tokens, n_experts]
    capacity: int,
):
    """Switch-style top-1 routing with capacity.

    Returns:
      dispatch: ``[tokens, n_experts, capacity]`` one-hot dispatch mask.
      combine:  same shape, dispatch * gate probability (for the return
        trip, carries the gradient to the router).
    """
    return _dense_from_slots(
        route_slots(logits, capacity, 1), logits, capacity
    )


def topk_route(
    logits: jax.Array,  # [tokens, n_experts]
    capacity: int,
    k: int = 2,
):
    """GShard-style top-k routing with capacity (k=2 is the classic
    configuration; k=1 degenerates to :func:`top1_route` up to gate
    normalisation).

    Each token's k chosen experts receive it in slot order (slot 0 fills
    queues first); gates are the chosen experts' softmax probabilities
    normalised over the k choices. An overflowed (dropped) choice's share
    is simply lost — the kept choice keeps its normalised weight
    ``g_kept/(g1+..+gk)``, it is NOT re-scaled to 1 (GShard semantics;
    the residual path covers the dropped mass). Returns the same
    ``(dispatch, combine)`` pair as :func:`top1_route`
    (``[tokens, n_experts, capacity]``).

    All routing bookkeeping lives in :func:`route_slots` (shared with the
    sort dispatch path, so the two ``dispatch_impl``s cannot drift).
    """
    return _dense_from_slots(
        route_slots(logits, capacity, k), logits, capacity
    )


def load_balancing_loss(
    logits: jax.Array, axis_name=None
) -> jax.Array:
    """Switch/GShard auxiliary load-balancing loss:
    ``n_experts * mean_e(fraction_of_tokens_e * mean_router_prob_e)``
    (top-1 assignment fraction, the standard estimator for any k) —
    1.0 at perfect balance, grows as routing collapses onto few experts.
    Add ``aux_weight * load_balancing_loss(logits)`` to the task loss.

    ``axis_name``: when the token dim is SHARDED over mesh axes, pass
    the axis name (or tuple of names — e.g. ``('data', 'expert')`` under
    a composed plan) — the per-expert fraction and mean probability are
    pmean'd over the axes before the product, so the value is invariant
    to token-shard layout (the loss of the GLOBAL batch, identical to
    computing it locally over the gathered logits; equal-sized shards
    assumed, as everywhere in the plan).
    """
    n_experts = logits.shape[-1]
    probs = jax.nn.softmax(logits, axis=-1)
    # fraction of tokens whose top-1 choice is each expert
    top1 = jax.nn.one_hot(jnp.argmax(probs, -1), n_experts, dtype=probs.dtype)
    frac = top1.mean(axis=0)
    mean_prob = probs.mean(axis=0)
    if axis_name is not None:
        frac = lax.pmean(frac, axis_name)
        mean_prob = lax.pmean(mean_prob, axis_name)
    return n_experts * jnp.sum(frac * mean_prob)


def routing_stats(logits: jax.Array, capacity: int, k: int = 1) -> dict:
    """Drop/pad accounting for one routing pass (shard-local; callers
    inside ``shard_map`` psum the counts over the expert axis —
    :func:`moe_layer_local` with ``return_stats=True`` does).

    Returns float32 scalars/vectors (so they ride the plan's metric
    pmean): ``expert_load`` ``[n_experts]`` kept-token counts per
    expert, ``dropped`` (capacity-overflow assignments, the tokens the
    residual path carries), ``padded`` (empty queue slots shipped over
    the wire anyway — the static-shape tax), and ``capacity``.
    """
    n_experts = logits.shape[-1]
    sentinel = n_experts * capacity
    load = jnp.zeros((n_experts,), jnp.float32)
    dropped = jnp.zeros((), jnp.float32)
    for slot, _ in route_slots(logits, capacity, k):
        kept = slot != sentinel
        expert = jnp.where(kept, slot // capacity, 0)
        load = load + jnp.where(
            kept[:, None],
            jax.nn.one_hot(expert, n_experts, dtype=jnp.float32),
            0.0,
        ).sum(0)
        dropped = dropped + (~kept).astype(jnp.float32).sum()
    return {
        "expert_load": load,
        "dropped": dropped,
        "padded": jnp.float32(sentinel) - load.sum(),
        "capacity": jnp.float32(capacity),
    }


def route_slots(
    logits: jax.Array,  # [tokens, n_experts]
    capacity: int,
    k: int = 1,
):
    """Index-form routing: the same Switch/GShard bookkeeping as
    :func:`top1_route` / :func:`topk_route`, but returning per-choice
    ``(slot, gate)`` pairs instead of dense ``[T, E, C]`` tensors.

    ``slot[t] = expert[t]*capacity + queue_pos[t]`` for kept tokens and
    the sentinel ``n_experts*capacity`` for dropped ones; ``gate`` carries
    the (k-normalised) router weight. O(T·E) bookkeeping, nothing O(T·E·C).
    """
    n_experts = logits.shape[-1]
    if k > n_experts:
        raise ValueError(f"k={k} exceeds n_experts={n_experts}")
    probs = jax.nn.softmax(logits, axis=-1)
    sentinel = n_experts * capacity

    if k == 1:
        expert = jnp.argmax(probs, axis=-1)
        gate = jnp.take_along_axis(probs, expert[:, None], axis=-1)[:, 0]
        onehot = jax.nn.one_hot(expert, n_experts, dtype=jnp.int32)
        pos = ((jnp.cumsum(onehot, axis=0) - 1) * onehot).sum(-1)
        keep = pos < capacity
        slot = jnp.where(keep, expert * capacity + pos, sentinel)
        return [(slot, gate)]

    # Top-k selection in LOGIT space with an explicit taken-mask:
    # prob-space masking re-selects expert 0 when remaining softmax mass
    # underflows (diverged router), and -inf masking alone still re-picks
    # a taken expert when the CALLER pads disallowed experts with -inf. A
    # duplicate pick (only possible when every untaken expert is -inf) is
    # zeroed outright — no queue slot, no gate weight. Queue bookkeeping
    # stays int32: a low-precision logits dtype must never round slot
    # indices (bf16 cumsum collides queue slots past 256 tokens).
    taken = jnp.zeros_like(logits, dtype=jnp.int32)
    chosen = []
    for _ in range(k):
        avail = jnp.where(taken > 0, -jnp.inf, logits)
        expert = jnp.argmax(avail, axis=-1)
        onehot = jax.nn.one_hot(expert, n_experts, dtype=jnp.int32)
        onehot = onehot * (1 - taken)
        gate = (probs * onehot).sum(-1)
        chosen.append((expert, onehot, gate))
        taken = taken + onehot

    denom = sum(g for _, _, g in chosen) + 1e-9
    counts = jnp.zeros((n_experts,), jnp.int32)
    out = []
    for expert, onehot, gate in chosen:
        pos = (jnp.cumsum(onehot, axis=0) - 1) * onehot + counts[None, :]
        pos_tok = (pos * onehot).sum(-1)
        keep = (pos_tok < capacity) & (onehot.sum(-1) > 0)
        slot = jnp.where(keep, expert * capacity + pos_tok, sentinel)
        out.append((slot, gate / denom))
        counts = counts + (onehot * keep[:, None]).sum(0)
        counts = jnp.minimum(counts, capacity)
    return out


def dispatch_einsum(x, logits, capacity, k):
    """Dense one-hot dispatch (reference): builds ``[T, E, C]`` dispatch /
    combine tensors. Returns ``(queues [E, C, d], combine_fn)`` where
    ``combine_fn(back [E, C, d]) -> [T, d]``."""
    if k == 1:
        dispatch, combine = top1_route(logits, capacity)
    else:
        dispatch, combine = topk_route(logits, capacity, k)
    queues = jnp.einsum("td,tec->ecd", x, dispatch)

    def combine_fn(back):
        return jnp.einsum("ecd,tec->td", back, combine)

    return queues, combine_fn


def dispatch_sort(x, logits, capacity, k):
    """Index-based dispatch: queue assembly is one int scatter of slot ids
    plus one row gather — O(T·d + E·C·d) work and memory, no ``[T, E, C]``
    tensor anywhere (the scalable form at LM scale, where the dense form's
    O(T·E·C·d) dispatch einsum dominates the layer).

    Same routing bookkeeping as :func:`dispatch_einsum` (via
    :func:`route_slots`), so results are identical. Returns the same
    ``(queues, combine_fn)`` pair."""
    tokens, d = x.shape
    n_experts = logits.shape[-1]
    slots = route_slots(logits, capacity, k)
    sentinel = n_experts * capacity
    # Match the einsum path's promotion semantics exactly: its queue einsum
    # promotes (x, dispatch[logits.dtype]) and its combine einsum promotes
    # (back, combine[f32-promoted gates]) — switching dispatch_impl must
    # not change dtypes or gate precision.
    q_dtype = jnp.promote_types(x.dtype, logits.dtype)

    # token_of_slot: which token fills each queue slot (sentinel-initialised
    # so empty slots gather the zero row). Dropped tokens write the
    # sentinel slot, which is sliced off.
    token_of_slot = jnp.full((sentinel + 1,), tokens, jnp.int32)
    for slot, _ in slots:
        token_of_slot = token_of_slot.at[slot].set(
            jnp.arange(tokens, dtype=jnp.int32)
        )
    x_pad = jnp.concatenate([x, jnp.zeros((1, d), x.dtype)]).astype(q_dtype)
    queues = x_pad[token_of_slot[:sentinel]].reshape(n_experts, capacity, d)

    def combine_fn(back):
        gate_dtype = slots[0][1].dtype
        out_dtype = jnp.promote_types(back.dtype, gate_dtype)
        flat = jnp.concatenate(
            [back.reshape(sentinel, d),
             jnp.zeros((1, d), back.dtype)]
        ).astype(out_dtype)
        out = jnp.zeros((tokens, d), out_dtype)
        for slot, gate in slots:
            out = out + flat[slot] * gate[:, None].astype(out_dtype)
        return out

    return queues, combine_fn


_DISPATCH = {"einsum": dispatch_einsum, "sort": dispatch_sort}


def resolve_dispatch_impl(
    tokens: int, n_experts: int, d_model: int, dtype,
    impl: str = "auto",
) -> str:
    """Device-aware dispatch choice, through the autotune registry
    (:mod:`chainermn_tpu.tuning`), keyed on ``(device_kind,
    bucket(T, E, d), dtype)``.

    Measured crossover the default table encodes (r5 bench artifacts):
    sort is 167.8x the einsum path on the CPU proxy (T2048xE8xD64) but
    only 1.63x on TPU v5e at the production shape (T16384xE16xD512) —
    einsum-competitive there, dominant nowhere measured, so the table
    says ``sort`` for every backend and the persistent cache (seeded
    from on-chip sweeps) owns any shape bucket where the dense form
    wins. ``impl`` other than ``"auto"`` short-circuits (explicit
    caller choice is never overridden).
    """
    if impl != "auto":
        return impl
    from chainermn_tpu import tuning

    key = tuning.decision_key(shape=(tokens, n_experts, d_model),
                              dtype=dtype)
    return tuning.choice("moe_dispatch", ("sort", "einsum"), key)


def moe_capacity(
    tokens: int, n_experts: int, k: int,
    capacity_factor: Optional[float],
) -> int:
    """The static per-expert queue depth: ``ceil(tokens*k/n_experts *
    capacity_factor)``, floored at 1 (``capacity_factor=0`` is the
    legal minimal-capacity extreme: one slot per expert, everything
    else drops to the residual). ``capacity_factor=None`` means NO-DROP
    capacity (``tokens`` — the worst case of every local token choosing
    the same expert), the serving contract: routing decouples across
    co-resident rows, so streams stay bit-identical to sequential
    ``generate`` whatever else shares the batch."""
    import math

    if capacity_factor is None:
        return max(1, tokens)
    if capacity_factor < 0:
        raise ValueError(
            f"capacity_factor must be >= 0 (or None for no-drop), got "
            f"{capacity_factor}"
        )
    return max(1, math.ceil(tokens * k / n_experts * capacity_factor))


def resolve_expert_parallel(
    tokens: int, n_experts: int, d_model: int, dtype,
    choice: str = "auto",
) -> str:
    """``'on'``/``'off'`` — whether this MoE workload should spread over
    an ``'expert'`` mesh axis (two all_to_alls per layer, experts
    sharded) or stay replicated-local (every shard hosts every expert,
    zero collectives). Resolved through the autotune registry (decision
    ``expert_parallel``, keyed like ``moe_dispatch``); the table says
    ``off`` everywhere — spreading must EARN adoption through bench's
    ``moe`` phase step-time rows (spread-gated, the spec_tokens
    precedent), because on a single host the a2a pair is pure overhead
    and only a real multi-chip capture can price the HBM-per-expert win
    honestly. ``choice`` other than ``'auto'`` short-circuits."""
    if choice != "auto":
        return choice
    from chainermn_tpu import tuning

    key = tuning.decision_key(shape=(tokens, n_experts, d_model),
                              dtype=dtype)
    return tuning.choice("expert_parallel", ("off", "on"), key)


def moe_layer_local(
    x: jax.Array,              # [tokens_local, d_model]
    router_w: jax.Array,       # [d_model, n_experts_global]
    expert_fn: Callable,       # expert_fn(params, x[capacity, d]) -> same
    expert_params: PyTree,     # THIS shard's expert params
    axis_name: str = "expert",
    *,
    capacity_factor: Optional[float] = 1.25,
    k: int = 1,
    dispatch_impl: str = "auto",
    experts_per_shard: int = 1,
    return_stats: bool = False,
    stats_axes=None,
):
    """One MoE layer inside ``shard_map``: ``experts_per_shard`` experts
    per shard along ``axis_name`` (global expert ``e`` lives on shard
    ``e // experts_per_shard``); tokens ride two ``all_to_all``s. ``k=1``
    is Switch-style top-1 routing, ``k=2`` GShard-style top-2 (capacity
    scales with k).

    ``dispatch_impl``: ``'einsum'`` (dense one-hot [T,E,C] tensors — the
    reference form, fine at test scale), ``'sort'`` (index scatter +
    gather, O(T·d) — the scalable form; same routing, same numbers), or
    ``'auto'`` (default): device-aware choice via the autotune registry
    — see :func:`resolve_dispatch_impl` for the measured crossover the
    default encodes. Either impl is numerically identical (tested), so
    the choice is pure performance.

    ``experts_per_shard > 1``: ``expert_params`` leaves stack a leading
    ``[experts_per_shard, ...]`` dim (:func:`make_expert_params` over
    this shard's slice) and ``expert_fn`` is vmapped over it; the
    ``all_to_all`` ships ``experts_per_shard`` queues per peer, so the
    collective count is UNCHANGED (still exactly two per layer).

    ``capacity_factor=None`` selects no-drop capacity (see
    :func:`moe_capacity`).

    Returns the combined expert outputs for the local tokens (zeros for
    dropped tokens — add the residual outside); with
    ``return_stats=True``, ``(out, aux)`` where ``aux`` carries the
    layout-invariant ``load_balance`` loss plus :func:`routing_stats`
    totals psum'd over ``stats_axes`` (``expert_load`` ``[n_experts]``,
    ``dropped``, ``padded``, ``capacity`` — float32). ``stats_axes``
    defaults to ``axis_name`` but under a composed plan must name EVERY
    axis the token dim shards over (``dp_axes + ('expert',)``) or the
    aux loss is the mean of per-data-shard values, not the global one.
    """
    n = lax.axis_size(axis_name)
    eps = int(experts_per_shard)
    tokens, d = x.shape
    e_global = n * eps
    if router_w.shape[-1] != e_global:
        raise ValueError(
            f"router_w scores {router_w.shape[-1]} experts but the "
            f"'{axis_name}' axis hosts {e_global} "
            f"({n} shards x {eps} experts/shard)"
        )
    capacity = moe_capacity(tokens, e_global, k, capacity_factor)

    logits = x @ router_w  # [tokens, e_global]
    impl = resolve_dispatch_impl(tokens, e_global, d, x.dtype,
                                 dispatch_impl)
    queues, combine_fn = _DISPATCH[impl](x, logits, capacity, k)

    # Exchange: shard i sends queue rows [j*eps:(j+1)*eps] to shard j,
    # receives ITS experts' queues from every shard
    # -> [n(senders) * eps, capacity, d], sender-major
    recv = lax.all_to_all(queues, axis_name, split_axis=0, concat_axis=0,
                          tiled=True)
    recv = recv.reshape(n, eps, capacity, d).transpose(1, 0, 2, 3)
    if eps == 1:
        # one expert per shard: keep the original expert_fn contract
        # (params un-stacked, one MXU-batched call over n*capacity rows)
        out = expert_fn(expert_params, recv.reshape(n * capacity, d))
        out = out.reshape(1, n, capacity, d)
    else:
        out = jax.vmap(expert_fn)(
            expert_params, recv.reshape(eps, n * capacity, d)
        ).reshape(eps, n, capacity, d)
    # restore global-expert-major order for the return trip
    out = out.transpose(1, 0, 2, 3).reshape(e_global, capacity, d)
    back = lax.all_to_all(out, axis_name, split_axis=0, concat_axis=0,
                          tiled=True)
    combined = combine_fn(back)
    if not return_stats:
        return combined
    stats = routing_stats(logits, capacity, k)
    red = axis_name if stats_axes is None else tuple(stats_axes)
    aux = {
        "load_balance": load_balancing_loss(logits, red),
        "expert_load": lax.psum(stats["expert_load"], red),
        "dropped": lax.psum(stats["dropped"], red),
        "padded": lax.psum(stats["padded"], red),
        "capacity": stats["capacity"],
    }
    return combined, aux


def record_moe_dispatch(stats, *, layer: Optional[int] = None) -> None:
    """Emit one ``moe_dispatch`` trace event from a host-fetched MoE
    stats/aux mapping (ISSUE 20 observability row).

    ``stats`` is the dict :func:`routing_stats` (or the ``aux`` of
    ``moe_layer_local(..., return_stats=True)`` / the plan's
    ``moe_layer`` metrics) returns: ``expert_load`` ``[n_experts]``,
    ``dropped``, ``padded``, ``capacity``. Values may still be device
    arrays — they are fetched here, so call this OUTSIDE jit, after the
    step that produced them (trace events cannot fire from compiled
    code; same host-side-mirror shape as the scheduler's ``serving``
    events). No-op when no recorder is active; never raises into the
    training/serving loop.

    The metrics tap mirrors the event as ``moe_dropped_tokens_total`` /
    ``moe_padded_tokens_total`` counters and per-expert
    ``moe_expert_load`` / ``moe_capacity`` gauges
    (docs/observability.md name table)."""
    try:
        from chainermn_tpu.observability import trace as _trace

        rec = _trace.active()
    except Exception:
        return
    if rec is None:
        return
    try:
        import numpy as _np

        load = _np.asarray(
            jax.device_get(stats["expert_load"]), dtype=_np.float64
        ).ravel()
        fields = {
            "expert_load": [round(float(v), 3) for v in load],
            "n_experts": int(load.size),
            "dropped": round(float(jax.device_get(stats["dropped"])), 3),
            "padded": round(float(jax.device_get(stats["padded"])), 3),
            "capacity": float(jax.device_get(stats["capacity"])),
        }
        if layer is not None:
            fields["layer"] = int(layer)
        rec.event("moe_dispatch", **fields)
    except Exception:
        pass


def make_expert_params(init_fn: Callable, rng: jax.Array, n_experts: int):
    """Stack ``n_experts`` independently-initialised expert param trees
    along a leading axis (shard over the ``'expert'`` mesh axis)."""
    rngs = jax.random.split(rng, n_experts)
    trees = [init_fn(r) for r in rngs]
    return jax.tree.map(lambda *ls: jnp.stack(ls), *trees)
