"""Expert parallelism (MoE) — all_to_all token routing over an ``'expert'``
mesh axis.

Absent from the reference (SURVEY.md section 2.2 lists EP as the optional
TPU-era extension). Mechanism: each shard hosts one (or more) experts; a
top-1 router scores tokens, tokens travel to their expert's shard via
``all_to_all``, the expert MLP runs, and a second ``all_to_all`` returns
outputs — the same two-collective shape as Ulysses sequence parallelism,
with capacity-bounded dispatch making every shape static for XLA.

Capacity discipline (the TPU answer to ragged routing): each expert
processes at most ``capacity = ceil(tokens/experts * capacity_factor)``
tokens per shard; overflow tokens are dropped (standard Switch-style
routing) and their outputs fall back to zero — callers add the residual
path so dropped tokens pass through unchanged.

Differentiable end to end: routing uses straight-through softmax gating
(gradient flows through the gate probability), and ``all_to_all`` has an
exact transpose.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

PyTree = Any


def top1_route(
    logits: jax.Array,  # [tokens, n_experts]
    capacity: int,
):
    """Switch-style top-1 routing with capacity.

    Returns:
      dispatch: ``[tokens, n_experts, capacity]`` one-hot dispatch mask.
      combine:  same shape, dispatch * gate probability (for the return
        trip, carries the gradient to the router).
    """
    n_experts = logits.shape[-1]
    probs = jax.nn.softmax(logits, axis=-1)
    expert = jnp.argmax(probs, axis=-1)  # [tokens]
    gate = jnp.take_along_axis(probs, expert[:, None], axis=-1)[:, 0]

    onehot = jax.nn.one_hot(expert, n_experts, dtype=jnp.int32)
    # position of each token within its expert's queue
    pos = jnp.cumsum(onehot, axis=0) * onehot - 1  # [tokens, experts]
    pos = pos.max(axis=-1)  # [tokens]
    keep = pos < capacity

    dispatch = (
        jax.nn.one_hot(expert, n_experts, dtype=logits.dtype)[:, :, None]
        * jax.nn.one_hot(pos, capacity, dtype=logits.dtype)[:, None, :]
    )
    dispatch = dispatch * keep[:, None, None].astype(logits.dtype)
    combine = dispatch * gate[:, None, None]
    return dispatch, combine


def moe_layer_local(
    x: jax.Array,              # [tokens_local, d_model]
    router_w: jax.Array,       # [d_model, n_experts_global]
    expert_fn: Callable,       # expert_fn(params, x[capacity, d]) -> same
    expert_params: PyTree,     # THIS shard's expert params
    axis_name: str = "expert",
    *,
    capacity_factor: float = 1.25,
) -> jax.Array:
    """One MoE layer inside ``shard_map``: one expert per shard along
    ``axis_name``; tokens ride two ``all_to_all``s.

    Returns the combined expert outputs for the local tokens (zeros for
    dropped tokens — add the residual outside).
    """
    import math

    n = lax.axis_size(axis_name)
    tokens, d = x.shape
    capacity = max(1, math.ceil(tokens / n * capacity_factor))

    logits = x @ router_w  # [tokens, n]
    dispatch, combine = top1_route(logits, capacity)

    # Gather each expert's queue locally: [n, capacity, d]
    queues = jnp.einsum("td,tec->ecd", x, dispatch)
    # Exchange: shard i sends queue row e to shard e, receives its own
    # expert's queue from every shard -> [n(senders), capacity, d]
    recv = lax.all_to_all(queues, axis_name, split_axis=0, concat_axis=0,
                          tiled=True)
    # Run THIS shard's expert on all n*capacity tokens at once (MXU-batched)
    out = expert_fn(expert_params, recv.reshape(n * capacity, d))
    out = out.reshape(n, capacity, d)
    # Return trip + weighted combine back into token order
    back = lax.all_to_all(out, axis_name, split_axis=0, concat_axis=0,
                          tiled=True)
    return jnp.einsum("ecd,tec->td", back, combine)


def make_expert_params(init_fn: Callable, rng: jax.Array, n_experts: int):
    """Stack ``n_experts`` independently-initialised expert param trees
    along a leading axis (shard over the ``'expert'`` mesh axis)."""
    rngs = jax.random.split(rng, n_experts)
    trees = [init_fn(r) for r in rngs]
    return jax.tree.map(lambda *ls: jnp.stack(ls), *trees)
