"""Genuinely concurrent host-plane gradient reduction (round-5 VERDICT
ask #6: a MEASURED case where double buffering pays).

The in-jit ``double_buffering`` flag removes the data dependency between
step *t*'s parameter update and step *t*'s collective (certified
structurally in ``tests/test_optimizer.py``), but whether that turns
into wall-clock speedup is the RUNTIME's call: XLA:TPU's async
collectives can exploit it on a multi-chip mesh; XLA:CPU emits
synchronous ``all-reduce`` and a single chip's psum is a no-op — neither
can show the win (see docs/benchmarks.md "when to enable it").

This module is the overlap made explicit, on the plane where this
environment HAS real communication latency: the C++ framed-TCP host mesh
(the reference's MPI role — ``communicators/_host_comm.py``,
``native/src/host_comm.cpp``). A background thread runs the host-plane
allreduce of step *t*'s gradients while the main thread computes step
*t+1*; the caller applies the reduced gradients one step stale — exactly
the reference ``_DoubleBufferingOptimizer``'s staleness-1 semantics
(``optimizers.py`` †) with the side-stream overlap made literal (thread
instead of CUDA stream; socket I/O and the XLA compute both release the
GIL, so the overlap is real parallelism, not cooperative scheduling).

Measured: ``tests/test_multiprocess.py::test_mp_async_double_buffer_overlap``
runs the sequential (compute → blocking allreduce) and double-buffered
(compute ∥ previous allreduce) loops over 4 real processes — identical
compute and identical wire bytes in both variants by construction — and
asserts the overlap speedup.

When to use WHICH double buffering:

- multi-chip TPU mesh, gradient allreduce in-program → the in-jit flag
  (``create_multi_node_optimizer(double_buffering=True)``); XLA overlaps.
- gradients crossing a host-plane/DCN wire outside the jitted program
  (parameter-server-ish deployments, the mp harness, debugging rigs) →
  this reducer.
"""

from __future__ import annotations

import threading
from typing import Any

import jax
import numpy as np

__all__ = ["AsyncHostGradReducer"]


def _tree_sum(a: Any, b: Any) -> Any:
    return jax.tree.map(lambda x, y: x + y, a, b)


class AsyncHostGradReducer:
    """Staleness-1 gradient reduction over the host plane, with the
    collective running on a background thread.

    Usage (the double-buffered loop)::

        reducer = AsyncHostGradReducer(comm)
        for batch in data:
            grads = compute_grads(params, batch)       # step t
            stale = reducer.exchange(grads)            # t-1's mean, or
            if stale is not None:                      # None on step 0
                params = apply(params, stale)

    ``exchange`` submits this step's gradients and returns the PREVIOUS
    step's reduced mean — collecting it first, so at most one reduction
    is ever in flight. ``flush()`` drains the pipeline (returns the last
    submitted reduction; call once after the loop so no gradient is
    dropped).

    **Host-plane exclusivity (hard constraint):** while a reduction is
    in flight (``in_flight`` is True — between ``exchange``/``_submit``
    and the next collect), NO other host-plane traffic may be issued
    from any thread on any rank: the framed-TCP channels are untagged
    per-pair FIFOs, so a concurrent ``allreduce_obj``/``barrier`` from
    the main thread interleaves frames with the background reduction
    and deadlocks or mis-delivers (the same wildcard-vs-collective
    ordering constraint the eager p2p API documents). Do host-plane
    logging/metrics either before ``exchange`` or after ``flush`` —
    never between. The drill in ``tests/mp_worker.py`` follows this
    discipline.
    """

    def __init__(self, comm, *, average: bool = True,
                 simulated_dcn_latency_s: float = 0.0) -> None:
        self._host = comm.host
        self._n = comm.host.size
        self._average = average
        self._latency = simulated_dcn_latency_s
        self._thread: threading.Thread | None = None
        self._result: Any = None
        self._error: BaseException | None = None

    # -- internals -----------------------------------------------------

    def _run(self, grads_np) -> None:
        try:
            import time

            t_floor = time.perf_counter() + self._latency
            total = self._host.allreduce_obj(grads_np, op=_tree_sum)
            if self._average:
                total = jax.tree.map(lambda x: x / self._n, total)
            if self._latency > 0.0:
                # RTT floor: on loopback the framed-TCP round trip is
                # CPU-cheap; a DCN hop is a genuine in-flight WAIT. The
                # floor models that wait (GIL released, like a socket
                # block), letting single-core hosts exhibit the overlap
                # a real cross-host wire would show. Applied to the
                # sync baseline identically (reduce_sync shares this
                # path), so the comparison stays like-for-like.
                remaining = t_floor - time.perf_counter()
                if remaining > 0:
                    time.sleep(remaining)
            self._result = total
        except BaseException as e:  # surfaced on the caller's thread
            self._error = e

    def _submit(self, grads) -> None:
        assert self._thread is None, "a reduction is already in flight"
        # Host-side snapshot BEFORE the thread starts: the caller is free
        # to donate/overwrite the device buffers afterwards.
        grads_np = jax.tree.map(lambda g: np.asarray(g), grads)
        self._thread = threading.Thread(
            target=self._run, args=(grads_np,), daemon=True
        )
        self._thread.start()

    def _collect(self) -> Any:
        if self._thread is None:
            return None
        self._thread.join()
        self._thread = None
        if self._error is not None:
            err, self._error = self._error, None
            raise err
        out, self._result = self._result, None
        return out

    # -- public --------------------------------------------------------

    @property
    def in_flight(self) -> bool:
        """True while a background reduction owns the host plane — see
        the exclusivity constraint in the class docstring."""
        return self._thread is not None

    def exchange(self, grads) -> Any:
        """Collect step *t-1*'s reduced mean (None on the first call),
        then launch step *t*'s reduction in the background."""
        prev = self._collect()
        self._submit(grads)
        return prev

    def flush(self) -> Any:
        """Drain the in-flight reduction (the final step's mean)."""
        return self._collect()

    def reduce_sync(self, grads) -> Any:
        """The sequential baseline: same wire, same bytes, blocking —
        what the double-buffered loop is measured against."""
        self._submit(grads)
        return self._collect()
