"""Placeholder datasets for model-parallel ranks.

Reference: ``chainermn/datasets/empty_dataset.py`` (dagger)
``create_empty_dataset`` (SURVEY.md section 2.6): a same-length dataset of
``None``s for ranks that receive activations, not data — keeps the iterator
machinery (epoch lengths, progress) consistent across ranks.
"""

from __future__ import annotations

from typing import Any, Sequence


class _EmptyDataset:
    def __init__(self, length: int) -> None:
        self._length = length

    def __len__(self) -> int:
        return self._length

    def __getitem__(self, i):
        if isinstance(i, slice):
            return [None] * len(range(*i.indices(self._length)))
        if not -self._length <= i < self._length:
            raise IndexError(i)
        return None

    def __iter__(self):
        return iter([None] * self._length)


def create_empty_dataset(dataset: Sequence[Any]) -> _EmptyDataset:
    """An all-``None`` dataset with the same length as ``dataset``."""
    return _EmptyDataset(len(dataset))
