"""Dataset scattering by index arithmetic.

Reference: ``chainermn/datasets/scatter_dataset.py`` (dagger) (SURVEY.md
sections 2.6, 3.3): rank 0 permutes indices with a seed, slices into
``comm.size`` near-equal contiguous chunks, and *pickles each rank's
SubDataset over MPI*.

TPU-native design (SURVEY.md section 3.3 "TPU mapping"): **no data moves at
all.** Every process computes its own ``(begin, end)`` slice of the same
seeded permutation from ``comm.rank``; only the seed needs agreement, done
with one tiny ``bcast_obj`` when the caller doesn't fix it. The result is
bit-identical to the reference's scatter (same permutation, same chunking)
without serialising the dataset.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import numpy as np

from chainermn_tpu.communicators.base import CommunicatorBase


class SubDataset:
    """A view of ``dataset`` restricted to ``indices`` — the role of
    Chainer's ``SubDataset`` that the reference scattered to each rank."""

    def __init__(self, dataset: Sequence[Any], indices: np.ndarray) -> None:
        self._dataset = dataset
        self.indices = np.asarray(indices)

    def __len__(self) -> int:
        return len(self.indices)

    def __getitem__(self, i):
        if isinstance(i, slice):
            return [self._dataset[int(j)] for j in self.indices[i]]
        return self._dataset[int(self.indices[i])]

    def __iter__(self):
        for j in self.indices:
            yield self._dataset[int(j)]


def _shard_bounds(n: int, size: int, rank: int) -> tuple[int, int]:
    """Near-equal contiguous chunking, first ``n % size`` shards one longer —
    the reference's balance-within-plus-minus-1 invariant (SURVEY.md
    section 4, test_scatter_dataset)."""
    base, rem = divmod(n, size)
    begin = rank * base + min(rank, rem)
    end = begin + base + (1 if rank < rem else 0)
    return begin, end


def scatter_dataset(
    dataset: Sequence[Any],
    comm: CommunicatorBase,
    *,
    shuffle: bool = False,
    seed: Optional[int] = None,
    root: int = 0,
    force_equal_length: bool = False,
    rank: Optional[int] = None,
    size: Optional[int] = None,
) -> SubDataset:
    """Return this rank's shard of ``dataset``.

    Args:
      shuffle, seed: seeded global permutation before chunking (all ranks
        derive the same permutation; if ``seed`` is None it is chosen on
        ``root`` and broadcast — the only communication this function does).
      force_equal_length: pad short shards by wrapping (keeps per-step batch
        shapes static across ranks — on TPU this also avoids recompilation).
      rank/size: override the sharding granularity; defaults to the host
        plane (``comm.rank``/``comm.host.size``), since in SPMD one process
        loads data for all its local devices and the mesh shards the batch.
    """
    n = len(dataset)
    size = comm.host.size if size is None else size
    rank = comm.rank if rank is None else rank

    if shuffle:
        if seed is None:
            seed = int(np.random.randint(0, 2**31 - 1)) if comm.rank == root else 0
            seed = comm.bcast_obj(seed, root)
        order = np.random.RandomState(seed).permutation(n)
    else:
        order = np.arange(n)

    begin, end = _shard_bounds(n, size, rank)
    indices = order[begin:end]
    if force_equal_length and n > 0:
        target = -(-n // size)  # ceil
        if len(indices) == 0:
            # More ranks than examples: wrap around the global order so the
            # shard still yields `target` items (static batch shapes — no
            # rank may come up empty or collectives hang / recompile).
            indices = order[(begin + np.arange(target)) % n]
        elif len(indices) < target:
            reps = -(-target // len(indices))
            indices = np.tile(indices, reps)[:target]
    return SubDataset(dataset, indices)
