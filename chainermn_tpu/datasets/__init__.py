"""Data layer: dataset scattering and placeholders.

Reference: ``chainermn/datasets/`` (dagger) (SURVEY.md sections 2.6, 3.3).
"""

from chainermn_tpu.datasets.scatter_dataset import scatter_dataset, SubDataset
from chainermn_tpu.datasets.empty_dataset import create_empty_dataset

__all__ = ["scatter_dataset", "SubDataset", "create_empty_dataset"]
