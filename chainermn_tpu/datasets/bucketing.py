"""Length bucketing + padding for variable-length data under ``jit``.

The reference leaned on define-by-run: every ragged batch just ran
(``examples/seq2seq/seq2seq.py`` (dagger) sorted/padded ad hoc). Under XLA
each distinct shape is a separate compilation, so the framework needs a
*discipline*: round sequence lengths up to a small fixed set of bucket
lengths. Compile count is then bounded by ``len(buckets)`` while padding
waste stays bounded by the bucket spacing (SURVEY.md section 7 "hard
parts": variable-length/dynamic shapes under jit).
"""

from __future__ import annotations

from typing import Iterable, List, Sequence, Tuple

import numpy as np

#: power-of-two-ish default ladder; dense at short lengths where MT data lives
DEFAULT_BUCKETS = (16, 32, 64, 128, 256, 512)


def bucket_length(n: int, buckets: Sequence[int] = DEFAULT_BUCKETS) -> int:
    """Smallest bucket >= n (sequences longer than the last bucket are
    truncated to it — callers choose buckets to make this rare)."""
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


def pad_to(seq, length: int, pad_id: int = 0) -> Tuple[np.ndarray, np.ndarray]:
    """Pad/truncate one sequence to ``length``; returns (tokens, mask)."""
    seq = np.asarray(seq[:length], dtype=np.int32)
    out = np.full((length,), pad_id, np.int32)
    mask = np.zeros((length,), np.float32)
    out[: len(seq)] = seq
    mask[: len(seq)] = 1.0
    return out, mask


def bucket_batches(
    pairs: Iterable[Tuple[Sequence[int], Sequence[int]]],
    batch_size: int,
    *,
    buckets: Sequence[int] = DEFAULT_BUCKETS,
    pad_id: int = 0,
    drop_remainder: bool = True,
) -> Iterable[dict]:
    """Group (src, tgt) token-sequence pairs into padded fixed-shape batches.

    Each pair is assigned the bucket of ``max(len(src), len(tgt))``; batches
    are emitted per-bucket when full. Yields dicts with ``src``/``tgt``
    int32 arrays ``[batch, bucket]`` and float32 ``src_mask``/``tgt_mask``.
    Only ``len(buckets)`` distinct shapes ever reach ``jit``.
    """
    pools: dict[int, List[Tuple]] = {}
    for src, tgt in pairs:
        b = bucket_length(max(len(src), len(tgt)), buckets)
        pools.setdefault(b, []).append((src, tgt))
        pool = pools[b]
        if len(pool) == batch_size:
            yield _emit(pool, b, pad_id, len(pool))
            pools[b] = []
    if not drop_remainder:
        for b, pool in pools.items():
            if pool:
                # pad the batch dim up with repeats so the shape stays fixed
                n_real = len(pool)
                while len(pool) < batch_size:
                    pool.append(pool[-1])
                yield _emit(pool, b, pad_id, n_real)


def _emit(pool, bucket: int, pad_id: int, n_real: int) -> dict:
    srcs, tgts, sms, tms = [], [], [], []
    for s, t in pool:
        ps, ms = pad_to(s, bucket, pad_id)
        pt, mt = pad_to(t, bucket, pad_id)
        srcs.append(ps)
        tgts.append(pt)
        sms.append(ms)
        tms.append(mt)
    return {
        "src": np.stack(srcs),
        "tgt": np.stack(tgts),
        "src_mask": np.stack(sms),
        "tgt_mask": np.stack(tms),
        "bucket": bucket,
        # Eval-side extras: the ragged originals (BLEU references) and the
        # real row count — rows past n_real are shape-keeping repeats and
        # must not enter corpus statistics.
        "tgt_raw": [list(t) for _, t in pool],
        "n_real": n_real,
    }
