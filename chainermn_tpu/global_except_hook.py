"""Global exception hook: one crashed process kills the whole job.

Reference: ``chainermn/global_except_hook.py`` (dagger) (SURVEY.md sections
2.7, 5): installs a ``sys.excepthook`` that prints the traceback and calls
``MPI_Abort(MPI_COMM_WORLD)`` so a single rank's Python exception tears the
job down instead of leaving the other ranks hung inside a collective.

TPU-native: the JAX distributed runtime's coordinator already propagates
process death; the remaining gap is *prompt* teardown when Python raises
outside any JAX call. The hook prints a rank-tagged traceback, attempts a
clean ``jax.distributed.shutdown()``, then hard-exits so the coordinator
declares this process dead and peers abort their pending collectives.
"""

from __future__ import annotations

import os
import sys
import traceback

_hook_installed = False


def _global_except_hook(exctype, value, tb) -> None:
    try:
        rank = None
        try:
            import jax

            rank = jax.process_index()
            nprocs = jax.process_count()
        except Exception:
            nprocs = None
        sys.stderr.write("\n*****************************************************\n")
        if rank is not None:
            sys.stderr.write(
                f"chainermn_tpu: uncaught exception on process {rank}"
                + (f"/{nprocs}" if nprocs else "")
                + "\n"
            )
        traceback.print_exception(exctype, value, tb)
        sys.stderr.write("*****************************************************\n\n")
        sys.stderr.flush()
        if nprocs is not None and nprocs > 1:
            # BOUNDED clean-shutdown attempt: jax.distributed.shutdown()
            # waits at a coordination shutdown barrier for ALL tasks —
            # but the peers cannot reach it, they are blocked in
            # collectives waiting on THIS process. Unbounded, that is a
            # deadlock: our sockets stay open, peers never get EOF,
            # nobody exits (measured in the crash-teardown drill: 3-way
            # wedge until coordination timeouts, leader hung forever).
            # A daemon thread + short join keeps the attempt best-effort;
            # the hard exit below is the real MPI_Abort.
            try:
                import threading

                def _try_shutdown():
                    try:
                        import jax

                        jax.distributed.shutdown()
                    except Exception:
                        pass

                t = threading.Thread(target=_try_shutdown, daemon=True)
                t.start()
                t.join(5.0)
            finally:
                # Hard exit UNCONDITIONALLY (even if the thread could
                # not start): fds close, peers' host-plane recvs EOF,
                # their own hooks fire — death propagates promptly (the
                # reference's MPI_Abort equivalent). Falling through to
                # a normal exit would hit jax's atexit shutdown barrier
                # and re-create the deadlock.
                os._exit(1)
    except Exception:
        # The hook itself must never mask the original error.
        sys.__excepthook__(exctype, value, tb)


def _add_hook() -> None:
    """Install the hook (idempotent). Named after the reference's private
    installer; examples call this right after creating a communicator."""
    global _hook_installed
    if _hook_installed:
        return
    sys.excepthook = _global_except_hook
    _hook_installed = True
