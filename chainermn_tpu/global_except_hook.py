"""Global exception hook: one crashed process kills the whole job.

Reference: ``chainermn/global_except_hook.py`` (dagger) (SURVEY.md sections
2.7, 5): installs a ``sys.excepthook`` that prints the traceback and calls
``MPI_Abort(MPI_COMM_WORLD)`` so a single rank's Python exception tears the
job down instead of leaving the other ranks hung inside a collective.

TPU-native: the JAX distributed runtime's coordinator already propagates
process death; the remaining gap is *prompt* teardown when Python raises
outside any JAX call. The hook prints a rank-tagged traceback, attempts a
clean ``jax.distributed.shutdown()``, then hard-exits so the coordinator
declares this process dead and peers abort their pending collectives.
"""

from __future__ import annotations

import os
import sys
import traceback

_hook_installed = False


def _global_except_hook(exctype, value, tb) -> None:
    try:
        rank = None
        try:
            import jax

            rank = jax.process_index()
            nprocs = jax.process_count()
        except Exception:
            nprocs = None
        sys.stderr.write("\n*****************************************************\n")
        if rank is not None:
            sys.stderr.write(
                f"chainermn_tpu: uncaught exception on process {rank}"
                + (f"/{nprocs}" if nprocs else "")
                + "\n"
            )
        traceback.print_exception(exctype, value, tb)
        sys.stderr.write("*****************************************************\n\n")
        sys.stderr.flush()
        if nprocs is not None and nprocs > 1:
            try:
                import jax

                jax.distributed.shutdown()
            except Exception:
                pass
            # Hard exit: the coordinator notices the death and peers abort
            # (the reference's MPI_Abort equivalent).
            os._exit(1)
    except Exception:
        # The hook itself must never mask the original error.
        sys.__excepthook__(exctype, value, tb)


def _add_hook() -> None:
    """Install the hook (idempotent). Named after the reference's private
    installer; examples call this right after creating a communicator."""
    global _hook_installed
    if _hook_installed:
        return
    sys.excepthook = _global_except_hook
    _hook_installed = True
