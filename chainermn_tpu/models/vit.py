"""Vision Transformer — the modern TPU-shaped ImageNet family.

Beyond the reference (2017-era CNNs only, ``examples/imagenet`` †): a
ViT is the hardware-natural ImageNet model on TPU — the whole network is
large dense matmuls (patch embedding + encoder blocks) with none of the
small-channel convs that starve the 128-wide MXU in the ResNet stem
(see the space-to-depth discussion in :mod:`chainermn_tpu.models.resnet`).

Reuses :class:`chainermn_tpu.models.transformer.TransformerBlock` with
``causal=False`` (bidirectional encoder) — the same pluggable-attention
block that powers the LM, so flash kernels, GQA, and remat policies all
apply unchanged. Pre-LN, learned position embeddings, mean-pool or CLS
readout, bf16 compute / f32 params per the package convention.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import flax.linen as nn
import jax.numpy as jnp

from chainermn_tpu.models.transformer import (
    TransformerBlock,
    _remat_block,
)


class VisionTransformer(nn.Module):
    """ViT over ``[B, H, W, C]`` images → ``[B, num_classes]`` logits.

    Defaults are ViT-S/16 (22M params at 224²): d_model 384, 12 layers,
    6 heads, ff 1536.
    """

    num_classes: int = 1000
    patch_size: int = 16
    num_layers: int = 12
    num_heads: int = 6
    d_model: int = 384
    d_ff: int = 1536
    compute_dtype: Any = jnp.bfloat16
    attention_fn: Optional[Callable] = None
    dropout_rate: float = 0.0
    #: ``'mean'`` — global average pool of the final tokens (the simple,
    #: shift-friendly readout); ``'cls'`` — prepend a learned class token
    #: and read its final state (the original recipe).
    pool: str = "mean"
    #: rematerialize each encoder block (same policies as the LM).
    remat: bool = False
    remat_policy: str = "dots"

    @nn.compact
    def __call__(self, images, train: bool = True):
        if self.pool not in ("mean", "cls"):
            raise ValueError(f"pool must be mean|cls, got {self.pool!r}")
        B, H, W, _ = images.shape
        p = self.patch_size
        if H % p or W % p:
            raise ValueError(
                f"image size {(H, W)} not divisible by patch {p}"
            )
        # Patch embedding: one strided conv == per-patch linear; its
        # [p*p*C, d_model] matmul is MXU-shaped (768x384 at S/16).
        x = nn.Conv(
            self.d_model, kernel_size=(p, p), strides=(p, p),
            padding="VALID", dtype=self.compute_dtype,
            param_dtype=jnp.float32, name="patch_embed",
        )(images.astype(self.compute_dtype))
        x = x.reshape(B, -1, self.d_model)  # [B, N, D]
        n_tokens = x.shape[1]

        if self.pool == "cls":
            cls = self.param(
                "cls_token", nn.initializers.zeros, (1, 1, self.d_model),
                jnp.float32,
            )
            x = jnp.concatenate(
                [jnp.broadcast_to(cls, (B, 1, self.d_model)).astype(
                    self.compute_dtype), x],
                axis=1,
            )
            n_tokens += 1

        pos = self.param(
            "pos_embed",
            nn.initializers.normal(stddev=0.02),
            (1, n_tokens, self.d_model), jnp.float32,
        )
        x = x + pos.astype(self.compute_dtype)

        block = (_remat_block(self.remat_policy) if self.remat
                 else TransformerBlock)
        for i in range(self.num_layers):
            x = block(
                num_heads=self.num_heads, d_ff=self.d_ff,
                compute_dtype=self.compute_dtype,
                attention_fn=self.attention_fn,
                dropout_rate=self.dropout_rate,
                causal=False, name=f"block_{i}",
            )(x, None, None, train, False)

        x = nn.LayerNorm(
            dtype=self.compute_dtype, param_dtype=jnp.float32
        )(x)
        pooled = x[:, 0] if self.pool == "cls" else x.mean(axis=1)
        # f32 head: the classification logits feed a softmax-CE whose
        # numerics should not inherit bf16 rounding.
        return nn.Dense(
            self.num_classes, dtype=jnp.float32, param_dtype=jnp.float32,
            name="head",
        )(pooled.astype(jnp.float32))
