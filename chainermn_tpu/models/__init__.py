"""Model zoo matching the reference's example models (SURVEY.md section 2.8):
MNIST MLP, ImageNet family (AlexNet / GoogLeNet / ResNet-50), seq2seq LSTM —
plus the Transformer LM the benchmark configs add (BASELINE.json) and the
ViT-S/16 encoder family (beyond the reference: the MXU-natural ImageNet
model, built on the LM's TransformerBlock with ``causal=False``)."""

from chainermn_tpu.models.mlp import MLP
from chainermn_tpu.models.vit import VisionTransformer
from chainermn_tpu.models.imagenet import AlexNet, GoogLeNet
from chainermn_tpu.models.seq2seq import (
    Seq2Seq,
    beam_search_decode,
    greedy_decode,
    seq2seq_loss,
)
from chainermn_tpu.models.transformer import (
    TransformerLM,
    mlm_corrupt,
    mlm_loss,
    beam_search,
    generate,
    init_cache,
    lm_loss,
    lm_loss_fused,
)
from chainermn_tpu.models.resnet import (
    ResNet,
    ResNet18,
    ResNet34,
    ResNet50,
    ResNet101,
    ResNet152,
)
from chainermn_tpu.models.detection import (
    TinyDetector,
    TwoStageDetector,
    detection_loss,
    two_stage_loss,
)

__all__ = [
    "VisionTransformer",
    "MLP",
    "AlexNet",
    "GoogLeNet",
    "Seq2Seq",
    "beam_search_decode",
    "greedy_decode",
    "seq2seq_loss",
    "TransformerLM",
    "mlm_corrupt",
    "mlm_loss",
    "lm_loss",
    "lm_loss_fused",
    "generate",
    "beam_search",
    "init_cache",
    "ResNet",
    "ResNet18",
    "ResNet34",
    "ResNet50",
    "ResNet101",
    "ResNet152",
    "TinyDetector",
    "TwoStageDetector",
    "detection_loss",
    "two_stage_loss",
]
