"""Shared decoding utilities for the transformer and seq2seq beam
searches — one owner for the ranking formula so the two decoders cannot
drift."""

from __future__ import annotations

import jax.numpy as jnp


def gnmt_ranking(scores, gen_len, alpha: float):
    """GNMT length-penalized ranking values:
    ``score / ((5 + len) / 6)**alpha``.

    Well-defined for any alpha: positive counters the short-hypothesis
    bias of raw summed log-probs; negative favours shorter hypotheses;
    0 is the raw score (callers usually skip the call entirely then).
    """
    return scores / ((5.0 + gen_len.astype(jnp.float32)) / 6.0) ** alpha


def rank_beams(seqs, scores, gen_len, alpha: float):
    """Order ``(seqs [B, K, T], scores [B, K])`` best-first under the
    GNMT-penalized ranking; the returned scores stay raw."""
    order = jnp.argsort(-gnmt_ranking(scores, gen_len, alpha), axis=1)
    return (jnp.take_along_axis(seqs, order[..., None], axis=1),
            jnp.take_along_axis(scores, order, axis=1))


__all__ = ["gnmt_ranking", "rank_beams"]
