"""ResNet family — the framework's flagship/benchmark model.

Reference: ``examples/imagenet/models/resnet50.py`` (dagger) (SURVEY.md
section 2.8) — ResNet-50 was ChainerMN's headline benchmark workload (the
``BASELINE.json`` north star: scaling efficiency of ResNet-50 ImageNet on a
TPU pod slice).

TPU-first design decisions:
  - **bf16 compute, f32 state**: convolutions run in ``bfloat16`` so they tile
    onto the MXU at full rate; parameters, BatchNorm statistics and the final
    logits stay ``float32`` (master-weight discipline — the TPU analogue of
    the reference's fp16 compressed-allreduce story keeping f32 masters).
  - **Static NHWC shapes** end to end; no data-dependent control flow, so the
    whole network is one fusible XLA program.
  - **Sync BatchNorm by construction**: pass ``bn_axis_name='data'`` (or use
    :meth:`~chainermn_tpu.links.MultiNodeBatchNormalization.for_communicator`)
    and the BN statistics are ``psum``-ed over the data-parallel mesh axis —
    the reference needed a dedicated ``MultiNodeBatchNormalization`` link for
    this (``links/batch_normalization.py`` (dagger)).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

from chainermn_tpu.links.batch_normalization import MultiNodeBatchNormalization

ModuleDef = Any


class BottleneckBlock(nn.Module):
    """1x1 -> 3x3 -> 1x1 bottleneck residual block (ResNet-50/101/152)."""

    filters: int
    conv: ModuleDef
    norm: ModuleDef
    strides: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        # zero-init the last BN scale: residual branch starts as identity,
        # required for large-batch training (the regime the reference's
        # 32K-batch ImageNet runs lived in)
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters * 4, (1, 1), self.strides, name="conv_proj"
            )(residual)
            residual = self.norm(name="norm_proj")(residual)
        return nn.relu(residual + y)


class BasicBlock(nn.Module):
    """3x3 -> 3x3 residual block (ResNet-18/34)."""

    filters: int
    conv: ModuleDef
    norm: ModuleDef
    strides: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), self.strides)(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1), self.strides, name="conv_proj")(
                residual
            )
            residual = self.norm(name="norm_proj")(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    """Configurable ResNet over NHWC inputs.

    Args:
      stage_sizes: blocks per stage, e.g. ``(3, 4, 6, 3)`` for ResNet-50.
      block_cls: :class:`BottleneckBlock` or :class:`BasicBlock`.
      num_classes: classifier width.
      compute_dtype: dtype for conv/matmul compute (``bfloat16`` for the MXU).
      bn_axis_name: mesh axis (or axes tuple) to synchronize BatchNorm
        statistics over; ``None`` = local BN (single-device semantics).
    """

    stage_sizes: Sequence[int]
    block_cls: Callable
    num_classes: int = 1000
    num_filters: int = 64
    compute_dtype: Any = jnp.bfloat16
    bn_axis_name: Optional[Any] = None
    bn_momentum: float = 0.9
    #: rematerialize each residual block in the backward pass. The b128
    #: ResNet-50 train step is HBM-bandwidth-bound on one v5e chip (measured:
    #: 46 GB accessed/step ~= 57 ms at peak BW vs 15 ms of pure FLOPs), so
    #: recomputing block activations trades cheap MXU FLOPs for the bytes
    #: that actually gate throughput (SURVEY.md env note: "use
    #: jax.checkpoint/remat to trade FLOPs for memory").
    remat: bool = False
    #: remat save policy (only with ``remat=True``): ``None`` — save
    #: nothing (full recompute; measured r2: LOSES throughput, 57->66 ms,
    #: XLA re-reads block inputs more than it saves); ``'conv'`` — save
    #: conv/matmul outputs, recompute only the cheap elementwise BN
    #: normalize + relu chain: the bytes of 2 of every 3 saved tensors
    #: disappear while the recompute is VPU-trivial — the fine-grained
    #: point the whole-block policy overshoots.
    remat_policy: Optional[str] = None
    #: ``'standard'`` — the classic 7x7/s2 conv + 3x3 maxpool;
    #: ``'space_to_depth'`` — rearrange 4x4 pixel blocks into 48 channels and
    #: run a 3x3/s1 conv (the MLPerf-era TPU stem): a 3-channel conv wastes
    #: the 128-lane MXU, and the measured stem cost is ~13% of the whole b128
    #: v5e train step. Same [56, 56, 64] stem output shape; NOT
    #: weight-compatible with 'standard'.
    stem: str = "standard"

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(
            nn.Conv, use_bias=False, dtype=self.compute_dtype, param_dtype=jnp.float32
        )
        norm = partial(
            MultiNodeBatchNormalization,
            use_running_average=not train,
            momentum=self.bn_momentum,
            epsilon=1e-5,
            dtype=self.compute_dtype,
            param_dtype=jnp.float32,
            axis_name=self.bn_axis_name,
        )

        x = x.astype(self.compute_dtype)
        if self.stem == "space_to_depth":
            B, H, W, C = x.shape
            if H % 4 or W % 4:
                raise ValueError(
                    f"space_to_depth stem needs H, W divisible by 4, got "
                    f"({H}, {W})"
                )
            x = x.reshape(B, H // 4, 4, W // 4, 4, C)
            x = x.transpose(0, 1, 3, 2, 4, 5).reshape(B, H // 4, W // 4, 16 * C)
            x = conv(self.num_filters, (3, 3), name="conv_init_s2d")(x)
        elif self.stem == "standard":
            x = conv(self.num_filters, (7, 7), (2, 2),
                     padding=[(3, 3), (3, 3)], name="conv_init")(x)
        else:
            raise ValueError(f"unknown stem {self.stem!r}")
        x = norm(name="bn_init")(x)
        x = nn.relu(x)
        if self.stem == "standard":
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        if self.remat_policy not in (None, "conv"):
            raise ValueError(f"unknown remat_policy {self.remat_policy!r}")
        if self.remat_policy is not None and not self.remat:
            raise ValueError("remat_policy requires remat=True")
        if self.remat:
            if self.remat_policy == "conv":
                def _save_conv(prim, *_, **__):
                    return prim.name in ("conv_general_dilated",
                                         "dot_general")

                block_cls = nn.remat(self.block_cls, policy=_save_conv)
            else:
                block_cls = nn.remat(self.block_cls)
        else:
            block_cls = self.block_cls
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = block_cls(
                    self.num_filters * 2**i,
                    conv=conv,
                    norm=norm,
                    strides=strides,
                )(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32, param_dtype=jnp.float32)(x)
        return x.astype(jnp.float32)


ResNet18 = partial(ResNet, stage_sizes=(2, 2, 2, 2), block_cls=BasicBlock)
ResNet34 = partial(ResNet, stage_sizes=(3, 4, 6, 3), block_cls=BasicBlock)
ResNet50 = partial(ResNet, stage_sizes=(3, 4, 6, 3), block_cls=BottleneckBlock)
ResNet101 = partial(ResNet, stage_sizes=(3, 4, 23, 3), block_cls=BottleneckBlock)
ResNet152 = partial(ResNet, stage_sizes=(3, 8, 36, 3), block_cls=BottleneckBlock)
