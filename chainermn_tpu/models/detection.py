"""Detection models — the Faster-RCNN-style stress workload.

Reference: the fork's benchmark configs name "ChainerCV Faster-RCNN (stress
hierarchical communicator, odd grad shapes)" (BASELINE.json ``configs``;
SURVEY.md §7 hard-parts list). Two models: :class:`TinyDetector` (the
single-stage RPN that carries the grad-shape stress alone) and
:class:`TwoStageDetector` (the honest Faster-RCNN shape: RPN -> static
top-K proposals -> RoI-align -> per-RoI class+box head — the second stage
with the genuinely awkward shapes). The stress, not the mAP, is the point:

- **odd gradient shapes** — deliberately non-round channel counts (13, 27,
  54...) and a mixed bag of parameter ranks, the shapes that broke naive
  gradient packers in the reference era and that exercise this framework's
  claim that XLA's fused allreduce needs no packing at all;
- **dynamic image shapes** — detection batches come in many (H, W) sizes;
  under jit this forces the bucketing discipline
  (:mod:`chainermn_tpu.datasets.bucketing` for sequences; here a 2-d shape
  ladder) with one compile per bucket;
- **ragged ground truth** — variable boxes per image, padded + masked.

The model is a small anchor-based detector: conv backbone → shared head →
per-anchor objectness + box deltas; the loss does real IoU matching of
anchors to padded GT boxes entirely under jit (static shapes, masked).
"""

from __future__ import annotations

from typing import Any, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax

#: anchor sizes (px) and aspect ratios per feature-map cell
ANCHOR_SIZES = (32.0, 64.0, 128.0)
ANCHOR_RATIOS = (0.5, 1.0, 2.0)
STRIDE = 16  # backbone downsampling


def _rpn_trunk(images, channels, num_anchors, compute_dtype):
    """Shared backbone + RPN head (both detectors; one definition so the
    trunks cannot drift): stride-2 conv ladder to /16, then objectness +
    anchor-delta 1x1 convs. Returns (feat [B,Hf,Wf,C], obj [B,Hf,Wf,A]
    f32, deltas [B,Hf,Wf,A,4] f32). Must run inside ``@nn.compact``."""
    x = images.astype(compute_dtype)
    for i, ch in enumerate(channels):
        # stride-2 convs: 3 levels + the head's stride-2 = /16 total
        x = nn.Conv(ch, (3, 3), strides=(2, 2), name=f"conv{i}")(x)
        x = nn.relu(x)
    feat = nn.relu(
        nn.Conv(channels[-1], (3, 3), strides=(2, 2), name="head")(x)
    )
    obj = nn.Conv(num_anchors, (1, 1), name="objectness")(feat)
    deltas = nn.Conv(num_anchors * 4, (1, 1), name="boxes")(feat)
    B, Hf, Wf, _ = deltas.shape
    return (
        feat,
        obj.astype(jnp.float32),
        deltas.reshape(B, Hf, Wf, num_anchors, 4).astype(jnp.float32),
    )


def smooth_l1(err: jax.Array) -> jax.Array:
    """Smooth-L1 (Huber, beta=1) summed over the last axis — the box
    regression form BOTH stage losses share."""
    return jnp.where(
        jnp.abs(err) < 1.0, 0.5 * err * err, jnp.abs(err) - 0.5
    ).sum(-1)


class TinyDetector(nn.Module):
    """Backbone + RPN-style head with deliberately odd channel counts."""

    channels: Sequence[int] = (13, 27, 54)  # odd on purpose (grad stress)
    num_anchors: int = len(ANCHOR_SIZES) * len(ANCHOR_RATIOS)
    compute_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, images: jax.Array):
        """images [B, H, W, 3] → (objectness [B, Hf, Wf, A],
        box deltas [B, Hf, Wf, A, 4]) with Hf = H // STRIDE."""
        _, obj, deltas = _rpn_trunk(
            images, self.channels, self.num_anchors, self.compute_dtype
        )
        return obj, deltas


def make_anchors(hf: int, wf: int) -> jax.Array:
    """Anchor boxes [Hf*Wf*A, 4] as (y0, x0, y1, x1) in pixels."""
    ys = (jnp.arange(hf) + 0.5) * STRIDE
    xs = (jnp.arange(wf) + 0.5) * STRIDE
    cy, cx = jnp.meshgrid(ys, xs, indexing="ij")  # [Hf, Wf]
    boxes = []
    for size in ANCHOR_SIZES:
        for ratio in ANCHOR_RATIOS:
            h = size * (ratio ** 0.5)
            w = size / (ratio ** 0.5)
            boxes.append(jnp.stack(
                [cy - h / 2, cx - w / 2, cy + h / 2, cx + w / 2], axis=-1
            ))
    return jnp.stack(boxes, axis=2).reshape(-1, 4)  # [Hf*Wf*A, 4]


def iou_matrix(anchors: jax.Array, gt: jax.Array) -> jax.Array:
    """IoU of anchors [K, 4] against gt boxes [N, 4] → [K, N]."""
    a = anchors[:, None, :]  # [K, 1, 4]
    g = gt[None, :, :]       # [1, N, 4]
    inter_h = jnp.clip(
        jnp.minimum(a[..., 2], g[..., 2]) - jnp.maximum(a[..., 0], g[..., 0]),
        0,
    )
    inter_w = jnp.clip(
        jnp.minimum(a[..., 3], g[..., 3]) - jnp.maximum(a[..., 1], g[..., 1]),
        0,
    )
    inter = inter_h * inter_w
    area_a = (a[..., 2] - a[..., 0]) * (a[..., 3] - a[..., 1])
    area_g = jnp.clip(
        (g[..., 2] - g[..., 0]) * (g[..., 3] - g[..., 1]), 1e-6
    )
    return inter / jnp.clip(area_a + area_g - inter, 1e-6)


def delta_scale(hf: int, wf: int) -> jax.Array:
    """The RPN delta normalisation: ``detection_loss`` ENCODES regression
    targets as ``(gt - anchors) / delta_scale`` and ``decode_anchors``
    inverts it — one helper so the pair cannot drift apart."""
    return jnp.asarray([hf, wf, hf, wf], jnp.float32) * STRIDE


def decode_anchors(deltas: jax.Array, hf: int, wf: int) -> jax.Array:
    """Anchor deltas [..., K, 4] (the head's normalised corner offsets)
    -> absolute boxes [..., K, 4] in image pixels — the inverse of the
    encoding ``detection_loss`` regresses to."""
    return make_anchors(hf, wf) + deltas * delta_scale(hf, wf)


def propose_rois(
    obj: jax.Array,      # [B, Hf, Wf, A]
    deltas: jax.Array,   # [B, Hf, Wf, A, 4]
    num_rois: int,
) -> tuple[jax.Array, jax.Array]:
    """RPN outputs -> STATIC top-K proposal boxes (jit-friendly: a fixed
    ``num_rois`` via ``lax.top_k`` on objectness, no data-dependent NMS —
    the TPU-first replacement for the reference pipeline's dynamic
    proposal pruning). Returns (boxes [B, R, 4] in image pixels, clipped
    to the image, and their scores [B, R])."""
    B, Hf, Wf, A = obj.shape
    K = Hf * Wf * A
    scores = obj.reshape(B, K)
    boxes = decode_anchors(deltas.reshape(B, K, 4), Hf, Wf)
    top_scores, idx = jax.lax.top_k(scores, num_rois)  # [B, R]
    top_boxes = jnp.take_along_axis(boxes, idx[..., None], axis=1)
    # Clip to image extent; keep y0<y1, x0<x1 degenerate-safe.
    H, W = float(Hf * STRIDE), float(Wf * STRIDE)
    y0, x0, y1, x1 = jnp.split(top_boxes, 4, axis=-1)
    # Min corner strictly inside so the >=1px guard cannot overshoot.
    y0 = jnp.clip(y0, 0.0, H - 1.0)
    x0 = jnp.clip(x0, 0.0, W - 1.0)
    y1 = jnp.maximum(jnp.clip(y1, 0.0, H), y0 + 1.0)
    x1 = jnp.maximum(jnp.clip(x1, 0.0, W), x0 + 1.0)
    top_boxes = jnp.concatenate([y0, x0, y1, x1], axis=-1)
    return top_boxes, jax.nn.sigmoid(top_scores)


def roi_align(
    feat: jax.Array,    # [Hf, Wf, C]
    boxes: jax.Array,   # [R, 4] in FEATURE-map coordinates
    out_size: int,
) -> jax.Array:
    """Bilinear RoI-align of one feature map: sample an ``out_size`` x
    ``out_size`` grid of cell-center points per box — static shapes, all
    gathers (differentiable w.r.t. ``feat``; box coords are typically
    ``stop_gradient``-ed by the caller, as in the reference pipeline)."""
    Hf, Wf, C = feat.shape

    def one_box(box):
        y0, x0, y1, x1 = box
        ys = y0 + (jnp.arange(out_size) + 0.5) / out_size * (y1 - y0)
        xs = x0 + (jnp.arange(out_size) + 0.5) / out_size * (x1 - x0)
        # center coords -> continuous pixel index space
        ys = jnp.clip(ys - 0.5, 0.0, Hf - 1.0)
        xs = jnp.clip(xs - 0.5, 0.0, Wf - 1.0)
        yl = jnp.floor(ys).astype(jnp.int32)
        xl = jnp.floor(xs).astype(jnp.int32)
        yh = jnp.minimum(yl + 1, Hf - 1)
        xh = jnp.minimum(xl + 1, Wf - 1)
        wy = (ys - yl)[:, None, None]  # [S, 1, 1]
        wx = (xs - xl)[None, :, None]  # [1, S, 1]
        g = lambda yi, xi: feat[yi[:, None], xi[None, :]]  # [S, S, C]
        return (
            g(yl, xl) * (1 - wy) * (1 - wx)
            + g(yl, xh) * (1 - wy) * wx
            + g(yh, xl) * wy * (1 - wx)
            + g(yh, xh) * wy * wx
        )

    return jax.vmap(one_box)(boxes)  # [R, S, S, C]


class TwoStageDetector(nn.Module):
    """Faster-RCNN-style TWO-stage detector (round-4 VERDICT item 5;
    BASELINE.json ``configs[3]`` names "ChainerCV Faster-RCNN").

    TPU-first second stage: RPN -> STATIC top-K proposals
    (:func:`propose_rois`) -> bilinear :func:`roi_align` -> per-RoI
    class + box-refinement head — every tensor statically shaped under
    jit; ragged GT stays padded + masked in the loss. Proposal
    coordinates are ``stop_gradient``-ed (reference semantics: the RPN
    trains from its own loss, the RoI head trains through the pooled
    FEATURES), so the backbone receives gradients from both stages.
    Channel counts stay deliberately odd (grad-shape stress)."""

    channels: Sequence[int] = (13, 27, 54)
    num_classes: int = 7    # foreground classes; index 0 = background
    num_rois: int = 32      # static proposal count
    roi_size: int = 5
    head_width: int = 93    # odd on purpose
    num_anchors: int = len(ANCHOR_SIZES) * len(ANCHOR_RATIOS)
    compute_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, images: jax.Array) -> dict:
        feat, obj32, deltas32 = _rpn_trunk(
            images, self.channels, self.num_anchors, self.compute_dtype
        )
        B = feat.shape[0]
        proposals, scores = propose_rois(obj32, deltas32, self.num_rois)
        proposals = jax.lax.stop_gradient(proposals)
        roi_feats = jax.vmap(
            lambda f, b: roi_align(f, b / STRIDE, self.roi_size)
        )(feat, proposals)  # [B, R, S, S, C]
        h = roi_feats.reshape(B, self.num_rois, -1)
        h = nn.relu(nn.Dense(self.head_width, name="roi_fc")(h))
        cls = nn.Dense(self.num_classes + 1, name="roi_cls")(h)
        refine = nn.Dense(4, name="roi_refine")(h)
        return {
            "obj": obj32,
            "deltas": deltas32,
            "proposals": proposals,          # [B, R, 4] image px
            "proposal_scores": scores,       # [B, R]
            "cls": cls.astype(jnp.float32),  # [B, R, classes+1]
            "refine": refine.astype(jnp.float32),
        }


def roi_head_loss(
    proposals: jax.Array,  # [B, R, 4]
    cls: jax.Array,        # [B, R, classes+1]
    refine: jax.Array,     # [B, R, 4]
    gt_boxes: jax.Array,   # [B, N, 4] padded
    gt_mask: jax.Array,    # [B, N]
    gt_labels: jax.Array,  # [B, N] int in [0, classes)
    *,
    pos_iou: float = 0.5,
) -> jax.Array:
    """Second-stage loss under jit: IoU-match the static proposals to
    (masked) GT; cross-entropy over classes+background on ALL RoIs,
    smooth-L1 refinement on positives. Padded GT rows are IoU-neutral —
    the same masking discipline as the RPN loss."""
    def one(props_i, cls_i, ref_i, gt_i, m_i, lab_i):
        iou = iou_matrix(props_i, gt_i)  # [R, N]
        iou = jnp.where(m_i[None, :] > 0, iou, -jnp.inf)
        best = jnp.max(iou, axis=1)
        best_idx = jnp.argmax(iou, axis=1)
        any_gt = jnp.any(m_i > 0)
        pos = (best >= pos_iou) & any_gt
        # 0 = background; foreground labels shift by +1.
        target = jnp.where(pos, lab_i[best_idx] + 1, 0)
        ce = optax.softmax_cross_entropy_with_integer_labels(
            cls_i, target
        ).mean()
        matched = gt_i[best_idx]  # [R, 4]
        size = jnp.maximum(
            jnp.concatenate([
                props_i[:, 2:] - props_i[:, :2],
                props_i[:, 2:] - props_i[:, :2],
            ], axis=-1),
            1.0,
        )  # [R, 4] (h, w, h, w)
        err = ref_i - (matched - props_i) / size
        l1 = smooth_l1(err)
        n_pos = jnp.clip(pos.sum(), 1)
        reg = jnp.where(pos, l1, 0.0).sum() / n_pos
        return ce + reg

    return jax.vmap(one)(
        proposals, cls, refine, gt_boxes, gt_mask, gt_labels
    ).mean()


def two_stage_loss(
    outputs: dict,
    gt_boxes: jax.Array,
    gt_mask: jax.Array,
    gt_labels: jax.Array,
    *,
    pos_iou: float = 0.5,
) -> jax.Array:
    """Full Faster-RCNN-style objective: RPN (objectness + anchor
    regression) + RoI head (classification + refinement)."""
    rpn = detection_loss(
        outputs["obj"], outputs["deltas"], gt_boxes, gt_mask,
        pos_iou=pos_iou,
    )
    roi = roi_head_loss(
        outputs["proposals"], outputs["cls"], outputs["refine"],
        gt_boxes, gt_mask, gt_labels, pos_iou=pos_iou,
    )
    return rpn + roi


def detection_loss(
    obj: jax.Array,        # [B, Hf, Wf, A]
    deltas: jax.Array,     # [B, Hf, Wf, A, 4]
    gt_boxes: jax.Array,   # [B, N, 4] padded
    gt_mask: jax.Array,    # [B, N] 1 for real boxes
    *,
    pos_iou: float = 0.5,
) -> jax.Array:
    """RPN loss under jit: IoU-match anchors to (masked) GT, BCE objectness
    + smooth-L1 box regression on positive anchors. Padded GT rows are
    IoU-neutralised (set to -inf IoU), so garbage in padding cannot alter
    the loss — tested."""
    B, Hf, Wf, A = obj.shape
    anchors = make_anchors(Hf, Wf)  # [K, 4]
    K = anchors.shape[0]
    obj = obj.reshape(B, K)
    deltas = deltas.reshape(B, K, 4)

    def one(obj_i, deltas_i, gt_i, m_i):
        iou = iou_matrix(anchors, gt_i)  # [K, N]
        iou = jnp.where(m_i[None, :] > 0, iou, -jnp.inf)
        best = jnp.max(iou, axis=1)              # [K]
        best_idx = jnp.argmax(iou, axis=1)       # [K]
        any_gt = jnp.any(m_i > 0)
        pos = (best >= pos_iou) & any_gt
        labels = pos.astype(jnp.float32)
        # objectness: BCE over all anchors
        bce = optax.sigmoid_binary_cross_entropy(obj_i, labels).mean()
        # box regression: smooth-L1 of (normalised) corner offsets, positives
        matched = gt_i[best_idx]  # [K, 4]
        err = (deltas_i - (matched - anchors) / delta_scale(Hf, Wf))
        l1 = smooth_l1(err)
        n_pos = jnp.clip(pos.sum(), 1)
        reg = jnp.where(pos, l1, 0.0).sum() / n_pos
        return bce + reg

    return jax.vmap(one)(obj, deltas, gt_boxes, gt_mask).mean()
