"""Single-stage detection model — the Faster-RCNN-style stress workload.

Reference: the fork's benchmark configs name "ChainerCV Faster-RCNN (stress
hierarchical communicator, odd grad shapes)" (BASELINE.json ``configs``;
SURVEY.md §7 hard-parts list). The stress, not the mAP, is the point:

- **odd gradient shapes** — deliberately non-round channel counts (13, 27,
  54...) and a mixed bag of parameter ranks, the shapes that broke naive
  gradient packers in the reference era and that exercise this framework's
  claim that XLA's fused allreduce needs no packing at all;
- **dynamic image shapes** — detection batches come in many (H, W) sizes;
  under jit this forces the bucketing discipline
  (:mod:`chainermn_tpu.datasets.bucketing` for sequences; here a 2-d shape
  ladder) with one compile per bucket;
- **ragged ground truth** — variable boxes per image, padded + masked.

The model is a small anchor-based detector: conv backbone → shared head →
per-anchor objectness + box deltas; the loss does real IoU matching of
anchors to padded GT boxes entirely under jit (static shapes, masked).
"""

from __future__ import annotations

from typing import Any, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax

#: anchor sizes (px) and aspect ratios per feature-map cell
ANCHOR_SIZES = (32.0, 64.0, 128.0)
ANCHOR_RATIOS = (0.5, 1.0, 2.0)
STRIDE = 16  # backbone downsampling


class TinyDetector(nn.Module):
    """Backbone + RPN-style head with deliberately odd channel counts."""

    channels: Sequence[int] = (13, 27, 54)  # odd on purpose (grad stress)
    num_anchors: int = len(ANCHOR_SIZES) * len(ANCHOR_RATIOS)
    compute_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, images: jax.Array):
        """images [B, H, W, 3] → (objectness [B, Hf, Wf, A],
        box deltas [B, Hf, Wf, A, 4]) with Hf = H // STRIDE."""
        x = images.astype(self.compute_dtype)
        for i, ch in enumerate(self.channels):
            # stride-2 convs: 3 levels + the head's stride-2 = /16 total
            x = nn.Conv(ch, (3, 3), strides=(2, 2), name=f"conv{i}")(x)
            x = nn.relu(x)
        x = nn.Conv(self.channels[-1], (3, 3), strides=(2, 2), name="head")(x)
        x = nn.relu(x)
        obj = nn.Conv(self.num_anchors, (1, 1), name="objectness")(x)
        deltas = nn.Conv(self.num_anchors * 4, (1, 1), name="boxes")(x)
        B, Hf, Wf, _ = deltas.shape
        return (
            obj.astype(jnp.float32),
            deltas.reshape(B, Hf, Wf, self.num_anchors, 4).astype(jnp.float32),
        )


def make_anchors(hf: int, wf: int) -> jax.Array:
    """Anchor boxes [Hf*Wf*A, 4] as (y0, x0, y1, x1) in pixels."""
    ys = (jnp.arange(hf) + 0.5) * STRIDE
    xs = (jnp.arange(wf) + 0.5) * STRIDE
    cy, cx = jnp.meshgrid(ys, xs, indexing="ij")  # [Hf, Wf]
    boxes = []
    for size in ANCHOR_SIZES:
        for ratio in ANCHOR_RATIOS:
            h = size * (ratio ** 0.5)
            w = size / (ratio ** 0.5)
            boxes.append(jnp.stack(
                [cy - h / 2, cx - w / 2, cy + h / 2, cx + w / 2], axis=-1
            ))
    return jnp.stack(boxes, axis=2).reshape(-1, 4)  # [Hf*Wf*A, 4]


def iou_matrix(anchors: jax.Array, gt: jax.Array) -> jax.Array:
    """IoU of anchors [K, 4] against gt boxes [N, 4] → [K, N]."""
    a = anchors[:, None, :]  # [K, 1, 4]
    g = gt[None, :, :]       # [1, N, 4]
    inter_h = jnp.clip(
        jnp.minimum(a[..., 2], g[..., 2]) - jnp.maximum(a[..., 0], g[..., 0]),
        0,
    )
    inter_w = jnp.clip(
        jnp.minimum(a[..., 3], g[..., 3]) - jnp.maximum(a[..., 1], g[..., 1]),
        0,
    )
    inter = inter_h * inter_w
    area_a = (a[..., 2] - a[..., 0]) * (a[..., 3] - a[..., 1])
    area_g = jnp.clip(
        (g[..., 2] - g[..., 0]) * (g[..., 3] - g[..., 1]), 1e-6
    )
    return inter / jnp.clip(area_a + area_g - inter, 1e-6)


def detection_loss(
    obj: jax.Array,        # [B, Hf, Wf, A]
    deltas: jax.Array,     # [B, Hf, Wf, A, 4]
    gt_boxes: jax.Array,   # [B, N, 4] padded
    gt_mask: jax.Array,    # [B, N] 1 for real boxes
    *,
    pos_iou: float = 0.5,
) -> jax.Array:
    """RPN loss under jit: IoU-match anchors to (masked) GT, BCE objectness
    + smooth-L1 box regression on positive anchors. Padded GT rows are
    IoU-neutralised (set to -inf IoU), so garbage in padding cannot alter
    the loss — tested."""
    B, Hf, Wf, A = obj.shape
    anchors = make_anchors(Hf, Wf)  # [K, 4]
    K = anchors.shape[0]
    obj = obj.reshape(B, K)
    deltas = deltas.reshape(B, K, 4)

    def one(obj_i, deltas_i, gt_i, m_i):
        iou = iou_matrix(anchors, gt_i)  # [K, N]
        iou = jnp.where(m_i[None, :] > 0, iou, -jnp.inf)
        best = jnp.max(iou, axis=1)              # [K]
        best_idx = jnp.argmax(iou, axis=1)       # [K]
        any_gt = jnp.any(m_i > 0)
        pos = (best >= pos_iou) & any_gt
        labels = pos.astype(jnp.float32)
        # objectness: BCE over all anchors
        bce = optax.sigmoid_binary_cross_entropy(obj_i, labels).mean()
        # box regression: smooth-L1 of (normalised) corner offsets, positives
        matched = gt_i[best_idx]  # [K, 4]
        scale = jnp.asarray([Hf, Wf, Hf, Wf], jnp.float32) * STRIDE
        err = (deltas_i - (matched - anchors) / scale)
        l1 = jnp.where(
            jnp.abs(err) < 1.0, 0.5 * err * err, jnp.abs(err) - 0.5
        ).sum(-1)
        n_pos = jnp.clip(pos.sum(), 1)
        reg = jnp.where(pos, l1, 0.0).sum() / n_pos
        return bce + reg

    return jax.vmap(one)(obj, deltas, gt_boxes, gt_mask).mean()
