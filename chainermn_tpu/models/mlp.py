"""3-layer MLP — the reference's canonical MNIST smoke-test model
(``examples/mnist/train_mnist.py`` (dagger), SURVEY.md section 2.8)."""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp


class MLP(nn.Module):
    """``n_units`` hidden x2 + ``n_out`` head, ReLU — same shape as the
    reference's MNIST MLP."""

    n_units: int = 1000
    n_out: int = 10

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(self.n_units)(x))
        x = nn.relu(nn.Dense(self.n_units)(x))
        return nn.Dense(self.n_out)(x)
