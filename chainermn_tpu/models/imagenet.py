"""AlexNet / GoogLeNet — the rest of the reference's ImageNet model family
(``examples/imagenet/models/{alex,googlenet,googlenetbn}.py`` (dagger),
SURVEY.md section 2.8). ResNet lives in :mod:`chainermn_tpu.models.resnet`.

Same TPU conventions as ResNet: NHWC, bf16 compute / f32 params, optional
sync-BN over a mesh axis for the BN variants.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Optional

import flax.linen as nn
import jax.numpy as jnp

from chainermn_tpu.links.batch_normalization import MultiNodeBatchNormalization


class AlexNet(nn.Module):
    """AlexNet (single-tower) — ``examples/imagenet/models/alex.py`` (dagger)."""

    num_classes: int = 1000
    compute_dtype: Any = jnp.bfloat16
    dropout_rate: float = 0.5

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(
            nn.Conv, dtype=self.compute_dtype, param_dtype=jnp.float32
        )
        x = x.astype(self.compute_dtype)
        x = nn.relu(conv(96, (11, 11), (4, 4), padding="VALID")(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = nn.relu(conv(256, (5, 5), padding=[(2, 2), (2, 2)])(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = nn.relu(conv(384, (3, 3), padding=[(1, 1), (1, 1)])(x))
        x = nn.relu(conv(384, (3, 3), padding=[(1, 1), (1, 1)])(x))
        x = nn.relu(conv(256, (3, 3), padding=[(1, 1), (1, 1)])(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(4096, dtype=self.compute_dtype,
                             param_dtype=jnp.float32)(x))
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        x = nn.relu(nn.Dense(4096, dtype=self.compute_dtype,
                             param_dtype=jnp.float32)(x))
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        x = nn.Dense(self.num_classes, dtype=jnp.float32,
                     param_dtype=jnp.float32)(x)
        return x.astype(jnp.float32)


class _Inception(nn.Module):
    """Inception-v1 block; ``use_bn`` makes it the googlenetbn variant."""

    c1: int
    c3r: int
    c3: int
    c5r: int
    c5: int
    cp: int
    use_bn: bool = False
    bn_axis_name: Optional[Any] = None
    compute_dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(
            nn.Conv, dtype=self.compute_dtype, param_dtype=jnp.float32,
            use_bias=not self.use_bn,
        )

        def act(h, name):
            if self.use_bn:
                h = MultiNodeBatchNormalization(
                    use_running_average=not train,
                    axis_name=self.bn_axis_name,
                    dtype=self.compute_dtype,
                    param_dtype=jnp.float32,
                    name=f"bn_{name}",
                )(h)
            return nn.relu(h)

        b1 = act(conv(self.c1, (1, 1), name="b1")(x), "b1")
        b3 = act(conv(self.c3r, (1, 1), name="b3r")(x), "b3r")
        b3 = act(conv(self.c3, (3, 3), padding=[(1, 1), (1, 1)], name="b3")(b3),
                 "b3")
        b5 = act(conv(self.c5r, (1, 1), name="b5r")(x), "b5r")
        b5 = act(conv(self.c5, (5, 5), padding=[(2, 2), (2, 2)], name="b5")(b5),
                 "b5")
        bp = nn.max_pool(x, (3, 3), strides=(1, 1), padding=((1, 1), (1, 1)))
        bp = act(conv(self.cp, (1, 1), name="bp")(bp), "bp")
        return jnp.concatenate([b1, b3, b5, bp], axis=-1)


_INCEPTION_CFG = [
    # (c1, c3r, c3, c5r, c5, cp), with pool markers between stages
    (64, 96, 128, 16, 32, 32),
    (128, 128, 192, 32, 96, 64),
    "pool",
    (192, 96, 208, 16, 48, 64),
    (160, 112, 224, 24, 64, 64),
    (128, 128, 256, 24, 64, 64),
    (112, 144, 288, 32, 64, 64),
    (256, 160, 320, 32, 128, 128),
    "pool",
    (256, 160, 320, 32, 128, 128),
    (384, 192, 384, 48, 128, 128),
]


class GoogLeNet(nn.Module):
    """GoogLeNet (inception v1) — ``models/googlenet.py`` (dagger); with
    ``use_bn=True`` it is the ``googlenetbn.py`` (dagger) variant whose BN
    stats sync over ``bn_axis_name`` (the case the reference's
    MultiNodeBatchNormalization existed for)."""

    num_classes: int = 1000
    use_bn: bool = False
    bn_axis_name: Optional[Any] = None
    compute_dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(
            nn.Conv, dtype=self.compute_dtype, param_dtype=jnp.float32
        )
        x = x.astype(self.compute_dtype)
        x = nn.relu(conv(64, (7, 7), (2, 2), padding=[(3, 3), (3, 3)])(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        x = nn.relu(conv(64, (1, 1))(x))
        x = nn.relu(conv(192, (3, 3), padding=[(1, 1), (1, 1)])(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        for i, cfg in enumerate(_INCEPTION_CFG):
            if cfg == "pool":
                x = nn.max_pool(
                    x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1))
                )
            else:
                x = _Inception(
                    *cfg,
                    use_bn=self.use_bn,
                    bn_axis_name=self.bn_axis_name,
                    compute_dtype=self.compute_dtype,
                    name=f"inc_{i}",
                )(x, train=train)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32,
                     param_dtype=jnp.float32)(x)
        return x.astype(jnp.float32)
