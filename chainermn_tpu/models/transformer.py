"""Transformer-base causal LM — the ``BASELINE.json`` benchmark config that
exercises large embedding gradients and the double-buffered allreduce
(``Transformer-base LM (new — large embedding grads, double-buffered
allreduce)``). Not present in the reference (2017-era); shape follows the
original Transformer-base (6 layers, d_model 512, 8 heads, d_ff 2048).

TPU-first choices: bf16 compute / f32 params; pre-LN (stable without warmup
gymnastics); pluggable attention so the same module runs single-device
(flash/blockwise kernels, :mod:`chainermn_tpu.ops`) or sequence-parallel
(ring/Ulysses locals from :mod:`chainermn_tpu.parallel` when applied inside
``shard_map`` — pass ``attention_fn=lambda q,k,v,causal,scale:
ring_attention_local(q, k, v, 'seq', causal=causal, scale=scale)``).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from chainermn_tpu.ops.attention import blockwise_attention


def apply_rope(x, positions, base: float = 10000.0):
    """Rotary position embedding on ``[B, T, H, Dh]`` (half-split pairing).

    ``positions``: ``[T]`` GLOBAL positions — sequence-parallel shards pass
    their own offsets, so rotations agree across shards (rotation commutes
    with the ring/Ulysses resharding because it is per-position). A
    ``[B, T]`` array gives each batch row its OWN positions — the serving
    engine's slot array, where every slot sits at a different depth.
    """
    half = x.shape[-1] // 2
    freqs = base ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    ang = positions.astype(jnp.float32)[..., None] * freqs  # [..., T, half]
    if ang.ndim == 2:  # [T, half]: shared across the batch
        cos = jnp.cos(ang)[None, :, None, :].astype(x.dtype)
        sin = jnp.sin(ang)[None, :, None, :].astype(x.dtype)
    else:  # [B, T, half]: per-row slot positions
        cos = jnp.cos(ang)[:, :, None, :].astype(x.dtype)
        sin = jnp.sin(ang)[:, :, None, :].astype(x.dtype)
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos],
                           axis=-1)


class TransformerBlock(nn.Module):
    num_heads: int
    d_ff: int
    compute_dtype: Any = jnp.bfloat16
    attention_fn: Optional[Callable] = None
    #: residual dropout on the attention and FFN branch outputs (the
    #: GPT-2 placement; attention-matrix dropout is deliberately NOT
    #: offered — it would break the flash kernels' LSE bookkeeping and
    #: modern LM recipes train without it). Active when ``train=True``;
    #: callers supply the ``'dropout'`` rng.
    dropout_rate: float = 0.0
    #: kv heads for GQA/MQA (None → num_heads, i.e. standard MHA). The kv
    #: projection shrinks accordingly; the attention kernel shares kv heads
    #: across their q-head group (:mod:`chainermn_tpu.ops.flash_attention`).
    num_kv_heads: Optional[int] = None
    #: KV-cache capacity for ``decode=True`` (single-token autoregressive
    #: steps). Training/prefill paths ignore it.
    decode_max_len: int = 2048
    #: causal sliding-window width. The TRAINING path cannot apply it
    #: itself (attention is pluggable): pass an ``attention_fn`` that
    #: honours the same window (``flash_attention(..., window=W)``) — a
    #: window without one is rejected. The DECODE path applies it to the
    #: KV-cache mask directly, keeping inference consistent with the
    #: windowed training distribution.
    window: Optional[int] = None
    #: bidirectional attention when False (encoder blocks — ViT, BERT
    #: style). Decode/window paths are causal-only and reject it.
    causal: bool = True
    #: decode KV-cache layout: ``'dense'`` (``[B, decode_max_len, ...]``
    #: per slot — the classic fixed ring) or ``'paged'`` (shared block
    #: pool + per-slot block tables, :mod:`chainermn_tpu.ops.paged_kv` —
    #: the serving engine's HBM-shared layout). Paged requires the
    #: per-row decode path (``decode_positions`` + ``block_tables``).
    kv_layout: str = "dense"
    #: tokens per pool block (paged layout; tuned via the
    #: ``kv_block_size`` autotune decision).
    kv_block_size: int = 64
    #: pool capacity in blocks (paged layout; block 0 is scratch).
    kv_num_blocks: int = 0
    #: slot-decode attention impl: ``'xla'`` (scatter → dense-view
    #: gather → einsum attend — the reference path) or ``'fused'`` (the
    #: flash-decoding Pallas kernel, :mod:`chainermn_tpu.ops.
    #: paged_decode` — one HBM pass, no dense view; registry decision
    #: ``decode_attend_impl``, resolved by the serving engine). The
    #: CACHE WRITE is shared between the impls — only the attend read
    #: differs, so streams agree to fp32-accumulation tolerance.
    decode_attend_impl: str = "xla"
    #: mesh axis name for tensor-parallel decode: the block then holds
    #: LOCAL heads/kv-heads/d_ff (set ``head_dim`` explicitly) and
    #: inserts exactly one ``psum`` per column→row pair (attention
    #: output projection, FFN down projection) via
    #: :mod:`chainermn_tpu.parallel.tensor`'s adjoint ops. Row-parallel
    #: biases must be pre-divided by the axis size (the engine's param
    #: sharder does this).
    tp_axis: Optional[str] = None
    #: per-head width override; required under ``tp_axis`` where
    #: ``d_model // num_heads`` no longer holds (num_heads is local).
    head_dim: Optional[int] = None
    #: sow each NON-decode forward's post-rope K/V into a mutable
    #: ``'kv_out'`` collection (``{'k': (kh,), 'v': (vh,)}`` per block,
    #: ``compute_dtype`` — exactly what the slot-decode cache stores).
    #: The serving engine's sequence-parallel prefill (ISSUE 13) runs a
    #: train-mode forward over the prompt shards and scatters these into
    #: the paged/dense cache at true positions.
    sow_kv: bool = False
    #: mixture-of-experts FFN (ISSUE 20): with ``n_experts > 0`` the
    #: dense ``ff_up``/``ff_down`` pair is replaced by ``n_experts``
    #: independent MLPs behind a top-1 router (``moe_router`` /
    #: ``moe_w_up`` / ``moe_b_up`` / ``moe_w_down`` / ``moe_b_down``
    #: params; expert leaves stack a leading ``[n_experts, ...]`` dim).
    #: 0 (default) keeps the dense FFN — nothing changes.
    n_experts: int = 0
    #: mesh axis hosting expert shards for the serving/decode path.
    #: ``None`` evaluates every expert locally and combines with the
    #: one-hot gate (the reference form — exact, E x FLOPs, right for
    #: the sequential :func:`generate` and the engine's non-TP arms).
    #: Set (the engine sets it to ``tp_axis``) the FFN switches to the
    #: ownership-split form: each shard routes its owned slice of the
    #: replicated token rows, two ``all_to_all``s ship queues to the
    #: expert owners and back, and ONE ``psum`` re-replicates — the MoE
    #: analogue of dense ``ff_down``'s ``reduce_from_tp``, so TP stays
    #: at exactly 2 all-reduces per layer plus 2 all_to_alls per MoE
    #: layer. ``n_experts`` stays GLOBAL; the local expert count is
    #: read off the (sharder-sliced) param leaf at trace time.
    expert_axis: Optional[str] = None
    #: queue-build impl for the ownership-split path: ``'sort'`` /
    #: ``'einsum'`` / ``'auto'`` (registry decision ``moe_dispatch``,
    #: resolved at trace time — same numbers either way).
    moe_dispatch_impl: str = "auto"
    #: DECLARED leading dim of the expert param leaves (flax validates
    #: param shapes at apply): ``None`` = ``n_experts`` (full leaves —
    #: every single-device use). The serving engine's TP clone sets it
    #: to ``n_experts // tp`` so the per-shard model matches the
    #: sharder's sliced leaves; ``n_experts`` itself stays GLOBAL (the
    #: router scores every expert).
    moe_experts_local: Optional[int] = None

    @staticmethod
    def _lora_delta(name, adapters, inp, out):
        """Add the low-rank delta ``(inp @ A) @ B`` for projection
        ``name`` (ISSUE 14: multi-tenant adapters). ``adapters`` maps a
        projection name to its ``(A, B)`` pair — either unbatched
        ``[d_in, r]`` / ``[r, d_out]`` (one adapter for every row: the
        sequential ``generate`` reference) or per-row ``[B, d_in, r]`` /
        ``[B, r, d_out]`` (the serving engine's per-slot tenant gather).
        The scale is pre-folded into ``B`` by the
        :class:`~chainermn_tpu.serving.adapters.AdapterBank`, so both
        paths consume the identical values. A zero A/B row contributes
        an exact 0 — the zero-adapter tenant stays bitwise the base
        model."""
        if not adapters or name not in adapters:
            return out
        A, B = adapters[name]
        A = A.astype(inp.dtype)
        B = B.astype(inp.dtype)
        if A.ndim == 2:  # shared adapter (reference path)
            delta = (inp @ A) @ B
        else:  # per-row gathered stacks (serving slot array)
            delta = jnp.einsum(
                "btr,bro->bto", jnp.einsum("btd,bdr->btr", inp, A), B
            )
        return out + delta.astype(out.dtype)

    def _decode_attend(self, qh, kh_new, vh_new, head_dim):
        """One-token attention against the mutable KV cache.

        The cache is a fixed-shape ``[B, max_len, kvh, dh]`` ring written
        at ``cache_index`` — fixed shapes keep the decode step a single
        compiled program (XLA semantics: no dynamic shapes), the TPU
        answer to the reference era's growing Python-side state. Masked
        positions beyond the index cost bandwidth, not correctness;
        decode is memory-bound either way.
        """
        B = qh.shape[0]
        kv_heads = kh_new.shape[2]
        ck = self.variable(
            "cache", "cached_key",
            lambda: jnp.zeros(
                (B, self.decode_max_len, kv_heads, head_dim),
                self.compute_dtype,
            ),
        )
        cv = self.variable(
            "cache", "cached_value",
            lambda: jnp.zeros(
                (B, self.decode_max_len, kv_heads, head_dim),
                self.compute_dtype,
            ),
        )
        idx = self.variable(
            "cache", "cache_index", lambda: jnp.zeros((), jnp.int32)
        )
        i = idx.value
        ck.value = jax.lax.dynamic_update_slice(
            ck.value, kh_new.astype(self.compute_dtype), (0, i, 0, 0)
        )
        cv.value = jax.lax.dynamic_update_slice(
            cv.value, vh_new.astype(self.compute_dtype), (0, i, 0, 0)
        )
        idx.value = i + 1

        group = self.num_heads // kv_heads
        # q: [B, 1, H, dh] → [B, kvh, group, dh]; cache k/v: [B, L, kvh, dh]
        q = qh[:, 0].reshape(B, kv_heads, group, head_dim)
        scores = jnp.einsum(
            "bngd,blnd->bngl", q.astype(jnp.float32),
            ck.value.astype(jnp.float32),
        ) * (head_dim ** -0.5)
        pos = jnp.arange(self.decode_max_len)
        mask = pos <= i  # [L]
        if self.window is not None:
            # Same band the windowed training attention saw: j > i - W.
            mask &= pos > i - self.window
        scores = jnp.where(mask[None, None, None, :], scores, -jnp.inf)
        w = jax.nn.softmax(scores, axis=-1)
        o = jnp.einsum(
            "bngl,blnd->bngd", w, cv.value.astype(jnp.float32)
        )
        return o.reshape(B, 1, self.num_heads, head_dim).astype(
            self.compute_dtype
        )

    def _slot_decode_attend(self, qh, kh_new, vh_new, head_dim, positions,
                            block_tables, slots):
        """Slot-array cached attention (the serving engine's path).

        Unlike :meth:`_decode_attend`'s shared scalar write index, every
        batch row carries its OWN position (``positions[b]`` = where row
        ``b``'s first new token is written), so a fixed slot array can
        hold requests at arbitrary depths in one compiled program.
        ``T >= 1`` tokens per row are written at ``positions[b] + t`` and
        each query ``t`` attends with the causal mask ``pos <=
        positions[b] + t`` — ``T == 1`` is the steady-state decode step,
        ``T == bucket`` is prefill (pad-position writes land beyond the
        row's true length and are re-written by later decode steps
        before any mask ever admits them), and ``T == K+1`` is the
        speculative verify span (:mod:`chainermn_tpu.serving.speculate`):
        rejected-draft writes are stale by the same argument — the
        engine rewinds positions on the HOST only, so the next span
        starts at the accept point and re-writes every stale row before
        its position is ever admitted. Writes that overhang the cache
        horizon (a verify span near ``max_len``) are dropped by the
        scatter (dense rows out of bounds) or redirected to the scratch
        block (paged, :func:`~chainermn_tpu.ops.paged_kv.paged_update`);
        the engine caps ACCEPTANCE inside the horizon, so committed
        tokens always have real cache rows.

        Two cache layouts behind one arithmetic: ``'dense'`` stores
        ``[n_slots, decode_max_len, kvh, dh]`` directly (``slots`` maps
        token rows onto cache rows — prefill passes one slot id, the
        decode step passes None for the identity); ``'paged'`` scatters
        into the shared block pool and gathers the row's blocks back
        into the SAME dense view (:mod:`chainermn_tpu.ops.paged_kv`), so
        the einsums/masks — and therefore the tokens — are identical
        between the layouts.
        """
        B, T = qh.shape[:2]
        kv_heads = kh_new.shape[2]
        dt = self.compute_dtype
        if self.decode_attend_impl not in ("xla", "fused"):
            raise ValueError(
                f"decode_attend_impl must be 'xla' or 'fused', got "
                f"{self.decode_attend_impl!r}"
            )
        if self.kv_layout == "paged":
            from chainermn_tpu.ops.paged_kv import paged_lookup, paged_update

            if block_tables is None:
                raise ValueError("kv_layout='paged' needs block_tables")
            if self.kv_num_blocks < 2:
                raise ValueError(
                    "kv_layout='paged' needs kv_num_blocks >= 2 (block 0 "
                    f"is scratch), got {self.kv_num_blocks}"
                )
            nb, bs = self.kv_num_blocks, self.kv_block_size
            pk = self.variable(
                "cache", "pool_key",
                lambda: jnp.zeros((nb, bs, kv_heads, head_dim), dt),
            )
            pv = self.variable(
                "cache", "pool_value",
                lambda: jnp.zeros((nb, bs, kv_heads, head_dim), dt),
            )
            pk.value = paged_update(pk.value, block_tables, positions,
                                    kh_new.astype(dt))
            pv.value = paged_update(pv.value, block_tables, positions,
                                    vh_new.astype(dt))
            if self.decode_attend_impl == "fused":
                from chainermn_tpu.ops.paged_decode import (
                    paged_flash_decode,
                )

                # One HBM pass over the LIVE blocks — the table rides as
                # a scalar-prefetch operand, no dense view ever exists.
                # Scratch block 0 is masked in-kernel (the same released
                # -slot / beyond-horizon staleness argument as below).
                return paged_flash_decode(
                    qh.astype(dt), pk.value, pv.value, block_tables,
                    positions, window=self.window,
                    scale=head_dim ** -0.5, scratch_block=0,
                )
            keys = paged_lookup(pk.value, block_tables)
            vals = paged_lookup(pv.value, block_tables)
        else:
            ck = self.variable(
                "cache", "cached_key",
                lambda: jnp.zeros(
                    (B, self.decode_max_len, kv_heads, head_dim), dt
                ),
            )
            cv = self.variable(
                "cache", "cached_value",
                lambda: jnp.zeros(
                    (B, self.decode_max_len, kv_heads, head_dim), dt
                ),
            )
            rows = (jnp.arange(B, dtype=jnp.int32)
                    if slots is None else slots)
            cols = positions[:, None] + jnp.arange(T, dtype=positions.dtype)
            ck.value = ck.value.at[rows[:, None], cols].set(
                kh_new.astype(dt)
            )
            cv.value = cv.value.at[rows[:, None], cols].set(
                vh_new.astype(dt)
            )
            if self.decode_attend_impl == "fused":
                from chainermn_tpu.ops.paged_decode import (
                    dense_flash_decode,
                )

                # The dense ring through the SAME kernel: the cache
                # reshapes (zero-copy) into implicit blocks with an
                # identity table — the prefill view's per-slot gather
                # becomes table rows, never a materialized copy.
                return dense_flash_decode(
                    qh.astype(dt), ck.value, cv.value, positions,
                    slots=slots, window=self.window,
                    scale=head_dim ** -0.5,
                )
            if slots is None:
                keys, vals = ck.value, cv.value
            else:  # prefill view: gather just the written rows
                keys = ck.value[slots]
                vals = cv.value[slots]

        L = keys.shape[1]
        pos_l = jnp.arange(L)
        qpos = positions[:, None] + jnp.arange(T, dtype=positions.dtype)
        mask = pos_l[None, None, :] <= qpos[:, :, None]  # [B, T, L]
        if self.window is not None:
            mask &= pos_l[None, None, :] > qpos[:, :, None] - self.window
        group = self.num_heads // kv_heads
        q = qh.reshape(B, T, kv_heads, group, head_dim)
        scores = jnp.einsum(
            "btngd,blnd->btngl", q.astype(jnp.float32),
            keys.astype(jnp.float32),
        ) * (head_dim ** -0.5)
        scores = jnp.where(mask[:, :, None, None, :], scores, -jnp.inf)
        w = jax.nn.softmax(scores, axis=-1)
        o = jnp.einsum("btngl,blnd->btngd", w, vals.astype(jnp.float32))
        return o.reshape(B, T, self.num_heads, head_dim).astype(dt)

    def _moe_ffn(self, h):
        """Top-1 mixture-of-experts FFN branch (ISSUE 20).

        Routing is per token row and position-independent, so the SAME
        code serves training forwards, prefill and single-token decode —
        per-slot expert routing inside the engine's one jitted decode
        program is just this method applied to ``[B, 1, D]`` rows.

        ``expert_axis=None``: every expert evaluated, one-hot + gate
        combine — the exact reference form (row-independent, so the
        engine's co-resident slots route without coupling and streams
        stay bit-identical to the sequential :func:`generate`).

        ``expert_axis`` set: ownership-split serving form — pad the
        replicated rows to a multiple of the axis size, route the owned
        slice through :func:`~chainermn_tpu.parallel.moe.moe_layer_local`
        (no-drop capacity: serving never drops tokens), scatter the
        owned outputs into a zero buffer and re-replicate with ONE
        ``psum``. Routing uses the same ``argmax(softmax)`` as
        ``route_slots``, so both forms pick identical experts.
        """
        E = self.n_experts
        e_decl = self.moe_experts_local or E
        D = h.shape[-1]
        cd = self.compute_dtype
        kern = nn.initializers.variance_scaling(
            1.0, "fan_in", "truncated_normal", in_axis=-2, out_axis=-1,
            batch_axis=(0,),
        )
        router = self.param(
            "moe_router", nn.initializers.normal(0.02), (D, E),
            jnp.float32,
        )
        # expert-stacked leaves: [E, ...] full, or the sharder's
        # [E/n, ...] slice under the engine's TP clone (e_decl)
        w_up = self.param("moe_w_up", kern, (e_decl, D, self.d_ff),
                          jnp.float32)
        b_up = self.param("moe_b_up", nn.initializers.zeros_init(),
                          (e_decl, self.d_ff), jnp.float32)
        w_down = self.param("moe_w_down", kern, (e_decl, self.d_ff, D),
                            jnp.float32)
        b_down = self.param("moe_b_down", nn.initializers.zeros_init(),
                            (e_decl, D), jnp.float32)

        if self.expert_axis is None:
            # The expert dim follows the LEAF: every real local
            # application carries full leaves (e_eff == n_experts,
            # exact semantics); the cache-init eval_shape applies the
            # TP-local clone outside shard_map, where only shapes flow.
            e_eff = w_up.shape[0]
            logits = h @ router[:, :e_eff]  # f32 promote: routing precision
            probs = jax.nn.softmax(logits, axis=-1)
            gate = jnp.max(probs, axis=-1)
            idx = jnp.argmax(probs, axis=-1)
            up = jnp.einsum("...d,edf->...ef", h,
                            w_up.astype(cd)) + b_up.astype(cd)
            down = jnp.einsum("...ef,efd->...ed", nn.gelu(up),
                              w_down.astype(cd)) + b_down.astype(cd)
            combine = (jax.nn.one_hot(idx, e_eff, dtype=down.dtype)
                       * gate.astype(down.dtype)[..., None])
            return jnp.einsum("...ed,...e->...d", down, combine)

        from chainermn_tpu.parallel import moe as _moe

        ax = self.expert_axis
        n = jax.lax.axis_size(ax)
        eps = w_up.shape[0]  # E_local: the sharder's slice, not E
        B, T, _ = h.shape
        rows = B * T
        own = -(-rows // n)
        hr = h.reshape(rows, D)
        if own * n != rows:
            hr = jnp.pad(hr, ((0, own * n - rows), (0, 0)))
        i = jax.lax.axis_index(ax)
        sl = jax.lax.dynamic_slice_in_dim(hr, i * own, own)
        eparams = (w_up.astype(cd), b_up.astype(cd),
                   w_down.astype(cd), b_down.astype(cd))
        if eps == 1:
            eparams = jax.tree.map(lambda l: l[0], eparams)

        def expert_mlp(p, xq):
            wu, bu, wd, bd = p
            return nn.gelu(xq @ wu + bu) @ wd + bd

        out_own = _moe.moe_layer_local(
            sl, router, expert_mlp, eparams, ax,
            capacity_factor=None, dispatch_impl=self.moe_dispatch_impl,
            experts_per_shard=eps,
        )
        full = jnp.zeros((own * n, D), out_own.dtype)
        full = jax.lax.dynamic_update_slice_in_dim(full, out_own,
                                                   i * own, 0)
        # ONE psum re-replicates — the MoE analogue of dense ff_down's
        # reduce_from_tp (TP stays at exactly 2 all-reduces per layer)
        full = jax.lax.psum(full, ax)
        return full[:rows].reshape(B, T, D)

    @nn.compact
    def __call__(self, x, segment_ids=None, rope_positions=None,
                 train: bool = True, decode: bool = False,
                 decode_positions=None, block_tables=None,
                 decode_slots=None, adapters=None):
        # ``train`` is positional so ``nn.remat(..., static_argnums=(4,))``
        # can mark it static. ``decode_positions`` ([B] int32 first-new
        # -token positions) selects the slot-array decode path
        # (:meth:`_slot_decode_attend`); ``block_tables`` ([B, max_blocks]
        # int32) feeds the paged layout; ``decode_slots`` ([B] int32) maps
        # token rows onto dense-cache rows (prefill of one slot out of
        # many); ``adapters`` ({'qkv'|'proj'|'ff_up'|'ff_down': (A, B)})
        # adds per-projection low-rank deltas (:meth:`_lora_delta`).
        D = x.shape[-1]
        head_dim = self.head_dim or D // self.num_heads
        kv_heads = self.num_kv_heads or self.num_heads
        attn = self.attention_fn or blockwise_attention
        if self.tp_axis is not None:
            from chainermn_tpu.parallel.tensor import (
                copy_to_tp,
                reduce_from_tp,
            )

        h = nn.LayerNorm(dtype=self.compute_dtype, param_dtype=jnp.float32)(x)
        if self.tp_axis is not None:
            h = copy_to_tp(h, self.tp_axis)
        qkv = nn.Dense(
            (self.num_heads + 2 * kv_heads) * head_dim, use_bias=False,
            dtype=self.compute_dtype, param_dtype=jnp.float32, name="qkv",
        )(h)
        # Column-parallel delta (ISSUE 14): h is replicated under TP
        # (post copy_to_tp), the adapter's B is column-sharded like the
        # qkv kernel — the delta lands on the shard's own columns, no
        # new collective.
        qkv = self._lora_delta("qkv", adapters, h, qkv)
        q, k, v = jnp.split(
            qkv,
            [self.num_heads * head_dim, (self.num_heads + kv_heads) * head_dim],
            axis=-1,
        )
        B, T = q.shape[:2]

        def heads(t, n):
            return t.reshape(B, T, n, head_dim)

        qh, kh = heads(q, self.num_heads), heads(k, kv_heads)
        if rope_positions is not None:
            qh = apply_rope(qh, rope_positions)
            kh = apply_rope(kh, rope_positions)
        if decode:
            if not self.causal:
                raise ValueError("decode=True requires a causal block")
            if decode_positions is not None:
                o = self._slot_decode_attend(
                    qh, kh, heads(v, kv_heads), head_dim,
                    decode_positions, block_tables, decode_slots,
                )
            else:
                if T != 1:
                    raise ValueError(
                        f"decode=True expects one token per step, got T={T}"
                    )
                o = self._decode_attend(qh, kh, heads(v, kv_heads), head_dim)
        else:
            if self.window is not None and self.attention_fn is None:
                raise ValueError(
                    "window needs a window-honouring attention_fn (e.g. "
                    "flash_attention(..., window=W)) — the default "
                    "blockwise reference has no window support"
                )
            if self.window is not None and not self.causal:
                raise ValueError("window requires a causal block")
            vh = heads(v, kv_heads)
            if self.sow_kv:
                self.sow("kv_out", "k", kh.astype(self.compute_dtype))
                self.sow("kv_out", "v", vh.astype(self.compute_dtype))
            kw = {} if segment_ids is None else {"segment_ids": segment_ids}
            o = attn(qh, kh, vh, causal=self.causal,
                     scale=head_dim**-0.5, **kw)
        o_flat = o.reshape(B, T, self.num_heads * head_dim)
        o = nn.Dense(
            D, use_bias=False,
            dtype=self.compute_dtype, param_dtype=jnp.float32, name="proj",
        )(o_flat)
        # Row-parallel delta (ISSUE 14): the adapter's A is sharded
        # along the same local-head rows as the proj kernel, so the
        # per-shard partial delta rides the existing psum below —
        # exactly the pre-adapter collective set.
        o = self._lora_delta("proj", adapters, o_flat, o)
        if self.tp_axis is not None:
            # Row-parallel output projection: the ONE psum of the
            # attention column→row pair.
            o = reduce_from_tp(o, self.tp_axis)
        if self.dropout_rate > 0.0:
            o = nn.Dropout(self.dropout_rate, deterministic=not train)(o)
        x = x + o

        h = nn.LayerNorm(dtype=self.compute_dtype, param_dtype=jnp.float32)(x)
        if self.n_experts > 0:
            if adapters is not None and (
                "ff_up" in adapters or "ff_down" in adapters
            ):
                raise ValueError(
                    "MoE blocks have no ff_up/ff_down projections to "
                    "hook — adapters may target qkv/proj only"
                )
            h = self._moe_ffn(h)
            if self.dropout_rate > 0.0:
                h = nn.Dropout(self.dropout_rate,
                               deterministic=not train)(h)
            return x + h
        if self.tp_axis is not None:
            h = copy_to_tp(h, self.tp_axis)
        up = nn.Dense(
            self.d_ff, dtype=self.compute_dtype, param_dtype=jnp.float32,
            name="ff_up",
        )(h)
        # Column-parallel (B sharded with the ff_up kernel's d_ff split).
        h = nn.gelu(self._lora_delta("ff_up", adapters, h, up))
        down = nn.Dense(
            D, dtype=self.compute_dtype, param_dtype=jnp.float32, name="ff_down",
        )(h)
        # Row-parallel (A sharded with the ff_down kernel's d_ff rows;
        # the partial delta rides the layer's second psum).
        h = self._lora_delta("ff_down", adapters, h, down)
        if self.tp_axis is not None:
            # Row-parallel FFN down projection (psum #2 of the layer).
            # ff_down's bias rides INSIDE the reduce: the sharder stores
            # bias / axis_size so the psum reassembles it exactly.
            h = reduce_from_tp(h, self.tp_axis)
        if self.dropout_rate > 0.0:
            h = nn.Dropout(self.dropout_rate, deterministic=not train)(h)
        return x + h


def _remat_block(remat_policy: str):
    """``nn.remat``-wrapped :class:`TransformerBlock` for the given save
    policy — ONE construction shared by :class:`TransformerLM` and
    :class:`chainermn_tpu.models.vit.VisionTransformer` so the
    policy-name surface cannot drift between the families."""
    if remat_policy == "dots":
        policy = jax.checkpoint_policies.dots_with_no_batch_dims_saveable
    elif remat_policy == "nothing":
        policy = None  # jax.checkpoint default: save nothing
    else:
        raise ValueError(
            f"remat_policy must be 'dots' or 'nothing', got "
            f"{remat_policy!r}"
        )
    return nn.remat(
        TransformerBlock,
        policy=policy,
        static_argnums=(4, 5),  # (self, x, seg, rope_pos, train, dec)
    )


class TransformerLM(nn.Module):
    """Causal LM over integer tokens ``[B, T]`` → logits ``[B, T, vocab]``."""

    vocab_size: int = 32000
    num_layers: int = 6
    num_heads: int = 8
    d_model: int = 512
    d_ff: int = 2048
    max_len: int = 2048
    compute_dtype: Any = jnp.bfloat16
    attention_fn: Optional[Callable] = None
    #: global position offset of the local sequence shard (sequence-parallel
    #: runs pass ``axis_index * T_local`` so learned positions line up).
    pos_offset: int = 0
    #: rematerialize each block in the backward pass (keep only the matmul
    #: outputs that feed the MXU — ``dots_with_no_batch_dims_saveable``);
    #: trades ~1/3 more FLOPs for activation memory, the standard TPU move
    #: for fitting larger B*T (SURVEY.md "use jax.checkpoint to trade FLOPs
    #: for memory").
    remat: bool = False
    #: remat save policy (with ``remat=True``): ``'dots'`` — keep matmul
    #: outputs, recompute elementwise/norm chains (the default; cheapest
    #: recompute); ``'nothing'`` — save only block inputs, recompute
    #: everything (max memory saving, ~1/3 extra FLOPs: the knob the MFU
    #: sweep explores for HBM-bound configs).
    remat_policy: str = "dots"
    #: skip the weight-tied LM head and return the final (post-LN) hidden
    #: states; pair with :func:`lm_loss_fused` to avoid materializing the
    #:  ``[B, T, vocab]`` logits tensor.
    return_hidden: bool = False
    #: kv heads for GQA/MQA (None → num_heads).
    num_kv_heads: Optional[int] = None
    #: ``'learned'`` (reference-style absolute table) or ``'rope'``
    #: (rotary — no position parameters; relative by construction, the
    #: natural choice under sequence parallelism where a learned table
    #: would need per-shard rolling).
    pos_encoding: str = "learned"
    #: causal sliding-window width (see ``TransformerBlock.window``):
    #: training requires a window-honouring ``attention_fn``; the decode
    #: path masks the KV cache to the same band automatically.
    window: Optional[int] = None
    #: residual dropout rate (see ``TransformerBlock.dropout_rate``);
    #: pass ``rngs={'dropout': key}`` to ``apply`` when training with it.
    dropout_rate: float = 0.0
    #: bidirectional (BERT/MLM-style) encoder when False: every block
    #: attends both directions, the weight-tied head scores each
    #: position against the full vocabulary (pair with
    #: :func:`mlm_loss`), and autoregressive decode is rejected.
    causal: bool = True
    #: decode KV-cache layout (see ``TransformerBlock.kv_layout``):
    #: ``'dense'`` or ``'paged'`` — the serving engine clones the model
    #: with the resolved layout; :func:`generate` uses the legacy dense
    #: ring either way.
    kv_layout: str = "dense"
    #: tokens per paged-pool block (``TransformerBlock.kv_block_size``).
    kv_block_size: int = 64
    #: paged-pool capacity in blocks (``TransformerBlock.kv_num_blocks``).
    kv_num_blocks: int = 0
    #: slot-decode attend impl (``TransformerBlock.decode_attend_impl``):
    #: ``'xla'`` or ``'fused'`` — the serving engine clones the model
    #: with the registry-resolved impl (decision ``decode_attend_impl``).
    decode_attend_impl: str = "xla"
    #: decode-cache capacity override: dense slot caches allocate
    #: ``decode_cache_len`` rows instead of ``max_len`` (a serving
    #: horizon shorter than the trained context — pos_emb stays at
    #: ``max_len`` so trained params load unchanged). None → ``max_len``.
    decode_cache_len: Optional[int] = None
    #: tensor-parallel mesh axis (see ``TransformerBlock.tp_axis``);
    #: set together with LOCAL ``num_heads``/``num_kv_heads``/``d_ff``
    #: and an explicit ``head_dim`` (the serving engine's
    #: ``shard_lm_params`` builds the matching param tree).
    tp_axis: Optional[str] = None
    #: per-head width override for the blocks (required under
    #: ``tp_axis``).
    head_dim: Optional[int] = None
    #: thread ``TransformerBlock.sow_kv`` through every block (the
    #: sequence-parallel prefill's KV capture, ISSUE 13).
    sow_kv: bool = False
    #: mixture-of-experts FFN in every block (ISSUE 20; see
    #: ``TransformerBlock.n_experts``). 0 (default) = dense FFN.
    #: GLOBAL expert count — under ``expert_axis`` the serving sharder
    #: slices the stacked expert leaves, the field does not change.
    n_experts: int = 0
    #: expert-shard mesh axis for serving decode (see
    #: ``TransformerBlock.expert_axis``; the engine sets it to its TP
    #: axis — expert shards live on the TP mesh).
    expert_axis: Optional[str] = None
    #: MoE queue-build impl for the ownership-split path
    #: (``TransformerBlock.moe_dispatch_impl``).
    moe_dispatch_impl: str = "auto"
    #: declared expert-leaf leading dim for per-shard param trees
    #: (``TransformerBlock.moe_experts_local``; the engine's TP clone
    #: sets ``n_experts // tp``).
    moe_experts_local: Optional[int] = None

    @nn.compact
    def __call__(self, tokens, *, segment_ids=None, positions=None,
                 train: bool = True, decode: bool = False,
                 decode_positions=None, block_tables=None,
                 decode_slots=None, adapters=None):
        """``segment_ids`` (optional ``[B, T]``) confines attention to
        packed documents; requires a segment-capable ``attention_fn``
        (e.g. :func:`chainermn_tpu.ops.flash_attention.flash_attention`).
        ``positions`` (optional ``[T]`` int32 GLOBAL positions) overrides
        ``pos_offset + arange(T)`` — sequence-parallel shards pass
        ``axis_index * T_local + arange(T_local)``.
        ``decode=True`` runs one-token autoregressive steps (``T == 1``)
        against the mutable ``'cache'`` collection; see :func:`generate`.
        ``decode_positions`` (optional ``[B]`` int32) switches decode to
        the slot-array path — per-row write positions, ``T >= 1``
        chunked prefill, paged/dense layouts, ``decode_slots`` row
        mapping — the serving engine's contract
        (:mod:`chainermn_tpu.serving`).
        ``adapters`` (optional, ISSUE 14): per-layer low-rank deltas —
        a sequence of ``num_layers`` dicts, each mapping a hooked
        projection (``qkv``/``proj``/``ff_up``/``ff_down``) to its
        ``(A, B)`` pair (see :meth:`TransformerBlock._lora_delta` for
        the unbatched vs per-row forms); the serving engine's
        :class:`~chainermn_tpu.serving.adapters.AdapterBank` builds
        both."""
        if segment_ids is not None and self.attention_fn is None:
            raise ValueError(
                "segment_ids needs a segment-capable attention_fn — pass "
                "attention_fn=flash_attention (the default blockwise "
                "reference does not take segment masks)"
            )
        if self.pos_encoding not in ("learned", "rope"):
            raise ValueError(
                f"pos_encoding must be 'learned' or 'rope', got "
                f"{self.pos_encoding!r}"
            )
        if decode and not self.causal:
            raise ValueError(
                "decode=True is autoregressive and requires causal=True"
            )
        if decode_positions is not None and not decode:
            raise ValueError("decode_positions requires decode=True")
        if adapters is not None and len(adapters) != self.num_layers:
            raise ValueError(
                f"adapters covers {len(adapters)} layers, model has "
                f"{self.num_layers}"
            )
        B, T = tokens.shape
        if decode_positions is not None and positions is None:
            # Per-row global positions for rope / the learned table:
            # row b's tokens sit at decode_positions[b] + [0, T).
            positions = (decode_positions[:, None]
                         + jnp.arange(T, dtype=jnp.int32)[None])
        emb = nn.Embed(
            self.vocab_size, self.d_model, param_dtype=jnp.float32,
            dtype=self.compute_dtype, name="tok_emb",
        )
        x = emb(tokens)
        rope_positions = None
        if self.pos_encoding == "rope":
            if positions is None:
                positions = self.pos_offset + jnp.arange(T, dtype=jnp.int32)
            rope_positions = positions
        else:
            pos_emb = self.param(
                "pos_emb",
                nn.initializers.normal(0.02),
                (self.max_len, self.d_model),
                jnp.float32,
            )
            if positions is not None:
                pos = pos_emb[positions]  # [T, D] or [B, T, D] (per-row)
            else:
                pos = jax.lax.dynamic_slice_in_dim(
                    pos_emb, self.pos_offset, T, axis=0
                )
            if pos.ndim == 2:
                pos = pos[None]
            x = x + pos.astype(self.compute_dtype)
        block_cls = (
            _remat_block(self.remat_policy) if self.remat
            else TransformerBlock
        )
        for i in range(self.num_layers):
            x = block_cls(
                num_heads=self.num_heads,
                d_ff=self.d_ff,
                compute_dtype=self.compute_dtype,
                attention_fn=self.attention_fn,
                num_kv_heads=self.num_kv_heads,
                decode_max_len=self.decode_cache_len or self.max_len,
                window=self.window,
                dropout_rate=self.dropout_rate,
                causal=self.causal,
                kv_layout=self.kv_layout,
                kv_block_size=self.kv_block_size,
                kv_num_blocks=self.kv_num_blocks,
                decode_attend_impl=self.decode_attend_impl,
                tp_axis=self.tp_axis,
                head_dim=self.head_dim,
                sow_kv=self.sow_kv,
                n_experts=self.n_experts,
                expert_axis=self.expert_axis,
                moe_dispatch_impl=self.moe_dispatch_impl,
                moe_experts_local=self.moe_experts_local,
                name=f"block_{i}",
            )(x, segment_ids, rope_positions, train, decode,
              decode_positions, block_tables, decode_slots,
              adapters[i] if adapters is not None else None)
        x = nn.LayerNorm(dtype=self.compute_dtype, param_dtype=jnp.float32)(x)
        if self.return_hidden:
            return x
        logits = emb.attend(x.astype(jnp.float32))  # weight-tied output head
        return logits


def lm_loss(logits, tokens, mask=None):
    """Next-token cross-entropy: predict ``tokens[:, 1:]`` from positions
    ``[:, :-1]``; optional padding ``mask`` (same shape as tokens, 1=real)."""
    import optax

    targets = tokens[:, 1:]
    logits = logits[:, :-1]
    losses = optax.softmax_cross_entropy_with_integer_labels(logits, targets)
    if mask is not None:
        m = mask[:, 1:].astype(losses.dtype)
        return (losses * m).sum() / jnp.maximum(m.sum(), 1)
    return losses.mean()


def mlm_loss(logits, targets, mask):
    """Masked-LM cross-entropy: predict the ORIGINAL token at each masked
    position (no shift — the encoder sees both directions). ``targets``
    are the pre-masking tokens, ``mask`` is 1 where the input was
    corrupted (the only positions scored, per the BERT recipe)."""
    import optax

    losses = optax.softmax_cross_entropy_with_integer_labels(
        logits, targets
    )
    m = mask.astype(losses.dtype)
    return (losses * m).sum() / jnp.maximum(m.sum(), 1)


def mlm_corrupt(rng, tokens, *, mask_id, vocab_size, rate=0.15):
    """BERT-style corruption under jit: select ``rate`` of positions;
    of those 80% → ``mask_id``, 10% → random REAL token, 10% →
    unchanged. Returns ``(corrupted, selected_mask)``. Random draws
    that would land on ``mask_id`` are shifted by one (mod vocab) so
    the documented 80/10/10 mix holds even for small vocabularies."""
    k1, k2, k3 = jax.random.split(rng, 3)
    sel = jax.random.uniform(k1, tokens.shape) < rate
    roll = jax.random.uniform(k2, tokens.shape)
    rand_tok = jax.random.randint(k3, tokens.shape, 0, vocab_size)
    rand_tok = jnp.where(rand_tok == mask_id,
                         (rand_tok + 1) % vocab_size, rand_tok)
    corrupted = jnp.where(sel & (roll < 0.8), mask_id, tokens)
    corrupted = jnp.where(sel & (roll >= 0.8) & (roll < 0.9), rand_tok,
                          corrupted)
    return corrupted, sel


def lm_loss_fused(hidden, emb_table, tokens, *, n_chunks=8,
                  compute_dtype=jnp.bfloat16):
    """Fused chunked LM-head + next-token cross-entropy.

    The naive head materializes ``[B, T, vocab]`` f32 logits (≈ 4·B·T·V
    bytes of HBM traffic both ways, plus an f32 matmul off the MXU's fast
    path). This computes the head matmul per token-chunk in ``compute_dtype``
    with f32 MXU accumulation, reduces each chunk to its scalar loss
    immediately, and rematerializes the chunk in the backward pass
    (``jax.checkpoint``) — so the full logits tensor never exists in HBM in
    either pass. Equivalent to ``lm_loss(emb.attend(hidden), tokens)`` up to
    compute-dtype rounding; pair with ``TransformerLM(return_hidden=True)``.

    Args:
      hidden: final post-LN hidden states ``[B, T, D]``.
      emb_table: tied embedding table ``[vocab, D]`` (f32 master copy).
      tokens: integer tokens ``[B, T]``.
      n_chunks: token-dimension split; ``B*(T-1)`` need not divide evenly —
        the tail partial chunk is padded and masked out.
    """
    B, T, D = hidden.shape
    h = hidden[:, :-1].reshape(-1, D)
    t = tokens[:, 1:].reshape(-1)
    n = h.shape[0]
    chunk = -(-n // n_chunks)  # ceil
    pad = chunk * n_chunks - n
    h = jnp.pad(h, ((0, pad), (0, 0)))
    t = jnp.pad(t, (0, pad))
    valid = jnp.pad(jnp.ones((n,), jnp.float32), (0, pad))
    w = emb_table.astype(compute_dtype).T  # [D, vocab]

    @jax.checkpoint
    def chunk_loss(hc, tc, mc):
        logits = jnp.dot(
            hc.astype(compute_dtype), w,
            preferred_element_type=jnp.float32,
        )
        lse = jax.nn.logsumexp(logits, axis=-1)
        gold = jnp.take_along_axis(logits, tc[:, None], axis=-1)[:, 0]
        return jnp.sum((lse - gold) * mc)

    def body(acc, xs):
        hc, tc, mc = xs
        return acc + chunk_loss(hc, tc, mc), ()

    total, _ = jax.lax.scan(
        body, jnp.float32(0.0),
        (h.reshape(n_chunks, chunk, D),
         t.reshape(n_chunks, chunk),
         valid.reshape(n_chunks, chunk)),
    )
    return total / n


def init_cache(model: TransformerLM, params, batch_size: int):
    """Allocate the fixed-shape KV cache for ``generate`` (one
    ``[B, max_len, kv_heads, head_dim]`` key+value pair per block, plus a
    scalar write index). Pure shape evaluation — no FLOPs run."""
    dummy = jnp.zeros((batch_size, 1), jnp.int32)
    variables = jax.eval_shape(
        lambda: model.apply(
            params, dummy,
            positions=jnp.zeros((1,), jnp.int32),
            train=False, decode=True, mutable=["cache"],
        )[1]
    )
    return jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), variables)


def _decode_setup(model: TransformerLM, params, prompt, n_steps, pad_id):
    """Shared ``generate``/``beam_search`` scaffolding: validation,
    per-row true prompt lengths, and the prompt padded out to the decode
    horizon."""
    if model.return_hidden:
        raise ValueError("decoding needs logits; build the model with "
                         "return_hidden=False")
    if n_steps > model.max_len:
        raise ValueError(
            f"n_steps={n_steps} exceeds the cache capacity "
            f"max_len={model.max_len}"
        )
    B, P = prompt.shape
    # True length = index of the FIRST pad (rows without pad span all of
    # P): the right-padding convention. Tokens after a mid-row pad_id are
    # ignored — counting non-pad tokens instead would silently misalign
    # teacher forcing for such rows, which is worse than truncating.
    is_pad = prompt == pad_id
    prompt_len = jnp.where(
        jnp.any(is_pad, axis=1),
        jnp.argmax(is_pad, axis=1).astype(jnp.int32),
        jnp.int32(P),
    )
    padded = jnp.pad(prompt, ((0, 0), (0, max(0, n_steps - P))),
                     constant_values=pad_id)
    return B, P, prompt_len, padded


def _filter_logits(logits, top_k, top_p):
    """Top-k / nucleus filtering on ``[B, V]`` logits: tokens outside the
    k highest (and outside the smallest set whose probability mass
    reaches ``top_p``) are masked to -inf. Static shapes throughout —
    the nucleus cut uses a sorted cumulative sum, no dynamic slicing."""
    if top_p is None:
        if top_k is not None:
            kth = jax.lax.top_k(logits, top_k)[0][:, -1:]  # k-th largest
            logits = jnp.where(logits < kth, -jnp.inf, logits)
        return logits
    sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
    if top_k is not None:
        # Reuse the descending sort for the k-th threshold — no second
        # vocab-sized pass — and restrict the nucleus mass to the top-k
        # survivors (HF semantics: top_p renormalizes AFTER top_k).
        kth = sorted_logits[:, top_k - 1:top_k]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
        sorted_logits = jnp.where(
            jnp.arange(sorted_logits.shape[-1])[None] < top_k,
            sorted_logits, -jnp.inf,
        )
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # Keep tokens while the mass BEFORE them is < top_p (the first
    # token is always kept).
    keep_sorted = jnp.concatenate(
        [jnp.ones_like(cum[:, :1], bool), cum[:, :-1] < top_p], axis=-1
    )
    # Threshold = smallest kept logit per row.
    thresh = jnp.min(
        jnp.where(keep_sorted, sorted_logits, jnp.inf), axis=-1,
        keepdims=True,
    )
    return jnp.where(logits < thresh, -jnp.inf, logits)


def _tempered_filtered(logits, temperature, top_k, top_p):
    """Sampling logits: temperature FIRST, then top-k/top-p (the HF
    convention — the nucleus is selected from the temperature-adjusted
    distribution, so top_p values tuned elsewhere transfer; under
    filter-then-temperature the survivor set would be temperature
    -independent)."""
    return _filter_logits(logits / temperature, top_k, top_p)


def stream_sample_keys(base_key, seeds, counters):
    """Counter-based sampling keys (docs/serving.md "Sampling"): row ``i``
    draws with ``fold_in(fold_in(base_key, seeds[i]), counters[i])``.

    The key for a sampled token is a PURE function of (base key, request
    seed, absolute stream position) — there is no consumed split chain, so
    it does not depend on which program asks: monolithic ``generate``, the
    serving engine's decode/verify/mixed grids, a chunked or
    sequence-parallel prefill, or a resumed stream on another replica all
    derive the identical key for position ``i`` of request ``seeds[i]``.
    That invariance is what extends the bit-identical-stream guarantee to
    ``temperature > 0``: any schedule that reaches position ``i`` with the
    same history sees the same logits AND the same key, hence the same
    token. ``counters[i]`` is the absolute position of the token being
    SAMPLED (the first generated token of a length-P prompt has counter
    P). Threefry is batch-invariant, so per-row keys drawn here match
    per-request individual calls exactly.
    """
    def one(seed, counter):
        return jax.random.fold_in(jax.random.fold_in(base_key, seed), counter)

    return jax.vmap(one)(jnp.asarray(seeds), jnp.asarray(counters))


def generate(model: TransformerLM, params, prompt, n_steps: int, *,
             temperature: float = 0.0, rng=None, seeds=None, pad_id: int = 0,
             top_k: Optional[int] = None, top_p: Optional[float] = None,
             adapters=None):
    """Autoregressive generation with a per-block KV cache.

    TPU-first shape discipline: ONE jitted ``lax.scan`` of single-token
    decode steps covers both prefill and sampling — step ``t`` feeds the
    prompt token while ``t < prompt_len`` (teacher forcing) and the
    previous step's sampled token afterwards, so there is exactly one
    compiled program regardless of prompt length (no per-length
    recompiles; a ragged batch of prompts just pads with ``pad_id`` and
    per-row lengths). The cache is written in the same pass the prompt is
    consumed, so no separate prefill program is needed.

    Args:
      model: a ``TransformerLM`` (``return_hidden`` must be False).
      params: the ``{'params': ...}`` variables from ``init``/training.
      prompt: ``[B, P]`` int32 prompt tokens, right-padded with ``pad_id``.
      n_steps: total sequence length to produce INCLUDING the prompt
        (``<= model.max_len``).
      temperature: 0 → greedy argmax; otherwise softmax sampling at this
        temperature (requires ``rng``).
      rng: PRNG BASE key for sampling (ignored when greedy). Keys are
        derived per token by :func:`stream_sample_keys` — position ``t``
        of row ``i`` draws with ``fold_in(fold_in(rng, seeds[i]), t)`` —
        not by a consumed split chain, so generation at a fixed
        ``(rng, seeds)`` is bit-identical to the serving engine's
        chunked / sequence-parallel / speculative schedules over the
        same requests.
      seeds: ``[B]`` int32 per-row stream seeds (default all zeros).
        The serving scheduler derives one per request
        (``crc32(request_id)``); pass the same values here to reproduce
        a served stream exactly.
      top_k: sample only among the k highest-probability tokens.
      top_p: nucleus sampling — restrict to the smallest token set whose
        probability mass reaches ``top_p``. Composes with ``top_k``
        (intersection) and is computed AFTER the temperature division
        (the HF convention, so tuned values transfer). Both require
        ``temperature > 0``.
      pad_id: padding token in ``prompt``; positions where every shorter
        row has run out of prompt switch to model continuations.
      adapters: optional per-layer low-rank deltas (ISSUE 14) — the
        unbatched ``(A, B)`` form shared by every row; the single-
        tenant reference the serving engine's per-slot gather is pinned
        against (``AdapterBank.adapter_arrays`` hands out exactly the
        values the engine gathers, scale pre-folded).

    Returns:
      ``[B, n_steps]`` int32 tokens (prompt positions pass through).
    """
    B, P, prompt_len, padded_prompt = _decode_setup(
        model, params, prompt, n_steps, pad_id
    )
    cache = init_cache(model, params, B)["cache"]
    if temperature > 0.0 and rng is None:
        raise ValueError("sampling (temperature > 0) requires rng")
    if (top_k is not None or top_p is not None) and temperature <= 0.0:
        raise ValueError("top_k/top_p filtering is for sampling — set "
                         "temperature > 0")
    if top_p is not None and not (0.0 < top_p <= 1.0):
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")
    if top_k is not None and not (1 <= top_k <= model.vocab_size):
        raise ValueError(
            f"top_k must be in [1, vocab_size={model.vocab_size}], "
            f"got {top_k}"
        )
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    seeds = (jnp.zeros((B,), jnp.int32) if seeds is None
             else jnp.asarray(seeds, jnp.int32))

    def step(carry, t):
        cache, prev_tok = carry
        # Teacher-force while this row still has prompt left.
        in_prompt = t < prompt_len  # [B]
        tok = jnp.where(in_prompt, padded_prompt[:, t], prev_tok)
        logits, mutated = model.apply(
            {**params, "cache": cache}, tok[:, None],
            positions=jnp.full((1,), t, jnp.int32),
            train=False, decode=True, mutable=["cache"],
            adapters=adapters,
        )
        logits = logits[:, 0]  # [B, vocab]
        if temperature > 0.0:
            # Step t samples the token for position t+1: counter t+1.
            # No key threads through the carry — each position's key is
            # derived fresh, so discarded draws (teacher-forced rows)
            # never perturb later positions.
            keys = stream_sample_keys(
                rng, seeds, jnp.full((B,), t + 1, jnp.int32))
            nxt = jax.vmap(jax.random.categorical)(
                keys, _tempered_filtered(logits, temperature, top_k, top_p),
            )
        else:
            nxt = jnp.argmax(logits, axis=-1)
        return (mutated["cache"], nxt.astype(prompt.dtype)), tok

    _, toks = jax.lax.scan(
        step, (cache, padded_prompt[:, 0]),
        jnp.arange(n_steps, dtype=jnp.int32),
    )
    # ``toks[t]`` is the token CONSUMED at position t, which is already
    # the desired output there: the prompt token while t < prompt_len,
    # and otherwise prev_tok — i.e. the model's sample from step t-1,
    # its continuation for position t.
    return jnp.moveaxis(toks, 0, 1)  # [B, n_steps]


def beam_search(model: TransformerLM, params, prompt, n_steps: int,
                beam_size: int, *, eos_id: Optional[int] = None,
                pad_id: int = 0, length_penalty: float = 0.0):
    """Beam-search decoding over the KV cache — ONE jitted ``lax.scan``.

    Same shape discipline as :func:`generate`: prompt consumption and
    beam expansion share the scan (prompt steps force every beam onto the
    prompt token with scores pinned to ``[0, -inf, ...]``, so the first
    free step expands from a single live beam), and the per-block caches
    are batched ``B·beam`` and REORDERED by backpointer gather at every
    step — no post-hoc hypothesis reconstruction pass.

    Args:
      model: ``TransformerLM`` with ``return_hidden=False``.
      params: ``{'params': ...}`` variables.
      prompt: ``[B, P]`` int32, right-padded with ``pad_id`` (ragged rows
        expand beams from their own true length).
      n_steps: total length INCLUDING the prompt (``<= model.max_len``).
      beam_size: hypotheses kept per row.
      eos_id: optional end token: finished beams are frozen (they extend
        only with ``pad_id`` at no score change).
      length_penalty: GNMT alpha — hypotheses are RANKED by
        ``score / ((5 + len) / 6)**alpha`` (len = generated tokens up to
        and including EOS): positive counters the short-hypothesis bias
        of raw summed log-probs, negative favours shorter hypotheses,
        0 ranks by raw score. The returned ``scores`` stay raw either
        way.

    Returns:
      ``(tokens, scores)``: ``[B, beam, n_steps]`` int32 hypotheses
      (best-first under the chosen ranking) and their ``[B, beam]`` raw
      summed log-probabilities.
    """
    if beam_size < 1:
        raise ValueError(f"beam_size must be >= 1, got {beam_size}")
    B, P, prompt_len, padded = _decode_setup(
        model, params, prompt, n_steps, pad_id
    )
    K = beam_size
    V = model.vocab_size

    cache = init_cache(model, params, B * K)["cache"]
    scores0 = jnp.tile(
        jnp.array([0.0] + [-jnp.inf] * (K - 1), jnp.float32), (B, 1)
    )
    seqs0 = jnp.full((B, K, n_steps), pad_id, prompt.dtype)

    def reorder(tree, parents):
        """Gather the beam dimension of ``[B·K, ...]`` cache leaves by
        the ``[B, K]`` backpointers."""
        def one(leaf):
            if leaf.ndim == 0:  # shared cache_index scalar
                return leaf
            shaped = leaf.reshape(B, K, *leaf.shape[1:])
            idx = parents.reshape(B, K, *([1] * (leaf.ndim - 1)))
            return jnp.take_along_axis(shaped, idx, axis=1).reshape(
                leaf.shape
            )
        return jax.tree.map(one, tree)

    def step(carry, t):
        cache, prev_tok, scores, seqs, finished, gen_len = carry
        # Two per-row phases, offset by one: the token CONSUMED at t is
        # prompt-forced while t < prompt_len, but the EXPANSION chosen at
        # t is consumed at t+1 — so beam search activates one step early,
        # at the LAST prompt step (t == prompt_len - 1), where the top-K
        # first tokens and their scores spread from the single live beam.
        in_prompt = (t < prompt_len)[:, None]  # [B, 1] consumption phase
        # Beam phase: the expansion chosen at t is consumed at t+1, so it
        # activates one step before the prompt ends AND must NOT commit on
        # the final step (that choice would never be consumed — scoring or
        # reordering by it would corrupt the returned hypotheses).
        expanding = (
            (t >= prompt_len - 1)[:, None] & (t < n_steps - 1)
        )  # [B, 1]
        tok = jnp.where(in_prompt, padded[:, t][:, None], prev_tok)

        logits, mutated = model.apply(
            {**params, "cache": cache}, tok.reshape(B * K, 1),
            positions=jnp.full((1,), t, jnp.int32),
            train=False, decode=True, mutable=["cache"],
        )
        logp = jax.nn.log_softmax(
            logits[:, 0].astype(jnp.float32)
        ).reshape(B, K, V)

        # Frozen (finished) beams may only extend with pad at no cost.
        if eos_id is not None:
            frozen = jnp.full((V,), -jnp.inf).at[pad_id].set(0.0)
            logp = jnp.where(finished[..., None], frozen[None, None], logp)

        total = scores[..., None] + logp  # [B, K, V]
        top_scores, flat_idx = jax.lax.top_k(total.reshape(B, K * V), K)
        parents = flat_idx // V  # [B, K]
        next_tok = (flat_idx % V).astype(prompt.dtype)

        # Pre-expansion prompt steps: identity beams, pinned scores (the
        # chosen next_tok is irrelevant — consumption stays forced).
        ident = jnp.broadcast_to(jnp.arange(K, dtype=parents.dtype), (B, K))
        parents = jnp.where(expanding, parents, ident)
        new_scores = jnp.where(expanding, top_scores, scores)

        # The identity gather of prefill steps is not free (parents is
        # traced — XLA cannot fold it): skip the whole-cache copy until
        # some row actually expands.
        cache = jax.lax.cond(
            jnp.any(expanding),
            lambda c: reorder(c, parents),
            lambda c: c,
            mutated["cache"],
        )
        seqs = jnp.take_along_axis(seqs, parents[..., None], axis=1)
        # Position t records the token CONSUMED at t by this slot's
        # PARENT lineage (gather tok by backpointer — in prompt steps the
        # token is row-uniform so the gather is a no-op).
        seqs = seqs.at[:, :, t].set(
            jnp.take_along_axis(tok, parents, axis=1)
        )
        # Generated-token count per surviving lineage (for the length
        # penalty): a committed expansion by an unfinished beam adds one.
        gen_len = jnp.take_along_axis(gen_len, parents, axis=1)
        if eos_id is not None:
            finished = jnp.take_along_axis(finished, parents, axis=1)
        gen_len = gen_len + (expanding & ~finished).astype(jnp.int32)
        if eos_id is not None:
            finished = finished | (expanding & (next_tok == eos_id))
        return ((cache, next_tok, new_scores, seqs, finished, gen_len),
                None)

    finished0 = jnp.zeros((B, K), bool)
    (cache, last, scores, seqs, finished, gen_len), _ = jax.lax.scan(
        step,
        (cache, jnp.broadcast_to(padded[:, 0][:, None], (B, K)),
         scores0, seqs0, finished0, jnp.zeros((B, K), jnp.int32)),
        jnp.arange(n_steps, dtype=jnp.int32),
    )
    if length_penalty != 0.0:
        from chainermn_tpu.models._decode_common import rank_beams

        return rank_beams(seqs, scores, gen_len, length_penalty)
    order = jnp.argsort(-scores, axis=1)
    return (jnp.take_along_axis(seqs, order[..., None], axis=1),
            jnp.take_along_axis(scores, order, axis=1))
