"""Transformer-base causal LM — the ``BASELINE.json`` benchmark config that
exercises large embedding gradients and the double-buffered allreduce
(``Transformer-base LM (new — large embedding grads, double-buffered
allreduce)``). Not present in the reference (2017-era); shape follows the
original Transformer-base (6 layers, d_model 512, 8 heads, d_ff 2048).

TPU-first choices: bf16 compute / f32 params; pre-LN (stable without warmup
gymnastics); pluggable attention so the same module runs single-device
(flash/blockwise kernels, :mod:`chainermn_tpu.ops`) or sequence-parallel
(ring/Ulysses locals from :mod:`chainermn_tpu.parallel` when applied inside
``shard_map`` — pass ``attention_fn=lambda q,k,v,causal,scale:
ring_attention_local(q, k, v, 'seq', causal=causal, scale=scale)``).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from chainermn_tpu.ops.attention import blockwise_attention


class TransformerBlock(nn.Module):
    num_heads: int
    d_ff: int
    compute_dtype: Any = jnp.bfloat16
    attention_fn: Optional[Callable] = None
    dropout_rate: float = 0.0

    @nn.compact
    def __call__(self, x, *, train: bool = True):
        D = x.shape[-1]
        head_dim = D // self.num_heads
        attn = self.attention_fn or blockwise_attention

        h = nn.LayerNorm(dtype=self.compute_dtype, param_dtype=jnp.float32)(x)
        qkv = nn.Dense(
            3 * D, use_bias=False,
            dtype=self.compute_dtype, param_dtype=jnp.float32, name="qkv",
        )(h)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        B, T = q.shape[:2]

        def heads(t):
            return t.reshape(B, T, self.num_heads, head_dim)

        o = attn(heads(q), heads(k), heads(v), causal=True, scale=head_dim**-0.5)
        o = nn.Dense(
            D, use_bias=False,
            dtype=self.compute_dtype, param_dtype=jnp.float32, name="proj",
        )(o.reshape(B, T, D))
        x = x + o

        h = nn.LayerNorm(dtype=self.compute_dtype, param_dtype=jnp.float32)(x)
        h = nn.Dense(
            self.d_ff, dtype=self.compute_dtype, param_dtype=jnp.float32,
            name="ff_up",
        )(h)
        h = nn.gelu(h)
        h = nn.Dense(
            D, dtype=self.compute_dtype, param_dtype=jnp.float32, name="ff_down",
        )(h)
        return x + h


class TransformerLM(nn.Module):
    """Causal LM over integer tokens ``[B, T]`` → logits ``[B, T, vocab]``."""

    vocab_size: int = 32000
    num_layers: int = 6
    num_heads: int = 8
    d_model: int = 512
    d_ff: int = 2048
    max_len: int = 2048
    compute_dtype: Any = jnp.bfloat16
    attention_fn: Optional[Callable] = None
    #: global position offset of the local sequence shard (sequence-parallel
    #: runs pass ``axis_index * T_local`` so learned positions line up).
    pos_offset: int = 0

    @nn.compact
    def __call__(self, tokens, *, train: bool = True):
        B, T = tokens.shape
        emb = nn.Embed(
            self.vocab_size, self.d_model, param_dtype=jnp.float32,
            dtype=self.compute_dtype, name="tok_emb",
        )
        pos_emb = self.param(
            "pos_emb",
            nn.initializers.normal(0.02),
            (self.max_len, self.d_model),
            jnp.float32,
        )
        x = emb(tokens)
        pos = jax.lax.dynamic_slice_in_dim(pos_emb, self.pos_offset, T, axis=0)
        x = x + pos[None].astype(self.compute_dtype)
        for i in range(self.num_layers):
            x = TransformerBlock(
                num_heads=self.num_heads,
                d_ff=self.d_ff,
                compute_dtype=self.compute_dtype,
                attention_fn=self.attention_fn,
                name=f"block_{i}",
            )(x, train=train)
        x = nn.LayerNorm(dtype=self.compute_dtype, param_dtype=jnp.float32)(x)
        logits = emb.attend(x.astype(jnp.float32))  # weight-tied output head
        return logits


def lm_loss(logits, tokens, mask=None):
    """Next-token cross-entropy: predict ``tokens[:, 1:]`` from positions
    ``[:, :-1]``; optional padding ``mask`` (same shape as tokens, 1=real)."""
    import optax

    targets = tokens[:, 1:]
    logits = logits[:, :-1]
    losses = optax.softmax_cross_entropy_with_integer_labels(logits, targets)
    if mask is not None:
        m = mask[:, 1:].astype(losses.dtype)
        return (losses * m).sum() / jnp.maximum(m.sum(), 1)
    return losses.mean()
