"""Transformer-base causal LM — the ``BASELINE.json`` benchmark config that
exercises large embedding gradients and the double-buffered allreduce
(``Transformer-base LM (new — large embedding grads, double-buffered
allreduce)``). Not present in the reference (2017-era); shape follows the
original Transformer-base (6 layers, d_model 512, 8 heads, d_ff 2048).

TPU-first choices: bf16 compute / f32 params; pre-LN (stable without warmup
gymnastics); pluggable attention so the same module runs single-device
(flash/blockwise kernels, :mod:`chainermn_tpu.ops`) or sequence-parallel
(ring/Ulysses locals from :mod:`chainermn_tpu.parallel` when applied inside
``shard_map`` — pass ``attention_fn=lambda q,k,v,causal,scale:
ring_attention_local(q, k, v, 'seq', causal=causal, scale=scale)``).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from chainermn_tpu.ops.attention import blockwise_attention


def apply_rope(x, positions, base: float = 10000.0):
    """Rotary position embedding on ``[B, T, H, Dh]`` (half-split pairing).

    ``positions``: ``[T]`` GLOBAL positions — sequence-parallel shards pass
    their own offsets, so rotations agree across shards (rotation commutes
    with the ring/Ulysses resharding because it is per-position).
    """
    half = x.shape[-1] // 2
    freqs = base ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    ang = positions.astype(jnp.float32)[:, None] * freqs[None]  # [T, half]
    cos = jnp.cos(ang)[None, :, None, :].astype(x.dtype)
    sin = jnp.sin(ang)[None, :, None, :].astype(x.dtype)
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos],
                           axis=-1)


class TransformerBlock(nn.Module):
    num_heads: int
    d_ff: int
    compute_dtype: Any = jnp.bfloat16
    attention_fn: Optional[Callable] = None
    dropout_rate: float = 0.0
    #: kv heads for GQA/MQA (None → num_heads, i.e. standard MHA). The kv
    #: projection shrinks accordingly; the attention kernel shares kv heads
    #: across their q-head group (:mod:`chainermn_tpu.ops.flash_attention`).
    num_kv_heads: Optional[int] = None

    @nn.compact
    def __call__(self, x, segment_ids=None, rope_positions=None,
                 train: bool = True):
        # ``train`` is positional so ``nn.remat(..., static_argnums=(4,))``
        # can mark it static.
        D = x.shape[-1]
        head_dim = D // self.num_heads
        kv_heads = self.num_kv_heads or self.num_heads
        attn = self.attention_fn or blockwise_attention

        h = nn.LayerNorm(dtype=self.compute_dtype, param_dtype=jnp.float32)(x)
        qkv = nn.Dense(
            (self.num_heads + 2 * kv_heads) * head_dim, use_bias=False,
            dtype=self.compute_dtype, param_dtype=jnp.float32, name="qkv",
        )(h)
        q, k, v = jnp.split(
            qkv,
            [self.num_heads * head_dim, (self.num_heads + kv_heads) * head_dim],
            axis=-1,
        )
        B, T = q.shape[:2]

        def heads(t, n):
            return t.reshape(B, T, n, head_dim)

        qh, kh = heads(q, self.num_heads), heads(k, kv_heads)
        if rope_positions is not None:
            qh = apply_rope(qh, rope_positions)
            kh = apply_rope(kh, rope_positions)
        kw = {} if segment_ids is None else {"segment_ids": segment_ids}
        o = attn(qh, kh,
                 heads(v, kv_heads), causal=True, scale=head_dim**-0.5, **kw)
        o = nn.Dense(
            D, use_bias=False,
            dtype=self.compute_dtype, param_dtype=jnp.float32, name="proj",
        )(o.reshape(B, T, D))
        x = x + o

        h = nn.LayerNorm(dtype=self.compute_dtype, param_dtype=jnp.float32)(x)
        h = nn.Dense(
            self.d_ff, dtype=self.compute_dtype, param_dtype=jnp.float32,
            name="ff_up",
        )(h)
        h = nn.gelu(h)
        h = nn.Dense(
            D, dtype=self.compute_dtype, param_dtype=jnp.float32, name="ff_down",
        )(h)
        return x + h


class TransformerLM(nn.Module):
    """Causal LM over integer tokens ``[B, T]`` → logits ``[B, T, vocab]``."""

    vocab_size: int = 32000
    num_layers: int = 6
    num_heads: int = 8
    d_model: int = 512
    d_ff: int = 2048
    max_len: int = 2048
    compute_dtype: Any = jnp.bfloat16
    attention_fn: Optional[Callable] = None
    #: global position offset of the local sequence shard (sequence-parallel
    #: runs pass ``axis_index * T_local`` so learned positions line up).
    pos_offset: int = 0
    #: rematerialize each block in the backward pass (keep only the matmul
    #: outputs that feed the MXU — ``dots_with_no_batch_dims_saveable``);
    #: trades ~1/3 more FLOPs for activation memory, the standard TPU move
    #: for fitting larger B*T (SURVEY.md "use jax.checkpoint to trade FLOPs
    #: for memory").
    remat: bool = False
    #: skip the weight-tied LM head and return the final (post-LN) hidden
    #: states; pair with :func:`lm_loss_fused` to avoid materializing the
    #:  ``[B, T, vocab]`` logits tensor.
    return_hidden: bool = False
    #: kv heads for GQA/MQA (None → num_heads).
    num_kv_heads: Optional[int] = None
    #: ``'learned'`` (reference-style absolute table) or ``'rope'``
    #: (rotary — no position parameters; relative by construction, the
    #: natural choice under sequence parallelism where a learned table
    #: would need per-shard rolling).
    pos_encoding: str = "learned"

    @nn.compact
    def __call__(self, tokens, *, segment_ids=None, positions=None,
                 train: bool = True):
        """``segment_ids`` (optional ``[B, T]``) confines attention to
        packed documents; requires a segment-capable ``attention_fn``
        (e.g. :func:`chainermn_tpu.ops.flash_attention.flash_attention`).
        ``positions`` (optional ``[T]`` int32 GLOBAL positions) overrides
        ``pos_offset + arange(T)`` — sequence-parallel shards pass
        ``axis_index * T_local + arange(T_local)``."""
        if segment_ids is not None and self.attention_fn is None:
            raise ValueError(
                "segment_ids needs a segment-capable attention_fn — pass "
                "attention_fn=flash_attention (the default blockwise "
                "reference does not take segment masks)"
            )
        if self.pos_encoding not in ("learned", "rope"):
            raise ValueError(
                f"pos_encoding must be 'learned' or 'rope', got "
                f"{self.pos_encoding!r}"
            )
        B, T = tokens.shape
        emb = nn.Embed(
            self.vocab_size, self.d_model, param_dtype=jnp.float32,
            dtype=self.compute_dtype, name="tok_emb",
        )
        x = emb(tokens)
        rope_positions = None
        if self.pos_encoding == "rope":
            if positions is None:
                positions = self.pos_offset + jnp.arange(T, dtype=jnp.int32)
            rope_positions = positions
        else:
            pos_emb = self.param(
                "pos_emb",
                nn.initializers.normal(0.02),
                (self.max_len, self.d_model),
                jnp.float32,
            )
            if positions is not None:
                pos = pos_emb[positions]
            else:
                pos = jax.lax.dynamic_slice_in_dim(
                    pos_emb, self.pos_offset, T, axis=0
                )
            x = x + pos[None].astype(self.compute_dtype)
        block_cls = TransformerBlock
        if self.remat:
            block_cls = nn.remat(
                TransformerBlock,
                policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
                static_argnums=(4,),  # (self, x, seg, rope_pos, train)
            )
        for i in range(self.num_layers):
            x = block_cls(
                num_heads=self.num_heads,
                d_ff=self.d_ff,
                compute_dtype=self.compute_dtype,
                attention_fn=self.attention_fn,
                num_kv_heads=self.num_kv_heads,
                name=f"block_{i}",
            )(x, segment_ids, rope_positions, train)
        x = nn.LayerNorm(dtype=self.compute_dtype, param_dtype=jnp.float32)(x)
        if self.return_hidden:
            return x
        logits = emb.attend(x.astype(jnp.float32))  # weight-tied output head
        return logits


def lm_loss(logits, tokens, mask=None):
    """Next-token cross-entropy: predict ``tokens[:, 1:]`` from positions
    ``[:, :-1]``; optional padding ``mask`` (same shape as tokens, 1=real)."""
    import optax

    targets = tokens[:, 1:]
    logits = logits[:, :-1]
    losses = optax.softmax_cross_entropy_with_integer_labels(logits, targets)
    if mask is not None:
        m = mask[:, 1:].astype(losses.dtype)
        return (losses * m).sum() / jnp.maximum(m.sum(), 1)
    return losses.mean()


def lm_loss_fused(hidden, emb_table, tokens, *, n_chunks=8,
                  compute_dtype=jnp.bfloat16):
    """Fused chunked LM-head + next-token cross-entropy.

    The naive head materializes ``[B, T, vocab]`` f32 logits (≈ 4·B·T·V
    bytes of HBM traffic both ways, plus an f32 matmul off the MXU's fast
    path). This computes the head matmul per token-chunk in ``compute_dtype``
    with f32 MXU accumulation, reduces each chunk to its scalar loss
    immediately, and rematerializes the chunk in the backward pass
    (``jax.checkpoint``) — so the full logits tensor never exists in HBM in
    either pass. Equivalent to ``lm_loss(emb.attend(hidden), tokens)`` up to
    compute-dtype rounding; pair with ``TransformerLM(return_hidden=True)``.

    Args:
      hidden: final post-LN hidden states ``[B, T, D]``.
      emb_table: tied embedding table ``[vocab, D]`` (f32 master copy).
      tokens: integer tokens ``[B, T]``.
      n_chunks: token-dimension split; ``B*(T-1)`` need not divide evenly —
        the tail partial chunk is padded and masked out.
    """
    B, T, D = hidden.shape
    h = hidden[:, :-1].reshape(-1, D)
    t = tokens[:, 1:].reshape(-1)
    n = h.shape[0]
    chunk = -(-n // n_chunks)  # ceil
    pad = chunk * n_chunks - n
    h = jnp.pad(h, ((0, pad), (0, 0)))
    t = jnp.pad(t, (0, pad))
    valid = jnp.pad(jnp.ones((n,), jnp.float32), (0, pad))
    w = emb_table.astype(compute_dtype).T  # [D, vocab]

    @jax.checkpoint
    def chunk_loss(hc, tc, mc):
        logits = jnp.dot(
            hc.astype(compute_dtype), w,
            preferred_element_type=jnp.float32,
        )
        lse = jax.nn.logsumexp(logits, axis=-1)
        gold = jnp.take_along_axis(logits, tc[:, None], axis=-1)[:, 0]
        return jnp.sum((lse - gold) * mc)

    def body(acc, xs):
        hc, tc, mc = xs
        return acc + chunk_loss(hc, tc, mc), ()

    total, _ = jax.lax.scan(
        body, jnp.float32(0.0),
        (h.reshape(n_chunks, chunk, D),
         t.reshape(n_chunks, chunk),
         valid.reshape(n_chunks, chunk)),
    )
    return total / n
