"""LSTM encoder–decoder for machine translation — the reference's seq2seq
benchmark workload (``examples/seq2seq/seq2seq.py`` (dagger), SURVEY.md
sections 2.8, 7: "variable-length grads stress the packer").

The TPU design problem the reference never faced (define-by-run handled
ragged batches natively): under ``jit`` every shape is static, so variable
length becomes **padding + masks + bucketing** (see
:mod:`chainermn_tpu.datasets.bucketing` for the compile-cache-friendly
bucketing discipline). The recurrence is ``nn.scan`` over the per-step
stacked-cell module — one compiled loop, weights resident across steps, no
per-step dispatch.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp


class _StackStep(nn.Module):
    """One time-step through ``num_layers`` LSTM cells with mask freezing:
    where mask == 0 (padding) carries and outputs hold their previous
    values, so padded steps are no-ops — the static-shape answer to
    variable-length sequences."""

    hidden: int
    num_layers: int

    @nn.compact
    def __call__(self, carry, xm):
        x, m = xm  # x: [B, E], m: [B]
        keep = m[:, None] > 0
        new_carry = []
        h = x
        for i in range(self.num_layers):
            cell = nn.OptimizedLSTMCell(self.hidden, name=f"lstm_{i}")
            (c_i, h_i), out = cell(carry[i], h)
            c_i = jnp.where(keep, c_i, carry[i][0])
            h_i = jnp.where(keep, h_i, carry[i][1])
            h = jnp.where(keep, out, carry[i][1])
            new_carry.append((c_i, h_i))
        return tuple(new_carry), h


class _StackedLSTM(nn.Module):
    """``num_layers`` LSTMs scanned over time: xs ``[B, T, E]``,
    mask ``[B, T]`` → (outputs ``[B, T, H]``, final carry)."""

    hidden: int
    num_layers: int = 2

    @nn.compact
    def __call__(self, xs, mask, carry=None):
        B = xs.shape[0]
        if carry is None:
            zeros = jnp.zeros((B, self.hidden), xs.dtype)
            carry = tuple((zeros, zeros) for _ in range(self.num_layers))
        scan = nn.scan(
            _StackStep,
            variable_broadcast="params",
            split_rngs={"params": False},
            in_axes=1,
            out_axes=1,
        )(self.hidden, self.num_layers, name="step")
        carry, outs = scan(carry, (xs, mask))
        return outs, carry


class Seq2Seq(nn.Module):
    """Encoder–decoder LSTM MT model (teacher forcing).

    Mirrors the reference example's shape: embed → stacked-LSTM encoder →
    final state seeds the decoder → stacked-LSTM decoder → vocab projection.
    Setup-style so :meth:`encode` / :meth:`decode_step` (greedy inference,
    the reference example's BLEU-eval path) share submodules — and therefore
    parameters — with the teacher-forced :meth:`__call__`.
    """

    src_vocab: int
    tgt_vocab: int
    embed: int = 256
    hidden: int = 512
    num_layers: int = 2
    compute_dtype: Any = jnp.float32

    def setup(self):
        self.src_emb = nn.Embed(self.src_vocab, self.embed, name="src_emb")
        self.tgt_emb = nn.Embed(self.tgt_vocab, self.embed, name="tgt_emb")
        self.encoder = _StackedLSTM(self.hidden, self.num_layers, name="encoder")
        self.decoder = _StackedLSTM(self.hidden, self.num_layers, name="decoder")
        self.proj = nn.Dense(self.tgt_vocab, name="proj")

    def __call__(
        self,
        src_tokens: jax.Array,   # [B, Ts]
        tgt_tokens: jax.Array,   # [B, Tt] (decoder input, BOS-shifted)
        src_mask: jax.Array,     # [B, Ts]
        tgt_mask: jax.Array,     # [B, Tt]
    ) -> jax.Array:
        src = self.src_emb(src_tokens).astype(self.compute_dtype)
        tgt = self.tgt_emb(tgt_tokens).astype(self.compute_dtype)
        _, enc_carry = self.encoder(src, src_mask.astype(src.dtype))
        dec_out, _ = self.decoder(tgt, tgt_mask.astype(tgt.dtype), carry=enc_carry)
        return self.proj(dec_out)

    def encode(self, src_tokens: jax.Array, src_mask: jax.Array):
        """Run the encoder; returns the carry that seeds the decoder."""
        src = self.src_emb(src_tokens).astype(self.compute_dtype)
        _, enc_carry = self.encoder(src, src_mask.astype(src.dtype))
        return enc_carry

    def decode_step(self, carry, tok: jax.Array):
        """One greedy-decode step: ``tok [B]`` → (logits ``[B, V]``, carry)."""
        emb = self.tgt_emb(tok[:, None]).astype(self.compute_dtype)  # [B,1,E]
        out, carry = self.decoder(
            emb, jnp.ones((tok.shape[0], 1), emb.dtype), carry=carry
        )
        return self.proj(out[:, 0]), carry


def greedy_decode(
    model: Seq2Seq,
    variables,
    src_tokens: jax.Array,
    src_mask: jax.Array,
    max_len: int,
    *,
    bos: int = 1,
    eos: int = 2,
) -> jax.Array:
    """Jittable greedy decoding: ``[B, Ts]`` sources → ``[B, max_len]``
    hypothesis token ids. Positions after the first emitted ``eos`` are
    filled with ``eos`` (host-side truncation recovers the sentence) — the
    static-shape answer to the reference example's variable-length decode
    (``examples/seq2seq/seq2seq.py`` (dagger) BLEU eval, SURVEY.md §2.8).
    """
    B = src_tokens.shape[0]
    carry = model.apply(variables, src_tokens, src_mask, method=Seq2Seq.encode)

    def body(state, _):
        carry, tok, done = state
        logits, carry = model.apply(
            variables, carry, tok, method=Seq2Seq.decode_step
        )
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        nxt = jnp.where(done, jnp.int32(eos), nxt)
        done = done | (nxt == eos)
        return (carry, nxt, done), nxt

    init = (
        carry,
        jnp.full((B,), bos, jnp.int32),
        jnp.zeros((B,), dtype=bool),
    )
    _, toks = jax.lax.scan(body, init, None, length=max_len)
    return toks.T  # [B, max_len]


def beam_search_decode(
    model: Seq2Seq,
    variables,
    src_tokens: jax.Array,
    src_mask: jax.Array,
    max_len: int,
    beam_size: int,
    *,
    bos: int = 1,
    eos: int = 2,
    length_penalty: float = 0.0,
):
    """Jittable beam-search decoding: ``[B, Ts]`` sources →
    ``([B, beam, max_len]`` hypotheses best-first, ``[B, beam]`` summed
    log-probs). Same static-shape discipline as :func:`greedy_decode`
    (finished beams pad with ``eos`` at no score change; host-side
    truncation recovers sentences), with the LSTM carries batched
    ``B·beam`` and reordered by backpointer gather each step. Simpler
    than the transformer's :func:`~chainermn_tpu.models.transformer.
    beam_search`: there is no prompt phase, so every step's expansion is
    recorded at its own position.

    ``length_penalty`` (GNMT alpha) ranks hypotheses by
    ``score / ((5 + len) / 6)**alpha`` with ``len`` counted up to and
    including EOS (positive favours longer hypotheses, negative shorter);
    returned scores stay raw.
    """
    if beam_size < 1:
        raise ValueError(f"beam_size must be >= 1, got {beam_size}")
    B = src_tokens.shape[0]
    K = beam_size
    V = model.tgt_vocab
    carry = model.apply(variables, src_tokens, src_mask,
                        method=Seq2Seq.encode)
    # Tile to beams, b-major: row b*K + k is (batch b, beam k).
    carry = jax.tree.map(lambda x: jnp.repeat(x, K, axis=0), carry)
    scores0 = jnp.tile(
        jnp.array([0.0] + [-jnp.inf] * (K - 1), jnp.float32), (B, 1)
    )

    def reorder(tree, parents):
        def one(leaf):
            shaped = leaf.reshape(B, K, *leaf.shape[1:])
            idx = parents.reshape(B, K, *([1] * (leaf.ndim - 1)))
            return jnp.take_along_axis(shaped, idx, axis=1).reshape(
                leaf.shape
            )
        return jax.tree.map(one, tree)

    def body(state, _):
        carry, tok, scores, finished, gen_len = state
        logits, carry = model.apply(
            variables, carry, tok.reshape(B * K),
            method=Seq2Seq.decode_step,
        )
        logp = jax.nn.log_softmax(
            logits.astype(jnp.float32)
        ).reshape(B, K, V)
        frozen = jnp.full((V,), -jnp.inf).at[eos].set(0.0)
        logp = jnp.where(finished[..., None], frozen[None, None], logp)

        total = scores[..., None] + logp
        top_scores, flat_idx = jax.lax.top_k(total.reshape(B, K * V), K)
        parents = flat_idx // V
        next_tok = (flat_idx % V).astype(jnp.int32)

        carry = reorder(carry, parents)
        finished = jnp.take_along_axis(finished, parents, axis=1)
        gen_len = jnp.take_along_axis(gen_len, parents, axis=1)
        gen_len = gen_len + (~finished).astype(jnp.int32)
        finished = finished | (next_tok == eos)
        return ((carry, next_tok, top_scores, finished, gen_len),
                (next_tok, parents))

    init = (
        carry,
        jnp.full((B, K), bos, jnp.int32),
        scores0,
        jnp.zeros((B, K), bool),
        jnp.zeros((B, K), jnp.int32),
    )
    (_, _, scores, _, gen_len), (toks, parents) = jax.lax.scan(
        body, init, None, length=max_len
    )

    # Hypothesis reconstruction: walk the backpointers from the end.
    # (The LSTM carry is tiny, but sequences were not carried through the
    # scan — a reverse pointer-chase is cheaper than per-step [B,K,T]
    # gathers for long max_len.)
    def back(slot, t_par):
        tok_t, par_t = t_par
        return jnp.take_along_axis(par_t, slot, axis=1), \
            jnp.take_along_axis(tok_t, slot, axis=1)

    slot0 = jnp.broadcast_to(jnp.arange(K), (B, K))
    _, rev = jax.lax.scan(
        back, slot0, (jnp.flip(toks, 0), jnp.flip(parents, 0))
    )
    seqs = jnp.flip(jnp.moveaxis(rev, 0, 2), 2)  # [B, K, max_len]
    if length_penalty != 0.0:
        from chainermn_tpu.models._decode_common import rank_beams

        return rank_beams(seqs, scores, gen_len, length_penalty)
    # Already best-first under raw scores: the final step's top_k returns
    # them sorted descending, and seqs slots match that order.
    return seqs, scores


def seq2seq_loss(logits, targets, tgt_mask):
    """Masked cross-entropy over decoder outputs: ``targets`` are the
    gold next tokens aligned with the decoder input positions."""
    import optax

    losses = optax.softmax_cross_entropy_with_integer_labels(logits, targets)
    m = tgt_mask.astype(losses.dtype)
    return (losses * m).sum() / jnp.maximum(m.sum(), 1)
