"""LSTM encoder–decoder for machine translation — the reference's seq2seq
benchmark workload (``examples/seq2seq/seq2seq.py`` (dagger), SURVEY.md
sections 2.8, 7: "variable-length grads stress the packer").

The TPU design problem the reference never faced (define-by-run handled
ragged batches natively): under ``jit`` every shape is static, so variable
length becomes **padding + masks + bucketing** (see
:mod:`chainermn_tpu.datasets.bucketing` for the compile-cache-friendly
bucketing discipline). The recurrence is ``nn.scan`` over the per-step
stacked-cell module — one compiled loop, weights resident across steps, no
per-step dispatch.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp


class _StackStep(nn.Module):
    """One time-step through ``num_layers`` LSTM cells with mask freezing:
    where mask == 0 (padding) carries and outputs hold their previous
    values, so padded steps are no-ops — the static-shape answer to
    variable-length sequences."""

    hidden: int
    num_layers: int

    @nn.compact
    def __call__(self, carry, xm):
        x, m = xm  # x: [B, E], m: [B]
        keep = m[:, None] > 0
        new_carry = []
        h = x
        for i in range(self.num_layers):
            cell = nn.OptimizedLSTMCell(self.hidden, name=f"lstm_{i}")
            (c_i, h_i), out = cell(carry[i], h)
            c_i = jnp.where(keep, c_i, carry[i][0])
            h_i = jnp.where(keep, h_i, carry[i][1])
            h = jnp.where(keep, out, carry[i][1])
            new_carry.append((c_i, h_i))
        return tuple(new_carry), h


class _StackedLSTM(nn.Module):
    """``num_layers`` LSTMs scanned over time: xs ``[B, T, E]``,
    mask ``[B, T]`` → (outputs ``[B, T, H]``, final carry)."""

    hidden: int
    num_layers: int = 2

    @nn.compact
    def __call__(self, xs, mask, carry=None):
        B = xs.shape[0]
        if carry is None:
            zeros = jnp.zeros((B, self.hidden), xs.dtype)
            carry = tuple((zeros, zeros) for _ in range(self.num_layers))
        scan = nn.scan(
            _StackStep,
            variable_broadcast="params",
            split_rngs={"params": False},
            in_axes=1,
            out_axes=1,
        )(self.hidden, self.num_layers, name="step")
        carry, outs = scan(carry, (xs, mask))
        return outs, carry


class Seq2Seq(nn.Module):
    """Encoder–decoder LSTM MT model (teacher forcing).

    Mirrors the reference example's shape: embed → stacked-LSTM encoder →
    final state seeds the decoder → stacked-LSTM decoder → vocab projection.
    """

    src_vocab: int
    tgt_vocab: int
    embed: int = 256
    hidden: int = 512
    num_layers: int = 2
    compute_dtype: Any = jnp.float32

    @nn.compact
    def __call__(
        self,
        src_tokens: jax.Array,   # [B, Ts]
        tgt_tokens: jax.Array,   # [B, Tt] (decoder input, BOS-shifted)
        src_mask: jax.Array,     # [B, Ts]
        tgt_mask: jax.Array,     # [B, Tt]
    ) -> jax.Array:
        src = nn.Embed(self.src_vocab, self.embed, name="src_emb")(src_tokens)
        tgt = nn.Embed(self.tgt_vocab, self.embed, name="tgt_emb")(tgt_tokens)
        src = src.astype(self.compute_dtype)
        tgt = tgt.astype(self.compute_dtype)

        _, enc_carry = _StackedLSTM(
            self.hidden, self.num_layers, name="encoder"
        )(src, src_mask.astype(src.dtype))
        dec_out, _ = _StackedLSTM(
            self.hidden, self.num_layers, name="decoder"
        )(tgt, tgt_mask.astype(tgt.dtype), carry=enc_carry)
        return nn.Dense(self.tgt_vocab, name="proj")(dec_out)


def seq2seq_loss(logits, targets, tgt_mask):
    """Masked cross-entropy over decoder outputs: ``targets`` are the
    gold next tokens aligned with the decoder input positions."""
    import optax

    losses = optax.softmax_cross_entropy_with_integer_labels(logits, targets)
    m = tgt_mask.astype(losses.dtype)
    return (losses * m).sum() / jnp.maximum(m.sum(), 1)
