"""LSTM encoder–decoder for machine translation — the reference's seq2seq
benchmark workload (``examples/seq2seq/seq2seq.py`` (dagger), SURVEY.md
sections 2.8, 7: "variable-length grads stress the packer").

The TPU design problem the reference never faced (define-by-run handled
ragged batches natively): under ``jit`` every shape is static, so variable
length becomes **padding + masks + bucketing** (see
:mod:`chainermn_tpu.datasets.bucketing` for the compile-cache-friendly
bucketing discipline). The recurrence is ``nn.scan`` over the per-step
stacked-cell module — one compiled loop, weights resident across steps, no
per-step dispatch.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp


class _StackStep(nn.Module):
    """One time-step through ``num_layers`` LSTM cells with mask freezing:
    where mask == 0 (padding) carries and outputs hold their previous
    values, so padded steps are no-ops — the static-shape answer to
    variable-length sequences."""

    hidden: int
    num_layers: int

    @nn.compact
    def __call__(self, carry, xm):
        x, m = xm  # x: [B, E], m: [B]
        keep = m[:, None] > 0
        new_carry = []
        h = x
        for i in range(self.num_layers):
            cell = nn.OptimizedLSTMCell(self.hidden, name=f"lstm_{i}")
            (c_i, h_i), out = cell(carry[i], h)
            c_i = jnp.where(keep, c_i, carry[i][0])
            h_i = jnp.where(keep, h_i, carry[i][1])
            h = jnp.where(keep, out, carry[i][1])
            new_carry.append((c_i, h_i))
        return tuple(new_carry), h


class _StackedLSTM(nn.Module):
    """``num_layers`` LSTMs scanned over time: xs ``[B, T, E]``,
    mask ``[B, T]`` → (outputs ``[B, T, H]``, final carry)."""

    hidden: int
    num_layers: int = 2

    @nn.compact
    def __call__(self, xs, mask, carry=None):
        B = xs.shape[0]
        if carry is None:
            zeros = jnp.zeros((B, self.hidden), xs.dtype)
            carry = tuple((zeros, zeros) for _ in range(self.num_layers))
        scan = nn.scan(
            _StackStep,
            variable_broadcast="params",
            split_rngs={"params": False},
            in_axes=1,
            out_axes=1,
        )(self.hidden, self.num_layers, name="step")
        carry, outs = scan(carry, (xs, mask))
        return outs, carry


class Seq2Seq(nn.Module):
    """Encoder–decoder LSTM MT model (teacher forcing).

    Mirrors the reference example's shape: embed → stacked-LSTM encoder →
    final state seeds the decoder → stacked-LSTM decoder → vocab projection.
    Setup-style so :meth:`encode` / :meth:`decode_step` (greedy inference,
    the reference example's BLEU-eval path) share submodules — and therefore
    parameters — with the teacher-forced :meth:`__call__`.
    """

    src_vocab: int
    tgt_vocab: int
    embed: int = 256
    hidden: int = 512
    num_layers: int = 2
    compute_dtype: Any = jnp.float32

    def setup(self):
        self.src_emb = nn.Embed(self.src_vocab, self.embed, name="src_emb")
        self.tgt_emb = nn.Embed(self.tgt_vocab, self.embed, name="tgt_emb")
        self.encoder = _StackedLSTM(self.hidden, self.num_layers, name="encoder")
        self.decoder = _StackedLSTM(self.hidden, self.num_layers, name="decoder")
        self.proj = nn.Dense(self.tgt_vocab, name="proj")

    def __call__(
        self,
        src_tokens: jax.Array,   # [B, Ts]
        tgt_tokens: jax.Array,   # [B, Tt] (decoder input, BOS-shifted)
        src_mask: jax.Array,     # [B, Ts]
        tgt_mask: jax.Array,     # [B, Tt]
    ) -> jax.Array:
        src = self.src_emb(src_tokens).astype(self.compute_dtype)
        tgt = self.tgt_emb(tgt_tokens).astype(self.compute_dtype)
        _, enc_carry = self.encoder(src, src_mask.astype(src.dtype))
        dec_out, _ = self.decoder(tgt, tgt_mask.astype(tgt.dtype), carry=enc_carry)
        return self.proj(dec_out)

    def encode(self, src_tokens: jax.Array, src_mask: jax.Array):
        """Run the encoder; returns the carry that seeds the decoder."""
        src = self.src_emb(src_tokens).astype(self.compute_dtype)
        _, enc_carry = self.encoder(src, src_mask.astype(src.dtype))
        return enc_carry

    def decode_step(self, carry, tok: jax.Array):
        """One greedy-decode step: ``tok [B]`` → (logits ``[B, V]``, carry)."""
        emb = self.tgt_emb(tok[:, None]).astype(self.compute_dtype)  # [B,1,E]
        out, carry = self.decoder(
            emb, jnp.ones((tok.shape[0], 1), emb.dtype), carry=carry
        )
        return self.proj(out[:, 0]), carry


def greedy_decode(
    model: Seq2Seq,
    variables,
    src_tokens: jax.Array,
    src_mask: jax.Array,
    max_len: int,
    *,
    bos: int = 1,
    eos: int = 2,
) -> jax.Array:
    """Jittable greedy decoding: ``[B, Ts]`` sources → ``[B, max_len]``
    hypothesis token ids. Positions after the first emitted ``eos`` are
    filled with ``eos`` (host-side truncation recovers the sentence) — the
    static-shape answer to the reference example's variable-length decode
    (``examples/seq2seq/seq2seq.py`` (dagger) BLEU eval, SURVEY.md §2.8).
    """
    B = src_tokens.shape[0]
    carry = model.apply(variables, src_tokens, src_mask, method=Seq2Seq.encode)

    def body(state, _):
        carry, tok, done = state
        logits, carry = model.apply(
            variables, carry, tok, method=Seq2Seq.decode_step
        )
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        nxt = jnp.where(done, jnp.int32(eos), nxt)
        done = done | (nxt == eos)
        return (carry, nxt, done), nxt

    init = (
        carry,
        jnp.full((B,), bos, jnp.int32),
        jnp.zeros((B,), dtype=bool),
    )
    _, toks = jax.lax.scan(body, init, None, length=max_len)
    return toks.T  # [B, max_len]


def seq2seq_loss(logits, targets, tgt_mask):
    """Masked cross-entropy over decoder outputs: ``targets`` are the
    gold next tokens aligned with the decoder input positions."""
    import optax

    losses = optax.softmax_cross_entropy_with_integer_labels(logits, targets)
    m = tgt_mask.astype(losses.dtype)
    return (losses * m).sum() / jnp.maximum(m.sum(), 1)
