#!/usr/bin/env python
"""Summarize a chainermn_tpu observability trace (JSONL) into per-op
byte/time tables (ISSUE 2: the consumer side of the wire counters).

Usage::

    python tools/trace_report.py TRACE.jsonl [MORE.jsonl ...]
        [--json] [--chrome OUT.json] [--journeys] [--top K]

Multiple JSONL files concatenate before summarizing — the per-rank
trace files of one cluster run merge into one report.

Sections:

- **collectives** — per (op, plane): count, total payload bytes, total
  and mean duration, achieved GB/s where both are known, the wire
  dtypes seen, and how many events carry 'auto' dispatch provenance.
  ``allreduce_grad`` events SUBSUME their per-leaf ``allreduce``
  children (nested spans — don't sum the two rows).
- **steps** — per-phase mean/max milliseconds over the Trainer's
  step-timeline events (data_wait / h2d / compute / logging /
  extensions).
- **dispatch** — every autotune decision the traced processes resolved
  (name=winner(source), keyed).
- **overlap** — comm/compute overlap (ISSUE 3): the step's overlap
  configuration (``overlap_config`` events — double-buffering
  staleness, reduction schedule, donation), the per-bucket ``wire``
  layout the compiled schedules committed to, the COMPOSED schedules
  grouped by composition signature with a per-stage bytes/time table
  (ISSUE 12: wire events carrying ``composition``/``stage`` fields —
  one row per ``rs``/``ar``/``ag`` stage of the derived pipeline), and
  — where measured wire events exist (the eager
  ``OverlappedBucketReducer``; dur = dispatch->ready, blocked = wait
  actually paid at collect) — per-step comm time vs comm time hidden
  behind compute and the ``hidden_fraction`` between them. Omitted
  when the trace carries no overlap events.
- **serving** — continuous-batching accounting (ISSUE 4) from the
  scheduler's ``serving`` events: requests/tokens served, tokens/s over
  device-busy time, nearest-rank p50/p99 per-token latency (one decode
  step = one token for every active request; under speculation, the
  tick latency for 1..K+1 tokens), TTFT (submit → first token) p50/p99,
  mean slot occupancy, and queue-wait/prefill means. When ``speculate``
  events exist (ISSUE 5), adds drafted/accepted token counts, the
  acceptance rate, and an accept-length histogram. When
  ``prefix_cache`` events exist (ISSUE 7), adds the prefix-sharing
  rollup: admission lookups/hits, prompt vs prefilled vs cache-served
  token totals (the measured prefill-work reduction) and COW copies.
  ISSUE 14: prefill/finish events roll up PER TENANT (requests,
  tokens, TTFT/TPOT p50/p99, SLO attainment) with a Jain fairness
  index over the token totals; events without a ``tenant`` tag fall
  back to one ``'default'`` tenant so pre-tenant traces keep parsing.
  Omitted when the trace has no serving events.
- **journeys** (``--journeys``; ISSUE 17) — per-request CAUSAL
  timelines merged across ranks by journey/span ids (hop order, never
  clock order), epoch stamps aligned by the traced ``clock_sync``
  offsets and displayed WITH their uncertainty, the top-K slowest
  requests by TTFT, and per-journey TTFT critical-path decomposition
  (queue wait / prefill / handoff / preemption gap — the components
  sum back to the measured ``ttft_s`` within rounding + clock
  uncertainty, or the report says so loudly).
- **moe** (ISSUE 20) — expert-dispatch rollup from ``moe_dispatch``
  events: aggregate per-expert load histogram with ``load_fractions``
  (a skewed row is the router-collapse signal), dropped/padded token
  totals and the dispatch capacity, plus the layers observed. Omitted
  when the trace carries no MoE events.
- **stragglers** — flagged divergence reports, if any.
- **roofline** — where a device kind with a known HBM peak appears
  (bench.py's per-kind tables, the same floors tools/byte_audit.py
  uses), collective GB/s is floored against it: an eager-plane number
  near the HBM peak is copy-bound, far below it is latency/dispatch
  -bound. Skipped silently when bench.py is unimportable.

``--json`` prints the machine-readable summary (the contract tested in
tests/test_capture_tools.py); default output is a human table.
``--chrome`` additionally writes a Chrome-trace/Perfetto file.

Durations caveat: device-plane events record dispatch-to-return unless
the trace was captured with ``CHAINERMN_TPU_TRACE_SYNC=1`` (the meta
event's ``sync`` field says which); host-plane (obj) events are true
blocking durations either way. See docs/observability.md.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _HERE not in sys.path:
    sys.path.insert(0, _HERE)


def _trace_mod():
    """The observability trace module, loaded by FILE PATH: one owner of
    the JSONL parser and the Chrome exporter (no drift), without paying
    for ``import chainermn_tpu`` (which pulls jax) in a report tool."""
    import importlib.util

    path = os.path.join(_HERE, "chainermn_tpu", "observability", "trace.py")
    spec = importlib.util.spec_from_file_location("_obs_trace", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _journey_mod():
    """The journey merge module, loaded the same file-path way (pure
    stdlib by contract — see its module docstring)."""
    import importlib.util

    path = os.path.join(
        _HERE, "chainermn_tpu", "observability", "journey.py")
    spec = importlib.util.spec_from_file_location("_obs_journey", path)
    mod = importlib.util.module_from_spec(spec)
    # Register BEFORE exec: @dataclass resolves its defining module
    # through sys.modules (3.10's KW_ONLY probe dies on None).
    sys.modules["_obs_journey"] = mod
    spec.loader.exec_module(mod)
    return mod


def _read_events(paths) -> list[dict]:
    if isinstance(paths, str):
        paths = [paths]
    tm = _trace_mod()
    events: list[dict] = []
    for p in paths:
        events.extend(tm.read_jsonl(p))
    return events


def _hbm_peak(device_kind: str):
    """Per-kind HBM peak via bench.py's table (the single place device
    peaks live — byte_audit.py derives its floors the same way)."""
    try:
        import bench

        return bench._peak_lookup(device_kind, bench._PEAK_HBM_BYTES)
    except Exception:
        return None


def summarize(events: list[dict]) -> dict:
    """The machine-readable summary: stable keys, deterministic ordering
    (tests/test_capture_tools.py pins this contract)."""
    coll: dict = {}
    steps: list[dict] = []
    dispatch: list[dict] = []
    stragglers: list[dict] = []
    packs: list[dict] = []
    moes: list[dict] = []
    schemas: set[int] = set()
    meta: dict = {}

    for ev in events:
        if "schema" in ev:
            schemas.add(ev["schema"])
        kind = ev.get("kind")
        if kind == "meta":
            # first meta wins for top-level fields; sync=True anywhere
            # means at least part of the trace has true durations
            for k in ("started_at", "sync", "source", "mode"):
                if k in ev and k not in meta:
                    meta[k] = ev[k]
            # dropped_events ACCUMULATES (one close() meta per recorder;
            # a multi-process trace file carries several) — a summary
            # over a lossy trace must say so loudly, not silently
            # under-count (ISSUE 6 satellite; previously ignored).
            if ev.get("dropped_events"):
                meta["dropped_events"] = (
                    meta.get("dropped_events", 0)
                    + int(ev["dropped_events"])
                )
            continue
        if kind == "collective":
            key = (ev.get("op", "?"), ev.get("plane", "?"))
            row = coll.setdefault(key, {
                "n": 0, "nbytes": 0, "dur_s": 0.0, "n_with_bytes": 0,
                "n_with_dur": 0, "wire_dtypes": set(), "n_auto": 0,
                "devices": set(),
            })
            row["n"] += 1
            if ev.get("nbytes") is not None:
                row["nbytes"] += int(ev["nbytes"])
                row["n_with_bytes"] += 1
            if ev.get("dur_s") is not None:
                row["dur_s"] += float(ev["dur_s"])
                row["n_with_dur"] += 1
            if ev.get("wire_dtype"):
                row["wire_dtypes"].add(str(ev["wire_dtype"]))
            if ev.get("provenance"):
                row["n_auto"] += 1
            if ev.get("device"):
                row["devices"].add(str(ev["device"]))
        elif kind == "step":
            steps.append(ev)
        elif kind == "dispatch":
            dispatch.append(ev)
        elif kind == "straggler":
            stragglers.append(ev)
        elif kind == "pack":
            packs.append(ev)
        elif kind == "moe_dispatch":
            moes.append(ev)

    ops = []
    for (op, plane) in sorted(coll):
        row = coll[(op, plane)]
        entry = {
            "op": op,
            "plane": plane,
            "n": row["n"],
            "total_bytes": row["nbytes"],
            "total_s": round(row["dur_s"], 6),
            "mean_ms": (round(row["dur_s"] / row["n_with_dur"] * 1e3, 4)
                        if row["n_with_dur"] else None),
            "wire_dtypes": sorted(row["wire_dtypes"]),
            "auto_events": row["n_auto"],
        }
        if row["nbytes"] and row["dur_s"] > 0 and row["n_with_bytes"]:
            # 6 decimals: host-plane obj collectives run at KB/ms scales
            # where 3 would round every row to 0.0
            entry["gbps"] = round(row["nbytes"] / row["dur_s"] / 1e9, 6)
        entry["_devices"] = sorted(row["devices"])  # stripped before emit
        ops.append(entry)

    phase_stats: dict = {}
    for ev in steps:
        for k, v in (ev.get("phases") or {}).items():
            s = phase_stats.setdefault(k, {"sum": 0.0, "max": 0.0, "n": 0})
            s["sum"] += float(v)
            s["max"] = max(s["max"], float(v))
            s["n"] += 1
    phases = {
        k: {"mean_ms": round(s["sum"] / s["n"] * 1e3, 4),
            "max_ms": round(s["max"] * 1e3, 4), "n": s["n"]}
        for k, s in sorted(phase_stats.items()) if s["n"]
    }

    disp = [
        {"name": d.get("name"), "key": d.get("key"),
         "winner": d.get("winner"), "source": d.get("source")}
        for d in dispatch
    ]

    out = {
        "schema_versions": sorted(schemas),
        "meta": meta,
        "n_events": len(events),
        "collectives": ops,
        "steps": {"n": len(steps), "phases": phases},
        "dispatch": disp,
        "packs": [
            {k: p.get(k) for k in
             ("op", "nbytes", "bucket_bytes", "n_buckets", "wire_dtype")}
            for p in packs
        ],
        "stragglers": [
            {"flagged_ranks": s.get("flagged_ranks"),
             "phases": s.get("phases")}
            for s in stragglers
        ],
    }

    # Roofline floors where the device kind names a known HBM peak:
    # device-plane ops only, floored against the kinds THEY actually ran
    # on (a multi-backend trace — bench's accel child + cpu fallback in
    # one file — must not cross-product ops against foreign devices, and
    # a host-plane pickle transfer has no HBM roofline at all).
    floors = []
    for entry in ops:
        if entry["plane"] != "device" or not entry.get("gbps"):
            continue
        for kind in entry["_devices"]:
            peak = _hbm_peak(kind)
            if not peak:
                continue
            floors.append({
                "device": kind, "op": entry["op"],
                "achieved_gbps": entry["gbps"],
                "hbm_peak_gbps": round(peak / 1e9, 1),
                "fraction_of_peak": round(entry["gbps"] * 1e9 / peak, 4),
            })
    for entry in ops:
        entry.pop("_devices")
    if floors:
        out["roofline"] = floors

    # MoE dispatch rollup (ISSUE 20): aggregate the per-layer expert
    # load histogram and the drop/pad token flow across every
    # ``moe_dispatch`` event — a skewed ``load_fractions`` row is the
    # router-collapse signal the aux loss is supposed to prevent.
    if moes:
        load: list[float] = []
        dropped = padded = 0.0
        for ev in moes:
            dropped += float(ev.get("dropped") or 0)
            padded += float(ev.get("padded") or 0)
            for i, v in enumerate(ev.get("expert_load") or ()):
                while len(load) <= i:
                    load.append(0.0)
                load[i] += float(v)
        total = sum(load)
        out["moe"] = {
            "n_events": len(moes),
            "dropped_tokens": round(dropped, 3),
            "padded_slots": round(padded, 3),
            "capacity": max((float(ev.get("capacity") or 0)
                             for ev in moes), default=0.0),
            "expert_load": [round(v, 3) for v in load],
            "load_fractions": [round(v / total, 4) if total else 0.0
                               for v in load],
            "layers": sorted({int(ev["layer"]) for ev in moes
                              if ev.get("layer") is not None}),
        }

    # Overlap section (one owner of the rollup: the trace module's
    # summarize_overlap — bench's overlap phase reads the same shape).
    overlap = _trace_mod().summarize_overlap(events)
    if overlap is not None:
        out["overlap"] = overlap
    # Serving section (ISSUE 4: same one-owner discipline —
    # summarize_serving feeds this report AND bench's serving phase).
    serving = _trace_mod().summarize_serving(events)
    if serving is not None:
        out["serving"] = serving
    return out


def _fmt_bytes(n: int) -> str:
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{n} B"
        n /= 1024
    return f"{n}"


def render_text(s: dict) -> str:
    lines = []
    dropped = s["meta"].get("dropped_events")
    if dropped:
        lines.append(
            f"*** WARNING: the recorder DROPPED {dropped} event(s) "
            f"(in-memory buffer overflow) — every count below "
            f"undercounts; raise MAX_BUFFERED_EVENTS or shorten the "
            f"capture ***"
        )
    lines.append(
        f"trace: {s['n_events']} events, schema {s['schema_versions']}, "
        f"sync={s['meta'].get('sync', False)}"
    )
    if s["collectives"]:
        lines.append("")
        lines.append(f"{'op':<18} {'plane':<7} {'n':>6} {'bytes':>12} "
                     f"{'total s':>9} {'mean ms':>9} {'GB/s':>7} "
                     f"{'auto':>5}  wire")
        for e in s["collectives"]:
            lines.append(
                f"{e['op']:<18} {e['plane']:<7} {e['n']:>6} "
                f"{_fmt_bytes(e['total_bytes']):>12} "
                f"{e['total_s']:>9.4f} "
                f"{(e['mean_ms'] if e['mean_ms'] is not None else 0):>9.3f} "
                f"{(str(e.get('gbps', '-'))):>7} "
                f"{e['auto_events']:>5}  {','.join(e['wire_dtypes']) or '-'}"
            )
        lines.append("(allreduce_grad rows subsume their nested "
                     "per-leaf allreduce rows; don't sum)")
    if s["steps"]["n"]:
        lines.append("")
        lines.append(f"steps: {s['steps']['n']}")
        for k, v in s["steps"]["phases"].items():
            lines.append(f"  {k:<12} mean {v['mean_ms']:>9.3f} ms   "
                         f"max {v['max_ms']:>9.3f} ms")
    if s["dispatch"]:
        lines.append("")
        lines.append("dispatch decisions:")
        for d in s["dispatch"]:
            lines.append(f"  {d['name']}={d['winner']} ({d['source']}) "
                         f"key={d['key']}")
    if s["packs"]:
        lines.append("")
        lines.append("gradient packs (per compilation):")
        for p in s["packs"]:
            lines.append(
                f"  {p['op']}: {p['n_buckets']} bucket(s) x "
                f"<= {_fmt_bytes(p['bucket_bytes'] or 0)}, wire "
                f"{p['wire_dtype']}, {_fmt_bytes(p['nbytes'] or 0)} total"
            )
    if s.get("overlap"):
        ov = s["overlap"]
        lines.append("")
        lines.append("comm/compute overlap:")
        for cfg in ov.get("config", []):
            lines.append(
                f"  mode: double_buffering={cfg.get('double_buffering')} "
                f"staleness={cfg.get('staleness')} "
                f"schedule={cfg.get('schedule') or 'communicator-default'} "
                f"donate={cfg.get('donate')}"
            )
        for name, row in ov.get("schedules", {}).items():
            lines.append(
                f"  {name}: {row['buckets']} bucket(s), "
                f"{_fmt_bytes(row['nbytes'])} wire, "
                f"{row['overlapped']} overlapped"
            )
        for sig, row in ov.get("compositions", {}).items():
            pred = (f", predicted {row['predicted_ms']:.3f} ms"
                    if row.get("predicted_ms") is not None else "")
            lines.append(
                f"  composed {sig} [{row['schedule']}]: "
                f"{row['buckets']} bucket(s), "
                f"{_fmt_bytes(row['nbytes'])} wire, "
                f"{row['overlapped']} overlapped{pred}"
            )
            for st, srow in row.get("stages", {}).items():
                dur = (f", {srow['dur_ms']:.3f} ms"
                       if srow.get("dur_ms") is not None else "")
                lines.append(
                    f"    {st} [{srow.get('op')}]: n={srow['n']}, "
                    f"{_fmt_bytes(srow['nbytes'])}{dur}"
                )
                # ISSUE 15: the per-slice column — one sub-row per
                # bucket slice with its measured dur beside the layout
                # bytes (unsliced stages carry no 'slices' table).
                for s_key, sl in sorted(
                    srow.get("slices", {}).items(),
                    key=lambda kv: int(kv[0][1:]),
                ):
                    sdur = (f", {sl['dur_ms']:.3f} ms"
                            if sl.get("dur_ms") is not None else "")
                    sblk = (f" ({sl['blocked_ms']:.3f} ms blocked)"
                            if sl.get("blocked_ms") is not None else "")
                    lines.append(
                        f"      {s_key}: n={sl['n']}, "
                        f"{_fmt_bytes(sl['nbytes'])}{sdur}{sblk}"
                    )
        m = ov.get("measured")
        if m:
            lines.append(
                f"  measured: comm {m['comm_ms_total']:.3f} ms total, "
                f"{m['comm_ms_hidden']:.3f} ms hidden behind compute "
                f"({m['hidden_fraction'] * 100:.1f}% hidden, "
                f"{m['n']} bucket events)"
            )
        # ISSUE 16: the cost-model schedule search's audit — predicted
        # beside measured per arm, skipped arms still priced (no silent
        # coverage loss), and a LOUD flag when the model's error blew
        # past the measurement spread (the exhaustive-fallback gate).
        ss = ov.get("sched_search")
        if ss:
            err, spread = ss.get("err_pct"), ss.get("spread_pct")
            loud = (err is not None and spread is not None
                    and err > spread)
            head = f"  schedule search [{ss.get('mode')}] " \
                   f"({ss.get('provenance')})"
            if err is not None:
                head += f": model err {err:.1f}%"
                if spread is not None:
                    head += (f" > spread {spread:.1f}% !! MODEL PAST "
                             f"GATE — exhaustive fallback" if loud else
                             f" <= spread {spread:.1f}%")
            lines.append(head)
            for sig, row in ss.get("rows", {}).items():
                p = (f"predicted {row['predicted_ms']:>9.3f} ms"
                     if row.get("predicted_ms") is not None
                     else " " * 22)
                mm = (f"  measured {row['measured_ms']:>9.3f} ms"
                      if row.get("measured_ms") is not None
                      else "  (skipped)")
                lines.append(f"    {sig}: {p}{mm}")
    if s.get("serving"):
        sv = s["serving"]
        lines.append("")
        lines.append("serving (continuous batching):")
        lines.append(
            f"  {sv['requests']} request(s), {sv['generated_tokens']} "
            f"token(s) over {sv['prefills']} prefill(s) + "
            f"{sv['decode_steps']} decode step(s)"
        )
        if sv.get("tokens_per_sec") is not None:
            lines.append(f"  tokens/s: {sv['tokens_per_sec']}")
        if sv.get("token_ms_p50") is not None:
            lines.append(
                f"  per-token latency: p50 {sv['token_ms_p50']:.3f} ms, "
                f"p99 {sv['token_ms_p99']:.3f} ms"
            )
        if sv.get("ttft_ms_p50") is not None:
            lines.append(
                f"  TTFT: p50 {sv['ttft_ms_p50']:.3f} ms, "
                f"p99 {sv['ttft_ms_p99']:.3f} ms"
            )
        if sv.get("tpot_ms_p50") is not None:
            lines.append(
                f"  TPOT: p50 {sv['tpot_ms_p50']:.3f} ms, "
                f"p99 {sv['tpot_ms_p99']:.3f} ms per request"
            )
        if sv.get("slo_attainment") is not None:
            lines.append(
                f"  SLO attainment: {sv['slo_attainment'] * 100:.1f}% "
                f"of {sv['slo_requests']} target-bearing request(s)"
            )
        if sv.get("preemptions"):
            lines.append(f"  preemptions: {sv['preemptions']}")
        ck = sv.get("chunked_prefill")
        if ck:
            lines.append(
                f"  chunked prefill: {ck['chunk_tokens']} prompt "
                f"token(s) over {ck['chunks']} mixed-step chunk(s)"
            )
        if sv.get("occupancy_mean") is not None:
            lines.append(
                f"  slot occupancy: {sv['occupancy_mean'] * 100:.1f}% mean"
            )
        sp = sv.get("speculation")
        if sp:
            rate = sp.get("accept_rate")
            lines.append(
                f"  speculation: {sp['drafted']} drafted, "
                f"{sp['accepted']} accepted"
                + (f" ({rate * 100:.1f}% acceptance)"
                   if rate is not None else "")
                + f" over {sp['ticks']} tick(s)"
            )
            hist = " ".join(
                f"{k}:{v}" for k, v in sorted(
                    sp.get("accept_len_hist", {}).items(),
                    key=lambda kv: int(kv[0]),
                )
            )
            if hist:
                lines.append(f"  accept-length histogram: {hist}")
        px = sv.get("prefix_cache")
        if px:
            lines.append(
                f"  prefix cache: {px['hits']}/{px['lookups']} admissions "
                f"hit ({px['hit_rate'] * 100:.1f}%), "
                f"{px['prefilled_tokens']}/{px['prompt_tokens']} prompt "
                f"tokens prefilled ({px['hit_tokens']} served from "
                f"cache), {px['cow_blocks']} COW block cop"
                f"{'y' if px['cow_blocks'] == 1 else 'ies'}"
            )
        tn = sv.get("tenants")
        if tn:
            # ISSUE 14: the per-tenant rollup (requests/tokens/latency
            # percentiles/SLO) + the Jain fairness index over token
            # totals; pre-tenant traces print one 'default' row.
            lines.append(
                f"  tenants: {len(tn)} (Jain fairness "
                f"{sv['tenant_fairness_jain']:.4f})"
            )
            for t, row in tn.items():
                parts = [f"{row['requests']} req",
                         f"{row['generated_tokens']} tok"]
                if row.get("ttft_ms_p50") is not None:
                    parts.append(
                        f"TTFT p50/p99 {row['ttft_ms_p50']:.3f}/"
                        f"{row['ttft_ms_p99']:.3f} ms")
                if row.get("tpot_ms_p50") is not None:
                    parts.append(
                        f"TPOT p50/p99 {row['tpot_ms_p50']:.3f}/"
                        f"{row['tpot_ms_p99']:.3f} ms")
                if row.get("slo_requests"):
                    parts.append(
                        f"SLO {row['slo_attainment'] * 100:.1f}% of "
                        f"{row['slo_requests']}")
                lines.append(f"    {t}: " + ", ".join(parts))
        # queue_wait and prefill are separate events: a truncated trace
        # may carry one without the other — guard each independently.
        if sv.get("queue_wait_ms_mean") is not None:
            lines.append(
                f"  queue wait: {sv['queue_wait_ms_mean']:.3f} ms mean"
            )
        if sv.get("prefill_ms_mean") is not None:
            lines.append(
                f"  prefill: {sv['prefill_ms_mean']:.3f} ms mean"
            )
    if s.get("moe"):
        mo = s["moe"]
        lines.append("")
        lines.append(
            f"moe dispatch: {mo['n_events']} events, capacity "
            f"{mo['capacity']:g}, dropped {mo['dropped_tokens']:g} "
            f"tokens, padded {mo['padded_slots']:g} slots"
        )
        if mo.get("layers"):
            lines.append(f"  layers: {mo['layers']}")
        if mo.get("expert_load"):
            frac = " ".join(
                f"e{i}={f * 100:.1f}%"
                for i, f in enumerate(mo["load_fractions"])
            )
            lines.append(f"  expert load: {frac}")
    if s["stragglers"]:
        lines.append("")
        lines.append(f"STRAGGLER reports: {len(s['stragglers'])}")
        for r in s["stragglers"]:
            lines.append(f"  flagged ranks {r['flagged_ranks']}: "
                         f"{json.dumps(r['phases'])}")
    if s.get("roofline"):
        lines.append("")
        lines.append("roofline (eager-plane achieved vs HBM peak):")
        for f in s["roofline"]:
            lines.append(
                f"  {f['op']} on {f['device']}: {f['achieved_gbps']} GB/s "
                f"= {f['fraction_of_peak'] * 100:.1f}% of "
                f"{f['hbm_peak_gbps']} GB/s"
            )
    return "\n".join(lines)


def render_journeys(j: dict) -> str:
    """Human rendering of the :func:`journey.merge_journeys` section."""
    lines = []
    clock = j["clock"]
    lines.append(
        f"journeys: {j['n_journeys']} merged, {j['n_complete']} "
        f"complete, {j['n_orphan_spans']} orphan span(s)"
    )
    if clock["offsets"]:
        for rank, off in sorted(clock["offsets"].items()):
            lines.append(
                f"  clock: rank {rank} offset "
                f"{off['offset_s'] * 1e3:+.3f} ms to rank "
                f"{off['peer']} (± {off['uncertainty_s'] * 1e3:.3f} ms)"
            )
    else:
        lines.append(
            "  clock: no clock_sync events — cross-rank stamps are "
            "raw epochs (uncertainty unbounded)"
        )
    for row in j["slowest"]:
        d = row["decomposition"]
        head = (f"  {row['journey']}: {row['n_spans']} span(s) over "
                f"rank(s) {row['ranks']}")
        if not row["complete"]:
            head += "  [INCOMPLETE: no finish]"
        if not row["contiguous"]:
            head += "  [HOP GAPS]"
        if row["orphan_spans"]:
            head += f"  [ORPHANS: {row['orphan_spans']}]"
        lines.append(head)
        if d is not None:
            parts = [
                f"queue {d['queue_wait_s'] * 1e3:.3f}",
                f"prefill {d['prefill_s'] * 1e3:.3f}",
                f"handoff {d['handoff_s'] * 1e3:.3f}",
            ]
            if d["preempts_before_first_token"]:
                parts.append(
                    f"preempt-gap {d['preempt_gap_s'] * 1e3:.3f} "
                    f"({d['preempts_before_first_token']} preempt(s))")
            decomp = (f"    TTFT {d['ttft_s'] * 1e3:.3f} ms = "
                      + " + ".join(parts)
                      + f"  (residual {d['residual_s'] * 1e3:+.4f} ms)")
            lines.append(decomp)
            if d.get("total_s") is not None:
                lines.append(
                    f"    total {d['total_s'] * 1e3:.3f} ms "
                    f"(decode {d['decode_s'] * 1e3:.3f} ms)")
        for sp in row["spans"]:
            what = sp["phase"] or sp["kind"]
            dur = (f"  dur {sp['dur_s'] * 1e3:.3f} ms"
                   if sp.get("dur_s") is not None else "")
            lines.append(
                f"    hop {sp['hop']:<2} rank {sp['rank']} "
                f"{what:<14} t_adj {sp['t_adj']}{dur}"
            )
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Summarize a chainermn_tpu observability JSONL trace"
    )
    ap.add_argument("trace", nargs="+",
                    help="JSONL trace file(s) — per-rank files of one "
                         "run concatenate before summarizing")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable summary")
    ap.add_argument("--chrome", metavar="OUT",
                    help="also write a Chrome-trace/Perfetto JSON file")
    ap.add_argument("--journeys", action="store_true",
                    help="merge per-request causal journeys across "
                         "ranks (ISSUE 17) and report the slowest")
    ap.add_argument("--top", type=int, default=5,
                    help="journeys to show in the slowest table "
                         "(default 5)")
    args = ap.parse_args(argv)

    events = _read_events(args.trace)
    summary = summarize(events)
    if args.journeys:
        summary["journeys"] = _journey_mod().merge_journeys(
            events, top=args.top)
    # Loud on stderr too, so --json pipelines (and humans paging the
    # table) cannot miss a lossy trace.
    if summary["meta"].get("dropped_events"):
        print(
            f"WARNING: trace dropped "
            f"{summary['meta']['dropped_events']} event(s) — summary "
            f"undercounts",
            file=sys.stderr,
        )
    if args.chrome:
        with open(args.chrome, "w") as f:
            json.dump(_trace_mod().chrome_trace(events), f)
        if not args.json:
            print(f"chrome trace: {args.chrome}", file=sys.stderr)
    try:
        if args.json:
            print(json.dumps(summary, sort_keys=True))
        else:
            text = render_text(summary)
            if args.journeys:
                text += "\n\n" + render_journeys(summary["journeys"])
            print(text)
    except BrokenPipeError:
        # piped into head/less that closed early — not an error
        try:
            sys.stdout.close()
        except OSError:
            pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
