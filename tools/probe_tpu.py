#!/usr/bin/env python
"""Diagnostic TPU probe (round-5 VERDICT ask #1): record WHAT the probe
sees, not just that it failed.

The tunnelled-TPU init path (axon PJRT plugin, loopback relay) has two
observable stages:

1. **relay endpoint** — the plugin's RPCs dial ``127.0.0.1:8082`` (state
   session) / ``:8083`` (device enumeration). When the tunnel is down
   these refuse instantly, but the gRPC channel inside PJRT retries with
   backoff until deadline — which is why a naive ``jax.devices()`` probe
   *hangs* for its full timeout instead of failing fast. A 2-second TCP
   connect tells us the truth immediately.
2. **backend init** — only attempted when the relay accepts: subprocess
   ``jax.devices()`` with a timeout, stderr captured, so a hang *past* a
   live endpoint is distinguishable from a dead endpoint.

Each invocation appends one JSON record to
``tools/capture_logs/probes.jsonl`` and prints it; ``bench.py`` folds the
latest record into ``BENCH_DETAILS.json`` so failed rounds still carry a
diagnosis trail (round-4 verdict: "probe failure is endured, not
diagnosed").

Exit code: 0 = chip answered, 2 = relay down, 3 = relay up but init
failed/hung.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import time
from datetime import datetime, timezone

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LOG_DIR = os.path.join(REPO, "tools", "capture_logs")
RELAY_PORTS = (8082, 8083)

#: probes.jsonl record schema version (records before this field
#: existed are implicitly version 0).
PROBE_SCHEMA = 1

_FINGERPRINT_VARS = (
    "JAX_PLATFORMS",
    "PALLAS_AXON_TPU_GEN",
    "PALLAS_AXON_POOL_IPS",
    "PALLAS_AXON_REMOTE_COMPILE",
    "AXON_LOOPBACK_RELAY",
    "TPU_SKIP_MDS_QUERY",
    "PYTHONPATH",
)


def _env_fingerprint() -> dict:
    fp = {k: os.environ.get(k) for k in _FINGERPRINT_VARS}
    try:
        import importlib.metadata as md

        fp["jax"] = md.version("jax")
        fp["libtpu"] = md.version("libtpu")
    except Exception:  # pragma: no cover - metadata always present in image
        pass
    # Is a relay/tunnel process even present in this container? (Round-5
    # finding: during the multi-round outage NO relay process existed —
    # the tunnel is provided from outside the container and was simply
    # absent, so nothing in-container can revive it.)
    try:
        n = 0
        for pid in os.listdir("/proc"):
            if not pid.isdigit():
                continue
            try:
                with open(f"/proc/{pid}/cmdline", "rb") as f:
                    argv0 = f.read().split(b"\0", 1)[0]
            except OSError:
                continue
            # argv[0] basename only: a grep/driver process whose
            # ARGUMENTS mention the tunnel must not count as the tunnel.
            name = os.path.basename(argv0.decode("utf-8", "replace"))
            if any(s in name for s in ("relay", "axon", "tunnel")):
                n += 1
        fp["relay_processes_in_container"] = n
    except OSError:  # pragma: no cover
        pass
    return fp


def _tcp_check(port: int, timeout: float = 2.0) -> dict:
    t0 = time.time()
    s = socket.socket()
    s.settimeout(timeout)
    try:
        s.connect(("127.0.0.1", port))
        return {"port": port, "ok": True,
                "elapsed_s": round(time.time() - t0, 3)}
    except OSError as e:
        return {"port": port, "ok": False, "error": type(e).__name__,
                "detail": str(e)[:120],
                "elapsed_s": round(time.time() - t0, 3)}
    finally:
        s.close()


def _init_check(timeout: float) -> dict:
    """Subprocess jax.devices() with captured stderr — only worth paying
    for when the relay endpoint accepts connections."""
    code = (
        "import json, time, jax; t0 = time.time(); d = jax.devices(); "
        "print(json.dumps({'devices': [str(x) for x in d], "
        "'platform': d[0].platform, 'kind': d[0].device_kind, "
        "'n': len(d), 'elapsed_s': round(time.time() - t0, 1)}))"
    )
    t0 = time.time()
    try:
        p = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=timeout,
        )
    except subprocess.TimeoutExpired as e:
        return {
            "stage": "backend_init", "ok": False, "hung": True,
            "timeout_s": timeout,
            "stderr_tail": ((e.stderr or b"").decode("utf-8", "replace")
                            if isinstance(e.stderr, bytes)
                            else (e.stderr or ""))[-2000:],
        }
    out: dict = {"stage": "backend_init", "ok": p.returncode == 0,
                 "elapsed_s": round(time.time() - t0, 1)}
    if p.returncode == 0:
        try:
            out.update(json.loads(p.stdout.strip().splitlines()[-1]))
        except Exception:
            out["stdout_tail"] = p.stdout[-500:]
    else:
        out["returncode"] = p.returncode
        out["stderr_tail"] = p.stderr[-2000:]
    return out


def probe(init_timeout: float = 180.0) -> dict:
    """Run the staged probe; returns the record (also appended to the
    probes log). Cheap when the relay is down (~2 s, no JAX import)."""
    rec: dict = {
        # Versioned record shape (ISSUE 2 satellite): consumers
        # (bench.py's probe trail, chip_watch.sh, future dashboards) key
        # on this to evolve the format without guessing. Bump on any
        # incompatible field change.
        "schema": PROBE_SCHEMA,
        "at": datetime.now(timezone.utc).isoformat(timespec="seconds"),
        "env": _env_fingerprint(),
    }
    # The TCP short-circuit only applies when this process is actually
    # behind the loopback tunnel — on a direct-libtpu TPU VM or any
    # other accelerator host those ports mean nothing and init must be
    # attempted regardless.
    tunnel_env = bool(os.environ.get("AXON_LOOPBACK_RELAY")
                      or os.environ.get("PALLAS_AXON_POOL_IPS"))
    if tunnel_env:
        rec["relay"] = [_tcp_check(p) for p in RELAY_PORTS]
    relay_down = tunnel_env and not any(r["ok"] for r in rec["relay"])
    if relay_down:
        rec["diagnosis"] = (
            "relay endpoints 127.0.0.1:8082/:8083 refuse connections — "
            "tunnel down; PJRT gRPC channel would retry-with-backoff "
            "(the observed jax.devices() hang), no point attempting init"
        )
        rec["verdict"] = "relay_down"
    else:
        rec["init"] = _init_check(init_timeout)
        if rec["init"].get("ok") and rec["init"].get("platform") == "cpu":
            # Init "succeeding" onto the CPU backend is NOT a live chip —
            # chip_watch.sh keys a full capture off exit code 0.
            rec["verdict"] = "cpu_only"
            rec["diagnosis"] = (
                "backend init reached only the CPU backend — no "
                "accelerator visible to this process"
            )
        elif rec["init"].get("ok"):
            rec["verdict"] = "chip_up"
            rec["diagnosis"] = "chip answered"
        elif rec["init"].get("hung"):
            rec["verdict"] = "init_hang"
            rec["diagnosis"] = (
                "relay endpoint accepts TCP but backend init hung past "
                f"{init_timeout:.0f}s — wedge is past the tunnel "
                "(claim/grant or device enumeration); see stderr_tail"
            )
        else:
            rec["verdict"] = "init_error"
            rec["diagnosis"] = "backend init failed; see stderr_tail"
    try:
        # Best-effort side channel: a logging failure (read-only
        # checkout, full disk) must never veto a chip_up result.
        os.makedirs(LOG_DIR, exist_ok=True)
        with open(os.path.join(LOG_DIR, "probes.jsonl"), "a") as f:
            f.write(json.dumps(rec) + "\n")
    except OSError:
        pass
    return rec


def tail_records(n: int) -> list[dict]:
    """Newest ``n`` probe records (oldest first) — the single owner of
    the probes.jsonl location and format; bench.py folds these into
    BENCH_DETAILS.json as the probe-diagnosis trail."""
    path = os.path.join(LOG_DIR, "probes.jsonl")
    try:
        lines = [ln for ln in open(path).read().splitlines() if ln.strip()]
        return [json.loads(ln) for ln in lines[-n:]]
    except (OSError, json.JSONDecodeError):
        return []


def latest_record() -> dict | None:
    """Most recent probe record, or None."""
    recs = tail_records(1)
    return recs[-1] if recs else None


if __name__ == "__main__":
    timeout = float(sys.argv[1]) if len(sys.argv) > 1 else 180.0
    record = probe(timeout)
    print(json.dumps(record, indent=2))
    sys.exit({"chip_up": 0, "relay_down": 2}.get(record["verdict"], 3))
