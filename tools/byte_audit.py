#!/usr/bin/env python
"""Roofline byte audit for the bench workloads (round-5 VERDICT ask #3).

The ResNet roofline (docs/benchmarks.md) was grounded in two numbers per
config: XLA ``cost_analysis`` FLOPs and the compiled module's byte
traffic — this tool produces the same pair for the TRANSFORMER bench
step (and, for cross-checking, the ResNet one), so the MFU targets are
mechanistic instead of aspirational.

Usage::

    python tools/byte_audit.py transformer [--remat dots|nothing|none]
        [--batch 16] [--chunks 16]
    python tools/byte_audit.py resnet [--remat none|conv|full] [--batch 128]

Prints one JSON object: per-step FLOPs, XLA "bytes accessed" (post-fusion
HBM traffic estimate of the partitioned module), peak/temp memory from
``memory_analysis``, and the derived compute/bandwidth floors for the
device (or the v5e reference numbers when compiling on CPU — the compile
is backend-honest for FLOPs; bytes-accessed on CPU reflects CPU fusion
and is labelled as such).

The bench's own workload definitions are reused (``bench._resnet_setup``
and the same transformer construction as ``bench._bench_transformer``)
so the audit cannot drift from what the bench times.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _HERE)

V5E_PEAK_FLOPS = 197e12  # bf16
V5E_HBM_GBPS = 819e9


def _analyses(compiled) -> dict:
    out: dict = {}
    try:
        a = compiled.cost_analysis()
        a = a[0] if isinstance(a, (list, tuple)) else a
        out["flops"] = float(a.get("flops", 0.0))
        out["bytes_accessed"] = float(a.get("bytes accessed", 0.0))
    except Exception as e:
        out["cost_analysis_error"] = f"{type(e).__name__}: {e}"[:160]
    try:
        m = compiled.memory_analysis()
        for k in ("temp_size_in_bytes", "argument_size_in_bytes",
                  "output_size_in_bytes", "generated_code_size_in_bytes"):
            v = getattr(m, k, None)
            if v is not None:
                out[k] = int(v)
    except Exception as e:
        out["memory_analysis_error"] = f"{type(e).__name__}: {e}"[:160]
    return out


def _floors(rec: dict, steps_in_program: int) -> None:
    """Derive per-step floors; on a non-TPU backend the v5e peaks are
    used and labelled."""
    import jax

    kind = jax.devices()[0].device_kind
    on_tpu = jax.devices()[0].platform == "tpu"
    rec["device_kind"] = kind
    rec["floors_vs"] = kind if on_tpu else "v5e (reference; CPU compile)"
    flops = rec.get("flops")
    nbytes = rec.get("bytes_accessed")
    if flops:
        rec["flops_per_step"] = flops / steps_in_program
        rec["compute_floor_ms"] = round(
            flops / steps_in_program / V5E_PEAK_FLOPS * 1e3, 1)
    if nbytes:
        rec["bytes_per_step"] = nbytes / steps_in_program
        rec["bandwidth_floor_ms"] = round(
            nbytes / steps_in_program / V5E_HBM_GBPS * 1e3, 1)
        if not on_tpu:
            rec["bytes_note"] = (
                "bytes accessed from the CPU-compiled module: CPU fusion "
                "differs from TPU; treat as an upper-ish bound and "
                "re-audit on chip (tools/on_chip_capture.sh logs this)"
            )


def audit_transformer(remat: str, batch: int, chunks: int) -> dict:
    """AOT-compile the LM-scale bench transformer step (the exact
    construction of ``bench._bench_transformer`` on-accel: flash
    attention, double-buffered bf16 allreduce, adam, fused chunked LM
    head) and pull its analyses. One scan step inside the program so
    per-step numbers need no trip-count division."""
    import jax
    import jax.numpy as jnp
    import optax
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    from chainermn_tpu import create_communicator, create_multi_node_optimizer
    from chainermn_tpu.models import TransformerLM, lm_loss_fused
    from chainermn_tpu.ops.flash_attention import flash_attention

    comm = create_communicator("xla")
    T = 2048
    interpret = jax.devices()[0].platform != "tpu"

    def attn(q, k, v, *, causal, scale):
        return flash_attention(q, k, v, causal=causal, scale=scale,
                               interpret=interpret)

    model = TransformerLM(
        num_layers=8, d_model=1024, num_heads=16, d_ff=4096,
        max_len=2048, remat=remat != "none",
        remat_policy="dots" if remat == "dots" else "nothing",
        return_hidden=True, attention_fn=attn,
    )
    B = batch * comm.size
    tokens = jax.numpy.zeros((B, T), jnp.int32)
    params = jax.eval_shape(
        lambda k, t: model.init(k, t, train=True),
        jax.random.PRNGKey(1), tokens[:2],
    )
    params = jax.tree.map(
        lambda s: jnp.zeros(s.shape, s.dtype), params)
    opt = create_multi_node_optimizer(
        optax.adam(1e-4), comm, double_buffering=True,
        allreduce_grad_dtype=jnp.bfloat16,
    )

    def loss_fn(p, tok):
        hidden = model.apply(p, tok, train=True)
        emb = p["params"]["tok_emb"]["embedding"]
        return lm_loss_fused(hidden, emb, tok, n_chunks=chunks)

    def local(params, opt_state, tok):
        loss, grads = jax.value_and_grad(loss_fn)(params, tok)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    fn = jax.jit(
        shard_map(local, mesh=comm.mesh,
                  in_specs=(P(), P(), P(comm.grad_axes)),
                  out_specs=(P(), P(), P()), check_vma=False)
    )
    opt_state = opt.init(params)
    compiled = fn.lower(params, opt_state, tokens).compile()
    rec = {"workload": "transformer",
           "config": f"8L-d1024-ff4096-v32k B{B}xT{T} "
                     f"remat={remat} chunks={chunks}"}
    rec.update(_analyses(compiled))
    _floors(rec, steps_in_program=1)
    n_params = sum(
        x.size for x in jax.tree.leaves(params))
    rec["params_m"] = round(n_params / 1e6, 1)
    # The bench's MODEL-flops convention (6P/token + causal attention),
    # for MFU-target math independent of remat recompute.
    model_flops = (6 * n_params + 6 * 8 * T * 1024) * B * T
    rec["model_flops_per_step"] = model_flops
    rec["model_compute_floor_ms"] = round(
        model_flops / V5E_PEAK_FLOPS * 1e3, 1)
    return rec


def audit_resnet(remat: str, batch: int) -> dict:
    import bench

    from chainermn_tpu import create_communicator

    os.environ["CHAINERMN_BENCH_RESNET_BATCH"] = str(batch)
    comm = create_communicator("xla")
    import jax

    on_accel = jax.devices()[0].platform != "cpu"
    step, state, (x, y), b, _, _ = bench._resnet_setup(
        comm, on_accel, force_remat=remat if on_accel else None)
    rec = {"workload": "resnet50" if on_accel else "resnet18-proxy",
           "config": f"b{b} remat={remat}"}
    try:
        compiled = step.lower(state, (x, y)).compile()
        rec.update(_analyses(compiled))
        _floors(rec, steps_in_program=1)
    except Exception as e:
        rec["error"] = f"{type(e).__name__}: {e}"[:200]
    return rec


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("workload", choices=["transformer", "resnet"])
    ap.add_argument("--remat", default="dots")
    ap.add_argument("--batch", type=int, default=None)
    ap.add_argument("--chunks", type=int, default=16)
    args = ap.parse_args()
    if args.workload == "transformer":
        rec = audit_transformer(
            args.remat, args.batch or 16, args.chunks)
    else:
        rec = audit_resnet(
            args.remat if args.remat != "dots" else "none",
            args.batch or 128)
    print(json.dumps(rec, indent=1))


if __name__ == "__main__":
    main()
