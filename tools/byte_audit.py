#!/usr/bin/env python
"""Roofline byte audit for the bench workloads (round-5 VERDICT ask #3).

The ResNet roofline (docs/benchmarks.md) was grounded in two numbers per
config: XLA ``cost_analysis`` FLOPs and the compiled module's byte
traffic — this tool produces the same pair for the TRANSFORMER bench
step (and, for cross-checking, the ResNet one), so the MFU targets are
mechanistic instead of aspirational.

Usage::

    python tools/byte_audit.py transformer [--remat dots|nothing|none]
        [--batch 16] [--chunks 16]
    python tools/byte_audit.py resnet [--remat none|conv|full] [--batch 128]
    python tools/byte_audit.py decode [--live-frac 0.5]
    python tools/byte_audit.py moe

Prints one JSON object: per-step FLOPs, XLA "bytes accessed" (post-fusion
HBM traffic estimate of the partitioned module), peak/temp memory from
``memory_analysis``, and the derived compute/bandwidth floors for the
device (or the v5e reference numbers when compiling on CPU — the compile
is backend-honest for FLOPs; bytes-accessed on CPU reflects CPU fusion
and is labelled as such).

The bench's own workload definitions are reused (``bench._resnet_setup``
and the same transformer construction as ``bench._bench_transformer``)
so the audit cannot drift from what the bench times.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _HERE)


def _note(msg: str) -> None:
    """Progress trail on stderr, flushed: the audit's slowest phase (an
    AOT ``.compile()`` of the full train step) goes through the axon
    remote-compile relay on chip and has been observed to wedge past the
    capture's 900 s timeout with ZERO output — the trail turns an empty
    log into 'wedged at <phase>'."""
    print(f"[audit] {msg}", file=sys.stderr, flush=True)

V5E_PEAK_FLOPS = 197e12  # bf16
V5E_HBM_GBPS = 819e9


def _device_peaks() -> tuple[float, float, str]:
    """(flops, hbm_bytes_per_s, label) for the actual device, from
    bench's adjacent per-kind tables (one matcher, one place to add a
    kind) — the v5e reference numbers, labelled as such, when unknown
    or on CPU."""
    import jax

    import bench

    kind = jax.devices()[0].device_kind
    on_tpu = jax.devices()[0].platform == "tpu"
    if on_tpu:
        flops = bench._peak_lookup(kind, bench._PEAK_BF16_FLOPS)
        hbm = bench._peak_lookup(kind, bench._PEAK_HBM_BYTES)
        if flops and hbm:
            return flops, hbm, kind
        if on_tpu and (flops or hbm):  # half-known: fill, label honestly
            return (flops or V5E_PEAK_FLOPS, hbm or V5E_HBM_GBPS,
                    f"{kind} (missing table entry filled with v5e)")
    return V5E_PEAK_FLOPS, V5E_HBM_GBPS, (
        f"v5e (reference; {'unknown kind ' + kind if on_tpu else 'CPU compile'})"
    )


def _analyses(compiled) -> dict:
    out: dict = {}
    try:
        a = compiled.cost_analysis()
        a = a[0] if isinstance(a, (list, tuple)) else a
        out["flops"] = float(a.get("flops", 0.0))
        out["bytes_accessed"] = float(a.get("bytes accessed", 0.0))
    except Exception as e:
        out["cost_analysis_error"] = f"{type(e).__name__}: {e}"[:160]
    try:
        m = compiled.memory_analysis()
        for k in ("temp_size_in_bytes", "argument_size_in_bytes",
                  "output_size_in_bytes", "generated_code_size_in_bytes"):
            v = getattr(m, k, None)
            if v is not None:
                out[k] = int(v)
    except Exception as e:
        out["memory_analysis_error"] = f"{type(e).__name__}: {e}"[:160]
    return out


def _floors(rec: dict, steps_in_program: int) -> None:
    """Derive per-step floors against the ACTUAL device's peaks (per
    bench's kind table); the v5e reference numbers, labelled as such,
    when the kind is unknown or the compile ran on CPU."""
    import jax

    peak_flops, peak_hbm, label = _device_peaks()
    on_tpu = jax.devices()[0].platform == "tpu"
    rec["device_kind"] = jax.devices()[0].device_kind
    rec["floors_vs"] = label
    flops = rec.get("flops")
    nbytes = rec.get("bytes_accessed")
    if flops:
        rec["flops_per_step"] = flops / steps_in_program
        rec["compute_floor_ms"] = round(
            flops / steps_in_program / peak_flops * 1e3, 1)
    if nbytes:
        rec["bytes_per_step"] = nbytes / steps_in_program
        rec["bandwidth_floor_ms"] = round(
            nbytes / steps_in_program / peak_hbm * 1e3, 1)
        if not on_tpu:
            rec["bytes_note"] = (
                "bytes accessed from the CPU-compiled module: CPU fusion "
                "differs from TPU; treat as an upper-ish bound and "
                "re-audit on chip (tools/on_chip_capture.sh logs this)"
            )


def _seq_ring_bytes(model, B: int, T: int, n: int) -> dict:
    """The seq-axis ring's wire-byte accounting for this workload at
    ``n`` sequence shards (ISSUE 13): per hop the unrolled plan ring
    (``seq_ring_attention_local``) moves the stacked (K, V) pair of one
    shard's slice — ``2 * B * T/n * kv_heads * head_dim`` elements — as
    ONE collective-permute; a forward pass is ``n-1`` hops per layer,
    the backward ``(n-1) + n`` (kv ring + the travelling dk/dv
    accumulator). These bytes cross the ICI neighbour links, NOT HBM,
    so they are reported as roofline INPUTS (floor them against the
    device's ICI bandwidth when sizing a mesh), not folded into the
    HBM floors above."""
    import numpy as np

    kv_heads = model.num_kv_heads or model.num_heads
    head_dim = model.d_model // model.num_heads
    try:
        itemsize = np.dtype(model.compute_dtype).itemsize
    except TypeError:
        itemsize = 2  # bfloat16: not a numpy dtype, 2 wire bytes
    per_hop = 2 * B * (T // n) * kv_heads * head_dim * itemsize
    layers = model.num_layers
    return {
        "shards": n,
        "per_hop_kv_bytes": per_hop,
        "hops_per_layer_fwd": n - 1,
        "hops_per_layer_bwd": 2 * n - 1,
        "ring_bytes_per_step": per_hop * (3 * n - 2) * layers,
        "plane": "ici (neighbour exchange; not an HBM floor)",
    }


def audit_transformer(remat: str, batch: int, chunks: int) -> dict:
    """AOT-compile the LM-scale bench transformer step — the VERY
    workload ``bench._bench_transformer`` times, via the shared
    ``bench._transformer_setup`` (knobs flow through the same
    CHAINERMN_BENCH_TF_* env surface the bench and capture script use),
    with one scan step in the program so per-step numbers need no
    trip-count division."""
    import jax

    import bench

    from chainermn_tpu import create_communicator

    os.environ["CHAINERMN_BENCH_TF_REMAT"] = remat
    os.environ["CHAINERMN_BENCH_TF_BATCH"] = str(batch)
    os.environ["CHAINERMN_BENCH_TF_CHUNKS"] = str(chunks)
    comm = create_communicator("xla")
    on_tpu = jax.devices()[0].platform == "tpu"
    _note(f"transformer: tracing step (backend={jax.devices()[0].platform})")
    (fn, (params, opt_state, tokens), B, T, _steps, model, cfg, _kf,
     _nc) = bench._transformer_setup(
        comm, on_accel=True, steps=1, interpret=not on_tpu,
        abstract_params=True)
    lowered = fn.lower(params, opt_state, tokens)
    _note("transformer: lowered; compiling (the phase that can wedge "
          "behind the remote-compile relay)")
    compiled = lowered.compile()
    _note("transformer: compiled; running analyses")
    rec = {"workload": "transformer",
           "config": f"{cfg} B{B}xT{T} remat={remat} chunks={chunks}",
           "cost_analysis_note": (
               "the bench step body sits inside lax.scan (and the fused "
               "LM head scans over chunks); XLA cost_analysis does not "
               "multiply through scan regions (see bench.py's MFU note), "
               "so flops/bytes_accessed under-count — "
               "model_flops_per_step is the grounded compute number"
           )}
    rec.update(_analyses(compiled))
    _floors(rec, steps_in_program=1)
    n_params = sum(x.size for x in jax.tree.leaves(params))
    rec["params_m"] = round(n_params / 1e6, 1)
    # The bench's MODEL-flops convention (6P/token + causal attention),
    # for MFU-target math independent of remat recompute.
    peak_flops, _, _ = _device_peaks()
    # Per DEVICE (cost_analysis also describes the per-device
    # partitioned module) — same division as the bench's MFU.
    model_flops = (
        6 * n_params + 6 * model.num_layers * T * model.d_model
    ) * B * T / comm.size
    rec["model_flops_per_step"] = model_flops
    rec["model_compute_floor_ms"] = round(
        model_flops / peak_flops * 1e3, 1)
    # ISSUE 13: the seq-axis ring's per-hop K/V wire bytes for THIS
    # workload — the ICI-side roofline input for long-context sharding.
    n_seq = int(os.environ.get("CHAINERMN_AUDIT_SEQ_SHARDS", "4"))
    if n_seq > 1 and T % n_seq == 0:
        rec["seq_ring"] = _seq_ring_bytes(model, B, T, n_seq)
    return rec


def _moe_a2a_bytes(*, tokens_local: int, d_model: int, n_shards: int,
                   eps: int, k: int, capacity_factor, itemsize: int,
                   n_layers: int) -> dict:
    """The expert axis's all_to_all wire accounting (ISSUE 20) — pure
    shape arithmetic, no compile, backend-independent.

    Each shard assembles queues ``[E_global, capacity, d_model]`` for
    its local tokens and ships the off-shard ``(n-1)/n`` fraction per
    ``all_to_all``; dispatch + combine = exactly 2 per MoE layer on the
    forward (pinned structurally in tests/test_moe.py), 3 on
    forward+backward (XLA merges one backward transpose into a forward
    a2a). ``capacity`` is the drop/pad knob: padded slots cross the
    wire as zeros — the ``pad_fraction`` row prices what a tighter
    capacity factor would save. These bytes cross ICI, not HBM, so they
    are roofline INPUTS (floor them against the device's a2a
    bandwidth), not folded into the HBM floors."""
    from chainermn_tpu.parallel.moe import moe_capacity

    e_global = n_shards * eps
    capacity = moe_capacity(tokens_local, e_global, k, capacity_factor)
    queue_bytes = e_global * capacity * d_model * itemsize
    wire = queue_bytes * (n_shards - 1) // max(1, n_shards)
    slots = e_global * capacity
    pad_fraction = max(0, slots - tokens_local * k) / max(1, slots)
    return {
        "shards": n_shards,
        "experts": e_global,
        "experts_per_shard": eps,
        "capacity": capacity,
        "queue_bytes_per_shard": queue_bytes,
        "wire_bytes_per_a2a": wire,
        "a2a_per_layer_fwd": 2,
        "a2a_per_layer_fwd_bwd": 3,
        "dispatch_combine_wire_bytes_fwd": 2 * wire * n_layers,
        "dispatch_combine_wire_bytes_fwd_bwd": 3 * wire * n_layers,
        "pad_fraction": round(pad_fraction, 4),
        "plane": "ici (all_to_all; not an HBM floor)",
    }


def audit_moe() -> dict:
    """ISSUE 20: roofline the expert axis's dispatch/combine wire.

    Structural side only — the a2a byte model needs no compile (the
    arithmetic mirrors ``moe_layer_local``'s queue shapes exactly), so
    the same rows are honest on CPU and on chip. Audited at the bench
    ``moe`` phase's CPU-proxy shape AND at its accel shape (the
    on-chip roofline target), with a serving-decode row for the
    ownership-split TP MoE tick (per-slot rows, no-drop capacity)."""
    import jax

    rec = {"workload": "moe", "plane": "ici"}
    # bench._bench_moe_plan's shape convention: CPU proxy vs accel.
    rec["train_proxy"] = dict(
        config="T128xE8xD64 f32 expert4xdata2 (bench CPU-proxy shape)",
        **_moe_a2a_bytes(tokens_local=64, d_model=64, n_shards=4,
                         eps=2, k=1, capacity_factor=1.25,
                         itemsize=4, n_layers=1))
    rec["train_accel"] = dict(
        config="T512xE8xD256 f32 expert4xdata2 (bench accel shape, "
               "8-chip mesh)",
        **_moe_a2a_bytes(tokens_local=256, d_model=256, n_shards=4,
                         eps=2, k=1, capacity_factor=1.25,
                         itemsize=4, n_layers=1))
    # Serving decode tick (engine ownership split over the TP mesh):
    # own_rows slots per shard, no-drop capacity, bf16 activations at
    # the accel serving shape (bench._bench_serving's convention).
    rec["serving_decode_accel"] = dict(
        config="slots=16 tp=4 E8 D512 bf16 no-drop (serving accel "
               "shape)",
        **_moe_a2a_bytes(tokens_local=4, d_model=512, n_shards=4,
                         eps=2, k=1, capacity_factor=None,
                         itemsize=2, n_layers=4))
    rec["device_kind"] = jax.devices()[0].device_kind
    rec["itemsize_note"] = (
        "train rows price float32 queues (the bench moe phase's "
        "dtype); serving row prices the engine's bf16 compute dtype"
    )
    return rec


def audit_resnet(remat: str, batch: int) -> dict:
    import bench

    from chainermn_tpu import create_communicator

    os.environ["CHAINERMN_BENCH_RESNET_BATCH"] = str(batch)
    comm = create_communicator("xla")
    import jax

    # Always audit the ACCEL workload (ResNet-50 at the bench batch):
    # the audit exists to ground the on-chip MFU target, and the FLOPs
    # side is backend-honest even when the compile runs on CPU (the
    # resnet step has no Pallas kernels, so a CPU compile is legal).
    step, state, (x, y), b, _, _ = bench._resnet_setup(
        comm, True, force_remat=remat)
    rec = {"workload": "resnet50", "config": f"b{b} remat={remat}"}
    try:
        _note(f"resnet: lowering (backend={jax.devices()[0].platform})")
        lowered = step.lower(state, (x, y))
        _note("resnet: lowered; compiling")
        compiled = lowered.compile()
        _note("resnet: compiled; running analyses")
        rec.update(_analyses(compiled))
        _floors(rec, steps_in_program=1)
    except Exception as e:
        rec["error"] = f"{type(e).__name__}: {e}"[:200]
    return rec


def _decode_attend_models(*, slots: int, max_len: int, bs: int,
                          heads: int, kv_heads: int, head_dim: int,
                          itemsize: int, live_frac: float) -> dict:
    """Structural per-tick HBM byte models for the three paged-decode
    attend stories (ISSUE 19) — pure shape arithmetic, no compile, so
    the accounting is backend-independent:

    - ``floor``: ONE live-KV read (every live (token, kv-head) element
      of K and V touched exactly once) + the q read and o write. No
      attend that looks at the whole live history can read less.
    - ``fused``: the kernel's actual traffic — live blocks once per
      kv-head slice (grid ``(B, Hkv, M)``, block ``(1, bs, 1, D)``),
      PLUS one redirect block per (slot, head) (dead grid cells aim
      their DMA at a fixed block; Pallas skips refetching an unchanged
      index, so the dead tail costs O(1) reads, not O(M)), PLUS the
      sublane-padded q/o rows (``R_pad >= 8``).
    - ``xla_gather``: the dense-view story — ``pool[tables]`` reads the
      FULL table width regardless of liveness, materializes the view
      (write + attend read-back), and the masked fp32 scores make an
      HBM round-trip. Horizon-priced by construction: its bytes do not
      shrink when the history is short.

    ``live_frac`` sets the live history length (fraction of
    ``max_len``) for the floor/fused side; ``*_full`` rows price the
    full-horizon case where even the fused kernel must read every
    block. Ratios land in docs/benchmarks.md next to the measured
    serving_decode_kernel rows."""
    group = heads // kv_heads
    r_pad = max(8, -(-group // 8) * 8)  # T=1 decode tick rows
    q_bytes = slots * kv_heads * r_pad * head_dim * itemsize
    o_bytes = q_bytes
    qo_floor = 2 * slots * heads * head_dim * itemsize  # unpadded
    m_total = -(-max_len // bs)
    block_bytes = bs * head_dim * itemsize  # one kv-head's slice

    def kv(nblocks):  # K and V, every kv head, nblocks per slot
        return 2 * slots * kv_heads * nblocks * block_bytes

    def story(nblocks):
        floor = kv(nblocks) + qo_floor
        fused = kv(min(nblocks + 1, m_total)) + q_bytes + o_bytes
        xla = (3 * kv(m_total)                      # gather+write+read
               + 2 * slots * heads * m_total * bs * 4   # fp32 scores
               + qo_floor)
        return {
            "floor_bytes": floor, "fused_bytes": fused,
            "xla_gather_bytes": xla,
            "fused_vs_floor_x": round(fused / floor, 2),
            "xla_vs_fused_x": round(xla / fused, 1),
        }

    live = max(1, min(m_total, round(m_total * live_frac)))
    rec = {"live_blocks": live, "total_blocks": m_total,
           "live_frac": live_frac}
    rec.update(story(live))
    rec.update({k + "_full": v for k, v in story(m_total).items()})
    return rec


def audit_decode(live_frac: float) -> dict:
    """ISSUE 19: roofline the paged DECODE tick, xla vs fused.

    Measured side: AOT-compile the serving engine's real decode-step
    program (``_decode_step_jit`` — the very program the bench's
    serving phases time) per ``decode_attend_impl`` at the bench's
    backend shape and run the usual analyses/floors. On CPU the fused
    program compiles the kernel's interpret-mode EMULATION, whose
    bytes describe the emulator, not the kernel — labelled, and the
    reason the structural section exists.

    Structural side: :func:`_decode_attend_models` at the audited
    shape AND at the accel serving shape (the on-chip roofline
    target; arithmetic needs no compile)."""
    import functools

    import jax
    import jax.numpy as jnp

    from chainermn_tpu.models.transformer import TransformerLM
    from chainermn_tpu.serving import ServingEngine

    on_tpu = jax.devices()[0].platform == "tpu"
    # The serving bench's shape convention (bench._bench_serving):
    # accel vs CPU-proxy.
    if on_tpu:
        layers, d_model, heads, d_ff = 4, 512, 8, 2048
        vocab, max_len, slots, bs = 32000, 512, 16, 32
        dtype = jnp.bfloat16
    else:
        layers, d_model, heads, d_ff = 2, 64, 4, 128
        vocab, max_len, slots, bs = 256, 64, 4, 8
        dtype = jnp.float32
    model = TransformerLM(
        vocab_size=vocab, num_layers=layers, num_heads=heads,
        d_model=d_model, d_ff=d_ff, max_len=max_len, compute_dtype=dtype,
    )
    _note(f"decode: init params (backend={jax.devices()[0].platform})")
    params = jax.jit(
        functools.partial(model.init, train=False)
    )(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    itemsize = jnp.dtype(dtype).itemsize
    head_dim = d_model // heads
    rec = {
        "workload": "paged_decode",
        "config": (f"D{d_model}xH{heads}xL{max_len} slots={slots} "
                   f"bs={bs} layers={layers}"),
        "impls": {},
    }
    for impl in ("xla", "fused"):
        _note(f"decode: compiling decode step (attend={impl})")
        sub: dict = {}
        try:
            eng = ServingEngine(
                model, params, num_slots=slots, max_len=max_len,
                decode_impl="paged", decode_attend_impl=impl,
                kv_block_size=bs, prefill_buckets=(8,), spec_tokens=0,
            )
            args = (
                eng._cache, eng._vars,
                jnp.zeros((slots,), jnp.int32),
                jnp.zeros((slots,), jnp.int32),
                jnp.asarray(eng._dummy_tables()),
                jnp.asarray(eng._seeds),
            )
            compiled = eng._decode_step_jit.lower(*args).compile()
            sub.update(_analyses(compiled))
            _floors(sub, steps_in_program=1)
            if impl == "fused" and not on_tpu:
                sub["bytes_note"] = (
                    "CPU compile runs the kernel's interpret-mode "
                    "emulation: these bytes describe the emulator, not "
                    "the kernel — the structural section below is the "
                    "honest fused number off-chip; re-audit on chip "
                    "(tools/on_chip_capture.sh logs this)"
                )
        except Exception as e:
            sub["error"] = f"{type(e).__name__}: {e}"[:200]
        rec["impls"][impl] = sub
    _note("decode: structural attend models")
    rec["attend_model"] = _decode_attend_models(
        slots=slots, max_len=max_len, bs=bs, heads=heads,
        kv_heads=heads, head_dim=head_dim, itemsize=itemsize,
        live_frac=live_frac)
    if not on_tpu:
        # The on-chip roofline target, priced by the same arithmetic.
        rec["attend_model_accel_shape"] = dict(
            config="D512xH8xL512 slots=16 bs=32 bf16",
            **_decode_attend_models(
                slots=16, max_len=512, bs=32, heads=8, kv_heads=8,
                head_dim=64, itemsize=2, live_frac=live_frac))
    return rec


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("workload",
                    choices=["transformer", "resnet", "decode", "moe"])
    ap.add_argument("--remat", default="dots")
    ap.add_argument("--batch", type=int, default=None)
    ap.add_argument("--chunks", type=int, default=16)
    ap.add_argument(
        "--seq-shards", type=int, default=4,
        help="seq-axis shard count for the transformer audit's "
             "seq_ring wire-byte rows (ISSUE 13); the ring's per-hop "
             "K/V bytes are ICI-plane roofline inputs")
    ap.add_argument(
        "--live-frac", type=float, default=0.5,
        help="live-history fraction of max_len for the decode audit's "
             "floor/fused attend models (ISSUE 19); the xla dense-view "
             "gather is horizon-priced regardless")
    ap.add_argument(
        "--target", choices=["auto", "cpu"], default="auto",
        help="cpu: pin the CPU backend before first device use "
             "(conftest's recipe) — FLOPs are backend-honest either way "
             "and the compile cannot wedge behind the chip tunnel; "
             "bytes-accessed is then labelled CPU-fusion")
    args = ap.parse_args()
    if args.target == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")
    os.environ["CHAINERMN_AUDIT_SEQ_SHARDS"] = str(args.seq_shards)
    if args.workload == "transformer":
        rec = audit_transformer(
            args.remat, args.batch or 16, args.chunks)
    elif args.workload == "decode":
        rec = audit_decode(args.live_frac)
    elif args.workload == "moe":
        rec = audit_moe()
    else:
        rec = audit_resnet(
            args.remat if args.remat != "dots" else "none",
            args.batch or 128)
    print(json.dumps(rec, indent=1))


if __name__ == "__main__":
    main()
