#!/usr/bin/env python
"""Roofline byte audit for the bench workloads (round-5 VERDICT ask #3).

The ResNet roofline (docs/benchmarks.md) was grounded in two numbers per
config: XLA ``cost_analysis`` FLOPs and the compiled module's byte
traffic — this tool produces the same pair for the TRANSFORMER bench
step (and, for cross-checking, the ResNet one), so the MFU targets are
mechanistic instead of aspirational.

Usage::

    python tools/byte_audit.py transformer [--remat dots|nothing|none]
        [--batch 16] [--chunks 16]
    python tools/byte_audit.py resnet [--remat none|conv|full] [--batch 128]

Prints one JSON object: per-step FLOPs, XLA "bytes accessed" (post-fusion
HBM traffic estimate of the partitioned module), peak/temp memory from
``memory_analysis``, and the derived compute/bandwidth floors for the
device (or the v5e reference numbers when compiling on CPU — the compile
is backend-honest for FLOPs; bytes-accessed on CPU reflects CPU fusion
and is labelled as such).

The bench's own workload definitions are reused (``bench._resnet_setup``
and the same transformer construction as ``bench._bench_transformer``)
so the audit cannot drift from what the bench times.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _HERE)


def _note(msg: str) -> None:
    """Progress trail on stderr, flushed: the audit's slowest phase (an
    AOT ``.compile()`` of the full train step) goes through the axon
    remote-compile relay on chip and has been observed to wedge past the
    capture's 900 s timeout with ZERO output — the trail turns an empty
    log into 'wedged at <phase>'."""
    print(f"[audit] {msg}", file=sys.stderr, flush=True)

V5E_PEAK_FLOPS = 197e12  # bf16
V5E_HBM_GBPS = 819e9


def _device_peaks() -> tuple[float, float, str]:
    """(flops, hbm_bytes_per_s, label) for the actual device, from
    bench's adjacent per-kind tables (one matcher, one place to add a
    kind) — the v5e reference numbers, labelled as such, when unknown
    or on CPU."""
    import jax

    import bench

    kind = jax.devices()[0].device_kind
    on_tpu = jax.devices()[0].platform == "tpu"
    if on_tpu:
        flops = bench._peak_lookup(kind, bench._PEAK_BF16_FLOPS)
        hbm = bench._peak_lookup(kind, bench._PEAK_HBM_BYTES)
        if flops and hbm:
            return flops, hbm, kind
        if on_tpu and (flops or hbm):  # half-known: fill, label honestly
            return (flops or V5E_PEAK_FLOPS, hbm or V5E_HBM_GBPS,
                    f"{kind} (missing table entry filled with v5e)")
    return V5E_PEAK_FLOPS, V5E_HBM_GBPS, (
        f"v5e (reference; {'unknown kind ' + kind if on_tpu else 'CPU compile'})"
    )


def _analyses(compiled) -> dict:
    out: dict = {}
    try:
        a = compiled.cost_analysis()
        a = a[0] if isinstance(a, (list, tuple)) else a
        out["flops"] = float(a.get("flops", 0.0))
        out["bytes_accessed"] = float(a.get("bytes accessed", 0.0))
    except Exception as e:
        out["cost_analysis_error"] = f"{type(e).__name__}: {e}"[:160]
    try:
        m = compiled.memory_analysis()
        for k in ("temp_size_in_bytes", "argument_size_in_bytes",
                  "output_size_in_bytes", "generated_code_size_in_bytes"):
            v = getattr(m, k, None)
            if v is not None:
                out[k] = int(v)
    except Exception as e:
        out["memory_analysis_error"] = f"{type(e).__name__}: {e}"[:160]
    return out


def _floors(rec: dict, steps_in_program: int) -> None:
    """Derive per-step floors against the ACTUAL device's peaks (per
    bench's kind table); the v5e reference numbers, labelled as such,
    when the kind is unknown or the compile ran on CPU."""
    import jax

    peak_flops, peak_hbm, label = _device_peaks()
    on_tpu = jax.devices()[0].platform == "tpu"
    rec["device_kind"] = jax.devices()[0].device_kind
    rec["floors_vs"] = label
    flops = rec.get("flops")
    nbytes = rec.get("bytes_accessed")
    if flops:
        rec["flops_per_step"] = flops / steps_in_program
        rec["compute_floor_ms"] = round(
            flops / steps_in_program / peak_flops * 1e3, 1)
    if nbytes:
        rec["bytes_per_step"] = nbytes / steps_in_program
        rec["bandwidth_floor_ms"] = round(
            nbytes / steps_in_program / peak_hbm * 1e3, 1)
        if not on_tpu:
            rec["bytes_note"] = (
                "bytes accessed from the CPU-compiled module: CPU fusion "
                "differs from TPU; treat as an upper-ish bound and "
                "re-audit on chip (tools/on_chip_capture.sh logs this)"
            )


def _seq_ring_bytes(model, B: int, T: int, n: int) -> dict:
    """The seq-axis ring's wire-byte accounting for this workload at
    ``n`` sequence shards (ISSUE 13): per hop the unrolled plan ring
    (``seq_ring_attention_local``) moves the stacked (K, V) pair of one
    shard's slice — ``2 * B * T/n * kv_heads * head_dim`` elements — as
    ONE collective-permute; a forward pass is ``n-1`` hops per layer,
    the backward ``(n-1) + n`` (kv ring + the travelling dk/dv
    accumulator). These bytes cross the ICI neighbour links, NOT HBM,
    so they are reported as roofline INPUTS (floor them against the
    device's ICI bandwidth when sizing a mesh), not folded into the
    HBM floors above."""
    import numpy as np

    kv_heads = model.num_kv_heads or model.num_heads
    head_dim = model.d_model // model.num_heads
    try:
        itemsize = np.dtype(model.compute_dtype).itemsize
    except TypeError:
        itemsize = 2  # bfloat16: not a numpy dtype, 2 wire bytes
    per_hop = 2 * B * (T // n) * kv_heads * head_dim * itemsize
    layers = model.num_layers
    return {
        "shards": n,
        "per_hop_kv_bytes": per_hop,
        "hops_per_layer_fwd": n - 1,
        "hops_per_layer_bwd": 2 * n - 1,
        "ring_bytes_per_step": per_hop * (3 * n - 2) * layers,
        "plane": "ici (neighbour exchange; not an HBM floor)",
    }


def audit_transformer(remat: str, batch: int, chunks: int) -> dict:
    """AOT-compile the LM-scale bench transformer step — the VERY
    workload ``bench._bench_transformer`` times, via the shared
    ``bench._transformer_setup`` (knobs flow through the same
    CHAINERMN_BENCH_TF_* env surface the bench and capture script use),
    with one scan step in the program so per-step numbers need no
    trip-count division."""
    import jax

    import bench

    from chainermn_tpu import create_communicator

    os.environ["CHAINERMN_BENCH_TF_REMAT"] = remat
    os.environ["CHAINERMN_BENCH_TF_BATCH"] = str(batch)
    os.environ["CHAINERMN_BENCH_TF_CHUNKS"] = str(chunks)
    comm = create_communicator("xla")
    on_tpu = jax.devices()[0].platform == "tpu"
    _note(f"transformer: tracing step (backend={jax.devices()[0].platform})")
    (fn, (params, opt_state, tokens), B, T, _steps, model, cfg, _kf,
     _nc) = bench._transformer_setup(
        comm, on_accel=True, steps=1, interpret=not on_tpu,
        abstract_params=True)
    lowered = fn.lower(params, opt_state, tokens)
    _note("transformer: lowered; compiling (the phase that can wedge "
          "behind the remote-compile relay)")
    compiled = lowered.compile()
    _note("transformer: compiled; running analyses")
    rec = {"workload": "transformer",
           "config": f"{cfg} B{B}xT{T} remat={remat} chunks={chunks}",
           "cost_analysis_note": (
               "the bench step body sits inside lax.scan (and the fused "
               "LM head scans over chunks); XLA cost_analysis does not "
               "multiply through scan regions (see bench.py's MFU note), "
               "so flops/bytes_accessed under-count — "
               "model_flops_per_step is the grounded compute number"
           )}
    rec.update(_analyses(compiled))
    _floors(rec, steps_in_program=1)
    n_params = sum(x.size for x in jax.tree.leaves(params))
    rec["params_m"] = round(n_params / 1e6, 1)
    # The bench's MODEL-flops convention (6P/token + causal attention),
    # for MFU-target math independent of remat recompute.
    peak_flops, _, _ = _device_peaks()
    # Per DEVICE (cost_analysis also describes the per-device
    # partitioned module) — same division as the bench's MFU.
    model_flops = (
        6 * n_params + 6 * model.num_layers * T * model.d_model
    ) * B * T / comm.size
    rec["model_flops_per_step"] = model_flops
    rec["model_compute_floor_ms"] = round(
        model_flops / peak_flops * 1e3, 1)
    # ISSUE 13: the seq-axis ring's per-hop K/V wire bytes for THIS
    # workload — the ICI-side roofline input for long-context sharding.
    n_seq = int(os.environ.get("CHAINERMN_AUDIT_SEQ_SHARDS", "4"))
    if n_seq > 1 and T % n_seq == 0:
        rec["seq_ring"] = _seq_ring_bytes(model, B, T, n_seq)
    return rec


def audit_resnet(remat: str, batch: int) -> dict:
    import bench

    from chainermn_tpu import create_communicator

    os.environ["CHAINERMN_BENCH_RESNET_BATCH"] = str(batch)
    comm = create_communicator("xla")
    import jax

    # Always audit the ACCEL workload (ResNet-50 at the bench batch):
    # the audit exists to ground the on-chip MFU target, and the FLOPs
    # side is backend-honest even when the compile runs on CPU (the
    # resnet step has no Pallas kernels, so a CPU compile is legal).
    step, state, (x, y), b, _, _ = bench._resnet_setup(
        comm, True, force_remat=remat)
    rec = {"workload": "resnet50", "config": f"b{b} remat={remat}"}
    try:
        _note(f"resnet: lowering (backend={jax.devices()[0].platform})")
        lowered = step.lower(state, (x, y))
        _note("resnet: lowered; compiling")
        compiled = lowered.compile()
        _note("resnet: compiled; running analyses")
        rec.update(_analyses(compiled))
        _floors(rec, steps_in_program=1)
    except Exception as e:
        rec["error"] = f"{type(e).__name__}: {e}"[:200]
    return rec


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("workload", choices=["transformer", "resnet"])
    ap.add_argument("--remat", default="dots")
    ap.add_argument("--batch", type=int, default=None)
    ap.add_argument("--chunks", type=int, default=16)
    ap.add_argument(
        "--seq-shards", type=int, default=4,
        help="seq-axis shard count for the transformer audit's "
             "seq_ring wire-byte rows (ISSUE 13); the ring's per-hop "
             "K/V bytes are ICI-plane roofline inputs")
    ap.add_argument(
        "--target", choices=["auto", "cpu"], default="auto",
        help="cpu: pin the CPU backend before first device use "
             "(conftest's recipe) — FLOPs are backend-honest either way "
             "and the compile cannot wedge behind the chip tunnel; "
             "bytes-accessed is then labelled CPU-fusion")
    args = ap.parse_args()
    if args.target == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")
    os.environ["CHAINERMN_AUDIT_SEQ_SHARDS"] = str(args.seq_shards)
    if args.workload == "transformer":
        rec = audit_transformer(
            args.remat, args.batch or 16, args.chunks)
    else:
        rec = audit_resnet(
            args.remat if args.remat != "dots" else "none",
            args.batch or 128)
    print(json.dumps(rec, indent=1))


if __name__ == "__main__":
    main()
