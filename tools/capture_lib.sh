# Shared freshness predicate for chip_watch.sh / on_chip_capture.sh.
#
# fresh_artifact <glob> <success-token> <marker>: true iff some file in
# tools/capture_logs matching <glob>, newer than <marker>, contains
# <success-token>. The explicit loop matters: `find -exec grep -l {} +`
# exits 0 when find matches ZERO files (grep never runs), which read as
# "capture complete" on a fresh watch and silently disabled the whole
# capture — caught in review 2026-08-01.
fresh_artifact() {
  local glob=$1 token=$2 marker=$3 f
  [ -n "$marker" ] && [ -e "$marker" ] || return 1
  # NUL-delimited walk: a `for f in $(find ...)` word-splits paths, so a
  # log name with whitespace would silently break the predicate. The
  # while loop reads from process substitution (not a pipeline), so the
  # early `return 0` happens in THIS shell.
  while IFS= read -r -d '' f; do
    grep -q "$token" "$f" && return 0
  done < <(find tools/capture_logs -name "$glob" \
             -newer "$marker" -print0 2>/dev/null)
  return 1
}
