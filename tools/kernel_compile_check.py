#!/usr/bin/env python
"""Mosaic AOT compile check for EVERY Pallas kernel in the tree.

The repo's standing trap (CLAUDE.md, verified round 4): interpret mode
accepts layouts Mosaic rejects — CPU-green kernels can still be
chip-dead. This tool AOT-lowers each kernel entry point with
``interpret=False`` at representative on-chip shapes and ``.compile()``s
it, so a layout rejection becomes a named row in the capture artifact
instead of a surprise mid-bench. No kernel is RUN — compile only, a few
seconds each even through the remote-compile relay (the progress trail
on stderr marks the wedge point if that relay hangs, the byte_audit
precedent).

Checked kernels:

- flash attention forward (causal, GQA, window variant)
- flash attention backward (dq + dkv kernels, via jax.grad)
- fused paged decode (ISSUE 19): plain tick T=1, verify span T>1,
  window, and the dense-cache wrapper — the ``(1, bs, 1, D)`` KV block
  (second-to-last dim 1 over the kv-head axis) is exactly the kind of
  layout Mosaic might refuse, flagged in ROADMAP's on-chip residue.

Usage::

    python tools/kernel_compile_check.py          # needs the real chip
    python tools/kernel_compile_check.py --json out.json

On CPU every case fails fast with the honest explanation (Mosaic
lowering needs a TPU backend) — the capture script only runs this on
chip. Exit code: number of failed cases (0 = all compiled).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

_HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _HERE)


def _note(msg: str) -> None:
    print(f"[kernel-check] {msg}", file=sys.stderr, flush=True)


def _cases():
    """(name, thunk) per kernel entry point; each thunk returns a
    lowered-and-compiled executable (discarded — compile IS the test)."""
    import functools

    import jax
    import jax.numpy as jnp

    from chainermn_tpu.ops.flash_attention import flash_attention
    from chainermn_tpu.ops.paged_decode import (
        dense_flash_decode,
        paged_flash_decode,
    )

    dt = jnp.bfloat16
    # Flash at the bench transformer's LM block shape.
    B, T, Hq, Hkv, D = 2, 2048, 8, 4, 64
    q = jax.ShapeDtypeStruct((B, T, Hq, D), dt)
    kv = jax.ShapeDtypeStruct((B, T, Hkv, D), dt)

    def flash(**kw):
        return jax.jit(functools.partial(
            flash_attention, causal=True, interpret=False,
            block_q=512, block_k=1024, **kw))

    def flash_bwd():
        def loss(q_, k_, v_):
            return flash_attention(
                q_, k_, v_, causal=True, interpret=False,
                block_q=512, block_k=1024).astype(jnp.float32).sum()

        return jax.jit(jax.grad(loss, argnums=(0, 1, 2)))

    # Paged decode at the accel serving shape (bench._bench_serving):
    # slots=16, max_len=512, bs=32 — pool of 257 blocks (scratch + all).
    S, L, bs = 16, 512, 32
    M = L // bs
    pool = jax.ShapeDtypeStruct((S * M + 1, bs, Hkv, D), dt)
    tables = jax.ShapeDtypeStruct((S, M), jnp.int32)
    pos = jax.ShapeDtypeStruct((S,), jnp.int32)

    def paged(T_rows, **kw):
        qd = jax.ShapeDtypeStruct((S, T_rows, Hq, D), dt)
        return (jax.jit(functools.partial(
            paged_flash_decode, interpret=False, **kw)),
            (qd, pool, pool, tables, pos))

    dense_cache = jax.ShapeDtypeStruct((S, L, Hkv, D), dt)
    qd1 = jax.ShapeDtypeStruct((S, 1, Hq, D), dt)

    return [
        ("flash_fwd", lambda: flash().lower(q, kv, kv).compile()),
        ("flash_fwd_window",
         lambda: flash(window=1024).lower(q, kv, kv).compile()),
        ("flash_bwd", lambda: flash_bwd().lower(q, kv, kv).compile()),
        ("paged_decode_t1",
         lambda: (lambda f, a: f.lower(*a).compile())(*paged(1))),
        ("paged_decode_verify_t4",
         lambda: (lambda f, a: f.lower(*a).compile())(*paged(4))),
        ("paged_decode_window",
         lambda: (lambda f, a: f.lower(*a).compile())(
             *paged(1, window=128))),
        ("dense_decode",
         lambda: jax.jit(functools.partial(
             dense_flash_decode, interpret=False)).lower(
             qd1, dense_cache, dense_cache, pos).compile()),
    ]


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--json", default=None,
                    help="also write the result rows to this path")
    args = ap.parse_args()

    import jax

    backend = jax.devices()[0].platform
    rows = []
    for name, thunk in _cases():
        _note(f"compiling {name} (backend={backend})")
        t0 = time.perf_counter()
        row = {"kernel": name}
        try:
            thunk()
            row["ok"] = True
        except Exception as e:
            row["ok"] = False
            row["error"] = f"{type(e).__name__}: {e}"[:300]
        row["compile_s"] = round(time.perf_counter() - t0, 2)
        rows.append(row)
    failures = sum(1 for r in rows if not r["ok"])
    out = {
        "backend": backend,
        "device_kind": jax.devices()[0].device_kind,
        "n_cases": len(rows),
        "failures": failures,
        "results": rows,
    }
    if backend != "tpu":
        out["note"] = (
            "non-TPU backend: Mosaic never ran, failures here say "
            "nothing about the chip — run via tools/on_chip_capture.sh"
        )
    doc = json.dumps(out, indent=1)
    print(doc)
    if args.json:
        with open(args.json, "w") as f:
            f.write(doc + "\n")
    return failures


if __name__ == "__main__":
    sys.exit(main())
