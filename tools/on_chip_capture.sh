#!/bin/bash
# One-command on-chip capture (round-4 VERDICT items 1+2+6+7): the moment
# the tunnelled TPU answers, grab — in priority order — the headline bench
# (fresh last_good_tpu + curve + kernel sweep), then the ResNet-50 MFU
# sweep, then the transformer MFU sweep; finally, if a sweep found a
# better config, re-run the bench with the winner's env knobs so the
# carried artifact holds the BEST honest numbers. Outputs in
# tools/capture_logs/.
set -u
cd "$(dirname "$0")/.."
mkdir -p tools/capture_logs
stamp=$(date -u +%Y%m%dT%H%M%SZ)

# Stage gating: when the watcher re-fires after a mid-capture relay
# death, redo only what FAILED. An artifact satisfies a stage if it is
# newer than $CAPTURE_SINCE (the watcher's watch-start marker) and
# carries the stage's success token. Without CAPTURE_SINCE (manual
# runs) every stage runs.
. "$(dirname "$0")/capture_lib.sh"
_fresh() { fresh_artifact "$1" "$2" "${CAPTURE_SINCE:-}"; }

# Stage 0 (ISSUE 19, first on purpose — the kernel_sweep precedent:
# a Mosaic layout rejection must reach the artifact even if the budget
# cuts everything below): AOT compile-check EVERY Pallas kernel (flash
# fwd/bwd + fused paged decode) with interpret=False. Interpret mode
# accepts layouts Mosaic rejects; this stage is what upgrades the
# CPU-green kernels to chip-trusted — and the gate on ever adopting
# decode_attend_impl=fused (ROADMAP's on-chip residue list).
if _fresh 'kernel_compile_2*.json' '"n_cases"'; then
  echo "[capture $stamp] stage 0: skipped (fresh kernel compile check exists)"
else
  echo "[capture $stamp] stage 0: Mosaic compile check (all Pallas kernels)"
  timeout 900 python tools/kernel_compile_check.py \
    --json "tools/capture_logs/kernel_compile_$stamp.json" \
    > /dev/null 2> "tools/capture_logs/kernel_compile_$stamp.log"
  rc=$?
  echo "[capture] kernel compile check rc=$rc (0 = all compiled):"
  python - "tools/capture_logs/kernel_compile_$stamp.json" <<'PYEOF'
import json, sys
try:
    doc = json.load(open(sys.argv[1]))
except Exception as e:
    print(f"  (no artifact: {e})")
else:
    for r in doc.get("results", []):
        mark = "ok" if r.get("ok") else f"FAIL {r.get('error', '')[:120]}"
        print(f"  {r['kernel']}: {mark} ({r.get('compile_s')}s)")
PYEOF
fi

# bench_2* (not bench_*): stage 4 writes bench_best_<stamp>.log, whose
# live best-config rows must not suppress the default-config stage-1
# bench the README/docs numbers are drawn from.
if _fresh 'bench_2*.log' '"source": "live"'; then
  echo "[capture $stamp] stage 1: skipped (fresh live bench exists)"
else
  echo "[capture $stamp] stage 1: bench.py (+ structured trace)"
  # Observability trace artifact (ISSUE 2): the bench children append
  # wire/phase events here; the report summarizes per-op bytes/time.
  CHAINERMN_TPU_TRACE="tools/capture_logs/trace_bench_$stamp.jsonl" \
    timeout 1800 python bench.py > "tools/capture_logs/bench_$stamp.log" 2>&1
  echo "[capture] bench rc=$? last line:"; tail -1 "tools/capture_logs/bench_$stamp.log" | cut -c1-400
  if [ -s "tools/capture_logs/trace_bench_$stamp.jsonl" ]; then
    timeout 300 python tools/trace_report.py \
      "tools/capture_logs/trace_bench_$stamp.jsonl" \
      --chrome "tools/capture_logs/trace_bench_$stamp.chrome.json" \
      > "tools/capture_logs/trace_report_$stamp.txt" 2>&1
    echo "[capture] trace report rc=$?:"
    head -3 "tools/capture_logs/trace_report_$stamp.txt"
  else
    echo "[capture] no trace emitted (bench wrote no events)"
  fi
  # Live-telemetry snapshot (ISSUE 6): when a long-running process on
  # this host exposes /metrics (CHAINERMN_TPU_METRICS_PORT), archive one
  # scrape + health probe beside the bench log. 2 s fetch timeout inside
  # metrics_dump: a down endpoint costs nothing and fails quietly.
  if [ -n "${CHAINERMN_TPU_METRICS_PORT:-}" ] \
      && [ "${CHAINERMN_TPU_METRICS_PORT}" != "0" ]; then
    if timeout 30 python tools/metrics_dump.py --raw \
        > "tools/capture_logs/metrics_$stamp.prom" 2>/dev/null; then
      timeout 30 python tools/metrics_dump.py --health \
        > "tools/capture_logs/healthz_$stamp.json" 2>/dev/null
      echo "[capture] metrics snapshot: metrics_$stamp.prom + healthz"
    else
      rm -f "tools/capture_logs/metrics_$stamp.prom"
      echo "[capture] metrics endpoint down (port ${CHAINERMN_TPU_METRICS_PORT}) — skipped"
    fi
  fi
fi

if _fresh 'byte_audit_tf_2*.json' '"flops":' \
    && _fresh 'byte_audit_resnet_2*.json' '"flops":' \
    && _fresh 'byte_audit_decode_2*.json' '"attend_model"'; then
  echo "[capture] stage 1b: skipped (fresh audits exist)"
else
  echo "[capture] stage 1b: roofline byte audits (CPU-target: FLOPs are"
  echo "  backend-honest, and the TPU-target AOT compile wedged >900s"
  echo "  behind the remote-compile relay on 2026-08-01 — chip time goes"
  echo "  to the sweeps instead; a bounded TPU-target attempt runs last)"
  timeout 600 python tools/byte_audit.py transformer --remat dots --target cpu \
    > "tools/capture_logs/byte_audit_tf_$stamp.json" \
    2> "tools/capture_logs/byte_audit_tf_$stamp.log"
  echo "[capture] tf audit rc=$?"
  timeout 600 python tools/byte_audit.py resnet --remat none --target cpu \
    > "tools/capture_logs/byte_audit_resnet_$stamp.json" \
    2> "tools/capture_logs/byte_audit_resnet_$stamp.log"
  echo "[capture] resnet audit rc=$?"
  # ISSUE 19: the paged-decode roofline (structural attend models are
  # backend-independent; the measured impls re-run TPU-target in stage 5)
  timeout 600 python tools/byte_audit.py decode --target cpu \
    > "tools/capture_logs/byte_audit_decode_$stamp.json" \
    2> "tools/capture_logs/byte_audit_decode_$stamp.log"
  echo "[capture] decode audit rc=$?"
fi

if _fresh 'resnet_sweep_*.log' 'n_variants'; then
  echo "[capture] stage 2: skipped (fresh resnet sweep rows exist)"
else
  echo "[capture] stage 2: resnet sweep"
  timeout 2400 python examples/imagenet/sweep_mfu.py \
    > "tools/capture_logs/resnet_sweep_$stamp.log" 2>&1
  echo "[capture] resnet sweep rc=$?"; tail -2 "tools/capture_logs/resnet_sweep_$stamp.log"
fi

if _fresh 'transformer_sweep_*.log' 'n_variants'; then
  echo "[capture] stage 3: skipped (fresh transformer sweep rows exist)"
else
  echo "[capture] stage 3: transformer sweep (db=true grid, then one"
  echo "  db=false cost-probe: the db cost is MEASURED at LM scale but"
  echo "  never adopted into the headline — double-buffered allreduce"
  echo "  is part of the BASELINE workload identity)"
  timeout 2400 python examples/transformer/sweep_mfu.py \
    --remat dots,nothing --chunks 8,16 --blocks 512x1024 --batch 16,32 \
    --heads 16,8 --db true \
    > "tools/capture_logs/transformer_sweep_$stamp.log" 2>&1
  echo "[capture] transformer sweep rc=$?"; tail -2 "tools/capture_logs/transformer_sweep_$stamp.log"
  timeout 600 python examples/transformer/sweep_mfu.py \
    --remat dots --chunks 16 --blocks 512x1024 --batch 16 \
    --heads 16 --db false \
    >> "tools/capture_logs/transformer_sweep_$stamp.log" 2>&1
  echo "[capture] db-cost probe rc=$?"
fi

_newest_sweep() {  # newest COMPLETE sweep log (n_variants line), else
                   # newest row-bearing one (partial grid, labelled below)
  local f
  for f in $(ls -t tools/capture_logs/$1 2>/dev/null); do
    grep -q n_variants "$f" && { echo "$f"; return; }
  done
  ls -t tools/capture_logs/$1 2>/dev/null | head -1
}

if _fresh 'bench_best_*.log' '"source": "live"'; then
  echo "[capture] stage 4: skipped (fresh best-config bench exists)"
else
echo "[capture] stage 4: adopt winners -> fresh bench at best config"
# Stage 2/3 may have been skip-gated, so this stamp's files need not
# exist; prefer a COMPLETE grid over a newer partial one.
rs_log=$(_newest_sweep 'resnet_sweep_*.log')
tf_log=$(_newest_sweep 'transformer_sweep_*.log')
echo "[capture] winners from: ${rs_log:-none} ${tf_log:-none}"
knobs=$(python - "${rs_log:-/dev/null}" "${tf_log:-/dev/null}" <<'PYEOF'
import json, sys

def rows_of(path):
    out = []
    try:
        for line in open(path).read().splitlines():
            try:
                row = json.loads(line)
            except Exception:
                continue
            if "step_ms" in row:
                out.append(row)
    except OSError:
        pass
    return out

env = []
# Headline ResNet is the STANDARD stem: adopt the best standard row
# even when a space_to_depth variant is globally fastest.
std = [r for r in rows_of(sys.argv[1]) if r.get("stem") == "standard"]
if std:
    # Winner by THROUGHPUT: batch is part of the grid, and min(step_ms)
    # would just pick the smallest batch.
    rb = max(std, key=lambda r: r.get("images_per_sec", 0))
    env.append(f"CHAINERMN_BENCH_RESNET_REMAT={rb['remat']}")
    env.append(f"CHAINERMN_BENCH_RESNET_BATCH={rb['batch']}")
    # Adopt donate too: the sweep sweeps it, bench.py defaults it off —
    # without this the re-run can quietly disagree with the winner row.
    env.append(
        "CHAINERMN_BENCH_RESNET_DONATE="
        + ("true" if rb.get("donate", False) else "false"))
# Headline adoption only ever considers db=true rows: the db=false
# cost-probe row is evidence for the docs, not a candidate config —
# adopting it would silently flip the baseline's workload identity
# under an unchanged metric name.
tf_rows = [r for r in rows_of(sys.argv[2]) if r.get("db", True)]
if any("mfu" in r for r in tf_rows):
    tb = max(tf_rows, key=lambda r: r.get("mfu", 0))
elif tf_rows:
    tb = max(tf_rows, key=lambda r: r.get("tokens_per_sec", 0))
else:
    tb = None
if tb:
    env.append(f"CHAINERMN_BENCH_TF_REMAT={tb['remat']}")
    env.append(f"CHAINERMN_BENCH_TF_BATCH={tb['batch']}")
    env.append(f"CHAINERMN_BENCH_TF_CHUNKS={tb['n_chunks']}")
    if "heads" in tb:
        env.append(f"CHAINERMN_BENCH_TF_HEADS={tb['heads']}")

print(" ".join(env))
PYEOF
)
echo "[capture] adopted knobs: ${knobs:-none}"
if [ -n "${knobs:-}" ]; then
  env $knobs timeout 1800 python bench.py \
    > "tools/capture_logs/bench_best_$stamp.log" 2>&1
  echo "[capture] best-config bench rc=$?"
  tail -1 "tools/capture_logs/bench_best_$stamp.log" | cut -c1-400
fi
fi
if _fresh 'byte_audit_tf_tpu_*.json' '"flops":'; then
  echo "[capture] stage 5: skipped (fresh TPU-target audit exists)"
else
  echo "[capture] stage 5: bounded TPU-target byte audit (the on-chip"
  echo "  bytes-accessed number; progress trail shows the wedge phase if"
  echo "  the remote compile hangs again)"
  timeout 600 python tools/byte_audit.py transformer --remat dots \
    > "tools/capture_logs/byte_audit_tf_tpu_$stamp.json" \
    2> "tools/capture_logs/byte_audit_tf_tpu_$stamp.log"
  echo "[capture] tf tpu-audit rc=$? trail:"
  tail -2 "tools/capture_logs/byte_audit_tf_tpu_$stamp.log"
  # ISSUE 19: on-chip decode audit — the REAL fused bytes-accessed
  # number (the CPU run above measured the interpret emulator)
  timeout 600 python tools/byte_audit.py decode \
    > "tools/capture_logs/byte_audit_decode_tpu_$stamp.json" \
    2> "tools/capture_logs/byte_audit_decode_tpu_$stamp.log"
  echo "[capture] decode tpu-audit rc=$? trail:"
  tail -2 "tools/capture_logs/byte_audit_decode_tpu_$stamp.log"
fi
echo "[capture $stamp] done"
