#!/bin/bash
# One-command on-chip capture (round-4 VERDICT items 1+2+6+7): the moment
# the tunnelled TPU answers, grab — in priority order — the headline bench
# (fresh last_good_tpu + curve + kernel sweep), then the ResNet-50 MFU
# sweep, then the transformer MFU sweep; finally, if a sweep found a
# better config, re-run the bench with the winner's env knobs so the
# carried artifact holds the BEST honest numbers. Outputs in
# tools/capture_logs/.
set -u
cd "$(dirname "$0")/.."
mkdir -p tools/capture_logs
stamp=$(date -u +%Y%m%dT%H%M%SZ)

echo "[capture $stamp] stage 1: bench.py"
timeout 1800 python bench.py > "tools/capture_logs/bench_$stamp.log" 2>&1
echo "[capture] bench rc=$? last line:"; tail -1 "tools/capture_logs/bench_$stamp.log" | cut -c1-400

echo "[capture] stage 1b: roofline byte audits (AOT compile + analyses)"
timeout 900 python tools/byte_audit.py transformer --remat dots \
  > "tools/capture_logs/byte_audit_tf_$stamp.json" \
  2> "tools/capture_logs/byte_audit_tf_$stamp.log"
echo "[capture] tf audit rc=$?"
timeout 900 python tools/byte_audit.py resnet --remat none \
  > "tools/capture_logs/byte_audit_resnet_$stamp.json" \
  2> "tools/capture_logs/byte_audit_resnet_$stamp.log"
echo "[capture] resnet audit rc=$?"

echo "[capture] stage 2: resnet sweep"
timeout 2400 python examples/imagenet/sweep_mfu.py \
  > "tools/capture_logs/resnet_sweep_$stamp.log" 2>&1
echo "[capture] resnet sweep rc=$?"; tail -2 "tools/capture_logs/resnet_sweep_$stamp.log"

echo "[capture] stage 3: transformer sweep"
timeout 2400 python examples/transformer/sweep_mfu.py \
  --remat dots,nothing --chunks 16,32 --blocks 512x1024,512x512 --batch 16,32 \
  > "tools/capture_logs/transformer_sweep_$stamp.log" 2>&1
echo "[capture] transformer sweep rc=$?"; tail -2 "tools/capture_logs/transformer_sweep_$stamp.log"

echo "[capture] stage 4: adopt winners -> fresh bench at best config"
knobs=$(python - "tools/capture_logs/resnet_sweep_$stamp.log" \
               "tools/capture_logs/transformer_sweep_$stamp.log" <<'PYEOF'
import json, sys

def rows_of(path):
    out = []
    try:
        for line in open(path).read().splitlines():
            try:
                row = json.loads(line)
            except Exception:
                continue
            if "step_ms" in row:
                out.append(row)
    except OSError:
        pass
    return out

env = []
# Headline ResNet is the STANDARD stem: adopt the best standard row
# even when a space_to_depth variant is globally fastest.
std = [r for r in rows_of(sys.argv[1]) if r.get("stem") == "standard"]
if std:
    rb = min(std, key=lambda r: r["step_ms"])
    env.append(f"CHAINERMN_BENCH_RESNET_REMAT={rb['remat']}")
    env.append(f"CHAINERMN_BENCH_RESNET_BATCH={rb['batch']}")
    # Adopt donate too: the sweep sweeps it, bench.py defaults it off —
    # without this the re-run can quietly disagree with the winner row.
    env.append(
        "CHAINERMN_BENCH_RESNET_DONATE="
        + ("true" if rb.get("donate", False) else "false"))
tf_rows = rows_of(sys.argv[2])
tb = min(tf_rows, key=lambda r: r["step_ms"]) if tf_rows else None
if tb:
    env.append(f"CHAINERMN_BENCH_TF_REMAT={tb['remat']}")
    env.append(f"CHAINERMN_BENCH_TF_BATCH={tb['batch']}")
    env.append(f"CHAINERMN_BENCH_TF_CHUNKS={tb['n_chunks']}")
print(" ".join(env))
PYEOF
)
echo "[capture] adopted knobs: ${knobs:-none}"
if [ -n "${knobs:-}" ]; then
  env $knobs timeout 1800 python bench.py \
    > "tools/capture_logs/bench_best_$stamp.log" 2>&1
  echo "[capture] best-config bench rc=$?"
  tail -1 "tools/capture_logs/bench_best_$stamp.log" | cut -c1-400
fi
echo "[capture $stamp] done"
