#!/bin/bash
# One-command on-chip capture (round-4 VERDICT items 1+2+6+7): the moment
# the tunnelled TPU answers, grab — in priority order — the headline bench
# (fresh last_good_tpu + curve + kernel sweep), then the ResNet-50 MFU
# sweep, then the transformer MFU sweep. Each stage bounded; outputs to
# tools/capture_logs/.
set -u
cd "$(dirname "$0")/.."
mkdir -p tools/capture_logs
stamp=$(date -u +%Y%m%dT%H%M%SZ)

echo "[capture $stamp] stage 1: bench.py" 
timeout 1800 python bench.py > "tools/capture_logs/bench_$stamp.log" 2>&1
echo "[capture] bench rc=$? last line:"; tail -1 "tools/capture_logs/bench_$stamp.log" | cut -c1-400

echo "[capture] stage 2: resnet sweep"
timeout 2400 python examples/imagenet/sweep_mfu.py \
  > "tools/capture_logs/resnet_sweep_$stamp.log" 2>&1
echo "[capture] resnet sweep rc=$?"; tail -2 "tools/capture_logs/resnet_sweep_$stamp.log"

echo "[capture] stage 3: transformer sweep"
timeout 2400 python examples/transformer/sweep_mfu.py \
  --remat dots,nothing --chunks 16,32 --blocks 512x1024,512x512 --batch 16,32 \
  > "tools/capture_logs/transformer_sweep_$stamp.log" 2>&1
echo "[capture] transformer sweep rc=$?"; tail -2 "tools/capture_logs/transformer_sweep_$stamp.log"
echo "[capture $stamp] done"
