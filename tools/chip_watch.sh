#!/bin/bash
# Round-long chip pursuit (round-5 VERDICT ask #1): poll the relay
# endpoint cheaply (2 s TCP check — no JAX import, no hang) and the
# moment it answers, run the full on-chip capture. Every poll leaves a
# record in tools/capture_logs/probes.jsonl, so even an all-failed round
# ships a diagnosis trail instead of silence.
#
# Usage: tools/chip_watch.sh [interval_s] [max_hours]
set -u
cd "$(dirname "$0")/.."
interval=${1:-120}
max_hours=${2:-11}
deadline=$(( $(date +%s) + max_hours * 3600 ))
mkdir -p tools/capture_logs
log=tools/capture_logs/watch.log
echo "[watch $(date -u +%H:%M:%S)] start: interval=${interval}s max=${max_hours}h" >> "$log"
captures=0
while [ "$(date +%s)" -lt "$deadline" ]; do
  python tools/probe_tpu.py 180 > /dev/null 2>&1
  rc=$?
  if [ "$rc" -eq 0 ]; then
    echo "[watch $(date -u +%H:%M:%S)] CHIP UP — launching capture" >> "$log"
    bash tools/on_chip_capture.sh >> "$log" 2>&1
    captures=$((captures + 1))
    echo "[watch $(date -u +%H:%M:%S)] capture #$captures done" >> "$log"
    # One full capture is the round's goal; keep a slow heartbeat after
    # so a later flap is still recorded, but don't re-run the capture.
    interval=1800
  else
    echo "[watch $(date -u +%H:%M:%S)] probe rc=$rc" >> "$log"
  fi
  sleep "$interval"
done
echo "[watch $(date -u +%H:%M:%S)] deadline reached (captures=$captures)" >> "$log"
