#!/bin/bash
# Round-long chip pursuit (round-5 VERDICT ask #1): poll the relay
# endpoint cheaply (2 s TCP check — no JAX import, no hang) and the
# moment it answers, run the full on-chip capture. Every poll leaves a
# record in tools/capture_logs/probes.jsonl, so even an all-failed round
# ships a diagnosis trail instead of silence.
#
# Usage: tools/chip_watch.sh [interval_s] [max_hours]
set -u
cd "$(dirname "$0")/.."
interval=${1:-120}
max_hours=${2:-11}
deadline=$(( $(date +%s) + max_hours * 3600 ))
mkdir -p tools/capture_logs
log=tools/capture_logs/watch.log
# Freshness marker: capture_logs is git-tracked and accumulates
# artifacts ACROSS rounds, so "sweep rows exist" must mean "landed
# since THIS watch started" — a stale log from a previous round
# otherwise silently disables the round's whole capture.
marker="tools/capture_logs/.watch_start"
# Persist across watcher RESTARTS within a round: re-touching on every
# start would mark the round's already-landed artifacts stale and re-run
# completed 30-min stages. The marker is untracked, so a fresh checkout
# (next round) starts clean. The capture-attempt COUNTER persists beside
# it for the same reason: an in-process-only count let a restart-looping
# watcher exceed the per-round cap (ADVICE r5) — a fresh marker resets
# the counter, a surviving marker keeps the round's running total.
counter="tools/capture_logs/.watch_captures"
[ -e "$marker" ] || { touch "$marker"; echo 0 > "$counter"; }
. tools/capture_lib.sh
echo "[watch $(date -u +%H:%M:%S)] start: interval=${interval}s max=${max_hours}h" >> "$log"
captures=$(cat "$counter" 2>/dev/null || echo 0)
case "$captures" in
  ''|*[!0-9]*) captures=0 ;;  # missing/garbled counter file
esac
max_captures=6
while [ "$(date +%s)" -lt "$deadline" ]; do
  python tools/probe_tpu.py 180 > /dev/null 2>&1
  rc=$?
  # Live-telemetry heartbeat (ISSUE 6): when a metrics endpoint is
  # exported, append one /healthz line per poll — a stalled run's
  # last_beat_age then shows up in the watch trail even if the capture
  # never fires. Quiet + cheap: 2 s fetch timeout, failures dropped.
  if [ -n "${CHAINERMN_TPU_METRICS_PORT:-}" ] \
      && [ "${CHAINERMN_TPU_METRICS_PORT}" != "0" ]; then
    timeout 15 python tools/metrics_dump.py --health \
      >> tools/capture_logs/healthz_watch.jsonl 2>/dev/null || true
  fi
  if [ "$rc" -eq 0 ]; then
    # A capture is COMPLETE once a LIVE bench and BOTH sweeps have
    # landed in THIS watch run (the 2026-08-01 wedge: stage 1 landed,
    # then the relay's compile leg died mid-stage-2 — a one-shot policy
    # would have left the sweeps unrun for the rest of the round;
    # checking only one stage, or counting a previous round's logs,
    # re-creates the same silent failure). Re-fire on chip-up until
    # complete — the capture script skips stages whose artifacts are
    # already fresh (same marker), so a re-fire redoes only what
    # failed. The stage-5 TPU byte audit is deliberately NOT part of
    # completeness: it is known to wedge behind the relay, and holding
    # the heartbeat hostage to it would spend every chip-up window on a
    # 600 s timeout. Cap the re-fires so a persistently failing stage
    # can't eat the round.
    if fresh_artifact 'bench_2*.log' '"source": "live"' "$marker" \
        && fresh_artifact 'resnet_sweep_*.log' n_variants "$marker" \
        && fresh_artifact 'transformer_sweep_*.log' n_variants "$marker"; then
      echo "[watch $(date -u +%H:%M:%S)] chip up; capture complete (live bench + both sweeps) — heartbeat" >> "$log"
      interval=1800
    elif [ "$captures" -ge "$max_captures" ]; then
      echo "[watch $(date -u +%H:%M:%S)] chip up; capture INCOMPLETE but re-fire cap ($max_captures) reached — heartbeat" >> "$log"
      interval=1800
    else
      echo "[watch $(date -u +%H:%M:%S)] CHIP UP — launching capture (attempt $((captures + 1)))" >> "$log"
      # Persist the attempt BEFORE launching: a watcher killed
      # mid-capture and restarted must still count it against the cap.
      captures=$((captures + 1))
      echo "$captures" > "$counter"
      CAPTURE_SINCE="$marker" bash tools/on_chip_capture.sh >> "$log" 2>&1
      echo "[watch $(date -u +%H:%M:%S)] capture #$captures done" >> "$log"
    fi
  else
    echo "[watch $(date -u +%H:%M:%S)] probe rc=$rc" >> "$log"
  fi
  sleep "$interval"
done
echo "[watch $(date -u +%H:%M:%S)] deadline reached (captures=$captures)" >> "$log"
