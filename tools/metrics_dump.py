#!/usr/bin/env python
"""Snapshot/format a live chainermn_tpu metrics endpoint (ISSUE 6).

Usage::

    python tools/metrics_dump.py                 # scrape + format table
    python tools/metrics_dump.py --port 9100     # explicit port
    python tools/metrics_dump.py --ports 9100,9101,9102  # replica merge
    python tools/metrics_dump.py --raw           # verbatim exposition
    python tools/metrics_dump.py --json          # parsed, one JSON line
    python tools/metrics_dump.py --health        # /healthz, one JSON line
    python tools/metrics_dump.py saved.prom      # format a saved scrape
    python tools/metrics_dump.py --label tenant=acme   # one tenant only

``--ports a,b,c`` (ISSUE 8) fetches several replica endpoints and
merges them into ONE labeled table/JSON object — every series gains a
``port="<p>"`` label, so a cluster run (one exporter per replica
process, the ``N + rank`` port contract) is inspectable with one
command. Endpoints that don't answer are reported on stderr and
skipped; the exit code is 1 only when NONE answered. With ``--health``
it returns ``{port: healthz-or-error}`` as one JSON line instead.

``--label key=value`` (ISSUE 14) filters the parsed table/JSON to the
series carrying that label — ``--label tenant=<id>`` narrows a
multi-tenant endpoint (or saved scrape, or ``--ports`` merge) to one
tenant's gauges/counters/histograms. A filter that matches NOTHING
exits 1 with a stderr note (a typoed tenant id must be loud, not an
empty table); ``--raw``/``--health`` pass unparsed payloads through
and refuse the combination.

The port defaults to ``CHAINERMN_TPU_METRICS_PORT`` (the exporter's env
contract; per-rank endpoints live at port+rank — pass ``--port``
explicitly for a non-zero rank). Exit code 1 when the endpoint is
unreachable — the capture scripts lean on that to make a down endpoint
cost nothing.

Like ``tools/trace_report.py``, the metrics module is loaded by FILE
PATH: one owner of the exposition parser, without paying for
``import chainermn_tpu`` (which pulls jax) in a snapshot tool.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import urllib.error
import urllib.request

_HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _metrics_mod():
    import importlib.util

    path = os.path.join(
        _HERE, "chainermn_tpu", "observability", "metrics.py"
    )
    spec = importlib.util.spec_from_file_location("_obs_metrics", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _fetch(url: str, timeout: float) -> str:
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.read().decode()


def render_table(parsed: dict) -> str:
    """Parsed exposition -> human table: histograms collapse to
    count/sum per label set (the quantiles live server-side in the
    snapshot; the exposition carries buckets), everything else one row
    per series, sorted."""
    lines = []
    hist: dict = {}
    plain: list = []
    for (name, labels), value in sorted(parsed.items()):
        base = None
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix):
                base = (name[: -len(suffix)], suffix)
                break
        if base is not None:
            root, suffix = base
            key_labels = tuple(kv for kv in labels if kv[0] != "le")
            row = hist.setdefault((root, key_labels),
                                  {"count": 0, "sum": 0.0})
            if suffix == "_count":
                row["count"] = int(value)
            elif suffix == "_sum":
                row["sum"] = value
        else:
            plain.append((name, labels, value))
    for name, labels, value in plain:
        lab = ",".join(f"{k}={v}" for k, v in labels)
        lines.append(f"{name:<34} {lab:<40} {value:g}")
    for (root, labels), row in sorted(hist.items()):
        lab = ",".join(f"{k}={v}" for k, v in labels)
        mean = row["sum"] / row["count"] * 1e3 if row["count"] else 0.0
        lines.append(
            f"{root:<34} {lab:<40} n={row['count']} "
            f"mean={mean:.3f} ms"
        )
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Snapshot/format a live chainermn_tpu /metrics "
                    "endpoint"
    )
    ap.add_argument("file", nargs="?",
                    help="saved exposition file to format offline "
                         "(skips the HTTP fetch)")
    ap.add_argument("--port", type=int, default=None,
                    help="endpoint port (default: "
                         "$CHAINERMN_TPU_METRICS_PORT)")
    ap.add_argument("--ports", default=None,
                    help="comma-separated replica ports to fetch and "
                         "merge into one port-labeled table")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--timeout", type=float, default=2.0)
    ap.add_argument("--raw", action="store_true",
                    help="print the exposition verbatim")
    ap.add_argument("--json", action="store_true",
                    help="parsed series as one JSON object")
    ap.add_argument("--health", action="store_true",
                    help="fetch /healthz instead of /metrics")
    ap.add_argument("--label", default=None, metavar="KEY=VALUE",
                    help="keep only series carrying this label (e.g. "
                         "tenant=acme); exits 1 when nothing matches")
    args = ap.parse_args(argv)

    label_filter = None
    if args.label is not None:
        if args.raw or args.health:
            print("metrics_dump: --label filters PARSED series — it "
                  "cannot combine with --raw/--health", file=sys.stderr)
            return 1
        key, sep, value = args.label.partition("=")
        if not sep or not key:
            print(f"metrics_dump: bad --label {args.label!r} "
                  "(want key=value)", file=sys.stderr)
            return 1
        label_filter = (key, value)

    def apply_label(parsed: dict) -> dict | None:
        """Filter parsed series by --label; None (after a stderr note)
        when nothing survives."""
        if label_filter is None:
            return parsed
        out = {
            (name, labels): v for (name, labels), v in parsed.items()
            if label_filter in labels
        }
        if not out:
            print(
                f"metrics_dump: no series carry "
                f"{label_filter[0]}={label_filter[1]!r}",
                file=sys.stderr,
            )
            return None
        return out

    if args.ports:
        try:
            ports = [int(p) for p in args.ports.split(",") if p.strip()]
        except ValueError:
            print(f"metrics_dump: bad --ports {args.ports!r}",
                  file=sys.stderr)
            return 1
        if not ports:
            print("metrics_dump: --ports named no ports", file=sys.stderr)
            return 1
        path = "/healthz" if args.health else "/metrics"
        texts: dict = {}
        for p in ports:
            url = f"http://{args.host}:{p}{path}"
            try:
                texts[p] = _fetch(url, args.timeout)
            except (urllib.error.URLError, OSError, ValueError) as e:
                print(f"metrics_dump: {url} unreachable: {e}",
                      file=sys.stderr)
        if not texts:
            print("metrics_dump: no replica endpoint answered",
                  file=sys.stderr)
            return 1
        if args.health:
            merged_h = {}
            for p in ports:
                if p in texts:
                    try:
                        merged_h[str(p)] = json.loads(texts[p])
                    except json.JSONDecodeError:
                        merged_h[str(p)] = {"error": "bad json"}
                else:
                    merged_h[str(p)] = {"error": "unreachable"}
            print(json.dumps(merged_h, sort_keys=True))
            return 0
        if args.raw:
            for p, text in sorted(texts.items()):
                sys.stdout.write(f"# replica port {p}\n{text}")
            return 0
        mod = _metrics_mod()
        merged: dict = {}
        for p, text in sorted(texts.items()):
            for (name, labels), v in mod.parse_exposition(text).items():
                merged[(name, tuple(sorted(
                    labels + (("port", str(p)),))))] = v
        merged = apply_label(merged)
        if merged is None:
            return 1
        if args.json:
            print(json.dumps(
                {f"{name}{dict(labels) or ''}": v
                 for (name, labels), v in sorted(merged.items())},
                sort_keys=True, default=str,
            ))
        else:
            print(render_table(merged))
        return 0

    if args.file:
        try:
            text = open(args.file).read()
        except OSError as e:
            print(f"metrics_dump: {e}", file=sys.stderr)
            return 1
    else:
        port = args.port
        if port is None:
            v = os.environ.get("CHAINERMN_TPU_METRICS_PORT")
            if not v:
                print("metrics_dump: no --port and "
                      "CHAINERMN_TPU_METRICS_PORT unset", file=sys.stderr)
                return 1
            try:
                port = int(v)
            except ValueError:
                print(f"metrics_dump: bad port {v!r}", file=sys.stderr)
                return 1
        path = "/healthz" if args.health else "/metrics"
        url = f"http://{args.host}:{port}{path}"
        try:
            text = _fetch(url, args.timeout)
        except (urllib.error.URLError, OSError, ValueError) as e:
            print(f"metrics_dump: {url} unreachable: {e}",
                  file=sys.stderr)
            return 1

    if args.health:
        # already JSON from the endpoint; normalise to one line
        try:
            print(json.dumps(json.loads(text), sort_keys=True))
        except json.JSONDecodeError:
            print(text.strip())
        return 0
    if args.raw:
        sys.stdout.write(text)
        return 0
    parsed = _metrics_mod().parse_exposition(text)
    parsed = apply_label(parsed)
    if parsed is None:
        return 1
    if args.json:
        print(json.dumps(
            {f"{name}{dict(labels) or ''}": v
             for (name, labels), v in sorted(parsed.items())},
            sort_keys=True, default=str,
        ))
    else:
        print(render_table(parsed))
    return 0


if __name__ == "__main__":
    sys.exit(main())
