"""Shared CPU-backend environment scrub for the standalone drivers
(``bench.py``, ``__graft_entry__.py``).

Round-1 lesson (VERDICT.md): externally injected accelerator plugin shims
register themselves via PYTHONPATH, ignore ``JAX_PLATFORMS=cpu``, and can
hang JAX backend init when their tunnel is dead. Subprocesses that must
only ever see the CPU backend get this environment; keeping the scrub in
one place keeps both drivers in lockstep.
"""

from __future__ import annotations

import os

_PLUGIN_ENV_VARS = ("JAX_PLATFORM_NAME", "TPU_LIBRARY_PATH", "PJRT_DEVICE")


def cpu_scrubbed_env(n_devices: int = 8, cache_dir: str | None = None) -> dict:
    env = dict(os.environ)
    env["PYTHONPATH"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={n_devices}"
    for k in _PLUGIN_ENV_VARS:
        env.pop(k, None)
    if cache_dir:
        # Persistent compilation cache: repeat driver invocations skip the
        # CPU-mesh XLA compiles that dominate wall time.
        env.setdefault("JAX_COMPILATION_CACHE_DIR", cache_dir)
        env.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")
        env.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "0")
    return env
