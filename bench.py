"""Benchmark driver: ResNet-50 data-parallel training throughput.

Prints ONE JSON line:
``{"metric": ..., "value": N, "unit": "images/sec", "vs_baseline": N, ...}``
with supplementary fields: ``mfu`` (model-FLOPs utilisation against the
chip's bf16 peak), ``allreduce_gbps`` (the reference's second tracked
metric, BASELINE.json / SURVEY.md section 6: achieved bytes/s of a jitted
gradient-buffer allreduce), ``device_kind``, ``n_devices``, and ``error``
when a fallback path was taken.

The primary benchmark is the reference's headline workload (ResNet-50
ImageNet, ``examples/imagenet`` (dagger), SURVEY.md section 6): one fully
jitted SPMD train step — forward, backward, bf16-compressed gradient
allreduce over the mesh, SGD update — on synthetic 224x224 data, i.e. the
same measurement the reference's images/sec numbers report (data pipeline
excluded).

Robustness contract (round-1 lesson, VERDICT.md): this process never
imports jax itself. Backend acquisition happens in bounded subprocesses —
a TPU probe with a timeout, then the real bench; on any failure it reruns
on a scrubbed-environment CPU backend; a JSON line is ALWAYS emitted and
the exit code is always 0.

Baseline: ``BASELINE.json`` has ``"published": {}`` (the reference repo's
own numbers were unreadable — empty mount), so ``vs_baseline`` compares
per-device throughput against the best documented ChainerMN-era
per-accelerator figure: the 15-minute ImageNet run (Akiba, Suzuki & Fukuda,
arXiv:1711.04325 — 90 epochs, 1024 P100s) ~= 125 images/sec/P100.
UNVERIFIED external figure and different hardware — ``mfu`` is the
hardware-honest number; see BASELINE.md.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

_HERE = os.path.dirname(os.path.abspath(__file__))
# The staged TPU prober (tools/probe_tpu.py) is imported by the probe
# helpers; one appended path entry, not one per retry attempt.
_TOOLS_DIR = os.path.join(_HERE, "tools")
if _TOOLS_DIR not in sys.path:
    sys.path.append(_TOOLS_DIR)

BASELINE_IMG_PER_SEC_PER_DEVICE = 125.0

# Peak bf16 FLOPs/s per chip by device_kind substring (public figures).
_PEAK_BF16_FLOPS = {
    "v2": 46e12,
    "v3": 123e12,
    "v4": 275e12,
    "v5 lite": 197e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "v5": 459e12,  # after the lite variants; substring order matters
    "v6 lite": 918e12,
    "v6e": 918e12,
}

# HBM bytes/s per chip, SAME keys and ordering rule as the flops table
# (public spec sheets). Kept adjacent so a new device kind is added to
# both in one place — tools/byte_audit.py derives its roofline floors
# from these via _peak_lookup.
_PEAK_HBM_BYTES = {
    "v2": 700e9,
    "v3": 900e9,
    "v4": 1228e9,
    "v5 lite": 819e9,
    "v5e": 819e9,
    "v5p": 2765e9,
    "v5": 2765e9,
    "v6 lite": 1640e9,
    "v6e": 1640e9,
}

# Env-tunable so the probe schedule can be compressed when driving the
# orchestration in tests (the defaults fit the driver's real budget).
PROBE_TIMEOUT = int(os.environ.get("CHAINERMN_BENCH_PROBE_TIMEOUT", 120))
TOTAL_BUDGET = int(os.environ.get("CHAINERMN_BENCH_BUDGET", 1500))
PROBE_RETRY_SLEEP = int(os.environ.get("CHAINERMN_BENCH_PROBE_SLEEP", 45))
PROBE_RETRIES = int(os.environ.get("CHAINERMN_BENCH_PROBE_RETRIES", 5))
CPU_BENCH_RESERVE = 330  # budget to keep for the CPU fallback + margin
# What the FULL CPU fallback actually needs (primary + supplementary
# phases, ~8-10 min measured on this contended 1-core box) + the
# parent's 180 s margin. The probe window is capped so this much budget
# survives probing — the single constant both the window cap and the
# probe give-up guard derive from.
CPU_FALLBACK_NEED = int(os.environ.get("CHAINERMN_BENCH_CPU_NEED", 630))


def _cpu_env(n_devices: int = 8) -> dict:
    """Environment that can only ever see the CPU backend (see
    ``_driver_env.cpu_scrubbed_env``)."""
    from _driver_env import cpu_scrubbed_env

    return cpu_scrubbed_env(
        n_devices, cache_dir=os.path.join(_HERE, ".jax_cache")
    )


def _probe_accelerator(timeout: float):
    """Return {'platform','kind','n'} or None, never raising.

    Staged (round-5 VERDICT ask #1 — diagnose, don't endure): a 2 s TCP
    check of the tunnel's relay endpoints FIRST — when the tunnel is
    down they refuse instantly, while a jax.devices() probe would hang
    for its whole timeout inside PJRT's gRPC retry loop. The full
    backend-init probe runs only past a live endpoint. Every attempt —
    failed ones especially — appends a diagnosis record to
    ``tools/capture_logs/probes.jsonl`` (env fingerprint, per-stage
    elapsed, which init step wedged), folded into BENCH_DETAILS.json at
    emit time. If the staged prober is unimportable (file missing in a
    partial checkout) this falls back to the plain subprocess probe
    rather than silently reporting 'no accelerator'."""
    try:
        from probe_tpu import probe
    except ImportError:
        return _probe_accelerator_plain(timeout)
    try:
        rec = probe(timeout)
        if rec["verdict"] != "chip_up":
            return None
        info = {k: rec["init"][k] for k in ("platform", "kind", "n")}
        return None if info["platform"] == "cpu" else info
    except (KeyError, TypeError, ValueError):
        # Diagnosis record malformed: trust the plain probe instead of
        # converting a live chip into a CPU fallback.
        return _probe_accelerator_plain(timeout)


def _probe_accelerator_plain(timeout: float):
    """The pre-diagnostic probe: subprocess jax.devices(), no staging."""
    code = (
        "import jax, json; ds = jax.devices(); "
        "print(json.dumps({'platform': ds[0].platform, "
        "'kind': ds[0].device_kind, 'n': len(ds)}))"
    )
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, text=True, timeout=timeout, cwd=_HERE,
        )
        if proc.returncode != 0:
            return None
        info = json.loads(proc.stdout.strip().splitlines()[-1])
        return None if info["platform"] == "cpu" else info
    except Exception:
        return None


def _last_json_line(text) -> dict | None:
    """Parse the last JSON object line from child stdout (bytes or str)."""
    if isinstance(text, bytes):
        text = text.decode(errors="replace")
    for line in reversed((text or "").splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    return None


def _run_child(mode: str, timeout: float, env=None):
    """Run ``bench.py --run <mode>``; return its parsed JSON line or an
    error string."""
    try:
        proc = subprocess.run(
            [sys.executable, os.path.join(_HERE, "bench.py"), "--run", mode],
            env=env, cwd=_HERE, capture_output=True, text=True,
            timeout=timeout,
        )
    except subprocess.TimeoutExpired as e:
        # The child prints the primary JSON line BEFORE the slower
        # supplementary benchmarks — salvage it from the partial output.
        result = _last_json_line(e.stdout)
        if result is not None:
            result["bench_note"] = (
                f"child timed out after {timeout:.0f}s; "
                "supplementary metrics missing"
            )
            return result, None
        return None, f"{mode} bench timed out after {timeout:.0f}s"
    if proc.returncode != 0:
        tail = (proc.stderr or proc.stdout or "")[-800:]
        return None, f"{mode} bench rc={proc.returncode}: {tail}"
    result = _last_json_line(proc.stdout)
    if result is not None:
        return result, None
    return None, f"{mode} bench emitted no JSON line"


_LAST_TPU_CACHE = os.path.join(_HERE, ".bench_last_tpu.json")

# Observability trace (ISSUE 2): every bench child appends structured
# wire/phase events here; tools/trace_report.py summarizes it. The
# capture script points CHAINERMN_TPU_TRACE at a per-stamp file in
# tools/capture_logs/ instead.
_TRACE_PATH = os.environ.get(
    "CHAINERMN_TPU_TRACE", os.path.join(_HERE, "BENCH_TRACE.jsonl")
)


def _truncate_trace() -> None:
    """Start each DRIVER run with a fresh trace (children append within
    the run — accel child, cpu fallback, native-loop children all land
    in one file). Creates the directory like the child Recorders do: a
    missing parent dir must not silently skip the truncation while the
    children go on appending to a stale file."""
    try:
        parent = os.path.dirname(os.path.abspath(_TRACE_PATH))
        os.makedirs(parent, exist_ok=True)
        open(_TRACE_PATH, "w").close()
    except OSError:
        pass


_CACHE_META_KEYS = (
    "measured_at", "carried_keys", "row_provenance", "source", "stale",
    "age_hours", "bench_note", "error",
)

# Keys whose methodology was repudiated: never carried forward from a
# cached blob. transformer_hw_util was always meaningless (XLA
# cost_analysis doesn't multiply scan trip counts — r3). The native-input
# rows keep their names under the new differenced-fresh-process method;
# cached values from the old per-step-sync method (identifiable by the
# absence of the native_input_method marker) measured the tunnel
# pathology, not the pipeline, and must not be resurrected.
_ALWAYS_RETIRED_KEYS = ("transformer_hw_util",)
_OLD_METHOD_NATIVE_KEYS = (
    "native_input_images_per_sec",
    "synthetic_images_per_sec",
    "input_pipeline_overhead_pct",
)
# r5: long-context rows moved to the chained-scan method (the
# single-dispatch numbers measured kernel + tunnel dispatch latency and
# masked the banded-grid win); cached single-dispatch values
# (identifiable by the absent flash_32k_method marker) must not be
# carried under the new row names. xla_32k_error stays — the OOM
# classification is method-independent.
_OLD_METHOD_32K_KEYS = (
    "flash_32k_fwd_ms",
    "flash_32k_window2k_fwd_ms",
    "xla_32k_fwd_ms",
)


def _purge_retired(old: dict) -> None:
    for k in _ALWAYS_RETIRED_KEYS:
        old.pop(k, None)
    if "native_input_method" not in old:
        for k in _OLD_METHOD_NATIVE_KEYS:
            old.pop(k, None)
    if "flash_32k_method" not in old:
        for k in _OLD_METHOD_32K_KEYS:
            old.pop(k, None)
    # provenance rows must not outlive the data rows they describe
    prov = old.get("row_provenance")
    if isinstance(prov, dict):
        for k in [k for k in prov if k not in old]:
            prov.pop(k)


def _save_last_tpu(result: dict) -> None:
    """Merge ``result`` over the previous cached on-chip blob.

    A live run that TIMES OUT mid-way salvages only its earlier rows; a
    plain overwrite would silently drop supplementary rows (transformer
    MFU, s2d, …) a previous fuller run had measured (observed r3). Rows
    the new run didn't produce are kept and listed in ``carried_keys``
    with their own measured_at, so provenance stays honest per row."""
    try:
        try:
            with open(_LAST_TPU_CACHE) as f:
                old = json.load(f)
        except (OSError, json.JSONDecodeError):
            old = {}
        _purge_retired(old)
        same_device = (
            old.get("device_kind") == result.get("device_kind")
            or "device_kind" not in old
        )
        # Device-relative rows (mfu, tokens/s) from a DIFFERENT chip must
        # not be carried under this chip's identity.
        kept = {
            k: v for k, v in old.items()
            if same_device
            and k not in result and k not in _CACHE_META_KEYS
        }
        cached = dict(kept)
        cached.update(result)
        cached.pop("carried_keys", None)
        stamp = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
        if kept:
            # rows inherited from an older run, with that run's timestamp
            prev = old.get("carried_keys", {})
            stamps = dict(prev.get("stamps", {}))
            old_stamp = old.get("measured_at")
            for k in kept:
                stamps.setdefault(k, old_stamp)
            cached["carried_keys"] = {
                "keys": sorted(kept),
                "stamps": {k: stamps.get(k) for k in kept},
            }
        # Per-ROW provenance (round-5 VERDICT ask #7): every row names
        # when it was measured and whether THIS save produced it live or
        # inherited it — a stale overlay can never read as a fresh
        # capture even row by row. Rows already carried keep their
        # original stamp.
        prev_prov = old.get("row_provenance", {})
        prev_ck_stamps = (old.get("carried_keys") or {}).get("stamps", {})
        prov = {}
        for k in kept:
            p = prev_prov.get(k) if isinstance(prev_prov, dict) else None
            # Stamp priority: the row's own provenance, then the OLD
            # blob's per-row carried_keys stamp (a pre-provenance blob
            # may already have inherited this row from an even older
            # run), then the blob-level stamp — never newer than the
            # row's true measurement.
            stamp_k = (
                (p or {}).get("measured_at")
                or prev_ck_stamps.get(k)
                or old.get("measured_at")
            )
            prov[k] = {"measured_at": stamp_k, "source": "carried"}
        for k in result:
            if k not in _CACHE_META_KEYS:
                prov[k] = {"measured_at": stamp, "source": "live"}
        cached["row_provenance"] = prov
        cached["measured_at"] = stamp
        with open(_LAST_TPU_CACHE, "w") as f:
            json.dump(cached, f)
    except OSError:
        pass


def _attach_last_tpu(result: dict) -> None:
    """On a CPU fallback, attach the most recent SUCCESSFUL on-chip result
    so a transiently dead accelerator tunnel doesn't erase real measured
    capability. The carried blob is loudly marked — ``source: "carry"``,
    ``stale: true``, and its age — so no consumer can mistake stale
    capability for a current measurement. The top-level fields still
    describe THIS run honestly."""
    try:
        with open(_LAST_TPU_CACHE) as f:
            carried = json.load(f)
    except (OSError, json.JSONDecodeError):
        return
    _purge_retired(carried)
    carried["source"] = "carry"
    carried["stale"] = True
    try:
        import calendar

        measured = calendar.timegm(
            time.strptime(carried["measured_at"], "%Y-%m-%dT%H:%M:%SZ")
        )
        carried["age_hours"] = round((time.time() - measured) / 3600, 1)
    except (KeyError, ValueError, OverflowError):
        pass
    result["last_good_tpu"] = carried


def _probe_with_retries(deadline: float, errors: list) -> dict | None:
    """Probe the accelerator repeatedly with backoff (round-2 lesson: the
    tunnelled TPU flaps — a single-shot probe lost two rounds' live
    numbers). Keeps trying while enough budget remains for an accel bench
    plus the CPU fallback reserve."""
    # Wall-clock window, not an attempt count: the staged probe fails in
    # ~2 s when the tunnel is down (TCP refusal), so a fixed attempt
    # count would concede the chip in ~3 min where the old hanging probe
    # spent ~13 — and the round-2 lesson is that the tunnel flaps on
    # minutes timescales. Keep probing for the window the old schedule
    # implied — but always leave CPU_FALLBACK_NEED (+ the parent's
    # 180 s margin) for the CPU fallback, so it is not squeezed into
    # its timeout-salvage path.
    window = max(60, min(PROBE_RETRIES * (PROBE_TIMEOUT + PROBE_RETRY_SLEEP),
                         TOTAL_BUDGET - CPU_FALLBACK_NEED - 180))
    probe_deadline = time.monotonic() + window
    attempt = 0
    while True:
        attempt += 1
        remaining = deadline - time.monotonic()
        if remaining < CPU_FALLBACK_NEED + 60:
            errors.append(
                f"accelerator probe gave up after {attempt - 1} attempts "
                "(budget exhausted)"
            )
            return None
        accel = _probe_accelerator(min(PROBE_TIMEOUT, remaining - CPU_BENCH_RESERVE))
        if accel is not None:
            if attempt > 1:
                errors.append(
                    f"accelerator probe succeeded on attempt {attempt}"
                )
            return accel
        if time.monotonic() >= probe_deadline:
            diag = _latest_probe_diagnosis()
            errors.append(
                f"accelerator probe failed {attempt} times over "
                f"~{window // 60} min"
                + (f" — {diag}" if diag else " (backend init dead or hung)")
            )
            return None
        time.sleep(PROBE_RETRY_SLEEP)


def _latest_probe_diagnosis() -> str | None:
    """Short diagnosis string from the newest probes.jsonl record."""
    try:
        from probe_tpu import latest_record

        rec = latest_record()
        if rec:
            return f"{rec['verdict']}: {rec.get('diagnosis', '')}"[:200]
    except Exception:
        pass
    return None


def _attach_probe_trail(result: dict, n: int = 8) -> None:
    """Fold the newest probe-diagnosis records into the result so a
    failed round still ships evidence of WHAT each probe saw."""
    try:
        from probe_tpu import tail_records

        trail = tail_records(n)
        if trail:
            result["probe_trail"] = trail
    except Exception:
        pass


_DETAILS_PATH = os.path.join(_HERE, "BENCH_DETAILS.json")

# The driver captures only a bounded tail of stdout and parses the last
# JSON line from it (observed: BENCH_r01/r02 both carry ``parsed: null``
# with a 2000-char tail that starts mid-line). Keys on this whitelist are
# the headline numbers; everything else goes to BENCH_DETAILS.json.
_COMPACT_KEYS = (
    "metric", "value", "unit", "vs_baseline", "source", "step_time_ms",
    "device_kind", "n_devices", "mfu", "transformer_tokens_per_sec",
    "transformer_mfu", "flash_fwdbwd_speedup", "allreduce_gbps",
    "resnet50_s2d_images_per_sec", "moe_dispatch_sort_speedup",
    "moe_step_ms", "moe_selected", "moe_spread_pct", "moe_drop_rate",
    "native_input_images_per_sec", "double_buffer_speedup",
    "flash_32k_fwd_ms", "flash_32k_window2k_fwd_ms",
    "kernel_sweep_failures", "kernel_sweep_numeric_failures",
    "kernel_sweep_numeric_errors", "proxy_spread_pct", "autotune",
    "hidden_comm_fraction", "reduction_schedule_selected",
    "overlap_spread_pct", "composed_best_vs_two_level",
    "composed_spread_pct", "composed_selected",
    "composed_sliced_ms", "composed_slices_selected",
    "composed_sliced_spread_pct",
    "sched_search_selected", "cost_model_err_pct",
    "serving_tokens_per_sec", "serving_spread_pct",
    "serving_spec_selected", "serving_spec_speedup",
    "serving_spec_accept_rate", "serving_prefix_ttft_speedup",
    "serving_prefix_hit_rate", "serving_prefix_spread_pct",
    "serving_cluster_goodput_tokens_per_sec", "serving_cluster_scaling",
    "serving_cluster_disagg_speedup", "serving_cluster_spread_pct",
    "plan_vs_handwired", "plan_spread_pct",
    "serving_burst_goodput", "serving_burst_ttft_p99_ms",
    "serving_burst_spread_pct", "serving_burst_selected",
    "serving_sampled_tokens_per_sec", "serving_sampled_spread_pct",
    "serving_sampled_spec_speedup", "serving_sampled_spec_accept_rate",
    "serving_sampled_selected",
    "serving_decode_kernel_ms", "serving_decode_kernel_spread_pct",
    "serving_decode_kernel_fused_speedup",
    "serving_decode_kernel_selected",
    "seq_parallel_selected", "seq_parallel_ttft_ms",
    "seq_parallel_spread_pct",
    "serving_tenants_goodput", "serving_tenants_fairness",
    "serving_tenants_spread_pct", "serving_tenants_selected",
)


def _emit_final(result: dict) -> None:
    """Write the full result to BENCH_DETAILS.json and print a COMPACT
    final JSON line guaranteed to fit (with margin) inside the driver's
    2000-char stdout tail window."""
    wrote_details = False
    try:
        full = dict(result)
        full["emitted_at"] = time.strftime(
            "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
        )
        with open(_DETAILS_PATH, "w") as f:
            json.dump(full, f, indent=1)
            f.write("\n")
        wrote_details = True
    except OSError:
        pass
    compact = {k: result[k] for k in _COMPACT_KEYS if k in result}
    if "bench_note" in result:
        compact["bench_note"] = str(result["bench_note"])[:160]
    if "error" in result:
        compact["error"] = str(result["error"])[:240]
    carried = result.get("last_good_tpu")
    if isinstance(carried, dict):
        compact["last_good_tpu"] = {
            k: carried[k]
            for k in ("value", "mfu", "age_hours", "stale", "measured_at")
            if k in carried
        }
        compact["last_good_tpu"]["stale"] = True
        # Rows the cache inherited from an OLDER run than measured_at
        # (merge-on-save): surface count + oldest stamp so the compact
        # line can't pass off a days-old row under an hours-old stamp.
        ck = carried.get("carried_keys")
        if isinstance(ck, dict) and ck.get("keys"):
            stamps = [s for s in (ck.get("stamps") or {}).values() if s]
            compact["last_good_tpu"]["rows_from_older_runs"] = len(ck["keys"])
            if stamps:
                compact["last_good_tpu"]["oldest_row_measured_at"] = (
                    min(stamps)
                )
        # Per-row provenance rollup (VERDICT r5 ask #7): how many rows
        # the newest save measured live vs inherited — the compact line
        # can't pass a mostly-carried blob off as a fresh capture.
        prov = carried.get("row_provenance")
        if isinstance(prov, dict) and prov:
            fresh = sum(
                1 for p in prov.values()
                if isinstance(p, dict) and p.get("source") == "live"
            )
            compact["last_good_tpu"]["fresh_rows"] = fresh
            compact["last_good_tpu"]["carried_rows"] = len(prov) - fresh
    if wrote_details:
        compact["details"] = "BENCH_DETAILS.json"
    else:
        compact["details_write_failed"] = True
    # Hard driver contract: the final line must parse inside the
    # 2000-char stdout tail window. The key list grows a few entries
    # per PR and a saturated run (every phase landed every row) can
    # overflow — shed the NEWEST keys first (reverse declaration
    # order; the details file always has everything) rather than let
    # the tail truncate mid-JSON, and say how many were shed. The
    # identity/provenance core is never shed.
    keep = ("metric", "value", "unit", "source", "device_kind",
            "n_devices", "error", "details", "details_write_failed",
            "last_good_tpu")
    line = json.dumps(compact)
    shed = 0
    for k in reversed(_COMPACT_KEYS):
        if len(line) < 1840:
            break
        if k in compact and k not in keep:
            del compact[k]
            shed += 1
            compact["compact_keys_shed"] = shed
            line = json.dumps(compact)
    print(line, flush=True)


def main() -> None:
    deadline = time.monotonic() + TOTAL_BUDGET
    errors = []
    _truncate_trace()

    accel = _probe_with_retries(deadline, errors)
    if accel is not None:
        # All remaining budget minus the CPU-fallback reserve: the fixed
        # 900 s cap made the 2026-08-01 live run drop its last phase
        # (native input) with ~4 min still on the clock. The child prints
        # a cumulative line after every phase, so even a timeout only
        # costs the unfinished phase; a child that wedges before its
        # FIRST line still leaves the reserve for the CPU fallback's own
        # early-primary-line salvage.
        remaining = deadline - time.monotonic()
        budget = remaining - CPU_BENCH_RESERVE
        if budget >= 60.0:
            result, err = _run_child("accel", budget)
            if result is not None:
                result["source"] = "live"
                _save_last_tpu(result)
                _emit_final(result)
                return
            errors.append(err)
        else:
            # Degenerate tail (probe retries ate the window): the old
            # max(60, ...) floor granted the accel child a slice carved
            # OUT of the CPU-fallback reserve — the reserve is what
            # lets a wedged-before-first-line accel child be followed
            # by a CPU fallback with time to print its own primary
            # line, so when it cannot be honoured the accel child is
            # skipped, not squeezed in (ADVICE r5).
            errors.append(
                f"accel bench skipped: {remaining:.0f}s left cannot "
                f"honour the {CPU_BENCH_RESERVE}s CPU-fallback reserve"
            )

    budget = max(60.0, deadline - time.monotonic() - 180)
    result, err = _run_child("cpu", budget, env=_cpu_env())
    if result is None:
        errors.append(err)

    # Late re-probe: the tunnel flaps — it may be back by now. A reduced
    # accel run still beats a carried number; its primary JSON line is
    # printed before the supplementary benchmarks, so even a timeout
    # salvages live TPU figures.
    remaining = deadline - time.monotonic()
    if remaining > 150:
        accel = _probe_accelerator(min(PROBE_TIMEOUT, remaining - 30))
        if accel is not None:
            late, err2 = _run_child(
                "accel", deadline - time.monotonic() - 15
            )
            if late is not None:
                late["source"] = "live"
                late["bench_note"] = (
                    late.get("bench_note", "")
                    + " captured on late re-probe after earlier probe failures"
                ).strip()
                _save_last_tpu(late)
                _emit_final(late)
                return
            errors.append(f"late re-probe bench: {err2}")

    if result is not None:
        result["source"] = "cpu-fallback"
        result["error"] = "; ".join(e for e in errors if e)
        _attach_last_tpu(result)
        _attach_probe_trail(result)
        _emit_final(result)
        return

    out = {
        "metric": "resnet50_images_per_sec",
        "value": 0.0,
        "unit": "images/sec",
        "vs_baseline": 0.0,
        "source": "failed",
        "error": "; ".join(e for e in errors if e),
    }
    _attach_last_tpu(out)
    _attach_probe_trail(out)
    _emit_final(out)


# ---------------------------------------------------------------------------
# Child process: the actual measurements (jax imported only here).
# ---------------------------------------------------------------------------


def _repeat_median(sample, repeats: int):
    """Median-of-n measurement discipline (round-5 VERDICT ask #8): the
    single-sample CPU-proxy rows drifted round-to-round (flash interpret
    0.75x->0.63x, s2d 36.9->31.4) with no way to tell a real regression
    from noise. ``sample`` is a zero-arg measurement returning a float;
    returns ``(median, spread_pct)`` with spread = 100*(max-min)/median.
    ``repeats=1`` degenerates to the single sample (spread 0) — used on
    the chip, where the budget goes to more steps per sample instead."""
    vals = sorted(sample() for _ in range(max(1, repeats)))
    n = len(vals)
    med = (vals[n // 2] if n % 2
           else 0.5 * (vals[n // 2 - 1] + vals[n // 2]))
    spread = 100.0 * (vals[-1] - vals[0]) / med if med else 0.0
    return med, round(spread, 1)


def _peak_lookup(device_kind: str, table: dict):
    """Order-sensitive substring match over a per-kind peak table (the
    single matcher for _PEAK_BF16_FLOPS and _PEAK_HBM_BYTES)."""
    kind = device_kind.lower()
    for sub, peak in table.items():
        if sub in kind:
            return peak
    return None


def _peak_flops(device_kind: str):
    return _peak_lookup(device_kind, _PEAK_BF16_FLOPS)


def _fetch_scalar(x) -> float:
    """Force REAL device synchronisation by materialising a scalar on the
    host. ``jax.block_until_ready`` proved unreliable under the experimental
    tunnelled TPU platform (round-2 finding: it returned after dispatch,
    yielding impossible >100% MFU); a host transfer cannot lie."""
    import jax
    import numpy as np

    return float(np.asarray(jax.device_get(x)).ravel()[0])


def _bench_attention(on_accel: bool):
    """Flash-attention Pallas kernel vs XLA's fused attention on the same
    chip (VERDICT round-1 item 6: 'microbench kernel-vs-XLA attention on the
    real chip and record the win'). Iterations are dependency-chained
    through a scan so the device cannot overlap or elide them."""
    import jax
    import jax.numpy as jnp

    from chainermn_tpu.ops.attention import dot_product_attention
    from chainermn_tpu.ops.flash_attention import flash_attention

    if on_accel:
        B, T, H, D, iters = 4, 4096, 8, 128, 10
    else:
        B, T, H, D, iters = 1, 256, 2, 64, 2
    rng = jax.random.PRNGKey(7)
    kq, kk, kv = jax.random.split(rng, 3)
    q = jax.random.normal(kq, (B, T, H, D), jnp.bfloat16)
    k = jax.random.normal(kk, (B, T, H, D), jnp.bfloat16)
    v = jax.random.normal(kv, (B, T, H, D), jnp.bfloat16)

    spreads = []

    def chained(fn, n):
        """The dependency-chained scan harness — ONE builder for every
        attention row (T=4096 and T=32768), so the timing method cannot
        silently diverge between them again (the r2–r5 32k rows used a
        single dispatch and carried tens of ms of tunnel latency)."""
        @jax.jit
        def many(q, k, v):
            def body(qc, _):
                out = fn(qc, k, v)
                return (qc + 0.0001 * out).astype(qc.dtype), ()
            qc, _ = jax.lax.scan(body, q, None, length=n)
            return jnp.sum(qc.astype(jnp.float32))
        return many

    def timed(fn):
        many = chained(fn, iters)
        _fetch_scalar(many(q, k, v))  # compile + warm

        def sample():
            t0 = time.perf_counter()
            _fetch_scalar(many(q, k, v))
            return (time.perf_counter() - t0) / iters * 1000

        # n=5: the interpret-mode flash rows measured 60%+ spread at
        # n=3 — the row driving two rounds of phantom "drift".
        med, spread = _repeat_median(sample, 1 if on_accel else 5)
        spreads.append(spread)
        return med

    def grad_of(attn):
        # Full backward (dq AND dk/dv kernels — grad wrt q alone would let
        # JAX dead-code-eliminate the dkv kernel); sum into q's shape so the
        # chained-scan timing harness can thread it.
        def fn(q, k, v):
            dq, dk, dv = jax.grad(
                lambda qq, kk, vv: jnp.sum(attn(qq, kk, vv).astype(jnp.float32)),
                argnums=(0, 1, 2),
            )(q, k, v)
            return dq + dk + dv
        return fn

    flash = lambda q, k, v: flash_attention(q, k, v, causal=True)  # noqa: E731
    xla = lambda q, k, v: dot_product_attention(q, k, v, causal=True)  # noqa: E731
    f_fwd, x_fwd = timed(flash), timed(xla)
    f_bwd, x_bwd = timed(grad_of(flash)), timed(grad_of(xla))
    out = {
        "attn_shape": f"B{B}xT{T}xH{H}xD{D}_bf16_causal",
        "flash_fwd_ms": round(f_fwd, 3),
        "xla_fwd_ms": round(x_fwd, 3),
        "flash_fwdbwd_ms": round(f_bwd, 3),
        "xla_fwdbwd_ms": round(x_bwd, 3),
        "flash_fwd_speedup": round(x_fwd / f_fwd, 2),
        "flash_fwdbwd_speedup": round(x_bwd / f_bwd, 2),
    }
    if not on_accel:
        # Worst per-measurement spread of the 4 medians-of-3 above: the
        # driver line can now tell proxy jitter from a real regression.
        out["attn_proxy_spread_pct"] = max(spreads)

    # Adopt the fwd+bwd rows (the training-relevant comparison) as this
    # (device, shape-bucket)'s attention-variant decision — the measured
    # flash-vs-xla inversion (3.0x on chip, 0.56x CPU interpret) is
    # exactly what ops.attention's 'auto' dispatch needs persisted.
    try:
        from chainermn_tpu import tuning

        key = tuning.decision_key(shape=(T, H, D), dtype=jnp.bfloat16)
        # spreads=None on accel: single-sample rows take the registry's
        # 10% noise floor (see _bench_moe_dispatch).
        tuning.record_measurement(
            "attention", key, {"flash": f_bwd, "xla": x_bwd},
            spreads=(None if on_accel
                     else {"flash": spreads[2], "xla": spreads[3]}),
        )
        out["attention_selected"] = tuning.choice(
            "attention", ("flash", "xla"), key
        )
    except Exception as e:
        out["attention_autotune_error"] = f"{type(e).__name__}: {e}"[:120]

    if on_accel:
        # Long-context single-chip point: the VMEM-blocked kernel keeps
        # working where materialised attention stops compiling (measured
        # T=32768: flash 90 ms; XLA attention fails to compile).
        LT = 32768

        ql = jax.random.normal(kq, (1, LT, 8, 128), jnp.bfloat16)

        def timed_long(attn, n=4):
            """Long-context timing via the SAME ``chained`` harness as
            the T=4096 rows. The r2–r5 single-dispatch version measured
            kernel + tunnel dispatch latency (tens of ms), which swamped
            the banded-grid win: full-causal 104.9 ms vs windowed-2k
            72.4 ms read as 1.45x where the k-block span math says ~8x
            of the work vanishes."""
            many = chained(attn, n)
            _fetch_scalar(many(ql, ql, ql))  # compile + warm
            t0 = time.perf_counter()
            _fetch_scalar(many(ql, ql, ql))
            return round((time.perf_counter() - t0) / n * 1000, 1)

        def classify(e, note: str = "") -> str:
            """Name the real cause, not just the exception class (round-4
            VERDICT item 8). ``note`` carries the per-path explanation —
            only the XLA comparator materialises the O(T^2) scores."""
            import re

            msg = str(e)
            low = msg.lower()
            if ("resource_exhausted" in low or "out of memory" in low
                    or "oom" in low or "exceeds the limit" in low
                    or ("allocat" in low and "fail" in low)):
                m = re.search(
                    r"[\d.]+\s*(?:[gmk]i?b|bytes)", low
                )
                size = f" ({m.group(0)})" if m else ""
                return f"OOM{size}{note}"
            return f"{type(e).__name__}: {msg}"[:200]

        xla_oom_note = (": expected — the materialised O(T^2) score "
                        "tensor alone is 8 heads * 32768^2 * 4 B = "
                        "34.4 GB vs 16 GB HBM")
        try:
            out["flash_32k_fwd_ms"] = timed_long(
                lambda q, k, v: flash_attention(q, k, v, causal=True)
            )
        except Exception as e:
            out["flash_32k_error"] = classify(e)
        try:
            # Same iters as the flash row: on 16 GB parts this OOMs in
            # compile, but on a larger-HBM chip the row must not fall
            # back to the retired single-dispatch method.
            out["xla_32k_fwd_ms"] = timed_long(
                lambda q, k, v: dot_product_attention(q, k, v, causal=True)
            )
        except Exception as e:
            # keep *_ms keys type-stable (floats); failures get their own key
            out["xla_32k_error"] = classify(e, xla_oom_note)

        # Sliding window at long context: the band-narrowed grid should
        # approach full-causal-time * (window/T) — the row that certifies
        # the O(T*W) claim on silicon (r3; docs/api.md ops section).
        try:
            win = 2048
            out["flash_32k_window2k_fwd_ms"] = timed_long(
                lambda q, k, v: flash_attention(
                    q, k, v, causal=True, window=win
                ),
                n=8,  # ~8x less work than full-causal; amortise more
            )
        except Exception as e:
            out["flash_32k_window_error"] = f"{type(e).__name__}"[:80]
        # Method marker as soon as ANY new-method 32k row exists (the
        # native_input_method pattern): it must survive a sibling-row
        # failure or _purge_retired would scrub the valid rows from the
        # carried blob.
        if any(k in out for k in _OLD_METHOD_32K_KEYS):
            out["flash_32k_method"] = "chained-scan"
    return out


def _resnet_setup(comm, on_accel: bool, *, stem: str = "standard",
                  force_remat: str | None = None):
    """Shared ResNet bench setup (headline and s2d variants): model, global
    batch (multihost-converted), jitted step, initial state. One place owns
    the workload definition so the variants cannot drift."""
    import jax
    import jax.numpy as jnp
    import optax

    from chainermn_tpu import create_multi_node_optimizer
    from chainermn_tpu.models import ResNet18, ResNet50
    from chainermn_tpu.training.train_step import (
        create_train_state,
        make_train_step,
    )

    knobs = {}
    if on_accel:
        # Perf knobs adoptable from the sweep's winner without a code
        # edit (examples/imagenet/sweep_mfu.py -> docs/benchmarks.md
        # roofline): remat mode and per-device batch. ALWAYS recorded in
        # the returned knobs (defaults included) so the carried-result
        # machinery compares like with like.
        remat_mode = (force_remat if force_remat is not None else
                      os.environ.get("CHAINERMN_BENCH_RESNET_REMAT", "none"))
        if remat_mode not in ("none", "conv", "full"):
            raise ValueError(
                "CHAINERMN_BENCH_RESNET_REMAT must be none|conv|full, "
                f"got {remat_mode!r}"
            )
        model = ResNet50(
            num_classes=1000, stem=stem,
            remat=remat_mode != "none",
            remat_policy="conv" if remat_mode == "conv" else None,
        )
        per_device_batch = int(
            os.environ.get("CHAINERMN_BENCH_RESNET_BATCH", "128")
        )
        hw = 224
        metric = "resnet50_images_per_sec"
        donate = (os.environ.get(
            "CHAINERMN_BENCH_RESNET_DONATE", "false").lower()
            in ("1", "true", "yes"))
        knobs = {"resnet_remat": remat_mode,
                 "resnet_batch": per_device_batch,
                 "resnet_donate": donate}
    else:
        model = ResNet18(num_classes=100, compute_dtype=jnp.float32,
                         stem=stem)
        per_device_batch, hw = 8, 32
        metric = "resnet18_cpu_proxy_images_per_sec"

    batch = per_device_batch * comm.size
    rng = jax.random.PRNGKey(0)
    # bf16 images: halves the input-pipeline HBM bytes of a bandwidth-bound
    # step (measured +6% img/s on v5e); the model casts to its compute dtype
    # at entry either way.
    x = jax.random.normal(rng, (batch, hw, hw, 3), jnp.bfloat16)
    y = jax.random.randint(rng, (batch,), 0, 10)
    if jax.process_count() > 1:
        # Each process holds the full batch locally; assemble the global
        # sharded arrays the jitted step's in_specs expect.
        from jax.experimental import multihost_utils
        from jax.sharding import PartitionSpec as P

        x, y = multihost_utils.host_local_array_to_global_array(
            (x, y), comm.mesh, P()
        )

    variables = jax.jit(lambda k, xb: model.init(k, xb, train=True))(
        jax.random.PRNGKey(42), x[:2]
    )

    def loss_fn(params, batch_, model_state):
        xb, yb = batch_
        logits, mutated = model.apply(
            {"params": params, "batch_stats": model_state},
            xb,
            train=True,
            mutable=["batch_stats"],
        )
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, yb
        ).mean()
        return loss, ({}, mutated["batch_stats"])

    optimizer = create_multi_node_optimizer(
        optax.sgd(0.1, momentum=0.9), comm,
        allreduce_grad_dtype=jnp.bfloat16,
    )
    state = create_train_state(
        variables["params"], optimizer, comm,
        model_state=variables["batch_stats"],
    )
    step = make_train_step(loss_fn, optimizer, comm,
                           donate=bool(knobs.get("resnet_donate", False)))
    return step, state, (x, y), batch, metric, knobs


def _bench_s2d_resnet(comm, on_accel: bool):
    """ResNet-50 with the space-to-depth stem (supplementary): the 3-channel
    7x7 conv wastes the 128-lane MXU; rearranging 4x4 pixel blocks into 48
    channels is the classic TPU fix (measured +16% img/s on v5e). Reported
    separately because the stem is not weight-compatible with the standard
    ResNet-50 the headline metric measures."""
    steps = 13 if on_accel else 2
    step, state, batch_arrays, batch, _, _ = _resnet_setup(
        comm, on_accel, stem="space_to_depth"
    )
    for _ in range(3):
        state, m = step(state, batch_arrays)
    _fetch_scalar(m["loss"])

    def sample():
        nonlocal state
        t0 = time.perf_counter()
        for _ in range(steps):
            state, m = step(state, batch_arrays)
        _fetch_scalar(m["loss"])
        return (time.perf_counter() - t0) / steps

    dt, spread = _repeat_median(sample, 1 if on_accel else 3)
    out = {
        "resnet50_s2d_images_per_sec": round(batch / dt, 2),
        "resnet50_s2d_step_ms": round(dt * 1e3, 2),
    }
    if not on_accel:
        out["resnet50_s2d_spread_pct"] = spread
    return out


def _bench_moe_dispatch(on_accel: bool):
    """MoE dispatch-cost crossover (VERDICT r2 item 8): dense one-hot
    einsum (O(T·E·C·d)) vs index sort/scatter dispatch (O(T·d)) at LM
    scale — queue assembly + weighted combine, single device (the
    all_to_all between them is identical either way)."""
    import jax
    import jax.numpy as jnp

    from chainermn_tpu.parallel.moe import dispatch_einsum, dispatch_sort

    if on_accel:
        T, E, D, iters = 16384, 16, 512, 10
    else:
        T, E, D, iters = 2048, 8, 64, 3
    capacity = int(T / E * 1.25)
    rng = jax.random.PRNGKey(0)
    x = jax.random.normal(rng, (T, D), jnp.bfloat16)
    logits = jax.random.normal(jax.random.fold_in(rng, 1), (T, E),
                               jnp.float32)

    spreads = []

    def timed(fn):
        @jax.jit
        def run(x, logits):
            def body(c, _):
                queues, combine_fn = fn(c, logits, capacity, 2)
                out = combine_fn(queues)  # identity "expert": pure dispatch
                return (c + 0.001 * out).astype(c.dtype), ()

            c, _ = jax.lax.scan(body, x, None, length=iters)
            return jnp.sum(c.astype(jnp.float32))

        _fetch_scalar(run(x, logits))  # compile + warm

        def sample():
            t0 = time.perf_counter()
            _fetch_scalar(run(x, logits))
            return (time.perf_counter() - t0) / iters * 1000

        med, spread = _repeat_median(sample, 1 if on_accel else 3)
        spreads.append(spread)
        return med

    einsum_ms = timed(dispatch_einsum)
    sort_ms = timed(dispatch_sort)
    out = {
        "moe_dispatch_shape": f"T{T}xE{E}xD{D}_cap{capacity}_top2",
        "moe_dispatch_einsum_ms": round(einsum_ms, 3),
        "moe_dispatch_sort_ms": round(sort_ms, 3),
        "moe_dispatch_sort_speedup": round(einsum_ms / sort_ms, 2),
    }
    if not on_accel:
        out["moe_dispatch_spread_pct"] = max(spreads)
    # Adopt the rows this phase ALREADY measured as the dispatch
    # decision for this (device, shape-bucket): future runs route
    # moe_layer_local's 'auto' through the persisted winner instead of
    # re-measuring (chainermn_tpu.tuning).
    try:
        from chainermn_tpu import tuning

        key = tuning.decision_key(shape=(T, E, D), dtype=jnp.bfloat16)
        # On-accel rows are single samples (many chained iterations):
        # pass spreads=None so adoption applies the registry's 10%
        # single-sample noise floor instead of a fake spread of 0.
        tuning.record_measurement(
            "moe_dispatch", key,
            {"einsum": einsum_ms, "sort": sort_ms},
            spreads=(None if on_accel
                     else {"einsum": spreads[0], "sort": spreads[1]}),
        )
        out["moe_dispatch_selected"] = tuning.choice(
            "moe_dispatch", ("sort", "einsum"), key
        )
    except Exception as e:
        out["moe_dispatch_autotune_error"] = f"{type(e).__name__}: {e}"[:120]
    return out


def _bench_moe_plan(comm, on_accel: bool):
    """ISSUE 20: the expert axis, priced (CPU-proxy convention:
    median-of-n>=3 + spread — a delta inside ``moe_spread_pct`` is
    noise; on-accel rows are single samples under the registry's 10%
    floor).

    One MoE MLP train-step workload, identical routing semantics both
    ways:

    - ``on``: an ``expert x data`` ``ParallelPlan`` — expert leaves
      sharded over the expert axis, tokens dispatched through the two
      all_to_alls (``plan.moe_layer``, dispatch impl via the tuned
      ``moe_dispatch`` decision);
    - ``off``: a pure data plan with every expert replicated — the
      same top-1 sort dispatch run shard-locally, no expert wire.

    The pair is adopted (spread-gated) as this shape's
    ``expert_parallel`` decision, and the drop accounting rides out as
    ``moe_drop_rate`` (dropped tokens / routed tokens at capacity
    factor 1.25)."""
    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import PartitionSpec as P

    from chainermn_tpu.parallel.moe import (
        dispatch_sort,
        load_balancing_loss,
        make_expert_params,
        moe_capacity,
        record_moe_dispatch,
    )
    from chainermn_tpu.parallel.plan import ParallelPlan

    n = comm.size
    e_axis = 4 if n >= 8 else (2 if n >= 2 else 1)
    data_axis = max(1, n // e_axis)
    eps = 2  # experts per shard: the a2a ships eps queues per peer
    E = e_axis * eps
    D = 256 if on_accel else 64
    F = 2 * D
    tokens = (64 if on_accel else 16) * n
    steps = 16 if on_accel else 4

    rng = jax.random.PRNGKey(0)

    def _expert_init(r):
        k1, k2 = jax.random.split(r)
        return {"w1": jax.random.normal(k1, (D, F), jnp.float32) * 0.05,
                "w2": jax.random.normal(k2, (F, D), jnp.float32) * 0.05}

    def expert_fn(p, xq):
        return jnp.tanh(xq @ p["w1"]) @ p["w2"]

    # global expert e lives on shard e // eps: stack [e_axis, eps, ...]
    # so the expert-spec'd leading dim matches the axis size and each
    # shard's squeezed leaf is the [eps, ...] stack moe_layer_local
    # vmaps over
    experts = jax.tree.map(
        lambda l: l.reshape(e_axis, eps, *l.shape[1:]),
        make_expert_params(_expert_init, rng, E),
    )
    params = {
        "experts": experts,
        "router": jax.random.normal(jax.random.fold_in(rng, 1),
                                    (D, E), jnp.float32) / 4.0,
    }
    x = jax.random.normal(jax.random.fold_in(rng, 2), (tokens, D),
                          jnp.float32)
    y = jax.random.normal(jax.random.fold_in(rng, 3), (tokens, D),
                          jnp.float32)
    inner = optax.sgd(1e-2)
    devices = list(comm.mesh.devices.flat)
    spreads = []

    def time_plan(plan, loss_fn, specs):
        state = plan.create_train_state(params, inner, param_specs=specs)
        step = plan.compile_train_step(loss_fn, inner, params,
                                       param_specs=specs)
        state, m = step(state, (x, y))
        state, m = step(state, (x, y))
        _fetch_scalar(m["loss"])

        def sample():
            nonlocal state, m
            t0 = time.perf_counter()
            for _ in range(steps):
                state, m = step(state, (x, y))
            _fetch_scalar(m["loss"])
            return (time.perf_counter() - t0) / steps * 1000

        med, spread = _repeat_median(sample, 1 if on_accel else 3)
        spreads.append(spread)
        return med, m

    # ---- on: expert (x data) plan, tokens through the two all_to_alls
    axes = ({"expert": e_axis, "data": data_axis}
            if data_axis > 1 else {"expert": e_axis})
    plan_on = ParallelPlan(axes, devices=devices)
    moe_fn, rec = plan_on.moe_layer(
        tokens_local=tokens // data_axis, d_model=D,
        experts_per_shard=eps, capacity_factor=1.25,
    )
    specs = {"experts": P("expert"), "router": P()}

    def loss_on(p, batch_):
        xb, yb = batch_
        out, aux = moe_fn(xb, p["router"], expert_fn, p["experts"])
        loss = (jnp.mean((xb + out - yb) ** 2)
                + 0.01 * aux["load_balance"])
        return loss, ({"dropped": aux["dropped"],
                       "padded": aux["padded"],
                       "capacity": aux["capacity"],
                       "expert_load": aux["expert_load"]}, ())

    on_ms, on_metrics = time_plan(plan_on, loss_on, specs)
    drop_rate = float(on_metrics["dropped"]) / tokens
    # Host-side mirror of the last step's routing stats (ISSUE 20
    # observability row: the moe_dispatch event -> tap gauges).
    record_moe_dispatch(on_metrics)

    # ---- off: pure data plan, every expert replicated, local dispatch
    plan_off = ParallelPlan({"data": max(1, n)}, devices=devices)
    off_specs = {"experts": P(), "router": P()}

    def loss_off(p, batch_):
        xb, yb = batch_
        logits = xb @ p["router"]
        cap = moe_capacity(xb.shape[0], E, 1, 1.25)
        queues, combine_fn = dispatch_sort(xb, logits, cap, 1)
        flat = jax.tree.map(lambda l: l.reshape(E, *l.shape[2:]),
                            p["experts"])
        out = combine_fn(jax.vmap(expert_fn)(flat, queues))
        loss = (jnp.mean((xb + out - yb) ** 2)
                + 0.01 * load_balancing_loss(logits, axis_name="data"))
        return loss, ({}, ())

    off_ms, _ = time_plan(plan_off, loss_off, off_specs)

    out = {
        "moe_plan_shape": f"T{tokens}xE{E}xD{D}",
        "moe_plan_mesh": plan_on.describe()["mesh"],
        "moe_plan_dispatch": rec["winner"],
        "moe_step_ms": round(on_ms, 3),
        "moe_off_step_ms": round(off_ms, 3),
        "moe_drop_rate": round(drop_rate, 4),
    }
    if not on_accel:
        out["moe_spread_pct"] = max(spreads)
    # Adopt the pair as this shape's expert_parallel decision (the
    # registry default is 'off': the axis must EARN its all_to_alls).
    try:
        from chainermn_tpu import tuning

        key = tuning.decision_key(shape=(tokens, E, D),
                                  dtype=jnp.float32)
        tuning.record_measurement(
            "expert_parallel", key,
            {"on": on_ms, "off": off_ms},
            spreads=(None if on_accel
                     else {"on": spreads[0], "off": spreads[1]}),
        )
        out["moe_selected"] = tuning.choice(
            "expert_parallel", ("on", "off"), key
        )
    except Exception as e:
        out["moe_autotune_error"] = f"{type(e).__name__}: {e}"[:120]
    return out


def _bench_serving(comm, on_accel: bool):
    """ISSUE 4: the continuous-batching serving phase.

    Three measurements on one LM (CPU-proxy convention: median-of-n>=3
    + spread; on-accel rows are single samples of many chained steps and
    adopt under the registry's 10% noise floor):

    1. steady-state decode step per ``decode_impl`` (dense slot ring vs
       paged block pool) — adopted as this shape's ``decode_impl``
       decision;
    2. the paged step across ``kv_block_size`` candidates — adopted as
       ``kv_block_size``;
    3. a full scheduler stream (staggered requests through
       ``prefill_priority`` admission, ``decode_impl='auto'`` so the
       freshly recorded decision is exercised with provenance):
       tokens/s + nearest-rank p50/p99 per-token latency + mean slot
       occupancy from ``Scheduler.summary()``;
    4. speculative spec-vs-plain (ISSUE 5): the same stream at every
       ``spec_tokens`` candidate K (n-gram drafting over each request's
       own history, greedy) — per-K tokens/s medians, ms-per-GENERATED-
       token rows (``serving_spec_ms``: acceptance rate priced in) and
       per-K acceptance rates, adopted as this shape's ``spec_tokens``
       decision via ``record_measurement`` (spread-gated: a noise-band
       "winner" is honestly refused and the table default stands).

    ``serving_model_shape`` (DxHxL) is the key material
    ``tuning seed`` uses to rebuild ``serving_decision_key`` offline.
    """
    import functools

    import jax
    import jax.numpy as jnp
    import numpy as np

    from chainermn_tpu.models.transformer import TransformerLM
    from chainermn_tpu.serving import (
        DECODE_IMPLS,
        SPEC_TOKENS,
        Request,
        Scheduler,
        ServingEngine,
        serving_decision_key,
    )

    if on_accel:
        layers, d_model, heads, d_ff = 4, 512, 8, 2048
        vocab, max_len, slots = 32000, 512, 16
        block_sizes = (16, 32, 64, 128)
        decode_steps, stream_requests, gen = 32, 24, 32
        dtype = jnp.bfloat16
    else:
        layers, d_model, heads, d_ff = 2, 64, 4, 128
        vocab, max_len, slots = 256, 64, 4
        block_sizes = (16, 64)
        decode_steps, stream_requests, gen = 6, 6, 4
        dtype = jnp.float32
    model = TransformerLM(
        vocab_size=vocab, num_layers=layers, num_heads=heads,
        d_model=d_model, d_ff=d_ff, max_len=max_len, compute_dtype=dtype,
    )
    params = jax.jit(
        functools.partial(model.init, train=False)
    )(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    out = {
        "serving_model_shape": f"D{d_model}xH{heads}xL{max_len}",
        "serving_slots": slots,
    }

    def step_median(impl, bs):
        # spec_tokens pinned to 0: these are the PLAIN decode rows — on
        # a box whose cache carries an adopted spec_tokens>0 an 'auto'
        # here would silently turn the baseline speculative.
        eng = ServingEngine(
            model, params, num_slots=slots, max_len=max_len,
            decode_impl=impl, kv_block_size=bs, prefill_buckets=(8, 16),
            spec_tokens=0,
        )
        for i in range(slots):  # full occupancy: the steady-state shape
            eng.prefill_join([1 + i % (vocab - 1)] * 4)

        def sample():
            t0 = time.perf_counter()
            for _ in range(decode_steps):
                eng.decode_step()
            return (time.perf_counter() - t0) / decode_steps * 1000

        sample()  # compile + warm
        return _repeat_median(sample, 1 if on_accel else 3)

    impl_ms, impl_spreads = {}, {}
    block_ms, block_spreads = {}, {}
    impl_ms["dense"], impl_spreads["dense"] = step_median("dense", 64)
    for bs in block_sizes:
        block_ms[str(bs)], block_spreads[str(bs)] = step_median("paged", bs)
    # the impl comparison uses paged at the table-default block size
    # (numeric min as the fallback — a string sort would rank '128'
    # before '16')
    paged_ref = "64" if "64" in block_ms else min(block_ms, key=int)
    impl_ms["paged"] = block_ms[paged_ref]
    impl_spreads["paged"] = block_spreads[paged_ref]
    out["serving_decode_impl_ms"] = {k: round(v, 4)
                                     for k, v in impl_ms.items()}
    out["serving_kv_block_ms"] = {k: round(v, 4)
                                  for k, v in block_ms.items()}
    if not on_accel:
        # Spread keys are emitted ONLY for real multi-sample runs: an
        # on-accel row is a single sample of many chained steps, and an
        # absent key is what tells the offline seeder to apply the same
        # 10% noise floor the live adoption uses (spreads=None below) —
        # a recorded 0.0 would read as "three tied medians" and pin a
        # coin flip.
        out["serving_decode_spread_pct"] = max(impl_spreads.values())
        out["serving_kv_block_spread_pct"] = max(block_spreads.values())

    try:
        from chainermn_tpu import tuning

        key = serving_decision_key(d_model, heads, max_len)
        tuning.record_measurement(
            "decode_impl", key, impl_ms,
            spreads=None if on_accel else impl_spreads,
        )
        tuning.record_measurement(
            "kv_block_size", key, block_ms,
            spreads=None if on_accel else block_spreads,
        )
        out["serving_decode_impl_selected"] = tuning.choice(
            "decode_impl", DECODE_IMPLS, key
        )
    except Exception as e:
        out["serving_autotune_error"] = f"{type(e).__name__}: {e}"[:120]

    # --- full scheduler stream at 'auto' decode/block (provenance
    # exercised) but PLAIN decode (spec_tokens=0): this is the headline
    # baseline the spec sweep below compares against; one engine reused
    # so repeats measure serving, not recompiles.
    eng = ServingEngine(
        model, params, num_slots=slots, max_len=max_len,
        decode_impl="auto", kv_block_size="auto", prefill_buckets=(8, 16),
        spec_tokens=0,
    )

    def run_stream(engine):
        sched = Scheduler(engine, policy="prefill_priority")
        rs = np.random.RandomState(0)
        for _ in range(stream_requests):
            p_len = int(rs.randint(3, 13))
            sched.submit(Request(
                prompt=rs.randint(1, vocab, size=p_len).tolist(),
                max_new_tokens=gen,
            ))
        sched.run()
        return sched.summary()

    def stream_medians(engine):
        """Median summary + tokens/s spread over repeats (one engine:
        repeats measure serving, not recompiles)."""
        run_stream(engine)  # compile + warm every bucket
        summaries = [run_stream(engine)
                     for _ in range(1 if on_accel else 3)]
        summaries.sort(key=lambda s: s["tokens_per_sec"])
        med = summaries[len(summaries) // 2]
        tps = [s["tokens_per_sec"] for s in summaries]
        spread = None
        if len(summaries) > 1 and med["tokens_per_sec"]:
            spread = round(
                100.0 * (tps[-1] - tps[0]) / med["tokens_per_sec"], 1
            )
        return med, spread

    med, spread = stream_medians(eng)
    out["serving_tokens_per_sec"] = med["tokens_per_sec"]
    if spread is not None:
        out["serving_spread_pct"] = spread
    out["serving_token_ms_p50"] = med["token_ms_p50"]
    out["serving_token_ms_p99"] = med["token_ms_p99"]
    out["serving_ttft_ms_p50"] = med.get("ttft_ms_p50")
    out["serving_occupancy_mean"] = med["occupancy_mean"]
    out["serving_requests"] = med["requests"]

    # --- speculative spec-vs-plain (ISSUE 5): identical stream at every
    # spec_tokens candidate, greedy n-gram drafting. ms per GENERATED
    # token (1000 / tokens-per-sec) is the adoption row — acceptance
    # rate is priced into it, and `tuning seed` rebuilds the decision
    # from exactly these keys offline.
    try:
        spec_ms, spec_tps, spec_spreads, spec_rates = {}, {}, {}, {}
        for k_str in SPEC_TOKENS:
            k = int(k_str)
            if k == 0:
                # the headline baseline above IS the K=0 row (identical
                # engine args and request stream, and the registry was
                # last mutated before it was built, so 'auto' resolved
                # the same) — reuse its medians instead of paying
                # another warm-up plus repeat streams.
                med_k, spread_k = med, spread
            else:
                eng_k = ServingEngine(
                    model, params, num_slots=slots, max_len=max_len,
                    decode_impl="auto", kv_block_size="auto",
                    prefill_buckets=(8, 16), spec_tokens=k,
                )
                med_k, spread_k = stream_medians(eng_k)
                del eng_k
            tps_k = med_k["tokens_per_sec"]
            spec_tps[k_str] = tps_k
            spec_ms[k_str] = round(1000.0 / tps_k, 4) if tps_k else None
            spec_spreads[k_str] = spread_k if spread_k is not None else 0.0
            sp = med_k.get("speculation") or {}
            if sp.get("accept_rate") is not None:
                spec_rates[k_str] = sp["accept_rate"]
        out["serving_spec_tokens_per_sec"] = spec_tps
        if all(v is not None for v in spec_ms.values()):
            out["serving_spec_ms"] = spec_ms
        if not on_accel:
            # same convention as the decode rows above: spread keys only
            # for real multi-sample runs; absent = 10% seeding floor.
            out["serving_spec_spread_pct"] = max(spec_spreads.values())
        if spec_rates:
            out["serving_spec_accept_rates"] = spec_rates
        sel = None
        if "serving_spec_ms" in out:
            from chainermn_tpu import tuning

            key = serving_decision_key(d_model, heads, max_len)
            tuning.record_measurement(
                "spec_tokens", key, spec_ms,
                spreads=None if on_accel else spec_spreads,
            )
            sel = tuning.choice("spec_tokens", SPEC_TOKENS, key)
            out["serving_spec_selected"] = sel
            if spec_tps.get("0"):
                best = sel if spec_tps.get(sel) else "0"
                out["serving_spec_speedup"] = round(
                    spec_tps[best] / spec_tps["0"], 3
                )
            if sel in spec_rates:
                out["serving_spec_accept_rate"] = spec_rates[sel]
    except Exception as e:  # never lose the phase's plain rows
        out["serving_spec_error"] = f"{type(e).__name__}: {e}"[:160]
    if not on_accel:
        out["serving_note"] = (
            "CPU-proxy honest floor: tiny LM on the loopback mesh — the "
            "medians rank decode impls/block sizes for THIS backend; "
            "absolute tokens/s is not chip throughput"
        )
    return out


def _bench_serving_prefix(comm, on_accel: bool):
    """ISSUE 7: prefix-sharing KV cache under high duplicate-prefix
    load — N requests over one long system prompt with short unique
    tails, hit depths cycling full/1/2 shared blocks so the
    ``min_shared_blocks`` thresholds produce genuinely different
    streams. The workload the cache exists for: TTFT should collapse
    to the unshared tail's prefill.

    Rows (CPU-proxy convention: median-of-n>=3 + spread; on-accel rows
    are single samples and the offline seeder applies the 10% floor):

    1. the same request stream with ``prefix_cache`` off vs on —
       median TTFT p50 per config (``serving_prefix_ttft_ms``), plus
       tokens/s; adopted as this shape's ``prefix_cache`` decision;
    2. the cache-on stream across ``min_shared_blocks`` candidates
       (``serving_prefix_msb_ttft_ms``) — adopted as
       ``min_shared_blocks``;
    3. the MEASURED prefill-work reduction from the ``prefix_cache``
       trace events (``serving_prefix_prefilled_tokens`` vs
       ``serving_prefix_prompt_tokens``) and the hit rate — the
       acceptance criterion's number, not prose.

    Streams are bit-identical on vs off (the suite pins it); only the
    latency may move, so the comparison is honest by construction.
    """
    import functools

    import jax
    import jax.numpy as jnp
    import numpy as np

    from chainermn_tpu.models.transformer import TransformerLM
    from chainermn_tpu.serving import (
        MIN_SHARED_BLOCKS,
        PREFIX_CACHE,
        Request,
        Scheduler,
        ServingEngine,
        serving_decision_key,
    )

    if on_accel:
        layers, d_model, heads, d_ff = 4, 512, 8, 2048
        vocab, max_len, slots = 32000, 512, 16
        block_size, shared_len, tail_len = 32, 256, 8
        n_requests, gen = 24, 16
        dtype = jnp.bfloat16
    else:
        layers, d_model, heads, d_ff = 2, 64, 4, 128
        vocab, max_len, slots = 256, 64, 4
        block_size, shared_len, tail_len = 8, 32, 4
        n_requests, gen = 6, 4
        dtype = jnp.float32
    model = TransformerLM(
        vocab_size=vocab, num_layers=layers, num_heads=heads,
        d_model=d_model, d_ff=d_ff, max_len=max_len, compute_dtype=dtype,
    )
    params = jax.jit(
        functools.partial(model.init, train=False)
    )(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))

    rs = np.random.RandomState(7)
    shared = rs.randint(1, vocab, size=shared_len).tolist()
    # Hit DEPTHS must span the min_shared_blocks candidates (1/2/4) or
    # the msb sweep measures three identical streams and seeds noise:
    # cycle full / 1-block / 2-block shared prefixes across requests.
    full_blocks = shared_len // block_size
    depth_cycle = (full_blocks, 1, 2)
    prompts = [
        shared[:depth_cycle[i % len(depth_cycle)] * block_size]
        + rs.randint(1, vocab, size=tail_len).tolist()
        for i in range(n_requests)
    ]

    # Own shape key (the seeder reads it for the two prefix decisions):
    # never the shared "serving_model_shape" — both phases use the same
    # model today, but a merged-doc overwrite would silently re-key the
    # serving phase's decisions if either shape diverged.
    out = {
        "serving_prefix_model_shape": f"D{d_model}xH{heads}xL{max_len}",
        "serving_prefix_shared_tokens": shared_len,
        "serving_prefix_requests": n_requests,
    }

    def run_stream(engine):
        sched = Scheduler(engine, policy="prefill_priority")
        for prompt in prompts:
            sched.submit(Request(prompt=prompt, max_new_tokens=gen))
        sched.run()
        return sched.summary()

    def stream_medians(engine):
        """(median summary by TTFT p50, spread) over repeats — one
        engine reused so repeats measure the steady-state cache-hot
        path (the trie persists across runs), not recompiles."""
        run_stream(engine)  # compile + warm (and, cache on, trie fill)
        summaries = [run_stream(engine)
                     for _ in range(1 if on_accel else 3)]
        summaries.sort(key=lambda s: s["ttft_ms_p50"])
        med = summaries[len(summaries) // 2]
        vals = [s["ttft_ms_p50"] for s in summaries]
        spread = None
        if len(summaries) > 1 and med["ttft_ms_p50"]:
            spread = round(
                100.0 * (vals[-1] - vals[0]) / med["ttft_ms_p50"], 1
            )
        return med, spread

    def engine_for(prefix_cache, msb="1"):
        return ServingEngine(
            model, params, num_slots=slots, max_len=max_len,
            decode_impl="paged", kv_block_size=block_size,
            prefill_buckets=(8, 16), spec_tokens=0,
            prefix_cache=prefix_cache, min_shared_blocks=msb,
        )

    # --- prefix_cache off vs on at the table-default threshold
    ttft_ms, ttft_spreads, tps = {}, {}, {}
    on_summary = None
    for cfg in PREFIX_CACHE:
        med, spread = stream_medians(engine_for(cfg))
        ttft_ms[cfg] = round(med["ttft_ms_p50"], 4)
        ttft_spreads[cfg] = spread if spread is not None else 0.0
        tps[cfg] = med["tokens_per_sec"]
        if cfg == "on":
            on_summary = med
    out["serving_prefix_ttft_ms"] = ttft_ms
    out["serving_prefix_tokens_per_sec"] = tps
    if not on_accel:
        # spread keys only for real multi-sample runs (absent = the
        # seeder's 10% single-sample floor) — the serving-phase
        # convention.
        out["serving_prefix_spread_pct"] = max(ttft_spreads.values())
    if ttft_ms.get("on"):
        out["serving_prefix_ttft_speedup"] = round(
            ttft_ms["off"] / ttft_ms["on"], 3
        )

    # --- the measured prefill-work reduction (trace-event rollup, not
    # prose): with every prompt = shared prefix + unique tail, a hot
    # cache prefills only the tails.
    px = (on_summary or {}).get("prefix_cache") or {}
    if px:
        out["serving_prefix_prompt_tokens"] = px.get("prompt_tokens")
        out["serving_prefix_prefilled_tokens"] = px.get("prefilled_tokens")
        out["serving_prefix_hit_rate"] = px.get("hit_token_rate")

    # --- min_shared_blocks sweep (cache on). msb='1' IS the 'on' arm
    # just measured (engine_for's default) — reuse that row instead of
    # re-benching an identical config.
    try:
        msb_ms = {"1": ttft_ms["on"]}
        msb_spreads = {"1": ttft_spreads["on"]}
        for msb in MIN_SHARED_BLOCKS:
            if msb == "1":
                continue
            med, spread = stream_medians(engine_for("on", msb))
            msb_ms[msb] = round(med["ttft_ms_p50"], 4)
            msb_spreads[msb] = spread if spread is not None else 0.0
        out["serving_prefix_msb_ttft_ms"] = msb_ms
        if not on_accel:
            out["serving_prefix_msb_spread_pct"] = max(
                msb_spreads.values())
    except Exception as e:  # never lose the on/off rows
        out["serving_prefix_msb_error"] = f"{type(e).__name__}: {e}"[:160]

    # --- adoption (spread-gated like every serving decision)
    try:
        from chainermn_tpu import tuning

        key = serving_decision_key(d_model, heads, max_len)
        tuning.record_measurement(
            "prefix_cache", key, ttft_ms,
            spreads=None if on_accel else ttft_spreads,
        )
        if "serving_prefix_msb_ttft_ms" in out:
            tuning.record_measurement(
                "min_shared_blocks", key, out["serving_prefix_msb_ttft_ms"],
                spreads=None if on_accel else msb_spreads,
            )
        out["serving_prefix_selected"] = tuning.choice(
            "prefix_cache", PREFIX_CACHE, key
        )
    except Exception as e:
        out["serving_prefix_autotune_error"] = (
            f"{type(e).__name__}: {e}"[:120])
    if not on_accel:
        out["serving_prefix_note"] = (
            "CPU-proxy honest floor: tiny LM, loopback — the on/off "
            "TTFT ranking holds for THIS backend; absolute ms is not "
            "chip latency"
        )
    return out


def _bench_serving_cluster(comm, on_accel: bool):
    """ISSUE 8: the cluster serving plane — goodput and TTFT at 1 vs 2
    vs 4 replicas over a ``replica × model`` device partition, and
    disaggregated vs colocated prefill/decode at 2 replicas (the
    handoff's TTFT cost/benefit, measured not asserted).

    Rows (CPU-proxy convention: median-of-n>=3 + spread; on-accel rows
    are single samples and the offline seeder applies the 10% floor):

    1. ``serving_cluster_goodput`` / ``serving_cluster_ttft_ms`` per
       replica count — open-loop request burst through the router,
       goodput = generated tokens / router wall;
    2. ``serving_cluster_disagg_ttft_ms`` — the SAME 2-replica set
       driven colocated vs disaggregated; adopted as this shape's
       ``cluster_disagg`` decision (spread-gated — the transfer hop
       must earn adoption, the spec_tokens precedent);
    3. transfer accounting from the router (bytes/handoffs) so the
       disaggregated row carries its measured wire cost.

    Streams are bit-identical across every arm (the suite pins it);
    only latency/goodput may move, so the comparison is honest by
    construction. Engines are reused across repeats (steady-state
    warm caches); each repeat gets a fresh Router.
    """
    import functools

    import jax
    import jax.numpy as jnp
    import numpy as np

    from chainermn_tpu.models.transformer import TransformerLM
    from chainermn_tpu.serving import Request
    from chainermn_tpu.serving.cluster import Router, make_replicas
    from chainermn_tpu.serving.engine import serving_decision_key

    if on_accel:
        layers, d_model, heads, d_ff = 4, 512, 8, 2048
        vocab, max_len, slots = 32000, 512, 8
        block_size, shared_len = 32, 128
        n_requests, gen = 24, 16
        dtype = jnp.bfloat16
    else:
        layers, d_model, heads, d_ff = 2, 64, 4, 128
        vocab, max_len, slots = 256, 64, 2
        block_size, shared_len = 8, 16
        n_requests, gen = 8, 4
        dtype = jnp.float32
    model = TransformerLM(
        vocab_size=vocab, num_layers=layers, num_heads=heads,
        d_model=d_model, d_ff=d_ff, max_len=max_len, compute_dtype=dtype,
    )
    params = jax.jit(
        functools.partial(model.init, train=False)
    )(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))

    devices = jax.devices()
    counts = [1, 2, 4]
    # tp=2 per replica when the device pool covers the largest
    # replica x model partition (8 devices); else unmeshed replicas
    # (same-process async dispatch only — the honest floor, noted on
    # the row).
    tp = 2 if len(devices) >= max(counts) * 2 else 1

    rs = np.random.RandomState(11)
    shared = rs.randint(1, vocab, size=shared_len).tolist()
    prompts = [
        (shared if i % 2 else shared[:shared_len // 2])
        + rs.randint(1, vocab, size=4).tolist()
        for i in range(n_requests)
    ]

    def burst(router):
        for i, p in enumerate(prompts):
            router.submit(Request(prompt=p, max_new_tokens=gen,
                                  session_id=f"s{i % 4}"))
        router.run(max_seconds=120)
        return router.summary()

    def medians(mk_router):
        burst(mk_router())  # compile + warm (trie fill on repeat 0)
        sums = [burst(mk_router()) for _ in range(1 if on_accel else 3)]
        sums.sort(key=lambda s: s.get("ttft_ms_p50") or 0.0)
        med = sums[len(sums) // 2]
        vals = [s.get("ttft_ms_p50") or 0.0 for s in sums]
        spread = None
        if len(sums) > 1 and med.get("ttft_ms_p50"):
            spread = round(
                100.0 * (vals[-1] - vals[0]) / med["ttft_ms_p50"], 1)
        return med, spread

    engine_kw = dict(
        num_slots=slots, max_len=max_len, decode_impl="paged",
        kv_block_size=block_size, prefill_buckets=(8, 16),
        spec_tokens=0, prefix_cache="on",
    )
    out = {
        "serving_cluster_model_shape": f"D{d_model}xH{heads}xL{max_len}",
        "serving_cluster_requests": n_requests,
        "serving_cluster_tp": tp,
        "serving_cluster_counts": counts,
    }

    goodput, ttft_ms, spreads = {}, {}, {}
    two_replica_set = None
    for n in counts:
        reps = make_replicas(model, params, n, tp=tp, **engine_kw)
        if n == 2:
            two_replica_set = reps
        med, spread = medians(lambda r=reps: Router(
            r, mode="colocated", policy="prefix_aware"))
        goodput[str(n)] = med.get("goodput_tokens_per_sec")
        ttft_ms[str(n)] = round(med.get("ttft_ms_p50") or 0.0, 4)
        spreads[str(n)] = spread if spread is not None else 0.0
    out["serving_cluster_goodput"] = goodput
    out["serving_cluster_ttft_ms"] = ttft_ms
    top = str(max(counts))
    out["serving_cluster_goodput_tokens_per_sec"] = goodput.get(top)
    if goodput.get("1") and goodput.get(top):
        out["serving_cluster_scaling"] = round(
            goodput[top] / goodput["1"], 3)
    if not on_accel:
        out["serving_cluster_spread_pct"] = max(spreads.values())

    # --- disaggregated vs colocated on the SAME 2-replica set
    if two_replica_set is not None:
        try:
            disagg_ms = {"colocated": ttft_ms["2"]}
            disagg_spreads = {"colocated": spreads["2"]}
            med, spread = medians(lambda: Router(
                two_replica_set, mode="disaggregated",
                prefill_replicas=[two_replica_set[0].replica_id]))
            disagg_ms["disaggregated"] = round(
                med.get("ttft_ms_p50") or 0.0, 4)
            disagg_spreads["disaggregated"] = (
                spread if spread is not None else 0.0)
            out["serving_cluster_disagg_ttft_ms"] = disagg_ms
            out["serving_cluster_transfers"] = med["kv_transfer"][
                "transfers"]
            out["serving_cluster_transfer_bytes"] = med["kv_transfer"][
                "bytes"]
            if not on_accel:
                out["serving_cluster_disagg_spread_pct"] = max(
                    disagg_spreads.values())
            if disagg_ms["disaggregated"]:
                out["serving_cluster_disagg_speedup"] = round(
                    disagg_ms["colocated"] / disagg_ms["disaggregated"],
                    3)
            # --- adoption (spread-gated like every serving decision)
            from chainermn_tpu import tuning

            key = serving_decision_key(d_model, heads, max_len)
            tuning.record_measurement(
                "cluster_disagg", key, disagg_ms,
                spreads=None if on_accel else disagg_spreads,
            )
            out["serving_cluster_disagg_selected"] = tuning.choice(
                "cluster_disagg",
                ("colocated", "disaggregated"), key,
            )
        except Exception as e:  # never lose the scaling rows
            out["serving_cluster_disagg_error"] = (
                f"{type(e).__name__}: {e}"[:160])
    if not on_accel:
        out["serving_cluster_note"] = (
            "CPU-proxy honest floor: tiny LM over the virtual-device "
            "mesh — replica scaling and the disagg TTFT ranking hold "
            "for THIS backend; absolute ms is not chip latency"
            + ("" if tp == 2 else
               "; tp=1 (shared device): replicas overlap via async "
               "dispatch only")
        )
    return out


def _bench_serving_burst(comm, on_accel: bool):
    """ISSUE 11: goodput under SLO for bursty OPEN-LOOP traffic —
    monolithic prefill vs chunked prefill vs chunked + SLO policy.

    Seeded Poisson arrivals (open loop: requests are stamped with their
    SCHEDULED arrival time, so a tick that runs long honestly inflates
    queue_wait/TTFT instead of silently slowing the offered load) over
    mixed prompt lengths — short conversational tails plus long
    prompts whose MONOLITHIC prefill freezes every active slot's
    decode for a full forward, the p99 killer chunking exists to fix.

    Every arm serves the identical request set with identical
    per-request TTFT/TPOT targets (calibrated from a monolithic
    warm-up run's medians, so "inside SLO" means "within ~2x/1.5x of
    this box's typical latency" for all three arms alike); goodput =
    generated tokens of requests that finished INSIDE their targets /
    wall. Rows (CPU-proxy convention: median-of-n>=3 + spread; on-accel
    single samples take the seeder's 10% floor):

    1. ``serving_burst_goodput`` / ``serving_burst_ttft_p99_ms`` per
       arm (monolithic / chunked / chunked_slo);
    2. ``serving_burst_chunk_ms`` — ms per SLO-good token at chunk 0
       vs the chunked arm (same admission policy; the SLO arm is a
       scheduler choice, not an engine decision) — adopted as this
       shape's ``prefill_chunk`` decision via ``record_measurement``
       (spread-gated: a noise-band winner is honestly refused and the
       table default 0 stands — the PR 4/5/7/8 precedent).
    """
    import functools

    import jax
    import jax.numpy as jnp
    import numpy as np

    from chainermn_tpu.models.transformer import TransformerLM
    from chainermn_tpu.serving import (
        PREFILL_CHUNKS,
        Request,
        Scheduler,
        ServingEngine,
        serving_decision_key,
    )

    if on_accel:
        layers, d_model, heads, d_ff = 4, 512, 8, 2048
        vocab, max_len, slots = 32000, 512, 8
        block_size, chunk = 32, 64
        n_requests, gen = 24, 24
        long_len, short_len = 256, 8
        mean_gap_s = 0.01
        dtype = jnp.bfloat16
    else:
        layers, d_model, heads, d_ff = 2, 64, 4, 128
        vocab, max_len, slots = 256, 64, 4
        block_size, chunk = 8, 16
        n_requests, gen = 10, 6
        long_len, short_len = 40, 4
        mean_gap_s = 0.002
        dtype = jnp.float32
    model = TransformerLM(
        vocab_size=vocab, num_layers=layers, num_heads=heads,
        d_model=d_model, d_ff=d_ff, max_len=max_len, compute_dtype=dtype,
    )
    params = jax.jit(
        functools.partial(model.init, train=False)
    )(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))

    # Seeded workload: every third request is a LONG prompt (the
    # interference source), the rest short; seeded Poisson inter-arrival
    # gaps. One schedule shared by every arm and repeat.
    rs = np.random.RandomState(17)
    reqs_spec = []
    for i in range(n_requests):
        p_len = long_len if i % 3 == 2 else short_len
        reqs_spec.append(
            (rs.randint(1, vocab, size=p_len).tolist(), gen))
    arrivals = np.cumsum(rs.exponential(scale=mean_gap_s,
                                        size=n_requests)).tolist()

    def drive(engine, policy, targets):
        sched = Scheduler(engine, policy=policy)
        sched.start_window()
        t0 = time.perf_counter()
        i = 0
        rounds = 0
        while i < len(reqs_spec) or not sched.drained:
            now = time.perf_counter() - t0
            while i < len(reqs_spec) and arrivals[i] <= now:
                p, g = reqs_spec[i]
                req = Request(
                    prompt=p, max_new_tokens=g,
                    ttft_target_ms=targets[0] if targets else None,
                    tpot_target_ms=targets[1] if targets else None,
                )
                # open-loop stamp: the SCHEDULED arrival, not "when the
                # loop got around to submitting it"
                req._arrival = t0 + arrivals[i]
                sched.submit(req)
                i += 1
            if not sched.drained:
                sched.tick()
            elif i < len(reqs_spec):
                time.sleep(max(
                    0.0, arrivals[i] - (time.perf_counter() - t0)))
            rounds += 1
            if rounds > 500_000:
                raise RuntimeError("serving_burst runaway loop")
        sched.close_window()
        return sched

    def measure(engine, policy, targets):
        sched = drive(engine, policy, targets)
        s = sched.summary()
        wall = s.get("wall_s") or 1e-9
        good = 0
        for ev in sched.event_window:
            if ev.get("kind") != "serving" or ev.get("phase") != "finish":
                continue
            verdicts = [ev.get(k) for k in ("slo_ttft_ok", "slo_tpot_ok")
                        if ev.get(k) is not None]
            if not verdicts or all(verdicts):
                good += int(ev.get("generated") or 0)
        return {
            "goodput": round(good / wall, 2),
            "ttft_p99_ms": s.get("ttft_ms_p99"),
            "tpot_p99_ms": s.get("tpot_ms_p99"),
            "slo_attainment": s.get("slo_attainment"),
            "preemptions": s.get("preemptions", 0),
        }

    def medians(engine, policy, targets):
        measure(engine, policy, targets)  # compile + warm
        rows = [measure(engine, policy, targets)
                for _ in range(1 if on_accel else 3)]
        rows.sort(key=lambda r: r["goodput"])
        med = rows[len(rows) // 2]
        vals = [r["goodput"] for r in rows]
        spread = None
        if len(rows) > 1 and med["goodput"]:
            spread = round(
                100.0 * (vals[-1] - vals[0]) / med["goodput"], 1)
        return med, spread

    engine_kw = dict(
        num_slots=slots, max_len=max_len, decode_impl="paged",
        kv_block_size=block_size, prefill_buckets=(8, 16),
        spec_tokens=0, prefix_cache="off",
    )
    mono = ServingEngine(model, params, prefill_chunk=0, **engine_kw)
    chunked = ServingEngine(model, params, prefill_chunk=chunk,
                            **engine_kw)

    # Calibrate the shared SLO targets from a WARM monolithic run
    # (first run compiles — calibrating on it would hand every arm a
    # compile-inflated, trivially satisfiable TTFT budget): 2x typical
    # TTFT, 1.5x typical TPOT — identical for every arm.
    drive(mono, "prefill_priority", None)
    cal = drive(mono, "prefill_priority", None).summary()
    ttft_target = 2.0 * (cal.get("ttft_ms_p50") or 10.0)
    tpot_target = 1.5 * (cal.get("tpot_ms_p50")
                         or cal.get("token_ms_p50") or 5.0)
    targets = (ttft_target, tpot_target)

    out = {
        "serving_burst_model_shape": f"D{d_model}xH{heads}xL{max_len}",
        "serving_burst_requests": n_requests,
        "serving_burst_chunk": chunk,
        "serving_burst_ttft_target_ms": round(ttft_target, 4),
        "serving_burst_tpot_target_ms": round(tpot_target, 4),
    }
    arms = (
        ("monolithic", mono, "prefill_priority"),
        ("chunked", chunked, "prefill_priority"),
        ("chunked_slo", chunked, "slo"),
    )
    goodput, ttft99, spreads, extra = {}, {}, {}, {}
    for name, eng, policy in arms:
        med, spread = medians(eng, policy, targets)
        goodput[name] = med["goodput"]
        ttft99[name] = med["ttft_p99_ms"]
        spreads[name] = spread if spread is not None else 0.0
        extra[name] = {"slo_attainment": med["slo_attainment"],
                       "preemptions": med["preemptions"],
                       "tpot_p99_ms": med["tpot_p99_ms"]}
    out["serving_burst_goodput"] = goodput
    out["serving_burst_ttft_p99_ms"] = ttft99
    out["serving_burst_arm_details"] = extra
    if not on_accel:
        # spread keys only for real multi-sample runs; absent = the
        # seeder applies the 10% on-accel noise floor (the serving
        # phases' shared convention)
        out["serving_burst_spread_pct"] = max(spreads.values())

    # --- prefill_chunk adoption: ms per SLO-good token, chunk 0 vs C
    # under the SAME admission policy (the engine decision, isolated
    # from the scheduler-policy choice).
    try:
        from chainermn_tpu import tuning

        if goodput.get("monolithic") and goodput.get("chunked"):
            chunk_ms = {
                "0": round(1000.0 / goodput["monolithic"], 4),
                str(chunk): round(1000.0 / goodput["chunked"], 4),
            }
            chunk_spreads = dict.fromkeys(
                chunk_ms, max(spreads["monolithic"], spreads["chunked"]))
            out["serving_burst_chunk_ms"] = chunk_ms
            key = serving_decision_key(d_model, heads, max_len)
            tuning.record_measurement(
                "prefill_chunk", key, chunk_ms,
                spreads=None if on_accel else chunk_spreads,
            )
            out["serving_burst_selected"] = tuning.choice(
                "prefill_chunk", PREFILL_CHUNKS, key)
            out["serving_burst_chunked_speedup"] = round(
                goodput["chunked"] / goodput["monolithic"], 3)
    except Exception as e:
        out["serving_burst_autotune_error"] = (
            f"{type(e).__name__}: {e}"[:160])
    if not on_accel:
        out["serving_burst_note"] = (
            "CPU-proxy honest floor: tiny LM, ms-scale open-loop gaps "
            "— the goodput ranking holds for THIS backend; absolute "
            "tokens/s is not chip throughput"
        )
    return out


def _bench_serving_sampled(comm, on_accel: bool):
    """ISSUE 18: sampled-traffic serving — the perf stack at
    temperature > 0.

    Before counter-based sampling every sampled request was pinned to
    the slow path (the ctor REJECTED spec_tokens>0 / prefill_chunk>0 /
    seq-parallel prefill at temperature>0); this phase measures what
    lifting the gate bought. One seeded request stream at temperature
    0.7 (per-request seeds fixed, so every arm serves a reproducible
    workload) through three arms sharing decode_impl/block size:

    1. ``plain`` — single-token decode, the pre-ISSUE-18 ceiling;
    2. ``spec`` — speculative decode (n-gram drafting, rejection-rule
       acceptance — docs/serving.md "Sampling");
    3. ``chunked`` — chunked prefill through the mixed step.

    Rows (CPU-proxy convention: median-of-n>=3 + spread):
    ``serving_sampled_tokens_per_sec`` per arm, the sampled spec
    acceptance rate, and a spread-gated ``serving_sampled_selected``
    verdict — 'plain' when no arm clears the noise band (honest
    refusal, the spec_tokens precedent). The verdict is recorded as
    cache EVIDENCE under its own ``sampled_serving`` name (acceptance
    rate + speedup beside the per-arm rows) — it drives NO dispatch
    decision: the greedy ``serving``/``serving_burst`` phases own the
    spec_tokens/prefill_chunk adoption rows, and ISSUE 18's whole
    point is that one decision now covers both modes.
    """
    import functools

    import jax
    import jax.numpy as jnp
    import numpy as np

    from chainermn_tpu.models.transformer import TransformerLM
    from chainermn_tpu.serving import Request, Scheduler, ServingEngine

    if on_accel:
        layers, d_model, heads, d_ff = 4, 512, 8, 2048
        vocab, max_len, slots = 32000, 512, 8
        block_size, chunk, spec_k = 32, 64, 3
        n_requests, gen = 16, 24
        dtype = jnp.bfloat16
    else:
        layers, d_model, heads, d_ff = 2, 64, 4, 128
        vocab, max_len, slots = 256, 64, 4
        block_size, chunk, spec_k = 8, 16, 2
        n_requests, gen = 8, 5
        dtype = jnp.float32
    model = TransformerLM(
        vocab_size=vocab, num_layers=layers, num_heads=heads,
        d_model=d_model, d_ff=d_ff, max_len=max_len, compute_dtype=dtype,
    )
    params = jax.jit(
        functools.partial(model.init, train=False)
    )(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))

    # One seeded workload with FIXED per-request seeds: every arm (and
    # every repeat) samples the identical token streams — counter-based
    # derivation makes throughput comparable across schedules because
    # the work really is the same tokens.
    rs = np.random.RandomState(23)
    reqs_spec = []
    for i in range(n_requests):
        p_len = int(rs.randint(3, 13))
        reqs_spec.append((rs.randint(1, vocab, size=p_len).tolist(),
                          gen, 1000 + i))

    def run_stream(engine):
        sched = Scheduler(engine, policy="prefill_priority")
        for p, g, sd in reqs_spec:
            sched.submit(Request(prompt=p, max_new_tokens=g, seed=sd))
        sched.run()
        return sched.summary()

    def stream_medians(engine):
        run_stream(engine)  # compile + warm every bucket
        summaries = [run_stream(engine)
                     for _ in range(1 if on_accel else 3)]
        summaries.sort(key=lambda s: s["tokens_per_sec"])
        med = summaries[len(summaries) // 2]
        tps = [s["tokens_per_sec"] for s in summaries]
        spread = None
        if len(summaries) > 1 and med["tokens_per_sec"]:
            spread = round(
                100.0 * (tps[-1] - tps[0]) / med["tokens_per_sec"], 1)
        return med, spread

    engine_kw = dict(
        num_slots=slots, max_len=max_len, decode_impl="paged",
        kv_block_size=block_size, prefill_buckets=(8, 16),
        prefix_cache="off", temperature=0.7, base_seed=42,
    )
    arms = (
        ("plain", dict(spec_tokens=0, prefill_chunk=0)),
        ("spec", dict(spec_tokens=spec_k, prefill_chunk=0)),
        ("chunked", dict(spec_tokens=0, prefill_chunk=chunk)),
    )
    out = {
        "serving_sampled_model_shape": f"D{d_model}xH{heads}xL{max_len}",
        "serving_sampled_requests": n_requests,
        "serving_sampled_temperature": 0.7,
    }
    tps, spreads = {}, {}
    accept_rate = None
    for name, kw in arms:
        eng = ServingEngine(model, params, **engine_kw, **kw)
        med, spread = stream_medians(eng)
        tps[name] = med["tokens_per_sec"]
        spreads[name] = spread if spread is not None else 0.0
        if name == "spec":
            sp = med.get("speculation") or {}
            accept_rate = sp.get("accept_rate")
        del eng
    out["serving_sampled_tokens_per_sec"] = tps
    if not on_accel:
        # spread keys only for real multi-sample runs (the serving
        # phases' shared convention; absent = on-accel 10% floor)
        out["serving_sampled_spread_pct"] = max(spreads.values())
    if accept_rate is not None:
        out["serving_sampled_spec_accept_rate"] = accept_rate
    if tps.get("plain"):
        out["serving_sampled_spec_speedup"] = round(
            (tps.get("spec") or 0.0) / tps["plain"], 3)
        # Spread-gated verdict through the registry's own decide rule,
        # recorded as cache evidence under a NON-decision name (no
        # resolve site reads 'sampled_serving' — the greedy phases own
        # the knob adoptions). None = spread-dominated: 'plain' stands,
        # the honest refusal every adoption row uses, and nothing is
        # stored.
        try:
            from chainermn_tpu import tuning
            from chainermn_tpu.serving import serving_decision_key

            key = serving_decision_key(d_model, heads, max_len)
            evidence = {"tokens_per_sec": tps}
            if accept_rate is not None:
                evidence["spec_accept_rate"] = accept_rate
            winner = tuning.record_measurement(
                "sampled_serving", key, tps,
                spreads=None if on_accel else spreads,
                higher_is_better=True,
                extra_evidence=evidence,
            )
            out["serving_sampled_selected"] = winner or "plain"
        except Exception as e:
            out["serving_sampled_autotune_error"] = (
                f"{type(e).__name__}: {e}"[:160])
    if not on_accel:
        out["serving_sampled_note"] = (
            "CPU-proxy honest floor: tiny LM, sampled streams — the "
            "arm ranking holds for THIS backend; absolute tokens/s is "
            "not chip throughput"
        )
    return out


def _bench_serving_decode_kernel(comm, on_accel: bool):
    """ISSUE 19: the fused paged-decode kernel vs the XLA dense-view
    attend — the adoption row for ``decode_attend_impl``.

    One paged engine shape, two arms differing ONLY in the attend read
    (``decode_attend_impl`` is a static model field; the write path is
    byte-identical): prefill every slot to HALF the horizon — the
    regime where the kernel's live-only block reads beat the gather's
    full-table-width habit (tools/byte_audit.py decode prices the HBM
    story) — then time steady-state decode ticks.

    Rows (CPU-proxy convention: median-of-n>=3 + spread):
    ``serving_decode_kernel_ms`` per arm, spread-gated adoption of
    ``decode_attend_impl`` via ``record_measurement``. On CPU the fused
    arm runs the kernel's interpret-mode EMULATION — slower than XLA by
    construction, so the expected CPU verdict is an HONEST REFUSAL (or
    an xla win): the table default stands and only an on-chip capture
    (tools/on_chip_capture.sh runs this phase plus the Mosaic
    compile-check) can flip the decision.
    """
    import functools
    import time

    import jax
    import jax.numpy as jnp

    from chainermn_tpu.models.transformer import TransformerLM
    from chainermn_tpu.serving import (
        DECODE_ATTEND_IMPLS,
        ServingEngine,
        serving_decision_key,
    )

    if on_accel:
        layers, d_model, heads, d_ff = 4, 512, 8, 2048
        vocab, max_len, slots = 32000, 512, 16
        block_size, prompt_len, decode_steps = 32, 256, 32
        dtype = jnp.bfloat16
    else:
        layers, d_model, heads, d_ff = 2, 64, 4, 128
        vocab, max_len, slots = 256, 64, 4
        block_size, prompt_len, decode_steps = 8, 32, 6
        dtype = jnp.float32
    model = TransformerLM(
        vocab_size=vocab, num_layers=layers, num_heads=heads,
        d_model=d_model, d_ff=d_ff, max_len=max_len, compute_dtype=dtype,
    )
    params = jax.jit(
        functools.partial(model.init, train=False)
    )(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    out = {
        "serving_decode_kernel_model_shape":
            f"D{d_model}xH{heads}xL{max_len}",
        "serving_decode_kernel_prompt_len": prompt_len,
    }

    def step_median(attend_impl):
        eng = ServingEngine(
            model, params, num_slots=slots, max_len=max_len,
            decode_impl="paged", decode_attend_impl=attend_impl,
            kv_block_size=block_size, prefill_buckets=(prompt_len,),
            spec_tokens=0,
        )
        for i in range(slots):  # half-horizon histories, full occupancy
            eng.prefill_join([1 + (i + j) % (vocab - 1)
                              for j in range(prompt_len)])

        def sample():
            t0 = time.perf_counter()
            for _ in range(decode_steps):
                eng.decode_step()
            return (time.perf_counter() - t0) / decode_steps * 1000

        sample()  # compile + warm
        return _repeat_median(sample, 1 if on_accel else 3)

    from chainermn_tpu._jax_compat import pallas_paged_decode_supported

    ms, spreads = {}, {}
    ms["xla"], spreads["xla"] = step_median("xla")
    if pallas_paged_decode_supported():
        ms["fused"], spreads["fused"] = step_median("fused")
    else:
        out["serving_decode_kernel_note"] = (
            "fused arm skipped: this jax's Pallas lacks scalar-prefetch "
            "grid specs (the engine's forced:jax-compat fallback)"
        )
    out["serving_decode_kernel_ms"] = {k: round(v, 4)
                                       for k, v in ms.items()}
    if not on_accel:
        # Absent spread key = on-accel single sample; the offline
        # seeder then applies the registry's 10% noise floor.
        out["serving_decode_kernel_spread_pct"] = max(spreads.values())
    if len(ms) == 2:
        out["serving_decode_kernel_fused_speedup"] = round(
            ms["xla"] / ms["fused"], 3) if ms["fused"] else None
        try:
            from chainermn_tpu import tuning

            key = serving_decision_key(d_model, heads, max_len)
            winner = tuning.record_measurement(
                "decode_attend_impl", key, ms,
                spreads=None if on_accel else spreads,
                extra_evidence={"prompt_len": prompt_len,
                                "decode_steps": decode_steps},
            )
            out["serving_decode_kernel_selected"] = tuning.choice(
                "decode_attend_impl", DECODE_ATTEND_IMPLS, key)
        except Exception as e:
            out["serving_decode_kernel_autotune_error"] = (
                f"{type(e).__name__}: {e}"[:120])
    if not on_accel:
        out.setdefault("serving_decode_kernel_note", (
            "CPU proxy runs the kernel in interpret mode (an emulator): "
            "the fused arm losing here says nothing about the chip — "
            "adoption waits for a live capture"
        ))
    return out


def _bench_serving_tenants(comm, on_accel: bool):
    """ISSUE 14: mixed-tenant adapter serving — N tenants' low-rank
    deltas over one base model, Zipf-skewed offered load, shared
    per-tenant system prompts (the namespaced prefix cache's food),
    deficit-round-robin fair-share admission.

    The run is SATURATED and wall-bounded (``max_seconds``) so the
    fairness property is actually exercised: the queue holds a
    Zipf-skewed backlog, and equal-weight DRR admission should serve
    tenants near-evenly regardless — Jain's index over the per-tenant
    served-token totals is the measured verdict, not prose. Rows
    (CPU-proxy convention: median-of-n>=3 + spread):

    1. ``serving_tenants_goodput`` — generated tokens / wall for the
       mixed-tenant gather engine;
    2. ``serving_tenants_fairness`` — Jain over per-tenant served
       tokens (1.0 = perfectly even service under the skewed backlog);
    3. ``serving_tenants_ttft_p99_ms`` — per-tenant p99 TTFT from the
       rollup (details file);
    4. ``serving_tenants_adapter_ms`` — ms per generated token serving
       the DOMINANT tenant's stream set via the gather bank vs a
       merged (weights-folded) engine — adopted as this shape's
       ``adapter_impl`` decision via ``record_measurement``
       (spread-gated: a noise-band winner is honestly refused and the
       table default ``gather`` stands, the PR 4/5/7/8/10 precedent).
    """
    import functools

    import jax
    import jax.numpy as jnp
    import numpy as np

    from chainermn_tpu.models.transformer import TransformerLM
    from chainermn_tpu.observability.stats import jain_index
    from chainermn_tpu.serving import (
        ADAPTER_IMPLS,
        AdapterBank,
        Request,
        Scheduler,
        ServingEngine,
        random_adapter,
        serving_decision_key,
    )

    if on_accel:
        layers, d_model, heads, d_ff = 4, 512, 8, 2048
        vocab, max_len, slots = 32000, 512, 8
        block_size, sys_len, tail_len = 32, 64, 8
        n_tenants, n_requests, gen = 4, 48, 24
        max_seconds = 20.0
        dtype = jnp.bfloat16
    else:
        layers, d_model, heads, d_ff = 2, 64, 4, 128
        vocab, max_len, slots = 256, 64, 4
        block_size, sys_len, tail_len = 8, 16, 4
        # Offered load deliberately exceeds what the wall bound can
        # serve (every tenant's backlog outlives the window on an idle
        # box): the queue stays backlogged for ALL tenants, so the
        # fairness index measures the ADMISSION policy — an FCFS run
        # would reproduce the offered Zipf skew (~0.77), fair-share
        # should push toward 1.0.
        n_tenants, n_requests, gen = 3, 120, 16
        max_seconds = 0.2
        dtype = jnp.float32
    model = TransformerLM(
        vocab_size=vocab, num_layers=layers, num_heads=heads,
        d_model=d_model, d_ff=d_ff, max_len=max_len, compute_dtype=dtype,
    )
    params = jax.jit(
        functools.partial(model.init, train=False)
    )(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))

    tenants = [f"tenant{i}" for i in range(n_tenants)]
    bank = AdapterBank(model, capacity=n_tenants + 1, rank=2)
    for i, t in enumerate(tenants):
        bank.register(t, random_adapter(model, 2, seed=100 + i,
                                        scale=0.5))
    weights = {t: 1.0 for t in tenants}

    # Zipf-skewed offered load over a shared per-tenant system prompt
    # plus a unique tail — one seeded schedule for every repeat/arm.
    rs = np.random.RandomState(23)
    sys_prompts = {t: rs.randint(1, vocab, size=sys_len).tolist()
                   for t in tenants}
    zipf_w = np.array([1.0 / (i + 1) ** 1.2 for i in range(n_tenants)])
    zipf_w /= zipf_w.sum()
    order = rs.choice(n_tenants, size=n_requests, p=zipf_w)
    reqs_spec = [
        (tenants[int(i)],
         sys_prompts[tenants[int(i)]]
         + rs.randint(1, vocab, size=tail_len).tolist())
        for i in order
    ]

    engine = ServingEngine(
        model, params, num_slots=slots, max_len=max_len,
        decode_impl="paged", kv_block_size=block_size,
        prefill_buckets=(8, 16, 32), spec_tokens=0, prefix_cache="on",
        min_shared_blocks=1, prefill_chunk=0,
        prefill_seq_parallel="off", adapter_bank=bank,
        adapter_impl="gather",
    )

    def run_mixed(bound, fair: bool = True):
        sched = Scheduler(engine, policy="prefill_priority",
                          tenant_weights=dict(weights) if fair
                          else None)
        for t, p in reqs_spec:
            sched.submit(Request(prompt=p, max_new_tokens=gen,
                                 tenant_id=t))
        sched.run(max_seconds=bound)
        s = sched.summary()
        # The wall bound leaves work in flight by design (saturation);
        # release the engine's slots so the next repeat starts from a
        # clean array instead of raising on a full engine.
        for slot in range(engine.num_slots):
            if engine._active[slot]:
                engine.leave(slot)
        wall = s.get("wall_s") or 1e-9
        per_tenant = {
            t: row["generated_tokens"]
            for t, row in (s.get("tenants") or {}).items()
        }
        fairness = jain_index([
            per_tenant.get(t, 0) / weights[t] for t in tenants
        ])
        return {
            "goodput": round((s.get("generated_tokens") or 0) / wall, 2),
            "fairness": round(fairness, 4) if fairness is not None
            else None,
            "ttft_p99": {t: (s.get("tenants") or {}).get(
                t, {}).get("ttft_ms_p99") for t in tenants},
        }

    run_mixed(max_seconds)  # compile + trie warm
    rows = [run_mixed(max_seconds) for _ in range(1 if on_accel else 3)]
    rows.sort(key=lambda r: r["goodput"])
    med = rows[len(rows) // 2]
    vals = [r["goodput"] for r in rows]
    spread = None
    if len(rows) > 1 and med["goodput"]:
        spread = round(100.0 * (vals[-1] - vals[0]) / med["goodput"], 1)

    # The FCFS contrast row: same backlog, fair share off — the
    # fairness delta is the admission policy's measured contribution.
    fifo = run_mixed(max_seconds, fair=False)

    out = {
        "serving_tenants_model_shape": f"D{d_model}xH{heads}xL{max_len}",
        "serving_tenants_n": n_tenants,
        "serving_tenants_requests": n_requests,
        "serving_tenants_goodput": med["goodput"],
        "serving_tenants_fairness": med["fairness"],
        "serving_tenants_fairness_fifo": fifo["fairness"],
        "serving_tenants_ttft_p99_ms": med["ttft_p99"],
    }
    if not on_accel and spread is not None:
        out["serving_tenants_spread_pct"] = spread

    # --- adapter_impl adoption: ms per generated token serving the
    # DOMINANT tenant's streams — the per-slot gather vs the folded
    # weights (the single-tenant-dominant question the decision asks).
    try:
        from chainermn_tpu import tuning

        dom = tenants[0]
        dom_reqs = [p for t, p in reqs_spec if t == dom][:slots + 2]

        def run_dominant(eng):
            sched = Scheduler(eng, policy="prefill_priority")
            for p in dom_reqs:
                sched.submit(Request(prompt=p, max_new_tokens=gen,
                                     tenant_id=dom))
            sched.run()
            s = sched.summary()
            toks = s.get("generated_tokens") or 1
            return (s.get("wall_s") or 1e-9) / toks * 1e3

        merged_eng = ServingEngine(
            model, params, num_slots=slots, max_len=max_len,
            decode_impl="paged", kv_block_size=block_size,
            prefill_buckets=(8, 16, 32), spec_tokens=0,
            prefix_cache="on", min_shared_blocks=1, prefill_chunk=0,
            prefill_seq_parallel="off", adapter_bank=bank,
            adapter_impl="merged", merged_tenant=dom,
        )
        arm_ms = {"gather": [], "merged": []}
        run_dominant(engine)
        run_dominant(merged_eng)  # compile both before timing
        for _ in range(1 if on_accel else 3):
            arm_ms["gather"].append(run_dominant(engine))
            arm_ms["merged"].append(run_dominant(merged_eng))
        med_ms = {}
        arm_spreads = {}
        for name, samples in arm_ms.items():
            samples.sort()
            m = samples[len(samples) // 2]
            med_ms[name] = round(m, 4)
            arm_spreads[name] = (
                round(100.0 * (samples[-1] - samples[0]) / m, 1)
                if len(samples) > 1 and m else 0.0)
        out["serving_tenants_adapter_ms"] = med_ms
        # The gather/merged arms' OWN spread, not the mixed-run goodput
        # spread (review finding: the offline seed gated adapter_impl
        # on serving_tenants_spread_pct, a different measurement — the
        # live adoption below and a re-seed from this row could
        # disagree on identical data).
        if not on_accel:
            out["serving_tenants_adapter_spread_pct"] = max(
                arm_spreads.values())
        key = serving_decision_key(d_model, heads, max_len)
        tuning.record_measurement(
            "adapter_impl", key, med_ms,
            spreads=None if on_accel else {
                k: max(arm_spreads.values()) for k in med_ms},
        )
        out["serving_tenants_selected"] = tuning.choice(
            "adapter_impl", ADAPTER_IMPLS, key)
        out["serving_tenants_merged_speedup"] = round(
            med_ms["gather"] / med_ms["merged"], 3)
    except Exception as e:
        out["serving_tenants_autotune_error"] = (
            f"{type(e).__name__}: {e}"[:160])
    if not on_accel:
        out["serving_tenants_note"] = (
            "CPU-proxy honest floor: tiny LM + rank-2 adapters — the "
            "fairness index and the gather/merged ranking hold for "
            "THIS backend; absolute tokens/s is not chip throughput"
        )
    return out


def _bench_native_input(comm, on_accel: bool):
    """Real-input-pipeline throughput (VERDICT r2 item 6): the jitted
    ResNet step fed by the C++ threaded prefetch loader
    (``native/data_loader.py`` — the reference's MultiprocessIterator role,
    ``examples/imagenet/train_imagenet.py`` (dagger)) plus
    ``prefetch_to_device`` double buffering, vs device-resident synthetic
    arrays.

    Methodology (round-3 finding): on the tunnelled TPU platform, the
    FIRST device→host readback permanently degrades subsequent large
    host→device transfers in that process from ~25 ms to ~2–4 s per 19 MB
    batch (the transport appears to fall back to a synchronous per-chunk
    protocol; measured: idle H2D 24 ms, H2D after one scalar fetch 2.0 s,
    no recovery after 3.5 s sleep). Any in-process loop that syncs per
    step therefore measures the tunnel pathology, not the input pipeline
    (round-2's 14 img/s row). Fix: run the end-to-end loop in FRESH
    subprocesses that perform no D2H until after the timed region, at two
    step counts, and difference the timings — setup, compile, and warmup
    backlog cancel; the difference is pure steady-state input+step time.
    Real (non-tunnelled) TPU hosts do not exhibit the degradation; there
    the simple in-process loop and this differenced measurement agree."""
    import os
    import tempfile

    import numpy as np

    from chainermn_tpu.native.data_loader import (
        NativeDataLoader,
        write_fixed_records,
    )

    # steps_small must exceed the total buffering depth (loader prefetch=4
    # + prefetch_to_device=2 = 6): a shorter timed region can be served
    # entirely from buffers filled during the untimed warmup/compile,
    # which would bias the difference toward pure loader time.
    steps_small, steps_big = (8, 24) if on_accel else (8, 16)
    step, state, (x_syn, y_syn), batch, _, _ = _resnet_setup(comm, on_accel)
    hw = x_syn.shape[1]

    # A few batches of records; the loader loops epochs, which is fine for
    # a throughput measurement (shuffle order changes per epoch).
    n_records = batch * 4
    rng = np.random.default_rng(0)
    images = rng.integers(0, 256, size=(n_records, hw, hw, 3), dtype=np.uint8)
    labels = rng.integers(0, 10, size=(n_records,)).astype(np.int32)
    fd, path = tempfile.mkstemp(suffix=".bin", prefix="bench_records_")
    os.close(fd)
    write_fixed_records(path, images, labels)
    out = {}
    try:
        # Host-side loader throughput alone (no JAX involvement): the
        # number that isolates the C++ reader+shuffle+batch assembly.
        # Timed from COLD construction so every consumed batch was
        # produced inside the timed window — no assumption about queue
        # fill state (a warm-up batch would make up to `prefetch` timed
        # batches free only in the producer-bound regime, biasing the
        # rate by an amount that depends on which side is faster).
        # Thread spin-up is inside the window; reps amortise it.
        reps = 24 if on_accel else 12
        t0 = time.perf_counter()
        loader = NativeDataLoader(
            path,
            [("image", np.uint8, (hw, hw, 3)), ("label", np.int32, ())],
            batch_size=batch, threads=4, prefetch=4,
        )
        try:
            for _ in range(reps):
                next(loader)
            dt_host = (time.perf_counter() - t0) / reps
        finally:
            loader.close()
        out["native_loader_host_images_per_sec"] = round(batch / dt_host, 2)

        # Synthetic comparison in THIS process (device-resident inputs —
        # no H2D in the loop, so the tunnel quirk cannot bite). Before
        # the child phase: it does not depend on the children and must
        # survive their failure.
        syn_steps = 12 if on_accel else 3
        state, m = step(state, (x_syn, y_syn))
        _fetch_scalar(m["loss"])
        t0 = time.perf_counter()
        for _ in range(syn_steps):
            state, m = step(state, (x_syn, y_syn))
        _fetch_scalar(m["loss"])
        dt_syn = (time.perf_counter() - t0) / syn_steps
        out["synthetic_images_per_sec"] = round(batch / dt_syn, 2)
        # Method marker set as soon as any new-method row exists: it is
        # what _purge_retired keys on, and must survive a child-phase
        # failure or the valid synthetic row above would be purged from
        # the carry cache as an old-method artifact.
        out["native_input_method"] = (
            f"fresh-process differenced ({steps_big}-{steps_small} "
            "steps), prefetch_to_device(2), no mid-loop D2H"
        )

        # End-to-end: two fresh child processes, differenced. Reuses
        # _run_child so the subprocess contract (timeout handling, error
        # tails, JSON-line parsing) lives in one place.
        def child(steps: int) -> float:
            env = dict(os.environ)
            env.update(
                CMN_NATIVE_STEPS=str(steps),
                CMN_NATIVE_RECORDS=path,
                CMN_NATIVE_HW=str(hw),
                CMN_NATIVE_BATCH=str(batch),
                CMN_NATIVE_ACCEL="1" if on_accel else "0",
            )
            r, err = _run_child(
                "native-loop", 300 if on_accel else 180, env=env
            )
            if r is None or "wall_s" not in r:
                raise RuntimeError(err or "native-loop child: no wall_s")
            return float(r["wall_s"])

        # The tunnel flaps on minute scales (r3: a child hung at backend
        # init minutes after its sibling succeeded). ONE spaced retry
        # total across both children rescues the row without starving the
        # benchmarks that run after this one.
        retries_left = 1

        def child_retry(steps: int) -> float:
            nonlocal retries_left
            try:
                return child(steps)
            except Exception:
                if retries_left <= 0:
                    raise
                retries_left -= 1
                time.sleep(20)
                return child(steps)

        # The child phase rolls the tunnel-flap dice twice; a failure
        # there must not discard the host-side row already measured.
        try:
            t_small = child_retry(steps_small)
            t_big = child_retry(steps_big)
        except Exception as e:
            out["native_input_error"] = (
                f"child phase: {type(e).__name__}: {e}"[:200]
            )
            return out
        dt_loader = (t_big - t_small) / (steps_big - steps_small)
        if dt_loader <= 0:
            out["native_input_error"] = (
                f"non-positive differenced step time ({t_big:.2f}s @ "
                f"{steps_big} vs {t_small:.2f}s @ {steps_small})"
            )
            return out

        out.update({
            "native_input_images_per_sec": round(batch / dt_loader, 2),
            "input_pipeline_overhead_pct": round(
                (dt_loader / dt_syn - 1) * 100, 1
            ),
        })
        return out
    finally:
        try:
            os.remove(path)
        except OSError:
            pass


def _run_native_loop() -> None:
    """Child mode for ``_bench_native_input``: run N end-to-end steps
    (C++ loader → device prefetch → jitted ResNet step) with NO device→
    host transfer between warmup and the final sync, and print the wall
    time of the timed region. See the parent's docstring for why."""
    import numpy as np

    steps = int(os.environ["CMN_NATIVE_STEPS"])
    path = os.environ["CMN_NATIVE_RECORDS"]
    hw = int(os.environ["CMN_NATIVE_HW"])
    batch = int(os.environ["CMN_NATIVE_BATCH"])
    on_accel = os.environ.get("CMN_NATIVE_ACCEL") == "1"

    import jax
    import jax.numpy as jnp

    from chainermn_tpu import create_communicator
    from chainermn_tpu.native.data_loader import NativeDataLoader
    from chainermn_tpu.training.prefetch import prefetch_to_device

    comm = create_communicator("xla")
    step, state, (x_syn, _), _, _, _ = _resnet_setup(comm, on_accel)
    dtype = x_syn.dtype
    del x_syn

    loader = NativeDataLoader(
        path,
        [("image", np.uint8, (hw, hw, 3)), ("label", np.int32, ())],
        batch_size=batch, threads=4, prefetch=4,
    )
    # u8 over H2D (4x fewer bytes than f32); normalisation on-device.
    norm = jax.jit(
        lambda img: img.astype(dtype) / jnp.asarray(127.5, dtype) - 1.0
    )

    def batches():
        for b in loader:
            yield b["image"], b["label"]

    try:
        it = prefetch_to_device(batches(), size=2)

        def fetch():
            img, lab = next(it)
            return norm(img), lab

        # Warmup: compiles (synchronously, on host) and seeds the device
        # pipeline. Crucially NO _fetch_scalar here — the first D2H would
        # poison every subsequent H2D on the tunnelled platform.
        for _ in range(2):
            state, m = step(state, fetch())

        t0 = time.perf_counter()
        for _ in range(steps):
            state, m = step(state, fetch())
        _fetch_scalar(m["loss"])  # the one true sync, ends the region
        wall = time.perf_counter() - t0
        print(json.dumps({"wall_s": wall, "steps": steps, "batch": batch}),
              flush=True)
    finally:
        loader.close()


def _transformer_setup(comm, on_accel: bool, steps: int | None = None,
                       interpret: bool | None = None,
                       abstract_params: bool = False):
    """Shared transformer workload definition (bench + byte audit): one
    place owns the model config, knobs, loss, and jitted step so the
    roofline audit (``tools/byte_audit.py``) cannot drift from what the
    bench times — the same rule `_resnet_setup` enforces for the ResNet
    variants. Returns ``(fn, args, B, T, steps, model, cfg,
    knob_fields, n_chunks)`` with ``fn`` the un-lowered jitted step and
    ``args = (params, opt_state, tokens)``. ``interpret`` overrides the
    flash-kernel interpret mode (default: interpret off accelerator) —
    the audit compiles the LM-SCALE config on CPU and needs both.
    ``abstract_params=True`` builds zero params from ``eval_shape`` (no
    forward executed) — for AOT-compile-only consumers like the byte
    audit, where a real interpret-mode init at LM scale would dominate
    wall time producing values nobody reads."""
    import jax
    import jax.numpy as jnp
    import optax
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    from chainermn_tpu import create_multi_node_optimizer
    from chainermn_tpu.models import TransformerLM, lm_loss_fused
    from chainermn_tpu.ops.flash_attention import flash_attention

    knob_fields = {}
    use_db = True  # CPU-proxy config keeps the baseline-faithful default
    if on_accel:
        # LM-scale config (VERDICT r2 item 3): 8L / d1024 / 16H / ff4096,
        # T=2048 — ~134M params incl. the 32k tied embedding. Perf knobs
        # adoptable from the sweep's winner without a code edit
        # (examples/transformer/sweep_mfu.py); MFU here uses MODEL flops
        # (6P/token), so remat granularity never inflates it. Non-default
        # knob values are recorded in the artifact.
        remat_mode = os.environ.get("CHAINERMN_BENCH_TF_REMAT", "dots")
        if remat_mode not in ("none", "dots", "nothing"):
            raise ValueError(
                "CHAINERMN_BENCH_TF_REMAT must be none|dots|nothing, "
                f"got {remat_mode!r}"
            )
        B = int(os.environ.get("CHAINERMN_BENCH_TF_BATCH", "16"))
        n_chunks = int(os.environ.get("CHAINERMN_BENCH_TF_CHUNKS", "16"))
        # Head GEOMETRY at fixed d_model: H16xD64 (the classic -base
        # split) vs H8xD128. Identical params and model FLOPs — the qkv
        # projections are d_model x d_model either way — but D=64 head
        # tiles fill only half the 128-wide MXU contraction / VMEM lane
        # dim, so D=128 is the hardware-shaped split. Sweepable so the
        # capture measures rather than asserts the difference.
        n_heads = int(os.environ.get("CHAINERMN_BENCH_TF_HEADS", "16"))
        if n_heads < 1 or 1024 % n_heads:
            raise ValueError(
                f"CHAINERMN_BENCH_TF_HEADS must divide 1024, got {n_heads}"
            )
        # Double buffering is part of the BASELINE workload identity
        # ("Transformer-base LM, double-buffered allreduce"), hence the
        # default — but on ONE chip there is no collective to overlap
        # and the bank carry is pure cost (micro row: 0.85x), so the
        # sweep measures both and the knob records which ran.
        db_env = os.environ.get("CHAINERMN_BENCH_TF_DB", "true").lower()
        if db_env not in ("true", "false"):
            raise ValueError(
                f"CHAINERMN_BENCH_TF_DB must be true|false, got {db_env!r}"
            )
        use_db = db_env == "true"
        T = 2048
        if steps is None:
            steps = 10
        model = TransformerLM(
            num_layers=8, d_model=1024, num_heads=n_heads, d_ff=4096,
            max_len=2048, remat=remat_mode != "none",
            remat_policy="dots" if remat_mode != "nothing" else "nothing",
            return_hidden=True,
        )
        cfg = "8L-d1024-ff4096-v32k"
        # ALWAYS recorded (defaults included) so the carried-result
        # machinery compares like with like — same rule as the ResNet
        # knobs.
        knob_fields = {"tf_remat": remat_mode, "tf_batch": B,
                       "tf_chunks": n_chunks, "tf_heads": n_heads,
                       "tf_db": use_db}
    else:
        B, T = 2, 128
        if steps is None:
            steps = 2
        model = TransformerLM(vocab_size=512, num_layers=2, d_model=64,
                              d_ff=128, max_len=256, return_hidden=True)
        n_chunks = 2
        cfg = "tiny-cpu-proxy"
    if interpret is None:
        interpret = not on_accel

    def attn(q, k, v, *, causal, scale):
        return flash_attention(q, k, v, causal=causal, scale=scale,
                               interpret=interpret)

    model = model.clone(attention_fn=attn)
    B *= comm.size
    tokens = jax.random.randint(
        jax.random.PRNGKey(0), (B, T), 0, model.vocab_size
    )
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        tokens = multihost_utils.host_local_array_to_global_array(
            tokens, comm.mesh, P()
        )
    if abstract_params:
        params = jax.tree.map(
            lambda s: jnp.zeros(s.shape, s.dtype),
            jax.eval_shape(
                lambda k, t: model.init(k, t, train=True),
                jax.random.PRNGKey(1), tokens[:2],
            ),
        )
    else:
        params = jax.jit(
            lambda k, t: model.init(k, t, train=True)
        )(jax.random.PRNGKey(1), tokens[:2])
    opt = create_multi_node_optimizer(
        optax.adam(1e-4), comm, double_buffering=use_db,
        allreduce_grad_dtype=jnp.bfloat16,
    )
    axes = comm.grad_axes

    def loss_fn(p, tok):
        hidden = model.apply(p, tok, train=True)
        emb = p["params"]["tok_emb"]["embedding"]
        return lm_loss_fused(hidden, emb, tok, n_chunks=n_chunks)

    def local(params, opt_state, tok):
        def one(carry, _):
            params, opt_state = carry
            loss, grads = jax.value_and_grad(loss_fn)(params, tok)
            updates, opt_state = opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return (params, opt_state), loss

        (params, opt_state), losses = jax.lax.scan(
            one, (params, opt_state), None, length=steps
        )
        return losses[-1]

    fn = jax.jit(
        shard_map(local, mesh=comm.mesh,
                  in_specs=(P(), P(), P(axes)),
                  out_specs=P(), check_vma=False)
    )
    opt_state = opt.init(params)
    return (fn, (params, opt_state, tokens), B, T, steps, model, cfg,
            knob_fields, n_chunks)


def _bench_transformer(comm, on_accel: bool):
    """Transformer LM tokens/sec + MFU — the remaining BASELINE.json config
    ("Transformer-base LM — large embedding grads, double-buffered
    allreduce"): full train step (fwd + bwd + bf16 grad pmean + adam) with
    the flash-attention kernel, double buffering, per-block remat
    (dots-saveable policy) and the fused chunked LM head
    (``lm_loss_fused`` — the [B,T,vocab] logits tensor never hits HBM).
    MFU uses MODEL flops (6P/token + attention), not cost analysis —
    see the note at the bottom of this function."""
    import jax

    (fn, (params, opt_state, tokens), B, T, steps, model, cfg,
     knob_fields, n_chunks) = _transformer_setup(comm, on_accel)

    try:
        fn = fn.lower(params, opt_state, tokens).compile()
    except Exception:
        pass

    _fetch_scalar(fn(params, opt_state, tokens))  # compile + warm

    def sample():
        t0 = time.perf_counter()
        _fetch_scalar(fn(params, opt_state, tokens))
        return (time.perf_counter() - t0) / steps

    dt, tf_spread = _repeat_median(sample, 1 if on_accel else 3)

    # MFU uses MODEL flops (the PaLM-appendix convention): 6P per token for
    # the matmul stack + 6·L·T·d for causal attention fwd+bwd. Remat
    # recomputation deliberately NOT counted — that's the price paid, not
    # useful work. (XLA's cost analysis, which does count it, is reported
    # separately as hardware utilisation.)
    n_params = sum(x.size for x in jax.tree.leaves(params))
    model_flops_per_token = (
        6 * n_params
        + 6 * model.num_layers * T * model.d_model
    )
    model_step_flops = model_flops_per_token * B * T / comm.size  # per device

    out = {
        "transformer_tokens_per_sec": round(B * T / dt, 1),
        "transformer_step_ms": round(dt * 1e3, 2),
        "transformer_params_m": round(n_params / 1e6, 1),
        "transformer_config": (
            f"{cfg} B{B}xT{T} flash"
            + ("+double-buffer" if knob_fields.get("tf_db", True) else "")
            + (f"+remat[{model.remat_policy}]" if model.remat else "")
            + "+fused-head"
        ),
        **knob_fields,
    }
    if not on_accel:
        out["transformer_proxy_spread_pct"] = tf_spread
    peak = _peak_flops(jax.devices()[0].device_kind)
    if peak:
        out["transformer_mfu"] = round(model_step_flops / dt / peak, 4)
        out["transformer_model_tflops_per_step"] = round(
            model_step_flops / 1e12, 3
        )
        # NOTE: XLA's cost_analysis() does not multiply flops by the
        # scan/while trip count, so a per-step "hardware utilisation"
        # derived from it under the 10-step scan is meaningless (r3
        # measured 0.024 against a model-flops MFU of 0.35). The ResNet
        # rows are unaffected (no scan around the timed step there).
    return out


def _bench_double_buffering(comm, on_accel: bool):
    """Measured (not asserted) double-buffering overlap: step time of a
    communication-heavy MLP with ``double_buffering`` off vs on (VERDICT
    round-1 weak item 6 — the overlap claim needs a number behind it).

    On a single chip the grad psum is a no-op, so the honest expectation is
    a ratio ~1.0; the metric carries ``n_devices`` context and becomes
    meaningful on a real multi-chip mesh, where overlap hides the allreduce
    behind the next step's backward (staleness-1, the reference's
    ``_DoubleBufferingOptimizer``, SURVEY.md §2.3)."""
    import jax
    import jax.numpy as jnp
    import optax
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    from chainermn_tpu import create_multi_node_optimizer

    width = 4096 if on_accel else 256
    layers = 4
    batch = 8 * comm.size
    steps = 20 if on_accel else 3
    rng = jax.random.PRNGKey(0)
    params = [
        jax.random.normal(jax.random.fold_in(rng, i),
                          (width, width), jnp.float32) * 0.02
        for i in range(layers)
    ]
    x = jax.random.normal(rng, (batch, width), jnp.bfloat16)
    axes = comm.grad_axes

    def time_variant(double_buffering: bool) -> float:
        opt = create_multi_node_optimizer(
            optax.sgd(1e-3), comm, double_buffering=double_buffering,
            allreduce_grad_dtype=jnp.bfloat16,
        )

        def local(params, opt_state, xb):
            def one_step(carry, _):
                params, opt_state = carry

                def loss_fn(ps):
                    h = xb
                    for w in ps:
                        h = jnp.tanh(h @ w.astype(jnp.bfloat16))
                    return jnp.sum(h.astype(jnp.float32) ** 2)

                grads = jax.grad(loss_fn)(params)
                updates, opt_state = opt.update(grads, opt_state, params)
                params = optax.apply_updates(params, updates)
                return (params, opt_state), ()

            (params, opt_state), _ = jax.lax.scan(
                one_step, (params, opt_state), None, length=steps
            )
            return params

        fn = jax.jit(
            shard_map(local, mesh=comm.mesh,
                      in_specs=(P(), P(), P(axes)),
                      out_specs=P(), check_vma=False)
        )
        opt_state = opt.init(params)
        flops = None
        try:
            compiled = fn.lower(params, opt_state, x).compile()
            a = compiled.cost_analysis()
            a = a[0] if isinstance(a, (list, tuple)) else a
            flops = float(a.get("flops", 0.0)) or None
            fn = compiled
        except Exception:
            pass
        _fetch_scalar(fn(params, opt_state, x)[0][:1, :1])  # compile+warm

        def sample():
            t0 = time.perf_counter()
            _fetch_scalar(fn(params, opt_state, x)[0][:1, :1])
            return (time.perf_counter() - t0) / steps * 1000

        # The RATIO row is the one that drifted round-to-round (1.034x
        # r3 -> 0.876x r4 on the CPU proxy): median-of-3 on both
        # variants, chip included — each sample is one scan-fused call.
        med, spread = _repeat_median(sample, 3)
        return med, flops, spread

    plain, flops_p, spread_p = time_variant(False)
    buffered, flops_b, spread_b = time_variant(True)
    out = {
        "double_buffer_step_ms": round(buffered, 3),
        "plain_step_ms": round(plain, 3),
        "double_buffer_speedup": round(plain / buffered, 3),
        "double_buffer_spread_pct": max(spread_p, spread_b),
        "double_buffer_note": (
            (
                "single-chip: NO collective to overlap (psum is a no-op), "
                "so a ratio < 1.0 is the EXPECTED cost of carrying the "
                "grad-sized bank through the scan, and a >1.0 reading is a "
                "critical-path effect (the stale update decouples from the "
                "current backward), NOT collective overlap — flops_ratio "
                "1.0 certifies no work was eliminated. Enable double "
                "buffering only when a real inter-chip allreduce sits on "
                "the critical path (multi-host DCN); see "
                "docs/benchmarks.md and the structural independence test "
                "in tests/test_optimizer.py"
            )
            if comm.size == 1 else ""
        ),
    }
    if flops_p and flops_b:
        # 1.0 == both programs do the same work; the speedup is schedule,
        # not dead-code elimination.
        out["double_buffer_flops_ratio"] = round(flops_p / flops_b, 4)
    # Adopt the on/off step times as this backend's double_buffering
    # advisory record (the optimizer wrapper warns from it when the
    # flag is enabled where it measures as a loss).
    try:
        from chainermn_tpu import tuning

        key = tuning.decision_key(shape=(comm.size,), dtype="step")
        tuning.record_measurement(
            "double_buffering", key, {"on": buffered, "off": plain},
            spreads={"on": spread_b, "off": spread_p},
        )
        out["double_buffering_selected"] = tuning.choice(
            "double_buffering", ("on", "off"), key
        )
    except Exception as e:
        out["double_buffer_autotune_error"] = (
            f"{type(e).__name__}: {e}"[:120]
        )
    return out


def _bench_overlap(comm, on_accel: bool):
    """ISSUE 3: the reduction-SCHEDULE comparison and the overlap
    hidden-comm fraction, measured (CPU-proxy convention: median-of-n>=3
    + spread — a delta inside the spread is noise).

    Three measurements over one comm-heavy MLP workload (the
    double-buffer bench's shape family):

    1. step time per reduction schedule (flat / two_level / zero, all
       equivalence-tested) — adopted into the tuning cache as this
       topology's ``reduction_schedule`` decision, so the optimizer's
       ``'auto'`` resolves from evidence (provenance reported);
    2. overlap off vs on at the chosen schedule plus a no-collective
       compute-only baseline: ``hidden_comm_fraction`` =
       (plain - overlapped) / (plain - compute_only), clamped to [0,1]
       — the share of the wire the staleness-1 mode hid behind compute;
    3. the eager per-bucket driver
       (:class:`chainermn_tpu.parallel.reduction_schedule.OverlappedBucketReducer`):
       dispatch -> interleaved compute -> collect, with per-bucket wire
       events (dur vs blocked) — the measured fraction lands in the
       trace and is summarized here from the same events
       ``tools/trace_report.py``'s overlap section reads."""
    import jax
    import jax.numpy as jnp
    import optax
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    from chainermn_tpu import create_multi_node_optimizer
    from chainermn_tpu.observability import trace as obs_trace
    from chainermn_tpu.parallel.reduction_schedule import (
        DECISION as _SCHED_DECISION,
        OverlappedBucketReducer,
        SCHEDULES,
    )

    width = 2048 if on_accel else 192
    layers = 3
    batch = 8 * comm.size
    steps = 16 if on_accel else 3
    rng = jax.random.PRNGKey(0)
    params = [
        jax.random.normal(jax.random.fold_in(rng, i),
                          (width, width), jnp.float32) * 0.02
        for i in range(layers)
    ]
    x = jax.random.normal(rng, (batch, width), jnp.bfloat16)
    axes = comm.grad_axes
    payload_bytes = sum(p.size * 4 for p in params)

    def time_loop(opt, opt_spec, out_spec):
        def local(params, opt_state, xb):
            def one(carry, _):
                params, opt_state = carry

                def loss_fn(ps):
                    h = xb
                    for w in ps:
                        h = jnp.tanh(h @ w.astype(jnp.bfloat16))
                    return jnp.sum(h.astype(jnp.float32) ** 2)

                grads = jax.grad(loss_fn)(params)
                updates, opt_state = opt.update(grads, opt_state, params)
                params = optax.apply_updates(params, updates)
                return (params, opt_state), ()

            (params, opt_state), _ = jax.lax.scan(
                one, (params, opt_state), None, length=steps
            )
            return params

        fn = jax.jit(
            shard_map(local, mesh=comm.mesh,
                      in_specs=(P(), opt_spec, P(axes)),
                      out_specs=out_spec, check_vma=False)
        )
        opt_state = opt.init(params)
        _fetch_scalar(fn(params, opt_state, x)[0][:1, :1])  # compile+warm

        def sample():
            t0 = time.perf_counter()
            _fetch_scalar(fn(params, opt_state, x)[0][:1, :1])
            return (time.perf_counter() - t0) / steps * 1000

        return _repeat_median(sample, 3)

    # --- 1. schedule comparison, adopted as the dispatch decision
    sched_ms: dict = {}
    spreads: dict = {}
    for sched in SCHEDULES:
        opt = create_multi_node_optimizer(
            optax.sgd(1e-3), comm, allreduce_grad_dtype=jnp.bfloat16,
            reduction_schedule=sched,
        )
        med, spread = time_loop(opt, opt.opt_state_spec(), P())
        sched_ms[sched] = round(med, 3)
        spreads[sched] = spread
    out = {
        "overlap_schedule_ms": sched_ms,
        "overlap_schedule_spread_pct": max(spreads.values()),
        # Key material for offline seeding (tuning.cache must rebuild
        # the exact decision key the 'auto' resolution will ask for).
        "overlap_world_shape": [int(v) for v in comm.mesh.shape.values()],
        "overlap_payload_mb": max(1, payload_bytes >> 20),
    }
    selected = "flat"
    try:
        from chainermn_tpu import tuning

        key = tuning.decision_key(
            shape=tuple(int(v) for v in comm.mesh.shape.values())
            + (max(1, payload_bytes >> 20),),
            dtype="sched",
        )
        tuning.record_measurement(
            _SCHED_DECISION, key, sched_ms, spreads=spreads
        )
        selected = tuning.choice(_SCHED_DECISION, SCHEDULES, key)
        out["reduction_schedule_selected"] = selected
        rec = [d for d in tuning.decisions_taken()
               if d["name"] == _SCHED_DECISION and d["key"] == key]
        if rec:
            out["reduction_schedule_source"] = rec[-1]["source"]
    except Exception as e:
        out["overlap_autotune_error"] = f"{type(e).__name__}: {e}"[:120]

    # --- 2. hidden-comm fraction: compute-only vs plain vs overlapped.
    # Compute-only runs the inner optimizer on UN-reduced grads (per-
    # shard params returned sharded — identical FLOPs, zero collective).
    compute_ms, sp_c = time_loop(optax.sgd(1e-3), P(), P(axes))
    plain_opt = create_multi_node_optimizer(
        optax.sgd(1e-3), comm, allreduce_grad_dtype=jnp.bfloat16,
        reduction_schedule=selected,
    )
    # opt_state_spec(), not P(): a 'zero' winner carries sharded state.
    plain_ms, sp_p = time_loop(plain_opt, plain_opt.opt_state_spec(), P())
    db_ms, sp_d = time_loop(
        create_multi_node_optimizer(
            optax.sgd(1e-3), comm, allreduce_grad_dtype=jnp.bfloat16,
            reduction_schedule=(None if selected == "zero" else selected),
            double_buffering=True,
        ), P(), P(),
    )
    out.update({
        "overlap_compute_ms": round(compute_ms, 3),
        "overlap_plain_ms": round(plain_ms, 3),
        "overlap_db_ms": round(db_ms, 3),
        "overlap_spread_pct": max(sp_c, sp_p, sp_d, max(spreads.values())),
    })
    comm_ms = plain_ms - compute_ms
    if comm_ms > 0.01 * plain_ms:
        out["hidden_comm_fraction"] = round(
            min(1.0, max(0.0, (plain_ms - db_ms) / comm_ms)), 3
        )
    else:
        # No resolvable wire cost at this scale (single chip / loopback
        # noise floor): there is nothing to hide, report 0 honestly.
        out["hidden_comm_fraction"] = 0.0
        out["overlap_note"] = (
            "comm time below the measurement floor "
            f"({comm_ms:.3f} ms of {plain_ms:.3f} ms step) — no wire to "
            "hide on this topology; fraction reported as 0"
        )

    # --- 3. eager per-bucket overlap: real dispatch/collect timestamps
    # feeding the SAME wire-event contract trace_report's overlap
    # section summarizes.
    try:
        per_rank = (1 << 20) if on_accel else (1 << 14)
        gtree = {
            f"g{i}": jnp.full((comm.size, per_rank), float(i + 1),
                              jnp.float32)
            for i in range(3)
        }
        red = OverlappedBucketReducer(
            comm, bucket_bytes=per_rank * 4 * 2,  # ~2 leaves per bucket
        )
        busy = jax.jit(lambda a: jnp.tanh(a @ a.transpose()).sum())
        # Warm round: compiles the bucket collectives and the busy work —
        # its wire events carry compile time, so the measured round's
        # events are summarized separately below.
        red.dispatch(gtree)
        _fetch_scalar(busy(x.astype(jnp.float32)))
        red.collect()
        rec_ = obs_trace.active()
        n_before = len(rec_.events) if rec_ is not None else 0
        n_buckets = red.dispatch(gtree)
        overlap_work = busy(x.astype(jnp.float32))  # rides behind the wire
        mean = red.collect()
        _fetch_scalar(overlap_work)
        ok = all(
            abs(_fetch_scalar(mean[f"g{i}"][:1]) - (i + 1)) < 1e-5
            for i in range(3)
        )
        out["overlap_eager_buckets"] = n_buckets
        out["overlap_eager_mean_ok"] = bool(ok)
        if rec_ is not None:
            ov = obs_trace.summarize_overlap(rec_.events[n_before:])
            if ov and "measured" in ov:
                out["overlap_wire_hidden_fraction"] = (
                    ov["measured"]["hidden_fraction"]
                )
                out["overlap_wire_comm_ms"] = ov["measured"]["comm_ms_total"]
    except Exception as e:
        out["overlap_eager_error"] = f"{type(e).__name__}: {e}"[:160]
    return out


def _bench_composed(comm, on_accel: bool):
    """ISSUE 12: the derived-composition sweep — the mesh re-factored
    THREE-LEVEL (8 devices -> 2x2x2, the north-star multi-slice
    rehearsal a flat or 2-axis bench cannot stand in for) and every
    composition the deriver generates for it timed through the standard
    optimizer path (CPU-proxy convention: median-of-n>=3 + spread — a
    delta inside ``composed_spread_pct`` is noise).

    Rows are keyed by COMPOSITION SIGNATURE (the registry's spelling):
    the menu's ``flat``/``two_level`` appear as their derived instances
    (``ar(a0+a1+a2)`` / ``rs(a2)>ar(a0+a1)>ag(a2)``), so the
    best-vs-``two_level`` ratio on the compact line prices exactly what
    the composition layer buys beyond the old menu. The medians are
    adopted into the tuning cache as this 3-level world shape's
    ``reduction_schedule`` decision (spread-gated, carried-blob aware —
    ``tuning seed`` learns the same rows offline)."""
    import numpy as np

    import jax
    import jax.numpy as jnp
    import optax
    from jax import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    from chainermn_tpu import create_multi_node_optimizer
    from chainermn_tpu.communicators.xla_communicator import XlaCommunicator
    from chainermn_tpu.parallel.composition import (
        canonical_axis_names,
        derive_compositions,
        normalize_schedule_name,
        schedule_candidates,
        two_level_composition,
    )
    from chainermn_tpu.parallel.mesh import best_mesh_shape
    from chainermn_tpu.parallel.reduction_schedule import (
        DECISION as _SCHED_DECISION,
    )

    devices = list(comm.mesh.devices.flat)
    shape = best_mesh_shape(len(devices), 3)
    names = canonical_axis_names(3)
    comm3 = XlaCommunicator(
        mesh=Mesh(np.array(devices).reshape(shape), names)
    )
    axes = comm3.grad_axes

    width = 1536 if on_accel else 128
    layers = 2
    batch = 8 * comm3.size
    steps = 16 if on_accel else 2
    rng = jax.random.PRNGKey(0)
    params = [
        jax.random.normal(jax.random.fold_in(rng, i),
                          (width, width), jnp.float32) * 0.02
        for i in range(layers)
    ]
    x = jax.random.normal(rng, (batch, width), jnp.bfloat16)
    payload_bytes = sum(p.size * 4 for p in params)

    def time_loop(opt):
        def local(params, opt_state, xb):
            def one(carry, _):
                params, opt_state = carry

                def loss_fn(ps):
                    h = xb
                    for w in ps:
                        h = jnp.tanh(h @ w.astype(jnp.bfloat16))
                    return jnp.sum(h.astype(jnp.float32) ** 2)

                grads = jax.grad(loss_fn)(params)
                updates, opt_state = opt.update(grads, opt_state, params)
                params = optax.apply_updates(params, updates)
                return (params, opt_state), ()

            (params, opt_state), _ = jax.lax.scan(
                one, (params, opt_state), None, length=steps
            )
            return params

        fn = jax.jit(
            shard_map(local, mesh=comm3.mesh,
                      in_specs=(P(), opt.opt_state_spec(), P(axes)),
                      out_specs=P(), check_vma=False)
        )
        opt_state = opt.init(params)
        _fetch_scalar(fn(params, opt_state, x)[0][:1, :1])  # compile+warm

        def sample():
            t0 = time.perf_counter()
            _fetch_scalar(fn(params, opt_state, x)[0][:1, :1])
            return (time.perf_counter() - t0) / steps * 1000

        return _repeat_median(sample, 3)

    # --- cost-model schedule search (ISSUE 16): rank the derived grid
    # with the α-β model fitted from the PRIOR capture's rows and
    # measure only the top-k (+ the two_level ratio baseline) instead
    # of every arm. Degrades loudly: no prior rows for this mesh shape
    # -> forced:uncalibrated exhaustive sweep; model error past the
    # spread gate after measuring -> the skipped arms are measured
    # after all (exhaustive fallback, provenance says why). Skipped
    # arms are always logged WITH their predicted prices — no silent
    # coverage loss.
    from chainermn_tpu.parallel import cost_model as _cm

    payload_mb = max(1, payload_bytes >> 20)
    cands = [c.signature() for c in derive_compositions(names)]
    two_level_sig = two_level_composition(names).signature()
    model = _cm.load_from_bench_details(
        _DETAILS_PATH, world_shape=shape)
    search_mode = "topk"
    search_source = None
    try:
        from chainermn_tpu import tuning as _tuning_q

        key_q = _tuning_q.decision_key(
            shape=tuple(int(d) for d in shape) + (payload_mb,),
            dtype="search",
        )
        search_mode = _tuning_q.choice(
            "sched_search", ("topk", "exhaustive"), key_q)
        rec_q = [d for d in _tuning_q.decisions_taken()
                 if d["name"] == "sched_search" and d["key"] == key_q]
        if rec_q:
            search_source = rec_q[-1]["source"]
    except Exception:
        pass
    rank = _cm.rank_compositions(
        model, cands, payload_bytes, k=3, mode=search_mode)

    sched_ms: dict = {}
    spreads: dict = {}

    def _measure_arm(sig):
        opt = create_multi_node_optimizer(
            optax.sgd(1e-3), comm3, allreduce_grad_dtype=jnp.bfloat16,
            reduction_schedule=sig,
        )
        med, spread = time_loop(opt)
        sched_ms[sig] = round(med, 3)
        spreads[sig] = spread

    for sig in rank.measured:
        _measure_arm(sig)
    if two_level_sig not in sched_ms:
        _measure_arm(two_level_sig)  # the ratio baseline, always timed
    err_pct = _cm.model_error_pct(rank.predicted_ms, sched_ms)
    provenance = rank.provenance
    if (rank.mode == "topk" and err_pct is not None
            and err_pct > max(spreads.values())):
        # the model disagreed with the wall clock past the noise gate:
        # its ranking cannot be trusted to have skipped only losers —
        # measure everything, say why.
        provenance = (f"exhaustive:model_err {err_pct}% > spread "
                      f"{round(max(spreads.values()), 3)}%")
        for sig in rank.skipped:
            if sig not in sched_ms:
                _measure_arm(sig)
        err_pct = _cm.model_error_pct(rank.predicted_ms, sched_ms)
    searched_mode = ("topk" if len(sched_ms) < len(cands)
                     else "exhaustive")
    skipped = [s for s in rank.order if s not in sched_ms]
    best_sig = min(sched_ms, key=sched_ms.get)
    out = {
        "composed_schedule_ms": sched_ms,
        "composed_spread_pct": max(spreads.values()),
        "composed_world_shape": [int(d) for d in shape],
        "composed_payload_mb": payload_mb,
        "composed_best": best_sig,
        # what composing beyond the menu buys: the best derived
        # pipeline's speedup over the menu's two_level on this
        # 3-level factoring (>1 = a composition the menu could not
        # express wins; judge it against composed_spread_pct).
        "composed_best_vs_two_level": round(
            sched_ms[two_level_sig] / max(sched_ms[best_sig], 1e-9), 3
        ),
        "sched_search_selected": searched_mode,
        "sched_search_provenance": provenance,
        "sched_search_skipped": skipped,
    }
    if search_source:
        out["sched_search_source"] = search_source
    if rank.predicted_ms:
        out["sched_search_predicted_ms"] = rank.predicted_ms
    if err_pct is not None:
        out["cost_model_err_pct"] = err_pct
    if model is not None:
        out["cost_model_fit"] = {
            "source": model.source,
            "fit_err_pct": model.fit_err_pct,
            "n_rows": len(model.fit_rows),
        }
    import dataclasses as _dc_mod

    _cm.emit_sched_search_event(
        _dc_mod.replace(rank, mode=searched_mode, provenance=provenance),
        sched_ms, spread_pct=max(spreads.values()))
    try:
        from chainermn_tpu import tuning

        key = tuning.decision_key(
            shape=tuple(int(d) for d in shape)
            + (max(1, payload_bytes >> 20),),
            dtype="sched",
        )
        # Adopt under the registry's candidate SPELLING: the flat /
        # two_level derived instances go in by menu name (a signature
        # winner the candidate list excludes would be silently
        # discarded at choice() time), novel pipelines by signature.
        adopt_ms = {normalize_schedule_name(s, 3): v
                    for s, v in sched_ms.items()}
        adopt_spreads = {normalize_schedule_name(s, 3): v
                         for s, v in spreads.items()}
        # every top-k adoption carries the model audit as evidence —
        # the winner row records how far the predictions that chose
        # the measured set sat from the wall clock (ISSUE 16).
        audit = {"sched_search": provenance}
        if err_pct is not None:
            audit["cost_model_err_pct"] = err_pct
        tuning.record_measurement(
            _SCHED_DECISION, key, adopt_ms, spreads=adopt_spreads,
            extra_evidence=audit,
        )
        selected = tuning.choice(
            _SCHED_DECISION, schedule_candidates(3), key
        )
        out["composed_selected"] = selected
        rec = [d for d in tuning.decisions_taken()
               if d["name"] == _SCHED_DECISION and d["key"] == key]
        if rec:
            out["composed_schedule_source"] = rec[-1]["source"]
    except Exception as e:
        out["composed_autotune_error"] = f"{type(e).__name__}: {e}"[:120]

    # --- sliced arms (ISSUE 15): the hierarchical two_level instance
    # re-timed at comp_slices ∈ {1,2,4,8} — slice i's slow ar(a0+a1)
    # rides concurrently with slice i+1's fast rs/ag(a2), S× the
    # per-stage collectives at 1/S payload. Same CPU-proxy convention
    # (n>=3 medians + spread) and the same spread-gated adoption into
    # the ``comp_slices`` decision ``tuning seed`` learns offline from
    # these exact rows — offline and live must agree (the PR 14
    # adapter_impl lesson). The arm's key spelling is the slice count.
    try:
        from chainermn_tpu.parallel.composition import sliced_composition
        from chainermn_tpu.parallel.reduction_schedule import (
            SLICES_DECISION as _SLICES_DECISION,
            SLICE_CANDIDATES as _SLICE_CANDIDATES,
        )

        base_comp = two_level_composition(names)
        sliced_ms: dict = {}
        sliced_spreads: dict = {}
        sliced_pred: dict = {}
        for s in _SLICE_CANDIDATES:
            sig_s = (base_comp.signature() if s == "1" else
                     sliced_composition(base_comp, int(s)).signature())
            opt = create_multi_node_optimizer(
                optax.sgd(1e-3), comm3,
                allreduce_grad_dtype=jnp.bfloat16,
                reduction_schedule=sig_s,
            )
            med, spread = time_loop(opt)
            sliced_ms[s] = round(med, 3)
            sliced_spreads[s] = spread
            if model is not None:
                # the model prices sliced variants too (critical-path
                # ticks) — logged beside the measurement as its audit
                sliced_pred[s] = round(
                    model.predict(sig_s, payload_bytes), 3)
        out["composed_sliced_ms"] = sliced_ms
        out["composed_sliced_spread_pct"] = round(
            max(sliced_spreads.values()), 3)
        if sliced_pred:
            out["composed_sliced_predicted_ms"] = sliced_pred
        from chainermn_tpu import tuning

        key_s = tuning.decision_key(
            shape=tuple(int(d) for d in shape)
            + (max(1, payload_bytes >> 20),),
            dtype="slices",
        )
        tuning.record_measurement(
            _SLICES_DECISION, key_s, sliced_ms, spreads=sliced_spreads
        )
        out["composed_slices_selected"] = int(tuning.choice(
            _SLICES_DECISION, _SLICE_CANDIDATES, key_s
        ))
        rec_s = [d for d in tuning.decisions_taken()
                 if d["name"] == _SLICES_DECISION and d["key"] == key_s]
        if rec_s:
            out["composed_slices_source"] = rec_s[-1]["source"]
    except Exception as e:
        out["composed_sliced_error"] = f"{type(e).__name__}: {e}"[:120]
    return out


def _bench_plan(comm, on_accel: bool):
    """ISSUE 10: hand-wired vs plan-compiled train step (CPU-proxy
    convention: median-of-n>=3 + spread — a delta inside the spread is
    noise).

    One comm-heavy MLP workload, identical semantics both ways — ZeRO
    data parallelism over every device (reduce-scatter -> 1/n sharded
    update -> all-gather, adamw inner):

    - hand-wired: ``make_train_step`` + ``MultiNodeOptimizer(
      reduction_schedule='zero')`` over the communicator (the
      call-site-wrapper composition this repo shipped in PR 3);
    - plan: ``ParallelPlan({'zero': n})`` compiling the same step
      global-view through the spec providers, donation on.

    The ratio is the refactor's price tag (expected ~1.0x: same
    collectives, pinned structurally in tests/test_plan.py); both rows
    land in the compact line as ``plan_vs_handwired`` + spread."""
    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import PartitionSpec as P

    from chainermn_tpu.optimizers import create_multi_node_optimizer
    from chainermn_tpu.parallel.plan import ParallelPlan
    from chainermn_tpu.training.train_step import (
        create_train_state,
        make_train_step,
    )

    width = 1024 if on_accel else 128
    layers = 3
    n = comm.size
    batch = 8 * n
    steps = 16 if on_accel else 4
    rng = jax.random.PRNGKey(0)
    params = {
        f"w{i}": jax.random.normal(jax.random.fold_in(rng, i),
                                   (width, width), jnp.float32) * 0.02
        for i in range(layers)
    }
    x = jax.random.normal(rng, (batch, width), jnp.float32)
    y = jnp.zeros((batch,), jnp.int32)

    def loss_fn(p, batch_):
        xb, yb = batch_
        h = xb
        for i in range(layers):
            h = jnp.tanh(h @ p[f"w{i}"])
        return optax.softmax_cross_entropy_with_integer_labels(
            h[:, :16], yb
        ).mean()

    inner = optax.adamw(1e-3)

    def time_steps(step, state):
        # Two warm calls: the hand-wired path's eager-built state has
        # uncommitted shardings, so its SECOND call (committed outputs)
        # compiles a fresh signature — the plan path stays at one
        # compile because create_train_state places the state sharded.
        state, m = step(state, (x, y))
        state, m = step(state, (x, y))
        _fetch_scalar(m["loss"])

        def sample():
            nonlocal state
            t0 = time.perf_counter()
            for _ in range(steps):
                state, m = step(state, (x, y))
            _fetch_scalar(m["loss"])
            return (time.perf_counter() - t0) / steps * 1000

        med, spread = _repeat_median(sample, 1 if on_accel else 3)
        return med, spread, state

    opt = create_multi_node_optimizer(inner, comm,
                                      reduction_schedule="zero")
    # Copy: the donating hand-wired step would otherwise delete the
    # shared template params the plan state is built from below.
    hand_state = create_train_state(
        jax.tree.map(lambda p: jnp.array(p, copy=True), params), opt, comm
    )
    hand_step = make_train_step(loss_fn, opt, comm)
    hand_ms, hand_spread, _ = time_steps(hand_step, hand_state)

    devices = list(comm.mesh.devices.flat)
    plan = ParallelPlan({"zero": n}, devices=devices)
    plan_state = plan.create_train_state(params, inner)
    plan_step = plan.compile_train_step(loss_fn, inner, params)
    plan_ms, plan_spread, _ = time_steps(plan_step, plan_state)

    out = {
        "plan_step_ms": round(plan_ms, 3),
        "plan_handwired_ms": round(hand_ms, 3),
        "plan_vs_handwired": round(hand_ms / plan_ms, 3),
        "plan_spread_pct": max(hand_spread, plan_spread),
        "plan_mesh": plan.describe()["mesh"],
        "plan_compiles": plan_step.cache_size()
        if hasattr(plan_step, "cache_size") else None,
    }
    return out


def _bench_seq_parallel(comm, on_accel: bool):
    """ISSUE 13: the sequence axis, priced twice (CPU-proxy convention:
    median-of-n>=3 + spread — a delta inside ``seq_parallel_spread_pct``
    is noise; on-accel rows are single samples and the offline seeder
    applies the 10% floor):

    1. TRAINING — one ``data x seq`` plan-compiled Transformer step per
       ``seq_attn_impl`` candidate (ring's n-1 ppermutes/layer vs
       Ulysses' all_to_all reshard), adopted as this
       shards x heads x T shape's ``seq_attn_impl`` decision;
    2. SERVING — long-prompt TTFT through a TP engine at 1/2/4 model
       shards, monolithic vs sequence-parallel prefill at the top shard
       count, adopted (spread-gated) as this model shape's
       ``prefill_seq_parallel`` decision — the number that decides
       whether the wide-prefill/narrow-decode split finally earns
       ``cluster_disagg`` its hop.
    """
    import functools

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh

    from chainermn_tpu.models.transformer import TransformerLM
    from chainermn_tpu.parallel.plan import ParallelPlan
    from chainermn_tpu.parallel.plan_specs import SEQ_ATTN_IMPLS
    from chainermn_tpu.serving import ServingEngine, serving_decision_key

    devices = list(comm.mesh.devices.flat)
    n_seq = min(4, len(devices) // 2) or 1
    if on_accel:
        layers, d_model, heads, d_ff = 4, 512, 8, 2048
        vocab, T, batch = 32000, 2048, 2 * (len(devices) // n_seq or 1)
        dtype = jnp.bfloat16
        steps = 8
    else:
        layers, d_model, heads, d_ff = 2, 64, 4, 128
        vocab, T, batch = 256, 64, 4
        dtype = jnp.float32
        steps = 2
    t_local = T // n_seq

    # --- 1. training: ring vs ulysses through the ONE plan step
    plan = ParallelPlan(
        {"data": len(devices) // n_seq, "seq": n_seq}, devices=devices
    )
    tok = jax.random.randint(jax.random.PRNGKey(0), (batch, T), 0, vocab)
    import optax

    inner = optax.sgd(1e-3)
    attn_ms: dict = {}
    attn_spreads: dict = {}
    lm_kw = dict(
        vocab_size=vocab, num_layers=layers, num_heads=heads,
        d_model=d_model, d_ff=d_ff, max_len=T, compute_dtype=dtype,
        pos_encoding="rope", return_hidden=True,
    )
    # init through the attention-free twin: the ring/ulysses locals
    # need the mesh axis context the init trace does not have
    params = {"params": jax.jit(
        functools.partial(TransformerLM(**lm_kw).init, train=False)
    )(jax.random.PRNGKey(1), tok[:1, :8])["params"]}
    for impl in SEQ_ATTN_IMPLS:
        if impl == "ulysses" and heads % n_seq:
            continue  # forced-ring shape: nothing to compare
        attn_fn, _rec = plan.seq_attention(
            heads=heads, t_local=t_local, impl=impl
        )
        model = TransformerLM(**lm_kw, attention_fn=attn_fn)

        def loss_fn(p, batch_):
            pos = ParallelPlan.seq_local_positions(batch_.shape[1])
            h = model.apply({"params": p["params"]}, batch_,
                            positions=pos, train=False)
            return jnp.mean(h.astype(jnp.float32) ** 2)

        state = plan.create_train_state(params, inner)
        step = plan.compile_train_step(loss_fn, inner, params)
        state, m = step(state, tok)  # compile + warm
        _fetch_scalar(m["loss"])

        def sample():
            nonlocal state
            t0 = time.perf_counter()
            for _ in range(steps):
                state, m = step(state, tok)
            _fetch_scalar(m["loss"])
            return (time.perf_counter() - t0) / steps * 1000

        med, spread = _repeat_median(sample, 1 if on_accel else 3)
        attn_ms[impl] = round(med, 3)
        attn_spreads[impl] = spread
    out = {
        "seq_parallel_attn_ms": attn_ms,
        # T here is the LOCAL shard length — the seq_attn_impl decision
        # key's T-bucket (the plan's seq_attention and the offline
        # seeder must rebuild the same key).
        "seq_parallel_attn_shape": f"S{n_seq}xH{heads}xT{t_local}",
        "seq_parallel_shards": n_seq,
    }
    if not on_accel and attn_spreads:
        out["seq_parallel_attn_spread_pct"] = max(attn_spreads.values())

    try:
        from chainermn_tpu import tuning

        if len(attn_ms) > 1:
            akey = tuning.decision_key(
                shape=(n_seq, heads, t_local), dtype="seqattn"
            )
            tuning.record_measurement(
                "seq_attn_impl", akey, attn_ms,
                spreads=None if on_accel else attn_spreads,
            )
            out["seq_parallel_attn_selected"] = tuning.choice(
                "seq_attn_impl", SEQ_ATTN_IMPLS, akey
            )
    except Exception as e:
        out["seq_parallel_attn_autotune_error"] = (
            f"{type(e).__name__}: {e}"[:120])

    # --- 2. serving: long-prompt TTFT, monolithic vs seq-parallel
    if on_accel:
        s_layers, s_dm, s_heads, s_dff = 4, 512, 8, 2048
        s_vocab, s_maxlen, prompt_len, gen = 32000, 2048, 1500, 4
        s_dtype = jnp.bfloat16
    else:
        s_layers, s_dm, s_heads, s_dff = 2, 64, 4, 128
        s_vocab, s_maxlen, prompt_len, gen = 256, 64, 40, 2
        s_dtype = jnp.float32
    s_model = TransformerLM(
        vocab_size=s_vocab, num_layers=s_layers, num_heads=s_heads,
        d_model=s_dm, d_ff=s_dff, max_len=s_maxlen,
        compute_dtype=s_dtype,
    )
    s_params = jax.jit(
        functools.partial(s_model.init, train=False)
    )(jax.random.PRNGKey(2), jnp.zeros((1, 8), jnp.int32))
    rs = np.random.RandomState(11)
    prompt = rs.randint(1, s_vocab, size=prompt_len).tolist()

    def ttft_median(shards, seq_parallel):
        mesh = Mesh(np.array(devices[:shards]), ("model",))
        engine = ServingEngine(
            s_model, s_params, num_slots=2, max_len=s_maxlen,
            decode_impl="paged", kv_block_size="auto",
            prefill_buckets=(s_maxlen,), mesh=mesh,
            prefill_seq_parallel="on" if seq_parallel else "off",
        )

        def once():
            t0 = time.perf_counter()
            res = engine.prefill_join(prompt)
            jax.block_until_ready(jax.tree.leaves(engine._cache)[0])
            dt = (time.perf_counter() - t0) * 1000
            assert res is not None
            engine.leave(res[0])
            return dt

        once()  # compile + warm
        return _repeat_median(once, 1 if on_accel else 3)

    ttft_by_shards: dict = {}
    ttft_spreads: dict = {}
    top = None
    for shards in (1, 2, 4):
        if shards > len(devices) or s_heads % shards:
            continue
        kvh = s_heads  # MHA here; GQA shapes gate on kv heads too
        if shards > 1 and kvh % shards:
            continue
        med, spread = ttft_median(shards, seq_parallel=shards > 1)
        ttft_by_shards[str(shards)] = round(med, 4)
        ttft_spreads[str(shards)] = spread
        top = shards
    out["seq_parallel_ttft_shards_ms"] = ttft_by_shards
    out["seq_parallel_model_shape"] = f"D{s_dm}xH{s_heads}xL{s_maxlen}"
    if top and top > 1:
        # the decision's candidates, measured at the TOP shard count:
        # 'off' = the TP monolithic prefill on the SAME mesh (isolates
        # the sharded forward from the TP speedup itself)
        med_off, spread_off = ttft_median(top, seq_parallel=False)
        ttft_ms = {"off": round(med_off, 4),
                   "on": ttft_by_shards[str(top)]}
        sp = {"off": spread_off, "on": ttft_spreads[str(top)]}
        out["seq_parallel_ttft_ms"] = ttft_ms
        if not on_accel:
            out["seq_parallel_spread_pct"] = max(sp.values())
        if ttft_ms["on"]:
            out["seq_parallel_ttft_speedup"] = round(
                ttft_ms["off"] / ttft_ms["on"], 3
            )
        try:
            from chainermn_tpu import tuning

            key = serving_decision_key(s_dm, s_heads, s_maxlen)
            tuning.record_measurement(
                "prefill_seq_parallel", key, ttft_ms,
                spreads=None if on_accel else sp,
            )
            out["seq_parallel_selected"] = tuning.choice(
                "prefill_seq_parallel", ("off", "on"), key
            )
        except Exception as e:
            out["seq_parallel_autotune_error"] = (
                f"{type(e).__name__}: {e}"[:120])
    if not on_accel:
        out["seq_parallel_note"] = (
            "CPU-proxy honest floor: tiny LM, loopback ppermutes — the "
            "ring-vs-ulysses and off-vs-on rankings hold for THIS "
            "backend; absolute ms is not chip latency"
        )
    return out


def _bench_allreduce(comm, n_elems: int = 100_000_000):
    """The reference's ``allreduce_grad`` GB/s microbenchmark (BASELINE.json
    tracked metric): achieved bytes/s of a jitted psum over a flat bf16
    gradient-sized buffer — the fused equivalent of
    ``pure_nccl_communicator.py`` (dagger)'s pack -> ncclAllReduce path.

    Matches ``allreduce_grad`` semantics: every device holds the FULL
    ``n_elems`` gradient buffer. The buffer is made device-distinct (axis
    index added) inside the program so XLA cannot simplify the all-reduce
    of a replicated value into a local multiply."""
    import jax
    import jax.numpy as jnp
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    mesh = comm.mesh
    axes = comm.grad_axes
    axes_tuple = axes if isinstance(axes, tuple) else (axes,)
    n = comm.size
    dtype = jnp.bfloat16
    buf = jnp.ones((n_elems,), dtype)

    # Enough rounds to amortise the end-of-run scalar fetch (tens of ms of
    # tunnel round-trip) out of the per-iteration figure.
    iters = 50

    def local(x):
        # Iterations chained INSIDE one program: per-dispatch host latency
        # (large under the tunnelled platform) must not pollute a bandwidth
        # measurement. Each round's input depends on the previous psum, so
        # the collectives execute serially on-device.
        salt = sum(jax.lax.axis_index(a) for a in axes_tuple)

        def body(b, _):
            red = jax.lax.psum(b + salt.astype(b.dtype), axes)
            return (red * 0.5).astype(b.dtype), ()

        out, _ = jax.lax.scan(body, x, None, length=iters)
        return out

    fn = jax.jit(
        shard_map(local, mesh=mesh, in_specs=P(), out_specs=P(),
                  check_vma=False)
    )
    _fetch_scalar(fn(buf)[:1])  # compile + warm
    t0 = time.perf_counter()
    _fetch_scalar(fn(buf)[:1])  # true sync: host transfer, not block_until_ready
    dt = (time.perf_counter() - t0) / iters
    nbytes = n_elems * buf.dtype.itemsize
    # Algorithm bandwidth (bytes through the reduction per second). With
    # n devices a ring moves 2(n-1)/n * nbytes per device; report both.
    algbw = nbytes / dt
    busbw = algbw * (2 * (n - 1) / n) if n > 1 else algbw
    return {
        "allreduce_gbps": round(algbw / 1e9, 2),
        "allreduce_busbw_gbps": round(busbw / 1e9, 2),
        "allreduce_elems": n_elems,
        "allreduce_dtype": "bfloat16",
    }


def _bench_allreduce_curve(comm, on_accel: bool):
    """busbw-vs-message-size curve (round-4 VERDICT item 6, the BASELINE
    ``allreduce_grad GB/s`` metric's missing depth): jitted psum at
    1 MiB -> 512 MiB, bf16 and f32, fused single-buffer vs ~64 MiB
    bucketed (the TwoDimensionalCommunicator's packing discipline).
    Single-chip rows measure loopback reduction throughput; the shape of
    the curve (latency-bound small messages -> bandwidth-bound plateau)
    is the evidence the scaling model's bucket-size choice rests on."""
    import jax
    import jax.numpy as jnp
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    mesh = comm.mesh
    axes = comm.grad_axes
    axes_tuple = axes if isinstance(axes, tuple) else (axes,)
    n = comm.size
    bucket_elems_bf16 = 32 << 20  # 64 MiB of bf16

    if not on_accel:
        # Tiny sizes keep the CPU fallback fast; shrink the bucket too so
        # the bucketed row is a REAL multi-psum program, not a relabelled
        # copy of the fused one.
        bucket_elems_bf16 = 1 << 16

    cases = ([
        (1 << 19, jnp.bfloat16, "fused", 100),   # 1 MiB
        (1 << 23, jnp.bfloat16, "fused", 50),    # 16 MiB
        (1 << 26, jnp.bfloat16, "fused", 20),    # 128 MiB
        (1 << 28, jnp.bfloat16, "fused", 8),     # 512 MiB
        (1 << 28, jnp.bfloat16, "bucketed", 8),
        (1 << 26, jnp.float32, "fused", 20),     # 256 MiB f32
    ] if on_accel else [
        (1 << 16, jnp.bfloat16, "fused", 10),
        (1 << 18, jnp.bfloat16, "fused", 5),
        (1 << 18, jnp.bfloat16, "bucketed", 5),
    ])
    if n > 1:
        # The quantized wire only exists on a real multi-member axis
        # (size-1 short-circuits to the exact value by design).
        cases.append(
            (1 << 26, jnp.float32, "int8", 20) if on_accel
            else (1 << 18, jnp.float32, "int8", 5)
        )

    rows = []
    for n_elems, dtype, mode_, iters in cases:
        buf = jnp.ones((n_elems,), dtype)
        n_buckets = (max(1, n_elems // bucket_elems_bf16)
                     if mode_ == "bucketed" else 1)

        def local(x, n_buckets=n_buckets, mode=mode_):
            salt = sum(jax.lax.axis_index(a) for a in axes_tuple)

            def body(b, _):
                if mode == "int8":
                    from chainermn_tpu.parallel.collectives import (
                        int8_allreduce_mean,
                    )

                    red = int8_allreduce_mean(
                        b + salt.astype(b.dtype), axes_tuple
                    )
                elif n_buckets == 1:
                    red = jax.lax.psum(b + salt.astype(b.dtype), axes)
                else:
                    parts = jnp.split(b + salt.astype(b.dtype), n_buckets)
                    red = jnp.concatenate(
                        [jax.lax.psum(p, axes) for p in parts]
                    )
                return (red * 0.5).astype(b.dtype), ()

            out, _ = jax.lax.scan(body, x, None, length=iters)
            return out

        fn = jax.jit(shard_map(local, mesh=mesh, in_specs=P(),
                               out_specs=P(), check_vma=False))
        try:
            _fetch_scalar(fn(buf)[:1])  # compile + warm
            t0 = time.perf_counter()
            _fetch_scalar(fn(buf)[:1])
            dt = (time.perf_counter() - t0) / iters
        except Exception as e:
            rows.append({
                "mib": round(n_elems * jnp.dtype(dtype).itemsize / 2**20,
                             3),
                "dtype": jnp.dtype(dtype).name, "mode": mode_,
                "error": f"{type(e).__name__}: {e}"[:160],
            })
            continue
        nbytes = n_elems * jnp.dtype(dtype).itemsize
        algbw = nbytes / dt  # logical (pre-compression) bytes reduced/s
        # Bus bandwidth from the bytes that PHYSICALLY cross the wire:
        # ring allreduce moves 2(n-1)/n * itemsize per element; the int8
        # scheme moves ~2(n-1)/n * 1 byte regardless of logical dtype
        # (all_to_all int8 chunks + int8 all-gather; scales negligible).
        wire_itemsize = 1 if mode_ == "int8" else jnp.dtype(dtype).itemsize
        wire_bytes = n_elems * wire_itemsize
        busbw = (wire_bytes / dt) * (2 * (n - 1) / n) if n > 1 \
            else wire_bytes / dt
        rows.append({
            "mib": round(nbytes / 2**20, 3),
            "dtype": jnp.dtype(dtype).name,
            "mode": mode_,
            "n_buckets": n_buckets,
            "ms": round(dt * 1e3, 3),
            "algbw_gbps": round(algbw / 1e9, 2),
            "busbw_gbps": round(busbw / 1e9, 2),
        })
    out = {"allreduce_curve": rows}
    # Adopt the curve as this topology's wire decision: best busbw per
    # wire variant (bf16 fused vs the int8 two-phase wire), higher
    # wins. The bucket-size decision keeps its ~64 MB table default
    # unless the bucketed row is decisively slower than fused.
    try:
        from chainermn_tpu import tuning

        best = {}
        for row in rows:
            if "busbw_gbps" not in row:
                continue
            wire = ("int8" if row.get("mode") == "int8"
                    else {"bfloat16": "bf16", "float32": "f32"}.get(
                        row.get("dtype")))
            if wire:
                best[wire] = max(best.get(wire, 0.0), row["busbw_gbps"])
        # n > 1 only: at one device there IS no wire, and a dtype
        # "comparison" would adopt loopback-bandwidth noise.
        if len(best) > 1 and comm.size > 1:
            key = tuning.decision_key(shape=(comm.size,), dtype="grad")
            tuning.record_measurement(
                "allreduce_wire", key, best, higher_is_better=True,
            )
            out["allreduce_wire_selected"] = tuning.choice(
                "allreduce_wire", ("f32", "bf16", "int8"), key
            )
    except Exception as e:
        out["allreduce_wire_autotune_error"] = (
            f"{type(e).__name__}: {e}"[:120]
        )
    return out


def _bench_kernel_sweep(on_accel: bool):
    """On-chip Pallas kernel compile/perf sweep (round-4 VERDICT item 7):
    every flash-attention variant class — causal, banded sliding window
    (even AND odd widths: the even case regressed once), GQA, packed
    segments, unequal q/k lengths (the SP extended-K shape), fwd and
    fwd+bwd — jitted, run, and timed on the REAL chip, so a Mosaic
    layout rejection shows up in the driver artifact instead of waiting
    for someone to hand-drive the chip (CPU interpret mode accepts
    layouts Mosaic rejects — CLAUDE.md kernel convention)."""
    if not on_accel:
        return {"kernel_sweep": "skipped on CPU (interpret mode cannot "
                                "catch Mosaic layout rejections)"}
    import jax
    import jax.numpy as jnp

    from chainermn_tpu.ops.attention import dot_product_attention
    from chainermn_tpu.ops.flash_attention import flash_attention

    B, T, H, D = 2, 2048, 8, 128
    ks = jax.random.split(jax.random.PRNGKey(7), 3)
    q = jax.random.normal(ks[0], (B, T, H, D), jnp.bfloat16)
    kv2 = jax.random.normal(ks[1], (B, T, 2, D), jnp.bfloat16)
    seg = (jnp.arange(T)[None, :] // 512).astype(jnp.int32).repeat(B, 0)
    k_long = jax.random.normal(ks[2], (B, 3072, H, D), jnp.bfloat16)

    # Sliding-window reference: the materialised comparator has no window
    # arg, but an additive band bias reproduces it exactly.
    def band_bias(W):
        qpos = jnp.arange(T)[:, None]
        kpos = jnp.arange(T)[None, :]
        return jnp.where(
            qpos - kpos < W, 0.0, -1e9
        )[None, None, :, :].astype(jnp.float32)

    # Numerics references for the fwd variants: compile/run alone cannot
    # catch a SILENTLY wrong Mosaic schedule (e.g. a misdeclared parallel
    # grid dim) — compare each flash output against the materialised
    # reference on the chip itself. bf16 accumulate-order differences sit
    # well under the 0.05 gate; a scheduling bug blows past it.
    numerics = {
        "causal_fwd": (
            lambda q_, k_, v_: flash_attention(
                q_, k_, v_, causal=True, interpret=False),
            lambda q_, k_, v_: dot_product_attention(
                q_, k_, v_, causal=True),
        ),
        "window_odd_fwd": (
            lambda q_, k_, v_: flash_attention(
                q_, k_, v_, causal=True, window=1023, interpret=False),
            lambda q_, k_, v_: dot_product_attention(
                q_, k_, v_, causal=True, bias=band_bias(1023)),
        ),
        "segments_fwd": (
            lambda q_, k_, v_: flash_attention(
                q_, k_, v_, causal=True, segment_ids=seg, interpret=False),
            lambda q_, k_, v_: dot_product_attention(
                q_, k_, v_, causal=True, segment_ids=seg),
        ),
        "gqa4_fwdbwd": (
            lambda q_, k_, v_: flash_attention(
                q_, k_, v_, causal=True, interpret=False),
            lambda q_, k_, v_: dot_product_attention(
                q_, k_, v_, causal=True),
        ),
        "cross_len_fwd": (
            lambda q_, k_, v_: flash_attention(
                q_, k_, v_, causal=False, interpret=False),
            lambda q_, k_, v_: dot_product_attention(q_, k_, v_),
        ),
    }

    def fwd(fn):
        def f(*a):
            return jnp.sum(fn(*a).astype(jnp.float32))
        return f

    def fwdbwd(fn):
        def f(*a):
            return jnp.sum(fn(*a).astype(jnp.float32))
        # argnums=(0,1,2), NOT 0: grad wrt q alone needs only the dq
        # kernel — the dkv kernel would be dead-code-eliminated and
        # never face Mosaic (the gap that let the dkv segment specs go
        # unchecked until r5).
        return jax.grad(f, argnums=(0, 1, 2))

    variants = [
        ("causal_fwd", fwd(lambda q, k, v: flash_attention(
            q, k, v, causal=True, interpret=False)), (q, q, q)),
        ("causal_fwdbwd", fwdbwd(lambda q, k, v: flash_attention(
            q, k, v, causal=True, interpret=False)), (q, q, q)),
        ("window_even_fwdbwd", fwdbwd(lambda q, k, v: flash_attention(
            q, k, v, causal=True, window=1024, interpret=False)),
         (q, q, q)),
        ("window_odd_fwd", fwd(lambda q, k, v: flash_attention(
            q, k, v, causal=True, window=1023, interpret=False)),
         (q, q, q)),
        ("gqa4_fwdbwd", fwdbwd(lambda q, k, v: flash_attention(
            q, k, v, causal=True, interpret=False)), (q, kv2, kv2)),
        ("segments_fwd", fwd(lambda q, k, v: flash_attention(
            q, k, v, causal=True, segment_ids=seg, interpret=False)),
         (q, q, q)),
        ("segments_fwdbwd", fwdbwd(lambda q, k, v: flash_attention(
            q, k, v, causal=True, segment_ids=seg, interpret=False)),
         (q, q, q)),
        ("cross_len_fwd", fwd(lambda q, k, v: flash_attention(
            q, k, v, causal=False, interpret=False)), (q, k_long, k_long)),
    ]

    # The sliding-window SP entry (round-4 grid-collapse fix changed this
    # geometry): flash_block_fwd with an ODD extended-K length (even
    # window), q_offset=prefix, wrap-sentinel kv ids, tile-padded by the
    # SAME helper the SP path uses — the exact shape Mosaic must accept.
    from chainermn_tpu.parallel.local_attention import (
        _WRAP_SENTINEL,
        _pad_ext_to_block,
    )
    from chainermn_tpu.ops.flash_attention import flash_block_fwd

    W = 1024
    tail = W - 1
    k_pre, v_pre = q[:, -tail:], q[:, -tail:]
    k_ext = jnp.concatenate([k_pre, q], axis=1)  # odd length T + W - 1
    v_ext = jnp.concatenate([v_pre, q], axis=1)
    seg_q = jnp.zeros((B, T), jnp.int32)
    seg_k = jnp.concatenate(
        [jnp.full((B, tail), _WRAP_SENTINEL, jnp.int32), seg_q], axis=1
    )
    k_ext, v_ext, seg_k = _pad_ext_to_block(k_ext, v_ext, seg_k, 1024)

    def sp_ext(qq, kk, vv):
        out, _ = flash_block_fwd(
            qq, kk, vv, causal=True, scale=D**-0.5, window=W,
            q_offset=tail, seg_q=seg_q, seg_kv=seg_k,
            block_q=512, block_k=1024, interpret=False,
        )
        return jnp.sum(out.astype(jnp.float32))

    variants.append(("sp_window_ext_fwd", sp_ext, (q, k_ext, v_ext)))

    from chainermn_tpu.ops.flash_attention import flash_block_bwd

    def sp_ext_bwd(qq, kk, vv):
        # The SP ring's backward entry with the same extended-K banded
        # geometry: lse/delta derived from the fwd, do = ones. Compiles
        # the dq AND dkv kernels with wrap-sentinel segment ids.
        out, lse = flash_block_fwd(
            qq, kk, vv, causal=True, scale=D**-0.5, window=W,
            q_offset=tail, seg_q=seg_q, seg_kv=seg_k,
            block_q=512, block_k=1024, interpret=False,
        )
        do = jnp.ones_like(out)
        delta = jnp.sum(
            (do * out).astype(jnp.float32), axis=-1
        ).transpose(0, 2, 1)
        dq, dk, dv = flash_block_bwd(
            qq, kk, vv, do, lse, delta, causal=True, scale=D**-0.5,
            window=W, q_offset=tail, seg_q=seg_q, seg_kv=seg_k,
            block_q=512, block_k=1024, interpret=False,
        )
        return (jnp.sum(dq.astype(jnp.float32))
                + jnp.sum(dk.astype(jnp.float32))
                + jnp.sum(dv.astype(jnp.float32)))

    variants.append(("sp_window_ext_bwd", sp_ext_bwd, (q, k_ext, v_ext)))

    rows = []
    for name, fn, args in variants:
        row = {"kernel": name}
        try:
            jf = jax.jit(fn)
            out = jf(*args)
            _fetch_scalar(jax.tree.leaves(out)[0].ravel()[:1])
            t0 = time.perf_counter()
            for _ in range(3):
                out = jf(*args)
            _fetch_scalar(jax.tree.leaves(out)[0].ravel()[:1])
            row["ms"] = round((time.perf_counter() - t0) / 3 * 1e3, 2)
            row["ok"] = True
            if name in numerics:
                try:
                    flash_t, ref_t = numerics[name]
                    fa = jax.jit(
                        lambda *a, _f=flash_t: _f(*a).astype(jnp.float32)
                    )(*args)
                    rf = jax.jit(
                        lambda *a, _r=ref_t: _r(*a).astype(jnp.float32)
                    )(*args)
                    err = jnp.max(jnp.abs(fa - rf))
                    den = jnp.max(jnp.abs(rf)) + 1e-6
                    row["rel_err"] = round(_fetch_scalar(err / den), 5)
                    row["numerics_ok"] = row["rel_err"] < 0.05
                except Exception as e:
                    row["numerics_error"] = f"{type(e).__name__}: {e}"[:120]
        except Exception as e:
            row["ok"] = False
            row["error"] = f"{type(e).__name__}: {e}"[:160]
        rows.append(row)
    return {"kernel_sweep": rows, **_kernel_sweep_counts(rows)}


def _kernel_sweep_counts(rows) -> dict:
    """Compact-line counts for the sweep rows. A CRASHED numerics
    checker is not 0 numeric failures: rows whose checker raised
    (``numerics_error`` set, so ``numerics_ok`` is absent and the
    failure count can't see them) get their own
    ``kernel_sweep_numeric_errors`` key, so the numerics gate cannot be
    satisfied by the checker erroring out (ADVICE r5)."""
    return {
        "kernel_sweep_failures": sum(1 for r in rows if not r["ok"]),
        "kernel_sweep_numeric_failures": sum(
            1 for r in rows if not r.get("numerics_ok", True)
        ),
        "kernel_sweep_numeric_errors": sum(
            1 for r in rows if "numerics_error" in r
        ),
    }


def _run_bench(mode: str) -> None:
    import jax
    import jax.numpy as jnp

    from chainermn_tpu import create_communicator
    from chainermn_tpu.observability import trace as obs_trace

    trace_path = _TRACE_PATH
    try:
        obs_trace.enable(trace_path, meta={"source": "bench", "mode": mode})
    except OSError:
        trace_path = None
    # Live metrics plane (ISSUE 6): the recorder tap aggregates every
    # wire/step/serving event this child emits into the registry; the
    # snapshot lands in BENCH_DETAILS.json at the end, so each bench
    # artifact carries the rolled-up counter/histogram view beside the
    # raw trace.
    try:
        from chainermn_tpu.observability import metrics as obs_metrics

        obs_metrics.install_tap()
    except Exception:
        obs_metrics = None

    devices = jax.devices()
    on_accel = devices[0].platform != "cpu"
    if mode == "accel" and not on_accel:
        raise RuntimeError(
            "accel bench requested but only the cpu backend is available"
        )
    if mode == "cpu":
        # Parent budgeted for the tiny proxy; never run the full ResNet-50
        # here even if an accelerator slipped through the env scrub.
        on_accel = False
    comm = create_communicator("xla")

    # One tiny eager 'auto'-wire gradient allreduce through a separate
    # communicator: every emitted trace then carries a REAL collective
    # event whose wire dtype was resolved by the autotune registry, with
    # the decision's provenance attached (ISSUE 2 acceptance). The
    # headline workloads keep their explicit bf16 wire — this demo never
    # touches their configuration.
    auto_demo_err = None
    try:
        auto_comm = create_communicator("xla", allreduce_grad_dtype="auto")
        auto_comm.allreduce_grad(
            {"g": jnp.ones((auto_comm.size, 4), jnp.float32)}
        )
        del auto_comm
    except Exception as e:
        # Record (never raise): the demo exists so the trace carries an
        # auto-provenance event — losing it silently would let a broken
        # provenance path masquerade as "no auto sites ran".
        auto_demo_err = f"{type(e).__name__}: {e}"[:160]

    steps, warmup = (20, 3) if on_accel else (5, 1)
    step, state, (x, y), batch, metric, knob_fields = _resnet_setup(
        comm, on_accel
    )

    # AOT-compile once; reuse the executable for the timing loops and pull
    # XLA's own FLOP count (of the per-device partitioned module) for MFU.
    step_flops = None
    try:
        compiled = step.lower(state, (x, y)).compile()
        analysis = compiled.cost_analysis()
        if analysis:
            a = analysis[0] if isinstance(analysis, (list, tuple)) else analysis
            step_flops = float(a.get("flops", 0.0)) or None
        step = compiled
    except Exception:
        pass

    # MFU keeps the MODEL-flops convention: under remat, cost_analysis
    # of the compiled step counts recompute as work, so pull the flops
    # from a remat-free compile of the same workload instead (one extra
    # AOT compile, only on the non-default path — same convention as
    # examples/imagenet/sweep_mfu.py). The probe's duplicate state is
    # deleted before the timed region so it cannot occupy HBM during
    # the measurement it calibrates.
    if knob_fields.get("resnet_remat", "none") != "none":
        try:
            step0, state0, batch0, _, _, _ = _resnet_setup(
                comm, on_accel, force_remat="none"
            )
            compiled0 = step0.lower(state0, batch0).compile()
            a0 = compiled0.cost_analysis()
            a0 = a0[0] if isinstance(a0, (list, tuple)) else a0
            model_flops = float(a0.get("flops", 0.0)) or None
            del step0, state0, batch0, compiled0
            if model_flops:
                step_flops = model_flops
                knob_fields["mfu_note"] = (
                    "model flops from the remat-free program; recompute "
                    "counted as price, not useful work"
                )
        except Exception as e:
            knob_fields["mfu_note"] = (
                f"remat-free flops compile failed ({type(e).__name__}); "
                "mfu uses compiled-step flops INCLUDING recompute"
            )

    for _ in range(warmup):
        state, metrics = step(state, (x, y))
    _fetch_scalar(metrics["loss"])

    # Steps chain through `state`; the loss fetch at the end forces the
    # device to have executed every step (true sync — see _fetch_scalar).
    def sample():
        nonlocal state, metrics
        t0 = time.perf_counter()
        for _ in range(steps):
            state, metrics = step(state, (x, y))
        _fetch_scalar(metrics["loss"])
        return time.perf_counter() - t0

    dt, headline_spread = _repeat_median(sample, 1 if on_accel else 3)

    images_per_sec = batch * steps / dt
    per_device = images_per_sec / comm.size
    vs_baseline = per_device / BASELINE_IMG_PER_SEC_PER_DEVICE

    out = {
        "metric": metric,
        "value": round(images_per_sec, 2),
        "unit": "images/sec",
        "vs_baseline": round(vs_baseline, 3),
        "step_time_ms": round(dt / steps * 1e3, 2),
        "device_kind": devices[0].device_kind,
        "n_devices": comm.size,
        "baseline_note": (
            "vs_baseline compares per-device img/s to the unverified "
            "125 img/s/P100 ChainerMN-era figure (different hardware); "
            "mfu is the hardware-honest metric"
        ),
        **knob_fields,
    }
    if auto_demo_err:
        out["trace_auto_demo_error"] = auto_demo_err
    if not on_accel:
        out["proxy_spread_pct"] = headline_spread
    peak = _peak_flops(devices[0].device_kind)
    if step_flops and peak:
        # cost_analysis() describes the per-device SPMD-partitioned module,
        # so compare against a single chip's peak.
        achieved = step_flops / (dt / steps)
        out["mfu"] = round(achieved / peak, 4)
        out["per_device_step_tflops"] = round(step_flops / 1e12, 3)

    # Emit the primary number NOW — if the supplementary benchmark below
    # stalls past the parent's budget, this line is what gets salvaged.
    print(json.dumps(out), flush=True)

    def supp(name: str, err_key: str, fn) -> None:
        """One supplementary phase: exception-isolated (never lose the
        primary number), cumulative line after each, and a span in the
        observability trace so the per-phase wall time is in the
        artifact, not just the log ordering. The span sits INSIDE the
        try so a failed phase records ok=False — catching inside the
        span would stamp every failure ok=True."""
        try:
            with obs_trace.span(f"bench:{name}"):
                out.update(fn())
        except Exception as e:
            out[err_key] = f"{type(e).__name__}: {e}"[:200]
        print(json.dumps(out), flush=True)

    supp("allreduce", "allreduce_error",
         lambda: _bench_allreduce(
             comm, 100_000_000 if on_accel else 10_000_000))
    supp("allreduce_curve", "allreduce_curve_error",
         lambda: _bench_allreduce_curve(comm, on_accel))
    supp("attention", "attn_error", lambda: _bench_attention(on_accel))
    # Early on purpose (round-4 VERDICT item 7): a Mosaic layout
    # rejection must reach the artifact even if the budget cuts the
    # expensive transformer/native phases below.
    supp("kernel_sweep", "kernel_sweep_error",
         lambda: _bench_kernel_sweep(on_accel))
    supp("double_buffer", "double_buffer_error",
         lambda: _bench_double_buffering(comm, on_accel))
    supp("overlap", "overlap_error",
         lambda: _bench_overlap(comm, on_accel))
    supp("composed", "composed_error",
         lambda: _bench_composed(comm, on_accel))
    supp("plan", "plan_error",
         lambda: _bench_plan(comm, on_accel))
    supp("seq_parallel", "seq_parallel_error",
         lambda: _bench_seq_parallel(comm, on_accel))
    supp("transformer", "transformer_error",
         lambda: _bench_transformer(comm, on_accel))
    supp("s2d_resnet", "s2d_error", lambda: _bench_s2d_resnet(comm, on_accel))
    supp("moe_dispatch", "moe_dispatch_error",
         lambda: _bench_moe_dispatch(on_accel))
    supp("moe", "moe_error",
         lambda: _bench_moe_plan(comm, on_accel))
    supp("serving", "serving_error",
         lambda: _bench_serving(comm, on_accel))
    supp("serving_prefix", "serving_prefix_error",
         lambda: _bench_serving_prefix(comm, on_accel))
    supp("serving_cluster", "serving_cluster_error",
         lambda: _bench_serving_cluster(comm, on_accel))
    supp("serving_burst", "serving_burst_error",
         lambda: _bench_serving_burst(comm, on_accel))
    supp("serving_sampled", "serving_sampled_error",
         lambda: _bench_serving_sampled(comm, on_accel))
    supp("serving_decode_kernel", "serving_decode_kernel_error",
         lambda: _bench_serving_decode_kernel(comm, on_accel))
    supp("serving_tenants", "serving_tenants_error",
         lambda: _bench_serving_tenants(comm, on_accel))
    # Last on purpose: this one spawns fresh child processes whose backend
    # init rolls the tunnel-flap dice — a stall here must only ever cost
    # this row, not any of the above.
    supp("native_input", "native_input_error",
         lambda: _bench_native_input(comm, on_accel))

    # Dispatch provenance: every decision the autotune registry
    # resolved during this run (full trail in the artifact, a compact
    # name=winner(source) summary on the driver line) — each capture
    # shows which path every tuned site took and why.
    try:
        from chainermn_tpu import tuning

        out["autotune_decisions"] = tuning.decisions_taken()
        out["autotune"] = tuning.decisions_summary(max_len=160)
    except Exception as e:
        out["autotune_error"] = f"{type(e).__name__}: {e}"[:120]
    if trace_path is not None:
        out["trace"] = trace_path
        rec = obs_trace.active()
        if rec is not None:
            rec.flush()
    # Metrics snapshot (ISSUE 6): counters/gauges + streaming histogram
    # quantiles over the whole run — full blob to BENCH_DETAILS.json
    # only (the compact stdout line keeps its whitelist).
    if obs_metrics is not None:
        try:
            reg = obs_metrics.active_registry()
            if reg is not None:
                out["metrics_snapshot"] = reg.snapshot()
        except Exception as e:
            out["metrics_snapshot_error"] = f"{type(e).__name__}: {e}"[:120]
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "--run":
        if sys.argv[2] == "native-loop":
            _run_native_loop()
        else:
            _run_bench(sys.argv[2])
    else:
        main()
