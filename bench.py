"""Benchmark driver: ResNet-50 data-parallel training throughput.

Prints ONE JSON line:
``{"metric": ..., "value": N, "unit": "images/sec", "vs_baseline": N}``.

The benchmark is the reference's headline workload (ResNet-50 ImageNet,
``examples/imagenet`` (dagger), SURVEY.md section 6): one fully-jitted SPMD
train step — forward, backward, bf16-compressed gradient allreduce over the
mesh, SGD update — on synthetic 224x224 data, i.e. the same measurement the
reference's images/sec numbers report (data pipeline excluded).

Baseline: ``BASELINE.json`` has ``"published": {}`` (the reference repo's own
numbers were unreadable — empty mount), so ``vs_baseline`` is computed against
the best documented ChainerMN-era per-accelerator throughput: the 15-minute
ImageNet run (Akiba, Suzuki & Fukuda, arXiv:1711.04325 — 90 epochs, 1024
P100s) ~= 125 images/sec/P100. UNVERIFIED external figure; see BASELINE.md.
"""

from __future__ import annotations

import json
import time

BASELINE_IMG_PER_SEC_PER_DEVICE = 125.0


def main() -> None:
    import jax
    import jax.numpy as jnp
    import optax

    from chainermn_tpu import create_communicator, create_multi_node_optimizer
    from chainermn_tpu.models import ResNet50, ResNet18
    from chainermn_tpu.training.train_step import (
        create_train_state,
        make_train_step,
    )

    devices = jax.devices()
    on_accel = devices[0].platform != "cpu"
    comm = create_communicator("xla")

    if on_accel:
        model = ResNet50(num_classes=1000)
        per_device_batch, hw, steps, warmup = 64, 224, 20, 3
        metric = "resnet50_images_per_sec"
    else:
        # CPU fallback so the bench always emits a line (tiny proxy model).
        model = ResNet18(num_classes=100, compute_dtype=jnp.float32)
        per_device_batch, hw, steps, warmup = 8, 32, 5, 1
        metric = "resnet18_cpu_proxy_images_per_sec"

    batch = per_device_batch * comm.size
    rng = jax.random.PRNGKey(0)
    x = jax.random.normal(rng, (batch, hw, hw, 3), jnp.float32)
    y = jax.random.randint(rng, (batch,), 0, 10)
    if jax.process_count() > 1:
        # Each process holds the full batch locally; assemble the global
        # sharded arrays the jitted step's in_specs expect.
        from jax.experimental import multihost_utils
        from jax.sharding import PartitionSpec as P

        x, y = multihost_utils.host_local_array_to_global_array(
            (x, y), comm.mesh, P()
        )

    variables = jax.jit(lambda k, xb: model.init(k, xb, train=True))(
        jax.random.PRNGKey(42), x[:2]
    )

    def loss_fn(params, batch_, model_state):
        xb, yb = batch_
        logits, mutated = model.apply(
            {"params": params, "batch_stats": model_state},
            xb,
            train=True,
            mutable=["batch_stats"],
        )
        loss = optax.softmax_cross_entropy_with_integer_labels(logits, yb).mean()
        return loss, ({}, mutated["batch_stats"])

    optimizer = create_multi_node_optimizer(
        optax.sgd(0.1, momentum=0.9), comm, allreduce_grad_dtype=jnp.bfloat16
    )
    state = create_train_state(
        variables["params"], optimizer, comm,
        model_state=variables["batch_stats"],
    )
    step = make_train_step(loss_fn, optimizer, comm)

    for _ in range(warmup):
        state, metrics = step(state, (x, y))
    jax.block_until_ready(state.params)

    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = step(state, (x, y))
    jax.block_until_ready(state.params)
    dt = time.perf_counter() - t0

    images_per_sec = batch * steps / dt
    per_device = images_per_sec / comm.size
    vs_baseline = per_device / BASELINE_IMG_PER_SEC_PER_DEVICE

    print(
        json.dumps(
            {
                "metric": metric,
                "value": round(images_per_sec, 2),
                "unit": "images/sec",
                "vs_baseline": round(vs_baseline, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
