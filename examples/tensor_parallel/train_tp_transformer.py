"""Tensor-parallel transformer block training — Megatron-style, end to end.

The reference's only intra-layer parallelism was the channel-split
convolution example (``examples/parallel_convolution`` (dagger)); this is
the general form on the :mod:`chainermn_tpu.parallel.tensor` library: a
transformer block with heads-sharded attention and hidden-sharded MLP over
a ``('data', 'model')`` mesh — exactly one ``psum`` per column→row pair,
gradients taken inside ``shard_map`` (the library's usage contract), data
parallelism composed on the second mesh axis.

    python examples/tensor_parallel/train_tp_transformer.py
    python examples/tensor_parallel/train_tp_transformer.py --dp 1  # tp-only

The task: next-token-style regression on sequences from a fixed random
teacher transformer — the student matches it only if attention AND MLP
gradients flow correctly through the sharded layers.
"""

from __future__ import annotations

import argparse
import sys

import jax
import jax.numpy as jnp
import numpy as np
import optax

sys.path.insert(0, __file__.rsplit("/examples/", 1)[0])

import chainermn_tpu
from chainermn_tpu import global_except_hook
from chainermn_tpu.parallel.tensor import (
    stack_tp_params,
    tp_attention,
    tp_mlp,
)


def main(argv=None):
    p = argparse.ArgumentParser(
        description="ChainerMN-TPU example: Megatron-style tensor parallelism"
    )
    p.add_argument("--communicator", default="naive")
    p.add_argument("--batchsize", type=int, default=32)
    p.add_argument("--seq-len", type=int, default=16)
    p.add_argument("--d-model", type=int, default=32)
    p.add_argument("--n-heads", type=int, default=8)
    p.add_argument("--iterations", type=int, default=200)
    p.add_argument("--lr", type=float, default=2e-3)
    p.add_argument("--dp", type=int, default=None,
                   help="data-parallel width; model axis gets the rest "
                        "(default: 2 when the device count allows, else 1)")
    args = p.parse_args(argv)

    comm = chainermn_tpu.create_communicator(args.communicator)
    global_except_hook._add_hook()
    from jax import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    n = comm.size
    if args.dp is None:
        args.dp = 2 if n % 2 == 0 and n > 1 else 1
    if n % args.dp:
        raise SystemExit(f"--dp {args.dp} must divide the device count {n}")
    tp = n // args.dp
    if args.n_heads % tp:
        raise SystemExit(f"--n-heads {args.n_heads} must divide by tp={tp}")
    mesh = Mesh(
        np.array(comm.mesh.devices.flat).reshape(args.dp, tp),
        ("data", "model"),
    )
    if comm.rank == 0:
        print(f"tensor parallel: dp={args.dp} x tp={tp}, "
              f"{args.n_heads} heads, d_model={args.d_model}")

    D, FF = args.d_model, 4 * args.d_model

    def init_full(seed):
        ks = jax.random.split(jax.random.key(seed), 6)
        s = 1.0 / np.sqrt(D)
        return {
            "wq": jax.random.normal(ks[0], (D, D)) * s,
            "wk": jax.random.normal(ks[1], (D, D)) * s,
            "wv": jax.random.normal(ks[2], (D, D)) * s,
            "wo": jax.random.normal(ks[3], (D, D)) * s,
            "w1": jax.random.normal(ks[4], (D, FF)) * s,
            "w2": jax.random.normal(ks[5], (FF, D)) * (1.0 / np.sqrt(FF)),
        }

    def shard_full(full):
        return {
            "wq": stack_tp_params(full["wq"], tp, 1),
            "wk": stack_tp_params(full["wk"], tp, 1),
            "wv": stack_tp_params(full["wv"], tp, 1),
            "wo": stack_tp_params(full["wo"], tp, 0),
            "w1": stack_tp_params(full["w1"], tp, 1),
            "w2": stack_tp_params(full["w2"], tp, 0),
        }

    def block(p, x):
        h = x + tp_attention(
            x, p["wq"], p["wk"], p["wv"], p["wo"],
            axis_name="model", n_heads=args.n_heads, causal=True,
        )
        return h + tp_mlp(h, p["w1"], None, p["w2"], None, axis_name="model")

    params = shard_full(init_full(0))
    opt = optax.adam(args.lr)
    opt_state = opt.init(params)
    p_spec = jax.tree.map(lambda _: P("model"), params)
    s_spec = jax.tree.map(
        lambda l: P("model") if getattr(l, "ndim", 0) >= 1 else P(), opt_state
    )

    def local_step(params, opt_state, x, t):
        def loss_fn(params):
            local = jax.tree.map(lambda l: l[0], params)
            y = block(local, x)
            return jnp.mean((y - t) ** 2)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        loss = jax.lax.pmean(loss, ("data", "model"))
        # TP-sharded weight grads are exact per shard; average over data.
        grads = jax.lax.pmean(grads, "data")
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    step = jax.jit(
        shard_map(
            local_step,
            mesh=mesh,
            in_specs=(p_spec, s_spec, P("data"), P("data")),
            out_specs=(p_spec, s_spec, P()),
            check_vma=False,
        )
    )

    # Teacher: a fixed full-width block generating the targets.
    teacher = init_full(123)

    @jax.jit
    def teacher_block(x):
        from chainermn_tpu.ops.attention import dot_product_attention

        B, T = x.shape[:2]
        hd = D // args.n_heads
        q = (x @ teacher["wq"]).reshape(B, T, args.n_heads, hd)
        k = (x @ teacher["wk"]).reshape(B, T, args.n_heads, hd)
        v = (x @ teacher["wv"]).reshape(B, T, args.n_heads, hd)
        h = x + dot_product_attention(q, k, v, causal=True).reshape(B, T, D) @ teacher["wo"]
        return h + jax.nn.gelu(h @ teacher["w1"]) @ teacher["w2"]

    rng = np.random.RandomState(0)
    for it in range(1, args.iterations + 1):
        x = jnp.asarray(
            rng.randn(args.batchsize, args.seq_len, D).astype(np.float32)
        )
        t = teacher_block(x)
        params, opt_state, loss = step(params, opt_state, x, t)
        if comm.rank == 0 and it % 50 == 0:
            print(f"iter {it}/{args.iterations} loss={float(loss):.4f}")
    if comm.rank == 0:
        print(f"final: loss={float(loss):.4f}")
    return float(loss)


if __name__ == "__main__":
    main()
