"""Distributed detection training — the Faster-RCNN-style stress workload.

Reference: the fork's benchmark configs list "ChainerCV Faster-RCNN (stress
hierarchical communicator, odd grad shapes)" (BASELINE.json; SURVEY.md §7).
This example reproduces the *stress profile* on synthetic data:

- multi-scale images drawn from a small (H, W) bucket ladder — one jit
  compile per bucket, counted and reported (the dynamic-shape discipline);
- ragged ground-truth boxes, padded + masked per image;
- the hierarchical communicator by default (the config this workload was
  meant to stress), odd-channel gradients through the fused grad pmean.

    python examples/detection/train_detection.py --communicator hierarchical
"""

from __future__ import annotations

import argparse
import sys

import jax
import jax.numpy as jnp
import numpy as np
import optax

sys.path.insert(0, __file__.rsplit("/examples/", 1)[0])

import chainermn_tpu
from chainermn_tpu import global_except_hook
from chainermn_tpu.models.detection import (
    TinyDetector,
    TwoStageDetector,
    detection_loss,
    two_stage_loss,
)

#: (H, W) bucket ladder — multiples of 32 (backbone stride x2 safety)
SHAPE_BUCKETS = ((256, 256), (256, 320), (320, 256), (320, 320))
MAX_BOXES = 8


def synthetic_batch(rng, batch, hw, with_labels=False):
    """Images + padded boxes (+ class labels) for one shape bucket."""
    H, W = hw
    images = rng.randn(batch, H, W, 3).astype(np.float32)
    n = rng.randint(1, MAX_BOXES + 1, size=batch)
    boxes = np.zeros((batch, MAX_BOXES, 4), np.float32)
    mask = np.zeros((batch, MAX_BOXES), np.float32)
    for i in range(batch):
        for j in range(n[i]):
            y0 = rng.uniform(0, H - 64)
            x0 = rng.uniform(0, W - 64)
            h = rng.uniform(32, min(160, H - y0))
            w = rng.uniform(32, min(160, W - x0))
            boxes[i, j] = (y0, x0, y0 + h, x0 + w)
            mask[i, j] = 1.0
    if with_labels:
        labels = rng.randint(0, 7, size=(batch, MAX_BOXES)).astype(np.int32)
        return images, boxes, mask, labels
    return images, boxes, mask


def main(argv=None):
    p = argparse.ArgumentParser(
        description="ChainerMN-TPU example: detection stress (Faster-RCNN-style)"
    )
    p.add_argument("--communicator", default="hierarchical")
    p.add_argument("--batchsize", type=int, default=8)
    p.add_argument("--iterations", type=int, default=24)
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--two-stage", action="store_true",
                   help="Faster-RCNN-style RPN -> static top-K proposals "
                        "-> RoI-align -> per-RoI class+box head")
    args = p.parse_args(argv)

    comm = chainermn_tpu.create_communicator(args.communicator)
    global_except_hook._add_hook()
    if comm.rank == 0:
        print(f"communicator: {comm}")

    model = TwoStageDetector() if args.two_stage else TinyDetector()
    optimizer = chainermn_tpu.create_multi_node_optimizer(
        optax.adam(args.lr), comm
    )
    axes = comm.grad_axes

    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    def build_step():
        def local_step(params, opt_state, batch):
            if args.two_stage:
                images, boxes, mask, labels = batch
            else:
                images, boxes, mask = batch

            def loss_fn(p):
                if args.two_stage:
                    return two_stage_loss(
                        model.apply(p, images), boxes, mask, labels
                    )
                obj, deltas = model.apply(p, images)
                return detection_loss(obj, deltas, boxes, mask)

            loss, grads = jax.value_and_grad(loss_fn)(params)
            grads = jax.lax.pmean(grads, axes)
            loss = jax.lax.pmean(loss, axes)
            updates, opt_state = optimizer.actual_optimizer.update(
                grads, opt_state, params
            )
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss

        return jax.jit(
            shard_map(
                local_step,
                mesh=comm.mesh,
                in_specs=(P(), P(), P(axes)),
                out_specs=(P(), P(), P()),
                check_vma=False,
            )
        )

    step = build_step()
    rng = np.random.RandomState(comm.rank * 0 + 11)  # same data all ranks
    params = None
    opt_state = None
    compiled_buckets = set()

    for it in range(args.iterations):
        hw = SHAPE_BUCKETS[it % len(SHAPE_BUCKETS)]
        batch = synthetic_batch(rng, args.batchsize, hw,
                                with_labels=args.two_stage)
        images = batch[0]
        if params is None:
            params = model.init(jax.random.key(0), jnp.asarray(images[:1]))
            params = comm.bcast_data(params)
            opt_state = optimizer.actual_optimizer.init(params)
        if hw not in compiled_buckets:
            compiled_buckets.add(hw)
            if comm.rank == 0:
                print(f"  compiling shape bucket {hw}")
        params, opt_state, loss = step(
            params, opt_state, tuple(jnp.asarray(a) for a in batch),
        )
        if comm.rank == 0 and (it + 1) % 8 == 0:
            print(f"iter {it + 1}/{args.iterations} loss={float(loss):.4f}")

    if comm.rank == 0:
        print(f"final loss={float(loss):.4f} "
              f"({len(compiled_buckets)} shape-bucket compilations)")
    return float(loss)


if __name__ == "__main__":
    main()
