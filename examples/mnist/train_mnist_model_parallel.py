"""Model-parallel MNIST: the MLP split across two stages.

Reference: ``examples/mnist/train_mnist_model_parallel.py`` (dagger)
(SURVEY.md section 2.8): the 3-layer MLP is split across 2 ranks connected by
differentiable send/recv; rank 1 holds the loss.

TPU-native: the two stages are a :class:`MultiNodeChainList` executed as one
SPMD program over a ``'stage'`` mesh axis — stage transfers are ppermutes,
backward crosses the boundary automatically.

    python examples/mnist/train_mnist_model_parallel.py --iterations 100
"""

from __future__ import annotations

import argparse
import sys

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import shard_map
from jax.sharding import PartitionSpec as P

sys.path.insert(0, __file__.rsplit("/examples/", 1)[0])

import chainermn_tpu
from chainermn_tpu.links import MultiNodeChainList
from examples.mnist.train_mnist import get_mnist


def main(argv=None):
    p = argparse.ArgumentParser(description="model-parallel MNIST")
    p.add_argument("--communicator", default="naive")
    p.add_argument("--batchsize", type=int, default=128)
    p.add_argument("--iterations", type=int, default=100)
    p.add_argument("--lr", type=float, default=0.05)
    p.add_argument("--n-units", type=int, default=256)
    args = p.parse_args(argv)

    comm = chainermn_tpu.create_communicator(args.communicator)
    if comm.rank == 0:
        print(f"communicator: {comm} (2-stage model parallel)")

    # Single-controller SPMD: one process feeds the whole mesh. (In the
    # reference, non-data ranks held a create_empty_dataset placeholder; that
    # pattern applies here only in multi-process model parallelism.)
    train, _ = get_mnist()

    n_units = args.n_units

    def stage0_fn(params, x):
        h = jnp.maximum(x @ params["w0"] + params["b0"], 0.0)
        return jnp.maximum(h @ params["w1"] + params["b1"], 0.0)

    def stage0_init(rng, x):
        k0, k1 = jax.random.split(rng)
        s0 = 1.0 / np.sqrt(x.shape[-1])
        s1 = 1.0 / np.sqrt(n_units)
        return {
            "w0": jax.random.normal(k0, (x.shape[-1], n_units)) * s0,
            "b0": jnp.zeros(n_units),
            "w1": jax.random.normal(k1, (n_units, n_units)) * s1,
            "b1": jnp.zeros(n_units),
        }

    def stage1_fn(params, h):
        return h @ params["w2"] + params["b2"]

    def stage1_init(rng, h):
        s = 1.0 / np.sqrt(h.shape[-1])
        return {
            "w2": jax.random.normal(rng, (h.shape[-1], 10)) * s,
            "b2": jnp.zeros(10),
        }

    model = MultiNodeChainList(comm, axis_name=comm.axis_name)
    model.add_link(stage0_fn, rank=0, rank_out=1, init_fn=stage0_init)
    model.add_link(stage1_fn, rank=1, rank_in=0, init_fn=stage1_init)

    x0 = jnp.zeros((args.batchsize, 784))
    params = model.init(jax.random.key(0), x0)
    opt = optax.sgd(args.lr, momentum=0.9)
    opt_state = opt.init(params)

    mesh = comm.mesh
    ax = comm.axis_name

    def sharded_loss(params, x, y):
        """Replicated scalar loss of the multi-stage model. Differentiate
        *outside* the shard_map: the per-stage cotangents then route back
        through the stage transfers exactly once (differentiating a
        replicated loss inside each shard would multiply gradients by the
        axis size — see tests/test_links.py::test_chain_gradients...)."""

        def body(params, x, y):
            logits = model.apply(params, x)
            # logits live on stage 1's shard (zeros elsewhere); the psum is
            # both the broadcast and, under AD, the single fan-in point.
            logits = jax.lax.psum(logits, ax)
            loss = optax.softmax_cross_entropy_with_integer_labels(
                logits, y
            ).mean()
            acc = (logits.argmax(-1) == y).mean()
            return loss, acc

        return shard_map(
            body, mesh=mesh, in_specs=(P(), P(), P()), out_specs=(P(), P()),
            check_vma=False,
        )(params, x, y)

    @jax.jit
    def step(params, opt_state, x, y):
        (loss, acc), grads = jax.value_and_grad(
            sharded_loss, has_aux=True
        )(params, x, y)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss, acc

    items = list(train)
    rng = np.random.RandomState(1)
    acc = jnp.zeros(())
    for it in range(args.iterations):
        idx = rng.randint(0, len(items), size=args.batchsize)
        x = np.stack([items[i][0] for i in idx])
        y = np.stack([items[i][1] for i in idx])
        params, opt_state, loss, acc = step(params, opt_state, x, y)
        if comm.rank == 0 and (it + 1) % 25 == 0:
            print(
                f"iter {it + 1}/{args.iterations} "
                f"loss={float(loss):.4f} acc={float(acc):.4f}"
            )
    final_acc = float(acc)
    if comm.rank == 0:
        print(f"final acc={final_acc:.4f}")
    return final_acc


if __name__ == "__main__":
    main()
