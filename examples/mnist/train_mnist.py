"""Distributed MNIST training — the canonical smoke test.

Reference: ``examples/mnist/train_mnist.py`` (dagger) (SURVEY.md section 2.8):
``mpiexec -n N python train_mnist.py --communicator <name> --gpu``.

TPU-native: one process drives the whole mesh; run

    python examples/mnist/train_mnist.py --communicator naive      # CPU mesh
    python examples/mnist/train_mnist.py --communicator xla        # TPU

No torchvision/network: MNIST is synthesised deterministically when the real
ubyte files are absent (the training mechanics — scatter, psum, optimizer,
eval — are identical either way).
"""

from __future__ import annotations

import argparse
import sys

import jax
import jax.numpy as jnp
import numpy as np
import optax

sys.path.insert(0, __file__.rsplit("/examples/", 1)[0])

import chainermn_tpu
from chainermn_tpu import global_except_hook
from chainermn_tpu.models import MLP
from chainermn_tpu.training import Trainer, make_train_step, make_eval_step
from chainermn_tpu.training.train_step import create_train_state


def get_mnist(n_train=8192, n_test=1024, seed=0):
    """Synthetic stand-in with MNIST shapes: 10 gaussian blobs in 784-d.
    Learnable by an MLP, so accuracy is a meaningful smoke signal."""
    rng = np.random.RandomState(seed)
    centers = rng.randn(10, 784).astype(np.float32)

    def make(n):
        y = rng.randint(0, 10, size=n)
        x = centers[y] + 0.5 * rng.randn(n, 784).astype(np.float32)
        return [(x[i], np.int32(y[i])) for i in range(n)]

    return make(n_train), make(n_test)


def main(argv=None):
    p = argparse.ArgumentParser(description="ChainerMN-TPU example: MNIST")
    p.add_argument("--communicator", default="naive")
    p.add_argument("--batchsize", type=int, default=256)
    p.add_argument("--iterations", type=int, default=200)
    p.add_argument("--lr", type=float, default=0.05)
    p.add_argument("--double-buffering", action="store_true")
    p.add_argument("--local-sgd", type=int, default=0, metavar="H",
                   help="periodic parameter averaging every H steps "
                        "instead of the per-step gradient allreduce; "
                        "0 = off")
    p.add_argument("--outer-momentum", type=float, default=0.0,
                   help="DiLoCo outer heavy-ball momentum on the sync "
                        "deltas (try 0.6-0.9 with a reduced inner lr; "
                        "stacking it on an aggressive inner momentum "
                        "can diverge)")
    p.add_argument("--allreduce-grad-dtype", default=None)
    p.add_argument("--reduction-schedule", default=None,
                   metavar="SCHED",
                   help="gradient-reduction schedule: flat | two_level "
                        "| zero | auto | a composition signature, "
                        "sliced forms included (e.g. "
                        "'rs(data)[s0..3]>ag(data)'); default: the "
                        "communicator's own strategy")
    p.add_argument("--error-feedback", action="store_true",
                   help="EF-SGD residual feedback over the int8 wire "
                        "(requires --allreduce-grad-dtype int8)")
    p.add_argument("--checkpoint", default=None, metavar="DIR",
                   help="fault-tolerant snapshots every --checkpoint-interval "
                        "iters (async native writer); resumes automatically "
                        "from the newest snapshot all ranks share")
    p.add_argument("--checkpoint-interval", type=int, default=50)
    p.add_argument("--prefetch", type=int, default=0,
                   help="device-side input double buffering: batches kept "
                        "in flight ahead of the step (0 = off)")
    p.add_argument("--checkpoint-backend", default="npz",
                   choices=("npz", "orbax"),
                   help="npz: the framework's per-rank snapshot format; "
                        "orbax: stock orbax CheckpointManager storage with "
                        "the same cross-rank resume agreement")
    args = p.parse_args(argv)

    comm = chainermn_tpu.create_communicator(
        args.communicator, allreduce_grad_dtype=args.allreduce_grad_dtype
    )
    global_except_hook._add_hook()
    if comm.rank == 0:
        print(f"communicator: {comm}")

    train, test = get_mnist()
    # No-transfer scatter: each process computes its own shard (SURVEY 3.3).
    train = chainermn_tpu.scatter_dataset(train, comm, shuffle=True, seed=42)
    test = chainermn_tpu.scatter_dataset(test, comm)

    model = MLP()
    params = model.init(jax.random.key(0), jnp.zeros((1, 784)))["params"]

    if args.local_sgd:
        bad = [f for f, on in (
            ("--double-buffering", args.double_buffering),
            ("--error-feedback", args.error_feedback),
            ("--allreduce-grad-dtype", args.allreduce_grad_dtype),
            ("--reduction-schedule", args.reduction_schedule),
        ) if on]
        if bad:
            p.error(f"--local-sgd replaces the per-step gradient wire; "
                    f"{', '.join(bad)} would be silently ignored")
        optimizer = chainermn_tpu.create_local_sgd(
            optax.sgd(args.lr, momentum=0.9), comm,
            sync_every=args.local_sgd,
            outer_momentum=args.outer_momentum,
        )
    else:
        optimizer = chainermn_tpu.create_multi_node_optimizer(
            optax.sgd(args.lr, momentum=0.9),
            comm,
            double_buffering=args.double_buffering,
            error_feedback=args.error_feedback,
            reduction_schedule=args.reduction_schedule,
        )
    state = create_train_state(params, optimizer, comm)

    def loss_fn(params, batch):
        x, y = batch
        logits = model.apply({"params": params}, x)
        loss = optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()
        acc = (logits.argmax(-1) == y).mean()
        return loss, {"accuracy": acc}

    step = make_train_step(loss_fn, optimizer, comm)

    def metric_fn(params, batch):
        x, y = batch
        logits = model.apply({"params": params}, x)
        return {
            "val_loss": optax.softmax_cross_entropy_with_integer_labels(
                logits, y
            ).mean(),
            "val_acc": (logits.argmax(-1) == y).mean(),
        }

    eval_step = make_eval_step(metric_fn, comm)
    evaluator = chainermn_tpu.create_multi_node_evaluator(
        _evaluate(eval_step, test, args.batchsize, comm), comm
    )

    ckpt = None
    start_iteration = 0
    if args.checkpoint:
        if args.checkpoint_backend == "orbax":
            from chainermn_tpu.extensions import create_orbax_checkpointer

            ckpt = create_orbax_checkpointer(
                "mnist", comm, path=args.checkpoint
            )
        else:
            ckpt = chainermn_tpu.create_multi_node_checkpointer(
                "mnist", comm, path=args.checkpoint
            )
        state, restored_it = ckpt.maybe_load(state)
        if restored_it is not None:
            start_iteration = restored_it
            if comm.rank == 0:
                print(f"resumed from iteration {restored_it}")

    train_iter = chainermn_tpu.create_synchronized_iterator(
        train, args.batchsize, comm, seed=1
    )
    trainer = Trainer(step, state, train_iter, comm, log_interval=50,
                      prefetch=args.prefetch)

    def run_eval(tr):
        metrics = evaluator(tr.state)
        if comm.rank == 0:
            print("  eval:", {k: round(v, 4) for k, v in metrics.items()})

    trainer.extend(run_eval, interval=100)
    if ckpt is not None:
        def snapshot(tr):
            # async: serialize now, write+fsync on the C++ worker thread
            ckpt.save(tr.state, start_iteration + tr.iteration, block=False)

        trainer.extend(snapshot, interval=args.checkpoint_interval)
    state = trainer.run(max(0, args.iterations - start_iteration))
    if ckpt is not None:
        # Label with the TRUE iteration: when a restore already exceeded
        # --iterations, trainer.run did 0 steps and the weights are still
        # start_iteration's.
        ckpt.save(state, start_iteration + trainer.iteration, block=False)
        ckpt.close()  # drain async saves + release the backend

    final = evaluator(state)
    if comm.rank == 0:
        print("final:", {k: round(v, 4) for k, v in final.items()})
    return final


def _evaluate(eval_step, dataset, batchsize, comm):
    from chainermn_tpu.training.trainer import (
        default_collate,
        host_local_batch_to_global,
    )

    def fn(st):
        totals, n = {}, 0
        items = list(dataset)
        n_batches = max(0, (len(items) - batchsize) // batchsize + 1)
        if comm.host.size > 1:
            # Batch assembly below is collective: every process must run
            # the same number of iterations even if shard sizes differ ±1.
            n_batches = min(comm.allgather_obj(n_batches))
        for b in range(n_batches):
            i = b * batchsize
            batch = host_local_batch_to_global(
                default_collate(items[i : i + batchsize]), comm
            )
            m = eval_step(st.params, batch, st.model_state)
            for k, v in m.items():
                totals[k] = totals.get(k, 0.0) + float(v)
            n += 1
        return {k: v / max(n, 1) for k, v in totals.items()}

    return fn


if __name__ == "__main__":
    main()
