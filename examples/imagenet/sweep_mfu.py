"""ResNet-50 MFU sweep — perf methodology tool for the tracked
``resnet50_images_per_sec`` / ``mfu`` headline (SURVEY.md section 6,
BASELINE.json's benchmark workload; reference:
``examples/imagenet/train_imagenet.py`` †).

The b128 v5e train step is HBM-bandwidth-bound (see the remat note in
:mod:`chainermn_tpu.models.resnet`: ~46 GB touched/step vs ~15 ms of
pure FLOPs), so the knobs that matter are the ones that cut *bytes*:

  - remat mode: ``none`` | ``full`` (save nothing per block — measured
    r2: loses, 57->66 ms) | ``conv`` (save conv outputs, recompute only
    the elementwise BN/relu chain — cuts ~2/3 of saved-activation bytes
    for VPU-trivial recompute). MXU FLOPs are free when bandwidth gates;
    remat trades them for the bytes that actually gate throughput.
  - per-device batch: amortizes fixed per-step costs; changes the
    compiler's fusion/layout choices.
  - stem: ``standard`` (headline, weight-compatible) vs
    ``space_to_depth`` (MLPerf-era TPU stem, reported separately).
  - donation: in-place state buffers remove a params-sized copy.

Prints one JSON line per variant plus a ranked summary. Run on chip:

    python examples/imagenet/sweep_mfu.py
    python examples/imagenet/sweep_mfu.py --batches 128,256 --steps 10

MFU convention: MODEL flops (3x the forward conv/matmul FLOPs of the
un-rematerialized network), so remat recompute counts as price, not
useful work — directly comparable to bench.py's ``mfu``.
"""

from __future__ import annotations

import argparse
import itertools
import json
import sys
import time

import jax
import jax.numpy as jnp
import optax

sys.path.insert(0, __file__.rsplit("/examples/", 1)[0])

from bench import _fetch_scalar, _peak_flops

from chainermn_tpu import create_communicator, create_multi_node_optimizer
from chainermn_tpu.models import ResNet50
from chainermn_tpu.training.train_step import (
    create_train_state,
    make_train_step,
)


# MODEL flops per (per-device batch, stem): captured from XLA
# cost_analysis of the remat=False program — remat recompute is price,
# not useful work, so rematerialized variants are scored against the
# plain program's flops (same convention as bench.py's mfu).
_MODEL_FLOPS: dict = {}


def time_variant(comm, args, *, remat: str, per_device_batch: int,
                 stem: str, donate: bool) -> dict:
    on_cpu = jax.devices()[0].platform == "cpu"
    model = ResNet50(
        num_classes=1000, stem=stem, remat=remat != "none",
        remat_policy="conv" if remat == "conv" else None,
        compute_dtype=jnp.float32 if on_cpu else jnp.bfloat16,
    )
    hw = 64 if on_cpu else 224
    batch = per_device_batch * comm.size
    rng = jax.random.PRNGKey(0)
    x = jax.random.normal(rng, (batch, hw, hw, 3), jnp.bfloat16)
    y = jax.random.randint(rng, (batch,), 0, 1000)
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        from jax.sharding import PartitionSpec as P

        x, y = multihost_utils.host_local_array_to_global_array(
            (x, y), comm.mesh, P()
        )
    variables = jax.jit(lambda k, xb: model.init(k, xb, train=True))(
        jax.random.PRNGKey(42), x[:2]
    )

    def loss_fn(params, batch_, model_state):
        xb, yb = batch_
        logits, mutated = model.apply(
            {"params": params, "batch_stats": model_state}, xb,
            train=True, mutable=["batch_stats"],
        )
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, yb
        ).mean()
        return loss, ({}, mutated["batch_stats"])

    optimizer = create_multi_node_optimizer(
        optax.sgd(0.1, momentum=0.9), comm,
        allreduce_grad_dtype=jnp.bfloat16,
    )
    state = create_train_state(
        variables["params"], optimizer, comm,
        model_state=variables["batch_stats"],
    )
    step = make_train_step(loss_fn, optimizer, comm, donate=donate)

    t_c0 = time.perf_counter()
    compiled = step.lower(state, (x, y)).compile()
    compile_s = time.perf_counter() - t_c0
    hw_flops = None
    try:
        a = compiled.cost_analysis()
        a = a[0] if isinstance(a, (list, tuple)) else a
        hw_flops = float(a.get("flops", 0.0)) or None
    except Exception:
        pass
    if hw_flops and remat == "none":
        _MODEL_FLOPS[(per_device_batch, stem)] = hw_flops

    state, m = compiled(state, (x, y))
    for _ in range(2):  # warm
        state, m = compiled(state, (x, y))
    _fetch_scalar(m["loss"])
    t0 = time.perf_counter()
    for _ in range(args.steps):
        state, m = compiled(state, (x, y))
    _fetch_scalar(m["loss"])
    dt = (time.perf_counter() - t0) / args.steps

    out = {
        "remat": remat, "batch": per_device_batch, "stem": stem,
        "donate": donate,
        "step_ms": round(dt * 1e3, 2),
        "images_per_sec": round(batch / dt, 2),
        "compile_s": round(compile_s, 1),
    }
    peak = _peak_flops(jax.devices()[0].device_kind)
    model_flops = _MODEL_FLOPS.get((per_device_batch, stem), hw_flops)
    if peak and model_flops:
        out["mfu"] = round(model_flops / dt / peak, 4)
        if hw_flops and model_flops and hw_flops > model_flops * 1.01:
            out["recompute_flops_ratio"] = round(hw_flops / model_flops, 3)
    return out


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--communicator", default="xla")
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--batches", type=str, default="128,256",
                   help="comma list of per-device batch sizes")
    p.add_argument("--remat", type=str, default="none,conv,full",
                   help="comma list of none|conv|full")
    p.add_argument("--stems", type=str, default="standard,space_to_depth")
    p.add_argument("--donate", type=str, default="true")
    args = p.parse_args(argv)

    comm = create_communicator(args.communicator)

    def bools(s, flag):
        out = []
        for v in s.split(","):
            v = v.strip().lower()
            if v not in ("true", "false"):
                p.error(f"{flag} values must be true/false, got {v!r}")
            out.append(v == "true")
        return out

    batches = [int(s) for s in args.batches.split(",")]
    results = []
    remats = [s.strip() for s in args.remat.split(",")]
    for r_ in remats:
        if r_ not in ("none", "conv", "full"):
            p.error(f"--remat values must be none|conv|full, got {r_!r}")
    for remat, b, stem, donate in itertools.product(
        remats, batches,
        args.stems.split(","), bools(args.donate, "--donate"),
    ):
        try:
            r = time_variant(comm, args, remat=remat, per_device_batch=b,
                             stem=stem, donate=donate)
        except Exception as e:  # OOM: keep sweeping
            r = {"remat": remat, "batch": b, "stem": stem, "donate": donate,
                 "error": f"{type(e).__name__}: {e}"[:160]}
        print(json.dumps(r), flush=True)
        results.append(r)

    ok = [r for r in results if "step_ms" in r]
    # Best by MFU (fallback throughput): batch is a grid dimension, so
    # step_ms ordering would rank the smallest batch first regardless of
    # efficiency. The fallback is PER-RUN, not per-row — mixing mfu
    # (<=1) with raw throughput (thousands) would rank any mfu-less row
    # first; a row missing mfu in an mfu-bearing run ranks last (0).
    if any("mfu" in r for r in ok):
        ok.sort(key=lambda r: -r.get("mfu", 0))
    else:
        ok.sort(key=lambda r: -r.get("images_per_sec", 0))
    if ok:
        print(json.dumps({"best": ok[0], "n_variants": len(results)}))
    return ok


if __name__ == "__main__":
    main()
