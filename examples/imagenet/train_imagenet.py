"""Distributed ImageNet training — the benchmark workload.

Reference: ``examples/imagenet/train_imagenet.py`` (dagger) (SURVEY.md
section 2.8): ``mpiexec -n N python train_imagenet.py --arch resnet50
--communicator pure_nccl``. The BASELINE.json north star measures this
workload's scaling efficiency.

TPU-native: one process drives the mesh; the whole iteration (fwd, bwd,
bf16-compressed gradient psum, SGD) is one jitted SPMD program.

    python examples/imagenet/train_imagenet.py --arch resnet50 \
        --communicator xla --iterations 100 [--profile /tmp/trace]

Data: synthetic ImageNet-shaped samples by default (no network in this
environment); pass ``--train-root`` with a directory of ``.npy`` pairs to
train on real data — the training mechanics are identical.
"""

from __future__ import annotations

import argparse
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

sys.path.insert(0, __file__.rsplit("/examples/", 1)[0])

import chainermn_tpu
from chainermn_tpu import global_except_hook
from chainermn_tpu.models import VisionTransformer, AlexNet, GoogLeNet, ResNet50
from chainermn_tpu.training import make_train_step
from chainermn_tpu.training.train_step import create_train_state

ARCHS = {
    # dropout off: a per-step rng is model-specific plumbing this throughput
    # example doesn't need
    "alex": lambda bn_ax, **kw: AlexNet(dropout_rate=0.0),
    "googlenet": lambda bn_ax, **kw: GoogLeNet(),
    "googlenetbn": lambda bn_ax, **kw: GoogLeNet(use_bn=True, bn_axis_name=bn_ax),
    "resnet50": lambda bn_ax, **kw: ResNet50(bn_axis_name=bn_ax, **kw),
    # The TPU-natural ImageNet family (round 5): pure large matmuls, no
    # MXU-starving small-channel convs, no BatchNorm cross-rank sync.
    "vit_s16": lambda bn_ax, **kw: VisionTransformer(**kw),
}


def synthetic_batch(rng, batch, size):
    x = rng.standard_normal((batch, size, size, 3), np.float32)
    y = rng.integers(0, 1000, size=(batch,)).astype(np.int32)
    return x, y


def main(argv=None):
    p = argparse.ArgumentParser(description="ChainerMN-TPU example: ImageNet")
    p.add_argument("--arch", default="resnet50", choices=sorted(ARCHS))
    p.add_argument("--communicator", default="xla")
    p.add_argument("--batchsize", type=int, default=64,
                   help="per-mesh-slot batch size")
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("--iterations", type=int, default=100)
    p.add_argument("--lr", type=float, default=0.1)
    p.add_argument("--optimizer", default="sgd",
                   choices=["sgd", "lars", "lamb"],
                   help="sgd+momentum (default) or the large-batch "
                        "layer-adaptive optimizers — the regime the "
                        "reference's 15-minute/32K-batch ImageNet runs "
                        "lived in (arXiv:1711.04325)")
    p.add_argument("--double-buffering", action="store_true")
    p.add_argument("--local-sgd", type=int, default=0, metavar="H",
                   help="periodic parameter averaging every H steps "
                        "instead of the per-step gradient allreduce "
                        "(composes with --optimizer); 0 = off")
    p.add_argument("--allreduce-grad-dtype", default="bfloat16")
    p.add_argument("--error-feedback", action="store_true",
                   help="EF-SGD for the int8 quantized wire (requires "
                        "--allreduce-grad-dtype int8); shard-level on "
                        "the two_dimensional communicator")
    p.add_argument("--stem", default="standard",
                   choices=["standard", "space_to_depth"],
                   help="resnet50 input stem; space_to_depth trades the "
                        "MXU-hostile 3-channel 7x7 conv for a 48-channel "
                        "3x3 (measured +16%% img/s on v5e)")
    p.add_argument("--remat", nargs="?", const="full",
                   default=None,
                   choices=["full", "conv", "dots", "nothing"],
                   help="rematerialize blocks. resnet50: 'full' (save only "
                        "block inputs — max memory saving) or 'conv' (save "
                        "conv outputs, recompute the BN/relu chain — the "
                        "byte-cutting mode from the docs/benchmarks.md "
                        "roofline); bare --remat means 'full'. vit_s16: "
                        "'dots' (keep matmul outputs) or 'nothing' (the "
                        "LM policies)")
    p.add_argument("--profile", default=None,
                   help="directory for a jax.profiler trace of iters 10-20")
    p.add_argument("--train-root", default=None)
    p.add_argument("--native-loader", default=None, metavar="FILE.bin",
                   help="fixed-record file read by the C++ threaded "
                        "prefetch loader (chainermn_tpu.native.data_loader)")
    args = p.parse_args(argv)
    if args.local_sgd and (args.double_buffering or args.error_feedback):
        p.error("--local-sgd replaces the per-step gradient wire; "
                "--double-buffering/--error-feedback would be "
                "silently ignored")

    comm = chainermn_tpu.create_communicator(
        args.communicator,
        allreduce_grad_dtype=args.allreduce_grad_dtype or None,
    )
    global_except_hook._add_hook()
    if comm.rank == 0:
        print(f"communicator: {comm}  arch: {args.arch}")

    _REMAT_OF = {"resnet50": ("full", "conv"),
                 "vit_s16": ("dots", "nothing")}
    if args.remat and args.remat not in _REMAT_OF.get(args.arch, ()):
        p.error(
            f"--remat {args.remat} is not a policy of --arch {args.arch} "
            f"(valid for {args.arch}: {_REMAT_OF.get(args.arch, ())})")
    if args.stem != "standard" and args.arch != "resnet50":
        p.error(f"--stem is only supported for --arch resnet50 "
                f"(got {args.arch!r})")
    kw = {}
    if args.remat:
        kw["remat"] = True
        if args.remat != "full":
            kw["remat_policy"] = args.remat
    if args.arch == "resnet50":
        kw["stem"] = args.stem
    model = ARCHS[args.arch](comm.bn_axis_name, **kw)
    global_batch = args.batchsize * comm.size
    rng = np.random.default_rng(0)

    loader = None
    if args.native_loader:
        from chainermn_tpu.native.data_loader import NativeDataLoader

        hw = args.image_size
        # Each process reads only its own record-range shard (the dataset
        # scatter of SURVEY.md section 3.3 applied to files — same ±1
        # balance as scatter_dataset) and assembles the global batch from
        # it — sample-parallel across hosts.
        import os

        from chainermn_tpu.datasets.scatter_dataset import _shard_bounds

        n_proc, proc = jax.process_count(), jax.process_index()
        n_total = os.path.getsize(args.native_loader) // (hw * hw * 3 + 4)
        loader = NativeDataLoader(
            args.native_loader,
            [("image", np.uint8, (hw, hw, 3)), ("label", np.int32, ())],
            batch_size=global_batch,
            threads=4,
            prefetch=4,
            seed=proc,
            shard=_shard_bounds(n_total, n_proc, proc) if n_proc > 1 else None,
        )

    # u8 records cross host→device as u8 (4x fewer bytes) and normalise
    # on-device; the jitted cast fuses ahead of the first conv. The
    # prefetch_to_device wrapper keeps 2 batches in flight so the H2D
    # copy of batch t+1 overlaps the step running on batch t.
    _norm = jax.jit(lambda img: img.astype(jnp.float32) / 127.5 - 1.0)

    if loader is not None:
        from chainermn_tpu.training.prefetch import prefetch_to_device

        _prefetched = prefetch_to_device(
            ((b["image"], b["label"]) for b in loader), size=2
        )

        def next_batch():
            img, lab = next(_prefetched)
            return _norm(img), lab
    else:

        def next_batch():
            return synthetic_batch(rng, global_batch, args.image_size)

    x0, y0 = next_batch()

    variables = jax.jit(
        lambda k, xb: model.init(k, xb, train=True)
    )(jax.random.key(0), jnp.asarray(x0[: min(2, global_batch)]))
    batch_stats = variables.get("batch_stats", {})

    def loss_fn(params, batch, model_state):
        xb, yb = batch
        vars_in = {"params": params}
        mutable = []
        if batch_stats:
            vars_in["batch_stats"] = model_state
            mutable = ["batch_stats"]
        if mutable:
            logits, mutated = model.apply(
                vars_in, xb, train=True, mutable=mutable
            )
        else:
            logits = model.apply(vars_in, xb, train=True)
            mutated = {"batch_stats": model_state}
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, yb
        ).mean()
        acc = (logits.argmax(-1) == yb).mean()
        return loss, ({"accuracy": acc}, mutated.get("batch_stats", ()))

    inner_opt = {
        "sgd": lambda: optax.sgd(args.lr, momentum=0.9),
        "lars": lambda: optax.lars(args.lr),
        "lamb": lambda: optax.lamb(args.lr),
    }[args.optimizer]()
    if args.local_sgd:
        optimizer = chainermn_tpu.create_local_sgd(
            inner_opt, comm, sync_every=args.local_sgd,
        )
    else:
        optimizer = chainermn_tpu.create_multi_node_optimizer(
            inner_opt,
            comm,
            double_buffering=args.double_buffering,
            error_feedback=args.error_feedback,
        )
    state = create_train_state(
        variables["params"], optimizer, comm, model_state=batch_stats
    )
    step = make_train_step(loss_fn, optimizer, comm)

    t0 = time.perf_counter()
    for it in range(args.iterations):
        if args.profile and it == 10:
            jax.profiler.start_trace(args.profile)
        x, y = next_batch()
        state, metrics = step(state, (jnp.asarray(x), jnp.asarray(y)))
        if args.profile and it == 20:
            jax.block_until_ready(state.params)
            jax.profiler.stop_trace()
            if comm.rank == 0:
                print(f"profile written to {args.profile}")
        if comm.rank == 0 and (it + 1) % 10 == 0:
            jax.block_until_ready(metrics["loss"])
            dt = time.perf_counter() - t0
            ips = global_batch * (it + 1) / dt
            print(
                f"iter {it + 1}/{args.iterations} "
                f"loss={float(metrics['loss']):.4f} "
                f"acc={float(metrics['accuracy']):.4f} ({ips:.1f} img/s)"
            )
    jax.block_until_ready(state.params)
    if comm.rank == 0:
        total = time.perf_counter() - t0
        print(
            f"done: {args.iterations} iters, "
            f"{global_batch * args.iterations / total:.1f} images/sec"
        )


if __name__ == "__main__":
    main()
