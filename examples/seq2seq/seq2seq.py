"""Distributed seq2seq MT training — the variable-length-gradients workload.

Reference: ``examples/seq2seq/seq2seq.py`` (dagger) (SURVEY.md section 2.8):
LSTM encoder-decoder on WMT/europarl, the workload whose ragged batches
stressed the reference's gradient packer. Under XLA the analogous stress is
the *compile cache*: this example demonstrates the bucketing discipline
(:mod:`chainermn_tpu.datasets.bucketing`) — every batch shape is drawn from
a fixed bucket ladder, so the jitted train step compiles once per bucket.

    python examples/seq2seq/seq2seq.py --communicator naive --iterations 60

Data: synthetic "copy-with-noise translation" pairs (no corpus in this
environment); pass ``--train-file`` (tab-separated token-id lines) for real
data.
"""

from __future__ import annotations

import argparse
import sys

import jax
import jax.numpy as jnp
import numpy as np
import optax

sys.path.insert(0, __file__.rsplit("/examples/", 1)[0])

import chainermn_tpu
from chainermn_tpu import global_except_hook
from chainermn_tpu.datasets.bucketing import bucket_batches
from chainermn_tpu.models import Seq2Seq, seq2seq_loss
from chainermn_tpu.models.seq2seq import beam_search_decode, greedy_decode
from chainermn_tpu.utils import bleu as bleu_utils

VOCAB = 128
BOS = 1
EOS = 2


def synthetic_pairs(n, seed):
    """tgt = reversed src, EOS-terminated — learnable, ragged."""
    rng = np.random.RandomState(seed)
    pairs = []
    for _ in range(n):
        L = rng.randint(4, 30)
        src = rng.randint(3, VOCAB, size=L)
        tgt = src[::-1].copy()
        pairs.append((list(src), list(tgt) + [EOS]))
    return pairs


def main(argv=None):
    p = argparse.ArgumentParser(description="ChainerMN-TPU example: seq2seq")
    p.add_argument("--communicator", default="naive")
    p.add_argument("--batchsize", type=int, default=32,
                   help="global batch size (must divide by mesh size)")
    p.add_argument("--iterations", type=int, default=60)
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--train-file", default=None)
    p.add_argument("--eval", action="store_true",
                   help="after training, greedy-decode a held-out set and "
                        "report corpus BLEU aggregated across ranks "
                        "(the synthetic reversal task needs ~2000+ "
                        "iterations before BLEU leaves zero)")
    p.add_argument("--eval-size", type=int, default=256)
    p.add_argument("--beam", type=int, default=0, metavar="K",
                   help="with --eval: beam-search decode with K beams "
                        "instead of greedy (takes each row's top beam)")
    args = p.parse_args(argv)

    comm = chainermn_tpu.create_communicator(args.communicator)
    global_except_hook._add_hook()
    if comm.rank == 0:
        print(f"communicator: {comm}")

    if args.train_file:
        pairs = []
        with open(args.train_file) as f:
            for line in f:
                s, t = line.rstrip("\n").split("\t")
                pairs.append(
                    ([int(w) for w in s.split()], [int(w) for w in t.split()])
                )
    else:
        pairs = synthetic_pairs(4096, seed=0)
    pairs = chainermn_tpu.scatter_dataset(pairs, comm, shuffle=True, seed=7)
    # Re-gather the global batch per step (synchronized iterator semantics):
    # each process batches its own shard; the mesh shards the batch dim.

    model = Seq2Seq(src_vocab=VOCAB, tgt_vocab=VOCAB, embed=64, hidden=128)
    optimizer = chainermn_tpu.create_multi_node_optimizer(
        optax.adam(args.lr), comm
    )

    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    axes = comm.grad_axes

    def build_step():
        def local_step(params, opt_state, batch):
            src, tgt, sm, tm = batch
            tgt_in = jnp.concatenate(
                [jnp.full((tgt.shape[0], 1), BOS, tgt.dtype), tgt[:, :-1]],
                axis=1,
            )

            def loss_fn(p):
                logits = model.apply(p, src, tgt_in, sm, tm)
                return seq2seq_loss(logits, tgt, tm)

            loss, grads = jax.value_and_grad(loss_fn)(params)
            grads = jax.lax.pmean(grads, axes)
            loss = jax.lax.pmean(loss, axes)
            updates, opt_state = optimizer.actual_optimizer.update(
                grads, opt_state, params
            )
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss

        return jax.jit(
            shard_map(
                local_step,
                mesh=comm.mesh,
                in_specs=(P(), P(), P(axes)),
                out_specs=(P(), P(), P()),
                check_vma=False,
            )
        )

    step = build_step()

    params = None
    opt_state = None
    it = 0
    compiled_buckets = set()
    while it < args.iterations:
        for batch in bucket_batches(pairs, args.batchsize, drop_remainder=True):
            if it >= args.iterations:
                break
            src = jnp.asarray(batch["src"])
            tgt = jnp.asarray(batch["tgt"])
            sm = jnp.asarray(batch["src_mask"])
            tm = jnp.asarray(batch["tgt_mask"])
            if params is None:
                tgt_in = jnp.concatenate(
                    [jnp.full((tgt.shape[0], 1), BOS, tgt.dtype),
                     tgt[:, :-1]], axis=1,
                )
                params = model.init(jax.random.key(0), src, tgt_in, sm, tm)
                params = comm.bcast_data(params)
                opt_state = optimizer.actual_optimizer.init(params)
            if batch["bucket"] not in compiled_buckets and comm.rank == 0:
                compiled_buckets.add(batch["bucket"])
                print(f"  compiling bucket length {batch['bucket']}")
            params, opt_state, loss = step(params, opt_state, (src, tgt, sm, tm))
            it += 1
            if comm.rank == 0 and it % 20 == 0:
                print(f"iter {it}/{args.iterations} loss={float(loss):.4f}")
    if comm.rank == 0:
        print(f"final loss={float(loss):.4f} "
              f"({len(compiled_buckets)} bucket compilations)")

    result = {"loss": float(loss)}
    if args.eval:
        # Held-out set, sharded across ranks; greedy decode under jit per
        # source-length bucket; corpus BLEU from allreduce-summed n-gram
        # statistics (reference: the seq2seq example's BLEU eval, SURVEY.md
        # §2.8 — aggregation via the multi-node evaluator).
        held_out = synthetic_pairs(args.eval_size, seed=1234)
        shard = chainermn_tpu.scatter_dataset(held_out, comm, shuffle=False)
        if args.beam:
            decode = jax.jit(
                lambda s, m: beam_search_decode(
                    model, params, s, m, 36, args.beam, bos=BOS, eos=EOS
                )[0][:, 0]  # each row's best hypothesis
            )
        else:
            decode = jax.jit(
                lambda s, m: greedy_decode(
                    model, params, s, m, max_len=36, bos=BOS, eos=EOS
                )
            )

        def local_bleu_stats() -> dict:
            stats = []
            for batch in bucket_batches(
                shard, args.batchsize, drop_remainder=False
            ):
                hyp = np.asarray(
                    decode(jnp.asarray(batch["src"]),
                           jnp.asarray(batch["src_mask"]))
                )
                for row, ref in list(
                    zip(hyp, batch["tgt_raw"])
                )[: batch["n_real"]]:
                    stats.append(bleu_utils.bleu_stats(
                        bleu_utils.truncate_at_eos(row, EOS),
                        bleu_utils.truncate_at_eos(ref, EOS),
                    ))
            return bleu_utils.sum_stats(stats)

        evaluate = chainermn_tpu.create_multi_node_evaluator(
            local_bleu_stats, comm, reduce="sum",
            finalize=lambda total: {
                "bleu": bleu_utils.bleu_from_stats(total)
            },
        )
        result["bleu"] = evaluate()["bleu"]
        if comm.rank == 0:
            print(f"eval: corpus BLEU = {result['bleu']:.4f} "
                  f"({args.eval_size} held-out pairs, all ranks)")
    return result


if __name__ == "__main__":
    main()
