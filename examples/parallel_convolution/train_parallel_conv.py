"""Channel-parallel convolution — tensor parallelism over conv filters.

Reference: ``examples/parallel_convolution/`` (dagger) (SURVEY.md sections
2.2, 2.8): a convolution's output channels split across ranks, partial
results exchanged with collective functions — the reference's only
tensor-parallel pattern, built by hand from send/recv.

TPU-native, this is where the declarative model strictly dominates
(SURVEY.md section 2.2): shard the filter dimension of the conv weights
over a ``'model'`` mesh axis with ``NamedSharding`` and let pjit/XLA insert
the collectives. No bespoke communication code at all — compare the
reference's hand-rolled halo exchange.

    python examples/parallel_convolution/train_parallel_conv.py \
        --communicator naive --iterations 50
"""

from __future__ import annotations

import argparse
import sys

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

sys.path.insert(0, __file__.rsplit("/examples/", 1)[0])

import chainermn_tpu
from chainermn_tpu import global_except_hook


class ConvNet(nn.Module):
    """Small CNN whose conv channels will be sharded over the mesh."""

    num_classes: int = 10
    width: int = 64

    @nn.compact
    def __call__(self, x):
        x = nn.relu(nn.Conv(self.width, (3, 3))(x))
        x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = nn.relu(nn.Conv(2 * self.width, (3, 3))(x))
        x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        return nn.Dense(self.num_classes)(x)


def channel_sharding(params, mesh, axis="model"):
    """PartitionSpec tree: conv kernels shard their *output-channel* dim
    (last axis), biases shard their only dim — the channel-parallel layout
    of the reference example, expressed declaratively."""

    def spec_for(path, leaf):
        name = "/".join(str(p.key) for p in path if hasattr(p, "key"))
        if "Conv" in name and leaf.ndim == 4:  # HWIO kernel
            return P(None, None, None, axis)
        if "Conv" in name and leaf.ndim == 1:  # bias
            return P(axis)
        return P()  # dense head + others replicated

    return jax.tree_util.tree_map_with_path(spec_for, params)


def main(argv=None):
    p = argparse.ArgumentParser(
        description="ChainerMN-TPU example: channel-parallel convolution"
    )
    p.add_argument("--communicator", default="naive")
    p.add_argument("--batchsize", type=int, default=64)
    p.add_argument("--iterations", type=int, default=50)
    p.add_argument("--lr", type=float, default=0.05)
    args = p.parse_args(argv)

    comm = chainermn_tpu.create_communicator(args.communicator)
    global_except_hook._add_hook()
    mesh = jax.sharding.Mesh(
        comm.mesh.devices.reshape(-1), ("model",)
    )
    if comm.rank == 0:
        print(f"communicator: {comm} — conv channels sharded over 'model'")

    rng = np.random.RandomState(0)
    centers = rng.randn(10, 16, 16, 3).astype(np.float32)

    def batch():
        y = rng.randint(0, 10, size=args.batchsize)
        x = centers[y] + 0.3 * rng.randn(
            args.batchsize, 16, 16, 3
        ).astype(np.float32)
        return jnp.asarray(x), jnp.asarray(y)

    model = ConvNet()
    x0, _ = batch()
    params = model.init(jax.random.key(0), x0[:1])["params"]

    # Declarative channel parallelism: place the params sharded; jit does
    # the rest (collectives inserted by XLA from sharding propagation).
    specs = channel_sharding(params, mesh)
    params = jax.tree.map(
        lambda l, s: jax.device_put(l, NamedSharding(mesh, s)), params, specs
    )
    opt = optax.sgd(args.lr, momentum=0.9)
    opt_state = jax.jit(opt.init)(params)

    @jax.jit
    def step(params, opt_state, x, y):
        def loss_fn(p):
            logits = model.apply({"params": p}, x)
            loss = optax.softmax_cross_entropy_with_integer_labels(
                logits, y
            ).mean()
            return loss, (logits.argmax(-1) == y).mean()

        (loss, acc), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss, acc

    acc = jnp.zeros(())
    for it in range(args.iterations):
        x, y = batch()
        params, opt_state, loss, acc = step(params, opt_state, x, y)
        if comm.rank == 0 and (it + 1) % 10 == 0:
            print(
                f"iter {it + 1}/{args.iterations} "
                f"loss={float(loss):.4f} acc={float(acc):.4f}"
            )
    # Verify the kernels really are channel-sharded:
    k1 = params["Conv_0"]["kernel"]
    if comm.rank == 0:
        print(
            f"Conv_0 kernel sharding: {k1.sharding.spec} "
            f"final acc={float(acc):.4f}"
        )
    return float(acc)


if __name__ == "__main__":
    main()
