"""Expert-parallel (MoE) training — capacity-bounded routing, end to end.

Absent from the reference (SURVEY.md section 2.2 lists expert parallelism
as the TPU-era extension); this example trains a residual MoE classifier
over an ``'expert'`` mesh axis: one expert MLP per shard, tokens routed by
a learned gate through two ``all_to_all``s
(:func:`chainermn_tpu.parallel.moe.moe_layer_local`), Switch top-1 or
GShard top-2 routing, with the standard load-balancing auxiliary loss
keeping the gate from collapsing onto one expert.

    python examples/moe/train_moe_mlp.py --iterations 200
    python examples/moe/train_moe_mlp.py --topk 2 --aux-weight 0.01

The task: 10-blob classification where each blob prefers a different
random linear map — expert specialisation measurably helps, so rising
accuracy is a real signal that routing + expert training both work.
"""

from __future__ import annotations

import argparse
import sys

import jax
import jax.numpy as jnp
import numpy as np
import optax

sys.path.insert(0, __file__.rsplit("/examples/", 1)[0])

import chainermn_tpu
from chainermn_tpu import global_except_hook
from chainermn_tpu.parallel.moe import (
    load_balancing_loss,
    make_expert_params,
    moe_layer_local,
)


def main(argv=None):
    p = argparse.ArgumentParser(
        description="ChainerMN-TPU example: expert parallelism (MoE)"
    )
    p.add_argument("--communicator", default="naive")
    p.add_argument("--batchsize", type=int, default=256)
    p.add_argument("--iterations", type=int, default=200)
    p.add_argument("--width", type=int, default=64)
    p.add_argument("--lr", type=float, default=3e-3)
    p.add_argument("--topk", type=int, default=1, choices=(1, 2),
                   help="1: Switch top-1 routing; 2: GShard top-2")
    p.add_argument("--capacity-factor", type=float, default=1.5)
    p.add_argument("--dispatch-impl", default="auto",
                   choices=("auto", "einsum", "sort"),
                   help="queue assembly: dense one-hot einsum (reference), "
                        "index sort/scatter (scalable), or auto (default: "
                        "device-aware via the chainermn_tpu.tuning "
                        "registry)")
    p.add_argument("--aux-weight", type=float, default=1e-2,
                   help="load-balancing auxiliary loss weight")
    args = p.parse_args(argv)

    comm = chainermn_tpu.create_communicator(args.communicator)
    global_except_hook._add_hook()
    from jax import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    n_experts = comm.size
    mesh = Mesh(
        np.array(comm.mesh.devices.flat).reshape(n_experts), ("expert",)
    )
    if comm.rank == 0:
        print(f"moe: {n_experts} experts, top-{args.topk} routing, "
              f"capacity x{args.capacity_factor}")

    W = args.width

    def expert_fn(params, x):
        return jax.nn.gelu(x @ params["w1"]) @ params["w2"]

    def expert_init(rng):
        k1, k2 = jax.random.split(rng)
        return {
            "w1": jax.random.normal(k1, (W, 2 * W)) / np.sqrt(W),
            "w2": jax.random.normal(k2, (2 * W, W)) / np.sqrt(2 * W),
        }

    dense = {
        "w_in": jax.random.normal(jax.random.key(0), (20, W)) * 0.3,
        "router": jax.random.normal(jax.random.key(1), (W, n_experts)) * 0.1,
        "w_out": jax.random.normal(jax.random.key(3), (W, 10)) * 0.1,
    }
    experts = make_expert_params(expert_init, jax.random.key(2), n_experts)

    # Two optimizers: dense params (and their adam moments) replicate;
    # expert params (and moments) shard over the 'expert' axis — the
    # moments mirror the param shapes, so one spec rule covers the state:
    # arrays shard, scalars (step counts) replicate.
    opt_d = optax.adam(args.lr)
    opt_e = optax.adam(args.lr)
    opt_d_state = opt_d.init(dense)
    opt_e_state = opt_e.init(experts)
    e_state_spec = jax.tree.map(
        lambda l: P("expert") if getattr(l, "ndim", 0) >= 1 else P(),
        opt_e_state,
    )

    def local_step(dense, experts, opt_d_state, opt_e_state, x, y):
        def loss_fn(dense, experts):
            h = jnp.tanh(x @ dense["w_in"])
            my_experts = jax.tree.map(lambda l: l[0], experts)
            # Aux loss must regularise the SAME router distribution the
            # layer dispatched with — i.e. the pre-residual activations.
            aux = load_balancing_loss(h @ dense["router"])
            h = h + moe_layer_local(
                h, dense["router"], expert_fn, my_experts, "expert",
                capacity_factor=args.capacity_factor, k=args.topk,
                dispatch_impl=args.dispatch_impl,
            )
            logits = h @ dense["w_out"]
            task = optax.softmax_cross_entropy_with_integer_labels(
                logits, y
            ).mean()
            acc = (logits.argmax(-1) == y).mean()
            return task + args.aux_weight * aux, (task, acc)

        (loss, (task, acc)), (g_d, g_e) = jax.value_and_grad(
            loss_fn, argnums=(0, 1), has_aux=True
        )(dense, experts)
        # Token shards differ per slot: dense grads average over the mesh;
        # expert grads are per-shard by construction (each shard owns its
        # expert, fed through the all_to_all by every shard's tokens).
        g_d = jax.lax.pmean(g_d, "expert")
        task = jax.lax.pmean(task, "expert")
        acc = jax.lax.pmean(acc, "expert")
        upd_d, opt_d_state = opt_d.update(g_d, opt_d_state, dense)
        upd_e, opt_e_state = opt_e.update(g_e, opt_e_state, experts)
        return (
            optax.apply_updates(dense, upd_d),
            optax.apply_updates(experts, upd_e),
            opt_d_state,
            opt_e_state,
            task,
            acc,
        )

    e_spec = jax.tree.map(lambda _: P("expert"), experts)
    step = jax.jit(
        shard_map(
            local_step,
            mesh=mesh,
            in_specs=(P(), e_spec, P(), e_state_spec, P("expert"),
                      P("expert")),
            out_specs=(P(), e_spec, P(), e_state_spec, P(), P()),
            check_vma=False,
        )
    )

    rng = np.random.RandomState(0)
    maps = rng.randn(10, 20, 20).astype(np.float32) * 0.5
    centers = rng.randn(10, 20).astype(np.float32) * 2
    for it in range(1, args.iterations + 1):
        y = rng.randint(0, 10, size=args.batchsize)
        base = centers[y] + 0.3 * rng.randn(args.batchsize, 20).astype(np.float32)
        x = np.einsum("bi,bij->bj", base, maps[y]) + base
        dense, experts, opt_d_state, opt_e_state, loss, acc = step(
            dense, experts, opt_d_state, opt_e_state,
            jnp.asarray(x), jnp.asarray(y),
        )
        if comm.rank == 0 and it % 50 == 0:
            print(f"iter {it}/{args.iterations} "
                  f"loss={float(loss):.4f} acc={float(acc):.4f}")
    if comm.rank == 0:
        print(f"final: loss={float(loss):.4f} acc={float(acc):.4f}")
    return float(acc)


if __name__ == "__main__":
    main()
