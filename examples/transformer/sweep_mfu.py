"""Transformer-LM MFU sweep — perf methodology tool for the tracked
``transformer_mfu`` metric (SURVEY.md section 6 / docs/benchmarks.md).

Times the full train step (fwd + bwd + grad allreduce + adam) across a
small grid of the knobs that actually move single-chip MFU — remat
policy, fused-LM-head chunk count, flash block sizes, and head count at
fixed d_model (H16×D64 vs H8×D128: identical params and model FLOPs,
but head dim is the MXU contraction depth and the flash kernel's VMEM
lane width — D=64 fills half of each) — and prints one JSON line per
variant plus a ranked summary. Run on the real chip:

    python examples/transformer/sweep_mfu.py
    python examples/transformer/sweep_mfu.py --layers 8 --d-model 1024 \
        --seq-len 2048 --batch 16 --steps 8

The defaults mirror ``bench.py``'s accel transformer config so the best
variant's settings can be transplanted straight into the benchmark.
MFU convention: MODEL flops (6·P per token + 6·L·T·d attention), not
hardware flops — remat recompute is the price paid, not useful work.
"""

from __future__ import annotations

import argparse
import itertools
import json
import sys
import time

import jax
import jax.numpy as jnp
import optax

sys.path.insert(0, __file__.rsplit("/examples/", 1)[0])

# Canonical peak-FLOPs table and true-completion sync — shared with the
# tracked benchmark so sweep MFU is directly comparable to bench.py's
# transformer_mfu (a diverging copy once reported half the true v5e MFU).
from bench import _fetch_scalar, _peak_flops

from chainermn_tpu import create_communicator, create_multi_node_optimizer
from chainermn_tpu.models import TransformerLM, lm_loss_fused
from chainermn_tpu.ops.flash_attention import flash_attention


def time_variant(comm, args, *, remat: str, n_chunks: int,
                 block_q: int, block_k: int, batch: int,
                 n_heads: int, db: bool = True) -> dict:
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    interpret = jax.devices()[0].platform == "cpu"

    def attn(q, k, v, *, causal, scale):
        return flash_attention(q, k, v, causal=causal, scale=scale,
                               block_q=block_q, block_k=block_k,
                               interpret=interpret)

    # n_heads at fixed d_model: identical params/model-FLOPs, but
    # D = d_model/heads is the MXU contraction depth and the VMEM lane
    # width in the flash kernel — D=64 fills half of each.
    model = TransformerLM(
        num_layers=args.layers, d_model=args.d_model,
        num_heads=n_heads, d_ff=args.d_ff, max_len=args.seq_len,
        remat=remat != "none",
        remat_policy="dots" if remat != "nothing" else "nothing",
        return_hidden=True, attention_fn=attn,
    )
    B, T, steps = batch * comm.size, args.seq_len, args.steps
    tokens = jax.random.randint(
        jax.random.PRNGKey(0), (B, T), 0, model.vocab_size
    )
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        tokens = multihost_utils.host_local_array_to_global_array(
            tokens, comm.mesh, P()
        )
    params = jax.jit(lambda k, t: model.init(k, t, train=True))(
        jax.random.PRNGKey(1), tokens[:2]
    )
    opt = create_multi_node_optimizer(
        optax.adam(1e-4), comm, double_buffering=db,
        allreduce_grad_dtype=jnp.bfloat16,
    )

    def loss_fn(p, tok):
        hidden = model.apply(p, tok, train=True)
        emb = p["params"]["tok_emb"]["embedding"]
        return lm_loss_fused(hidden, emb, tok, n_chunks=n_chunks)

    def local(params, opt_state, tok):
        def one(carry, _):
            params, opt_state = carry
            loss, grads = jax.value_and_grad(loss_fn)(params, tok)
            updates, opt_state = opt.update(grads, opt_state, params)
            return (optax.apply_updates(params, updates), opt_state), loss

        (params, opt_state), losses = jax.lax.scan(
            one, (params, opt_state), None, length=steps
        )
        return losses[-1]

    fn = jax.jit(shard_map(
        local, mesh=comm.mesh,
        in_specs=(P(), P(), P(comm.grad_axes)), out_specs=P(),
        check_vma=False,
    ))
    opt_state = opt.init(params)
    t_c0 = time.perf_counter()
    _fetch_scalar(fn(params, opt_state, tokens))  # compile + warm
    compile_s = time.perf_counter() - t_c0
    t0 = time.perf_counter()
    _fetch_scalar(fn(params, opt_state, tokens))
    dt = (time.perf_counter() - t0) / steps

    n_params = sum(x.size for x in jax.tree.leaves(params))
    model_flops = (
        (6 * n_params + 6 * args.layers * T * args.d_model) * B * T
        / comm.size
    )
    out = {
        "remat": remat, "n_chunks": n_chunks, "batch": batch,
        "block_q": block_q, "block_k": block_k, "heads": n_heads,
        "db": db,
        "step_ms": round(dt * 1e3, 2),
        "tokens_per_sec": round(B * T / dt, 1),
        "compile_s": round(compile_s, 1),
    }
    peak = _peak_flops(jax.devices()[0].device_kind)
    if peak:
        out["mfu"] = round(model_flops / dt / peak, 4)
    return out


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--communicator", default="xla")
    p.add_argument("--layers", type=int, default=8)
    p.add_argument("--d-model", type=int, default=1024)
    p.add_argument("--db", type=str, default="true",
                   help="comma list of true/false: double-buffered "
                        "allreduce (baseline-identity default true; on "
                        "one chip the bank carry is pure cost)")
    p.add_argument("--heads", type=str, default="16,8",
                   help="comma list of head counts at fixed d_model "
                        "(same params/FLOPs; head dim = d_model/heads "
                        "sets MXU contraction depth)")
    p.add_argument("--d-ff", type=int, default=4096)
    p.add_argument("--seq-len", type=int, default=2048)
    p.add_argument("--batch", type=str, default="16",
                   help="comma list of per-device batch sizes")
    p.add_argument("--steps", type=int, default=8)
    p.add_argument("--remat", type=str, default="dots,none,nothing",
                   help="comma list of none|dots|nothing (granularity)")
    p.add_argument("--chunks", type=str, default="8,16,32")
    p.add_argument("--blocks", type=str, default="512x1024,256x512",
                   help="comma list of block_q x block_k")
    args = p.parse_args(argv)

    comm = create_communicator(args.communicator)
    remats = []
    for v in args.remat.split(","):
        v = v.strip().lower()
        # legacy spellings from earlier rounds keep working
        v = {"true": "dots", "false": "none"}.get(v, v)
        if v not in ("none", "dots", "nothing"):
            p.error(f"--remat values must be none|dots|nothing, got {v!r}")
        remats.append(v)
    chunks = [int(v) for v in args.chunks.split(",")]
    blocks = [tuple(int(v) for v in b.split("x"))
              for b in args.blocks.split(",")]
    batches = [int(v) for v in args.batch.split(",")]
    head_counts = [int(v) for v in str(args.heads).split(",")]
    for h in head_counts:
        if h < 1 or args.d_model % h:
            p.error(f"--heads values must divide d_model, got {h}")
    dbs = []
    for v in args.db.split(","):
        v = v.strip().lower()
        if v not in ("true", "false"):
            p.error(f"--db values must be true/false, got {v!r}")
        dbs.append(v == "true")

    results = []
    for remat, n_chunks, (bq, bk), batch, heads, db in itertools.product(
        remats, chunks, blocks, batches, head_counts, dbs
    ):
        try:
            r = time_variant(comm, args, remat=remat, n_chunks=n_chunks,
                             block_q=bq, block_k=bk, batch=batch,
                             n_heads=heads, db=db)
        except Exception as e:  # OOM / Mosaic layout reject: keep sweeping
            r = {"remat": remat, "n_chunks": n_chunks, "block_q": bq,
                 "block_k": bk, "batch": batch, "heads": heads, "db": db,
                 "error": f"{type(e).__name__}: {e}"[:160]}
        print(json.dumps(r), flush=True)
        results.append(r)

    ok = [r for r in results if "step_ms" in r]
    # Best by MFU (fallback throughput): batch is a grid dimension, so
    # step_ms ordering would rank the smallest batch first regardless of
    # efficiency. The fallback is PER-RUN, not per-row — mixing mfu
    # (<=1) with raw throughput (thousands) would rank any mfu-less row
    # first; a row missing mfu in an mfu-bearing run ranks last (0).
    if any("mfu" in r for r in ok):
        ok.sort(key=lambda r: -r.get("mfu", 0))
    else:
        ok.sort(key=lambda r: -r.get("tokens_per_sec", 0))
    if ok:
        print(json.dumps({"best": ok[0], "n_variants": len(results)}))
    return ok


if __name__ == "__main__":
    main()
