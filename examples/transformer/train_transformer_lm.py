"""Transformer-base LM training — the double-buffered-allreduce workload.

BASELINE.json config: "Transformer-base LM (new — large embedding grads,
double-buffered allreduce)". Demonstrates the v1.3-era optimizer features
(``double_buffering=True``, ``allreduce_grad_dtype='bfloat16'`` — SURVEY.md
section 2.3) on a modern workload, plus optional ring-attention sequence
parallelism for long context (``--sequence-parallel``).

    python examples/transformer/train_transformer_lm.py \
        --communicator naive --iterations 40 --double-buffering
    python examples/transformer/train_transformer_lm.py \
        --communicator naive --sequence-parallel --seq-len 512
    python examples/transformer/train_transformer_lm.py \
        --communicator naive --packed --num-kv-heads 2
"""

from __future__ import annotations

import argparse
import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

sys.path.insert(0, __file__.rsplit("/examples/", 1)[0])

import chainermn_tpu
from chainermn_tpu import global_except_hook
from chainermn_tpu.models import TransformerLM, lm_loss
from chainermn_tpu.training import make_train_step
from chainermn_tpu.training.train_step import create_train_state

VOCAB = 1024


def synthetic_tokens(rng, batch, seqlen):
    """Markov-ish synthetic text: next token correlates with current."""
    x = np.zeros((batch, seqlen), np.int32)
    x[:, 0] = rng.integers(0, VOCAB, size=batch)
    drift = rng.integers(1, 17, size=batch)
    for t in range(1, seqlen):
        stay = rng.random(batch) < 0.8
        x[:, t] = np.where(stay, (x[:, t - 1] + drift) % VOCAB,
                           rng.integers(0, VOCAB, size=batch))
    return x


def _make_optimizer(args, comm):
    """One builder for every training path in this example: local SGD
    (frequency lever) or the per-step multi-node wrapper (width/overlap
    levers) — mutually exclusive, validated at parse time."""
    if args.local_sgd:
        return chainermn_tpu.create_local_sgd(
            optax.adamw(args.lr), comm, sync_every=args.local_sgd,
        )
    return chainermn_tpu.create_multi_node_optimizer(
        optax.adamw(args.lr), comm,
        double_buffering=args.double_buffering,
        error_feedback=args.error_feedback,
    )


def main(argv=None):
    p = argparse.ArgumentParser(
        description="ChainerMN-TPU example: Transformer LM"
    )
    p.add_argument("--communicator", default="naive")
    p.add_argument("--batchsize", type=int, default=8,
                   help="per-mesh-slot batch size (data-parallel mode)")
    p.add_argument("--seq-len", type=int, default=256)
    p.add_argument("--iterations", type=int, default=40)
    p.add_argument("--lr", type=float, default=3e-4)
    p.add_argument("--double-buffering", action="store_true")
    p.add_argument("--allreduce-grad-dtype", default="bfloat16")
    p.add_argument("--mlm", action="store_true",
                   help="masked-LM pretraining on the BIDIRECTIONAL "
                        "encoder form (causal=False): BERT-style 80/10/10 "
                        "corruption, loss on masked positions only; "
                        "excludes --generate/--beam (no autoregressive "
                        "decode on an encoder)")
    p.add_argument("--local-sgd", type=int, default=0, metavar="H",
                   help="periodic parameter averaging every H steps "
                        "instead of the per-step gradient allreduce; "
                        "0 = off")
    p.add_argument("--error-feedback", action="store_true",
                   help="EF-SGD for the int8 quantized wire (requires "
                        "--allreduce-grad-dtype int8); shard-level on "
                        "the two_dimensional communicator")
    p.add_argument("--sequence-parallel", action="store_true",
                   help="shard the sequence over the mesh (ring attention)")
    p.add_argument("--packed", action="store_true",
                   help="pack variable-length documents into each row with "
                        "segment-id flash-attention masks (cross-document "
                        "attention and loss are masked)")
    p.add_argument("--num-kv-heads", type=int, default=None,
                   help="GQA: fewer kv heads than q heads (must divide)")
    p.add_argument("--pos-encoding", default="learned",
                   choices=("learned", "rope"),
                   help="absolute learned table (reference-style) or "
                        "rotary (no position parameters)")
    p.add_argument("--num-layers", type=int, default=6)
    p.add_argument("--d-model", type=int, default=512)
    p.add_argument("--generate", type=int, default=0, metavar="N",
                   help="after training, greedy-decode N tokens from a "
                        "synthetic prompt with the KV cache (data-parallel "
                        "mode only)")
    p.add_argument("--window", type=int, default=0, metavar="W",
                   help="causal sliding-window attention of width W via the "
                        "flash kernel (0 = full causal; composes with "
                        "--packed and --sequence-parallel)")
    p.add_argument("--beam", type=int, default=0, metavar="K",
                   help="with --generate: beam-search decode with K beams "
                        "instead of greedy")
    args = p.parse_args(argv)
    # Fail flag conflicts BEFORE any expensive setup (compile, data).
    # (--allreduce-grad-dtype configures the COMMUNICATOR's wire and
    # defaults to bf16 here; under local SGD that wire simply never
    # fires, so only the explicit optimizer opt-ins conflict.)
    if args.local_sgd and (args.double_buffering or args.error_feedback):
        p.error("--local-sgd replaces the per-step gradient wire; "
                "--double-buffering/--error-feedback would be "
                "silently ignored")
    if args.mlm and (args.generate or args.beam):
        p.error("--mlm is an encoder: no autoregressive decode "
                "(--generate/--beam)")
    if args.mlm and (args.window or args.sequence_parallel or args.packed):
        p.error("--mlm composes with the plain data-parallel path only "
                "(windows/SP/packing are causal-LM features here)")
    if args.local_sgd and args.sequence_parallel:
        p.error("--local-sgd is not wired into the sequence-parallel "
                "path (it builds its own per-step pmean loop); drop one "
                "of the flags")

    comm = chainermn_tpu.create_communicator(
        args.communicator,
        allreduce_grad_dtype=args.allreduce_grad_dtype or None,
    )
    global_except_hook._add_hook()
    if comm.rank == 0:
        print(f"communicator: {comm}  sp={args.sequence_parallel}")

    compute_dtype = (
        jnp.bfloat16 if jax.default_backend() == "tpu" else jnp.float32
    )
    rng = np.random.default_rng(0)

    if args.sequence_parallel and args.packed:
        raise SystemExit(
            "--sequence-parallel with --packed is not wired in this "
            "example (ring attention does accept segment_ids — see "
            "ring_attention_local — but this CLI keeps the modes separate)"
        )
    if args.sequence_parallel:
        run_sequence_parallel(args, comm, compute_dtype, rng)
    elif args.packed:
        run_packed(args, comm, compute_dtype, rng)
    else:
        run_data_parallel(args, comm, compute_dtype, rng)


def pack_documents(rng, batch, seqlen):
    """Pack 2-5 variable-length synthetic documents per row: returns
    ``(tokens, segment_ids)`` — the normal LM data layout (SURVEY.md §5
    long-context gap; the reference's seq2seq bucketing was the 2017
    answer to the same problem)."""
    if seqlen < 32:
        raise SystemExit(
            f"--packed needs --seq-len >= 32 (got {seqlen}): rows hold up "
            "to 5 documents with 8-token margins"
        )
    tokens = np.zeros((batch, seqlen), np.int32)
    seg = np.zeros((batch, seqlen), np.int32)
    for b in range(batch):
        n_docs = rng.integers(2, 6)
        cuts = np.sort(rng.choice(np.arange(8, seqlen - 8), n_docs - 1,
                                  replace=False))
        bounds = [0, *cuts.tolist(), seqlen]
        for d in range(n_docs):
            lo, hi = bounds[d], bounds[d + 1]
            tokens[b:b + 1, lo:hi] = synthetic_tokens(rng, 1, hi - lo)
            seg[b, lo:hi] = d
    return tokens, seg


def run_packed(args, comm, compute_dtype, rng):
    """Packed-sequence training: flash attention with segment-id masks so
    documents never attend across their boundaries, and the next-token loss
    skips cross-document targets."""
    from chainermn_tpu.ops.flash_attention import flash_attention

    interpret = jax.default_backend() != "tpu"

    def attn(q, k, v, *, causal, scale, segment_ids=None):
        # window composes with the packed-segment masks in the kernel
        # (0 = no window — full causal within each document).
        return flash_attention(q, k, v, causal=causal, scale=scale,
                               segment_ids=segment_ids,
                               window=args.window or None,
                               interpret=interpret)

    model = TransformerLM(
        vocab_size=VOCAB, num_layers=args.num_layers,
        d_model=args.d_model, d_ff=4 * args.d_model,
        max_len=args.seq_len, compute_dtype=compute_dtype,
        attention_fn=attn, num_kv_heads=args.num_kv_heads,
        pos_encoding=args.pos_encoding,
        window=args.window or None,
    )
    global_batch = args.batchsize * comm.size
    tokens0, seg0 = pack_documents(rng, global_batch, args.seq_len)
    params = jax.jit(model.init)(
        jax.random.key(0), jnp.asarray(tokens0[:1])
    )["params"]

    def loss_fn(params, batch):
        tokens, seg = batch
        logits = model.apply({"params": params}, tokens, segment_ids=seg)
        # Mask targets that would cross a document boundary.
        valid = jnp.concatenate(
            [jnp.ones_like(seg[:, :1]), (seg[:, 1:] == seg[:, :-1])], axis=1
        )
        return lm_loss(logits, tokens, mask=valid)

    optimizer = _make_optimizer(args, comm)
    state = create_train_state(params, optimizer, comm)
    step = make_train_step(loss_fn, optimizer, comm)

    t0 = time.perf_counter()
    for it in range(args.iterations):
        tokens, seg = pack_documents(rng, global_batch, args.seq_len)
        state, metrics = step(state, (jnp.asarray(tokens), jnp.asarray(seg)))
        if comm.rank == 0 and (it + 1) % 10 == 0:
            jax.block_until_ready(metrics["loss"])
            tps = global_batch * args.seq_len * (it + 1) / (
                time.perf_counter() - t0
            )
            print(
                f"iter {it + 1}/{args.iterations} "
                f"loss={float(metrics['loss']):.4f} ({tps:,.0f} tok/s, packed)"
            )
    jax.block_until_ready(state.params)
    if comm.rank == 0:
        print("done (packed)")


def run_data_parallel(args, comm, compute_dtype, rng):
    attention_fn = None
    if args.window:
        # Local attention needs the flash kernel (the blockwise default
        # has no window support); out-of-band blocks skip their matmuls.
        # The model also carries `window` so KV-cache decoding
        # (--generate) masks the same band — train and inference agree.
        from chainermn_tpu.ops.flash_attention import flash_attention

        def attention_fn(q, k, v, *, causal, scale):
            return flash_attention(q, k, v, causal=causal, scale=scale,
                                   window=args.window)

    model = TransformerLM(
        vocab_size=VOCAB, num_layers=args.num_layers,
        d_model=args.d_model, d_ff=4 * args.d_model,
        max_len=args.seq_len, compute_dtype=compute_dtype,
        num_kv_heads=args.num_kv_heads,
        pos_encoding=args.pos_encoding,
        attention_fn=attention_fn,
        window=args.window or None,
        causal=not args.mlm,
    )
    global_batch = args.batchsize * comm.size
    tokens0 = synthetic_tokens(rng, global_batch, args.seq_len)
    params = jax.jit(model.init)(
        jax.random.key(0), jnp.asarray(tokens0[:1])
    )["params"]

    if args.mlm:
        from chainermn_tpu.models import mlm_corrupt, mlm_loss

        MASK_ID = VOCAB - 1  # reserve the top id as [MASK]
        corrupt = jax.jit(functools.partial(
            mlm_corrupt, mask_id=MASK_ID, vocab_size=VOCAB, rate=0.15,
        ))

        def loss_fn(params, batch):
            x, targets, sel = batch
            logits = model.apply({"params": params}, x)
            return mlm_loss(logits, targets, sel)

        def make_batch(it):
            # Data lives in [0, MASK_ID): real tokens must never equal
            # the reserved [MASK] symbol or the 80/10/10 recipe muddies.
            targets = jnp.asarray(
                synthetic_tokens(rng, global_batch, args.seq_len)
            ) % MASK_ID
            x, sel = corrupt(jax.random.PRNGKey(it), targets)
            return (x, targets, sel)
    else:

        def loss_fn(params, tokens):
            logits = model.apply({"params": params}, tokens)
            return lm_loss(logits, tokens)

        def make_batch(it):
            return jnp.asarray(
                synthetic_tokens(rng, global_batch, args.seq_len)
            )

    optimizer = _make_optimizer(args, comm)
    state = create_train_state(params, optimizer, comm)
    step = make_train_step(loss_fn, optimizer, comm)

    t0 = time.perf_counter()
    for it in range(args.iterations):
        state, metrics = step(state, make_batch(it))
        if comm.rank == 0 and (it + 1) % 10 == 0:
            jax.block_until_ready(metrics["loss"])
            tps = global_batch * args.seq_len * (it + 1) / (
                time.perf_counter() - t0
            )
            print(
                f"iter {it + 1}/{args.iterations} "
                f"loss={float(metrics['loss']):.4f} ({tps:,.0f} tok/s)"
            )
    jax.block_until_ready(state.params)
    if args.generate and comm.rank == 0:
        # Inference demo on the just-trained weights: KV-cache greedy
        # decode (one jitted scan of single-token steps — see
        # chainermn_tpu.models.transformer.generate).
        from chainermn_tpu.models import generate

        prompt = jnp.asarray(
            synthetic_tokens(rng, 2, min(8, args.seq_len))
        )
        n = min(args.seq_len, prompt.shape[1] + args.generate)
        if args.beam:
            from chainermn_tpu.models import beam_search

            beams, bscores = beam_search(
                model, {"params": state.params}, prompt, n, args.beam,
                pad_id=-1,
            )
            print(f"beam_search (K={args.beam}): best scores "
                  f"{np.asarray(bscores[:, 0]).round(2).tolist()}; top "
                  f"continuations "
                  f"{np.asarray(beams[:, 0, prompt.shape[1]:]).tolist()}")
        out = generate(
            model, {"params": state.params}, prompt, n,
            pad_id=-1,  # synthetic tokens include 0; nothing is padding
        )
        print(f"generate: prompt {prompt.shape} -> {out.shape}; "
              f"continuations {np.asarray(out[:, prompt.shape[1]:]).tolist()}")
    if comm.rank == 0:
        print("done (mlm)" if args.mlm else "done (data-parallel)")


def run_sequence_parallel(args, comm, compute_dtype, rng):
    """Long-context mode: ONE sequence sharded over the whole mesh, ring
    attention streaming K/V blocks over ICI."""
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    from chainermn_tpu.parallel.ring_attention import ring_attention_local

    ax = comm.axis_name
    n = comm.size
    if args.seq_len % n:
        raise SystemExit(f"--seq-len must be divisible by mesh size {n}")
    t_local = args.seq_len // n

    if args.window:
        # Local attention: neighbour-tail exchanges instead of the full
        # K/V ring — O(window) communication per layer, any width.
        from chainermn_tpu.parallel.local_attention import (
            sliding_window_attention_local,
        )

        def ring_attn(q, k, v, *, causal, scale):
            return sliding_window_attention_local(
                q, k, v, ax, window=args.window, scale=scale
            )
    else:

        def ring_attn(q, k, v, *, causal, scale):
            return ring_attention_local(q, k, v, ax, causal=causal,
                                        scale=scale)

    model = TransformerLM(
        vocab_size=VOCAB, num_layers=args.num_layers,
        d_model=args.d_model, d_ff=4 * args.d_model,
        max_len=args.seq_len, compute_dtype=compute_dtype,
        attention_fn=ring_attn, num_kv_heads=args.num_kv_heads,
        pos_encoding=args.pos_encoding,
    )
    ref = TransformerLM(
        vocab_size=VOCAB, num_layers=args.num_layers,
        d_model=args.d_model, d_ff=4 * args.d_model,
        max_len=args.seq_len, compute_dtype=compute_dtype,
        num_kv_heads=args.num_kv_heads,
        pos_encoding=args.pos_encoding,
    )
    batch = 2
    tokens0 = synthetic_tokens(rng, batch, args.seq_len)
    params = jax.jit(ref.init)(jax.random.key(0), jnp.asarray(tokens0[:1]))
    opt = optax.adamw(args.lr)
    opt_state = opt.init(params)

    def local_step(params, opt_state, tokens):
        idx = jax.lax.axis_index(ax)

        def loss_fn(p):
            # The shard's GLOBAL positions serve both encodings: a learned
            # table gathers its rows (no more whole-table rolling + params
            # surgery), rotary rotates by them directly.
            pos = idx * t_local + jnp.arange(t_local, dtype=jnp.int32)
            logits = model.apply(p, tokens, positions=pos)
            return lm_loss(logits, tokens)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        grads = jax.lax.pmean(grads, ax)
        loss = jax.lax.pmean(loss, ax)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    step = jax.jit(
        shard_map(
            local_step,
            mesh=comm.mesh,
            in_specs=(P(), P(), P(None, ax)),
            out_specs=(P(), P(), P()),
            check_vma=False,
        )
    )

    t0 = time.perf_counter()
    for it in range(args.iterations):
        tokens = synthetic_tokens(rng, batch, args.seq_len)
        params, opt_state, loss = step(params, opt_state, jnp.asarray(tokens))
        if comm.rank == 0 and (it + 1) % 10 == 0:
            jax.block_until_ready(loss)
            tps = batch * args.seq_len * (it + 1) / (time.perf_counter() - t0)
            print(
                f"iter {it + 1}/{args.iterations} loss={float(loss):.4f} "
                f"({tps:,.0f} tok/s, seq {args.seq_len} over {n} shards)"
            )
    jax.block_until_ready(params)
    if comm.rank == 0:
        print("done (sequence-parallel)")


if __name__ == "__main__":
    main()
