"""Pipeline-parallel training — the micro-batched engine, end to end.

The reference only showed the *pattern* (chained send/recv via
``MultiNodeChainList``, one rank computing while the rest idled — SURVEY.md
section 2.2); this example runs the real GPipe engine
(:mod:`chainermn_tpu.parallel.pipeline`): a deep residual MLP split into
``n_stages`` homogeneous stages over a ``'stage'`` mesh axis, micro-batched
fill/steady/drain schedule in ONE jitted program, backward = the
automatically transposed reverse schedule.

    python examples/pipeline/train_pipeline_mlp.py --iterations 100
    python examples/pipeline/train_pipeline_mlp.py --remat-stages
    # (--remat-stages: recompute stage-internal activations in backward)
    python examples/pipeline/train_pipeline_mlp.py --schedule 1f1b
    # (1f1b: interleaved one-forward-one-backward engine — O(stages)
    #  saved activations at any microbatch count; embed trains through
    #  the engine's input grads, the softmax head through head grads)

The task (10-blob classification, same as the mnist example's synthetic
data) converges within ~100 iterations, so accuracy is a real signal that
gradients flow correctly through the pipeline.
"""

from __future__ import annotations

import argparse
import sys

import jax
import jax.numpy as jnp
import numpy as np
import optax

sys.path.insert(0, __file__.rsplit("/examples/", 1)[0])

import chainermn_tpu
from chainermn_tpu import global_except_hook
from chainermn_tpu.parallel.pipeline import make_pipeline, stack_stage_params


def main(argv=None):
    p = argparse.ArgumentParser(
        description="ChainerMN-TPU example: GPipe pipeline parallelism"
    )
    p.add_argument("--communicator", default="naive")
    p.add_argument("--batchsize", type=int, default=128)
    p.add_argument("--iterations", type=int, default=150)
    p.add_argument("--width", type=int, default=128)
    p.add_argument("--lr", type=float, default=2e-3)
    p.add_argument("--microbatches", type=int, default=None,
                   help="default: 2x the stage count")
    p.add_argument("--remat-stages", action="store_true",
                   help="recompute stage-internal activations in the "
                        "backward (saves memory for deep stages)")
    p.add_argument("--schedule", choices=("gpipe", "1f1b", "hetero"),
                   default="gpipe",
                   help="gpipe: differentiable apply + autodiff backward; "
                        "1f1b: interleaved fwd/bwd engine, O(stages) "
                        "activation memory at any microbatch count; "
                        "hetero: per-stage functions — embed and head "
                        "run INSIDE the pipeline")
    args = p.parse_args(argv)

    comm = chainermn_tpu.create_communicator(args.communicator)
    global_except_hook._add_hook()
    n_stages = comm.size
    from jax.sharding import Mesh

    mesh = Mesh(np.array(comm.mesh.devices.flat).reshape(n_stages), ("stage",))
    n_micro = args.microbatches or 2 * n_stages
    if comm.rank == 0:
        print(f"pipeline: {n_stages} stages x {n_micro} microbatches "
              f"(remat={args.remat_stages})")

    W = args.width

    def stage_fn(params, x):
        # one residual block per stage: homogeneous in/out shape [mb, W]
        h = jnp.tanh(x @ params["w1"] + params["b1"])
        return x + h @ params["w2"]

    keys = jax.random.split(jax.random.key(0), n_stages)
    stacked = stack_stage_params([
        {
            "w1": jax.random.normal(k, (W, W)) * (1.0 / np.sqrt(W)),
            "b1": jnp.zeros((W,)),
            "w2": jax.random.normal(jax.random.fold_in(k, 1), (W, W))
            * (0.5 / np.sqrt(W)),
        }
        for k in keys
    ])
    # Embed/head live OUTSIDE the pipelined region (data-sharded on real
    # meshes; replicated here) — the documented composition rule.
    w_in = jax.random.normal(jax.random.key(1), (784, W)) * 0.05
    w_out = jax.random.normal(jax.random.key(2), (W, 10)) * 0.05

    opt = optax.adam(args.lr)
    params = (stacked, w_in, w_out)
    opt_state = opt.init(params)

    if args.schedule == "gpipe":
        pipe = make_pipeline(
            stage_fn, mesh, n_microbatches=n_micro,
            remat_stages=args.remat_stages,
        )

        def loss_fn(params, batch):
            stacked, w_in, w_out = params
            x, y = batch
            h = jnp.tanh(x @ w_in)
            h = pipe(stacked, h)
            logits = h @ w_out
            loss = optax.softmax_cross_entropy_with_integer_labels(
                logits, y
            ).mean()
            acc = (logits.argmax(-1) == y).mean()
            return loss, acc

        @jax.jit
        def step(params, opt_state, batch):
            (loss, acc), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params, batch
            )
            updates, opt_state = opt.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss, acc

    elif args.schedule == "hetero":
        # Embed and head INSIDE the pipeline: stage 0 maps [mb, 784] ->
        # [mb, W], the last stage banks [mb, 10] logits — no outside
        # composition rule. Per-stage params are a replicated tuple.
        from chainermn_tpu.parallel.pipeline import make_pipeline_hetero

        def embed_fn(p, x):
            return jnp.tanh(x @ p["w_in"])

        def head_fn(p, h):
            return h @ p["w_out"]

        fns = [embed_fn] + [stage_fn] * (n_stages - 2) + [head_fn]
        blocks = [
            jax.tree.map(lambda l: l[i], stacked)
            for i in range(n_stages - 2)
        ]
        params = tuple(
            [{"w_in": w_in}] + blocks + [{"w_out": w_out}]
        )
        opt_state = opt.init(params)
        pipe = make_pipeline_hetero(
            fns, mesh, n_microbatches=n_micro,
            remat_stages=args.remat_stages,
        )

        def loss_fn(params, batch):
            x, y = batch
            logits = pipe(params, x)
            loss = optax.softmax_cross_entropy_with_integer_labels(
                logits, y
            ).mean()
            acc = (logits.argmax(-1) == y).mean()
            return loss, acc

        @jax.jit
        def step(params, opt_state, batch):
            (loss, acc), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params, batch
            )
            updates, opt_state = opt.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss, acc

    else:  # 1f1b: the engine IS the fwd+bwd; embed trains via input
        # grads, the softmax head via head grads.
        from chainermn_tpu.parallel.pipeline import make_pipeline_1f1b

        def head_loss(w_out, h_mb, y_mb):
            logits = h_mb @ w_out
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, y_mb
            ).mean()

        def loss_grad_fn(w_out, h_mb, y_mb):
            loss, (dw, dh) = jax.value_and_grad(
                head_loss, argnums=(0, 1)
            )(w_out, h_mb, y_mb)
            return loss, (dw, dh)

        engine = make_pipeline_1f1b(
            stage_fn, loss_grad_fn, mesh, n_microbatches=n_micro,
        )
        # Forward-only apply for the accuracy metric (the engine returns
        # loss+grads, not the final-stage activations).
        pipe_apply = make_pipeline(stage_fn, mesh, n_microbatches=n_micro)

        @jax.jit
        def step(params, opt_state, batch):
            stacked, w_in, w_out = params
            x, y = batch

            def embed(w_in):
                return jnp.tanh(x @ w_in)

            h, embed_vjp = jax.vjp(embed, w_in)
            loss, g_stages, g_head, dh = engine(
                stacked, h, y, w_out, collect_input_grads=True
            )
            (g_in,) = embed_vjp(dh)
            grads = (g_stages, g_in, g_head)
            updates, opt_state = opt.update(grads, opt_state, params)
            logits = pipe_apply(stacked, h) @ w_out
            acc = (logits.argmax(-1) == y).mean()
            return optax.apply_updates(params, updates), opt_state, loss, acc

    rng = np.random.RandomState(0)
    centers = rng.randn(10, 784).astype(np.float32)
    for it in range(1, args.iterations + 1):
        y = rng.randint(0, 10, size=args.batchsize)
        x = centers[y] + 0.5 * rng.randn(args.batchsize, 784).astype(np.float32)
        params, opt_state, loss, acc = step(
            params, opt_state, (jnp.asarray(x), jnp.asarray(y))
        )
        if comm.rank == 0 and it % 50 == 0:
            print(f"iter {it}/{args.iterations} "
                  f"loss={float(loss):.4f} acc={float(acc):.4f}")
    if comm.rank == 0:
        print(f"final: loss={float(loss):.4f} acc={float(acc):.4f}")
    return float(acc)


if __name__ == "__main__":
    main()
