"""Sequence-parallelism tests: ring attention and Ulysses all_to_all
attention must equal single-device full attention on the concatenated
sequence (values AND gradients) — the reference test suite's distributed ==
single-process invariant (SURVEY.md section 4) applied to the new
long-context layer (section 5)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from chainermn_tpu.ops.attention import (
    blockwise_attention,
    dot_product_attention,
)
from chainermn_tpu.parallel.ring_attention import make_ring_attention
from chainermn_tpu.parallel.ulysses import make_ulysses_attention

B, T, H, D = 2, 32, 8, 16  # T sharded 8-ways -> T_local = 4


def _qkv(seed=0, dtype=jnp.float32):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    shape = (B, T, H, D)
    return tuple(jax.random.normal(k, shape, dtype) for k in ks)


class TestLocalAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_blockwise_matches_full(self, causal):
        q, k, v = _qkv()
        ref = dot_product_attention(q, k, v, causal=causal)
        blk = blockwise_attention(q, k, v, block_k=8, causal=causal)
        np.testing.assert_allclose(blk, ref, rtol=1e-5, atol=1e-5)

    def test_blockwise_grads_match_full(self):
        q, k, v = _qkv(1)

        def loss_ref(q, k, v):
            return dot_product_attention(q, k, v, causal=True).sum()

        def loss_blk(q, k, v):
            return blockwise_attention(q, k, v, block_k=8, causal=True).sum()

        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        g_blk = jax.grad(loss_blk, argnums=(0, 1, 2))(q, k, v)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-4),
            g_blk,
            g_ref,
        )


class TestRingAttention:
    """Both impls must satisfy the distributed == single-device invariant:
    'einsum' is the autodiff reference; 'flash' is the Pallas block-kernel
    path with the hand-written ring backward (the production path)."""

    @pytest.mark.parametrize("impl", ["einsum", "flash"])
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_full_attention(self, comm, causal, impl):
        q, k, v = _qkv(2)
        ref = dot_product_attention(q, k, v, causal=causal)

        fn = make_ring_attention(
            comm.mesh, comm.axis_name, causal=causal, impl=impl
        )
        sharding = NamedSharding(comm.mesh, P(None, comm.axis_name))
        qs, ks, vs = (jax.device_put(t, sharding) for t in (q, k, v))
        out = fn(qs, ks, vs)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5, atol=1e-5)

    @pytest.mark.parametrize("impl", ["einsum", "flash"])
    def test_grads_match_full_attention(self, comm, impl):
        q, k, v = _qkv(3)
        fn = make_ring_attention(
            comm.mesh, comm.axis_name, causal=True, impl=impl
        )

        def loss_ring(q, k, v):
            return (fn(q, k, v) ** 2).sum()

        def loss_ref(q, k, v):
            return (dot_product_attention(q, k, v, causal=True) ** 2).sum()

        g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), b, rtol=1e-4, atol=1e-4
            ),
            g_ring,
            g_ref,
        )

    def test_bf16_inputs_f32_accumulation(self, comm):
        q, k, v = _qkv(4, jnp.bfloat16)
        fn = make_ring_attention(comm.mesh, comm.axis_name)
        out = fn(q, k, v)
        assert out.dtype == jnp.bfloat16
        ref = dot_product_attention(
            q.astype(jnp.float32), k.astype(jnp.float32), v.astype(jnp.float32)
        )
        np.testing.assert_allclose(
            np.asarray(out, np.float32), ref, rtol=2e-2, atol=2e-2
        )


class TestUlyssesAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_full_attention(self, comm, causal):
        q, k, v = _qkv(5)
        ref = dot_product_attention(q, k, v, causal=causal)
        fn = make_ulysses_attention(comm.mesh, comm.axis_name, causal=causal)
        out = fn(q, k, v)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5, atol=1e-5)

    def test_grads_match_full_attention(self, comm):
        q, k, v = _qkv(6)
        fn = make_ulysses_attention(comm.mesh, comm.axis_name, causal=True)

        def loss_u(q, k, v):
            return (fn(q, k, v) ** 2).sum()

        def loss_ref(q, k, v):
            return (dot_product_attention(q, k, v, causal=True) ** 2).sum()

        g_u = jax.grad(loss_u, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), b, rtol=1e-4, atol=1e-4
            ),
            g_u,
            g_ref,
        )

    def test_head_divisibility_enforced(self, comm):
        # H=6 not divisible by the 8-way axis
        q = jnp.zeros((B, T, 6, D))
        fn = make_ulysses_attention(comm.mesh, comm.axis_name)
        with pytest.raises(ValueError, match="not divisible"):
            fn(q, q, q)
